"""raylint effect lattice: per-function intrinsic effect inference.

Each function gets a set of *intrinsic* effects — costs its own body
pays on every call — which `flow.py` then propagates to fixpoint through
the package call graph. The lattice is the distilled history of this
repo's hot-path bugs:

  blocking   sleep, lock-wait, blocking ray_tpu.get, file/socket I/O,
             subprocess waits, timed future.result() — anything that
             parks the calling thread (the PR 9 class: a blocking shm
             read on the event loop's default executor deadlocked the
             whole process)
  syscall    a syscall paid once per call — os.urandom / getpid /
             uuid4 / secrets (the PR 8/11 class: ~288µs of urandom per
             request in the submit path)
  host-sync  a host-device synchronization — block_until_ready(),
             jax.device_get, np.asarray/float()/int()/.item() on a name
             bound from a jax call (the PR 14/RT017 class: one sync per
             iteration where the fused-scan budget is one per block)
  alloc      registry-churning construction — metrics Counter/Gauge/
             Histogram, fresh trace contexts, serve.batch wrappers,
             queue objects (the RT011/RT015/RT016 class)

Detection is deliberately shallow per function: one AST walk with the
same import-table name resolution the rule engine uses, plus RT017's
forward-flow map of jax-bound names. Depth comes from propagation, not
from per-site cleverness.
"""
from __future__ import annotations

import ast
from dataclasses import dataclass

# ------------------------------------------------------------ the lattice
BLOCKING = "blocking"
SYSCALL = "syscall"
HOST_SYNC = "host-sync"
ALLOC = "alloc"

ALL_EFFECTS = frozenset({BLOCKING, SYSCALL, HOST_SYNC, ALLOC})

# ------------------------------------------------------- context roots
# Root kinds and the effects forbidden on anything reachable from them.
# Rules map 1:1 onto effects (RT020=blocking, RT021=syscall,
# RT022=host-sync, RT023=alloc); a rule fires for a root only when the
# root kind forbids that rule's effect.
ROOT_FORBIDS: dict[str, frozenset] = {
    # a callback handed to loop.call_soon/_threadsafe/call_later runs ON
    # the event loop: blocking it stalls every coroutine in the process
    "event-loop": frozenset({BLOCKING}),
    # the shm fast-lane pumps: per-record cost IS the product
    "fast-pump": frozenset({BLOCKING, SYSCALL, ALLOC}),
    # tunnel record-exec paths: the cross-node fast lane's pump twins
    "tunnel-exec": frozenset({BLOCKING, SYSCALL, ALLOC}),
    # serve request handlers: per-request cost at serve QPS
    "serve-handler": frozenset({BLOCKING, SYSCALL, ALLOC}),
    # functions traced by jax.jit / lax.scan|while_loop|fori_loop: a
    # host sync inside the region serializes the fused dispatch
    "jit-region": frozenset({HOST_SYNC}),
}

# functions that are roots by NAME (leaf qualname match), colored from
# the production system's actual hot paths
NAMED_ROOTS: dict[str, str] = {
    "_fast_pump": "fast-pump",
    "fast_actor_submit_loop": "fast-pump",
    "_tunnel_exec_seq": "tunnel-exec",
    "_tunnel_exec_batch_sync": "tunnel-exec",
    "_tunnel_exec_task_batch": "tunnel-exec",
    "_tunnel_exec_one": "tunnel-exec",
    "_tunnel_exec_record_on_loop": "tunnel-exec",
    "rpc_tunnel_frame": "tunnel-exec",
    "handle_request": "serve-handler",
    "handle_request_streaming": "serve-handler",
}

# ------------------------------------------------------------ edge masks
# Effects that PROPAGATE caller-ward across each call-edge kind. The
# executor distinctions encode the repo's own fix idioms: shipping work
# to a PRIVATE pool (PR 9's _store_executor) is the cure for blocking,
# so nothing propagates back; the loop's DEFAULT executor is shared with
# the loop's own machinery, so blocking submitted there still starves it.
EDGE_MASKS: dict[str, frozenset] = {
    "call": ALL_EFFECTS,
    "remote": ALL_EFFECTS,        # .remote() dispatch: callee runs per call
    "task": ALL_EFFECTS,          # create_task/ensure_future: runs on loop
    "call_soon": ALL_EFFECTS,     # call_soon[_threadsafe]/call_later
    "default-executor": frozenset({BLOCKING}),  # run_in_executor(None, f)
    "executor": frozenset(),      # private pool submit: isolation by design
    "thread": frozenset(),        # Thread(target=...): its own thread
}

# rule id -> effect it polices
RULE_EFFECT = {
    "RT020": BLOCKING,
    "RT021": SYSCALL,
    "RT022": HOST_SYNC,
    "RT023": ALLOC,
}
EFFECT_RULE = {v: k for k, v in RULE_EFFECT.items()}


# ----------------------------------------------------------- effect sites
@dataclass(frozen=True)
class EffectSite:
    """One intrinsic effect occurrence inside a function body."""
    effect: str
    detail: str   # e.g. "os.urandom()" — line-stable, used in baseline keys
    line: int
    col: int


_SYSCALLS = {
    ("os", "urandom"), ("os", "getpid"), ("os", "getppid"),
    ("uuid", "uuid1"), ("uuid", "uuid4"),
    ("secrets", "token_bytes"), ("secrets", "token_hex"),
    ("secrets", "token_urlsafe"),
}
_SUBPROCESS = {"run", "call", "check_call", "check_output"}
_BLOCKING_ORIGINS = {
    ("time", "sleep"),
    ("os", "fsync"), ("os", "fdatasync"),
    ("socket", "create_connection"),
    ("shutil", "copyfile"), ("shutil", "copytree"),
}
_HOST_SYNC_NUMPY = {"asarray", "array"}
_QUEUE_CTORS = {("queue", "Queue"), ("queue", "SimpleQueue"),
                ("asyncio", "Queue")}
_METRIC_CTORS = {"Counter", "Gauge", "Histogram"}


def _is_framework_get(origin) -> bool:
    """Blocking ray_tpu.get: the public api / client entry points, not an
    unrelated in-package helper that happens to be named get."""
    if not origin or origin[0] != "ray_tpu" or origin[-1] != "get":
        return False
    return len(origin) == 2 or "api" in origin[:-1] or "client" in origin[:-1]


class EffectScanner:
    """Scans ONE function body (nested defs excluded — they are their own
    graph nodes; lambdas included — their deferred bodies are attributed
    to the enclosing function) and yields EffectSites.

    `imports` is any object with a `resolve(node) -> tuple|None` method
    (engine.ImportTable or flow.ModuleImports); `uses_jax` gates the
    attribute-shape host-sync legs the import table can't resolve.
    """

    def __init__(self, imports, uses_jax: bool):
        self.imports = imports
        self.uses_jax = uses_jax
        self.sites: list[EffectSite] = []
        # RT017's forward-flow idiom: names bound from jax-origin calls
        self._jax_bound: set[str] = set()

    # -- public -------------------------------------------------------------
    def scan(self, fn: ast.AST) -> list[EffectSite]:
        for stmt in fn.body:
            self._walk(stmt)
        return self.sites

    # -- walk ---------------------------------------------------------------
    def _walk(self, node: ast.AST):
        if isinstance(node, (ast.FunctionDef, ast.AsyncFunctionDef,
                             ast.ClassDef)):
            return  # separate graph nodes
        if isinstance(node, ast.Assign):
            self._track_jax_binding(node)
        if isinstance(node, ast.Call):
            self._check_call(node)
        for child in ast.iter_child_nodes(node):
            self._walk(child)

    def _track_jax_binding(self, node: ast.Assign):
        if len(node.targets) != 1 or not isinstance(node.targets[0], ast.Name):
            return
        name = node.targets[0].id
        if isinstance(node.value, ast.Call):
            origin = self.imports.resolve(node.value.func)
            if origin and origin[0] == "jax":
                self._jax_bound.add(name)
                return
        self._jax_bound.discard(name)

    # -- detectors ----------------------------------------------------------
    def _add(self, node: ast.AST, effect: str, detail: str):
        self.sites.append(EffectSite(effect, detail,
                                     getattr(node, "lineno", 0),
                                     getattr(node, "col_offset", 0)))

    def _check_call(self, node: ast.Call):
        func = node.func
        origin = self.imports.resolve(func)

        # ---- syscall-per-call
        if origin and tuple(origin[-2:]) in _SYSCALLS:
            self._add(node, SYSCALL, f"{'.'.join(origin)}()")
            return

        # ---- blocking
        if origin:
            if tuple(origin[-2:]) in _BLOCKING_ORIGINS:
                self._add(node, BLOCKING, f"{'.'.join(origin)}()")
                return
            if origin[0] == "subprocess" and origin[-1] in _SUBPROCESS:
                self._add(node, BLOCKING, f"subprocess.{origin[-1]}()")
                return
            if _is_framework_get(origin):
                self._add(node, BLOCKING, "ray_tpu.get()")
                return
        if (isinstance(func, ast.Name) and func.id == "open"
                and self.imports.resolve(func) is None):
            self._add(node, BLOCKING, "open()")
            return
        if isinstance(func, ast.Attribute) and origin is None:
            # timed future.result(t): the concurrent.futures blocking-wait
            # idiom (argless .result() on a done asyncio future is the
            # normal callback shape and stays clean)
            if func.attr == "result" and node.args:
                self._add(node, BLOCKING, ".result(timeout)")
                return
            # argless lock.acquire() / thread.join(): unbounded waits
            if func.attr in ("acquire", "join") and not node.args \
                    and not node.keywords:
                self._add(node, BLOCKING, f".{func.attr}()")
                return

        # ---- host-device sync
        if ((isinstance(func, ast.Attribute)
             and func.attr == "block_until_ready" and self.uses_jax)
                or (origin and tuple(origin[-2:]) ==
                    ("jax", "block_until_ready"))
                or origin == ("jax", "block_until_ready")):
            self._add(node, HOST_SYNC, "block_until_ready()")
            return
        if origin and origin[0] == "jax" and origin[-1] == "device_get":
            self._add(node, HOST_SYNC, "jax.device_get()")
            return
        if self._jax_bound:
            numpy_op = (origin[-1] if origin and origin[0] == "numpy"
                        and origin[-1] in _HOST_SYNC_NUMPY else None)
            builtin = (func.id if isinstance(func, ast.Name)
                       and func.id in ("float", "int")
                       and origin is None else None)
            if numpy_op or builtin:
                for arg in node.args:
                    if isinstance(arg, ast.Name) and arg.id in self._jax_bound:
                        fn = f"np.{numpy_op}" if numpy_op else builtin
                        self._add(node, HOST_SYNC, f"{fn}({arg.id})")
                        return
            if (isinstance(func, ast.Attribute) and func.attr == "item"
                    and not node.args
                    and isinstance(func.value, ast.Name)
                    and func.value.id in self._jax_bound):
                self._add(node, HOST_SYNC, f"{func.value.id}.item()")
                return

        # ---- alloc-heavy construction
        if origin and origin[0] == "ray_tpu":
            if origin[-1] in _METRIC_CTORS and "metrics" in origin[:-1]:
                self._add(node, ALLOC, f"metrics.{origin[-1]}()")
                return
            if "tracing" in origin[:-1]:
                leaf = origin[-1]
                if leaf in ("inject", "submit_context"):
                    self._add(node, ALLOC, f"tracing.{leaf}()")
                    return
                if leaf == "span" and self._span_fresh_root(node):
                    self._add(node, ALLOC, "tracing.span(fresh root)")
                    return
            if origin[-1] == "batch" and ("serve" in origin[:-1]
                                          or "batching" in origin[:-1]):
                self._add(node, ALLOC, "serve.batch()")
                return
        if origin and tuple(origin[-2:]) in _QUEUE_CTORS:
            self._add(node, ALLOC, f"{'.'.join(origin)}()")
            return

    @staticmethod
    def _span_fresh_root(node: ast.Call) -> bool:
        """tracing.span with a missing/None trace_ctx mints a new root."""
        tc = node.args[1] if len(node.args) >= 2 else None
        if tc is None:
            for kw in node.keywords:
                if kw.arg == "trace_ctx":
                    tc = kw.value
        return tc is None or (isinstance(tc, ast.Constant)
                              and tc.value is None)

"""raylint engine: AST walk, framework-name resolution, rule dispatch.

The linter is framework-aware: rules don't pattern-match on bare
identifiers, they resolve names through the module's import table so
`rt.get(...)`, `ray_tpu.core.api.get(...)` and `from ray_tpu import get;
get(...)` all canonicalise to the same `get` op, while an unrelated
`cache.get(...)` resolves to nothing.

Suppression: a finding is dropped when its physical line carries
`# raylint: disable=RT001[,RT002|all]`, or the file carries
`# raylint: disable-file=RT001` anywhere (conventionally the header).
"""
from __future__ import annotations

import ast
import io
import json
import math
import os
import re
import tokenize
from dataclasses import dataclass, field
from typing import Iterable, Sequence

# ------------------------------------------------------------------ findings
@dataclass(frozen=True)
class Finding:
    rule_id: str
    message: str
    path: str
    line: int
    col: int

    def as_dict(self) -> dict:
        # stable key order for JSON output (tested by test_json_stability)
        return {
            "rule": self.rule_id,
            "path": self.path,
            "line": self.line,
            "col": self.col,
            "message": self.message,
        }

    def render(self) -> str:
        return f"{self.path}:{self.line}:{self.col}: {self.rule_id} {self.message}"


PARSE_RULE_ID = "RT000"  # synthetic rule for files that fail to parse

# ------------------------------------------------------------------ registry
RULES: dict[str, type] = {}


def register(cls):
    """Class decorator adding a Rule subclass to the global registry."""
    if cls.id in RULES:
        raise ValueError(f"duplicate rule id {cls.id}")
    RULES[cls.id] = cls
    return cls


class Rule:
    """Base rule. Subclasses set id/summary/rationale and implement any of
    the `on_<nodetype>` hooks (on_call, on_functiondef, on_expr, on_if,
    on_try); the engine dispatches during a single AST walk."""

    id: str = ""
    summary: str = ""
    rationale: str = ""


def rule_table() -> list[dict]:
    return [
        {"id": rid, "summary": cls.summary, "rationale": cls.rationale}
        for rid, cls in sorted(RULES.items())
    ]


# ------------------------------------------------------------- import table
_FRAMEWORK_ROOT = "ray_tpu"
_NUMPY_ROOTS = {("numpy",), ("jax", "numpy")}


class ImportTable:
    """Maps local names to fully-dotted origin paths.

    `import ray_tpu as rt`        -> rt: ("ray_tpu",)
    `from ray_tpu import get`     -> get: ("ray_tpu", "get")
    `import jax.numpy as jnp`     -> jnp: ("jax", "numpy")
    `import a.b.c`                -> a: ("a",)   (attribute walk supplies b.c)
    """

    def __init__(self):
        self.bindings: dict[str, tuple[str, ...]] = {}

    def collect(self, tree: ast.AST):
        for node in ast.walk(tree):
            if isinstance(node, ast.Import):
                for alias in node.names:
                    parts = tuple(alias.name.split("."))
                    if alias.asname:
                        self.bindings[alias.asname] = parts
                    else:
                        self.bindings[parts[0]] = parts[:1]
            elif isinstance(node, ast.ImportFrom):
                if node.level or not node.module:
                    continue  # relative imports: origin unknown, stay silent
                base = tuple(node.module.split("."))
                for alias in node.names:
                    if alias.name == "*":
                        continue
                    self.bindings[alias.asname or alias.name] = base + (alias.name,)

    def resolve(self, node: ast.AST) -> tuple[str, ...] | None:
        """Resolve a Name/Attribute chain to a dotted origin path."""
        parts: list[str] = []
        while isinstance(node, ast.Attribute):
            parts.append(node.attr)
            node = node.value
        if not isinstance(node, ast.Name):
            return None
        origin = self.bindings.get(node.id)
        if origin is None:
            return None
        return origin + tuple(reversed(parts))


# ------------------------------------------------------------------ context
@dataclass
class RemoteFrame:
    node: ast.AST
    kind: str  # "task" | "actor_method"
    decorator_kwargs: frozenset = frozenset()


@dataclass
class Context:
    path: str
    imports: ImportTable
    findings: list[Finding] = field(default_factory=list)
    remote_stack: list[RemoteFrame] = field(default_factory=list)
    # target-name sets of the enclosing for-loops/comprehensions; RT002
    # fires only when a get() argument references one of these (a while
    # poll loop or wait()-then-get-one streaming is NOT a loop over refs)
    for_targets: list[set] = field(default_factory=list)
    # name -> element count, for np/jnp arrays bound in the current scope
    # (simple forward-flow map used by RT004's closure-capture check)
    array_bindings: dict[str, int] = field(default_factory=dict)
    # nesting depth of enclosing loop BODIES (for/while/comprehension)
    # within the current function scope; unlike for_targets this also
    # counts while-loops — RT009 fires on any per-iteration re-derivation
    loop_depth: int = 0
    # True while walking the body of an `async def` (reset inside nested
    # sync defs and lambdas: their bodies run on whatever thread calls
    # them, not necessarily the event loop) — RT010's blocking-call scope
    in_async: bool = False
    # nesting depth of enclosing function/lambda BODIES; unlike
    # loop_depth this survives into nested defs — RT011 fires on any
    # construction that re-runs per call rather than once at import
    func_depth: int = 0
    # name of the innermost enclosing def (None at module/class scope;
    # lambdas keep the enclosing def's name) — RT015 exempts one-time
    # setup bodies like __init__ by name
    func_name: str | None = None

    # -- reporting ----------------------------------------------------------
    def report(self, rule: Rule, node: ast.AST, message: str):
        self.findings.append(Finding(
            rule_id=rule.id, message=message, path=self.path,
            line=getattr(node, "lineno", 0), col=getattr(node, "col_offset", 0),
        ))

    # -- framework queries --------------------------------------------------
    @property
    def uses_framework(self) -> bool:
        """True when the module imports ray_tpu at all. Gates the rules
        that match on the `.remote()` attribute shape (unresolvable
        through the import table — the callee is a task/actor handle in a
        local variable), so an unrelated library's `.remote()` in a module
        that never touches ray_tpu stays clean."""
        return any(origin[0] == _FRAMEWORK_ROOT
                   for origin in self.imports.bindings.values())

    @property
    def in_remote(self) -> RemoteFrame | None:
        return self.remote_stack[-1] if self.remote_stack else None

    def loops_over(self, node: ast.AST) -> bool:
        """True when `node`'s subtree references a target bound by an
        enclosing for-loop or comprehension."""
        if not self.for_targets:
            return False
        bound = set().union(*self.for_targets)
        return any(isinstance(sub, ast.Name) and sub.id in bound
                   for sub in ast.walk(node))

    def framework_op(self, func: ast.AST) -> str | None:
        """Canonical op name ("get"/"put"/"wait"/"remote") for a call target
        that resolves into the ray_tpu API, else None."""
        origin = self.imports.resolve(func)
        if not origin or origin[0] != _FRAMEWORK_ROOT:
            return None
        if origin[-1] in ("get", "put", "wait", "remote"):
            return origin[-1]
        return None

    def collective_op(self, func: ast.AST) -> str | None:
        """Op name for a call into ray_tpu.collective (allreduce, barrier,
        ...), else None."""
        origin = self.imports.resolve(func)
        if not origin or origin[0] != _FRAMEWORK_ROOT:
            return None
        if "collective" in origin[:-1]:
            return origin[-1]
        return None

    def is_numpy_ctor(self, func: ast.AST) -> str | None:
        origin = self.imports.resolve(func)
        if not origin:
            return None
        for root in _NUMPY_ROOTS:
            if origin[: len(root)] == root:
                return origin[-1]
        return None

    def is_time_sleep(self, func: ast.AST) -> bool:
        return self.imports.resolve(func) == ("time", "sleep")

    def remote_decorator(self, node: ast.AST) -> frozenset | None:
        """If `node` (Function/ClassDef) carries a framework @remote
        decorator, return the decorator-call kwarg names (empty frozenset
        for the bare form); else None."""
        for deco in getattr(node, "decorator_list", []):
            if isinstance(deco, ast.Call):
                if self.framework_op(deco.func) == "remote":
                    return frozenset(
                        kw.arg for kw in deco.keywords if kw.arg)
            elif self.framework_op(deco) == "remote":
                return frozenset()
        return None


# ------------------------------------------------------------------- walker
_LOOP_TYPES = (ast.For, ast.AsyncFor, ast.While)
_COMP_TYPES = (ast.ListComp, ast.SetComp, ast.DictComp, ast.GeneratorExp)


class Walker:
    """Single-pass AST walk with manual recursion for the node types that
    change context (defs, classes, loops, comprehensions), dispatching each
    node to every enabled rule's `on_<type>` hook."""

    def __init__(self, ctx: Context, rules: Sequence[Rule]):
        self.ctx = ctx
        self.rules = rules
        self._hooks: dict[str, list] = {}

    def _dispatch(self, node: ast.AST):
        key = type(node).__name__.lower()
        hooks = self._hooks.get(key)
        if hooks is None:
            hooks = [h for rule in self.rules
                     if (h := getattr(rule, f"on_{key}", None))]
            self._hooks[key] = hooks
        for hook in hooks:
            hook(node, self.ctx)

    def walk(self, node: ast.AST):
        self._dispatch(node)
        if isinstance(node, (ast.FunctionDef, ast.AsyncFunctionDef)):
            self._walk_function(node)
        elif isinstance(node, ast.Lambda):
            self._walk_lambda(node)
        elif isinstance(node, ast.ClassDef):
            self._walk_class(node)
        elif isinstance(node, _LOOP_TYPES):
            self._walk_loop(node)
        elif isinstance(node, _COMP_TYPES):
            self._walk_comprehension(node)
        else:
            if isinstance(node, ast.Assign):
                self._record_array_binding(node)
            for child in ast.iter_child_nodes(node):
                self.walk(child)

    # -- context-bearing node types ----------------------------------------
    def _walk_function(self, node):
        ctx = self.ctx
        deco_kwargs = ctx.remote_decorator(node)
        frame = None
        if deco_kwargs is not None:
            frame = RemoteFrame(node, "task", deco_kwargs)
        elif getattr(node, "_rt_actor_method", False):
            frame = RemoteFrame(node, "actor_method")
        # decorators and defaults evaluate in the enclosing scope
        for deco in node.decorator_list:
            self.walk(deco)
        for default in [*node.args.defaults, *node.args.kw_defaults]:
            if default is not None:
                self.walk(default)
        if frame is not None:
            ctx.remote_stack.append(frame)
        saved_arrays = dict(ctx.array_bindings)
        saved_targets = ctx.for_targets
        saved_depth = ctx.loop_depth
        saved_async = ctx.in_async
        saved_name = ctx.func_name
        ctx.for_targets = []  # a nested def body doesn't run per-iteration
        ctx.loop_depth = 0
        ctx.in_async = isinstance(node, ast.AsyncFunctionDef)
        ctx.func_depth += 1
        ctx.func_name = node.name
        for stmt in node.body:
            self.walk(stmt)
        ctx.func_depth -= 1
        ctx.func_name = saved_name
        ctx.for_targets = saved_targets
        ctx.loop_depth = saved_depth
        ctx.in_async = saved_async
        ctx.array_bindings = saved_arrays
        if frame is not None:
            ctx.remote_stack.pop()

    def _walk_lambda(self, node: ast.Lambda):
        ctx = self.ctx
        # defaults evaluate eagerly in the enclosing scope; the body is
        # deferred and doesn't run per-iteration of any enclosing loop
        for default in [*node.args.defaults, *node.args.kw_defaults]:
            if default is not None:
                self.walk(default)
        saved_targets = ctx.for_targets
        saved_depth = ctx.loop_depth
        saved_async = ctx.in_async
        ctx.for_targets = []
        ctx.loop_depth = 0
        ctx.in_async = False  # deferred body: caller's thread, not the loop
        ctx.func_depth += 1
        self.walk(node.body)
        ctx.func_depth -= 1
        ctx.for_targets = saved_targets
        ctx.loop_depth = saved_depth
        ctx.in_async = saved_async

    def _walk_class(self, node: ast.ClassDef):
        is_actor = self.ctx.remote_decorator(node) is not None
        for deco in node.decorator_list:
            self.walk(deco)
        for stmt in node.body:
            if is_actor and isinstance(stmt, (ast.FunctionDef,
                                              ast.AsyncFunctionDef)):
                stmt._rt_actor_method = True
            self.walk(stmt)

    def _walk_loop(self, node):
        ctx = self.ctx
        if isinstance(node, (ast.For, ast.AsyncFor)):
            self.walk(node.iter)  # evaluated once, outside the loop
            self.walk(node.target)
            ctx.for_targets.append(_target_names(node.target))
            ctx.loop_depth += 1
            for stmt in node.body:
                self.walk(stmt)
            ctx.loop_depth -= 1
            ctx.for_targets.pop()
        else:  # While: no bound targets, but still a per-iteration body
            self.walk(node.test)
            ctx.loop_depth += 1
            for stmt in node.body:
                self.walk(stmt)
            ctx.loop_depth -= 1
        for stmt in node.orelse:
            self.walk(stmt)

    def _walk_comprehension(self, node):
        ctx = self.ctx
        gens = node.generators
        self.walk(gens[0].iter)  # first iterable evaluates once
        ctx.for_targets.append(
            set().union(*[_target_names(g.target) for g in gens]))
        ctx.loop_depth += 1
        for gen in gens:
            self.walk(gen.target)
            if gen is not gens[0]:
                self.walk(gen.iter)
            for cond in gen.ifs:
                self.walk(cond)
        if isinstance(node, ast.DictComp):
            self.walk(node.key)
            self.walk(node.value)
        else:
            self.walk(node.elt)
        ctx.loop_depth -= 1
        ctx.for_targets.pop()

    # -- RT004 dataflow -----------------------------------------------------
    def _record_array_binding(self, node: ast.Assign):
        if len(node.targets) != 1 or not isinstance(node.targets[0], ast.Name):
            return
        size = literal_array_size(node.value, self.ctx)
        if size is not None:
            self.ctx.array_bindings[node.targets[0].id] = size
        else:
            self.ctx.array_bindings.pop(node.targets[0].id, None)


def _target_names(target: ast.AST) -> set:
    return {sub.id for sub in ast.walk(target) if isinstance(sub, ast.Name)}


# --------------------------------------------------- RT004 size estimation
_SIZED_CTORS = {"zeros", "ones", "full", "empty", "zeros_like", "ones_like"}


def literal_array_size(node: ast.AST, ctx: Context) -> int | None:
    """Element count of a np/jnp constructor call whose shape is written as
    literals; None when it isn't such a call or the size is not static."""
    if not isinstance(node, ast.Call):
        return None
    ctor = ctx.is_numpy_ctor(node.func)
    if ctor is None or not node.args:
        return None
    if ctor == "arange":
        vals = [_literal_int(a) for a in node.args[:3]]
        if any(v is None for v in vals):
            return None
        if len(vals) == 1:
            start, stop, step = 0, vals[0], 1
        elif len(vals) == 2:
            start, stop, step = vals[0], vals[1], 1
        else:
            start, stop, step = vals
        if step == 0:
            return None
        return max(0, math.ceil((stop - start) / step))
    if ctor in _SIZED_CTORS:
        return _literal_shape_size(node.args[0])
    return None


def _literal_int(node: ast.AST) -> int | None:
    if isinstance(node, ast.Constant) and isinstance(node.value, int):
        return node.value
    return None


def _literal_shape_size(node: ast.AST) -> int | None:
    if isinstance(node, (ast.Tuple, ast.List)):
        total = 1
        for elt in node.elts:
            dim = _literal_int(elt)
            if dim is None:
                return None
            total *= dim
        return total
    return _literal_int(node)


# -------------------------------------------------------------- suppression
_SUPPRESS_RE = re.compile(
    r"#\s*raylint:\s*disable(-file)?\s*=\s*"
    r"((?:RT\d+|all)(?:\s*,\s*(?:RT\d+|all))*)")


def parse_suppressions(source: str) -> tuple[dict[int, set], set]:
    """Returns (line -> rule-ids suppressed on that line, file-wide ids).
    The token `all` suppresses every rule.

    Only real COMMENT tokens count: a directive quoted inside a string or
    docstring (e.g. documentation of the syntax itself) must not become a
    live suppression."""
    per_line: dict[int, set] = {}
    file_wide: set = set()
    try:
        tokens = list(tokenize.generate_tokens(io.StringIO(source).readline))
    except (tokenize.TokenError, IndentationError, SyntaxError):
        return per_line, file_wide  # unparseable: RT000 already reported
    for tok in tokens:
        if tok.type != tokenize.COMMENT:
            continue
        m = _SUPPRESS_RE.search(tok.string)
        if not m:
            continue
        ids = {t.strip() for t in m.group(2).split(",") if t.strip()}
        if m.group(1):
            file_wide |= ids
        else:
            per_line.setdefault(tok.start[0], set()).update(ids)
    return per_line, file_wide


def _suppressed(f: Finding, per_line: dict[int, set], file_wide: set) -> bool:
    ids = per_line.get(f.line, set()) | file_wide
    return f.rule_id in ids or "all" in ids


# ----------------------------------------------------------------- running
def _instantiate(select: Iterable[str] | None = None,
                 ignore: Iterable[str] | None = None) -> list[Rule]:
    unknown = (set(select or ()) | set(ignore or ())) - set(RULES)
    if unknown:
        raise ValueError(f"unknown rule ids: {sorted(unknown)}")
    wanted = set(select) if select else set(RULES)
    if ignore:
        wanted -= set(ignore)
    if not wanted:
        # a zero-rule run reporting "0 findings" would be a green gate
        # that checked nothing
        raise ValueError("select/ignore leave no rules enabled")
    return [RULES[rid]() for rid in sorted(wanted)]


def lint_source(source: str, path: str = "<string>", *,
                select=None, ignore=None) -> list[Finding]:
    """Lint one source string; returns unsuppressed findings, sorted."""
    import ray_tpu.devtools.lint.rules  # noqa: F401  (registers RT001-RT017)

    try:
        tree = ast.parse(source, filename=path)
    except SyntaxError as e:
        return [Finding(PARSE_RULE_ID, f"syntax error: {e.msg}", path,
                        e.lineno or 0, (e.offset or 1) - 1)]
    imports = ImportTable()
    imports.collect(tree)
    ctx = Context(path=path, imports=imports)
    Walker(ctx, _instantiate(select, ignore)).walk(tree)
    per_line, file_wide = parse_suppressions(source)
    kept = [f for f in ctx.findings if not _suppressed(f, per_line, file_wide)]
    return sorted(kept, key=lambda f: (f.path, f.line, f.col, f.rule_id))


def iter_python_files(paths: Iterable[str]) -> list[str]:
    paths = list(paths)
    out = []
    for path in paths:
        if os.path.isdir(path):
            for dirpath, dirnames, filenames in os.walk(path):
                # prune only cache/VCS dirs: skipping a broader name like
                # "build" could silently exclude real source from the gate
                dirnames[:] = sorted(
                    d for d in dirnames
                    if d not in ("__pycache__", ".git"))
                out.extend(os.path.join(dirpath, f)
                           for f in sorted(filenames) if f.endswith(".py"))
        elif os.path.isfile(path):
            out.append(path)  # explicit file arg: lint it, .py or not
        else:
            # a typo'd path silently reporting "0 findings" would leave a
            # CI gate green while linting nothing
            raise FileNotFoundError(f"{path}: no such file or directory")
    if not out:
        # same CI-gate reasoning: a renamed/emptied package must not
        # report a green "0 findings" over zero linted files
        raise FileNotFoundError(
            f"no python files found under: {', '.join(paths)}")
    return out


def lint_paths(paths: Iterable[str], *, select=None, ignore=None) -> list[Finding]:
    findings: list[Finding] = []
    for fp in iter_python_files(paths):
        with open(fp, encoding="utf-8") as f:
            findings.extend(lint_source(f.read(), fp,
                                        select=select, ignore=ignore))
    return sorted(findings, key=lambda f: (f.path, f.line, f.col, f.rule_id))


def to_json(findings: Sequence[Finding]) -> str:
    return json.dumps([f.as_dict() for f in findings], indent=2,
                      sort_keys=False)

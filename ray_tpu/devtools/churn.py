"""Simulated-churn harness: control-plane scale under node failure.

ROADMAP item 5's "thousand-node simulated-churn bench" — N lightweight
simulated raylet endpoints (a real RPC server + the real
:class:`~ray_tpu.core.raylet.ResourceLedger` bundle accounting, but no
worker pool and no shm arena, so hundreds fit in one process) register
with a real GCS and then join/leave on a seeded schedule while placement
groups and PG-bound actors are created, killed off their nodes, and
repaired. The same discipline as the chaos subsystem (Basiri et al.):
the churn schedule is a seeded RNG stream and the GCS-side 2PC faults
come from a seeded :class:`~ray_tpu.devtools.chaos.plan.ChaosPlan`
(``gcs.pg_prepare`` / ``gcs.pg_commit`` points), so a failing run
replays byte-for-byte.

Emits the BENCHVS rows that make scheduling scale under failure a
tracked number:

- ``pg_create_removal_per_s`` — PG create+remove cycles sustained while
  nodes churn underneath,
- ``pg_reschedule_p99_ms``   — node death → RESCHEDULING → CREATED
  repair latency, measured from the GCS's "pgs" pubsub stream,
- ``churn_unsatisfied_pg_s`` — total PG·seconds spent out of CREATED
  (the capacity-unavailability integral the repair loop minimizes).

The post-run :meth:`ChurnHarness.audit` is the leak oracle: every
bundle reservation held by a surviving node must belong to a live,
CREATED PG that assigns it to exactly that node — anything else is a
leak (and the tier-1 churn test asserts there are none).

Usage (also the bench.py ``pg_churn`` arm and
``tests/test_pg_ft.py::test_seeded_churn_plan_zero_leaks``)::

    h = ChurnHarness(nodes=64, seed=7)
    h.start()
    try:
        metrics = h.run(duration_s=10.0)
        leaks = h.audit()
    finally:
        h.stop()
"""

from __future__ import annotations

import asyncio
import logging
import random
import time

from ray_tpu.config import get_config
from ray_tpu.core.gcs import GcsServer
from ray_tpu.core.raylet import ResourceLedger
from ray_tpu.utils import aio, rpc
from ray_tpu.utils.ids import ActorID, NodeID, PlacementGroupID
from ray_tpu.utils.recorder import percentile

log = logging.getLogger(__name__)


class SimRaylet:
    """A raylet-shaped control-plane endpoint: registers with the GCS,
    heartbeats, and accounts placement-group bundles through the real
    :class:`ResourceLedger` (prepare/commit/return + the stale-bundle
    lease GC) — but grants *simulated* worker leases (it answers the
    worker-side ``create_actor`` RPC itself), spawns no processes and
    maps no shm. One asyncio server per node: hundreds per process."""

    def __init__(self, gcs_address: tuple[str, int],
                 resources: dict[str, float] | None = None,
                 host: str = "127.0.0.1"):
        self.cfg = get_config()
        self.node_id = NodeID.generate()
        self.gcs_address = gcs_address
        res = dict(resources or {"CPU": 8.0})
        res.setdefault("node", 1.0)
        self.ledger = ResourceLedger(res)
        # plain asyncio server on purpose: the native mux would cost one
        # epoll thread per simulated node
        self.server = rpc.RpcServer(host, 0)
        self.server.add_routes(self)
        self.gcs: rpc.Connection | None = None
        self._lease_seq = 0
        self._alive = False
        self._bg = aio.TaskGroup()

    # ------------------------------------------------------------ lifecycle
    async def start(self) -> tuple[str, int]:
        addr = await self.server.start()
        self.gcs = await rpc.connect(*self.gcs_address, timeout=10)
        await self._register()
        self._alive = True
        self._bg.spawn(self._heartbeat_loop())
        self._bg.spawn(self._bundle_gc_loop())
        return addr

    async def _register(self) -> None:
        """Registration payload + held-bundle reconciliation — one
        code path for the initial register and the restarted-GCS
        re-register (the heartbeat path), so they can't drift."""
        reply = await self.gcs.call("register_node", {
            "node_id": self.node_id,
            "address": self.server.address,
            "store_name": f"/sim_{self.node_id.hex()[:8]}",
            "resources": self.ledger.total,
            "labels": {"sim": "1"},
            "pid": 0,
            "bundles": self._held_bundles(),
        })
        for key in reply.get("return_bundles") or ():
            self.ledger.return_bundle(tuple(key))

    async def kill(self):
        """Abrupt death: close everything with no goodbyes — the GCS
        discovers the loss via the connection drop (one reap tick)."""
        self._alive = False
        await self._bg.cancel_all()
        if self.gcs is not None:
            try:
                await self.gcs.close()
            except (rpc.RpcError, OSError):
                pass  # hard-death semantics
        await self.server.stop()

    stop = kill  # sim nodes have nothing to drain

    async def _heartbeat_loop(self):
        version = 0
        while self._alive:
            version += 1
            try:
                reply = await self.gcs.call("heartbeat", {
                    "node_id": self.node_id,
                    "resources_available": self.ledger.available,
                    "version": version,
                })
                if isinstance(reply, dict) and not reply.get("ok", True):
                    # restarted GCS doesn't know this node: re-register
                    await self._register()
            except Exception:
                log.debug("sim heartbeat failed", exc_info=True)
            await asyncio.sleep(self.cfg.health_check_period_s)

    async def _bundle_gc_loop(self):
        lease_s = getattr(self.cfg, "pg_bundle_lease_s", 30.0)
        if lease_s <= 0:
            return
        while self._alive:
            await asyncio.sleep(max(0.2, lease_s / 4))
            self.ledger.gc_stale_bundles(time.monotonic(), lease_s)

    def _held_bundles(self) -> list[dict]:
        return self.ledger.held_bundles()

    # ------------------------------------------------------- bundle plane
    async def rpc_prepare_bundle(self, conn, p):
        key = (p["pg_id"], p["bundle_index"])
        return {"ok": self.ledger.prepare_bundle(key, p["resources"])}

    async def rpc_commit_bundle(self, conn, p):
        return {"ok": self.ledger.commit_bundle(
            (p["pg_id"], p["bundle_index"]))}

    async def rpc_return_bundle(self, conn, p):
        self.ledger.return_bundle((p["pg_id"], p["bundle_index"]))
        return {"ok": True}

    async def rpc_prepare_bundles(self, conn, p):
        """Batched 2PC phase 1 (protocol 2.0) — mirrors the real raylet."""
        return [{"ok": self.ledger.prepare_bundle((p["pg_id"], idx), res)}
                for idx, res in p["bundles"]]

    async def rpc_commit_bundles(self, conn, p):
        return [{"ok": self.ledger.commit_bundle((p["pg_id"], idx))}
                for idx in p["indices"]]

    async def rpc_list_bundles(self, conn, p):
        return self._held_bundles()

    # ---------------------------------------------------- simulated leases
    async def rpc_lease_worker(self, conn, p):
        """Simulated grant: resources allocate from the real ledger (PG
        bundles included) but the "worker" is this server itself — the
        GCS's follow-up ``create_actor`` RPC lands back here."""
        resources = dict(p.get("resources") or {"CPU": 1.0})
        pg_key = None
        if p.get("pg_id") is not None:
            pg_key = (p["pg_id"], p.get("bundle_index", 0))
            granted = self.ledger.bundle_allocate(pg_key, resources)
        else:
            granted = self.ledger.allocate(resources)
        if not granted:
            return {"granted": False}
        self._lease_seq += 1
        return {
            "granted": True,
            "lease_id": self._lease_seq,
            "worker_address": self.server.address,
            "worker_id": f"sim-{self.node_id.hex()[:8]}-{self._lease_seq}",
            "node_id": self.node_id,
            "tpu_chips": None,
        }

    async def rpc_lease_workers(self, conn, p):
        """Batched grants (protocol 2.0): one ledger pass, positional
        replies — the path _schedule_actor's lease coalescer takes."""
        return [await self.rpc_lease_worker(conn, req)
                for req in p["requests"]]

    async def rpc_return_lease(self, conn, p):
        return True  # sim leases are not tracked per-id

    # ------------------------------------------------- simulated worker RPC
    async def rpc_create_actor(self, conn, p):
        return {"ok": True}

    async def rpc_exit_worker(self, conn, p):
        return True


class ChurnHarness:
    """A real GCS + N :class:`SimRaylet` endpoints + a seeded churn/
    workload driver, all on one background event loop."""

    def __init__(self, *, nodes: int = 24, cpus_per_node: float = 8.0,
                 seed: int = 0, io: rpc.EventLoopThread | None = None):
        self.cfg = get_config()
        self.n_nodes = nodes
        self.cpus_per_node = cpus_per_node
        self.rng = random.Random(seed)
        self._own_io = io is None
        self.io = io or rpc.EventLoopThread()
        self.gcs = GcsServer()
        self.gcs_address: tuple[str, int] | None = None
        self.sims: list[SimRaylet] = []
        self.client: rpc.Connection | None = None
        #: "pgs" pubsub stream with a local receive timestamp per event —
        #: the measurement tap every churn metric derives from
        self.events: list[dict] = []
        self._persistent: list[PlacementGroupID] = []
        self._actors: list[ActorID] = []

    # ------------------------------------------------------------ lifecycle
    def start(self) -> None:
        from ray_tpu.devtools import chaos

        chaos.maybe_arm()  # seeded 2PC faults ride the config flag table
        self.gcs_address = self.io.run(self.gcs.start())
        self.client = self.io.run(
            rpc.connect(*self.gcs_address, timeout=10))
        self.client.on_message = self._on_push
        self.io.run(self.client.call("subscribe", {"channel": "pgs"}))
        for _ in range(self.n_nodes):
            self.add_node()

    def add_node(self) -> SimRaylet:
        sim = SimRaylet(self.gcs_address,
                        resources={"CPU": self.cpus_per_node})
        self.io.run(sim.start())
        self.sims.append(sim)
        return sim

    def stop(self) -> None:
        for sim in list(self.sims):
            try:
                self.io.run(sim.stop())
            except Exception:
                log.debug("sim stop failed", exc_info=True)
        self.sims.clear()
        if self.client is not None:
            try:
                self.io.run(self.client.close())
            except Exception:
                log.debug("client close failed", exc_info=True)
        try:
            self.io.run(self.gcs.stop())
        except Exception:
            log.debug("gcs stop failed", exc_info=True)
        if self._own_io:
            self.io.stop()

    def _on_push(self, msg):
        if msg.get("m") != "pubsub":
            return
        p = msg["p"]
        if p.get("channel") == "pgs" and isinstance(p.get("message"), dict):
            self.events.append(
                dict(p["message"], recv_ts=time.monotonic()))

    # -------------------------------------------------------------- workload
    def run(self, duration_s: float = 10.0, *, pg_cyclers: int = 4,
            persistent_pgs: int = 6, bundles_per_pg: int = 2,
            actors_per_pg: int = 1, strategy: str = "SPREAD",
            kill_every_s: float = 1.0, respawn_delay_s: float = 0.4,
            min_nodes: int = 4, settle_s: float = 20.0) -> dict:
        """Drive churn for ``duration_s``: ``pg_cyclers`` loops create+
        remove short-lived PGs, ``persistent_pgs`` PGs (each with
        ``actors_per_pg`` simulated PG-bound actors) live through the
        churn and get repaired every time a bundle-holding node dies,
        and the churner kills a random sim node every ~``kill_every_s``
        (seeded), respawning a replacement after ``respawn_delay_s``.
        After the clock runs out the harness waits (up to ``settle_s``)
        for every persistent PG to re-converge to CREATED and every sim
        actor to come back ALIVE, then returns the metric dict."""
        return self.io.run(self._run(
            duration_s, pg_cyclers, persistent_pgs, bundles_per_pg,
            actors_per_pg, strategy, kill_every_s, respawn_delay_s,
            min_nodes, settle_s),
            timeout=duration_s + settle_s + 120.0)

    async def _create_pg(self, bundles, strategy) -> tuple:
        pg_id = PlacementGroupID.generate()
        r = await self.client.call("create_placement_group", {
            "pg_id": pg_id, "bundles": bundles, "strategy": strategy})
        return pg_id, r.get("state")

    async def _run(self, duration_s, pg_cyclers, persistent_pgs,
                   bundles_per_pg, actors_per_pg, strategy, kill_every_s,
                   respawn_delay_s, min_nodes, settle_s) -> dict:
        t_start = time.monotonic()
        # persistent PGs + their simulated PG-bound actors
        for _ in range(persistent_pgs):
            bundles = [{"CPU": 1.0}] * bundles_per_pg
            pg_id, state = await self._create_pg(bundles, strategy)
            self._persistent.append(pg_id)
            for i in range(actors_per_pg):
                actor_id = ActorID.generate()
                await self.client.call("register_actor", {"spec": {
                    "actor_id": actor_id,
                    "resources": {"CPU": 0.5},
                    "placement_group": pg_id,
                    "bundle_index": i % bundles_per_pg,
                    "max_restarts": 1000,
                }})
                self._actors.append(actor_id)

        stop = asyncio.Event()
        cycles = 0
        infeasible_creates = 0

        async def cycler(k: int):
            nonlocal cycles, infeasible_creates
            while not stop.is_set():
                pg_id, state = await self._create_pg(
                    [{"CPU": 1.0}], "PACK")
                if state == "CREATED":
                    await self.client.call(
                        "remove_placement_group", {"pg_id": pg_id})
                    cycles += 1
                else:
                    infeasible_creates += 1
                    await self.client.call(
                        "remove_placement_group", {"pg_id": pg_id})
                    await asyncio.sleep(0.05)

        kills = 0

        async def churner():
            nonlocal kills
            while not stop.is_set():
                await asyncio.sleep(
                    kill_every_s * (0.5 + self.rng.random()))
                if stop.is_set() or len(self.sims) <= min_nodes:
                    continue
                # prefer bundle-holding victims (seeded choice): the
                # interesting failure is a node that takes PG capacity
                # with it — a miss only exercises the node-removed path
                holders = [i for i, s in enumerate(self.sims)
                           if s.ledger.bundles]
                pool = holders or range(len(self.sims))
                victim = self.sims.pop(self.rng.choice(list(pool)))
                kills += 1
                await victim.kill()
                await asyncio.sleep(respawn_delay_s)
                if not stop.is_set():
                    sim = SimRaylet(
                        self.gcs_address,
                        resources={"CPU": self.cpus_per_node})
                    await sim.start()
                    self.sims.append(sim)

        tasks = [asyncio.ensure_future(cycler(k))
                 for k in range(pg_cyclers)]
        tasks.append(asyncio.ensure_future(churner()))
        await asyncio.sleep(duration_s)
        stop.set()
        for t in tasks:
            t.cancel()
        await asyncio.gather(*tasks, return_exceptions=True)
        elapsed = time.monotonic() - t_start

        # settle: every persistent PG back to CREATED, every actor ALIVE
        settle_deadline = time.monotonic() + settle_s
        unsettled = set(self._persistent)
        while unsettled and time.monotonic() < settle_deadline:
            for pg_id in list(unsettled):
                info = await self.client.call(
                    "get_placement_group", {"pg_id": pg_id})
                if info and info["state"] == "CREATED":
                    unsettled.discard(pg_id)
            if unsettled:
                await asyncio.sleep(0.1)
        actors_alive = 0
        while time.monotonic() < settle_deadline:
            rows = await self.client.call("list_actors", {})
            by_id = {r["actor_id"]: r for r in rows}
            actors_alive = sum(
                1 for a in self._actors
                if by_id.get(a, {}).get("state") == "ALIVE")
            if actors_alive == len(self._actors):
                break
            await asyncio.sleep(0.1)
        settle_end = time.monotonic()

        return {
            "pg_create_removal_per_s": cycles / max(elapsed, 1e-9),
            "pg_cycles": cycles,
            "infeasible_creates": infeasible_creates,
            "node_kills": kills,
            "nodes_alive": len(self.sims),
            "unsettled_pgs": len(unsettled),
            "actors_total": len(self._actors),
            "actors_alive": actors_alive,
            **self._episode_metrics(settle_end),
        }

    # -------------------------------------------------------------- metrics
    def _episode_metrics(self, end_ts: float) -> dict:
        """Reschedule episodes from the "pgs" event stream: each
        RESCHEDULING push opens an episode for its pg, the next CREATED
        push closes it. Durations use the harness's receive clock (one
        host, one clock domain)."""
        open_at: dict[str, float] = {}
        durations: list[float] = []
        reschedules = 0
        for ev in self.events:
            pg_hex, state = ev.get("pg_id"), ev.get("state")
            ts = ev["recv_ts"]
            if state == "RESCHEDULING":
                reschedules += 1
                open_at.setdefault(pg_hex, ts)
            elif state in ("CREATED", "REMOVED") and pg_hex in open_at:
                durations.append(ts - open_at.pop(pg_hex))
        # still-open episodes accrue unsatisfied time to the end
        unsatisfied = sum(durations) + sum(
            end_ts - t0 for t0 in open_at.values())
        durations.sort()
        return {
            "pg_reschedules": reschedules,
            "pg_reschedule_p50_ms": percentile(durations, 0.5) * 1e3,
            "pg_reschedule_p99_ms": percentile(durations, 0.99) * 1e3,
            "churn_unsatisfied_pg_s": unsatisfied,
            "open_reschedules": len(open_at),
        }

    # ---------------------------------------------------------------- audit
    def audit(self) -> dict:
        """The leak oracle. Cross-checks every surviving node's bundle
        table against the GCS pgs table:

        - ``leaked``: a reservation held for a REMOVED/unknown PG, for a
          bundle assigned to a different node, or still uncommitted
          after settle;
        - ``missing``: a CREATED PG bundle whose assigned (alive,
          simulated) node does not actually hold the reservation.

        Zero of both is the acceptance bar the churn test asserts."""
        return self.io.run(self._audit())

    async def _audit(self) -> dict:
        leaked: list[dict] = []
        missing: list[dict] = []
        pgs = dict(self.gcs.pgs)
        held_by_node: dict[NodeID, dict[tuple, dict]] = {}
        for sim in self.sims:
            held_by_node[sim.node_id] = {
                (b["pg_id"], b["bundle_index"]): b
                for b in sim._held_bundles()
            }
        for sim in self.sims:
            for (pg_id, index), b in held_by_node[sim.node_id].items():
                pg = pgs.get(pg_id)
                if pg is None or pg.state == "REMOVED":
                    leaked.append({"node": sim.node_id.hex()[:12],
                                   "pg": pg_id.hex()[:12], "bundle": index,
                                   "why": "pg removed/unknown"})
                elif (index >= len(pg.bundle_nodes)
                        or pg.bundle_nodes[index] != sim.node_id):
                    leaked.append({"node": sim.node_id.hex()[:12],
                                   "pg": pg_id.hex()[:12], "bundle": index,
                                   "why": "assigned elsewhere"})
                elif not b.get("committed"):
                    leaked.append({"node": sim.node_id.hex()[:12],
                                   "pg": pg_id.hex()[:12], "bundle": index,
                                   "why": "uncommitted after settle"})
        sim_ids = set(held_by_node)
        for pg in pgs.values():
            if pg.state != "CREATED":
                continue
            for index, nid in enumerate(pg.bundle_nodes):
                if (nid in sim_ids
                        and (pg.pg_id, index) not in held_by_node[nid]):
                    missing.append({"node": nid.hex()[:12],
                                    "pg": pg.pg_id.hex()[:12],
                                    "bundle": index})
        return {"leaked": leaked, "missing": missing}

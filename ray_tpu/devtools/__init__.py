"""Developer tooling for ray_tpu: static analysis, correctness gates.

Counterpart of the reference repo's ci/lint stack (ref: ci/lint/*,
.bazelrc sanitizer configs): the native side is covered by the sanitizer
matrix in tests/test_store_tsan.py, the Python API layer by
`ray_tpu.devtools.lint` (raylint).
"""

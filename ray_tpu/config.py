"""Central flag table for the runtime.

TPU-native equivalent of the reference's ``RAY_CONFIG(type, name, default)``
table (ref: src/ray/common/ray_config_def.h:22) — a single declarative flag
registry, overridable per-process with ``RT_<NAME>`` environment variables and
serialized to every spawned process so the whole cluster agrees on one config.
"""

from __future__ import annotations

import dataclasses
import json
import os
from typing import Any

_ENV_PREFIX = "RT_"
_SERIALIZED_ENV = "RT_SYSTEM_CONFIG"


def _env_override(name: str, default: Any) -> Any:
    raw = os.environ.get(_ENV_PREFIX + name.upper())
    if raw is None:
        return default
    if isinstance(default, bool):
        return raw.lower() in ("1", "true", "yes", "on")
    if isinstance(default, int):
        return int(raw)
    if isinstance(default, float):
        return float(raw)
    return raw


@dataclasses.dataclass
class Config:
    # --- object store (plasma-equivalent; ref: src/ray/object_manager/plasma) ---
    object_store_memory: int = 512 * 1024 * 1024  # bytes of shm per node
    #: objects at or below this many bytes are returned inline in the task
    #: reply and live in the owner's in-process memory store
    #: (ref: RAY_CONFIG max_direct_call_object_size, ray_config_def.h:203).
    max_inline_object_size: int = 100 * 1024
    #: chunk size for inter-node object transfer
    object_transfer_chunk_size: int = 4 * 1024 * 1024
    #: spill sealed objects to disk when the arena passes this fraction
    #: (ref: local_object_manager.h:42 spill under pressure); <= 0 disables
    object_spilling_threshold: float = 0.8
    #: spill down to this fraction once triggered
    object_spilling_low_water: float = 0.6
    #: directory for spilled objects ("" = <temp_dir>/<session>/spill)
    object_spilling_dir: str = ""

    # --- memory tiering (spill/restore as a storage tier; ref:
    # pull_manager.h:49 admission window, local_object_manager.h:42) ---
    #: byte budget for concurrent restores/pulls in flight per raylet
    #: (PullManager-shaped admission window); excess queues FIFO
    pull_max_bytes_in_flight: int = 64 * 1024 * 1024
    #: seconds a queued pull/restore waits for admission before it is
    #: shed with a typed back-pressure error
    pull_admission_timeout_s: float = 30.0
    #: cooperative spill only claims arena-owner candidates untouched for
    #: at least this long (keeps mid-adoption pages hot)
    spill_cold_after_s: float = 0.25
    #: prefix cache spills unpinned pages to tier-1 instead of dropping
    #: them (the radix tree keeps the node; refs swap to disk)
    prefix_cache_spill: bool = True
    #: disk budget for tier-1 prefix-cache pages; beyond it the cache
    #: falls back to dropping LRU tier-1 leaves (the old eviction)
    prefix_cache_tier1_bytes: int = 1024 * 1024 * 1024

    # --- scheduler / raylet ---
    #: max workers a single raylet will fork
    max_workers_per_node: int = 64
    #: idle workers kept warm per node
    min_idle_workers: int = 1
    #: seconds before an idle leased worker is returned to the pool
    worker_lease_timeout_s: float = 10.0
    #: path to a C++ worker binary (rt_cpp_api.h + RT_REMOTE functions) for
    #: language="cpp" tasks; RT_CPP_WORKER env overrides (ref: cpp/ worker)
    cpp_worker_binary: str = ""
    #: place each worker in a kernel cgroup; a lease's "memory" resource
    #: becomes the worker's memory cap (ref: cgroup_manager.h "physical
    #: execution mode"). Needs a writable cgroup hierarchy.
    enable_worker_cgroups: bool = False
    #: hybrid scheduling: prefer local node until this utilization fraction
    #: (ref: hybrid_scheduling_policy.h:50)
    hybrid_threshold: float = 0.5
    #: concurrent lease requests per scheduling key (pipelined worker
    #: acquisition under bursts; ref: normal_task_submitter lease pipelining)
    max_lease_parallelism: int = 8
    #: max task specs pushed to a leased worker in one rpc frame — a deep
    #: backlog amortizes frame/pickle/loop-wakeup costs across the batch
    #: (ref: normal_task_submitter.cc direct PushTask pipelining)
    push_batch_size: int = 32

    # --- native fast path (shm task rings; ref: normal_task_submitter.cc
    # steady-state lease-cached PushTask loop — see core/fastpath.py) ---
    #: route eligible same-node task submissions over native shm rings
    fastpath_enabled: bool = True
    #: per-direction ring capacity in bytes
    fastpath_ring_bytes: int = 4 * 1024 * 1024
    #: task records above this size take the RPC path (big args belong in
    #: the object store, and the pop buffer must always fit one record)
    fastpath_record_max: int = 256 * 1024
    #: max unreplied fast-path tasks per worker before spilling to RPC
    fastpath_inflight_max: int = 4096
    #: coalesced ring flush: during a submit burst, records buffer until
    #: this many are pending (or fastpath_flush_max_bytes), then push in
    #: ONE native batch — one ring lock round + one consumer wake per
    #: batch instead of per record. 1 disables buffering entirely.
    fastpath_flush_max_records: int = 16
    #: byte cap for one coalesced flush batch
    fastpath_flush_max_bytes: int = 64 * 1024
    #: background flusher linger: how long a buffered burst tail may sit
    #: before the flusher thread pushes it (bounds worst-case added
    #: latency for fire-and-forget submits; get()/prepass flush sooner)
    fastpath_flush_linger_us: int = 300
    #: completion fast lane: results at or below this many bytes travel
    #: inside the ring completion record itself (no object-store put, no
    #: location registration); larger results are sealed into the node's
    #: shm arena and the record carries (size) so the driver's location
    #: cache is primed at completion time
    fastpath_inline_result_max: int = 8 * 1024
    #: how long the worker pump keeps retrying a partial reply-ring push
    #: before spilling the undelivered completion records to the driver
    #: over RPC (driver stalled / result ring full)
    fastpath_reply_spill_ms: int = 200
    #: serve data plane: route same-node replica calls over the actor shm
    #: rings (serve/dataplane) instead of the actor RPC plane; per-call
    #: RPC fallback (ref args, big payloads, broken lane) is always kept.
    #: Off switch for A/B (bench.py serve arm) and paranoia.
    serve_fastlane: bool = True

    # --- cross-node node tunnel (core/tunnel.py; ref: Pathways'
    # per-host dataflow channels — descriptors, not payloads, between
    # persistent per-host endpoints) ---
    #: route cross-node actor/serve/task calls over one persistent,
    #: multiplexed connection per node pair carrying the SAME packed
    #: wire records the shm rings use (coalesced frames instead of
    #: per-call pickled RPC specs); per-call RPC fallback always kept.
    #: Off switch for A/B (bench.py tunnel arm) and paranoia.
    node_tunnel: bool = True
    #: tunnel records above this many bytes do not ship their big args
    #: inline: each oversized top-level value seals into the sender's
    #: local shm arena and the record carries a (node, oid, nbytes)
    #: descriptor the receiver adopts via ONE batched pull
    tunnel_inline_max: int = 64 * 1024
    #: bench/test hook: bind tunnel lanes even for same-node actors
    #: (disables the same-node shm-ring shortcut so two raylets on one
    #: host exercise the full tunnel path)
    tunnel_force: bool = False
    #: reconnect-with-backoff ceiling for a broken tunnel connection;
    #: lanes break (per-call RPC fallback) the moment the tunnel drops
    #: and revive once the redial lands
    tunnel_reconnect_max_s: float = 5.0

    # --- native RPC mux (ref: grpc_server.h:88 completion-queue threads;
    # _native/src/mux.cc) ---
    #: serve control-plane RPC off a C++ epoll mux instead of asyncio
    #: streams (fan-in: N clients never serialize through per-connection
    #: reader coroutines); falls back to asyncio if the build is missing
    native_mux_enabled: bool = True
    #: the mux only engages on hosts with at least this many cores: its
    #: IO thread runs CONCURRENTLY with Python (the entire win), but on a
    #: 1-2 core host that thread and its eventfd wakes just preempt the
    #: interpreter — measured 25-35% slower there, faster with spare cores
    native_mux_min_cpus: int = 4

    # --- tracing (ref: util/tracing/tracing_helper.py span injection;
    # Dapper-style wire context — see utils/tracing.py) ---
    #: propagate span contexts through task specs AND the packed
    #: fast-lane/tunnel records (wire 2.1 trace leg), record spans into
    #: the task-event pipeline (state.list_spans / get_trace / timeline)
    tracing_enabled: bool = False
    #: head-based sampling: fraction of ROOTS (serve requests, driver
    #: .remote() calls with no active context) that start a sampled
    #: trace; children inherit the decision from the wire leg. The
    #: unsampled path is one contextvar read + one branch and ships no
    #: trace bytes (bench.py tracing_overhead_us).
    trace_sample_rate: float = 1.0
    #: GCS trace assembler: max assembled traces retained. Eviction
    #: protects the slowest ``trace_slow_keep`` fraction (the p99
    #: outliers you debug) and drops the oldest of the rest.
    trace_table_max: int = 512
    #: per-trace span cap (a runaway span loop can't eat the table)
    trace_spans_max: int = 512
    #: fraction of the slowest traces exempt from age-based eviction
    trace_slow_keep: float = 0.1
    #: ns="latency" KV retention: entries not republished for this many
    #: seconds (dead workers' leftover windows) are swept by the GCS
    #: health loop; <= 0 disables the sweep
    latency_retention_s: float = 600.0
    #: GCS task-event ring cap (also bounds the span history riding it)
    gcs_task_events_cap: int = 100_000

    # --- memory protection (ref: memory_monitor.h:52) ---
    #: fraction of system memory in use that triggers OOM killing;
    #: <= 0 disables the monitor
    memory_usage_threshold: float = 0.95
    memory_monitor_refresh_s: float = 1.0

    # --- GCS durability (ref: ray_config_def.h GCS storage knobs) ---
    #: opt-in machine-crash durability for the GCS WAL: every journaled
    #: table write is fdatasync'd (group-committed — concurrent writes in
    #: one loop tick share a single sync) before its RPC is acked, and
    #: snapshots fsync the tmp file before the rename plus the directory
    #: after it. Default off: the WAL is flushed to the OS page cache on
    #: every append, which survives a GCS process kill but not a machine
    #: crash/power loss.
    gcs_fsync: bool = False

    # --- chaos / fault injection (devtools/chaos; ref: the reference's
    # ResourceKiller-driven chaos tests, _private/test_utils.py:1419) ---
    #: arm the deterministic fault-injection controller in every process
    #: (driver, raylets, workers, GCS). Off = every chaos.point() site is
    #: a module-flag check compiled down to a falsy branch.
    chaos_enabled: bool = False
    #: ChaosPlan JSON: a file path, or an inline JSON object string
    chaos_plan: str = ""
    #: override the plan's seed (< 0 = use the plan's own)
    chaos_seed: int = -1
    #: fault-event JSONL dir ("" = <temp_dir>/chaos); read back by
    #: state.list_chaos_events() and `ray_tpu chaos events`
    chaos_log_dir: str = ""

    # --- timeouts / health (ref: gcs_health_check_manager.h:59) ---
    health_check_period_s: float = 1.0
    health_check_failure_threshold: int = 5
    rpc_connect_timeout_s: float = 30.0
    worker_start_timeout_s: float = 60.0
    #: raylet-side lease on a PREPARED-but-uncommitted placement-group
    #: bundle reservation: if the coordinating GCS dies between the 2PC
    #: prepare and commit, the raylet returns the reservation after this
    #: many seconds instead of leaking the capacity forever (a repeated
    #: prepare — the GCS repairing/retrying — refreshes the lease);
    #: <= 0 disables the GC
    pg_bundle_lease_s: float = 30.0

    # --- task / actor fault tolerance ---
    default_max_task_retries: int = 3
    default_max_actor_restarts: int = 0
    #: max bytes of lineage kept per owner for reconstruction
    #: (ref: task_manager.h:182)
    lineage_bytes_limit: int = 64 * 1024 * 1024

    # --- observability ---
    task_events_report_interval_s: float = 1.0
    #: hot-path flight recorder (utils/recorder.py): always-on ring of
    #: ns-stamped stage events per process, < 1µs/task budget (bench.py
    #: recorder_overhead_us). Off switch for A/B and paranoia.
    recorder_enabled: bool = True
    #: slots per process recorder ring (also the driver's retained
    #: latency-sample window); fixed-size, drop-oldest
    recorder_events_cap: int = 4096
    log_dir: str = ""
    temp_dir: str = "/tmp/ray_tpu"

    # --- collective / TPU ---
    #: default collective timeout
    collective_timeout_s: float = 120.0
    #: virtual CPU devices for tests; 0 = use real devices
    force_cpu_devices: int = 0

    def __post_init__(self) -> None:
        for f in dataclasses.fields(self):
            setattr(self, f.name, _env_override(f.name, getattr(self, f.name)))

    # -- propagation to child processes -------------------------------------
    def to_env(self) -> dict:
        """Serialize so spawned processes reconstruct the identical config."""
        return {_SERIALIZED_ENV: json.dumps(dataclasses.asdict(self))}

    @classmethod
    def from_env(cls) -> "Config":
        raw = os.environ.get(_SERIALIZED_ENV)
        cfg = cls()
        if raw:
            for k, v in json.loads(raw).items():
                if hasattr(cfg, k):
                    setattr(cfg, k, v)
            # env vars still win over the serialized blob
            for f in dataclasses.fields(cfg):
                setattr(cfg, f.name, _env_override(f.name, getattr(cfg, f.name)))
        return cfg


_global_config: Config | None = None


def get_config() -> Config:
    global _global_config
    if _global_config is None:
        _global_config = Config.from_env()
    return _global_config


def set_config(cfg: Config) -> None:
    global _global_config
    _global_config = cfg

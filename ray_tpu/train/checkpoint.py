"""Checkpoint: directory-of-files abstraction + top-K retention.

(ref: python/ray/train/_checkpoint.py Checkpoint,
_internal/checkpoint_manager.py CheckpointManager). Model state uses
orbax/msgpack-free numpy save under the hood via to_directory; jax pytrees
are handled with ray_tpu.utils.serialization (host numpy representation).
"""

from __future__ import annotations

import os
import pickle
import shutil
import tempfile
import time
from typing import Any


class Checkpoint:
    def __init__(self, path: str):
        self.path = path

    @classmethod
    def from_directory(cls, path: str) -> "Checkpoint":
        return cls(os.path.abspath(path))

    @classmethod
    def from_dict(cls, data: dict) -> "Checkpoint":
        """Convenience: persist a pytree dict (host numpy) as a checkpoint."""
        import jax
        import numpy as np

        tmp = tempfile.mkdtemp(prefix="rt_ckpt_")
        host = jax.tree.map(
            lambda x: np.asarray(x) if hasattr(x, "shape") else x, data
        )
        with open(os.path.join(tmp, "state.pkl"), "wb") as f:
            pickle.dump(host, f, protocol=5)
        return cls(tmp)

    def to_dict(self) -> dict:
        with open(os.path.join(self.path, "state.pkl"), "rb") as f:
            return pickle.load(f)

    def as_directory(self) -> str:
        return self.path

    def __repr__(self):
        return f"Checkpoint({self.path})"


class CheckpointManager:
    """Top-K retention on a storage path (ref: checkpoint_manager.py)."""

    def __init__(self, storage_path: str, num_to_keep: int | None = None,
                 score_attribute: str | None = None, score_order: str = "max"):
        self.storage_path = storage_path
        self.num_to_keep = num_to_keep
        self.score_attribute = score_attribute
        self.score_order = score_order
        self.checkpoints: list[tuple[float, str, dict]] = []  # (score, path, metrics)
        self._seq = 0  # monotonic: len(checkpoints) shrinks on evict and would collide
        os.makedirs(storage_path, exist_ok=True)

    def register(self, checkpoint: Checkpoint, metrics: dict) -> Checkpoint:
        self._seq += 1
        name = f"checkpoint_{int(time.time() * 1000)}_{self._seq:06d}"
        dest = os.path.join(self.storage_path, name)
        if os.path.abspath(checkpoint.path) != os.path.abspath(dest):
            shutil.copytree(checkpoint.path, dest)
        score = self._score(metrics)
        self.checkpoints.append((score, dest, dict(metrics)))
        self._evict()
        return Checkpoint(dest)

    def _score(self, metrics: dict) -> float:
        if self.score_attribute and self.score_attribute in metrics:
            v = float(metrics[self.score_attribute])
            return v if self.score_order == "max" else -v
        return float(len(self.checkpoints))  # recency

    def _evict(self):
        if self.num_to_keep is None:
            return
        while len(self.checkpoints) > self.num_to_keep:
            self.checkpoints.sort(key=lambda t: t[0])
            score, path, _ = self.checkpoints.pop(0)
            shutil.rmtree(path, ignore_errors=True)

    def best(self) -> Checkpoint | None:
        if not self.checkpoints:
            return None
        return Checkpoint(max(self.checkpoints, key=lambda t: t[0])[1])

    def latest(self) -> Checkpoint | None:
        if not self.checkpoints:
            return None
        return Checkpoint(self.checkpoints[-1][1])

"""Per-worker training session (ref: python/ray/train/_internal/session.py:
report / get_checkpoint / world_rank live here)."""

from __future__ import annotations

import dataclasses
import queue
from typing import Any

from ray_tpu.train.checkpoint import Checkpoint

_session: "TrainSession | None" = None


@dataclasses.dataclass
class TrainContext:
    world_rank: int
    world_size: int
    local_rank: int
    trial_name: str
    collective_group: str

    def get_world_rank(self) -> int:
        return self.world_rank

    def get_world_size(self) -> int:
        return self.world_size

    def get_local_rank(self) -> int:
        return self.local_rank


class TrainSession:
    def __init__(self, context: TrainContext, checkpoint: Checkpoint | None = None):
        self.context = context
        self.starting_checkpoint = checkpoint
        self.reports: list[dict] = []
        #: (metrics, checkpoint) tuples drained by the controller poll
        self.outbox: queue.Queue = queue.Queue()

    def report(self, metrics: dict, checkpoint: Checkpoint | None = None):
        self.reports.append(metrics)
        self.outbox.put((dict(metrics), checkpoint))


def init_session(context: TrainContext, checkpoint: Checkpoint | None = None) -> TrainSession:
    global _session
    _session = TrainSession(context, checkpoint)
    return _session


def get_session() -> TrainSession:
    if _session is None:
        raise RuntimeError("not inside a ray_tpu.train worker")
    return _session


def get_context() -> TrainContext:
    return get_session().context


def report(metrics: dict, checkpoint: Checkpoint | None = None) -> None:
    """Report metrics (+ optional checkpoint) to the trainer controller."""
    get_session().report(metrics, checkpoint)


def get_checkpoint() -> Checkpoint | None:
    return get_session().starting_checkpoint

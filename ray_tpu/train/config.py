"""Training configs (ref: python/ray/air/config.py ScalingConfig/RunConfig/
CheckpointConfig/FailureConfig — same shape, TPU resource vocabulary)."""

from __future__ import annotations

import dataclasses
from typing import Any


@dataclasses.dataclass
class ScalingConfig:
    num_workers: int = 1
    use_tpu: bool = False
    #: resources per worker actor, e.g. {"TPU": 4.0}
    resources_per_worker: dict[str, float] | None = None
    #: PG strategy: STRICT_PACK = one ICI domain (ref: SURVEY §7 step 2)
    placement_strategy: str = "PACK"
    #: per-worker collective backend: "xla" on TPU pods, "cpu" for tests
    collective_backend: str | None = None

    def worker_resources(self) -> dict[str, float]:
        res = dict(self.resources_per_worker or {})
        res.setdefault("CPU", 1.0)
        if self.use_tpu:
            res.setdefault("TPU", 1.0)
        return res

    def backend(self) -> str:
        if self.collective_backend:
            return self.collective_backend
        return "xla" if self.use_tpu else "cpu"


@dataclasses.dataclass
class CheckpointConfig:
    num_to_keep: int | None = None
    checkpoint_score_attribute: str | None = None
    checkpoint_score_order: str = "max"


@dataclasses.dataclass
class FailureConfig:
    #: worker-group restarts before giving up (-1 = unlimited)
    max_failures: int = 0


@dataclasses.dataclass
class RunConfig:
    name: str | None = None
    storage_path: str | None = None
    checkpoint_config: CheckpointConfig = dataclasses.field(default_factory=CheckpointConfig)
    failure_config: FailureConfig = dataclasses.field(default_factory=FailureConfig)

"""Training configs (ref: python/ray/air/config.py ScalingConfig/RunConfig/
CheckpointConfig/FailureConfig — same shape, TPU resource vocabulary)."""

from __future__ import annotations

import dataclasses
from typing import Any


@dataclasses.dataclass
class ScalingConfig:
    num_workers: int = 1
    use_tpu: bool = False
    #: resources per worker actor, e.g. {"TPU": 4.0}
    resources_per_worker: dict[str, float] | None = None
    #: PG strategy: STRICT_PACK = one ICI domain (ref: SURVEY §7 step 2)
    placement_strategy: str = "PACK"
    #: per-worker collective backend: "xla" on TPU pods, "cpu" for tests
    collective_backend: str | None = None
    #: TPU slice topology, e.g. "v4-16": one worker per slice host, each
    #: taking the host's full chip count + generation marker, gang-placed
    #: STRICT_SPREAD (the TPU-first 'one contiguous slice' request)
    topology: str | None = None

    def __post_init__(self):
        if self.topology:
            from ray_tpu.accelerators.tpu import TPUAcceleratorManager, slice_shape

            if not TPUAcceleratorManager.is_valid_tpu_accelerator_type(self.topology):
                raise ValueError(f"invalid TPU topology {self.topology!r}")
            self.use_tpu = True
            num_hosts, host_chips, gen = slice_shape(self.topology)
            self.num_workers = num_hosts
            if self.resources_per_worker is None:
                self.resources_per_worker = {
                    "TPU": float(host_chips),
                    gen: float(host_chips),
                }
            self.placement_strategy = "STRICT_SPREAD"

    def worker_resources(self) -> dict[str, float]:
        res = dict(self.resources_per_worker or {})
        res.setdefault("CPU", 1.0)
        if self.use_tpu:
            res.setdefault("TPU", 1.0)
        return res

    def backend(self) -> str:
        if self.collective_backend:
            return self.collective_backend
        return "xla" if self.use_tpu else "cpu"


@dataclasses.dataclass
class CheckpointConfig:
    num_to_keep: int | None = None
    checkpoint_score_attribute: str | None = None
    checkpoint_score_order: str = "max"


@dataclasses.dataclass
class FailureConfig:
    #: worker-group restarts before giving up (-1 = unlimited)
    max_failures: int = 0


@dataclasses.dataclass
class RunConfig:
    name: str | None = None
    storage_path: str | None = None
    checkpoint_config: CheckpointConfig = dataclasses.field(default_factory=CheckpointConfig)
    failure_config: FailureConfig = dataclasses.field(default_factory=FailureConfig)

"""Distributed training: JaxTrainer over actor worker groups.

The reference's Train stack re-imagined TPU-first (ref: SURVEY §2.5 Train
v1/v2): a controller drives a worker group of actors (one per TPU host),
workers rendezvous into one jax.distributed world, and the training step
itself is a single pjit program over the pod mesh — DDP/FSDP/TP become
partition specs (parallel/sharding.py), not wrapper modules.
"""

from ray_tpu.train.config import (  # noqa: F401
    CheckpointConfig,
    FailureConfig,
    RunConfig,
    ScalingConfig,
)
from ray_tpu.train.checkpoint import Checkpoint  # noqa: F401
from ray_tpu.train.session import (  # noqa: F401
    get_checkpoint,
    get_context,
    report,
)
from ray_tpu.train.trainer import JaxTrainer, Result, TrainingFailedError  # noqa: F401

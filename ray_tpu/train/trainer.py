"""JaxTrainer: controller + worker-group actors.

The reference's Train-v2 controller shape (ref: train/v2/_internal/execution/
controller/controller.py:93 run:469 — poll workers, apply FailurePolicy;
worker group ref: worker_group.py:105; v1 BackendExecutor ref:
_internal/backend_executor.py:146): a driver-side controller creates N
worker actors in a placement group, initializes the collective rendezvous
(GCS-KV -> jax.distributed on pods; named-actor CPU fake in tests), runs
``train_loop_per_worker`` on each, streams back report()s, keeps top-K
checkpoints, and restarts the whole group at the same world size on worker
failure up to FailureConfig.max_failures (elastic world-size changes imply
an XLA recompile, so group restart is the honest recovery unit —
SURVEY §7 "hard parts").
"""

from __future__ import annotations

import random
import time
import traceback
from typing import Any, Callable

import ray_tpu
from ray_tpu.core.ref import ActorError, TaskError
from ray_tpu.train.checkpoint import Checkpoint, CheckpointManager
from ray_tpu.train.config import RunConfig, ScalingConfig
from ray_tpu.train.session import TrainContext, init_session


class TrainingFailedError(RuntimeError):
    pass


class Result:
    def __init__(self, metrics: dict, checkpoint: Checkpoint | None,
                 metrics_history: list[dict], error: Exception | None = None):
        self.metrics = metrics
        self.checkpoint = checkpoint
        self.metrics_history = metrics_history
        self.error = error

    def __repr__(self):
        return f"Result(metrics={self.metrics}, checkpoint={self.checkpoint})"


class TrainWorker:
    """Actor hosting one training process (one TPU host's worth of chips)."""

    def __init__(self, rank: int, world_size: int, trial_name: str, backend: str,
                 group_name: str):
        self.rank = rank
        self.world_size = world_size
        self.trial_name = trial_name
        self.backend = backend
        self.group_name = group_name
        self._done = False
        self._result: Any = None
        self._error: str | None = None
        self._session = None

    def setup(self, checkpoint_path: str | None):
        import ray_tpu.collective as collective
        from ray_tpu.utils.device import configure_jax

        configure_jax()
        ckpt = Checkpoint.from_directory(checkpoint_path) if checkpoint_path else None
        context = TrainContext(
            world_rank=self.rank,
            world_size=self.world_size,
            local_rank=0,
            trial_name=self.trial_name,
            collective_group=self.group_name,
        )
        self._session = init_session(context, ckpt)
        if self.world_size > 1 or self.backend == "xla":
            collective.init_collective_group(
                self.world_size, self.rank, backend=self.backend,
                group_name=self.group_name,
            )
        return True

    def run(self, train_loop, config: dict):
        """Blocking execution of the user loop (runs on the actor's executor
        thread; poll() is served concurrently by the async loop)."""
        try:
            self._result = train_loop(config) if config is not None else train_loop()
            return {"ok": True}
        except Exception as e:  # noqa: BLE001
            self._error = f"{type(e).__name__}: {e}\n{traceback.format_exc()}"
            return {"ok": False, "error": self._error}
        finally:
            self._done = True

    def poll(self):
        """Drain report() outbox (ref: controller _poll_workers :249).
        _done is read BEFORE draining: a report enqueued between the drain
        and the done-check would otherwise be lost on the final poll."""
        done = self._done
        out = []
        if self._session is not None:
            while not self._session.outbox.empty():
                metrics, ckpt = self._session.outbox.get_nowait()
                out.append((metrics, ckpt.path if ckpt else None))
        return {"reports": out, "done": done, "error": self._error}


class JaxTrainer:
    def __init__(
        self,
        train_loop_per_worker: Callable,
        *,
        train_loop_config: dict | None = None,
        scaling_config: ScalingConfig | None = None,
        run_config: RunConfig | None = None,
        resume_from_checkpoint: Checkpoint | None = None,
        datasets: dict | None = None,
    ):
        self.train_loop = train_loop_per_worker
        self.train_loop_config = train_loop_config
        self.scaling = scaling_config or ScalingConfig()
        self.run_config = run_config or RunConfig()
        self.resume_from_checkpoint = resume_from_checkpoint
        self.datasets = datasets or {}

    # ------------------------------------------------------------------ fit
    def fit(self) -> Result:
        if not ray_tpu.is_initialized():
            ray_tpu.init()
        name = self.run_config.name or f"train_{int(time.time())}"
        storage = self.run_config.storage_path or f"/tmp/ray_tpu/{name}"
        ckpt_cfg = self.run_config.checkpoint_config
        manager = CheckpointManager(
            storage,
            num_to_keep=ckpt_cfg.num_to_keep,
            score_attribute=ckpt_cfg.checkpoint_score_attribute,
            score_order=ckpt_cfg.checkpoint_score_order,
        )
        max_failures = self.run_config.failure_config.max_failures
        attempt = 0
        history: list[dict] = []
        while True:
            try:
                metrics = self._run_attempt(name, attempt, manager, history)
                return Result(metrics, manager.latest(), history)
            except (ActorError, TaskError, TrainingFailedError) as e:
                attempt += 1
                if max_failures >= 0 and attempt > max_failures:
                    return Result(
                        history[-1] if history else {}, manager.latest(), history,
                        error=TrainingFailedError(str(e)),
                    )
                # elastic restart of the whole group (same world size);
                # backoff widens with consecutive failures so a node still
                # draining its last group isn't hammered at a fixed rate
                time.sleep(min(5.0, 0.5 * (2 ** (attempt - 1)))
                           * (0.5 + random.random()))

    def _run_attempt(self, name: str, attempt: int, manager: CheckpointManager,
                     history: list[dict]) -> dict:
        scaling = self.scaling
        n = scaling.num_workers
        group_name = f"{name}_g{attempt}"

        pg = ray_tpu.placement_group(
            [scaling.worker_resources() for _ in range(n)],
            strategy=scaling.placement_strategy,
        )
        pg.ready(timeout=60)
        WorkerCls = ray_tpu.remote(TrainWorker)
        workers = [
            # per-worker bundle_index: options differ every iteration
            WorkerCls.options(  # raylint: disable=RT009
                num_cpus=scaling.worker_resources().get("CPU", 1.0),
                resources={k: v for k, v in scaling.worker_resources().items()
                           if k != "CPU"},
                placement_group=pg,
                placement_group_bundle_index=i,
                # poll() must be servable while run() blocks an executor thread
                max_concurrency=2,
            ).remote(i, n, name, scaling.backend(), group_name)
            for i in range(n)
        ]
        try:
            resume = manager.latest() or self.resume_from_checkpoint
            ray_tpu.get(
                [w.setup.remote(resume.path if resume else None) for w in workers],
                timeout=120,
            )
            run_refs = [
                w.run.remote(self.train_loop, self.train_loop_config) for w in workers
            ]
            final = self._poll_loop(workers, run_refs, manager, history)
            return final
        finally:
            for w in workers:
                try:
                    ray_tpu.kill(w)
                except Exception:  # raylint: disable=RT012 — teardown: worker may already be dead
                    pass
            try:
                ray_tpu.remove_placement_group(pg)
            except Exception:  # raylint: disable=RT012 — teardown: PG dies with the cluster anyway
                pass

    def _poll_loop(self, workers, run_refs, manager: CheckpointManager,
                   history: list[dict]) -> dict:
        """Controller loop (ref: TrainController.run :469)."""
        last_metrics: dict = {}
        pending = list(run_refs)
        while True:
            # surface early run() failures (submission/unpickling errors)
            # instead of polling a worker that never started
            done_now, _ = ray_tpu.wait(pending, num_returns=len(pending), timeout=0.01)
            for r in ray_tpu.get(done_now):
                if not r.get("ok"):
                    raise TrainingFailedError(r.get("error", "unknown"))
            polls = ray_tpu.get([w.poll.remote() for w in workers], timeout=60)
            for rank, poll in enumerate(polls):
                for metrics, ckpt_path in poll["reports"]:
                    metrics = {**metrics, "world_rank": rank}
                    history.append(metrics)
                    last_metrics = metrics
                    if ckpt_path and rank == 0:
                        manager.register(Checkpoint(ckpt_path), metrics)
                if poll["error"]:
                    raise TrainingFailedError(f"worker {rank}: {poll['error']}")
            if all(p["done"] for p in polls):
                results = ray_tpu.get(pending, timeout=60)
                for r in results:
                    if not r.get("ok"):
                        raise TrainingFailedError(r.get("error", "unknown"))
                return last_metrics
            time.sleep(0.05)

"""ray_tpu.autoscaler — demand-driven cluster scaling.

TPU-native counterpart of the reference autoscaler v2 (ref:
python/ray/autoscaler/v2/ — instance-manager reconciler over a
NodeProvider). The scaling signal is per-node queued lease demand
reported through raylet heartbeats; the reconciler adds nodes while
demand persists and drains idle ones after a timeout. Providers are
pluggable: LocalSubprocessProvider launches real raylet subprocesses
(the test/e2e provider), a cloud/TPU-pod provider slots behind the same
three methods.
"""
from ray_tpu.autoscaler.autoscaler import Autoscaler, AutoscalerConfig
from ray_tpu.autoscaler.gke import GKETPUPodProvider
from ray_tpu.autoscaler.instance_manager import Instance, InstanceManager
from ray_tpu.autoscaler.node_provider import LocalSubprocessProvider, NodeProvider

__all__ = [
    "Autoscaler",
    "AutoscalerConfig",
    "GKETPUPodProvider",
    "Instance",
    "InstanceManager",
    "LocalSubprocessProvider",
    "NodeProvider",
]

"""Autoscaler reconciler: demand up, idle down.

TPU-native counterpart of the reference v2 reconciler (ref:
python/ray/autoscaler/v2/instance_manager/reconciler.py): a loop that
reads the GCS cluster view (resources + queued lease demand from raylet
heartbeats), launches nodes while demand persists past upscale_delay_s,
and drains nodes idle past idle_timeout_s down to min_nodes.
"""
from __future__ import annotations

import dataclasses
import threading
import time


@dataclasses.dataclass
class AutoscalerConfig:
    min_nodes: int = 1
    max_nodes: int = 4
    upscale_delay_s: float = 1.0
    idle_timeout_s: float = 10.0
    poll_interval_s: float = 0.5
    #: a launched node that never registers with the GCS within this
    #: window is reclaimed (raylet crashed while the cloud resource
    #: lives; without this the permanent 'pending' wedges scale-up)
    pending_timeout_s: float = 300.0
    #: resources for each new node (the provider default if None)
    node_resources: dict | None = None


class Autoscaler:
    """Runs against a live GCS; drives a NodeProvider."""

    def __init__(self, gcs_address: tuple[str, int], provider,
                 config: AutoscalerConfig | None = None):
        self.gcs_address = gcs_address
        self.provider = provider
        self.config = config or AutoscalerConfig()
        self._stop = threading.Event()
        self._thread: threading.Thread | None = None
        self._demand_since: float | None = None
        # GCS node_id hex -> first time seen idle
        self._idle_since: dict[str, float] = {}
        # provider id -> first time seen launched-but-unregistered
        self._pending_since: dict[str, float] = {}
        # provider node ids this autoscaler launched (never scales below
        # nodes it doesn't own)
        self._launched: list[str] = []
        self.events: list[dict] = []  # scaling decisions (observability)

    # ------------------------------------------------------------- lifecycle
    def start(self):
        self._thread = threading.Thread(target=self._run, daemon=True,
                                        name="rt-autoscaler")
        self._thread.start()
        return self

    def stop(self):
        self._stop.set()
        if self._thread is not None:
            self._thread.join(timeout=10)

    # -------------------------------------------------------------- the loop
    def _run(self):
        import logging

        from ray_tpu.utils import rpc

        logger = logging.getLogger("ray_tpu.autoscaler")
        io = rpc.EventLoopThread(name="rt-autoscale-io")
        conn = None
        try:
            while not self._stop.is_set():
                try:
                    if conn is None or conn._closed:
                        conn = io.run(rpc.connect(*self.gcs_address, timeout=10))
                    nodes = io.run(conn.call("get_cluster", {}))
                    self._reconcile(nodes)
                except Exception as e:
                    logger.warning("autoscaler reconcile failed: %r", e)
                    conn = None  # reconnect on the next pass
                self._stop.wait(self.config.poll_interval_s)
            if conn is not None:
                io.run(conn.close())
        finally:
            io.stop()

    def _reconcile(self, nodes: list[dict]):
        cfg = self.config
        now = time.monotonic()
        alive = [n for n in nodes if n.get("alive")]
        total_queued = sum(int(n.get("queued_leases", 0)) for n in alive)

        # advance provider-side lifecycle (v2 instance manager state
        # machine: cloud operations, ALLOCATED->RAY_RUNNING matching);
        # reconcile() returns the live set so one cloud list serves the
        # whole pass
        live_provider = None
        if hasattr(self.provider, "reconcile"):
            live_provider = self.provider.reconcile(alive)
        if live_provider is None:
            live_provider = set(self.provider.non_terminated_nodes())
        # prune launched nodes the provider no longer tracks (and their
        # pending timestamps: a reused provider id must not inherit one)
        self._launched = [l for l in self._launched if l in live_provider]
        for k in [k for k in self._pending_since if k not in self._launched]:
            del self._pending_since[k]
        # pending = launched but not yet registered with the GCS: while any
        # exist, don't launch more (ref: v2 instance-manager pending states)
        pending = []
        for l in list(self._launched):  # reclaim mutates the list
            if any(self.provider.matches(l, n) for n in alive):
                self._pending_since.pop(l, None)
                continue
            first = self._pending_since.setdefault(l, now)
            if now - first > cfg.pending_timeout_s:
                # cloud resource lives but its raylet never registered
                # (crashed during bootstrap): reclaim it or scale-up
                # wedges behind a permanent 'pending' entry
                self.provider.terminate_node(l)
                self._launched.remove(l)
                self._pending_since.pop(l, None)
                self.events.append({"ts": time.time(), "action": "reclaim",
                                    "node": l})
                continue
            pending.append(l)

        # ---- scale up: queued demand nothing alive can absorb
        if total_queued > 0 and not pending:
            if self._demand_since is None:
                self._demand_since = now
            elif (now - self._demand_since >= cfg.upscale_delay_s
                  and len(alive) < cfg.max_nodes):
                node_id = self.provider.create_node(cfg.node_resources)
                self._launched.append(node_id)
                self._demand_since = None
                self.events.append({"ts": time.time(), "action": "up",
                                    "node": node_id, "queued": total_queued})
        else:
            self._demand_since = None

        # ---- scale down: an autoscaler-launched node idle past the timeout
        if len(alive) <= cfg.min_nodes or not self._launched:
            self._idle_since = {}
            return
        for n in alive:
            provider_id = next(
                (l for l in self._launched if self.provider.matches(l, n)),
                None)
            if provider_id is None:
                continue  # never touch nodes this autoscaler didn't launch
            nid = n["node_id"].hex() if hasattr(n["node_id"], "hex") else str(n["node_id"])
            # idle = full resources available and no queued demand
            res_t, res_a = n["resources_total"], n["resources_available"]
            busy = any(res_a.get(k, 0.0) < v - 1e-9 for k, v in res_t.items()
                       if k != "node") or n.get("queued_leases", 0) > 0
            if busy:
                self._idle_since.pop(nid, None)
                continue
            first = self._idle_since.setdefault(nid, now)
            if now - first >= cfg.idle_timeout_s:
                # terminate exactly the node observed idle; one per pass
                self.provider.terminate_node(provider_id)
                self._launched.remove(provider_id)
                self._idle_since.pop(nid, None)
                self.events.append({"ts": time.time(), "action": "down",
                                    "node": provider_id})
                break

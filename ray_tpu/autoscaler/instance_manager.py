"""v2-style instance lifecycle tracking for autoscaled nodes.

TPU-native counterpart of the reference's autoscaler v2 instance manager
(ref: python/ray/autoscaler/v2/instance_manager/{instance_manager,
reconciler,instance_storage}.py + instance_manager.proto states): every
node the autoscaler launches is an :class:`Instance` advancing through

    QUEUED -> REQUESTED -> ALLOCATED -> RAY_RUNNING
                 |              |            |
                 v              v            v
        ALLOCATION_FAILED   TERMINATING -> TERMINATED
                              (RAY_STOPPING first when draining a live
                               ray node)

The :class:`InstanceManager` wraps any NodeProvider: the reconciler keeps
calling the familiar create/terminate/non_terminated surface, while the
manager records transitions (with timestamps, for observability and
stuck-instance detection) and advances cloud-side state on
``reconcile(gcs_nodes)`` — REQUESTED instances whose cloud resource
materialized become ALLOCATED, ALLOCATED instances whose raylet
registered become RAY_RUNNING, TERMINATING instances whose cloud
resource vanished become TERMINATED.
"""

from __future__ import annotations

import time
from dataclasses import dataclass, field

QUEUED = "QUEUED"
REQUESTED = "REQUESTED"
ALLOCATED = "ALLOCATED"
RAY_RUNNING = "RAY_RUNNING"
RAY_STOPPING = "RAY_STOPPING"
TERMINATING = "TERMINATING"
TERMINATED = "TERMINATED"
ALLOCATION_FAILED = "ALLOCATION_FAILED"

_LIVE_STATES = (QUEUED, REQUESTED, ALLOCATED, RAY_RUNNING, RAY_STOPPING)


@dataclass
class Instance:
    instance_id: str
    resources: dict
    state: str = QUEUED
    created_at: float = field(default_factory=time.time)
    state_since: float = field(default_factory=time.monotonic)
    transitions: list = field(default_factory=list)  # (ts, from, to)
    error: str | None = None

    def to(self, state: str) -> None:
        self.transitions.append((time.time(), self.state, state))
        self.state = state
        self.state_since = time.monotonic()


class InstanceManager:
    """NodeProvider facade + lifecycle ledger over a real provider."""

    #: REQUESTED instances whose cloud resource never appears within this
    #: window fail (async create errors surface as an absent resource)
    ALLOCATE_TIMEOUT_S = 300.0
    #: terminal instances kept for observability before eviction (the
    #: reference's instance_storage GCs terminal records the same way)
    KEEP_TERMINAL = 64

    def __init__(self, provider):
        self.provider = provider
        self.instances: dict[str, Instance] = {}

    # ----------------------------------------------- NodeProvider surface
    def create_node(self, resources: dict | None = None) -> str:
        inst = Instance("pending", dict(resources or {}))
        inst.to(REQUESTED)
        try:
            iid = self.provider.create_node(resources)
        except Exception as e:
            inst.instance_id = f"failed-{time.time_ns()}"
            inst.error = repr(e)
            inst.to(ALLOCATION_FAILED)
            self.instances[inst.instance_id] = inst
            raise
        inst.instance_id = iid
        self.instances[iid] = inst
        return iid

    def terminate_node(self, instance_id: str) -> None:
        inst = self.instances.get(instance_id)
        if inst is not None and inst.state not in (TERMINATING, TERMINATED):
            if inst.state == RAY_RUNNING:
                inst.to(RAY_STOPPING)
            inst.to(TERMINATING)
        self.provider.terminate_node(instance_id)

    def non_terminated_nodes(self) -> list[str]:
        return self.provider.non_terminated_nodes()

    def matches(self, instance_id: str, gcs_node: dict) -> bool:
        return self.provider.matches(instance_id, gcs_node)

    # --------------------------------------------------- state advancement
    def reconcile(self, gcs_nodes: list[dict]) -> set[str]:
        """Advance instance states from observed cloud + GCS reality.
        Returns the live provider-node set so the caller need not list
        the cloud a second time in the same pass."""
        if hasattr(self.provider, "reconcile"):
            self.provider.reconcile(gcs_nodes)
        live = set(self.provider.non_terminated_nodes())
        now = time.monotonic()
        for iid, inst in self.instances.items():
            if inst.state == REQUESTED and iid in live:
                inst.to(ALLOCATED)
            if inst.state in (REQUESTED, ALLOCATED) and any(
                    self.provider.matches(iid, n) for n in gcs_nodes):
                if inst.state == REQUESTED:
                    inst.to(ALLOCATED)
                inst.to(RAY_RUNNING)
            elif (inst.state == REQUESTED
                    and now - inst.state_since > self.ALLOCATE_TIMEOUT_S):
                # async create failure: the cloud resource never appeared
                inst.error = inst.error or "allocation timed out"
                inst.to(ALLOCATION_FAILED)
            elif inst.state in (RAY_STOPPING, TERMINATING) and iid not in live:
                inst.to(TERMINATED)
            elif (inst.state in (ALLOCATED, RAY_RUNNING)
                    and iid not in live):
                # cloud resource vanished under us (preemption, manual
                # delete): terminal, the reconciler may relaunch on demand
                inst.error = inst.error or "instance disappeared"
                inst.to(TERMINATED)
        self._evict_terminal()
        return live

    def _evict_terminal(self) -> None:
        terminal = [iid for iid, i in self.instances.items()
                    if i.state in (TERMINATED, ALLOCATION_FAILED)]
        for iid in terminal[:-self.KEEP_TERMINAL or None]:
            del self.instances[iid]

    # ------------------------------------------------------- observability
    def live_instances(self) -> list[Instance]:
        return [i for i in self.instances.values()
                if i.state in _LIVE_STATES]

    def summary(self) -> dict:
        out: dict[str, int] = {}
        for inst in self.instances.values():
            out[inst.state] = out.get(inst.state, 0) + 1
        return out

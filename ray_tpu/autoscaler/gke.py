"""GKE TPU pod-slice node provider.

The marquee cloud provider for a TPU-native framework: each autoscaled
"node" is a GKE *node pool* holding one TPU pod slice (ref role:
python/ray/autoscaler/batching_node_provider.py + the KubeRay provider —
here the unit of scaling is a whole slice, because a slice is the unit
ICI connectivity comes in).

All cloud traffic goes through an injectable ``transport`` callable
``(method, path, body) -> dict`` speaking the GKE REST surface
(container.googleapis.com v1), so tests drive the full provider +
instance-manager + reconciler stack against a fake cloud, and production
supplies :func:`gcp_transport` (metadata-server auth). Slice topologies
come from a static accelerator table mirroring
accelerators/tpu.py's type map.
"""

from __future__ import annotations

import json
import urllib.request

from ray_tpu.autoscaler.node_provider import NodeProvider

# tpu type -> (gke machine type, chips per host, default topology)
# (public GKE TPU docs; one entry per family this provider can request)
TPU_SLICES = {
    "v4-8": ("ct4p-hightpu-4t", 4, "2x2x1"),
    "v4-16": ("ct4p-hightpu-4t", 4, "2x2x2"),
    "v5litepod-4": ("ct5lp-hightpu-4t", 4, "2x2"),
    "v5litepod-8": ("ct5lp-hightpu-8t", 8, "2x4"),
    "v5litepod-16": ("ct5lp-hightpu-4t", 4, "4x4"),
    "v5p-8": ("ct5p-hightpu-4t", 4, "2x2x1"),
    "v5p-16": ("ct5p-hightpu-4t", 4, "2x2x2"),
    "v6e-4": ("ct6e-standard-4t", 4, "2x2"),
    "v6e-8": ("ct6e-standard-8t", 8, "2x4"),
    "v6e-16": ("ct6e-standard-4t", 4, "4x4"),
}

POOL_PREFIX = "rt-tpu-"


def gcp_transport(method: str, path: str, body: dict | None = None) -> dict:
    """Production transport: metadata-server token + container API."""
    tok = urllib.request.urlopen(urllib.request.Request(
        "http://metadata.google.internal/computeMetadata/v1/instance/"
        "service-accounts/default/token",
        headers={"Metadata-Flavor": "Google"}), timeout=10)
    token = json.loads(tok.read())["access_token"]
    req = urllib.request.Request(
        "https://container.googleapis.com/v1" + path,
        method=method,
        data=json.dumps(body).encode() if body is not None else None,
        headers={"Authorization": f"Bearer {token}",
                 "Content-Type": "application/json"})
    with urllib.request.urlopen(req, timeout=60) as resp:
        return json.loads(resp.read() or b"{}")


class GKETPUPodProvider(NodeProvider):
    """Scales TPU pod slices as GKE node pools.

    One ``create_node`` = one node pool = one slice; the raylet
    bootstrapped on the slice (via the pool's node labels -> startup
    DaemonSet, outside this provider's scope) registers with the node
    label ``instance=<pool name>`` which :meth:`matches` uses to link
    GCS rows back to instances."""

    def __init__(self, project: str, location: str, cluster: str,
                 tpu_type: str = "v5litepod-16",
                 transport=gcp_transport):
        if tpu_type not in TPU_SLICES:
            raise ValueError(
                f"unknown tpu_type {tpu_type!r}; known: "
                f"{sorted(TPU_SLICES)}")
        self.parent = (f"/projects/{project}/locations/{location}"
                       f"/clusters/{cluster}")
        self.tpu_type = tpu_type
        self.transport = transport
        # Pool names must survive provider restarts: a counter alone can
        # collide with rt-tpu-* pools left by a previous autoscaler run
        # started within the same second (GKE would 409 → surface as
        # ALLOCATION_FAILED). A per-provider random token makes every
        # incarnation's names disjoint without an extra startup GET.
        import uuid

        self._counter = 0
        self._token = uuid.uuid4().hex[:6]
        # pool name -> last create/delete operation name (poll handles)
        self._ops: dict[str, str] = {}

    # --------------------------------------------------------------- CRUD
    def create_node(self, resources: dict | None = None) -> str:
        machine, chips_per_host, topology = TPU_SLICES[self.tpu_type]
        # host count derives from the topology's CHIP product (the type
        # suffix counts TensorCores on v4/v5p — 2 per chip — and would
        # request a node count GKE rejects against tpuTopology)
        chips = 1
        for dim in topology.split("x"):
            chips *= int(dim)
        hosts = max(1, chips // chips_per_host)
        self._counter += 1
        name = f"{POOL_PREFIX}{self._token}-{self._counter}"
        body = {
            "nodePool": {
                "name": name,
                "initialNodeCount": hosts,
                "config": {
                    "machineType": machine,
                    # the slice bootstrap propagates this node label to the
                    # raylet's --labels, which matches() joins on
                    "labels": {"instance": name},
                },
                "placementPolicy": {"tpuTopology": topology,
                                    "type": "COMPACT"},
            }
        }
        op = self.transport("POST", f"{self.parent}/nodePools", body)
        self._ops[name] = op.get("name", "")
        return name

    def terminate_node(self, provider_node_id: str) -> None:
        op = self.transport(
            "DELETE", f"{self.parent}/nodePools/{provider_node_id}", None)
        self._ops[provider_node_id] = op.get("name", "")

    def non_terminated_nodes(self) -> list[str]:
        reply = self.transport("GET", f"{self.parent}/nodePools", None)
        out = []
        for pool in reply.get("nodePools", []):
            if not pool.get("name", "").startswith(POOL_PREFIX):
                continue  # never touch pools this provider didn't create
            if pool.get("status") in ("PROVISIONING", "RUNNING",
                                      "RECONCILING"):
                out.append(pool["name"])
        return out

    def matches(self, provider_node_id: str, gcs_node: dict) -> bool:
        labels = gcs_node.get("labels", {}) or {}
        return labels.get("instance") == provider_node_id

    def shutdown(self):
        pass  # node pools outlive the autoscaler process by design

"""Node providers: how the autoscaler creates and destroys nodes.

TPU-native counterpart of the reference provider interface (ref:
python/ray/autoscaler/node_provider.py NodeProvider,
_private/fake_multi_node/node_provider.py for the local variant).
"""
from __future__ import annotations

import os
import subprocess
import sys


class NodeProvider:
    """Minimal provider surface the reconciler drives."""

    def create_node(self, resources: dict[str, float]) -> str:
        raise NotImplementedError

    def terminate_node(self, provider_node_id: str) -> None:
        raise NotImplementedError

    def non_terminated_nodes(self) -> list[str]:
        raise NotImplementedError

    def matches(self, provider_node_id: str, gcs_node: dict) -> bool:
        """Does this GCS cluster-view row belong to the given provider
        node? Providers link their instances to registered raylets their
        own way (local: pid; cloud: an ``instance`` node label)."""
        raise NotImplementedError


class LocalSubprocessProvider(NodeProvider):
    """Launches real raylet subprocesses against one GCS — scaling on a
    single machine (the reference's fake_multi_node provider role, but the
    nodes are real raylets with real stores and worker pools)."""

    def __init__(self, gcs_address: str, default_resources: dict[str, float] | None = None,
                 store_capacity: int | None = None):
        self.gcs_address = gcs_address
        self.default_resources = default_resources or {"CPU": 4.0}
        self.store_capacity = store_capacity
        self._procs: dict[str, subprocess.Popen] = {}
        self._counter = 0

    def create_node(self, resources: dict[str, float] | None = None) -> str:
        res = dict(resources or self.default_resources)
        env = dict(os.environ)
        pkg_parent = os.path.dirname(os.path.dirname(os.path.dirname(os.path.abspath(__file__))))
        env["PYTHONPATH"] = pkg_parent + os.pathsep + env.get("PYTHONPATH", "")
        cmd = [
            sys.executable, "-m", "ray_tpu.core.raylet",
            "--gcs", self.gcs_address,
            "--num-cpus", str(res.get("CPU", 4.0)),
        ]
        extra = ",".join(f"{k}={v}" for k, v in res.items() if k not in ("CPU", "TPU"))
        if res.get("TPU"):
            cmd += ["--num-tpus", str(res["TPU"])]
        if extra:
            cmd += ["--resources", extra]
        if self.store_capacity:
            cmd += ["--store-capacity", str(self.store_capacity)]
        proc = subprocess.Popen(cmd, env=env)
        self._counter += 1
        node_id = f"local-{self._counter}-{proc.pid}"
        self._procs[node_id] = proc
        return node_id

    def terminate_node(self, provider_node_id: str) -> None:
        proc = self._procs.pop(provider_node_id, None)
        if proc is not None:
            proc.terminate()
            try:
                proc.wait(timeout=5)
            except Exception:
                proc.kill()

    def non_terminated_nodes(self) -> list[str]:
        return [nid for nid, p in self._procs.items() if p.poll() is None]

    def pid_of(self, provider_node_id: str) -> int | None:
        proc = self._procs.get(provider_node_id)
        return proc.pid if proc is not None else None

    def matches(self, provider_node_id: str, gcs_node: dict) -> bool:
        pid = self.pid_of(provider_node_id)
        return pid is not None and int(gcs_node.get("pid", 0)) == pid

    def shutdown(self):
        for nid in list(self._procs):
            self.terminate_node(nid)

"""Collective-backed resharding: spec A -> spec B without a driver hop.

When a consumer's ``in_spec`` disagrees with a stored manifest's spec,
the redistribute runs as ONE XLA program: local shards assemble into a
device-resident global array (shm -> device, zero host gathering), a
jit whose ``out_shardings`` names the new spec makes the compiler insert
the collective (all-gather / all-to-all / collective-permute over
ICI/DCN — GSPMD's resharding machinery), and the output shards seal
straight back into shm. The driver sees two manifests and nothing else;
the array bytes never ride an RPC frame. The XLA entry point lives in
``collective/xla_group.redistribute`` beside the eager collectives.
"""

from __future__ import annotations

import time

from ray_tpu.sharded import telemetry
from ray_tpu.sharded.manifest import (
    ShardedObjectRef,
    norm_spec,
    spec_to_tuple,
)
from ray_tpu.sharded.plane import get_sharded, put_sharded


def reshard(sref: ShardedObjectRef, spec, *, mesh=None) -> ShardedObjectRef:
    """Redistribute ``sref`` to ``spec``, returning a new
    ShardedObjectRef. A no-op (same manifest) when the specs already
    agree — compared dim-positionally, so P("dp") == P("dp", None).
    Runs device-side through the XLA collective layer; records the
    ``reshard`` stage and the new manifest's driver bytes."""
    ndim = len(sref.shape)
    spec_t = spec_to_tuple(spec)
    if norm_spec(spec_t, ndim) == norm_spec(tuple(sref.spec), ndim):
        return sref
    from ray_tpu.collective.xla_group import redistribute
    from ray_tpu.sharded.manifest import tuple_to_spec

    # canonical hashable PartitionSpec: list-ish specs must still hit
    # the per-(mesh, spec) cached redistribute program
    spec = tuple_to_spec(spec_t)
    if mesh is None:
        mesh = sref.build_mesh()
    t0 = time.perf_counter_ns()
    garr = get_sharded(sref, mesh=mesh)
    out = redistribute(garr, mesh, spec)
    new = put_sharded(out, mesh=mesh, spec=spec)
    telemetry.record(telemetry.RESHARD, time.perf_counter_ns() - t0,
                     int(sref.nbytes))
    return new

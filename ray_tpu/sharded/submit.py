"""pjit-aware task submission: ``@remote(in_specs=..., out_specs=...)``.

A sharded function fans out ONE task PER UNIQUE SHARD instead of one
task over the gathered array: each task is routed (node-affinity) to the
node whose shm arena holds its input shards, the worker's dependency
resolution hands it the shard values zero-copy out of local shm, and
each task's return IS the corresponding output shard — sealed into the
executing node's arena by the normal result path, with the completion
record priming the owner's location cache. The driver never gathers or
scatters array bytes; it moves manifests (Pathways' dispatch shape,
Barham et al., 2022).

Spec mediation: when a consumer's ``in_spec`` disagrees with a stored
manifest's spec, the argument is redistributed FIRST through the
collective-backed reshard path (collective/xla_group.redistribute), so
disagreement costs one XLA collective, not a driver funnel.

Fault story: each shard task's core lineage (driver-side spec stash)
makes a lost output shard re-materialize by re-running ONLY that shard's
task; the ``sharded.shard_seal`` fault point fires per shard task
(phase="task"), where a ``kill`` action dies before the seal — the exact
loss window the chaos plan exercises.
"""

from __future__ import annotations

import numpy as np

from ray_tpu.devtools import chaos
from ray_tpu.sharded import telemetry
from ray_tpu.sharded.manifest import (
    ShardedObjectRef,
    ShardEntry,
    ShardManifest,
    _dim_axes,
    norm_spec,
    partition_boxes,
    spec_to_tuple,
)
from ray_tpu.sharded.plane import manifest_nbytes
from ray_tpu.sharded.reshard import reshard


def _grid_axes(spec_t: tuple) -> tuple:
    """The mesh axes a spec consumes, in tile-enumeration (row-major
    dim, then intra-dim) order. Two sharded args align shard-for-shard
    iff these sequences are EQUAL: flat shard index i then decomposes
    into the same mesh coordinates for both — P("dp") rows pair with
    P(None, "dp") columns (both enumerate dp), but P("dp") must never
    silently pair with P("tp")."""
    return tuple(ax for e in spec_t for ax in _dim_axes(e))


def _make_shard_body(user_fn):
    """The per-shard task body (registered once per handle): runs the
    user function on device-local shard VALUES (the runtime resolved the
    shard refs out of local shm before entry) and returns the output
    shard, which the normal result path seals into this node's arena."""

    def _sharded_shard_call(_rt_shard_idx, _rt_out, *vals, **kw):
        out = np.asarray(user_fn(*vals, **kw))
        if _rt_out is not None:
            shape, dtype = _rt_out
            if tuple(out.shape) != tuple(shape) or str(out.dtype) != dtype:
                # fail AT the producing task, not deep inside a later
                # get_sharded stitch with a cryptic jax shape error
                raise TypeError(
                    f"shard {_rt_shard_idx} returned shape {out.shape} "
                    f"dtype {out.dtype}, but out_specs/out_shape/"
                    f"out_dtype declare {tuple(shape)}/{dtype} for this "
                    "tile; fix the declaration or the function")
        if chaos.ENABLED:
            # "sharded.shard_seal", task phase: `kill` dies here — after
            # the work, before the seal — so exactly this shard is lost
            # and core lineage re-runs exactly this task
            chaos.point("sharded.shard_seal", shard=int(_rt_shard_idx),
                        phase="task")
        return out

    return _sharded_shard_call


class ShardedFunction:
    """Handle produced by ``@remote(in_specs=..., out_specs=...)``."""

    def __init__(self, fn, opts: dict):
        self._fn = fn
        self._opts = dict(opts)
        self._body = _make_shard_body(fn)
        self.__name__ = getattr(fn, "__name__", "sharded_task")

    def options(self, **opts) -> "ShardedFunction":
        return ShardedFunction(self._fn, {**self._opts, **opts})

    def __call__(self, *a, **k):
        raise TypeError("sharded remote functions cannot be called "
                        "directly; use .remote()")

    # ------------------------------------------------------------- submit
    def remote(self, *args, **kwargs) -> ShardedObjectRef:
        from ray_tpu.core import api

        core = api.get_core()
        o = self._opts
        for k, v in kwargs.items():
            if isinstance(v, ShardedObjectRef):
                raise TypeError(
                    f"sharded args must be positional (kwarg {k!r} is a "
                    "ShardedObjectRef): in_specs aligns to positions")
        sharded_idx = [i for i, a in enumerate(args)
                       if isinstance(a, ShardedObjectRef)]
        if not sharded_idx:
            raise TypeError(
                "a sharded function takes at least one ShardedObjectRef "
                "argument (use plain @remote for unsharded tasks)")
        in_specs = o.get("in_specs")
        if in_specs is None:
            raise TypeError("@remote(in_specs=...) is required for "
                            "sharded submission")
        # PartitionSpec subclasses tuple: a bare P(...) broadcasts to
        # every arg; a plain tuple/list is the per-arg spec sequence
        from jax.sharding import PartitionSpec as _P

        if isinstance(in_specs, _P) or not isinstance(in_specs,
                                                      (tuple, list)):
            in_specs = (in_specs,) * len(args)
        if len(in_specs) < len(args):
            in_specs = tuple(in_specs) + (None,) * (len(args)
                                                    - len(in_specs))

        # spec mediation: redistribute any sharded arg whose stored spec
        # disagrees with the declared in_spec (one XLA collective; the
        # manifest swap is invisible to the caller's handle)
        args = list(args)
        mesh = o.get("mesh")
        for i in sharded_idx:
            sref = args[i]
            want = in_specs[i]
            if want is None:
                continue
            want_t = norm_spec(spec_to_tuple(want), len(sref.shape))
            have_t = norm_spec(tuple(sref.spec), len(sref.shape))
            if want_t != have_t:
                args[i] = reshard(sref, want, mesh=mesh)

        first = args[sharded_idx[0]]
        nshards = first.num_shards()
        axes0 = _grid_axes(tuple(first.spec))
        for i in sharded_idx[1:]:
            if args[i].num_shards() != nshards:
                raise ValueError(
                    f"sharded args disagree on shard count: "
                    f"{nshards} vs {args[i].num_shards()} (arg {i}); "
                    "declare in_specs that tile them identically")
            axes_i = _grid_axes(tuple(args[i].spec))
            if axes_i != axes0:
                raise ValueError(
                    f"sharded args tile over different mesh axes: arg 0 "
                    f"enumerates {axes0 or '(replicated)'} but arg {i} "
                    f"enumerates {axes_i or '(replicated)'} — shard i of "
                    "each would pair tiles from unrelated mesh "
                    "positions; declare in_specs over the same axes (in "
                    "the same order)")

        # node routing: each shard task goes to the raylet of the node
        # holding its (first sharded arg's) shard
        addr_of = self._node_addresses(core, args, sharded_idx)
        out_spec = o.get("out_specs")
        out_spec_t = (spec_to_tuple(out_spec) if out_spec is not None
                      else tuple(first.spec))
        out_shape = tuple(o.get("out_shape") or first.shape)
        out_dtype = str(o.get("out_dtype") or first.dtype)
        out_boxes = partition_boxes(out_shape, out_spec_t,
                                    first.mesh_axes)
        if len(out_boxes) != nshards:
            raise ValueError(
                f"out_specs {out_spec_t} tiles {out_shape} into "
                f"{len(out_boxes)} shards but the inputs have {nshards}; "
                "pick an out_spec with the same tile count or reshard "
                "the result explicitly")

        resources = dict(o.get("resources") or {})
        resources.setdefault("CPU", float(o.get("num_cpus", 1.0)))
        entries: list[ShardEntry] = []
        itemsize = np.dtype(out_dtype).itemsize
        for i in range(nshards):
            tile_shape = tuple(b - a for a, b in out_boxes[i])
            task_args = [i, (tile_shape, out_dtype)]
            for k, a in enumerate(args):
                if isinstance(a, ShardedObjectRef):
                    task_args.append(a.manifest.shards[i].ref)
                else:
                    task_args.append(a)
            node = first.manifest.shards[i].node
            ref = core.submit_task(
                self._body, tuple(task_args), dict(kwargs),
                num_returns=1,
                resources=dict(resources),
                max_retries=o.get("max_retries"),
                scheduling_node=addr_of.get(node),
                name=f"{self.__name__}:shard{i}",
            )
            vol = 1
            for a, b in out_boxes[i]:
                vol *= (b - a)
            entries.append(ShardEntry(box=out_boxes[i], ref=ref,
                                      node=node, nbytes=vol * itemsize))
        m = ShardManifest(global_shape=out_shape, dtype=out_dtype,
                          spec=out_spec_t, mesh_axes=dict(first.mesh_axes),
                          shards=entries)
        # driver traffic for the whole wave: shard descriptors in, one
        # manifest out — O(manifest), counter-verified in bench
        telemetry.count_driver_bytes(manifest_nbytes(m) + 64 * nshards)
        return ShardedObjectRef(m)

    def _node_addresses(self, core, args, sharded_idx) -> dict:
        """node-id binary -> raylet address for every node the input
        shards live on. The local node resolves without a GCS round
        trip; remote nodes share one cluster-view call."""
        local = (core.node_id.binary()
                 if core.node_id is not None else None)
        need = set()
        first = args[sharded_idx[0]]
        for s in first.manifest.shards:
            if s.node is not None and s.node != local:
                need.add(s.node)
        out = {}
        if local is not None:
            out[local] = tuple(core.raylet_address)
        if need:
            from ray_tpu.core import api

            for n in api.nodes():
                nid = n.get("node_id")
                nb = nid.binary() if hasattr(nid, "binary") else nid
                if nb in need and n.get("alive", True):
                    out[nb] = tuple(n["address"])
        return out

"""ShardedObjectRef: a manifest of per-host shards, not a blob.

The object-plane realization of GSPMD's central idea (Xu et al., 2021):
a distributed array is its partition spec plus per-device tiles, and the
global value never needs to exist in one address space. A
:class:`ShardedObjectRef` is pure METADATA — global shape/dtype, the
`PartitionSpec` (serialized as plain tuples), the mesh axes, and a shard
table mapping each unique tile box to an ordinary :class:`ObjectRef`
whose bytes live sealed in the producing host's shm arena. Everything
that moves through the driver is this manifest (~100 bytes/shard); the
array bytes move shm -> device -> XLA collective -> shm, never through
a driver RPC frame (Pathways' gather/scatter avoidance, Barham et al.,
2022).

Pickling a ShardedObjectRef ships the manifest; the embedded ObjectRefs
ride the normal borrower protocol, so workers/actors receiving one hold
real borrows on every shard.
"""

from __future__ import annotations

import math
from dataclasses import dataclass, field

from ray_tpu.core.ref import ObjectRef

# spec tuple form: each dim entry is None (replicated), an axis name, or
# a tuple of axis names (P(("dp","fsdp")) style multi-axis sharding)
SpecT = tuple


def spec_to_tuple(spec) -> SpecT:
    """jax PartitionSpec (or any sequence) -> plain nested tuples."""
    out = []
    for e in tuple(spec):
        if e is None or isinstance(e, str):
            out.append(e)
        else:
            out.append(tuple(e))
    return tuple(out)


def tuple_to_spec(spec_t: SpecT):
    from jax.sharding import PartitionSpec as P

    return P(*spec_t)


def norm_spec(spec_t: SpecT, ndim: int) -> SpecT:
    """Pad a spec tuple with trailing None so P("dp") == P("dp", None)
    comparisons are positional, the way PartitionSpec semantics are."""
    t = tuple(spec_t)[:ndim]
    return t + (None,) * (ndim - len(t))


def _dim_axes(entry) -> tuple:
    if entry is None:
        return ()
    if isinstance(entry, str):
        return (entry,)
    return tuple(entry)


def tile_counts(global_shape: tuple, spec_t: SpecT,
                mesh_axes: dict) -> tuple:
    """Tiles per dimension: the product of the sizes of the mesh axes the
    spec names on that dim (1 for replicated/unspecified dims)."""
    counts = []
    for d in range(len(global_shape)):
        n = 1
        if d < len(spec_t):
            for ax in _dim_axes(spec_t[d]):
                if ax not in mesh_axes:
                    raise ValueError(
                        f"spec axis {ax!r} not in mesh axes "
                        f"{sorted(mesh_axes)}")
                n *= int(mesh_axes[ax])
        if n > 1 and global_shape[d] % n:
            raise ValueError(
                f"dim {d} of shape {global_shape} not divisible by "
                f"{n} tiles ({spec_t[d]!r})")
        counts.append(n)
    return tuple(counts)


def partition_boxes(global_shape: tuple, spec_t: SpecT,
                    mesh_axes: dict) -> list[tuple]:
    """Ordered unique tile boxes: each a tuple of (start, stop) per dim,
    in row-major order over the tile grid. Replicas share a box, so the
    box list is the DEDUPED shard table — len(boxes) can be far smaller
    than the mesh size."""
    counts = tile_counts(global_shape, spec_t, mesh_axes)
    sizes = [global_shape[d] // counts[d] for d in range(len(counts))]
    boxes: list[tuple] = []
    total = math.prod(counts) if counts else 1
    for flat in range(total):
        idx = []
        rem = flat
        for c in reversed(counts):
            idx.append(rem % c)
            rem //= c
        idx.reverse()
        boxes.append(tuple(
            (i * s, (i + 1) * s) for i, s in zip(idx, sizes)))
    return boxes


def box_of_indices(index, global_shape: tuple) -> tuple:
    """Normalize a jax device-indices entry (tuple of slices) into a box
    tuple, filling open slices with the full dim extent."""
    out = []
    for d, sl in enumerate(index):
        start = 0 if sl.start is None else int(sl.start)
        stop = global_shape[d] if sl.stop is None else int(sl.stop)
        out.append((start, stop))
    # trailing dims a partial index omits are unsharded
    for d in range(len(index), len(global_shape)):
        out.append((0, global_shape[d]))
    return tuple(out)


@dataclass
class ShardEntry:
    """One unique tile: its box, the ObjectRef holding its bytes, and the
    node whose shm arena sealed it (None when unknown/memory-resident).

    ``tier``/``spill_path``/``spill_offset`` are the storage-tier leg
    (core/tiering.py): tier 0 = shm-resident, tier 1 = in the owning
    raylet's spill directory. ADVISORY — consumers never branch on it
    (``api.get``/pull restore transparently); it exists so dashboards
    and the bench can tell a disk-resident shard from a hot one."""

    box: tuple
    ref: ObjectRef
    node: bytes | None = None
    nbytes: int = 0
    tier: int = 0
    spill_path: str = ""
    spill_offset: int = 0


@dataclass
class ShardManifest:
    global_shape: tuple
    dtype: str
    spec: SpecT
    mesh_axes: dict  # axis name -> size, insertion-ordered
    shards: list[ShardEntry] = field(default_factory=list)

    def box_index(self) -> dict[tuple, int]:
        return {s.box: i for i, s in enumerate(self.shards)}

    @property
    def nbytes(self) -> int:
        return sum(s.nbytes for s in self.shards)


class ShardedObjectRef:
    """First-class handle to a sharded array in the object plane.

    Holds only the manifest. ``ray_tpu.get`` on it is deliberately NOT
    supported (raylint RT014 flags driver-side materialization): consume
    it with :func:`ray_tpu.sharded.get_sharded` (device-local assembly),
    pass it to a ``@remote(in_specs=...)`` task (per-shard routing), or
    :func:`ray_tpu.sharded.reshard` it.
    """

    __slots__ = ("manifest",)

    def __init__(self, manifest: ShardManifest):
        self.manifest = manifest

    # -- convenience views --------------------------------------------------
    @property
    def shape(self) -> tuple:
        return self.manifest.global_shape

    @property
    def dtype(self) -> str:
        return self.manifest.dtype

    @property
    def spec(self) -> SpecT:
        return self.manifest.spec

    @property
    def mesh_axes(self) -> dict:
        return self.manifest.mesh_axes

    @property
    def nbytes(self) -> int:
        return self.manifest.nbytes

    def partition_spec(self):
        return tuple_to_spec(self.manifest.spec)

    def shard_refs(self) -> list[ObjectRef]:
        return [s.ref for s in self.manifest.shards]

    def num_shards(self) -> int:
        return len(self.manifest.shards)

    def build_mesh(self, devices=None):
        """A jax Mesh with this manifest's axes over local (or given)
        devices — the default consumer-side mesh when none is passed."""
        import numpy as np

        from ray_tpu.utils.device import configure_jax

        configure_jax()
        import jax
        from jax.sharding import Mesh

        axes = self.manifest.mesh_axes
        size = math.prod(axes.values()) if axes else 1
        if devices is None:
            devices = jax.devices()
        if len(devices) < size:
            raise ValueError(
                f"manifest mesh {axes} needs {size} devices, "
                f"have {len(devices)}")
        arr = np.array(devices[:size]).reshape(*axes.values())
        return Mesh(arr, tuple(axes))

    def __reduce__(self):
        return (ShardedObjectRef, (self.manifest,))

    def __len__(self):
        return len(self.manifest.shards)

    def __repr__(self):
        m = self.manifest
        return (f"ShardedObjectRef(shape={m.global_shape}, dtype={m.dtype},"
                f" spec={m.spec}, shards={len(m.shards)})")

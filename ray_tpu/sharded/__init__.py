"""Sharded object plane: first-class distributed arrays in the object store.

The TPU-native feature the reference lacks (ROADMAP item 3): a
:class:`ShardedObjectRef` is a manifest — global shape/dtype,
PartitionSpec, mesh axes, per-shard ObjectRefs + owning node — whose
shards seal directly into each host's shm arena. ``put_sharded`` never
materializes the global array; ``get_sharded`` reassembles a
device-local ``jax.Array`` zero-copy from local shm;
``@ray_tpu.remote(in_specs=..., out_specs=...)`` fans one task per
shard, routed to the shard's node; spec disagreements redistribute
through one XLA collective (collective/xla_group.redistribute), never
through the driver.
"""

from ray_tpu.sharded.manifest import (  # noqa: F401
    ShardedObjectRef,
    ShardEntry,
    ShardManifest,
    partition_boxes,
    spec_to_tuple,
    tuple_to_spec,
)
from ray_tpu.sharded.plane import (  # noqa: F401
    fetch_shard,
    get_sharded,
    manifest_nbytes,
    put_sharded,
    stats,
)
from ray_tpu.sharded.reshard import reshard  # noqa: F401
from ray_tpu.sharded.submit import ShardedFunction  # noqa: F401

__all__ = [
    "ShardedObjectRef", "ShardEntry", "ShardManifest", "ShardedFunction",
    "put_sharded", "get_sharded", "fetch_shard", "reshard", "stats",
    "partition_boxes", "spec_to_tuple", "tuple_to_spec", "manifest_nbytes",
]

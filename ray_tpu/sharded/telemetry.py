"""Sharded-plane telemetry: stage windows, Prometheus feeds, byte counters.

Mirrors how PR 4 instrumented the task lanes: every sharded operation
records (stage, duration_ns, nbytes) — stages ``shard_seal`` /
``shard_fetch`` / ``reshard`` — into

- the process flight-recorder ring (utils/recorder.py stage ids 12-14),
  so postmortems show which shard op a process died inside;
- ``metrics.task_stage_seconds`` histograms + ``task_stage_us``
  percentile gauges (Prometheus/dashboard, same families as the task
  stages);
- a bounded per-process latency window published on the task-event
  flush timer under GCS ns="latency" (key ``<worker>.sharded``) so
  ``state.list_task_latency()`` merges the sharded stages beside
  ring_sub/exec/... with no extra surface.

Byte counters back the zero-copy claim: ``driver_bytes`` counts only
manifest/descriptor metadata that crossed the driver; ``array_bytes``
counts shard payload bytes that moved via shm/XLA instead.
"""

from __future__ import annotations

import threading

from ray_tpu.utils import metrics, recorder

SHARD_SEAL = "shard_seal"
SHARD_FETCH = "shard_fetch"
RESHARD = "reshard"
STAGES = (SHARD_SEAL, SHARD_FETCH, RESHARD)

_REC_STAGE = {SHARD_SEAL: recorder.SHARD_SEAL,
              SHARD_FETCH: recorder.SHARD_FETCH,
              RESHARD: recorder.RESHARD}

_WINDOW_CAP = 1024

_lock = threading.Lock()
_windows: dict[str, list[int]] = {s: [] for s in STAGES}
_count = 0
_published = -1
_snapped = -1  # _count at the last snapshot handed to the flush
# process-lifetime counters, like the metrics registry: totals span
# init/shutdown cycles within one process (reset_counters for A/B runs)
_counters = {"driver_bytes": 0, "array_bytes": 0,
             "shards_sealed": 0, "shards_fetched": 0, "reshards": 0}
_registered_core = None  # the CoreClient the latency source is attached to


def record(stage: str, dur_ns: int, nbytes: int = 0) -> None:
    """One sharded-plane stage event. ms-scale ops, so the histogram
    observe happens inline (no deferred decode needed, unlike the
    sub-µs task stages)."""
    global _count
    dur_ns = max(0, int(dur_ns))
    with _lock:
        win = _windows[stage]
        win.append(dur_ns)
        if len(win) > _WINDOW_CAP:
            del win[: len(win) - _WINDOW_CAP]
        _count += 1
        if stage == SHARD_SEAL:
            _counters["shards_sealed"] += 1
            _counters["array_bytes"] += nbytes
        elif stage == SHARD_FETCH:
            _counters["shards_fetched"] += 1
        else:
            _counters["reshards"] += 1
    metrics.task_stage_seconds.observe(dur_ns / 1e9, tags={"stage": stage})
    rec = recorder.get_recorder()
    if rec is not None:
        rec.record(b"", _REC_STAGE[stage],
                   a0=min(dur_ns, 0xFFFFFFFF),
                   a1=nbytes & 0xFFFFFFFF, a2=(nbytes >> 32) & 0xFFFFFFFF)
    _maybe_register()


def count_driver_bytes(n: int) -> None:
    """Metadata bytes (manifests, shard descriptors) that crossed the
    driver for a sharded op — the O(manifest) side of the ledger."""
    with _lock:
        _counters["driver_bytes"] += int(n)


def counters() -> dict:
    with _lock:
        return dict(_counters)


def reset_counters() -> None:
    """Bench A/B support: zero the byte/op counters (windows kept)."""
    with _lock:
        for k in _counters:
            _counters[k] = 0


def snapshot_if_fresh() -> dict | None:
    """Latency-source hook (CoreClient.add_latency_source): the bounded
    stage windows in the ns="latency" publish format, or None when no
    new sharded op happened since the last CONFIRMED publish.
    ``mark_published`` advances the cursor only once the flush's kv_put
    landed — a transient GCS error republishes this window next tick."""
    global _snapped
    with _lock:
        if _count == _published:
            return None
        _snapped = _count
        stages = {s: list(w) for s, w in _windows.items() if w}
    if not stages:
        return None
    for name, vals in stages.items():
        svals = sorted(vals)
        for q, qn in ((0.5, "p50"), (0.99, "p99")):
            metrics.task_stage_us.set(
                recorder.percentile(svals, q) / 1e3,
                tags={"stage": name, "q": qn})
    # no "count" key: list_task_latency's tasks_total must keep counting
    # TASKS — the per-stage counts below come from the stage lists
    return {"stages": stages}


def mark_published() -> None:
    """Publish confirmation from the flush (kv_put landed)."""
    global _published
    with _lock:
        _published = _snapped


def _maybe_register() -> None:
    """Attach the sharded window to the CURRENT CoreClient's latency
    publish loop (idempotent per core; skipped quietly before a core
    exists). Tracked by core identity, not a boolean: an init ->
    shutdown -> init cycle builds a fresh CoreClient whose
    _latency_sources starts empty — a sticky flag would silently stop
    publishing the sharded stages for the second session."""
    global _registered_core
    from ray_tpu.core import api

    core = api._core
    if core is None or core is _registered_core:
        return
    try:
        core.add_latency_source("sharded", snapshot_if_fresh,
                                confirm=mark_published)
        _registered_core = core
    except AttributeError:
        pass


def _reset_for_tests() -> None:
    global _count, _published, _snapped, _registered_core
    with _lock:
        for w in _windows.values():
            w.clear()
        _count = 0
        _published = -1
        _snapped = -1
        _registered_core = None
        for k in _counters:
            _counters[k] = 0

"""put_sharded / get_sharded: the sharded object plane's data path.

``put_sharded(jax_array)`` walks the array's addressable shards, dedupes
replicas by tile box, and seals each unique shard DIRECTLY into this
host's shm arena — the global array is never materialized, and nothing
but the manifest exists driver-side. ``get_sharded(ref)`` reassembles a
device-local ``jax.Array`` the opposite way: each shard is read
zero-copy out of local shm (the completion lane's location cache and
owner memory-store make the local-hit check one dict probe), device_put
onto its mesh position, and stitched with
``jax.make_array_from_single_device_arrays``.

Placement is partition-rule driven: a numpy input plus
``rules=PartitionRules.llama(), path="wq/kernel"`` picks its spec with
the same ``spec_for`` table the train layer shards parameters with.

Fault story: every seal passes the ``sharded.shard_seal`` chaos point
(action ``error`` -> ObjectStoreError, ``drop`` -> the sealed copy is
deleted after landing, i.e. "the seal was lost"). A shard produced by a
task (see submit.py) recovers from loss through the task's core lineage
— only THAT shard's producing task re-runs; put_sharded shards have no
producer and surface ObjectLostError, like ``ray.put`` values.
"""

from __future__ import annotations

import time

import numpy as np

from ray_tpu.core import object_store, tiering
from ray_tpu.core.ref import ObjectLostError, ObjectRef
from ray_tpu.devtools import chaos
from ray_tpu.sharded import telemetry
from ray_tpu.sharded.manifest import (
    ShardedObjectRef,
    ShardEntry,
    ShardManifest,
    box_of_indices,
    partition_boxes,
    spec_to_tuple,
    tuple_to_spec,
)


def _core():
    from ray_tpu.core import api

    return api.get_core()


# cold-set tracker for put_sharded seals (core/tiering.py): the raylet's
# cooperative spill can trade cold referenced shards to tier-1 and the
# tracker stamps each entry's (tier, spill_path) leg when they land.
# Weakref-held — tracking never outlives the manifest.
_cold: tiering.ColdTracker | None = None


def _cold_tracker() -> tiering.ColdTracker:
    global _cold
    if _cold is None:
        _cold = tiering.ColdTracker("shard_plane")
    return _cold


def _mesh_axes_of(mesh) -> dict:
    return {str(name): int(size) for name, size in mesh.shape.items()}


def manifest_nbytes(m: ShardManifest) -> int:
    """Deterministic size estimate of the wire manifest (what actually
    crosses the driver for this sharded object): fixed header + per-dim
    extents + ~(oid + owner address + box + node id) per shard. Used by
    the driver-bytes counter so bench can show O(manifest) vs O(array)
    without a side-effecting pickle of live ObjectRefs."""
    return 48 + 24 * len(m.global_shape) + 96 * len(m.shards)


def _seal_shard(core, value: np.ndarray, *, shard: int,
                phase: str) -> ObjectRef:
    """Seal one shard's bytes into the local shm arena (memory store in
    client mode) and return its owned ref. The ``sharded.shard_seal``
    fault point fires here for the put/reshard phases."""
    act = None
    if chaos.ENABLED:
        try:
            act = chaos.point("sharded.shard_seal", shard=int(shard),
                              phase=phase)
        except chaos.ChaosError as e:
            raise object_store.ObjectStoreError(
                f"shard {shard} seal: {e}") from e
    t0 = time.perf_counter_ns()
    ref = core.put_value(value, prefer_shm=True)
    dur = time.perf_counter_ns() - t0
    if act is not None and act.kind == "drop" and core.store is not None:
        # "the seal was lost": the bytes landed, then vanished — exactly
        # the window a node-local eviction/crash opens. Consumers see a
        # missing local copy and go through pull -> lineage recovery.
        core.store.delete(ref.id)
    telemetry.record(telemetry.SHARD_SEAL, dur, int(value.nbytes))
    return ref


def put_sharded(value, *, spec=None, mesh=None, rules=None, path: str = "",
                mesh_spec=None) -> ShardedObjectRef:
    """Store a sharded array as a manifest of per-host shm shards.

    ``value`` may be a jax.Array carrying a NamedSharding (mesh/spec are
    taken from it unless overridden) or a host array plus an explicit
    ``mesh`` (or ``mesh_spec``) and either ``spec`` or
    ``rules``+``path`` (PartitionRules.spec_for drives the choice).
    The global array is never serialized whole; replicas dedupe to one
    sealed copy per unique tile box.
    """
    core = _core()
    from ray_tpu.utils.device import configure_jax

    configure_jax()
    import jax

    if mesh is None and mesh_spec is not None:
        mesh = mesh_spec.build()

    # a NamedSharding-carrying jax.Array defaults mesh and spec
    # INDEPENDENTLY: overriding one must not silently drop the other
    if isinstance(value, jax.Array) and hasattr(value.sharding, "mesh"):
        if mesh is None:
            mesh = value.sharding.mesh
        if spec is None and rules is None:
            spec = value.sharding.spec
    if rules is not None and spec is None:
        ndim = getattr(value, "ndim", 0)
        spec = rules.spec_for(path, ndim)
    if mesh is None:
        raise ValueError("put_sharded needs a mesh (or a jax.Array with "
                         "a NamedSharding)")
    if spec is None:
        from jax.sharding import PartitionSpec as P

        spec = P()  # fully replicated: one shard
    spec_t = spec_to_tuple(spec)
    axes = _mesh_axes_of(mesh)
    global_shape = tuple(int(d) for d in value.shape)
    dtype = str(value.dtype)
    boxes = partition_boxes(global_shape, spec_t, axes)

    shard_values: dict[tuple, np.ndarray] = {}
    if isinstance(value, jax.Array):
        for s in value.addressable_shards:
            box = box_of_indices(s.index, global_shape)
            if box not in shard_values:
                shard_values[box] = np.asarray(s.data)
    else:
        arr = np.asarray(value)
        for box in boxes:
            shard_values[box] = arr[tuple(slice(a, b) for a, b in box)]

    entries: list[ShardEntry] = []
    node = core.node_id.binary() if core.node_id is not None else None
    for i, box in enumerate(boxes):
        sv = shard_values.get(box)
        if sv is None:
            raise ValueError(
                f"shard for box {box} is not addressable from this host; "
                "put_sharded runs where the shards live (call it in the "
                "worker that owns them)")
        sv = np.ascontiguousarray(sv)
        ref = _seal_shard(core, sv, shard=i, phase="put")
        entry = ShardEntry(box=box, ref=ref, node=node,
                           nbytes=int(sv.nbytes))
        entries.append(entry)
        if core.store is not None:
            _cold_tracker().track(ref.id.binary(), entry.nbytes, entry)
    m = ShardManifest(global_shape=global_shape, dtype=dtype, spec=spec_t,
                      mesh_axes=axes, shards=entries)
    telemetry.count_driver_bytes(manifest_nbytes(m))
    return ShardedObjectRef(m)


def fetch_shard(sref: ShardedObjectRef, i: int):
    """One shard's host value — zero-copy from local shm when the bytes
    are on this node, a raylet pull otherwise (api.get's caller-thread
    prepass handles the local hit). A lost task-produced shard
    re-materializes from lineage inside the get (only that shard's
    producing task re-runs); put_sharded shards have no producer."""
    _core()  # ensure the runtime is up before touching refs
    entry = sref.manifest.shards[i]
    t0 = time.perf_counter_ns()
    try:
        from ray_tpu.core import api

        value = api.get(entry.ref)
    except ObjectLostError as e:
        raise ObjectLostError(
            f"shard {i} of {sref!r} is lost and could not be "
            "re-materialized (put_sharded shards have no lineage; a "
            "task-produced shard's reconstruction was exhausted)"
        ) from e
    if entry.tier == tiering.TIER_DISK:
        # the get restored it through the raylet's spill file: the bytes
        # are shm-resident again, promote the advisory tier leg back
        entry.tier = tiering.TIER_SHM
        entry.spill_path = ""
    telemetry.record(telemetry.SHARD_FETCH, time.perf_counter_ns() - t0,
                     int(getattr(value, "nbytes", 0)))
    return value


def get_sharded(sref: ShardedObjectRef, *, mesh=None):
    """Reassemble a device-local ``jax.Array`` from the manifest: each
    unique shard is fetched once (zero-copy local read), device_put onto
    every mesh position that addresses its tile, and stitched without
    ever forming the global host array."""
    from ray_tpu.utils.device import configure_jax

    configure_jax()
    import jax
    from jax.sharding import NamedSharding

    if mesh is None:
        mesh = sref.build_mesh()
    shape = sref.shape
    sharding = NamedSharding(mesh, tuple_to_spec(sref.spec))
    index_map = sharding.addressable_devices_indices_map(shape)
    by_box = sref.manifest.box_index()
    cache: dict[tuple, np.ndarray] = {}
    parts = []
    for dev, index in index_map.items():
        box = box_of_indices(index, shape)
        i = by_box.get(box)
        if i is None:
            raise ValueError(
                f"mesh/spec disagree with the manifest: no shard covers "
                f"{box} (manifest spec {sref.spec}, axes {sref.mesh_axes})")
        val = cache.get(box)
        if val is None:
            val = np.asarray(fetch_shard(sref, i))
            cache[box] = val
        parts.append(jax.device_put(val, dev))
    telemetry.count_driver_bytes(manifest_nbytes(sref.manifest))
    return jax.make_array_from_single_device_arrays(shape, sharding, parts)


def stats() -> dict:
    """Sharded-plane counters: driver metadata bytes vs shard payload
    bytes, plus op counts (the bench arm's zero-copy evidence)."""
    return telemetry.counters()

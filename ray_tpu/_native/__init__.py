"""Native library loader: builds the C++ store on first use and ctypes-wraps it.

The shared library is compiled from ``src/*.cc`` with g++ into
``ray_tpu/_native/build/`` keyed by a source hash, so editing the C++
transparently rebuilds. No pip/pybind dependency: plain ``extern "C"`` +
ctypes, with Python mapping the same /dev/shm file for zero-copy views.
"""

from __future__ import annotations

import ctypes
import hashlib
import os
import subprocess
import threading

_HERE = os.path.dirname(os.path.abspath(__file__))
_SRC_DIR = os.path.join(_HERE, "src")
_BUILD_DIR = os.path.join(_HERE, "build")

_lib = None
_lib_lock = threading.Lock()


def _source_files() -> list[str]:
    # rt_cpp_* is the standalone C++ worker runtime (see build_cpp_worker),
    # not part of the in-process store library
    return sorted(
        os.path.join(_SRC_DIR, f)
        for f in os.listdir(_SRC_DIR)
        if f.endswith(".cc") and not f.startswith("rt_cpp")
    )


def _source_hash() -> str:
    h = hashlib.sha256()
    for path in _source_files():
        # one-time lazy build: get_lib() caches the CDLL, so this
        # file read never recurs per call
        with open(path, "rb") as f:  # raylint: disable=RT020 -- one-time build
            h.update(f.read())
    return h.hexdigest()[:16]


def _build() -> str:
    os.makedirs(_BUILD_DIR, exist_ok=True)
    tag = _source_hash()
    so_path = os.path.join(_BUILD_DIR, f"libray_tpu_native_{tag}.so")
    if os.path.exists(so_path):
        return so_path
    tmp = so_path + f".tmp{os.getpid()}"  # raylint: disable=RT021 -- once per rebuild, not per call
    cmd = [
        "g++", "-O2", "-g", "-std=c++17", "-shared", "-fPIC",
        "-o", tmp, *_source_files(), "-lpthread", "-lrt",
    ]
    subprocess.run(cmd, check=True, capture_output=True)  # raylint: disable=RT020 -- one-time compile behind the get_lib() cache
    os.replace(tmp, so_path)  # atomic: concurrent builders race safely
    return so_path


def _build_cpp_binary(sources: list[str], runtime_cc: str, prefix: str,
                      out_path: str | None) -> str:
    os.makedirs(_BUILD_DIR, exist_ok=True)
    runtime = os.path.join(_SRC_DIR, runtime_cc)
    headers = [os.path.join(_SRC_DIR, h)
               for h in ("picklite.h", "rt_cpp_api.h", "rt_wire.h",
                         "rt_cpp_client.h")]
    h = hashlib.sha256()
    for p in [*sources, runtime, *headers]:
        with open(p, "rb") as f:
            h.update(f.read())
    tag = h.hexdigest()[:16]
    out = out_path or os.path.join(_BUILD_DIR, f"{prefix}_{tag}")
    if os.path.exists(out):
        if out_path is None:
            return out  # hash is in the name: existing == current
        # explicit out_path: the name carries no hash, so check the sidecar
        try:
            with open(out + ".hash") as f:
                if f.read().strip() == tag:
                    return out
        except OSError:
            pass  # no/unreadable sidecar: rebuild
    tmp = out + f".tmp{os.getpid()}"
    proc = subprocess.run(
        ["g++", "-std=c++17", "-O2", "-I", _SRC_DIR, "-o", tmp,
         *sources, runtime, "-pthread"],
        capture_output=True, text=True,
    )
    if proc.returncode != 0:
        # this compiles user-authored code: surface the diagnostics
        raise RuntimeError(
            f"C++ build failed (g++ exit {proc.returncode}):\n{proc.stderr}"
        )
    os.replace(tmp, out)
    if out_path is not None:
        with open(out + ".hash", "w") as f:
            f.write(tag)
    return out


def build_cpp_worker(sources: list[str], out_path: str | None = None) -> str:
    """Compile a C++ worker binary: user RT_REMOTE sources + the rt runtime
    (rt_cpp_worker.cc / rt_cpp_api.h / picklite.h). Hash-keyed like the
    store build; returns the binary path for RT_CPP_WORKER."""
    return _build_cpp_binary(sources, "rt_cpp_worker.cc", "rt_cpp_worker", out_path)


def build_cpp_client(sources: list[str], out_path: str | None = None) -> str:
    """Compile a C++ driver binary against the rt client runtime
    (rt_cpp_client.cc): connect to a cluster and submit C++ tasks."""
    return _build_cpp_binary(sources, "rt_cpp_client.cc", "rt_cpp_client", out_path)


def get_lib() -> ctypes.CDLL:
    global _lib
    if _lib is not None:
        return _lib
    with _lib_lock:
        if _lib is None:
            lib = ctypes.CDLL(_build())
            u64 = ctypes.c_uint64
            p64 = ctypes.POINTER(u64)
            lib.rt_store_create.restype = ctypes.c_void_p
            lib.rt_store_create.argtypes = [ctypes.c_char_p, u64]
            lib.rt_store_connect.restype = ctypes.c_void_p
            lib.rt_store_connect.argtypes = [ctypes.c_char_p]
            lib.rt_store_close.argtypes = [ctypes.c_void_p]
            lib.rt_store_destroy.argtypes = [ctypes.c_char_p]
            lib.rt_store_capacity.restype = u64
            lib.rt_store_capacity.argtypes = [ctypes.c_void_p]
            lib.rt_store_bytes_in_use.restype = u64
            lib.rt_store_bytes_in_use.argtypes = [ctypes.c_void_p]
            lib.rt_store_list_spillable.restype = ctypes.c_int
            lib.rt_store_list_spillable.argtypes = [
                ctypes.c_void_p, ctypes.c_char_p, p64, ctypes.c_int]
            lib.rt_copy_nt.restype = None
            lib.rt_copy_nt.argtypes = [ctypes.c_void_p, ctypes.c_void_p, u64]
            lib.rt_create.argtypes = [ctypes.c_void_p, ctypes.c_char_p, u64, p64]
            lib.rt_seal.argtypes = [ctypes.c_void_p, ctypes.c_char_p]
            lib.rt_get.argtypes = [ctypes.c_void_p, ctypes.c_char_p, ctypes.c_int64, p64, p64]
            lib.rt_contains.argtypes = [ctypes.c_void_p, ctypes.c_char_p]
            lib.rt_release.argtypes = [ctypes.c_void_p, ctypes.c_char_p]
            lib.rt_delete.argtypes = [ctypes.c_void_p, ctypes.c_char_p]
            lib.rt_chan_create.argtypes = [ctypes.c_void_p, ctypes.c_char_p, u64, ctypes.c_uint32, p64]
            lib.rt_chan_data.argtypes = [ctypes.c_void_p, ctypes.c_char_p, p64, p64]
            lib.rt_chan_write_acquire.argtypes = [ctypes.c_void_p, ctypes.c_char_p, ctypes.c_int64]
            lib.rt_chan_write_release.argtypes = [ctypes.c_void_p, ctypes.c_char_p, u64]
            lib.rt_chan_read_acquire.argtypes = [ctypes.c_void_p, ctypes.c_char_p, u64, ctypes.c_int64, p64, p64]
            lib.rt_chan_read_release.argtypes = [ctypes.c_void_p, ctypes.c_char_p]
            lib.rt_chan_close.argtypes = [ctypes.c_void_p, ctypes.c_char_p]
            # task rings (fast-path transport)
            u8p = ctypes.POINTER(ctypes.c_uint8)
            i64 = ctypes.c_int64
            lib.rt_ring_pair_create.restype = ctypes.c_void_p
            lib.rt_ring_pair_create.argtypes = [ctypes.c_char_p, u64]
            lib.rt_ring_pair_open.restype = ctypes.c_void_p
            lib.rt_ring_pair_open.argtypes = [ctypes.c_char_p]
            lib.rt_ring_push.restype = ctypes.c_int
            lib.rt_ring_push.argtypes = [
                ctypes.c_void_p, ctypes.c_int, ctypes.c_char_p, u64, i64]
            lib.rt_ring_push_raw.restype = ctypes.c_int
            lib.rt_ring_push_raw.argtypes = [
                ctypes.c_void_p, ctypes.c_int, ctypes.c_char_p, u64, i64]
            lib.rt_ring_push_batch.restype = i64
            lib.rt_ring_push_batch.argtypes = [
                ctypes.c_void_p, ctypes.c_int, ctypes.c_char_p, u64, i64]
            lib.rt_ring_pop_batch.restype = i64
            lib.rt_ring_pop_batch.argtypes = [
                ctypes.c_void_p, ctypes.c_int, u8p, u64, i64]
            lib.rt_ring_pending.restype = u64
            lib.rt_ring_pending.argtypes = [ctypes.c_void_p, ctypes.c_int]
            lib.rt_ring_stats.restype = ctypes.c_int
            lib.rt_ring_stats.argtypes = [
                ctypes.c_void_p, ctypes.c_int, p64, ctypes.c_int]
            lib.rt_store_stats.restype = ctypes.c_int
            lib.rt_store_stats.argtypes = [ctypes.c_void_p, p64, ctypes.c_int]
            lib.rt_ring_close.argtypes = [ctypes.c_void_p, ctypes.c_int]
            lib.rt_ring_closed.restype = ctypes.c_int
            lib.rt_ring_closed.argtypes = [ctypes.c_void_p, ctypes.c_int]
            lib.rt_ring_pair_close.argtypes = [ctypes.c_void_p]
            lib.rt_ring_pair_destroy.argtypes = [ctypes.c_char_p]
            # chaos fault arms (devtools/chaos): runtime re-arm of the
            # env-gated counters in ring.cc / store.cc
            lib.rt_ring_chaos_set.restype = None
            lib.rt_ring_chaos_set.argtypes = [u64, u64]
            lib.rt_store_chaos_set.restype = None
            lib.rt_store_chaos_set.argtypes = [u64]
            # GCS state engine (gcs_core.cc)
            cp = ctypes.c_char_p
            lib.rt_gcs_open.restype = ctypes.c_void_p
            lib.rt_gcs_open.argtypes = [cp]
            lib.rt_gcs_close.argtypes = [ctypes.c_void_p]
            lib.rt_gcs_had_snapshot.restype = ctypes.c_int
            lib.rt_gcs_had_snapshot.argtypes = [ctypes.c_void_p]
            lib.rt_gcs_wal_records.restype = u64
            lib.rt_gcs_wal_records.argtypes = [ctypes.c_void_p]
            lib.rt_gcs_kv_put.restype = ctypes.c_int
            lib.rt_gcs_kv_put.argtypes = [
                ctypes.c_void_p, cp, u64, cp, u64, cp, u64,
                ctypes.c_int, ctypes.c_int]
            lib.rt_gcs_kv_get.restype = ctypes.c_int
            lib.rt_gcs_kv_get.argtypes = [
                ctypes.c_void_p, cp, u64, cp, u64, u8p, u64, p64]
            lib.rt_gcs_kv_del.restype = ctypes.c_int
            lib.rt_gcs_kv_del.argtypes = [
                ctypes.c_void_p, cp, u64, cp, u64, ctypes.c_int]
            lib.rt_gcs_kv_exists.restype = ctypes.c_int
            lib.rt_gcs_kv_exists.argtypes = [
                ctypes.c_void_p, cp, u64, cp, u64]
            lib.rt_gcs_kv_keys.restype = ctypes.c_int
            lib.rt_gcs_kv_keys.argtypes = [
                ctypes.c_void_p, cp, u64, cp, u64, u8p, u64, p64]
            lib.rt_gcs_kv_count.restype = u64
            lib.rt_gcs_kv_count.argtypes = [ctypes.c_void_p, cp, u64]
            lib.rt_gcs_journal_aux.restype = None
            lib.rt_gcs_journal_aux.argtypes = [ctypes.c_void_p, cp, u64]
            lib.rt_gcs_wal_ok.restype = ctypes.c_int
            lib.rt_gcs_wal_ok.argtypes = [ctypes.c_void_p]
            lib.rt_gcs_set_fsync.restype = None
            lib.rt_gcs_set_fsync.argtypes = [ctypes.c_void_p, ctypes.c_int]
            lib.rt_gcs_wal_sync.restype = ctypes.c_int
            lib.rt_gcs_wal_sync.argtypes = [ctypes.c_void_p]
            lib.rt_gcs_snapshot_aux.restype = ctypes.c_int
            lib.rt_gcs_snapshot_aux.argtypes = [ctypes.c_void_p, u8p, u64, p64]
            lib.rt_gcs_aux_count.restype = u64
            lib.rt_gcs_aux_count.argtypes = [ctypes.c_void_p]
            lib.rt_gcs_aux_get.restype = ctypes.c_int
            lib.rt_gcs_aux_get.argtypes = [ctypes.c_void_p, u64, u8p, u64, p64]
            lib.rt_gcs_snapshot.restype = ctypes.c_int
            lib.rt_gcs_snapshot.argtypes = [ctypes.c_void_p, cp, u64, cp]
            # RPC mux (mux.cc)
            lib.rt_mux_create.restype = ctypes.c_void_p
            lib.rt_mux_create.argtypes = [
                cp, ctypes.c_uint16, ctypes.POINTER(ctypes.c_uint16),
                ctypes.POINTER(ctypes.c_int)]
            lib.rt_mux_recv_batch.restype = i64
            lib.rt_mux_recv_batch.argtypes = [ctypes.c_void_p, u8p, u64]
            lib.rt_mux_send.restype = ctypes.c_int
            lib.rt_mux_send.argtypes = [ctypes.c_void_p, u64, cp, u64]
            lib.rt_mux_close_conn.argtypes = [ctypes.c_void_p, u64]
            lib.rt_mux_release.argtypes = [ctypes.c_void_p, u64]
            lib.rt_mux_port.restype = ctypes.c_uint16
            lib.rt_mux_port.argtypes = [ctypes.c_void_p]
            lib.rt_mux_stop.argtypes = [ctypes.c_void_p]
            _lib = lib
    return _lib

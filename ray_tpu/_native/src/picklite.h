// picklite — a pickle-subset codec for the ray_tpu wire protocol.
//
// The control plane frames every message as <u64 LE length><pickle bytes>
// (ref equivalent: the protobuf wire schemas under src/ray/protobuf/; here the
// schema is "python pickle of plain dicts", so native peers need a codec for
// exactly that subset). This header implements:
//
//   decode: the opcodes CPython's pickle protocol 5 emits for our envelopes —
//     dicts/lists/tuples/str/bytes/int/float/bool/None, memoization, framing,
//     out-of-band buffers (surfaced as bytes), and REDUCE-constructed objects
//     (TaskID/ObjectID/...) surfaced as Opaque{module, name, args}.
//   encode: a canonical subset (protocol 2 ops inside a protocol-5 header)
//     that CPython unpickles natively, including GLOBAL+REDUCE so native code
//     can raise real Python exception types on the driver.
//
// No Python, no dependencies. Header-only, C++17.
#pragma once

#include <cstdint>
#include <cstring>
#include <map>
#include <memory>
#include <stdexcept>
#include <string>
#include <utility>
#include <vector>

namespace picklite {

struct Value;
using ValuePtr = std::shared_ptr<Value>;

struct Value {
  enum Kind { kNone, kBool, kInt, kFloat, kStr, kBytes, kList, kTuple, kDict, kOpaque };
  Kind kind = kNone;
  bool b = false;
  int64_t i = 0;
  double d = 0.0;
  std::string s;                  // kStr / kBytes payload
  std::vector<ValuePtr> items;    // kList / kTuple elements; kOpaque ctor args
  std::vector<std::pair<ValuePtr, ValuePtr>> dict;  // kDict entries (insertion order)
  std::string mod, name;          // kOpaque: module + qualname of the callable

  static ValuePtr none() { return std::make_shared<Value>(); }
  static ValuePtr boolean(bool v) { auto p = std::make_shared<Value>(); p->kind = kBool; p->b = v; return p; }
  static ValuePtr integer(int64_t v) { auto p = std::make_shared<Value>(); p->kind = kInt; p->i = v; return p; }
  static ValuePtr real(double v) { auto p = std::make_shared<Value>(); p->kind = kFloat; p->d = v; return p; }
  static ValuePtr str(std::string v) { auto p = std::make_shared<Value>(); p->kind = kStr; p->s = std::move(v); return p; }
  static ValuePtr bytes(std::string v) { auto p = std::make_shared<Value>(); p->kind = kBytes; p->s = std::move(v); return p; }
  static ValuePtr list() { auto p = std::make_shared<Value>(); p->kind = kList; return p; }
  static ValuePtr tuple() { auto p = std::make_shared<Value>(); p->kind = kTuple; return p; }
  static ValuePtr dict_() { auto p = std::make_shared<Value>(); p->kind = kDict; return p; }
  static ValuePtr opaque(std::string m, std::string n) {
    auto p = std::make_shared<Value>(); p->kind = kOpaque; p->mod = std::move(m); p->name = std::move(n); return p;
  }

  // dict lookup by string key; nullptr when missing
  ValuePtr get(const std::string& key) const {
    for (auto& kv : dict)
      if (kv.first && kv.first->kind == kStr && kv.first->s == key) return kv.second;
    return nullptr;
  }
  void set(const std::string& key, ValuePtr v) {
    for (auto& kv : dict)
      if (kv.first && kv.first->kind == kStr && kv.first->s == key) { kv.second = std::move(v); return; }
    dict.emplace_back(Value::str(key), std::move(v));
  }
  bool truthy() const {
    switch (kind) {
      case kNone: return false;
      case kBool: return b;
      case kInt: return i != 0;
      case kFloat: return d != 0;
      case kStr: case kBytes: return !s.empty();
      case kList: case kTuple: return !items.empty();
      case kDict: return !dict.empty();
      default: return true;
    }
  }
};

class Error : public std::runtime_error {
 public:
  explicit Error(const std::string& m) : std::runtime_error("picklite: " + m) {}
};

// ------------------------------------------------------------------ decoder

class Decoder {
 public:
  // `buffers`: out-of-band pickle-5 buffers (NEXT_BUFFER pops in order),
  // surfaced to the value tree as kBytes.
  explicit Decoder(const uint8_t* data, size_t n,
                   std::vector<std::string> buffers = {})
      : p_(data), end_(data + n), buffers_(std::move(buffers)) {}

  ValuePtr parse() {
    std::vector<ValuePtr> stack;
    std::vector<size_t> marks;
    while (p_ < end_) {
      uint8_t op = *p_++;
      switch (op) {
        case 0x80: /*PROTO*/ need(1); ++p_; break;
        case 0x95: /*FRAME*/ need(8); p_ += 8; break;  // framing is advisory
        case '.': /*STOP*/
          if (stack.empty()) throw Error("STOP with empty stack");
          return stack.back();
        case 'N': stack.push_back(Value::none()); break;
        case 0x88: stack.push_back(Value::boolean(true)); break;
        case 0x89: stack.push_back(Value::boolean(false)); break;
        case 'K': /*BININT1*/ need(1); stack.push_back(Value::integer(*p_++)); break;
        case 'M': /*BININT2*/ { need(2); uint16_t v = rd16(); stack.push_back(Value::integer(v)); break; }
        case 'J': /*BININT*/ { need(4); int32_t v = (int32_t)rd32(); stack.push_back(Value::integer(v)); break; }
        case 0x8a: /*LONG1*/ { need(1); uint8_t n = *p_++; stack.push_back(Value::integer(rdlong(n))); break; }
        case 0x8b: /*LONG4*/ { need(4); uint32_t n = rd32(); stack.push_back(Value::integer(rdlong(n))); break; }
        case 'G': /*BINFLOAT (big-endian!)*/ {
          need(8);
          uint64_t u = 0;
          for (int k = 0; k < 8; ++k) u = (u << 8) | *p_++;
          double d; std::memcpy(&d, &u, 8);
          stack.push_back(Value::real(d));
          break;
        }
        case 0x8c: /*SHORT_BINUNICODE*/ { need(1); size_t n = *p_++; stack.push_back(Value::str(rdstr(n))); break; }
        case 'X': /*BINUNICODE*/ { need(4); size_t n = rd32(); stack.push_back(Value::str(rdstr(n))); break; }
        case 0x8d: /*BINUNICODE8*/ { need(8); size_t n = (size_t)rd64(); stack.push_back(Value::str(rdstr(n))); break; }
        case 'C': /*SHORT_BINBYTES*/ { need(1); size_t n = *p_++; stack.push_back(Value::bytes(rdstr(n))); break; }
        case 'B': /*BINBYTES*/ { need(4); size_t n = rd32(); stack.push_back(Value::bytes(rdstr(n))); break; }
        case 0x8e: /*BINBYTES8*/ { need(8); size_t n = (size_t)rd64(); stack.push_back(Value::bytes(rdstr(n))); break; }
        case 0x96: /*BYTEARRAY8*/ { need(8); size_t n = (size_t)rd64(); stack.push_back(Value::bytes(rdstr(n))); break; }
        case 0x97: /*NEXT_BUFFER*/ {
          if (buf_idx_ >= buffers_.size()) throw Error("NEXT_BUFFER underflow");
          stack.push_back(Value::bytes(buffers_[buf_idx_++]));
          break;
        }
        case 0x98: /*READONLY_BUFFER*/ break;  // view flag: no-op for us
        case ')': stack.push_back(Value::tuple()); break;
        case ']': stack.push_back(Value::list()); break;
        case '}': stack.push_back(Value::dict_()); break;
        case 0x8f: /*EMPTY_SET*/ stack.push_back(Value::list()); break;  // set ~ list
        case '(': /*MARK*/ marks.push_back(stack.size()); break;
        case 0x85: /*TUPLE1*/ collapse_tuple(stack, 1); break;
        case 0x86: /*TUPLE2*/ collapse_tuple(stack, 2); break;
        case 0x87: /*TUPLE3*/ collapse_tuple(stack, 3); break;
        case 't': /*TUPLE*/ {
          size_t m = pop_mark(marks);
          auto t = Value::tuple();
          t->items.assign(stack.begin() + m, stack.end());
          stack.resize(m);
          stack.push_back(t);
          break;
        }
        case 'a': /*APPEND*/ {
          auto v = pop(stack);
          top_kind(stack, Value::kList)->items.push_back(v);
          break;
        }
        case 'e': /*APPENDS*/ {
          size_t m = pop_mark(marks);
          auto lst = at_kind(stack, m - 1, Value::kList);
          lst->items.insert(lst->items.end(), stack.begin() + m, stack.end());
          stack.resize(m);
          break;
        }
        case 0x90: /*ADDITEMS (set)*/ {
          size_t m = pop_mark(marks);
          auto lst = at_kind(stack, m - 1, Value::kList);
          lst->items.insert(lst->items.end(), stack.begin() + m, stack.end());
          stack.resize(m);
          break;
        }
        case 's': /*SETITEM*/ {
          auto v = pop(stack), k = pop(stack);
          top_kind(stack, Value::kDict)->dict.emplace_back(k, v);
          break;
        }
        case 'u': /*SETITEMS*/ {
          size_t m = pop_mark(marks);
          auto d = at_kind(stack, m - 1, Value::kDict);
          if ((stack.size() - m) % 2) throw Error("odd SETITEMS");
          for (size_t k = m; k < stack.size(); k += 2)
            d->dict.emplace_back(stack[k], stack[k + 1]);
          stack.resize(m);
          break;
        }
        case 0x94: /*MEMOIZE*/ {
          if (stack.empty()) throw Error("MEMOIZE empty");
          memo_.push_back(stack.back());
          break;
        }
        case 'q': /*BINPUT*/ { need(1); size_t n = *p_++; put_memo(n, stack); break; }
        case 'r': /*LONG_BINPUT*/ { need(4); size_t n = rd32(); put_memo(n, stack); break; }
        case 'h': /*BINGET*/ { need(1); size_t n = *p_++; get_memo(n, stack); break; }
        case 'j': /*LONG_BINGET*/ { need(4); size_t n = rd32(); get_memo(n, stack); break; }
        case 0x93: /*STACK_GLOBAL*/ {
          auto name = pop(stack), mod = pop(stack);
          if (mod->kind != Value::kStr || name->kind != Value::kStr)
            throw Error("STACK_GLOBAL wants strings");
          stack.push_back(Value::opaque(mod->s, name->s));
          break;
        }
        case 'c': /*GLOBAL (newline text)*/ {
          std::string mod = rdline(), name = rdline();
          stack.push_back(Value::opaque(mod, name));
          break;
        }
        case 'R': /*REDUCE*/ {
          auto args = pop(stack), fn = pop(stack);
          stack.push_back(reduce(fn, args));
          break;
        }
        case 0x81: /*NEWOBJ*/ {
          auto args = pop(stack), cls = pop(stack);
          stack.push_back(reduce(cls, args));
          break;
        }
        case 0x92: /*NEWOBJ_EX*/ {
          pop(stack);  // kwargs
          auto args = pop(stack), cls = pop(stack);
          stack.push_back(reduce(cls, args));
          break;
        }
        case 'b': /*BUILD*/ { pop(stack); break; }  // drop state: opaque stays opaque
        default:
          throw Error("unsupported opcode 0x" + hex(op));
      }
    }
    throw Error("ran out of input before STOP");
  }

 private:
  const uint8_t* p_;
  const uint8_t* end_;
  std::vector<ValuePtr> memo_;
  std::map<size_t, ValuePtr> memo_map_;  // for BINPUT-addressed memos
  std::vector<std::string> buffers_;
  size_t buf_idx_ = 0;

  static std::string hex(uint8_t v) {
    static const char* digits = "0123456789abcdef";
    return std::string(1, digits[v >> 4]) + std::string(1, digits[v & 0xf]);
  }
  void need(size_t n) { if ((size_t)(end_ - p_) < n) throw Error("truncated"); }
  uint16_t rd16() { uint16_t v = p_[0] | (p_[1] << 8); p_ += 2; return v; }
  uint32_t rd32() { uint32_t v; std::memcpy(&v, p_, 4); p_ += 4; return v; }
  uint64_t rd64() { uint64_t v; std::memcpy(&v, p_, 8); p_ += 8; return v; }
  int64_t rdlong(size_t n) {
    need(n);
    if (n > 8) throw Error("LONG too wide for int64");
    uint64_t v = 0;
    for (size_t k = 0; k < n; ++k) v |= (uint64_t)p_[k] << (8 * k);
    if (n > 0 && n < 8 && (p_[n - 1] & 0x80)) v |= ~0ULL << (8 * n);  // sign-extend
    p_ += n;
    return (int64_t)v;
  }
  std::string rdstr(size_t n) { need(n); std::string s((const char*)p_, n); p_ += n; return s; }
  std::string rdline() {
    std::string s;
    while (p_ < end_ && *p_ != '\n') s.push_back((char)*p_++);
    if (p_ < end_) ++p_;
    return s;
  }
  static ValuePtr pop(std::vector<ValuePtr>& st) {
    if (st.empty()) throw Error("stack underflow");
    auto v = st.back(); st.pop_back(); return v;
  }
  static size_t pop_mark(std::vector<size_t>& marks) {
    if (marks.empty()) throw Error("no mark");
    size_t m = marks.back(); marks.pop_back(); return m;
  }
  static ValuePtr top_kind(std::vector<ValuePtr>& st, Value::Kind k) {
    if (st.empty() || st.back()->kind != k) throw Error("bad container on stack");
    return st.back();
  }
  static ValuePtr at_kind(std::vector<ValuePtr>& st, size_t idx, Value::Kind k) {
    if (idx >= st.size() || st[idx]->kind != k) throw Error("bad container at mark");
    return st[idx];
  }
  static void collapse_tuple(std::vector<ValuePtr>& st, size_t n) {
    if (st.size() < n) throw Error("tuple underflow");
    auto t = Value::tuple();
    t->items.assign(st.end() - n, st.end());
    st.resize(st.size() - n);
    st.push_back(t);
  }
  void put_memo(size_t n, std::vector<ValuePtr>& st) {
    if (st.empty()) throw Error("PUT empty");
    memo_map_[n] = st.back();
  }
  void get_memo(size_t n, std::vector<ValuePtr>& st) {
    auto it = memo_map_.find(n);
    if (it != memo_map_.end()) { st.push_back(it->second); return; }
    if (n < memo_.size()) { st.push_back(memo_[n]); return; }
    throw Error("memo miss");
  }
  // Callable application: keep REDUCE results opaque, carrying the ctor args
  // (enough to round-trip TaskID/ObjectID/... and to read e.g. id bytes).
  static ValuePtr reduce(const ValuePtr& fn, const ValuePtr& args) {
    auto v = Value::opaque(fn->mod, fn->name);
    if (fn->kind != Value::kOpaque) return v;  // degenerate; still opaque
    if (args->kind == Value::kTuple) v->items = args->items;
    else v->items.push_back(args);
    return v;
  }
};

// ------------------------------------------------------------------ encoder

class Encoder {
 public:
  std::string out;

  void header() { out += '\x80'; out += '\x05'; }  // PROTO 5 (ops below are <=2)
  void stop() { out += '.'; }

  void encode(const Value& v) {
    switch (v.kind) {
      case Value::kNone: out += 'N'; break;
      case Value::kBool: out += (v.b ? '\x88' : '\x89'); break;
      case Value::kInt: enc_int(v.i); break;
      case Value::kFloat: enc_float(v.d); break;
      case Value::kStr: enc_str(v.s); break;
      case Value::kBytes: enc_bytes(v.s); break;
      case Value::kTuple: enc_tuple(v.items); break;
      case Value::kList: {
        out += ']';
        if (!v.items.empty()) {
          out += '(';
          for (auto& it : v.items) encode(*it);
          out += 'e';
        }
        break;
      }
      case Value::kDict: {
        out += '}';
        if (!v.dict.empty()) {
          out += '(';
          for (auto& kv : v.dict) { encode(*kv.first); encode(*kv.second); }
          out += 'u';
        }
        break;
      }
      case Value::kOpaque: {
        // GLOBAL module\nname\n + args tuple + REDUCE: unpickles to
        // module.name(*args) on the Python side (how native code raises
        // e.g. ray_tpu.core.ref.TaskError on the driver).
        out += 'c';
        out += v.mod; out += '\n';
        out += v.name; out += '\n';
        enc_tuple(v.items);
        out += 'R';
        break;
      }
    }
  }

  static std::string dumps(const Value& v) {
    Encoder e;
    e.header();
    e.encode(v);
    e.stop();
    return e.out;
  }

 private:
  void u32(uint32_t v) { out.append((const char*)&v, 4); }
  void u64(uint64_t v) { out.append((const char*)&v, 8); }
  void enc_int(int64_t v) {
    if (v >= INT32_MIN && v <= INT32_MAX) {
      out += 'J';
      int32_t x = (int32_t)v;
      out.append((const char*)&x, 4);
      return;
    }
    out += '\x8a';  // LONG1
    uint8_t buf[9];
    size_t n = 0;
    uint64_t u = (uint64_t)v;
    do { buf[n++] = u & 0xff; u >>= 8; } while (n < 8);
    while (n > 1) {  // trim redundant sign bytes
      uint8_t hi = buf[n - 1], next = buf[n - 2];
      if ((hi == 0x00 && !(next & 0x80)) || (hi == 0xff && (next & 0x80))) --n;
      else break;
    }
    out += (char)n;
    out.append((const char*)buf, n);
  }
  void enc_float(double d) {
    out += 'G';
    uint64_t u; std::memcpy(&u, &d, 8);
    for (int k = 7; k >= 0; --k) out += (char)((u >> (8 * k)) & 0xff);
  }
  // Strict UTF-8 (matches CPython's decoder): rejects overlong encodings,
  // UTF-16 surrogates (U+D800-DFFF), code points above U+10FFFF, and
  // invalid lead bytes — anything CPython's BINUNICODE decode would reject.
  static bool valid_utf8(const std::string& s) {
    size_t i = 0, n = s.size();
    while (i < n) {
      uint8_t c = (uint8_t)s[i];
      if (c < 0x80) { ++i; continue; }
      size_t extra;
      uint32_t cp;
      if ((c & 0xe0) == 0xc0) { extra = 1; cp = c & 0x1f; }
      else if ((c & 0xf0) == 0xe0) { extra = 2; cp = c & 0x0f; }
      else if ((c & 0xf8) == 0xf0) { extra = 3; cp = c & 0x07; }
      else return false;  // continuation or F8+ lead byte
      if (i + extra >= n) return false;
      for (size_t k = 1; k <= extra; ++k) {
        uint8_t cc = (uint8_t)s[i + k];
        if ((cc & 0xc0) != 0x80) return false;
        cp = (cp << 6) | (cc & 0x3f);
      }
      static const uint32_t kMin[4] = {0, 0x80, 0x800, 0x10000};
      if (cp < kMin[extra]) return false;                 // overlong
      if (cp >= 0xd800 && cp <= 0xdfff) return false;    // surrogate
      if (cp > 0x10ffff) return false;                   // out of range
      i += extra + 1;
    }
    return true;
  }
  void enc_str(const std::string& s) {
    // BINUNICODE payloads must be UTF-8 or the Python-side unpickle blows
    // up far from the producing task — fail here with a clear error instead
    if (!valid_utf8(s))
      throw Error("Value::str holds non-UTF-8 bytes; use Value::bytes for binary data");
    // >=4 GiB payloads need the 8-byte length opcode: a silent uint32
    // truncation would emit a corrupt frame, not an error
    if (s.size() > 0xffffffffULL) { out += (char)0x8d; u64(s.size()); }
    else { out += 'X'; u32((uint32_t)s.size()); }
    out += s;
  }
  void enc_bytes(const std::string& s) {
    if (s.size() > 0xffffffffULL) { out += (char)0x8e; u64(s.size()); }
    else { out += 'B'; u32((uint32_t)s.size()); }
    out += s;
  }
  void enc_tuple(const std::vector<ValuePtr>& items) {
    if (items.empty()) { out += ')'; return; }
    if (items.size() <= 3) {
      for (auto& it : items) encode(*it);
      out += (char)(0x85 + items.size() - 1);
      return;
    }
    out += '(';
    for (auto& it : items) encode(*it);
    out += 't';
  }
};

inline ValuePtr loads(const std::string& data, std::vector<std::string> buffers = {}) {
  Decoder d((const uint8_t*)data.data(), data.size(), std::move(buffers));
  return d.parse();
}

inline std::string dumps(const Value& v) { return Encoder::dumps(v); }

}  // namespace picklite

// Shared-memory task rings: the native transport for the steady-state
// task-submission fast path.
//
// Role in the design (ref: src/ray/core_worker/transport/
// normal_task_submitter.cc:28 + core_worker.cc:2500 — the reference's
// steady-state submit->lease-cache->push->reply loop runs entirely in
// C++): once a lease is cached, pushing a task and reading its reply
// should cost two memcpys, not an asyncio frame + socket syscall on each
// side. A RingPair is one POSIX shm segment holding two SPSC byte rings
// (submit: driver -> worker, reply: worker -> driver). Producers and
// consumers block on process-shared robust condvars only when the ring is
// full/empty; in steady state both sides stay awake and no syscalls are
// made. Records are [u32 len][payload] frames; the payload encoding is
// the Python layer's business.
//
// Crash-safety: mutexes are robust (EOWNERDEAD -> consistent), and either
// side can mark the ring closed; blocked peers wake with kClosed.

#include <cerrno>
#include <cstdint>
#include <cstdlib>
#include <cstring>
#include <ctime>

#include <fcntl.h>
#include <pthread.h>
#include <sched.h>
#include <sys/mman.h>
#include <sys/stat.h>
#include <unistd.h>

namespace {

constexpr uint64_t kRingMagic = 0x52545249'4e473145ull;  // "RTRING1E"

enum RingError : int {
  kOK = 0,
  kTimeout = -4,
  kClosed = -7,
  kTooBig = -9,
  kSys = -6,
};

// Per-direction counters, IN the shared segment so both sides read the
// same numbers (the Python layer surfaces them as rt_ring_* gauges —
// the metric_defs.cc stats-family role for the ring transport). Updated
// under the ring mutex: plain adds, no extra atomics on the hot path.
struct RingStats {
  uint64_t push_ops;        // native push calls that moved >= 1 byte
  uint64_t push_bytes;
  uint64_t push_records;    // framed records pushed (where the call can tell)
  uint64_t pop_ops;         // pop calls that returned >= 1 record
  uint64_t pop_bytes;
  uint64_t pop_records;
  uint64_t producer_waits;  // futex sleeps while full (the "full" events)
  uint64_t consumer_waits;  // futex sleeps while empty
  uint64_t wake_signals;    // broadcasts actually issued (waiters != 0)
  uint64_t spin_hits;       // consumer spin found data without sleeping
  uint64_t partial_pushes;  // push_batch couldn't take the whole buffer
  uint64_t peak_used;       // max observed occupancy (bytes)
};
constexpr int kRingStatsFields = sizeof(RingStats) / sizeof(uint64_t);

struct Ring {
  pthread_mutex_t mu;
  pthread_cond_t cv;      // broadcast on push, pop and close
  uint64_t capacity;      // data area bytes
  uint64_t head;          // total bytes ever written (producer cursor)
  uint64_t tail;          // total bytes ever read (consumer cursor)
  uint32_t closed;
  uint32_t waiters;       // threads inside cond_wait (under mu)
  uint64_t data_off;      // data area offset from segment base
  RingStats stats;
};

struct PairHeader {
  uint64_t magic;
  uint64_t total_size;
  Ring sub;   // driver -> worker
  Ring rep;   // worker -> driver
};

struct RingHandle {
  PairHeader* hdr;
  uint8_t* base;
  uint64_t total;
  int fd;
};

uint64_t align_up(uint64_t n, uint64_t a) { return (n + a - 1) & ~(a - 1); }

// ---------------------------------------------------------------------------
// Chaos fault arms (devtools/chaos): env-gated counters that force the rare
// ring conditions — partial batch pushes and wait timeouts — on a fixed
// cadence, so the Python recovery paths (flush retry from the consumed
// prefix, RPC spill, lane break) are exercised below the Python layer.
// Disarmed (the default) the cost is one relaxed load of a zero. Armed via
// RT_CHAOS_RING_*_EVERY at dlopen (spawned workers inherit the env) or
// rt_ring_chaos_set at runtime. Counters are atomics: the arms must not
// introduce a data race the TSAN matrix would (rightly) flag.
uint64_t env_every(const char* name) {
  const char* raw = getenv(name);
  if (!raw) return 0;
  char* end = nullptr;
  unsigned long long v = strtoull(raw, &end, 10);
  return (end && *end == '\0') ? (uint64_t)v : 0;
}

uint64_t g_chaos_partial_every = env_every("RT_CHAOS_RING_PARTIAL_EVERY");
uint64_t g_chaos_timeout_every = env_every("RT_CHAOS_RING_TIMEOUT_EVERY");
uint64_t g_chaos_partial_ctr = 0;
uint64_t g_chaos_timeout_ctr = 0;

// true on every Nth call while armed
bool chaos_strike(uint64_t* every_p, uint64_t* ctr) {
  uint64_t every = __atomic_load_n(every_p, __ATOMIC_RELAXED);
  if (every == 0) return false;
  return __atomic_add_fetch(ctr, 1, __ATOMIC_RELAXED) % every == 0;
}

int lock(pthread_mutex_t* mu) {
  int rc = pthread_mutex_lock(mu);
  if (rc == EOWNERDEAD) {
    pthread_mutex_consistent(mu);
    rc = 0;
  }
  return rc;
}

void init_sync(pthread_mutex_t* mu, pthread_cond_t* cv) {
  pthread_mutexattr_t ma;
  pthread_mutexattr_init(&ma);
  pthread_mutexattr_setpshared(&ma, PTHREAD_PROCESS_SHARED);
  pthread_mutexattr_setrobust(&ma, PTHREAD_MUTEX_ROBUST);
  pthread_mutex_init(mu, &ma);
  pthread_mutexattr_destroy(&ma);
  pthread_condattr_t ca;
  pthread_condattr_init(&ca);
  pthread_condattr_setpshared(&ca, PTHREAD_PROCESS_SHARED);
  pthread_condattr_setclock(&ca, CLOCK_MONOTONIC);
  pthread_cond_init(cv, &ca);
  pthread_condattr_destroy(&ca);
}

// Wake sleepers only after the mutex is released, and only if there are
// any: broadcasting while holding the lock on a single-core host preempts
// the signaler into a woken thread that instantly blocks on the held
// mutex (two extra context switches per record); and in the spin-paired
// steady state nobody sleeps at all, so the futex syscall is skipped
// entirely.
void unlock_and_wake(Ring* r) {
  uint32_t waiters = r->waiters;
  if (waiters != 0) r->stats.wake_signals++;  // still under mu
  pthread_mutex_unlock(&r->mu);
  if (waiters != 0) pthread_cond_broadcast(&r->cv);
}

// Producer-side occupancy bookkeeping, called under mu after advancing head.
void note_push(Ring* r, uint64_t bytes, uint64_t records) {
  RingStats* st = &r->stats;
  st->push_ops++;
  st->push_bytes += bytes;
  st->push_records += records;
  uint64_t used = r->head - r->tail;
  if (used > st->peak_used) st->peak_used = used;
}

int timed_wait(Ring* r, int64_t timeout_ms) {
  pthread_cond_t* cv = &r->cv;
  pthread_mutex_t* mu = &r->mu;
  r->waiters++;
  int rc;
  if (timeout_ms < 0) {
    rc = pthread_cond_wait(cv, mu);
  } else {
    timespec ts;
    clock_gettime(CLOCK_MONOTONIC, &ts);
    ts.tv_sec += timeout_ms / 1000;
    ts.tv_nsec += (timeout_ms % 1000) * 1000000L;
    if (ts.tv_nsec >= 1000000000L) {
      ts.tv_sec += 1;
      ts.tv_nsec -= 1000000000L;
    }
    rc = pthread_cond_timedwait(cv, mu, &ts);
  }
  if (rc == EOWNERDEAD) {
    pthread_mutex_consistent(mu);
    rc = 0;
  }
  r->waiters--;
  return rc;
}

Ring* ring_of(RingHandle* h, int which) {
  return which == 0 ? &h->hdr->sub : &h->hdr->rep;
}

// Opportunistic spin before a futex sleep: on a busy ring the next record
// lands within microseconds, and a shared-futex sleep/wake round measured
// 60-90us per side here (vs ~1us for a yield). sched_yield (rather than a
// pause loop) matters on single-core hosts: it hands the core to the peer
// instead of burning the timeslice it needs — and while the consumer
// spins its `waiters` stays 0, so the producer skips ITS wake syscall
// too: a fast round trip (the completion lane's submit->execute->reply
// ping-pong) can close with zero futex calls on either side. 64
// iterations spans the peer's turnaround for a small task (each yield
// hands it a scheduler slice); an idle ring still reaches the futex
// sleep after ~65 yields, which return immediately when nothing else is
// runnable, so parked consumers stay cheap. Returns true if the
// condition became true without sleeping.
template <typename F>
bool spin_for(F cond) {
  for (int i = 0; i < 64; i++) {
    if (cond()) return true;
    sched_yield();
  }
  return cond();
}

void copy_in(uint8_t* data, uint64_t cap, uint64_t pos, const uint8_t* src,
             uint64_t len) {
  uint64_t off = pos % cap;
  uint64_t first = cap - off;
  if (first >= len) {
    memcpy(data + off, src, len);
  } else {
    memcpy(data + off, src, first);
    memcpy(data, src + first, len - first);
  }
}

void copy_out(const uint8_t* data, uint64_t cap, uint64_t pos, uint8_t* dst,
              uint64_t len) {
  uint64_t off = pos % cap;
  uint64_t first = cap - off;
  if (first >= len) {
    memcpy(dst, data + off, len);
  } else {
    memcpy(dst, data + off, first);
    memcpy(dst + first, data, len - first);
  }
}

}  // namespace

extern "C" {

// Create the segment (driver side). cap_each is the data capacity of EACH
// direction's ring. Returns NULL on failure.
void* rt_ring_pair_create(const char* name, uint64_t cap_each) {
  cap_each = align_up(cap_each, 64);
  uint64_t hdr_sz = align_up(sizeof(PairHeader), 64);
  uint64_t total = hdr_sz + 2 * cap_each;
  shm_unlink(name);
  int fd = shm_open(name, O_CREAT | O_EXCL | O_RDWR, 0600);
  if (fd < 0) return nullptr;
  if (ftruncate(fd, (off_t)total) != 0) {
    close(fd);
    shm_unlink(name);
    return nullptr;
  }
  void* mem = mmap(nullptr, total, PROT_READ | PROT_WRITE, MAP_SHARED, fd, 0);
  if (mem == MAP_FAILED) {
    close(fd);
    shm_unlink(name);
    return nullptr;
  }
  auto* hdr = (PairHeader*)mem;
  memset(hdr, 0, sizeof(PairHeader));
  hdr->total_size = total;
  hdr->sub.capacity = cap_each;
  hdr->sub.data_off = hdr_sz;
  hdr->rep.capacity = cap_each;
  hdr->rep.data_off = hdr_sz + cap_each;
  init_sync(&hdr->sub.mu, &hdr->sub.cv);
  init_sync(&hdr->rep.mu, &hdr->rep.cv);
  __atomic_store_n(&hdr->magic, kRingMagic, __ATOMIC_RELEASE);
  auto* h = new RingHandle{hdr, (uint8_t*)mem, total, fd};
  return h;
}

void* rt_ring_pair_open(const char* name) {
  int fd = shm_open(name, O_RDWR, 0600);
  if (fd < 0) return nullptr;
  struct stat st;
  if (fstat(fd, &st) != 0 || (uint64_t)st.st_size < sizeof(PairHeader)) {
    close(fd);
    return nullptr;
  }
  void* mem =
      mmap(nullptr, st.st_size, PROT_READ | PROT_WRITE, MAP_SHARED, fd, 0);
  if (mem == MAP_FAILED) {
    close(fd);
    return nullptr;
  }
  auto* hdr = (PairHeader*)mem;
  if (__atomic_load_n(&hdr->magic, __ATOMIC_ACQUIRE) != kRingMagic ||
      hdr->total_size != (uint64_t)st.st_size) {
    munmap(mem, st.st_size);
    close(fd);
    return nullptr;
  }
  auto* h = new RingHandle{hdr, (uint8_t*)mem, (uint64_t)st.st_size, fd};
  return h;
}

// Push one [u32 len][payload] record; blocks while full. which: 0=sub 1=rep.
int rt_ring_push(void* hp, int which, const uint8_t* buf, uint64_t len,
                 int64_t timeout_ms) {
  auto* h = (RingHandle*)hp;
  Ring* r = ring_of(h, which);
  if (chaos_strike(&g_chaos_timeout_every, &g_chaos_timeout_ctr))
    return kTimeout;  // forced "ring stayed full": caller retries/spills
  uint64_t need = align_up(4 + len, 8);
  if (need > r->capacity) return kTooBig;
  uint8_t* data = h->base + r->data_off;
  if (lock(&r->mu) != 0) return kSys;
  while (true) {
    if (r->closed) {
      pthread_mutex_unlock(&r->mu);
      return kClosed;
    }
    if (r->capacity - (r->head - r->tail) >= need) break;
    r->stats.producer_waits++;
    int rc = timed_wait(r, timeout_ms);
    if (rc == ETIMEDOUT) {
      pthread_mutex_unlock(&r->mu);
      return kTimeout;
    }
    if (rc != 0) {
      pthread_mutex_unlock(&r->mu);
      return kSys;
    }
  }
  uint32_t len32 = (uint32_t)len;
  copy_in(data, r->capacity, r->head, (const uint8_t*)&len32, 4);
  copy_in(data, r->capacity, r->head + 4, buf, len);
  __atomic_store_n(&r->head, r->head + need, __ATOMIC_RELEASE);
  note_push(r, need, 1);
  unlock_and_wake(r);
  return kOK;
}

// Push a buffer that already contains N framed records, atomically w.r.t.
// interleaving with this producer's other pushes (it is SPSC, so that just
// means one lock round). Blocks until all of it fits.
int rt_ring_push_raw(void* hp, int which, const uint8_t* buf, uint64_t len,
                     int64_t timeout_ms) {
  auto* h = (RingHandle*)hp;
  Ring* r = ring_of(h, which);
  uint64_t need = len;  // caller pre-aligned: every record align_up(4+n,8)
  if (need > r->capacity) return kTooBig;
  uint8_t* data = h->base + r->data_off;
  if (lock(&r->mu) != 0) return kSys;
  while (true) {
    if (r->closed) {
      pthread_mutex_unlock(&r->mu);
      return kClosed;
    }
    if (r->capacity - (r->head - r->tail) >= need) break;
    r->stats.producer_waits++;
    int rc = timed_wait(r, timeout_ms);
    if (rc == ETIMEDOUT) {
      pthread_mutex_unlock(&r->mu);
      return kTimeout;
    }
    if (rc != 0) {
      pthread_mutex_unlock(&r->mu);
      return kSys;
    }
  }
  copy_in(data, r->capacity, r->head, buf, len);
  __atomic_store_n(&r->head, r->head + need, __ATOMIC_RELEASE);
  note_push(r, need, 0);  // caller-framed: record count unknown here
  unlock_and_wake(r);
  return kOK;
}

// Push as many whole framed records from buf[0..len) as currently fit,
// waiting up to timeout_ms for space for the FIRST record only. buf holds
// N records in rt_ring_push_raw framing ([u32 len][payload], 8-aligned).
// Returns bytes consumed (0 on timeout — nothing was pushed), or a
// negative RingError. The coalesced-flush path uses this to drain a
// driver-side submit buffer in ONE lock round + at most one consumer
// wake per call, and to push partial prefixes instead of blocking the
// submitting thread when the ring is nearly full.
int64_t rt_ring_push_batch(void* hp, int which, const uint8_t* buf,
                           uint64_t len, int64_t timeout_ms) {
  auto* h = (RingHandle*)hp;
  Ring* r = ring_of(h, which);
  if (len < 4) return 0;
  uint32_t len32;
  memcpy(&len32, buf, 4);
  uint64_t first = align_up(4 + (uint64_t)len32, 8);
  if (first > r->capacity) return kTooBig;
  uint8_t* data = h->base + r->data_off;
  if (lock(&r->mu) != 0) return kSys;
  while (true) {
    if (r->closed) {
      pthread_mutex_unlock(&r->mu);
      return kClosed;
    }
    if (r->capacity - (r->head - r->tail) >= first) break;
    r->stats.producer_waits++;
    int rc = timed_wait(r, timeout_ms);
    if (rc == ETIMEDOUT) {
      pthread_mutex_unlock(&r->mu);
      return 0;
    }
    if (rc != 0) {
      pthread_mutex_unlock(&r->mu);
      return kSys;
    }
  }
  uint64_t avail = r->capacity - (r->head - r->tail);
  if (chaos_strike(&g_chaos_partial_every, &g_chaos_partial_ctr))
    avail = first;  // forced partial: only the head record fits this call
  uint64_t take = 0;
  uint64_t nrecs = 0;
  while (take + 4 <= len) {
    memcpy(&len32, buf + take, 4);
    uint64_t rec = align_up(4 + (uint64_t)len32, 8);
    if (take + rec > len || take + rec > avail) break;
    take += rec;
    nrecs++;
  }
  copy_in(data, r->capacity, r->head, buf, take);
  __atomic_store_n(&r->head, r->head + take, __ATOMIC_RELEASE);
  if (take) note_push(r, take, nrecs);
  if (take < len) r->stats.partial_pushes++;
  unlock_and_wake(r);
  return (int64_t)take;
}

// Pop as many whole records as fit into out[outcap]; blocks until at least
// one record is available (or timeout/closed). Returns total bytes written
// to out (still [u32 len][payload] framed, 8-aligned), 0 on timeout, or a
// negative RingError. kClosed is only returned once the ring is drained.
int64_t rt_ring_pop_batch(void* hp, int which, uint8_t* out, uint64_t outcap,
                          int64_t timeout_ms) {
  auto* h = (RingHandle*)hp;
  Ring* r = ring_of(h, which);
  if (chaos_strike(&g_chaos_timeout_every, &g_chaos_timeout_ctr))
    return 0;  // forced empty-wait timeout: consumer loops back around
  uint8_t* data = h->base + r->data_off;
  bool spun = spin_for([r] {
    return __atomic_load_n(&r->head, __ATOMIC_ACQUIRE) !=
               __atomic_load_n(&r->tail, __ATOMIC_RELAXED) ||
           __atomic_load_n(&r->closed, __ATOMIC_RELAXED);
  });
  if (lock(&r->mu) != 0) return kSys;
  if (spun && r->head != r->tail) r->stats.spin_hits++;
  while (r->head == r->tail) {
    if (r->closed) {
      pthread_mutex_unlock(&r->mu);
      return kClosed;
    }
    r->stats.consumer_waits++;
    int rc = timed_wait(r, timeout_ms);
    if (rc == ETIMEDOUT) {
      pthread_mutex_unlock(&r->mu);
      return 0;
    }
    if (rc != 0) {
      pthread_mutex_unlock(&r->mu);
      return kSys;
    }
  }
  uint64_t written = 0;
  uint64_t nrecs = 0;
  while (r->head != r->tail) {
    uint32_t len32;
    copy_out(data, r->capacity, r->tail, (uint8_t*)&len32, 4);
    uint64_t rec = align_up(4 + (uint64_t)len32, 8);
    if (written + rec > outcap) {
      if (written == 0) {
        // head record alone exceeds the caller's buffer: returning 0
        // would look like a timeout forever — surface a hard error so
        // the caller tears the ring down instead of spinning
        pthread_mutex_unlock(&r->mu);
        return kTooBig;
      }
      break;
    }
    copy_out(data, r->capacity, r->tail, out + written, rec);
    __atomic_store_n(&r->tail, r->tail + rec, __ATOMIC_RELEASE);
    written += rec;
    nrecs++;
  }
  RingStats* st = &r->stats;
  st->pop_ops++;
  st->pop_bytes += written;
  st->pop_records += nrecs;
  unlock_and_wake(r);
  return (int64_t)written;
}

// Copy one direction's stats block into out[0..n): field order matches
// RingStats (push_ops, push_bytes, push_records, pop_ops, pop_bytes,
// pop_records, producer_waits, consumer_waits, wake_signals, spin_hits,
// partial_pushes, peak_used). Returns the number of fields written.
// Takes the ring mutex: the caller is a ~1Hz metrics flush, and a
// locked copy keeps the counters race-free (TSAN matrix) without
// putting any atomics on the push/pop hot path.
int rt_ring_stats(void* hp, int which, uint64_t* out, int n) {
  auto* h = (RingHandle*)hp;
  Ring* r = ring_of(h, which);
  if (lock(&r->mu) != 0) return 0;
  const uint64_t* src = (const uint64_t*)&r->stats;
  int count = n < kRingStatsFields ? n : kRingStatsFields;
  for (int i = 0; i < count; i++) out[i] = src[i];
  pthread_mutex_unlock(&r->mu);
  return count;
}

// Bytes currently queued in one direction (approximate: unlocked read).
uint64_t rt_ring_pending(void* hp, int which) {
  auto* h = (RingHandle*)hp;
  Ring* r = ring_of(h, which);
  return r->head - r->tail;
}

void rt_ring_close(void* hp, int which) {
  auto* h = (RingHandle*)hp;
  Ring* r = ring_of(h, which);
  if (lock(&r->mu) == 0) {
    // atomic store: rt_ring_pop_batch's pre-lock spin and rt_ring_closed
    // read `closed` without the mutex, so the write must be atomic too
    // (mixed plain/atomic access to one location is UB and a TSAN race)
    __atomic_store_n(&r->closed, 1, __ATOMIC_RELEASE);
    pthread_cond_broadcast(&r->cv);
    pthread_mutex_unlock(&r->mu);
  }
}

int rt_ring_closed(void* hp, int which) {
  auto* h = (RingHandle*)hp;
  return (int)__atomic_load_n(&ring_of(h, which)->closed, __ATOMIC_ACQUIRE);
}

void rt_ring_pair_close(void* hp) {
  auto* h = (RingHandle*)hp;
  munmap(h->base, h->total);
  close(h->fd);
  delete h;
}

void rt_ring_pair_destroy(const char* name) { shm_unlink(name); }

// Runtime (re-)arm of the chaos fault counters; 0 disarms. The env path
// (RT_CHAOS_RING_PARTIAL_EVERY / RT_CHAOS_RING_TIMEOUT_EVERY at dlopen)
// serves spawned processes; this serves a library already loaded.
void rt_ring_chaos_set(uint64_t partial_every, uint64_t timeout_every) {
  __atomic_store_n(&g_chaos_partial_every, partial_every, __ATOMIC_RELAXED);
  __atomic_store_n(&g_chaos_timeout_every, timeout_every, __ATOMIC_RELAXED);
  __atomic_store_n(&g_chaos_partial_ctr, 0, __ATOMIC_RELAXED);
  __atomic_store_n(&g_chaos_timeout_ctr, 0, __ATOMIC_RELAXED);
}

}  // extern "C"

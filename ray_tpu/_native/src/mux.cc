// Native RPC server mux: an epoll thread owns the listen socket and every
// client connection; Python sees batched, already-framed messages.
//
// The role of the reference's gRPC server event loops (ref:
// src/ray/rpc/grpc_server.h:88 — N completion-queue threads drain all
// client connections off the Python/handler thread): under fan-in, the
// asyncio transport spends more time resuming per-connection reader
// coroutines and creating per-frame tasks than running handlers. Here:
//
//   - one C++ epoll thread accepts, reads [u64 len][payload] frames from
//     every connection, and appends records to a shared in-queue; an
//     eventfd wakes Python ONCE per burst (level-triggered read side),
//   - Python drains the whole burst in a single callback
//     (rt_mux_recv_batch), dispatching handlers with zero asyncio
//     Stream machinery,
//   - replies (rt_mux_send) try an immediate non-blocking send() on the
//     caller's thread — one syscall, no hop — and spill the remainder to
//     a per-conn out-buffer flushed by the epoll thread on EPOLLOUT.
//
// Record batch format (rt_mux_recv_batch):
//   [u64 conn_id][u32 type][u32 len][payload]*
//   type 0 = frame payload, 1 = connected (len 0), 2 = disconnected (len 0)

#include <arpa/inet.h>
#include <cerrno>
#include <cstdint>
#include <fcntl.h>
#include <cstring>
#include <deque>
#include <mutex>
#include <netinet/in.h>
#include <netinet/tcp.h>
#include <string>
#include <sys/epoll.h>
#include <sys/eventfd.h>
#include <sys/socket.h>
#include <thread>
#include <unistd.h>
#include <unordered_map>

namespace {

constexpr uint64_t kMaxFrame = 1ull << 32;      // 4GB sanity cap
constexpr size_t kMaxOutBuf = 256ull << 20;     // per-conn write backlog cap
constexpr size_t kReadChunk = 256 * 1024;

struct Conn {
  int fd;
  uint64_t id;
  std::string inbuf;        // unparsed read bytes
  std::mutex out_mu;
  std::string outbuf;       // pending write bytes (after partial sends)
  bool want_epollout = false;
  bool dead = false;
};

struct Record {
  uint64_t conn_id;
  uint32_t type;
  std::string payload;
};

struct Mux {
  int listen_fd = -1;
  int epfd = -1;
  int ready_efd = -1;   // signals Python: records available
  int wake_efd = -1;    // wakes the epoll thread (sends, stop)
  uint16_t port = 0;
  std::thread thr;
  std::mutex mu;        // guards conns, inq, next_id, stopping
  std::unordered_map<uint64_t, Conn*> conns;
  std::deque<Record> inq;
  uint64_t next_id = 1;
  bool stopping = false;
};

void push_record(Mux* m, uint64_t id, uint32_t type, std::string payload) {
  bool was_empty;
  {
    std::lock_guard<std::mutex> lk(m->mu);
    was_empty = m->inq.empty();
    m->inq.push_back({id, type, std::move(payload)});
  }
  if (was_empty) {
    uint64_t one = 1;
    ssize_t r = write(m->ready_efd, &one, 8);
    (void)r;
  }
}

void epoll_update(Mux* m, Conn* c) {
  epoll_event ev{};
  ev.events = EPOLLIN | (c->want_epollout ? EPOLLOUT : 0);
  ev.data.u64 = c->id;
  epoll_ctl(m->epfd, EPOLL_CTL_MOD, c->fd, &ev);
}

void drop_conn(Mux* m, Conn* c) {
  if (c->dead) return;
  c->dead = true;
  epoll_ctl(m->epfd, EPOLL_CTL_DEL, c->fd, nullptr);
  // do NOT close(fd) here: rt_mux_send on the Python loop thread may be
  // inside its send() loop on this very fd, and closing would let the
  // kernel reassign the number to a newly accepted connection — a reply
  // meant for this peer would land in another client's stream. shutdown
  // makes every pending/future send fail without freeing the number;
  // the fd closes in rt_mux_release, which Python only calls AFTER the
  // disconnect record was processed on the same thread all sends run on.
  shutdown(c->fd, SHUT_RDWR);
  push_record(m, c->id, 2, "");
  // the Conn object stays in the map (tombstone) until Python calls
  // rt_mux_release — sends to a dead id fail cleanly, never use-after-free
}

// parse complete frames out of c->inbuf
void parse_frames(Mux* m, Conn* c) {
  size_t off = 0;
  while (c->inbuf.size() - off >= 8) {
    uint64_t len;
    memcpy(&len, c->inbuf.data() + off, 8);
    if (len >= kMaxFrame) {  // protocol violation: hang up (>= : a frame
                             // of exactly 2^32 would wrap the u32 batch
                             // header length and desync the drain parser)
      drop_conn(m, c);
      return;
    }
    if (c->inbuf.size() - off - 8 < len) break;
    push_record(m, c->id, 0, c->inbuf.substr(off + 8, len));
    off += 8 + len;
  }
  if (off) c->inbuf.erase(0, off);
}

void handle_readable(Mux* m, Conn* c) {
  char buf[kReadChunk];
  for (;;) {
    ssize_t n = recv(c->fd, buf, sizeof(buf), 0);
    if (n > 0) {
      c->inbuf.append(buf, (size_t)n);
      if ((size_t)n < sizeof(buf)) break;
    } else if (n == 0) {
      parse_frames(m, c);
      drop_conn(m, c);
      return;
    } else {
      if (errno == EAGAIN || errno == EWOULDBLOCK) break;
      if (errno == EINTR) continue;
      drop_conn(m, c);
      return;
    }
  }
  parse_frames(m, c);
}

void handle_writable(Mux* m, Conn* c) {
  std::lock_guard<std::mutex> lk(c->out_mu);
  while (!c->outbuf.empty()) {
    ssize_t n = send(c->fd, c->outbuf.data(), c->outbuf.size(), MSG_NOSIGNAL);
    if (n > 0) {
      c->outbuf.erase(0, (size_t)n);
    } else if (n < 0 && (errno == EAGAIN || errno == EWOULDBLOCK)) {
      break;
    } else if (n < 0 && errno == EINTR) {
      continue;
    } else {
      drop_conn(m, c);
      return;
    }
  }
  if (c->outbuf.empty() && c->want_epollout) {
    c->want_epollout = false;
    epoll_update(m, c);
  }
}

void accept_loop(Mux* m) {
  for (;;) {
    int fd = accept4(m->listen_fd, nullptr, nullptr, SOCK_NONBLOCK);
    if (fd < 0) return;
    int one = 1;
    setsockopt(fd, IPPROTO_TCP, TCP_NODELAY, &one, sizeof(one));
    auto* c = new Conn();
    c->fd = fd;
    {
      std::lock_guard<std::mutex> lk(m->mu);
      c->id = m->next_id++;
      m->conns[c->id] = c;
    }
    epoll_event ev{};
    ev.events = EPOLLIN;
    ev.data.u64 = c->id;
    epoll_ctl(m->epfd, EPOLL_CTL_ADD, fd, &ev);
    push_record(m, c->id, 1, "");
  }
}

void mux_thread(Mux* m) {
  epoll_event evs[128];
  for (;;) {
    int n = epoll_wait(m->epfd, evs, 128, -1);
    if (n < 0) {
      if (errno == EINTR) continue;
      return;
    }
    for (int i = 0; i < n; i++) {
      uint64_t id = evs[i].data.u64;
      if (id == 0) {  // wake_efd: stop or arm-EPOLLOUT requests
        uint64_t junk;
        ssize_t r = read(m->wake_efd, &junk, 8);
        (void)r;
        std::lock_guard<std::mutex> lk(m->mu);
        if (m->stopping) return;
        for (auto& [cid, c] : m->conns) {
          if (c->dead) continue;
          std::lock_guard<std::mutex> ck(c->out_mu);
          if (!c->outbuf.empty() && !c->want_epollout) {
            c->want_epollout = true;
            epoll_update(m, c);
          }
        }
        continue;
      }
      if (id == UINT64_MAX) {  // listen socket
        accept_loop(m);
        continue;
      }
      Conn* c;
      {
        std::lock_guard<std::mutex> lk(m->mu);
        auto it = m->conns.find(id);
        if (it == m->conns.end()) continue;
        c = it->second;
      }
      if (c->dead) continue;
      if (evs[i].events & (EPOLLHUP | EPOLLERR)) {
        handle_readable(m, c);  // drain anything delivered before the hup
        drop_conn(m, c);
        continue;
      }
      if (evs[i].events & EPOLLOUT) handle_writable(m, c);
      if (evs[i].events & EPOLLIN) handle_readable(m, c);
    }
  }
}

}  // namespace

extern "C" {

// returns handle or null; *out_port/*out_efd report the bound port and
// the eventfd Python should add_reader()
void* rt_mux_create(const char* host, uint16_t port, uint16_t* out_port,
                    int* out_efd) {
  auto* m = new Mux();
  m->listen_fd = socket(AF_INET, SOCK_STREAM | SOCK_NONBLOCK, 0);
  if (m->listen_fd < 0) {
    delete m;
    return nullptr;
  }
  int one = 1;
  setsockopt(m->listen_fd, SOL_SOCKET, SO_REUSEADDR, &one, sizeof(one));
  sockaddr_in addr{};
  addr.sin_family = AF_INET;
  addr.sin_port = htons(port);
  // inet_addr does NO hostname resolution: a name like "localhost" yields
  // INADDR_NONE, which as a bind address means 255.255.255.255 — reject
  // it here so the caller falls back (python resolves names first)
  in_addr_t ip = INADDR_ANY;
  if (host && host[0]) {
    ip = inet_addr(host);
    if (ip == INADDR_NONE) {
      close(m->listen_fd);
      delete m;
      return nullptr;
    }
  }
  addr.sin_addr.s_addr = ip;
  if (bind(m->listen_fd, (sockaddr*)&addr, sizeof(addr)) != 0 ||
      listen(m->listen_fd, 512) != 0) {
    close(m->listen_fd);
    delete m;
    return nullptr;
  }
  socklen_t alen = sizeof(addr);
  getsockname(m->listen_fd, (sockaddr*)&addr, &alen);
  m->port = ntohs(addr.sin_port);
  m->epfd = epoll_create1(0);
  m->ready_efd = eventfd(0, EFD_NONBLOCK);
  m->wake_efd = eventfd(0, EFD_NONBLOCK);
  if (m->epfd < 0 || m->ready_efd < 0 || m->wake_efd < 0) {
    // fd exhaustion etc.: fail the create instead of epoll_ctl'ing -1
    // handles and leaving the caller with a mux that can never signal
    if (m->epfd >= 0) close(m->epfd);
    if (m->ready_efd >= 0) close(m->ready_efd);
    if (m->wake_efd >= 0) close(m->wake_efd);
    close(m->listen_fd);
    delete m;
    return nullptr;
  }
  epoll_event ev{};
  ev.events = EPOLLIN;
  ev.data.u64 = UINT64_MAX;  // listen marker
  epoll_ctl(m->epfd, EPOLL_CTL_ADD, m->listen_fd, &ev);
  ev.events = EPOLLIN;
  ev.data.u64 = 0;  // wake marker
  epoll_ctl(m->epfd, EPOLL_CTL_ADD, m->wake_efd, &ev);
  m->thr = std::thread(mux_thread, m);
  *out_port = m->port;
  *out_efd = m->ready_efd;
  return m;
}

// Drain queued records into buf: [u64 conn_id][u32 type][u32 len][payload]*
// Returns bytes packed (0 = nothing); a NEGATIVE value is -(bytes needed)
// when the next record alone exceeds buflen (caller grows and retries).
// Stops before overflowing buf; the eventfd re-signals if records remain.
int64_t rt_mux_recv_batch(void* h, uint8_t* buf, uint64_t buflen) {
  auto* m = (Mux*)h;
  uint64_t junk;
  ssize_t r = read(m->ready_efd, &junk, 8);
  (void)r;
  size_t off = 0;
  bool more = false;
  {
    std::lock_guard<std::mutex> lk(m->mu);
    while (!m->inq.empty()) {
      Record& rec = m->inq.front();
      size_t need = 16 + rec.payload.size();
      if (off == 0 && need > buflen) {
        uint64_t one = 1;
        ssize_t w = write(m->ready_efd, &one, 8);
        (void)w;
        return -(int64_t)need;
      }
      if (off + need > buflen) {
        more = true;
        break;
      }
      memcpy(buf + off, &rec.conn_id, 8);
      memcpy(buf + off + 8, &rec.type, 4);
      uint32_t len = (uint32_t)rec.payload.size();
      memcpy(buf + off + 12, &len, 4);
      memcpy(buf + off + 16, rec.payload.data(), rec.payload.size());
      off += need;
      m->inq.pop_front();
    }
  }
  if (more) {
    uint64_t one = 1;
    ssize_t w = write(m->ready_efd, &one, 8);
    (void)w;
  }
  return (int64_t)off;
}

// Send a pre-framed message ([u64 len][payload] ALREADY included by the
// caller). Immediate non-blocking send when the out-buffer is empty; the
// rest spills to the buffer and the epoll thread finishes it.
int rt_mux_send(void* h, uint64_t conn_id, const char* data, uint64_t len) {
  auto* m = (Mux*)h;
  Conn* c;
  {
    std::lock_guard<std::mutex> lk(m->mu);
    auto it = m->conns.find(conn_id);
    if (it == m->conns.end()) return -1;
    c = it->second;
  }
  if (c->dead) return -1;
  bool need_wake = false;
  {
    std::lock_guard<std::mutex> ck(c->out_mu);
    if (c->outbuf.empty()) {
      uint64_t sent = 0;
      while (sent < len) {
        ssize_t n = send(c->fd, data + sent, len - sent, MSG_NOSIGNAL);
        if (n > 0) {
          sent += (uint64_t)n;
        } else if (n < 0 && (errno == EAGAIN || errno == EWOULDBLOCK)) {
          break;
        } else if (n < 0 && errno == EINTR) {
          continue;
        } else {
          return -1;  // epoll thread will observe the error and drop
        }
      }
      if (sent < len) {
        c->outbuf.assign(data + sent, len - sent);
        need_wake = true;
      }
    } else {
      if (c->outbuf.size() + len > kMaxOutBuf) return -2;  // backlogged
      c->outbuf.append(data, len);
      need_wake = !c->want_epollout;
    }
  }
  if (need_wake) {
    uint64_t one = 1;
    ssize_t w = write(m->wake_efd, &one, 8);
    (void)w;
  }
  return 0;
}

void rt_mux_close_conn(void* h, uint64_t conn_id) {
  auto* m = (Mux*)h;
  std::lock_guard<std::mutex> lk(m->mu);
  auto it = m->conns.find(conn_id);
  if (it != m->conns.end() && !it->second->dead) {
    // shutdown wakes the epoll thread with EPOLLHUP; it runs drop_conn
    shutdown(it->second->fd, SHUT_RDWR);
  }
}

// Python saw the disconnect record and dropped its wrapper: free the slot
void rt_mux_release(void* h, uint64_t conn_id) {
  auto* m = (Mux*)h;
  Conn* c = nullptr;
  {
    std::lock_guard<std::mutex> lk(m->mu);
    auto it = m->conns.find(conn_id);
    if (it == m->conns.end() || !it->second->dead) return;
    c = it->second;
    m->conns.erase(it);
  }
  close(c->fd);  // deferred from drop_conn (see fd-reuse note there)
  delete c;
}

uint16_t rt_mux_port(void* h) { return ((Mux*)h)->port; }

void rt_mux_stop(void* h) {
  auto* m = (Mux*)h;
  {
    std::lock_guard<std::mutex> lk(m->mu);
    m->stopping = true;
  }
  uint64_t one = 1;
  ssize_t w = write(m->wake_efd, &one, 8);
  (void)w;
  m->thr.join();
  close(m->listen_fd);
  {
    std::lock_guard<std::mutex> lk(m->mu);
    for (auto& [id, c] : m->conns) {
      close(c->fd);  // dead conns kept their fd open for the send race
      delete c;
    }
    m->conns.clear();
  }
  close(m->epfd);
  close(m->ready_efd);
  close(m->wake_efd);
  delete m;
}

}  // extern "C"

// Native GCS state engine: namespaced KV tables + write-ahead log +
// atomic snapshots, shared by the Python GCS server via ctypes.
//
// The role of the reference's GCS storage layer (ref:
// src/ray/gcs/gcs_server/store_client/redis_store_client.cc — there every
// table op journals through Redis; src/ray/gcs/gcs_server/gcs_table_storage.h
// per-table storage): here a single-process C++ engine the GCS process
// links in. Python keeps the *policy* (actor scheduling, health, pubsub
// fanout); the *state* — every KV byte, every journal append, every
// snapshot/recovery — lives native, with the GIL released for the
// entire operation.
//
// Durability model (identical semantics to the round-4 Python WAL, now
// binary + CRC):
//   - WAL record:  [u32 len][u32 crc32(payload)][payload]
//     payload:     [u8 type] type 1 = kv_put  [u16 nsl][ns][u32 kl][k][u32 vl][v]
//                            type 2 = kv_del  [u16 nsl][ns][u32 kl][k]
//                            type 3 = aux     [opaque bytes] (Python table op)
//   - replay stops at the first short/corrupt record (torn tail from a
//     kill mid-append) and truncates it away; every complete record is
//     applied. CRC catches partial page writes, not just short tails.
//   - snapshot: "RTGCS1\n" [u64 auxlen][aux blob] then
//     ([u16 nsl][ns][u32 kl][k][u32 vl][v])* — written tmp + rename
//     (atomic), after which the WAL truncates. The aux blob is Python's
//     pickled table state; opaque here.

#include <cstdint>
#include <cstdio>
#include <cstring>
#include <map>
#include <mutex>
#include <string>
#include <unordered_map>
#include <vector>

#include <fcntl.h>
#include <sys/stat.h>
#include <unistd.h>

namespace {

// ---- crc32 (same polynomial as zlib; tiny table-driven impl) ----------
uint32_t crc_table[256];
struct CrcInit {
  CrcInit() {
    for (uint32_t i = 0; i < 256; i++) {
      uint32_t c = i;
      for (int k = 0; k < 8; k++) c = (c & 1) ? 0xEDB88320u ^ (c >> 1) : c >> 1;
      crc_table[i] = c;
    }
  }
} crc_init;

uint32_t crc32(const uint8_t* p, size_t n) {
  uint32_t c = 0xFFFFFFFFu;
  for (size_t i = 0; i < n; i++) c = crc_table[(c ^ p[i]) & 0xFF] ^ (c >> 8);
  return c ^ 0xFFFFFFFFu;
}

struct GcsStore {
  std::mutex mu;
  // ns -> ordered key map (ordered: prefix scans stream in sorted order)
  std::unordered_map<std::string, std::map<std::string, std::string>> kv;
  std::string path;        // snapshot path ("" = volatile, no WAL)
  std::string wal_path;    // path + ".wal"
  FILE* wal = nullptr;     // append handle, lazily opened
  bool wal_broken = false; // unrecoverable write failure: snapshots only
  // aux records recovered from the WAL at open() — Python table ops to
  // replay on top of the snapshot's aux blob
  std::vector<std::string> recovered_aux;
  std::string snapshot_aux;  // aux blob from the snapshot file
  bool had_snapshot = false;
  uint64_t wal_records = 0;  // records applied during open()'s replay
  // opt-in machine-crash durability (rt_gcs_set_fsync): appends mark the
  // WAL dirty and rt_gcs_wal_sync group-commits them with one fdatasync;
  // snapshots fsync the tmp file before the rename and the directory
  // after it. Off (default) = fflush-only: survives a process kill (the
  // bytes are in the OS page cache) but not a machine crash.
  bool do_fsync = false;
  bool wal_dirty = false;  // appended since the last fdatasync
  // a record was dropped by the append-failure rewind: the in-memory
  // table is ahead of the WAL, so durability is broken until the next
  // snapshot captures the table (wal_sync reports -1 meanwhile)
  bool wal_lost = false;
};

void put_u16(std::string& out, uint16_t v) { out.append((const char*)&v, 2); }
void put_u32(std::string& out, uint32_t v) { out.append((const char*)&v, 4); }
void put_u64(std::string& out, uint64_t v) { out.append((const char*)&v, 8); }

bool rd(const uint8_t*& p, const uint8_t* end, void* out, size_t n) {
  if (p + n > end) return false;
  memcpy(out, p, n);
  p += n;
  return true;
}

// encode one WAL payload for a kv put/del
std::string enc_kv(uint8_t type, const std::string& ns, const std::string& k,
                   const std::string* v) {
  std::string p;
  p.push_back((char)type);
  put_u16(p, (uint16_t)ns.size());
  p += ns;
  put_u32(p, (uint32_t)k.size());
  p += k;
  if (v) {
    put_u32(p, (uint32_t)v->size());
    p += *v;
  }
  return p;
}

// append one record to the WAL; on write failure rewind to the record
// boundary (a partial record would poison every later append)
void wal_append(GcsStore* s, const std::string& payload) {
  if (s->path.empty() || s->wal_broken) return;
  if (!s->wal) {
    s->wal = fopen(s->wal_path.c_str(), "ab");
    if (!s->wal) { s->wal_broken = true; return; }
  }
  long pos = ftell(s->wal);
  uint32_t len = (uint32_t)payload.size();
  uint32_t crc = crc32((const uint8_t*)payload.data(), payload.size());
  if (fwrite(&len, 4, 1, s->wal) != 1 ||
      fwrite(&crc, 4, 1, s->wal) != 1 ||
      fwrite(payload.data(), 1, payload.size(), s->wal) != payload.size() ||
      fflush(s->wal) != 0) {
    if (pos >= 0 && ftruncate(fileno(s->wal), pos) == 0) {
      fseek(s->wal, pos, SEEK_SET);
      s->wal_lost = true;  // record dropped: not durable until snapshot
    } else {
      fclose(s->wal);
      s->wal = nullptr;
      s->wal_broken = true;
    }
    return;
  }
  s->wal_dirty = true;  // group commit: rt_gcs_wal_sync makes it durable
}

bool load_snapshot(GcsStore* s) {
  FILE* f = fopen(s->path.c_str(), "rb");
  if (!f) return false;
  char magic[7];
  if (fread(magic, 1, 7, f) != 7 || memcmp(magic, "RTGCS1\n", 7) != 0) {
    fclose(f);
    return false;
  }
  uint64_t auxlen = 0;
  if (fread(&auxlen, 8, 1, f) != 1) { fclose(f); return false; }
  s->snapshot_aux.resize(auxlen);
  if (auxlen && fread(&s->snapshot_aux[0], 1, auxlen, f) != auxlen) {
    fclose(f);
    s->snapshot_aux.clear();
    return false;
  }
  for (;;) {
    uint16_t nsl;
    if (fread(&nsl, 2, 1, f) != 1) break;  // clean EOF
    std::string ns(nsl, 0);
    uint32_t kl, vl;
    if ((nsl && fread(&ns[0], 1, nsl, f) != nsl) ||
        fread(&kl, 4, 1, f) != 1) break;
    std::string k(kl, 0);
    if ((kl && fread(&k[0], 1, kl, f) != kl) || fread(&vl, 4, 1, f) != 1)
      break;
    std::string v(vl, 0);
    if (vl && fread(&v[0], 1, vl, f) != vl) break;
    s->kv[ns][std::move(k)] = std::move(v);
  }
  fclose(f);
  s->had_snapshot = true;
  return true;
}

void replay_wal(GcsStore* s) {
  FILE* f = fopen(s->wal_path.c_str(), "rb");
  if (!f) return;
  fseek(f, 0, SEEK_END);
  long size = ftell(f);
  fseek(f, 0, SEEK_SET);
  std::string buf(size > 0 ? (size_t)size : 0, 0);
  if (size > 0 && fread(&buf[0], 1, (size_t)size, f) != (size_t)size) {
    fclose(f);
    return;
  }
  fclose(f);
  const uint8_t* p = (const uint8_t*)buf.data();
  const uint8_t* end = p + buf.size();
  long good = 0;
  while (p + 8 <= end) {
    uint32_t len, crc;
    const uint8_t* rec_start = p;
    memcpy(&len, p, 4);
    memcpy(&crc, p + 4, 4);
    p += 8;
    if (p + len > end) { p = rec_start; break; }          // torn tail
    if (crc32(p, len) != crc) { p = rec_start; break; }   // corrupt record
    const uint8_t* q = p;
    const uint8_t* qend = p + len;
    p = qend;
    good = (long)(p - (const uint8_t*)buf.data());
    s->wal_records++;
    uint8_t type;
    if (!rd(q, qend, &type, 1)) continue;
    if (type == 3) {  // opaque Python table op
      s->recovered_aux.emplace_back((const char*)q, (size_t)(qend - q));
      continue;
    }
    uint16_t nsl;
    if (!rd(q, qend, &nsl, 2)) continue;
    std::string ns((const char*)q, 0);
    if (q + nsl > qend) continue;
    ns.assign((const char*)q, nsl);
    q += nsl;
    uint32_t kl;
    if (!rd(q, qend, &kl, 4) || q + kl > qend) continue;
    std::string k((const char*)q, kl);
    q += kl;
    if (type == 1) {
      uint32_t vl;
      if (!rd(q, qend, &vl, 4) || q + vl > qend) continue;
      s->kv[ns][std::move(k)].assign((const char*)q, vl);
    } else if (type == 2) {
      auto it = s->kv.find(ns);
      if (it != s->kv.end()) it->second.erase(k);
    }
  }
  // truncate any torn/corrupt tail so later appends start at a clean
  // record boundary. If NOTHING parsed, the file is either a previous
  // (pickle-framed) format or has a torn first record: sideline it as
  // .legacy — appends then start on a fresh file (never after garbage),
  // and the caller's migration path can inspect the sidelined bytes.
  if (good < size && good > 0) {
    if (truncate(s->wal_path.c_str(), good) != 0) { /* best effort */ }
  } else if (good == 0 && size > 0) {
    std::string legacy = s->wal_path + ".legacy";
    rename(s->wal_path.c_str(), legacy.c_str());
  }
}

// copy-out helper: -1 missing, -9 buffer too small (needed in *out_len),
// 0 copied
int copy_out(const std::string& v, uint8_t* buf, uint64_t buflen,
             uint64_t* out_len) {
  *out_len = v.size();
  if (v.size() > buflen) return -9;
  if (!v.empty()) memcpy(buf, v.data(), v.size());
  return 0;
}

}  // namespace

extern "C" {

void* rt_gcs_open(const char* path) {
  auto* s = new GcsStore();
  if (path && path[0]) {
    s->path = path;
    s->wal_path = s->path + ".wal";
    load_snapshot(s);
    replay_wal(s);
  }
  return s;
}

void rt_gcs_close(void* h) {
  auto* s = (GcsStore*)h;
  if (!s) return;
  std::unique_lock<std::mutex> lk(s->mu);
  if (s->wal) fclose(s->wal);
  lk.unlock();
  delete s;
}

int rt_gcs_had_snapshot(void* h) {
  auto* s = (GcsStore*)h;
  return s->had_snapshot ? 1 : 0;
}

uint64_t rt_gcs_wal_records(void* h) {
  return ((GcsStore*)h)->wal_records;
}

// returns 1 stored, 0 exists-and-overwrite-false
int rt_gcs_kv_put(void* h, const char* ns, uint64_t nsl, const char* key,
                  uint64_t kl, const char* val, uint64_t vl, int overwrite,
                  int journal) {
  auto* s = (GcsStore*)h;
  std::string nss(ns, nsl), k(key, kl), v(val, vl);
  std::lock_guard<std::mutex> lk(s->mu);
  auto& table = s->kv[nss];
  auto it = table.find(k);
  if (it != table.end() && !overwrite) return 0;
  if (journal) wal_append(s, enc_kv(1, nss, k, &v));
  if (it != table.end())
    it->second = std::move(v);
  else
    table.emplace(std::move(k), std::move(v));
  return 1;
}

int rt_gcs_kv_get(void* h, const char* ns, uint64_t nsl, const char* key,
                  uint64_t kl, uint8_t* buf, uint64_t buflen,
                  uint64_t* out_len) {
  auto* s = (GcsStore*)h;
  std::lock_guard<std::mutex> lk(s->mu);
  auto nit = s->kv.find(std::string(ns, nsl));
  if (nit == s->kv.end()) return -1;
  auto it = nit->second.find(std::string(key, kl));
  if (it == nit->second.end()) return -1;
  return copy_out(it->second, buf, buflen, out_len);
}

int rt_gcs_kv_del(void* h, const char* ns, uint64_t nsl, const char* key,
                  uint64_t kl, int journal) {
  auto* s = (GcsStore*)h;
  std::string nss(ns, nsl), k(key, kl);
  std::lock_guard<std::mutex> lk(s->mu);
  if (journal) wal_append(s, enc_kv(2, nss, k, nullptr));
  auto nit = s->kv.find(nss);
  if (nit == s->kv.end()) return 0;
  return nit->second.erase(k) ? 1 : 0;
}

int rt_gcs_kv_exists(void* h, const char* ns, uint64_t nsl, const char* key,
                     uint64_t kl) {
  auto* s = (GcsStore*)h;
  std::lock_guard<std::mutex> lk(s->mu);
  auto nit = s->kv.find(std::string(ns, nsl));
  return nit != s->kv.end() && nit->second.count(std::string(key, kl)) ? 1 : 0;
}

// packs matching keys as ([u32 len][key])*; -9 + needed size if short
int rt_gcs_kv_keys(void* h, const char* ns, uint64_t nsl, const char* prefix,
                   uint64_t pl, uint8_t* buf, uint64_t buflen,
                   uint64_t* out_len) {
  auto* s = (GcsStore*)h;
  std::string pre(prefix, pl);
  std::lock_guard<std::mutex> lk(s->mu);
  auto nit = s->kv.find(std::string(ns, nsl));
  std::string packed;
  if (nit != s->kv.end()) {
    // ordered map: seek to the prefix and stream until it stops matching
    for (auto it = nit->second.lower_bound(pre); it != nit->second.end();
         ++it) {
      if (it->first.compare(0, pre.size(), pre) != 0) break;
      put_u32(packed, (uint32_t)it->first.size());
      packed += it->first;
    }
  }
  return copy_out(packed, buf, buflen, out_len);
}

uint64_t rt_gcs_kv_count(void* h, const char* ns, uint64_t nsl) {
  auto* s = (GcsStore*)h;
  std::lock_guard<std::mutex> lk(s->mu);
  auto nit = s->kv.find(std::string(ns, nsl));
  return nit == s->kv.end() ? 0 : nit->second.size();
}

// journal an opaque Python table op (type-3 aux record)
void rt_gcs_journal_aux(void* h, const char* payload, uint64_t len) {
  auto* s = (GcsStore*)h;
  std::lock_guard<std::mutex> lk(s->mu);
  std::string p;
  p.push_back((char)3);
  p.append(payload, len);
  wal_append(s, p);
}

int rt_gcs_wal_ok(void* h) {
  auto* s = (GcsStore*)h;
  std::lock_guard<std::mutex> lk(s->mu);
  return (!s->path.empty() && !s->wal_broken) ? 1 : 0;
}

// ---- opt-in durability (group-committed fdatasync) ---------------------
void rt_gcs_set_fsync(void* h, int on) {
  auto* s = (GcsStore*)h;
  std::lock_guard<std::mutex> lk(s->mu);
  s->do_fsync = on != 0;
}

// fdatasync the WAL iff records were appended since the last sync. The
// caller (Python group-commit barrier) batches: N writes acked in one
// event-loop tick share ONE disk sync. Returns 0 synced/clean, -1 error —
// including a broken WAL or a record dropped by the append-failure
// rewind: writes that never reached the WAL must surface as not-durable,
// not be silently acked (the next snapshot repairs/truncates the WAL and
// restores the guarantee). The fdatasync runs on a dup'd fd OUTSIDE the
// store mutex: a multi-millisecond disk sync under s->mu would block
// every concurrent kv operation (and the GCS event loop behind them).
int rt_gcs_wal_sync(void* h) {
  auto* s = (GcsStore*)h;
  int fd = -1;
  {
    std::lock_guard<std::mutex> lk(s->mu);
    if (s->wal_broken || s->wal_lost) return -1;
    if (!s->wal_dirty || !s->wal) return 0;
    fd = dup(fileno(s->wal));  // survives a concurrent snapshot's fclose
    if (fd < 0) return -1;
    s->wal_dirty = false;
  }
  int rc = fdatasync(fd);
  close(fd);
  if (rc != 0) {
    std::lock_guard<std::mutex> lk(s->mu);
    s->wal_dirty = true;  // restore: the records are still unsynced
    return -1;
  }
  return 0;
}

// ---- recovery accessors ----------------------------------------------
int rt_gcs_snapshot_aux(void* h, uint8_t* buf, uint64_t buflen,
                        uint64_t* out_len) {
  auto* s = (GcsStore*)h;
  std::lock_guard<std::mutex> lk(s->mu);
  return copy_out(s->snapshot_aux, buf, buflen, out_len);
}

uint64_t rt_gcs_aux_count(void* h) {
  auto* s = (GcsStore*)h;
  std::lock_guard<std::mutex> lk(s->mu);
  return s->recovered_aux.size();
}

int rt_gcs_aux_get(void* h, uint64_t i, uint8_t* buf, uint64_t buflen,
                   uint64_t* out_len) {
  auto* s = (GcsStore*)h;
  std::lock_guard<std::mutex> lk(s->mu);
  if (i >= s->recovered_aux.size()) return -1;
  return copy_out(s->recovered_aux[i], buf, buflen, out_len);
}

// ---- snapshot ---------------------------------------------------------
// Writes tmp + rename (atomic), truncates the WAL, drops recovered aux.
// skip_ns: one namespace to leave out (volatile metrics), "" for none.
int rt_gcs_snapshot(void* h, const char* aux, uint64_t auxlen,
                    const char* skip_ns) {
  auto* s = (GcsStore*)h;
  std::lock_guard<std::mutex> lk(s->mu);
  if (s->path.empty()) return -1;
  std::string tmp = s->path + ".tmp" + std::to_string(getpid());
  FILE* f = fopen(tmp.c_str(), "wb");
  if (!f) return -2;
  std::string skip = skip_ns ? skip_ns : "";
  bool ok = fwrite("RTGCS1\n", 1, 7, f) == 7 &&
            fwrite(&auxlen, 8, 1, f) == 1 &&
            (auxlen == 0 || fwrite(aux, 1, auxlen, f) == auxlen);
  for (auto& [ns, table] : s->kv) {
    if (!ok) break;
    if (!skip.empty() && ns == skip) continue;
    for (auto& [k, v] : table) {
      uint16_t nsl = (uint16_t)ns.size();
      uint32_t kl = (uint32_t)k.size(), vl = (uint32_t)v.size();
      ok = fwrite(&nsl, 2, 1, f) == 1 &&
           (nsl == 0 || fwrite(ns.data(), 1, nsl, f) == nsl) &&
           fwrite(&kl, 4, 1, f) == 1 &&
           (kl == 0 || fwrite(k.data(), 1, kl, f) == kl) &&
           fwrite(&vl, 4, 1, f) == 1 &&
           (vl == 0 || fwrite(v.data(), 1, vl, f) == vl);
      if (!ok) break;
    }
  }
  ok = (fflush(f) == 0) && ok;
  // machine-crash safety (opt-in): the tmp file's bytes must be on disk
  // BEFORE the rename makes it the live snapshot, or a crash could leave
  // a correctly-named file with garbage contents
  if (ok && s->do_fsync && fsync(fileno(f)) != 0) ok = false;
  fclose(f);
  if (!ok) {
    remove(tmp.c_str());
    return -3;
  }
  if (rename(tmp.c_str(), s->path.c_str()) != 0) {
    remove(tmp.c_str());
    return -4;
  }
  if (s->do_fsync) {
    // persist the rename itself: fsync the containing directory
    size_t slash = s->path.find_last_of('/');
    std::string dir = slash == std::string::npos ? "." : s->path.substr(0, slash);
    if (dir.empty()) dir = "/";
    int dfd = open(dir.c_str(), O_RDONLY | O_DIRECTORY);
    if (dfd >= 0) {
      fsync(dfd);  // best effort: the data fsync above is the hard gate
      close(dfd);
    }
  }
  // state up to now is in the snapshot: the journal restarts empty
  if (s->wal) {
    fclose(s->wal);
    s->wal = nullptr;
  }
  remove(s->wal_path.c_str());
  s->wal_broken = false;
  s->wal_dirty = false;
  s->wal_lost = false;  // table state is in the snapshot: durable again
  s->recovered_aux.clear();
  s->snapshot_aux.assign(aux, auxlen);
  s->had_snapshot = true;
  return 0;
}

}  // extern "C"

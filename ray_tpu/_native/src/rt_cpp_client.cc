// rt_cpp_client.cc — C++ driver implementation (see rt_cpp_client.h).
//
// Protocol: GCS get_cluster -> raylet lease_worker(language=cpp) ->
// worker push_task -> inline result; lease cached across Call()s and
// returned on Close() (ref: normal_task_submitter lease caching).

#include "rt_cpp_client.h"

#include <fcntl.h>
#include <unistd.h>

#include <cstring>
#include <random>

#include "rt_wire.h"

namespace rt {

using picklite::Value;
using wire::dial;
using wire::pack_value;
using wire::read_frame;
using wire::unpack_value;
using wire::write_frame;

namespace {

ValuePtr envelope(const char* kind, int64_t corr_id) {
  auto msg = Value::dict_();
  msg->set("k", Value::str(kind));
  msg->set("i", Value::integer(corr_id));
  return msg;
}

std::string random_bytes(size_t n) {
  std::string out(n, 0);
  static std::mt19937_64 rng{std::random_device{}()};
  for (size_t i = 0; i < n; ++i) out[i] = (char)(rng() & 0xff);
  return out;
}

}  // namespace

ValuePtr Client::Rpc(int fd, const std::string& method, ValuePtr payload,
                     std::string* error) {
  int64_t corr_id = next_id_++;
  auto msg = envelope("c", corr_id);
  msg->set("m", Value::str(method));
  msg->set("p", payload ? payload : Value::none());
  if (!write_frame(fd, picklite::dumps(*msg))) {
    if (error) *error = "send failed (" + method + ")";
    return nullptr;
  }
  // synchronous client: replies come back in order on this connection
  std::string frame;
  while (read_frame(fd, &frame)) {
    ValuePtr reply;
    try {
      reply = picklite::loads(frame);
    } catch (const std::exception& e) {
      if (error) *error = std::string("undecodable reply: ") + e.what();
      return nullptr;
    }
    auto kind = reply->get("k");
    if (!kind || kind->s != "r") continue;  // skip pushes/notifications
    auto i = reply->get("i");
    if (!i || i->i != corr_id) continue;    // not ours (stale)
    auto err = reply->get("e");
    if (err && err->kind != Value::kNone) {
      if (error) {
        *error = err->mod + "." + err->name;
        if (!err->items.empty() && err->items[0]->kind == Value::kStr)
          *error += ": " + err->items[0]->s;
      }
      return nullptr;
    }
    auto v = reply->get("v");
    return v ? v : Value::none();
  }
  if (error) *error = "connection lost (" + method + ")";
  return nullptr;
}

bool Client::Connect(const std::string& gcs_host, int gcs_port) {
  int gcs_fd = dial(gcs_host, gcs_port);
  if (gcs_fd < 0) return false;
  std::string err;
  auto cluster = Rpc(gcs_fd, "get_cluster", Value::dict_(), &err);
  ::close(gcs_fd);
  if (!cluster || cluster->kind != Value::kList || cluster->items.empty())
    return false;
  // the GCS view lists alive nodes, but liveness can lag reality (health
  // reaping interval): try each raylet in turn instead of failing on a
  // stale first entry
  for (auto& node : cluster->items) {
    auto alive = node->get("alive");
    if (alive && !alive->truthy()) continue;
    auto addr = node->get("address");
    if (!addr || addr->items.size() != 2) continue;
    raylet_fd_ = dial(addr->items[0]->s, (int)addr->items[1]->i);
    if (raylet_fd_ >= 0) return true;
  }
  return false;
}

bool Client::EnsureWorker(std::string* error) {
  if (worker_fd_ >= 0) return true;
  auto p = Value::dict_();
  auto res = Value::dict_();
  res->set("CPU", Value::real(1.0));
  p->set("resources", res);
  p->set("pg_id", Value::none());
  p->set("bundle_index", Value::integer(-1));
  p->set("language", Value::str("cpp"));
  // bind the lease to this (persistent) raylet connection: a crashed C++
  // driver must not leak its worker + resources (ref: lease disposal on
  // owner death)
  p->set("owner_bound", Value::boolean(true));
  auto grant = Rpc(raylet_fd_, "lease_worker", p, error);
  if (!grant) return false;
  auto granted = grant->get("granted");
  if (!granted || !granted->truthy()) {
    if (error) *error = "lease not granted (spillback not supported in C++ client)";
    return false;
  }
  auto waddr = grant->get("worker_address");
  auto lid = grant->get("lease_id");
  if (!waddr || waddr->items.size() != 2) {
    if (error) *error = "bad lease reply";
    return false;
  }
  lease_id_ = lid ? lid->i : -1;
  worker_fd_ = dial(waddr->items[0]->s, (int)waddr->items[1]->i);
  if (worker_fd_ < 0) {
    if (error) *error = "cannot reach leased worker";
    return false;
  }
  return true;
}

ValuePtr Client::Call(const std::string& func_name, std::vector<ValuePtr> args,
                      std::string* error) {
  if (raylet_fd_ < 0) {
    if (error) *error = "not connected";
    return nullptr;
  }
  if (!EnsureWorker(error)) return nullptr;

  auto spec = Value::dict_();
  auto tid = Value::opaque("ray_tpu.utils.ids", "TaskID");
  tid->items.push_back(Value::bytes(random_bytes(16)));
  spec->set("task_id", tid);
  spec->set("name", Value::str(func_name));
  spec->set("func_name", Value::str(func_name));
  spec->set("func_id", Value::bytes("cpp:" + func_name));
  spec->set("language", Value::str("cpp"));
  auto arglist = Value::list();
  for (auto& a : args) {
    auto desc = Value::tuple();
    desc->items.push_back(Value::str("v"));
    desc->items.push_back(Value::bytes(pack_value(*a)));
    arglist->items.push_back(desc);
  }
  spec->set("args", arglist);
  spec->set("kwargs", Value::dict_());
  spec->set("num_returns", Value::integer(1));
  spec->set("owner_address", Value::none());
  spec->set("max_retries", Value::integer(0));
  spec->set("runtime_env", Value::none());

  auto payload = Value::dict_();
  payload->set("spec", spec);
  auto reply = Rpc(worker_fd_, "push_task", payload, error);
  if (!reply) {  // worker died mid-call: drop the lease, caller may retry
    ::close(worker_fd_);
    worker_fd_ = -1;
    lease_id_ = -1;
    return nullptr;
  }
  auto task_err = reply->get("error");
  if (task_err && task_err->kind != Value::kNone) {
    if (error) {
      *error = task_err->mod + "." + task_err->name;
      if (!task_err->items.empty() && task_err->items[0]->kind == Value::kStr)
        *error += ": " + task_err->items[0]->s;
    }
    return nullptr;
  }
  auto results = reply->get("results");
  if (!results || results->items.empty()) return Value::none();
  auto inline_b = results->items[0]->get("inline");
  if (!inline_b) {
    if (error) *error = "non-inline result (too large for the C++ client)";
    return nullptr;
  }
  try {
    return unpack_value(inline_b->s);
  } catch (const std::exception& e) {
    if (error) *error = std::string("result decode: ") + e.what();
    return nullptr;
  }
}

void Client::Close() {
  if (raylet_fd_ >= 0 && lease_id_ >= 0) {
    auto p = Value::dict_();
    p->set("lease_id", Value::integer(lease_id_));
    std::string err;
    Rpc(raylet_fd_, "return_lease", p, &err);
    lease_id_ = -1;
  }
  if (worker_fd_ >= 0) { ::close(worker_fd_); worker_fd_ = -1; }
  if (raylet_fd_ >= 0) { ::close(raylet_fd_); raylet_fd_ = -1; }
}

}  // namespace rt

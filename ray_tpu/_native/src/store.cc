// Shared-memory object store: the TPU-native plasma equivalent.
//
// Re-designed from the behavior of the reference's per-node object store
// (ref: src/ray/object_manager/plasma/store.h:55, object_store.h:76,
// eviction_policy.h, dlmalloc.cc) — create/seal/get/release/delete with
// blocking gets, LRU eviction of unreferenced sealed objects, and a
// boundary-tag first-fit allocator inside one mmap'd POSIX shm arena.
// Unlike plasma there is no client socket protocol: every process on the
// node maps the arena directly and synchronizes through process-shared
// robust mutexes — one less hop, which matters because on a TPU host the
// store's job is feeding host->device transfers at HBM-ingest rate.
//
// Also hosts mutable channel objects: the equivalent of the reference's
// experimental mutable-object protocol for compiled graphs
// (ref: src/ray/core_worker/experimental_mutable_object_manager.h:44,
// WriteAcquire/ReadAcquire at :156/:181) — a versioned single-writer,
// N-reader ring cell with process-shared condvars.

#include <cerrno>
#include <cstdint>
#if defined(__x86_64__) || defined(_M_X64)
#include <emmintrin.h>  // SSE2 streaming stores (rt_copy_nt)
#endif
#include <cstdlib>
#include <cstring>
#include <ctime>

#include <fcntl.h>
#include <pthread.h>
#include <sys/mman.h>
#include <sys/stat.h>
#include <unistd.h>

namespace {

constexpr uint64_t kMagic = 0x52545354'4f524532ull;  // "RTSTORE2"
constexpr int kIdSize = 20;
constexpr uint64_t kAlign = 64;

enum EntryState : uint32_t {
  kFree = 0,
  kCreated = 1,
  kSealed = 2,
  kChannel = 3,
  kEvicted = 4,  // tombstone: data freed by LRU; id remembered so a later
                 // get() fails fast (ObjectLostError / lineage reconstruction)
                 // instead of blocking forever
};

enum Error : int {
  kOK = 0,
  kNotFound = -1,
  kExists = -2,
  kOutOfMemory = -3,
  kTimeout = -4,
  kBadState = -5,
  kSysError = -6,
  kClosed = -7,
  kLost = -8,  // object was evicted after having been sealed
};

// Chaos fault arm (devtools/chaos): every Nth rt_seal reports kSysError
// while leaving the entry kCreated, so a retry can succeed — the forced
// version of a shm-layer seal failure. Armed via RT_CHAOS_STORE_SEAL_
// FAIL_EVERY at dlopen or rt_store_chaos_set at runtime; disarmed cost
// is one relaxed load of a zero. Atomics: no new TSAN race.
uint64_t env_every(const char* name) {
  const char* raw = getenv(name);
  if (!raw) return 0;
  char* end = nullptr;
  unsigned long long v = strtoull(raw, &end, 10);
  return (end && *end == '\0') ? (uint64_t)v : 0;
}

uint64_t g_chaos_seal_every = env_every("RT_CHAOS_STORE_SEAL_FAIL_EVERY");
uint64_t g_chaos_seal_ctr = 0;

bool chaos_seal_strike() {
  uint64_t every = __atomic_load_n(&g_chaos_seal_every, __ATOMIC_RELAXED);
  if (every == 0) return false;
  return __atomic_add_fetch(&g_chaos_seal_ctr, 1, __ATOMIC_RELAXED) % every == 0;
}

struct Entry {
  uint8_t id[kIdSize];
  uint32_t state;
  uint64_t offset;  // data offset from arena base
  uint64_t size;    // user-visible size
  int32_t refcnt;
  uint32_t pad;
  uint64_t lru_seq;
};

struct Block {  // boundary-tag allocator block header, padded so the user
                // data that follows it stays 64-byte aligned (DMA/vector
                // loads; serialization.py promises this alignment)
  uint64_t size;  // total block size incl. header+footer
  uint64_t free;  // 1 = free
  uint8_t pad[kAlign - 2 * sizeof(uint64_t)];
};
static_assert(sizeof(Block) == kAlign, "data after Block must stay aligned");
// footer: uint64_t size at block end - 8

struct ChannelHeader {  // lives at the start of a channel's data block
  pthread_mutex_t mu;
  pthread_cond_t cv;
  uint64_t version;        // incremented by each WriteRelease
  uint64_t payload_size;   // bytes written for the current version
  uint32_t num_readers;    // readers per version
  int32_t readers_left;    // acks outstanding for current version
  uint32_t closed;
  uint32_t pad;
};

// Arena-wide counters in the shared header, updated under the store
// mutex (plain adds) and read out via rt_store_stats for the metrics
// flush — the metric_defs.cc objects-family role for the local arena.
struct StoreStats {
  uint64_t creates;        // successful object creations
  uint64_t create_bytes;
  uint64_t seals;
  uint64_t gets;           // successful (sealed) reads
  uint64_t get_waits;      // futex sleeps inside blocking gets
  uint64_t get_lost;       // gets that hit an eviction tombstone
  uint64_t releases;
  uint64_t deletes;
  uint64_t evictions;      // LRU victims freed under pressure
  uint64_t evicted_bytes;
  uint64_t peak_bytes;     // max observed bytes_in_use
};
constexpr int kStoreStatsFields = sizeof(StoreStats) / sizeof(uint64_t);

struct StoreHeader {
  uint64_t magic;
  uint64_t capacity;     // total file size
  uint64_t table_off;
  uint64_t table_slots;
  uint64_t data_off;
  uint64_t data_size;
  pthread_mutex_t mu;
  pthread_cond_t cv;     // broadcast on seal/delete/release
  uint64_t lru_clock;
  uint64_t bytes_in_use;
  uint32_t closed;
  uint32_t pad;
  StoreStats stats;
};

struct Handle {
  StoreHeader* hdr;
  uint8_t* base;
  uint64_t capacity;
  int fd;
};

uint64_t align_up(uint64_t n, uint64_t a) { return (n + a - 1) & ~(a - 1); }

// ---- locking helpers (robust mutex: survive client crashes) ----

int lock(pthread_mutex_t* mu) {
  int rc = pthread_mutex_lock(mu);
  if (rc == EOWNERDEAD) {
    pthread_mutex_consistent(mu);
    rc = 0;
  }
  return rc;
}

// pthread_cond_(timed)wait reacquires the mutex and can itself observe the
// previous owner's death: repair the mutex or every later lock() fails with
// ENOTRECOVERABLE and the store is bricked after one client crash.
int cond_wait(pthread_cond_t* cv, pthread_mutex_t* mu) {
  int rc = pthread_cond_wait(cv, mu);
  if (rc == EOWNERDEAD) {
    pthread_mutex_consistent(mu);
    rc = 0;
  }
  return rc;
}

int cond_timedwait(pthread_cond_t* cv, pthread_mutex_t* mu, const timespec* ts) {
  int rc = pthread_cond_timedwait(cv, mu, ts);
  if (rc == EOWNERDEAD) {
    pthread_mutex_consistent(mu);
    rc = 0;
  }
  return rc;
}

void init_mutex(pthread_mutex_t* mu) {
  pthread_mutexattr_t ma;
  pthread_mutexattr_init(&ma);
  pthread_mutexattr_setpshared(&ma, PTHREAD_PROCESS_SHARED);
  pthread_mutexattr_setrobust(&ma, PTHREAD_MUTEX_ROBUST);
  pthread_mutex_init(mu, &ma);
  pthread_mutexattr_destroy(&ma);
}

void init_cond(pthread_cond_t* cv) {
  pthread_condattr_t ca;
  pthread_condattr_init(&ca);
  pthread_condattr_setpshared(&ca, PTHREAD_PROCESS_SHARED);
  pthread_condattr_setclock(&ca, CLOCK_MONOTONIC);
  pthread_cond_init(cv, &ca);
  pthread_condattr_destroy(&ca);
}

void deadline_after_ms(int64_t ms, timespec* ts) {
  clock_gettime(CLOCK_MONOTONIC, ts);
  ts->tv_sec += ms / 1000;
  ts->tv_nsec += (ms % 1000) * 1000000L;
  if (ts->tv_nsec >= 1000000000L) {
    ts->tv_sec += 1;
    ts->tv_nsec -= 1000000000L;
  }
}

// ---- allocator: boundary tags, first fit, coalescing ----

constexpr uint64_t kBlockOverhead = sizeof(Block) + sizeof(uint64_t);

uint64_t* footer_of(uint8_t* data_base, Block* b) {
  return reinterpret_cast<uint64_t*>(reinterpret_cast<uint8_t*>(b) + b->size -
                                     sizeof(uint64_t));
}

void write_block(uint8_t* data_base, Block* b, uint64_t size, uint64_t free) {
  b->size = size;
  b->free = free;
  *footer_of(data_base, b) = size;
}

Block* next_block(uint8_t* data_base, uint64_t data_size, Block* b) {
  uint8_t* n = reinterpret_cast<uint8_t*>(b) + b->size;
  if (n >= data_base + data_size) return nullptr;
  return reinterpret_cast<Block*>(n);
}

Block* prev_block(uint8_t* data_base, Block* b) {
  uint8_t* p = reinterpret_cast<uint8_t*>(b);
  if (p == data_base) return nullptr;
  uint64_t prev_size = *reinterpret_cast<uint64_t*>(p - sizeof(uint64_t));
  return reinterpret_cast<Block*>(p - prev_size);
}

// Allocate `user_size` bytes; returns data offset from arena base or 0.
uint64_t alloc_locked(Handle* h, uint64_t user_size) {
  StoreHeader* s = h->hdr;
  uint8_t* data_base = h->base + s->data_off;
  uint64_t need = align_up(user_size + kBlockOverhead, kAlign);
  Block* b = reinterpret_cast<Block*>(data_base);
  while (b) {
    if (b->free && b->size >= need) {
      uint64_t remainder = b->size - need;
      if (remainder >= kBlockOverhead + kAlign) {
        write_block(data_base, b, need, 0);
        Block* rest = next_block(data_base, s->data_size, b);
        write_block(data_base, rest, remainder, 1);
      } else {
        b->free = 0;
        *footer_of(data_base, b) = b->size;
      }
      s->bytes_in_use += b->size;
      return (reinterpret_cast<uint8_t*>(b) - h->base) + sizeof(Block);
    }
    b = next_block(data_base, s->data_size, b);
  }
  return 0;
}

void free_locked(Handle* h, uint64_t data_offset) {
  StoreHeader* s = h->hdr;
  uint8_t* data_base = h->base + s->data_off;
  Block* b = reinterpret_cast<Block*>(h->base + data_offset - sizeof(Block));
  s->bytes_in_use -= b->size;
  b->free = 1;
  // coalesce with next
  Block* n = next_block(data_base, s->data_size, b);
  if (n && n->free) write_block(data_base, b, b->size + n->size, 1);
  // coalesce with prev
  Block* p = prev_block(data_base, b);
  if (p && p->free) write_block(data_base, p, p->size + b->size, 1);
  else *footer_of(data_base, b) = b->size;
}

// ---- object table: open addressing on id hash ----

uint64_t hash_id(const uint8_t* id) {
  uint64_t x;
  memcpy(&x, id, 8);
  uint64_t y;
  memcpy(&y, id + 8, 8);
  uint32_t z;  // ObjectIDs are task_id(16) + return_index(4): the tail must
  memcpy(&z, id + 16, 4);  // feed the hash or one task's returns all collide
  x ^= y * 0x9e3779b97f4a7c15ull;
  x ^= (uint64_t)z * 0xc2b2ae3d27d4eb4full;
  x ^= x >> 33;
  x *= 0xff51afd7ed558ccdull;
  x ^= x >> 33;
  return x;
}

Entry* table(Handle* h) {
  return reinterpret_cast<Entry*>(h->base + h->hdr->table_off);
}

Entry* find_entry(Handle* h, const uint8_t* id) {
  Entry* t = table(h);
  uint64_t slots = h->hdr->table_slots;
  uint64_t i = hash_id(id) % slots;
  for (uint64_t probe = 0; probe < slots; ++probe) {
    Entry* e = &t[i];
    if (e->state == kFree) return nullptr;
    if (memcmp(e->id, id, kIdSize) == 0 && e->state != kFree) return e;
    i = (i + 1) % slots;
  }
  return nullptr;
}

void erase_entry(Handle* h, Entry* e);

Entry* insert_entry_once(Handle* h, const uint8_t* id) {
  Entry* t = table(h);
  uint64_t slots = h->hdr->table_slots;
  uint64_t i = hash_id(id) % slots;
  for (uint64_t probe = 0; probe < slots; ++probe) {
    Entry* e = &t[i];
    if (e->state == kFree) {
      memcpy(e->id, id, kIdSize);
      return e;
    }
    i = (i + 1) % slots;
  }
  return nullptr;  // table full
}

Entry* insert_entry(Handle* h, const uint8_t* id) {
  Entry* e = insert_entry_once(h, id);
  if (e) return e;
  // Table full: reclaim eviction tombstones (they exist only to fail lookups
  // fast; dropping them under table pressure is safe). erase_entry's cluster
  // re-insertion can relocate a not-yet-visited tombstone into an already-
  // scanned slot, so sweep until a full pass finds none.
  Entry* t = table(h);
  uint64_t slots = h->hdr->table_slots;
  bool erased_any = true;
  while (erased_any) {
    erased_any = false;
    for (uint64_t i = 0; i < slots; ++i) {
      if (t[i].state == kEvicted) {
        erase_entry(h, &t[i]);
        erased_any = true;
      }
    }
  }
  return insert_entry_once(h, id);
}

void erase_entry(Handle* h, Entry* e) {
  // Open addressing deletion: re-insert the rest of the cluster.
  Entry* t = table(h);
  uint64_t slots = h->hdr->table_slots;
  uint64_t i = e - t;
  e->state = kFree;
  uint64_t j = (i + 1) % slots;
  while (t[j].state != kFree) {
    Entry moved = t[j];
    t[j].state = kFree;
    Entry* dst = insert_entry(h, moved.id);
    uint8_t saved_id[kIdSize];
    memcpy(saved_id, moved.id, kIdSize);
    *dst = moved;
    memcpy(dst->id, saved_id, kIdSize);
    j = (j + 1) % slots;
  }
}

// Evict LRU sealed refcnt==0 objects until at least `need` bytes could fit.
// Returns 1 if anything was evicted.
int evict_locked(Handle* h, uint64_t need) {
  (void)need;
  Entry* t = table(h);
  uint64_t slots = h->hdr->table_slots;
  Entry* victim = nullptr;
  for (uint64_t i = 0; i < slots; ++i) {
    Entry* e = &t[i];
    if (e->state == kSealed && e->refcnt == 0) {
      if (!victim || e->lru_seq < victim->lru_seq) victim = e;
    }
  }
  if (!victim) return 0;
  h->hdr->stats.evictions++;
  h->hdr->stats.evicted_bytes += victim->size;
  free_locked(h, victim->offset);
  // Leave a tombstone instead of erasing: a live ObjectRef (or a stale GCS
  // location entry) may still point here, and a blocking get must see "lost",
  // not wait forever (ADVICE r1: eviction vs. live refs).
  victim->state = kEvicted;
  victim->offset = 0;
  victim->refcnt = 0;
  return 1;
}

}  // namespace

extern "C" {

// Non-temporal bulk copy: streaming stores skip the read-for-ownership
// traffic a cached memcpy pays on the destination lines (~2x effective
// write bandwidth for large one-shot copies like object-store puts —
// the destination is shm another process reads, so polluting THIS
// core's cache with it is pure loss). x86-64 SSE2 baseline; other
// architectures fall back to memcpy.
void rt_copy_nt(void* dst, const void* src, uint64_t n) {
#if defined(__x86_64__) || defined(_M_X64)
  char* d = static_cast<char*>(dst);
  const char* s = static_cast<const char*>(src);
  // small copies + head up to 16B alignment: plain memcpy
  if (n < (1u << 16)) {
    memcpy(d, s, n);
    return;
  }
  uint64_t head = (16 - (reinterpret_cast<uintptr_t>(d) & 15)) & 15;
  if (head) {
    memcpy(d, s, head);
    d += head;
    s += head;
    n -= head;
  }
  uint64_t vecs = n / 64;
  auto* dv = reinterpret_cast<__m128i*>(d);
  auto* sv = reinterpret_cast<const __m128i*>(s);
  for (uint64_t i = 0; i < vecs; ++i) {
    __m128i a = _mm_loadu_si128(sv + 4 * i + 0);
    __m128i b = _mm_loadu_si128(sv + 4 * i + 1);
    __m128i c = _mm_loadu_si128(sv + 4 * i + 2);
    __m128i e = _mm_loadu_si128(sv + 4 * i + 3);
    _mm_stream_si128(dv + 4 * i + 0, a);
    _mm_stream_si128(dv + 4 * i + 1, b);
    _mm_stream_si128(dv + 4 * i + 2, c);
    _mm_stream_si128(dv + 4 * i + 3, e);
  }
  _mm_sfence();
  uint64_t done = vecs * 64;
  if (done < n) memcpy(d + done, s + done, n - done);
#else
  memcpy(dst, src, n);
#endif
}

// Create a new store arena backed by /dev/shm/<name>. Returns handle or null.
void* rt_store_create(const char* name, uint64_t capacity) {
  // header + minimum 4096-slot table + one block of real space; anything
  // smaller underflows data_size and scribbles past the mapping.
  uint64_t min_capacity = align_up(sizeof(StoreHeader), kAlign) +
                          align_up(4096 * sizeof(Entry), kAlign) + (1u << 20);
  if (capacity < min_capacity) return nullptr;
  shm_unlink(name);
  int fd = shm_open(name, O_CREAT | O_EXCL | O_RDWR, 0600);
  if (fd < 0) return nullptr;
  if (ftruncate(fd, (off_t)capacity) != 0) {
    close(fd);
    shm_unlink(name);
    return nullptr;
  }
  void* base = mmap(nullptr, capacity, PROT_READ | PROT_WRITE, MAP_SHARED, fd, 0);
  if (base == MAP_FAILED) {
    close(fd);
    shm_unlink(name);
    return nullptr;
  }
  auto* h = new Handle;
  h->base = static_cast<uint8_t*>(base);
  h->hdr = reinterpret_cast<StoreHeader*>(base);
  h->capacity = capacity;
  h->fd = fd;

  StoreHeader* s = h->hdr;
  memset(s, 0, sizeof(StoreHeader));
  s->capacity = capacity;
  // size the table at ~1 slot per 16KB of arena, min 4096 slots
  uint64_t slots = capacity / 16384;
  if (slots < 4096) slots = 4096;
  s->table_off = align_up(sizeof(StoreHeader), kAlign);
  s->table_slots = slots;
  s->data_off = align_up(s->table_off + slots * sizeof(Entry), kAlign);
  s->data_size = capacity - s->data_off;
  memset(h->base + s->table_off, 0, slots * sizeof(Entry));
  init_mutex(&s->mu);
  init_cond(&s->cv);
  // one giant free block
  uint8_t* data_base = h->base + s->data_off;
  write_block(data_base, reinterpret_cast<Block*>(data_base), s->data_size, 1);
  __sync_synchronize();
  s->magic = kMagic;
  return h;
}

void* rt_store_connect(const char* name) {
  int fd = shm_open(name, O_RDWR, 0600);
  if (fd < 0) return nullptr;
  struct stat st;
  if (fstat(fd, &st) != 0) {
    close(fd);
    return nullptr;
  }
  void* base =
      mmap(nullptr, st.st_size, PROT_READ | PROT_WRITE, MAP_SHARED, fd, 0);
  if (base == MAP_FAILED) {
    close(fd);
    return nullptr;
  }
  auto* h = new Handle;
  h->base = static_cast<uint8_t*>(base);
  h->hdr = reinterpret_cast<StoreHeader*>(base);
  h->capacity = st.st_size;
  h->fd = fd;
  if (h->hdr->magic != kMagic) {
    munmap(base, st.st_size);
    close(fd);
    delete h;
    return nullptr;
  }
  return h;
}

void rt_store_close(void* hv) {
  auto* h = static_cast<Handle*>(hv);
  munmap(h->base, h->capacity);
  close(h->fd);
  delete h;
}

int rt_store_destroy(const char* name) { return shm_unlink(name); }

uint64_t rt_store_capacity(void* hv) {
  return static_cast<Handle*>(hv)->hdr->data_size;
}

uint64_t rt_store_bytes_in_use(void* hv) {
  return static_cast<Handle*>(hv)->hdr->bytes_in_use;
}

// Copy the arena stats block into out[0..n): field order matches
// StoreStats (creates, create_bytes, seals, gets, get_waits, get_lost,
// releases, deletes, evictions, evicted_bytes, peak_bytes). Locked copy
// (the caller is a ~1Hz metrics flush); returns fields written.
int rt_store_stats(void* hv, uint64_t* out, int n) {
  auto* h = static_cast<Handle*>(hv);
  StoreHeader* s = h->hdr;
  if (lock(&s->mu) != 0) return 0;
  const uint64_t* src = reinterpret_cast<const uint64_t*>(&s->stats);
  int count = n < kStoreStatsFields ? n : kStoreStatsFields;
  for (int i = 0; i < count; i++) out[i] = src[i];
  pthread_mutex_unlock(&s->mu);
  return count;
}

// Enumerate spill candidates: sealed, unreferenced objects, LRU-first.
// Writes up to `max` ids (kIdSize bytes each) + sizes; returns the count.
// The raylet uses this to pick what to move to disk under arena pressure
// (the LocalObjectManager role, ref: local_object_manager.h:42).
int rt_store_list_spillable(void* hv, uint8_t* ids_out, uint64_t* sizes_out,
                            int max) {
  auto* h = static_cast<Handle*>(hv);
  StoreHeader* s = h->hdr;
  lock(&s->mu);
  Entry* t = table(h);
  uint64_t slots = s->table_slots;
  // collect candidate slot indexes, then insertion-sort by lru_seq (max is
  // small — the raylet spills in bounded passes)
  int n = 0;
  struct Cand { uint64_t lru; uint64_t idx; };
  Cand* cands = new Cand[max];
  for (uint64_t i = 0; i < slots; ++i) {
    Entry* e = &t[i];
    if (e->state != kSealed || e->refcnt != 0) continue;
    Cand c{e->lru_seq, i};
    if (n < max) {
      int j = n++;
      while (j > 0 && cands[j - 1].lru > c.lru) { cands[j] = cands[j - 1]; --j; }
      cands[j] = c;
    } else if (cands[max - 1].lru > c.lru) {
      int j = max - 1;
      while (j > 0 && cands[j - 1].lru > c.lru) { cands[j] = cands[j - 1]; --j; }
      cands[j] = c;
    }
  }
  for (int k = 0; k < n; ++k) {
    Entry* e = &t[cands[k].idx];
    memcpy(ids_out + (uint64_t)k * kIdSize, e->id, kIdSize);
    sizes_out[k] = e->size;
  }
  delete[] cands;
  pthread_mutex_unlock(&s->mu);
  return n;
}

// Create an object; returns kOK and sets *offset_out (arena offset of data).
int rt_create(void* hv, const uint8_t* id, uint64_t size, uint64_t* offset_out) {
  auto* h = static_cast<Handle*>(hv);
  StoreHeader* s = h->hdr;
  lock(&s->mu);
  Entry* existing = find_entry(h, id);
  if (existing && existing->state != kEvicted) {
    pthread_mutex_unlock(&s->mu);
    return kExists;
  }
  uint64_t off = alloc_locked(h, size);
  while (off == 0) {
    if (!evict_locked(h, size)) break;
    off = alloc_locked(h, size);
  }
  if (off == 0) {
    pthread_mutex_unlock(&s->mu);
    return kOutOfMemory;
  }
  // Resurrect an evicted id in place (lineage reconstruction re-creates the
  // same ObjectID); otherwise claim a fresh slot.
  Entry* e = existing ? existing : insert_entry(h, id);
  if (!e) {
    free_locked(h, off);
    pthread_mutex_unlock(&s->mu);
    return kOutOfMemory;
  }
  e->state = kCreated;
  e->offset = off;
  e->size = size;
  e->refcnt = 1;  // creator holds a ref until seal+release
  e->lru_seq = ++s->lru_clock;
  s->stats.creates++;
  s->stats.create_bytes += size;
  if (s->bytes_in_use > s->stats.peak_bytes)
    s->stats.peak_bytes = s->bytes_in_use;
  *offset_out = off;
  pthread_mutex_unlock(&s->mu);
  return kOK;
}

int rt_seal(void* hv, const uint8_t* id) {
  auto* h = static_cast<Handle*>(hv);
  StoreHeader* s = h->hdr;
  if (chaos_seal_strike()) return kSysError;  // entry stays kCreated
  lock(&s->mu);
  Entry* e = find_entry(h, id);
  if (!e) {
    pthread_mutex_unlock(&s->mu);
    return kNotFound;
  }
  if (e->state != kCreated) {
    pthread_mutex_unlock(&s->mu);
    return kBadState;
  }
  e->state = kSealed;
  e->refcnt -= 1;  // drop creator ref
  e->lru_seq = ++s->lru_clock;
  s->stats.seals++;
  pthread_cond_broadcast(&s->cv);
  pthread_mutex_unlock(&s->mu);
  return kOK;
}

// Blocking get: waits until sealed or timeout; takes a reference.
int rt_get(void* hv, const uint8_t* id, int64_t timeout_ms, uint64_t* offset_out,
           uint64_t* size_out) {
  auto* h = static_cast<Handle*>(hv);
  StoreHeader* s = h->hdr;
  timespec deadline;
  if (timeout_ms >= 0) deadline_after_ms(timeout_ms, &deadline);
  lock(&s->mu);
  for (;;) {
    Entry* e = find_entry(h, id);
    if (e && e->state == kSealed) {
      e->refcnt += 1;
      e->lru_seq = ++s->lru_clock;
      s->stats.gets++;
      *offset_out = e->offset;
      *size_out = e->size;
      pthread_mutex_unlock(&s->mu);
      return kOK;
    }
    if (e && e->state == kEvicted) {
      s->stats.get_lost++;
      pthread_mutex_unlock(&s->mu);
      return kLost;  // fail fast: caller raises ObjectLostError / reconstructs
    }
    int rc;
    s->stats.get_waits++;
    if (timeout_ms >= 0) {
      rc = cond_timedwait(&s->cv, &s->mu, &deadline);
      if (rc == ETIMEDOUT) {
        pthread_mutex_unlock(&s->mu);
        return kTimeout;
      }
    } else {
      rc = cond_wait(&s->cv, &s->mu);
    }
    if (rc != 0) {
      pthread_mutex_unlock(&s->mu);
      return kSysError;
    }
  }
}

// Non-blocking existence check; does NOT take a reference.
int rt_contains(void* hv, const uint8_t* id) {
  auto* h = static_cast<Handle*>(hv);
  lock(&h->hdr->mu);
  Entry* e = find_entry(h, id);
  int found = (e && e->state == kSealed) ? 1 : 0;
  pthread_mutex_unlock(&h->hdr->mu);
  return found;
}

int rt_release(void* hv, const uint8_t* id) {
  auto* h = static_cast<Handle*>(hv);
  lock(&h->hdr->mu);
  Entry* e = find_entry(h, id);
  if (!e) {
    pthread_mutex_unlock(&h->hdr->mu);
    return kNotFound;
  }
  if (e->refcnt > 0) e->refcnt -= 1;
  h->hdr->stats.releases++;
  pthread_cond_broadcast(&h->hdr->cv);
  pthread_mutex_unlock(&h->hdr->mu);
  return kOK;
}

// Delete: frees now if unreferenced, else marks for no new refs by erasing
// from the table once refcnt hits zero (here: spin is avoided — caller is the
// owner and release() of last ref frees the memory).
int rt_delete(void* hv, const uint8_t* id) {
  auto* h = static_cast<Handle*>(hv);
  lock(&h->hdr->mu);
  Entry* e = find_entry(h, id);
  if (!e) {
    pthread_mutex_unlock(&h->hdr->mu);
    return kNotFound;
  }
  if (e->state == kEvicted) {
    erase_entry(h, e);  // tombstone: data already freed
  } else if (e->refcnt <= 0) {
    free_locked(h, e->offset);
    erase_entry(h, e);
  } else {
    // keep data alive for readers; demote lru so eviction reclaims it next
    e->lru_seq = 0;
  }
  h->hdr->stats.deletes++;
  pthread_cond_broadcast(&h->hdr->cv);
  pthread_mutex_unlock(&h->hdr->mu);
  return kOK;
}

// ---- mutable channel objects (compiled-graph substrate) ----

int rt_chan_create(void* hv, const uint8_t* id, uint64_t size,
                   uint32_t num_readers, uint64_t* offset_out) {
  auto* h = static_cast<Handle*>(hv);
  StoreHeader* s = h->hdr;
  uint64_t total = align_up(sizeof(ChannelHeader), kAlign) + size;
  lock(&s->mu);
  if (find_entry(h, id)) {
    pthread_mutex_unlock(&s->mu);
    return kExists;
  }
  uint64_t off = alloc_locked(h, total);
  while (off == 0) {
    if (!evict_locked(h, total)) break;
    off = alloc_locked(h, total);
  }
  if (off == 0) {
    pthread_mutex_unlock(&s->mu);
    return kOutOfMemory;
  }
  Entry* e = insert_entry(h, id);
  if (!e) {
    free_locked(h, off);
    pthread_mutex_unlock(&s->mu);
    return kOutOfMemory;
  }
  e->state = kChannel;
  e->offset = off;
  e->size = size;
  e->refcnt = 1;
  e->lru_seq = ~0ull;  // never evict channels
  ChannelHeader* ch = reinterpret_cast<ChannelHeader*>(h->base + off);
  memset(ch, 0, sizeof(ChannelHeader));
  init_mutex(&ch->mu);
  init_cond(&ch->cv);
  ch->num_readers = num_readers;
  ch->readers_left = 0;
  ch->version = 0;
  *offset_out = off + align_up(sizeof(ChannelHeader), kAlign);
  pthread_mutex_unlock(&s->mu);
  return kOK;
}

// Copies the channel's arena offset/size out under the store mutex. Entry*
// must never be held across the unlock: erase_entry's open-addressing cluster
// re-insertion relocates entries, so a cached pointer can dangle (ADVICE r1).
// The *data* never moves — channels are never evicted — so the copied offset
// stays valid for the blocking waits below.
static int chan_lookup(Handle* h, const uint8_t* id, uint64_t* off_out,
                       uint64_t* size_out) {
  lock(&h->hdr->mu);
  Entry* e = find_entry(h, id);
  if (!e || e->state != kChannel) {
    pthread_mutex_unlock(&h->hdr->mu);
    return kNotFound;
  }
  *off_out = e->offset;
  if (size_out) *size_out = e->size;
  pthread_mutex_unlock(&h->hdr->mu);
  return kOK;
}

static ChannelHeader* chan_hdr_at(Handle* h, uint64_t off) {
  return reinterpret_cast<ChannelHeader*>(h->base + off);
}

int rt_chan_data(void* hv, const uint8_t* id, uint64_t* offset_out,
                 uint64_t* size_out) {
  auto* h = static_cast<Handle*>(hv);
  uint64_t off;
  int rc = chan_lookup(h, id, &off, size_out);
  if (rc != kOK) return rc;
  *offset_out = off + align_up(sizeof(ChannelHeader), kAlign);
  return kOK;
}

// Writer: wait until all readers of the previous version have released.
int rt_chan_write_acquire(void* hv, const uint8_t* id, int64_t timeout_ms) {
  auto* h = static_cast<Handle*>(hv);
  uint64_t off;
  int rc = chan_lookup(h, id, &off, nullptr);
  if (rc != kOK) return rc;
  ChannelHeader* ch = chan_hdr_at(h, off);
  timespec deadline;
  if (timeout_ms >= 0) deadline_after_ms(timeout_ms, &deadline);
  lock(&ch->mu);
  while (ch->readers_left > 0 && !ch->closed) {
    int w = timeout_ms >= 0 ? cond_timedwait(&ch->cv, &ch->mu, &deadline)
                            : cond_wait(&ch->cv, &ch->mu);
    if (w == ETIMEDOUT) {
      pthread_mutex_unlock(&ch->mu);
      return kTimeout;
    }
  }
  int closed = ch->closed;
  pthread_mutex_unlock(&ch->mu);
  return closed ? kClosed : kOK;
}

int rt_chan_write_release(void* hv, const uint8_t* id, uint64_t payload_size) {
  auto* h = static_cast<Handle*>(hv);
  uint64_t off;
  int rc = chan_lookup(h, id, &off, nullptr);
  if (rc != kOK) return rc;
  ChannelHeader* ch = chan_hdr_at(h, off);
  lock(&ch->mu);
  ch->version += 1;
  ch->payload_size = payload_size;
  ch->readers_left = (int32_t)ch->num_readers;
  pthread_cond_broadcast(&ch->cv);
  pthread_mutex_unlock(&ch->mu);
  return kOK;
}

// Reader: wait for a version newer than last_version; returns it.
int rt_chan_read_acquire(void* hv, const uint8_t* id, uint64_t last_version,
                         int64_t timeout_ms, uint64_t* version_out,
                         uint64_t* payload_size_out) {
  auto* h = static_cast<Handle*>(hv);
  uint64_t off;
  int rc = chan_lookup(h, id, &off, nullptr);
  if (rc != kOK) return rc;
  ChannelHeader* ch = chan_hdr_at(h, off);
  timespec deadline;
  if (timeout_ms >= 0) deadline_after_ms(timeout_ms, &deadline);
  lock(&ch->mu);
  while (ch->version <= last_version && !ch->closed) {
    int w = timeout_ms >= 0 ? cond_timedwait(&ch->cv, &ch->mu, &deadline)
                            : cond_wait(&ch->cv, &ch->mu);
    if (w == ETIMEDOUT) {
      pthread_mutex_unlock(&ch->mu);
      return kTimeout;
    }
  }
  if (ch->closed) {
    pthread_mutex_unlock(&ch->mu);
    return kClosed;
  }
  *version_out = ch->version;
  *payload_size_out = ch->payload_size;
  pthread_mutex_unlock(&ch->mu);
  return kOK;
}

int rt_chan_read_release(void* hv, const uint8_t* id) {
  auto* h = static_cast<Handle*>(hv);
  uint64_t off;
  int rc = chan_lookup(h, id, &off, nullptr);
  if (rc != kOK) return rc;
  ChannelHeader* ch = chan_hdr_at(h, off);
  lock(&ch->mu);
  if (ch->readers_left > 0) ch->readers_left -= 1;
  pthread_cond_broadcast(&ch->cv);
  pthread_mutex_unlock(&ch->mu);
  return kOK;
}

int rt_chan_close(void* hv, const uint8_t* id) {
  auto* h = static_cast<Handle*>(hv);
  uint64_t off;
  int rc = chan_lookup(h, id, &off, nullptr);
  if (rc != kOK) return rc;
  ChannelHeader* ch = chan_hdr_at(h, off);
  lock(&ch->mu);
  ch->closed = 1;
  pthread_cond_broadcast(&ch->cv);
  pthread_mutex_unlock(&ch->mu);
  return kOK;
}

// Runtime (re-)arm of the seal-failure chaos counter; 0 disarms.
void rt_store_chaos_set(uint64_t seal_fail_every) {
  __atomic_store_n(&g_chaos_seal_every, seal_fail_every, __ATOMIC_RELAXED);
  __atomic_store_n(&g_chaos_seal_ctr, 0, __ATOMIC_RELAXED);
}

}  // extern "C"

// rt_cpp_worker.cc — C++ worker runtime for ray_tpu.
//
// Speaks the control-plane wire protocol natively (length-prefixed pickle
// frames; codec in picklite.h) — the C++ peer of ray_tpu/core/worker.py:
//   1. read the RT_* env contract the raylet's worker pool sets
//      (ref: worker_pool.h:231 fork/pop of language workers)
//   2. open a task-receiver server on an ephemeral port
//   3. register with the raylet: worker_ready{worker_id, address, pid}
//   4. serve push_task / cancel_if_current from driver connections
//   5. exit when the raylet connection closes (node death contract)
//
// Results are returned inline in the reply using the same packed layout as
// serialization.pack (u32 meta-len + pickled (sizes, header) + buffers);
// errors unpickle as real ray_tpu.core.ref.TaskError on the driver.

#include <csignal>
#include <unistd.h>

#include <atomic>
#include <cstdio>
#include <cstdlib>
#include <cstring>
#include <memory>
#include <mutex>
#include <set>
#include <thread>

#include "rt_cpp_api.h"
#include "rt_wire.h"

namespace rt {

std::map<std::string, TaskFn>& task_registry() {
  static std::map<std::string, TaskFn> reg;
  return reg;
}

namespace {

using wire::dial;
using wire::pack_value;
using wire::read_frame;
using wire::unpack_value;
using wire::write_frame;

// ----------------------------------------------------------------- worker

// Shared per-connection state: the read loop and detached task threads
// both hold a reference; the fd closes only when the last user is done,
// so a late task reply can never hit a recycled descriptor.
struct ConnState {
  int fd;
  std::atomic<int> inflight{0};
  std::atomic<bool> eof{false};
  std::mutex close_mu;
  bool closed = false;

  explicit ConnState(int f) : fd(f) {}

  void maybe_close() {
    if (!eof.load() || inflight.load() != 0) return;
    std::lock_guard<std::mutex> g(close_mu);
    if (!closed) {
      closed = true;
      ::close(fd);
    }
  }
};

struct Worker {
  std::string worker_id_hex;
  std::string raylet_host;
  int raylet_port = 0;
  int server_fd = -1;
  int server_port = 0;
  std::atomic<long> current_task_lo{0};  // first 8 bytes of running task id
  std::mutex exec_mu;                    // one task at a time (worker invariant)
  std::mutex write_mu;                   // interleaved responses per process
  // tasks accepted but not yet finished, marked at push RECEIPT (reader
  // thread) — a force-cancel racing task startup or a pipelined task queued
  // behind exec_mu must still match (peer of worker.py _current_tasks)
  std::mutex pending_mu;
  std::set<long> pending_tasks;

  ValuePtr envelope(const char* kind, int64_t corr_id) {
    auto msg = Value::dict_();
    msg->set("k", Value::str(kind));
    if (corr_id >= 0) msg->set("i", Value::integer(corr_id));
    return msg;
  }

  bool respond(int fd, int64_t corr_id, ValuePtr value, ValuePtr error = nullptr) {
    auto msg = envelope("r", corr_id);
    msg->set("v", value ? value : Value::none());
    msg->set("e", error ? error : Value::none());
    std::string frame = picklite::dumps(*msg);
    std::lock_guard<std::mutex> g(write_mu);  // replies may interleave
    return write_frame(fd, frame);
  }

  ValuePtr run_task(const ValuePtr& spec, long tlo) {
    auto fname = spec->get("func_name");
    if (!fname || fname->kind != Value::kStr)
      throw std::runtime_error("spec has no func_name (cpp task expected)");
    auto it = task_registry().find(fname->s);
    if (it == task_registry().end())
      throw std::runtime_error("no C++ task registered as '" + fname->s + "'");
    std::vector<ValuePtr> args;
    auto spec_args = spec->get("args");
    if (spec_args) {
      for (auto& a : spec_args->items) {
        // arg descriptors from _resolve_args: ("v", packed) inline values;
        // ("r", id, owner) plasma refs are not supported in C++ tasks yet
        if (a->kind != Value::kTuple || a->items.empty())
          throw std::runtime_error("bad arg descriptor");
        const std::string& tag = a->items[0]->s;
        if (tag == "v") {
          args.push_back(unpack_value(a->items[1]->s));
        } else if (tag == "p") {
          args.push_back(a->items[1]);
        } else {
          throw std::runtime_error(
              "C++ tasks take inline args only (got ObjectRef arg)");
        }
      }
    }
    std::lock_guard<std::mutex> g(exec_mu);
    // mark under the execution lock: with pipelined pushes, the marker must
    // always name the task that is actually running
    current_task_lo.store(tlo);
    try {
      auto out = it->second(args);
      current_task_lo.store(0);
      return out;
    } catch (...) {
      current_task_lo.store(0);
      throw;
    }
  }

  void handle_push_task(int fd, int64_t corr_id, const ValuePtr& payload) {
    auto spec = payload->get("spec");
    ValuePtr reply = Value::dict_();
    try {
      if (!spec) throw std::runtime_error("no spec");
      // current-task marker for the cancel_if_current identity check
      auto tid = spec->get("task_id");
      long tlo = 0;
      if (tid && !tid->items.empty() && tid->items[0]->kind == Value::kBytes &&
          tid->items[0]->s.size() >= 8)
        std::memcpy(&tlo, tid->items[0]->s.data(), 8);
      ValuePtr value = run_task(spec, tlo);
      int64_t num_returns = 1;
      auto nr = spec->get("num_returns");
      if (nr && nr->kind == Value::kInt) num_returns = nr->i;
      auto results = Value::list();
      if (num_returns == 1) {
        auto r = Value::dict_();
        r->set("inline", Value::bytes(pack_value(value ? *value : Value())));
        results->items.push_back(r);
      } else if (num_returns > 1) {
        if (!value || value->kind != Value::kTuple ||
            (int64_t)value->items.size() != num_returns)
          throw std::runtime_error("task must return a tuple of num_returns items");
        for (auto& item : value->items) {
          auto r = Value::dict_();
          r->set("inline", Value::bytes(pack_value(*item)));
          results->items.push_back(r);
        }
      }
      reply->set("results", results);
    } catch (const std::exception& e) {
      auto err = Value::opaque("ray_tpu.core.ref", "TaskError");
      err->items.push_back(Value::str(e.what()));
      reply->set("error", err);
    }
    respond(fd, corr_id, reply);
  }

  void serve_conn(std::shared_ptr<ConnState> cs) {
    const int fd = cs->fd;
    std::string frame;
    while (read_frame(fd, &frame)) {
      ValuePtr msg;
      try {
        msg = picklite::loads(frame);
      } catch (const std::exception&) {
        break;  // undecodable frame: drop the connection
      }
      auto kind = msg->get("k");
      if (!kind || kind->kind != Value::kStr) continue;
      if (kind->s == "n") continue;  // notifications: nothing to do yet
      if (kind->s != "c") continue;
      int64_t corr_id = msg->get("i") ? msg->get("i")->i : 0;
      auto method = msg->get("m");
      auto payload = msg->get("p");
      if (!method) continue;
      if (method->s == "push_task") {
        // mark at RECEIPT so a racing force-cancel can't slip between
        // accept and execution (the arg-decode window under exec_mu)
        long tlo = 0;
        auto spec = payload ? payload->get("spec") : nullptr;
        auto tid = spec ? spec->get("task_id") : nullptr;
        if (tid && !tid->items.empty() && tid->items[0]->kind == Value::kBytes &&
            tid->items[0]->s.size() >= 8)
          std::memcpy(&tlo, tid->items[0]->s.data(), 8);
        if (tlo != 0) {
          std::lock_guard<std::mutex> g(pending_mu);
          pending_tasks.insert(tlo);
        }
        // execute off-thread so this connection keeps reading — a
        // cancel_if_current sent on the SAME connection mid-task must be
        // seen while the task runs (exec_mu still serializes execution).
        // The ConnState ref keeps the fd alive until the reply is written.
        cs->inflight.fetch_add(1);
        std::thread([this, cs, corr_id, payload, tlo] {
          handle_push_task(cs->fd, corr_id, payload);
          if (tlo != 0) {
            std::lock_guard<std::mutex> g(pending_mu);
            pending_tasks.erase(tlo);
          }
          cs->inflight.fetch_sub(1);
          cs->maybe_close();
        }).detach();
      } else if (method->s == "cancel_if_current") {
        long tlo = 0;
        auto tid = payload ? payload->get("task_id") : nullptr;
        if (tid && !tid->items.empty() && tid->items[0]->s.size() >= 8)
          std::memcpy(&tlo, tid->items[0]->s.data(), 8);
        bool pending = false;
        if (tlo != 0) {
          std::lock_guard<std::mutex> g(pending_mu);
          pending = pending_tasks.count(tlo) != 0;
        }
        if (pending || (tlo != 0 && current_task_lo.load() == tlo)) {
          respond(fd, corr_id, Value::boolean(true));
          ::_exit(1);
        }
        respond(fd, corr_id, Value::boolean(false));
      } else if (method->s == "ping") {
        respond(fd, corr_id, Value::boolean(true));
      } else if (method->s == "__hello__") {
        auto v = Value::dict_();
        auto proto = Value::tuple();
        proto->items.push_back(Value::integer(wire::kProtocolMajor));
        proto->items.push_back(Value::integer(wire::kProtocolMinor));
        v->set("proto", proto);
        respond(fd, corr_id, v);
      } else {
        auto err = Value::opaque("ray_tpu.utils.rpc", "RpcError");
        err->items.push_back(
            Value::str("cpp worker: no handler for '" + method->s + "'"));
        respond(fd, corr_id, nullptr, err);
      }
    }
    cs->eof.store(true);
    cs->maybe_close();
  }

  int run() {
    ::signal(SIGPIPE, SIG_IGN);  // peer-closed writes return EPIPE, not kill
    const char* wid = ::getenv("RT_WORKER_ID");
    const char* rh = ::getenv("RT_RAYLET_HOST");
    const char* rp = ::getenv("RT_RAYLET_PORT");
    if (!wid || !rh || !rp) {
      std::fprintf(stderr, "rt_cpp_worker: RT_WORKER_ID/RT_RAYLET_HOST/RT_RAYLET_PORT required\n");
      return 2;
    }
    worker_id_hex = wid;
    raylet_host = rh;
    raylet_port = std::atoi(rp);

    // task-receiver server on an ephemeral port; bind ANY so drivers on
    // other nodes can dial a leased C++ worker (loopback would wall it off)
    server_fd = ::socket(AF_INET, SOCK_STREAM, 0);
    sockaddr_in addr{};
    addr.sin_family = AF_INET;
    addr.sin_addr.s_addr = htonl(INADDR_ANY);
    addr.sin_port = 0;
    if (::bind(server_fd, (sockaddr*)&addr, sizeof(addr)) != 0 ||
        ::listen(server_fd, 64) != 0) {
      std::perror("rt_cpp_worker: bind/listen");
      return 2;
    }
    socklen_t alen = sizeof(addr);
    ::getsockname(server_fd, (sockaddr*)&addr, &alen);
    server_port = ntohs(addr.sin_port);

    // register with the raylet (same handshake as the python worker)
    int rfd = dial(raylet_host, raylet_port);
    if (rfd < 0) {
      std::fprintf(stderr, "rt_cpp_worker: cannot reach raylet %s:%d\n",
                   raylet_host.c_str(), raylet_port);
      return 2;
    }
    // advertise the address this host is reachable on: the local IP of the
    // raylet dial (RT_ADVERTISE_HOST overrides), not a hardcoded loopback —
    // a driver on another node must be able to dial this worker
    std::string adv_host = "127.0.0.1";
    if (const char* ah = std::getenv("RT_ADVERTISE_HOST")) {
      adv_host = ah;
    } else {
      sockaddr_in local{};
      socklen_t llen = sizeof(local);
      if (::getsockname(rfd, (sockaddr*)&local, &llen) == 0) {
        char buf[INET_ADDRSTRLEN];
        if (inet_ntop(AF_INET, &local.sin_addr, buf, sizeof(buf)))
          adv_host = buf;
      }
    }
    {
      auto msg = envelope("c", 1);
      msg->set("m", Value::str("worker_ready"));
      auto p = Value::dict_();
      p->set("worker_id", Value::str(worker_id_hex));
      auto address = Value::tuple();
      address->items.push_back(Value::str(adv_host));
      address->items.push_back(Value::integer(server_port));
      p->set("address", address);
      p->set("pid", Value::integer((int64_t)::getpid()));
      p->set("language", Value::str("cpp"));
      msg->set("p", p);
      if (!write_frame(rfd, picklite::dumps(*msg))) return 2;
      std::string ack;
      if (!read_frame(rfd, &ack)) return 2;  // {"k":"r","i":1,...}
    }

    // raylet link doubles as the liveness contract: EOF => node gone => exit
    std::thread([rfd] {
      std::string frame;
      while (read_frame(rfd, &frame)) {
        // raylet only pushes notifications at workers today; ignore them
      }
      ::_exit(0);
    }).detach();

    while (true) {
      int cfd = ::accept(server_fd, nullptr, nullptr);
      if (cfd < 0) continue;
      int one = 1;
      ::setsockopt(cfd, IPPROTO_TCP, TCP_NODELAY, &one, sizeof(one));
      auto cs = std::make_shared<ConnState>(cfd);
      std::thread([this, cs] { serve_conn(cs); }).detach();
    }
  }
};

}  // namespace

int worker_main() {
  Worker w;
  return w.run();
}

}  // namespace rt

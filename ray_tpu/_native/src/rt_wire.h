// rt_wire.h — shared wire helpers for native peers (worker + client):
// blocking TCP framing (<u64 LE len><pickle>), dialing, and the packed
// value layout of serialization.pack (u32 meta-len | pickled (sizes,
// header) | 64-byte-aligned buffers).
#pragma once

#include <arpa/inet.h>
#include <netdb.h>
#include <netinet/in.h>
#include <netinet/tcp.h>
#include <sys/socket.h>
#include <unistd.h>

#include <cstdint>
#include <cstring>
#include <string>

#include "picklite.h"

namespace rt {
namespace wire {

// Wire-schema version; must match ray_tpu/utils/schema.py PROTOCOL_VERSION
// (tests/test_wire_schema.py cross-checks the two).
constexpr int kProtocolMajor = 2;
constexpr int kProtocolMinor = 3;

// ---------------------------------------------------------------------
// Fastpath record catalog (shm rings + node tunnels, core/fastpath.py).
// Every prefix byte and reply-status flag a native peer may see on a
// record stream MUST appear here AND in utils/schema.py
// (RECORD_PREFIXES / RECORD_FLAGS) — tests/test_wire_schema.py parses
// this block and asserts byte-for-byte parity in both directions, so a
// shipped-but-uncataloged wire entry is a tier-1 failure by
// construction.
constexpr char kRecPrefixTaskPickle = 'P';   // task, C-pickled, no stamp
constexpr char kRecPrefixTaskPacked = 'S';   // task, serialization.pack
constexpr char kRecPrefixTaskPickleTs = 'Q'; // task, C-pickled + u64 stamp
constexpr char kRecPrefixTaskPackedTs = 'R'; // task, packed + u64 stamp
constexpr char kRecPrefixActorPickle = 'A';  // actor, C-pickled + seq hdr
constexpr char kRecPrefixActorPacked = 'C';  // actor, packed + seq hdr
constexpr char kRecPrefixChunk = 'G';        // stream chunk (2.3): 'A'
// header shape (seq slot = per-stream chunk index, same trace bit),
// body <16s task_id><u32 status> + payload
constexpr uint32_t kReplyFlagStamped = 0x100;  // 16-byte stage stamp follows
constexpr uint32_t kReplyFlagSeqed = 0x200;    // u32 echoed seq follows
constexpr uint32_t kReplyFlagTraced = 0x400;   // 25-byte trace leg follows
// Reply status CODES (low bits below the flag bits), cataloged since
// 2.3 — utils/schema.py RECORD_STATUS mirrors these.
constexpr uint32_t kReplyStatusOk = 0;        // payload = packed value
constexpr uint32_t kReplyStatusOkShm = 1;     // sealed in the node arena
constexpr uint32_t kReplyStatusErr = 2;       // payload = pickled error
constexpr uint32_t kReplyStatusNeedSlow = 3;  // declined: RPC path owns it
constexpr uint32_t kReplyStatusChunk = 4;     // 'G' only: inline item
constexpr uint32_t kReplyStatusChunkShm = 5;  // 'G' only: sealed item
// Record-side trace flag (2.1): bit 63 of the u64 t_submit field of
// "Q"/"R"/"A"/"C" records — set = a 25-byte trace leg
// (<16s trace_id><8s span_id><u8 sampled>) follows the record header.
constexpr uint64_t kRecordTraceCtxBit = 1ULL << 63;
constexpr size_t kTraceCtxLen = 25;

inline bool read_exact(int fd, void* buf, size_t n) {
  auto* p = (char*)buf;
  while (n > 0) {
    ssize_t r = ::read(fd, p, n);
    if (r <= 0) return false;
    p += r;
    n -= (size_t)r;
  }
  return true;
}

inline bool write_all(int fd, const void* buf, size_t n) {
  auto* p = (const char*)buf;
  while (n > 0) {
    ssize_t r = ::write(fd, p, n);
    if (r <= 0) return false;
    p += r;
    n -= (size_t)r;
  }
  return true;
}

inline bool read_frame(int fd, std::string* out) {
  uint64_t len;
  if (!read_exact(fd, &len, 8)) return false;
  if (len > (1ULL << 33)) return false;  // sanity: 8 GiB frame cap
  out->resize(len);
  return read_exact(fd, out->data(), len);
}

inline bool write_frame(int fd, const std::string& payload) {
  uint64_t len = payload.size();
  std::string buf;
  buf.reserve(8 + payload.size());
  buf.append((const char*)&len, 8);
  buf.append(payload);
  return write_all(fd, buf.data(), buf.size());
}

inline int dial(const std::string& host, int port) {
  sockaddr_in addr{};
  addr.sin_family = AF_INET;
  addr.sin_port = htons((uint16_t)port);
  if (::inet_pton(AF_INET, host.c_str(), &addr.sin_addr) != 1) {
    // not a numeric address: resolve via getaddrinfo
    addrinfo hints{}, *res = nullptr;
    hints.ai_family = AF_INET;
    hints.ai_socktype = SOCK_STREAM;
    if (::getaddrinfo(host.c_str(), nullptr, &hints, &res) != 0 || !res)
      return -1;
    addr.sin_addr = ((sockaddr_in*)res->ai_addr)->sin_addr;
    ::freeaddrinfo(res);
  }
  int fd = ::socket(AF_INET, SOCK_STREAM, 0);
  if (fd < 0) return -1;
  int one = 1;
  ::setsockopt(fd, IPPROTO_TCP, TCP_NODELAY, &one, sizeof(one));
  if (::connect(fd, (sockaddr*)&addr, sizeof(addr)) != 0) {
    ::close(fd);
    return -1;
  }
  return fd;
}

constexpr size_t kAlign = 64;  // serialization._ALIGN

inline size_t align_up(size_t n) { return (n + kAlign - 1) & ~(kAlign - 1); }

// serialization.pack layout -> value tree
inline picklite::ValuePtr unpack_value(const std::string& packed) {
  using picklite::Value;
  if (packed.size() < 4) throw picklite::Error("short packed value");
  uint32_t meta_len;
  std::memcpy(&meta_len, packed.data(), 4);
  if (4 + (size_t)meta_len > packed.size()) throw picklite::Error("bad meta len");
  auto meta = picklite::loads(packed.substr(4, meta_len));
  if (meta->kind != Value::kTuple || meta->items.size() != 2)
    throw picklite::Error("bad meta tuple");
  auto& sizes = meta->items[0];
  auto& header = meta->items[1];
  std::vector<std::string> buffers;
  size_t off = 4 + meta_len;
  for (auto& sz : sizes->items) {
    off = align_up(off);
    size_t n = (size_t)sz->i;
    if (off + n > packed.size()) throw picklite::Error("buffer overrun");
    buffers.push_back(packed.substr(off, n));
    off += n;
  }
  return picklite::loads(header->s, std::move(buffers));
}

// value tree -> serialization.pack layout (no out-of-band buffers)
inline std::string pack_value(const picklite::Value& v) {
  using picklite::Value;
  std::string header = picklite::dumps(v);
  Value meta;
  meta.kind = Value::kTuple;
  meta.items.push_back(Value::list());
  meta.items.push_back(Value::bytes(header));
  std::string meta_b = picklite::dumps(meta);
  std::string packed;
  uint32_t meta_len = (uint32_t)meta_b.size();
  packed.append((const char*)&meta_len, 4);
  packed.append(meta_b);
  return packed;
}

}  // namespace wire
}  // namespace rt

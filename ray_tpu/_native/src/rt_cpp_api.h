// rt_cpp_api.h — the C++ worker API for ray_tpu.
//
// The native-language task surface (ref equivalent: cpp/ `ray::Task(...)`,
// 9.2k LoC C++ worker API; here tasks are registered by name and invoked
// cross-language from any driver via ray_tpu.cpp_function("name")).
//
// Usage — a worker binary:
//
//   #include "rt_cpp_api.h"
//   rt::ValuePtr Add(std::vector<rt::ValuePtr>& args) {
//     return rt::Value::integer(args.at(0)->i + args.at(1)->i);
//   }
//   RT_REMOTE(Add);
//   int main() { return rt::worker_main(); }
//
// Compile:  g++ -std=c++17 -O2 -I <this dir> my_worker.cc rt_cpp_worker.cc
// Point the cluster at the binary with RT_CPP_WORKER=<path>; then from
// Python:  ray_tpu.cpp_function("Add").remote(2, 3)  ->  5.
#pragma once

#include <functional>
#include <map>
#include <string>
#include <vector>

#include "picklite.h"

namespace rt {

using picklite::Value;
using picklite::ValuePtr;

// A task: receives decoded args, returns the result value. Throw
// std::exception to fail the task (surfaces as TaskError on the driver).
using TaskFn = std::function<ValuePtr(std::vector<ValuePtr>&)>;

// Name -> function registry for this worker binary.
std::map<std::string, TaskFn>& task_registry();

inline void register_task(const std::string& name, TaskFn fn) {
  task_registry()[name] = std::move(fn);
}

struct TaskRegistrar {
  TaskRegistrar(const char* name, TaskFn fn) { register_task(name, std::move(fn)); }
};

// Registers `fn` under its own identifier as the task name.
#define RT_REMOTE(fn) static ::rt::TaskRegistrar rt_reg_##fn(#fn, fn)

// Run the worker execution loop: reads the RT_* env contract the raylet
// sets (RT_WORKER_ID, RT_RAYLET_HOST/PORT, ...), registers with the raylet,
// then serves push_task until the raylet connection drops.
int worker_main();

}  // namespace rt

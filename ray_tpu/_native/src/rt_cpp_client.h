// rt_cpp_client.h — C++ driver API for ray_tpu.
//
// The native driver surface (ref equivalent: cpp/ `ray::Init()` +
// `ray::Task(...).Remote()`): a C++ program connects to a running cluster,
// leases C++ workers through the raylet, and submits tasks registered with
// RT_REMOTE in the cluster's C++ worker binary.
//
//   rt::Client c;
//   c.Connect("127.0.0.1", gcs_port);
//   auto v = c.Call("Add", {rt::Value::integer(2), rt::Value::integer(3)});
//   // v->i == 5
//   c.Close();
//
// Scope: blocking calls, inline results (<= max_inline_object_size), C++
// workers only. Ownership/borrowing of shm objects stays with Python
// drivers; this client is the task-submission surface.
#pragma once

#include <string>
#include <vector>

#include "rt_cpp_api.h"

namespace rt {

class Client {
 public:
  ~Client() { Close(); }

  // Resolve the raylet through the GCS and connect. False on failure.
  bool Connect(const std::string& gcs_host, int gcs_port);

  // Submit func_name(args...) to a C++ worker and wait for the result.
  // On task failure returns nullptr and fills *error (when given).
  ValuePtr Call(const std::string& func_name, std::vector<ValuePtr> args,
                std::string* error = nullptr);

  // Return the cached worker lease and drop connections.
  void Close();

  bool connected() const { return raylet_fd_ >= 0; }

 private:
  bool EnsureWorker(std::string* error);
  ValuePtr Rpc(int fd, const std::string& method, ValuePtr payload,
               std::string* error);

  int raylet_fd_ = -1;
  int worker_fd_ = -1;
  int64_t lease_id_ = -1;
  int64_t next_id_ = 1;
};

}  // namespace rt

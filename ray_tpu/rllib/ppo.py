"""PPO algorithm: config builder + training driver.

TPU-native counterpart of the reference algorithm layer (ref:
rllib/algorithms/algorithm.py:207 step :986 training_step :2004,
algorithm_config.py builder, ppo/ppo.py:362). One train() iteration:
parallel env-runner sampling -> learner-group update -> weight sync,
with episode metrics aggregated across runners.
"""
from __future__ import annotations

import time

import ray_tpu


class PPOConfig:
    """Builder-style config (ref: algorithm_config.py)."""

    def __init__(self):
        self.env_name: str | None = None
        self.env_config: dict = {}
        self.num_env_runners = 2
        self.num_envs_per_runner = 4
        self.rollout_fragment_length = 128
        self.num_learners = 1
        self.lr = 3e-4
        self.gamma = 0.99
        self.lam = 0.95
        self.clip = 0.2
        self.vf_coeff = 0.5
        self.entropy_coeff = 0.01
        self.epochs = 4
        self.minibatches = 4
        self.hidden = 64
        self.seed = 0
        self.collective_backend = "cpu"
        # ConnectorV2 hooks (ref: algorithm_config
        # env_to_module_connector / module_to_env_connector /
        # learner connector): a zero-arg factory OR a pipeline instance
        # (each actor gets its own copy either way)
        self.env_to_module_connector = None
        self.module_to_env_connector = None
        self.learner_connector = None

    def environment(self, env: str, env_config: dict | None = None) -> "PPOConfig":
        self.env_name = env
        self.env_config = dict(env_config or {})
        return self

    def env_runners(self, num_env_runners: int | None = None,
                    num_envs_per_env_runner: int | None = None,
                    rollout_fragment_length: int | None = None,
                    env_to_module_connector=None,
                    module_to_env_connector=None) -> "PPOConfig":
        if num_env_runners is not None:
            self.num_env_runners = num_env_runners
        if num_envs_per_env_runner is not None:
            self.num_envs_per_runner = num_envs_per_env_runner
        if rollout_fragment_length is not None:
            self.rollout_fragment_length = rollout_fragment_length
        if env_to_module_connector is not None:
            self.env_to_module_connector = env_to_module_connector
        if module_to_env_connector is not None:
            self.module_to_env_connector = module_to_env_connector
        return self

    def learners(self, num_learners: int | None = None) -> "PPOConfig":
        if num_learners is not None:
            self.num_learners = num_learners
        return self

    def training(self, *, lr=None, gamma=None, lam=None, clip=None,
                 vf_coeff=None, entropy_coeff=None, epochs=None,
                 minibatches=None, hidden=None) -> "PPOConfig":
        for name, val in (("lr", lr), ("gamma", gamma), ("lam", lam),
                          ("clip", clip), ("vf_coeff", vf_coeff),
                          ("entropy_coeff", entropy_coeff), ("epochs", epochs),
                          ("minibatches", minibatches), ("hidden", hidden)):
            if val is not None:
                setattr(self, name, val)
        return self

    def build(self) -> "PPO":
        if self.env_name is None:
            raise ValueError("PPOConfig.environment(...) is required")
        return PPO(self)


class PPO:
    """(ref: algorithms/algorithm.py Algorithm; also usable as a Tune
    trainable via PPO.as_trainable)."""

    def __init__(self, config: PPOConfig):
        from ray_tpu.rllib.env_runner import EnvRunner
        from ray_tpu.rllib.learner import Learner

        if not ray_tpu.is_initialized():
            ray_tpu.init()
        self.config = config
        from ray_tpu.rllib.connectors import ConnectorV2

        def build_pipe(factory_or_pipe):
            # factories (zero-arg callables) get called per actor; a
            # pipeline INSTANCE is also callable, so detect it by type —
            # each actor still gets its own copy via pickling
            if factory_or_pipe is None or isinstance(factory_or_pipe,
                                                     ConnectorV2):
                return factory_or_pipe
            return factory_or_pipe()

        RunnerCls = ray_tpu.remote(EnvRunner).options(num_cpus=0.5)
        e2m = config.env_to_module_connector
        m2e = config.module_to_env_connector
        self.runners = [
            RunnerCls.remote(
                config.env_name, config.num_envs_per_runner,
                seed=config.seed + 1000 * i, env_config=config.env_config,
                env_to_module=build_pipe(e2m),
                module_to_env=build_pipe(m2e),
            )
            for i in range(config.num_env_runners)
        ]
        self._has_connectors = e2m is not None
        # merge_states needs a pipeline of the same shape; build it once
        self._connector_proto = build_pipe(e2m)
        obs_dim, n_actions = ray_tpu.get(
            self.runners[0].obs_and_action_space.remote(), timeout=120
        )
        learner_cfg = {
            "obs_dim": obs_dim,
            "n_actions": n_actions,
            "hidden": config.hidden,
            "lr": config.lr,
            "gamma": config.gamma,
            "lam": config.lam,
            "clip": config.clip,
            "vf_coeff": config.vf_coeff,
            "entropy_coeff": config.entropy_coeff,
            "epochs": config.epochs,
            "minibatches": config.minibatches,
            "seed": config.seed,
            "collective_backend": config.collective_backend,
            "learner_connector": config.learner_connector,
        }
        LearnerCls = ray_tpu.remote(Learner).options(
            num_cpus=1.0, max_concurrency=2)
        group = f"rl_learners_{id(self)}"
        self.learners = [
            LearnerCls.remote(
                rank, config.num_learners, learner_cfg, group
            )
            for rank in range(config.num_learners)
        ]
        self._iteration = 0
        self._sync_weights()

    def _sync_weights(self):
        weights_ref = self.learners[0].get_weights.remote()
        weights = ray_tpu.get(weights_ref, timeout=300)
        ray_tpu.get([r.set_weights.remote(weights) for r in self.runners], timeout=120)

    def train(self) -> dict:
        """One iteration (ref: Algorithm.step :986): sample in parallel,
        shard rollouts across learners, update, sync."""
        t0 = time.monotonic()
        frag = self.config.rollout_fragment_length
        rollout_refs = [r.sample.remote(frag) for r in self.runners]
        rollouts = ray_tpu.get(rollout_refs, timeout=600)
        n_learn = len(self.learners)
        shards = [rollouts[i::n_learn] for i in range(n_learn)]
        # every learner participates (empty shards still join the sync)
        results = ray_tpu.get(
            [ln.update.remote(shard) for ln, shard in zip(self.learners, shards)],
            timeout=600,
        )
        results = [r for r in results if r["samples"] > 0]
        self._sync_weights()
        if self._has_connectors and len(self.runners) > 1:
            self._sync_connector_states()
        metrics_list = ray_tpu.get(
            [r.episode_metrics.remote() for r in self.runners], timeout=120
        )
        episodes = sum(m.get("episodes", 0) for m in metrics_list)
        means = [m["episode_return_mean"] for m in metrics_list
                 if "episode_return_mean" in m]
        self._iteration += 1
        return {
            "training_iteration": self._iteration,
            "episode_return_mean": sum(means) / len(means) if means else float("nan"),
            "episodes_this_iter": episodes,
            "loss": sum(r["loss"] for r in results) / len(results),
            "num_env_steps_sampled": frag
            * self.config.num_envs_per_runner
            * self.config.num_env_runners,
            "time_this_iter_s": time.monotonic() - t0,
        }

    def _sync_connector_states(self):
        """Merge env-to-module connector states (running obs statistics)
        across runners and re-broadcast, so every runner normalizes with
        the fleet-wide statistics (ref: EnvRunnerGroup connector-state
        aggregation)."""
        proto = self._connector_proto
        states = ray_tpu.get(
            [r.get_connector_state.remote() for r in self.runners],
            timeout=120)
        merged = proto.merge_states([s for s in states if s])
        if merged:
            ray_tpu.get(
                [r.set_connector_state.remote(merged) for r in self.runners],
                timeout=120)

    def get_weights(self):
        return ray_tpu.get(self.learners[0].get_weights.remote(), timeout=120)

    def stop(self):
        for a in self.runners + self.learners:
            try:
                ray_tpu.kill(a)
            except Exception:  # raylint: disable=RT012 — teardown: actor may already be dead
                pass

    @classmethod
    def as_trainable(cls, config: PPOConfig, stop_iters: int = 10):
        """Adapter for Tune (ref: Algorithm is-a Trainable)."""

        def trainable(tune_config: dict):
            from ray_tpu import tune

            cfg = config
            if "lr" in tune_config:
                cfg = cfg.training(lr=tune_config["lr"])
            algo = cfg.build()
            try:
                for _ in range(stop_iters):
                    tune.report(algo.train())
            finally:
                algo.stop()

        return trainable

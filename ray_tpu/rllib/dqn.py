"""DQN: off-policy Q-learning over a replay buffer.

TPU-native counterpart of the reference DQN stack (ref:
rllib/algorithms/dqn/dqn.py + dqn_rainbow_learner.py): double-DQN
targets, Huber loss, target-network syncs, epsilon-greedy env runners,
uniform or prioritized replay. The update is ONE jitted function over a
sampled batch — per-sample TD errors come back for priority updates.
"""
from __future__ import annotations

import time

import numpy as np

import ray_tpu
from ray_tpu.rllib.env_runner import EnvRunner
from ray_tpu.rllib.replay_buffer import PrioritizedReplayBuffer, ReplayBuffer


def q_init(key, obs_dim: int, n_actions: int, hidden: int = 64):
    from ray_tpu.rllib.core import mlp_init

    return {"q": mlp_init(key, [obs_dim, hidden, hidden, n_actions])}


def q_values(params, obs):
    from ray_tpu.rllib.core import mlp_apply

    return mlp_apply(params["q"], obs)


_greedy_jit = None


def _greedy_actions(params, obs):
    """Jitted env-runner hot path: one dispatch per vector-env step."""
    global _greedy_jit
    if _greedy_jit is None:
        import jax
        import jax.numpy as jnp

        _greedy_jit = jax.jit(
            lambda p, o: jnp.argmax(q_values(p, o), axis=-1))
    return _greedy_jit(params, obs)


def make_dqn_update(lr: float, gamma: float):
    """Jitted double-DQN step: online net picks the next action, target
    net evaluates it; Huber loss with importance weights; returns
    per-sample |TD| for prioritized replay."""
    import jax
    import jax.numpy as jnp
    import optax

    optimizer = optax.adam(lr)

    def loss_fn(params, target_params, batch):
        q = q_values(params, batch["obs"])
        qa = q[jnp.arange(q.shape[0]), batch["actions"]]
        next_online = q_values(params, batch["next_obs"])
        next_a = jnp.argmax(next_online, axis=-1)
        next_q = q_values(target_params, batch["next_obs"])
        next_qa = next_q[jnp.arange(next_q.shape[0]), next_a]
        target = batch["rewards"] + gamma * (1.0 - batch["dones"]) * \
            jax.lax.stop_gradient(next_qa)
        td = qa - target
        loss = jnp.mean(batch["weights"] * optax.huber_loss(qa, target))
        return loss, jnp.abs(td)

    @jax.jit
    def update(params, target_params, opt_state, batch):
        (loss, td), grads = jax.value_and_grad(loss_fn, has_aux=True)(
            params, target_params, batch)
        updates, opt_state = optimizer.update(grads, opt_state, params)
        params = optax.apply_updates(params, updates)
        return params, opt_state, loss, td

    return update, optimizer


class DQNEnvRunner(EnvRunner):
    """Epsilon-greedy sampling that returns flat transitions (ref:
    single_agent_env_runner.py under an off-policy algorithm)."""

    def __init__(self, *args, **kwargs):
        super().__init__(*args, **kwargs)
        self.epsilon = 1.0
        # gymnasium >= 1.0 vector envs autoreset on the step AFTER done
        # (NEXT_STEP mode): that step's "transition" is garbage (action
        # ignored, obs pair spans two episodes) and must not enter replay
        self._prev_done = np.zeros(self.num_envs, dtype=bool)

    def set_epsilon(self, eps: float) -> bool:
        self.epsilon = float(eps)
        return True

    def sample(self, num_steps: int) -> dict:
        import jax
        import jax.numpy as jnp

        assert self.params is not None, "set_weights before sample"
        n_actions = int(self.envs.single_action_space.n)
        obs_l, act_l, rew_l, next_l, done_l = [], [], [], [], []
        rng = np.random.default_rng(self.seed * 1_000_003 + self._rng_counter)
        for _ in range(num_steps):
            self._rng_counter += 1
            greedy = np.asarray(
                _greedy_actions(self.params, jnp.asarray(self.obs)))
            explore = rng.random(self.num_envs) < self.epsilon
            random_a = rng.integers(0, n_actions, size=self.num_envs)
            action = np.where(explore, random_a, greedy)
            next_obs, reward, term, trunc, _ = self.envs.step(action)
            # bootstrap through time-limit truncation (only a true terminal
            # zeroes the target), the standard off-policy distinction.
            # Envs that finished LAST step are doing their autoreset step
            # now: record nothing for them (keep = ~prev_done).
            keep = ~self._prev_done
            if keep.any():
                obs_l.append(self.obs[keep])
                act_l.append(action[keep])
                rew_l.append(np.asarray(reward, dtype=np.float32)[keep])
                next_l.append(next_obs[keep])
                done_l.append(np.asarray(term, dtype=np.float32)[keep])
            done = np.logical_or(term, trunc)
            self._ep_returns += np.where(keep, reward, 0.0)
            for i, d in enumerate(done):
                if d and keep[i]:
                    self.completed_returns.append(float(self._ep_returns[i]))
                    self._ep_returns[i] = 0.0
            self._prev_done = done & keep
            self.obs = next_obs
        return {
            "obs": np.concatenate(obs_l).astype(np.float32),
            "actions": np.concatenate(act_l).astype(np.int32),
            "rewards": np.concatenate(rew_l),
            "next_obs": np.concatenate(next_l).astype(np.float32),
            "dones": np.concatenate(done_l),
        }


class DQNConfig:
    """Builder-style config (ref: dqn.py DQNConfig)."""

    def __init__(self):
        self.env_name: str | None = None
        self.env_config: dict = {}
        self.num_env_runners = 2
        self.num_envs_per_runner = 4
        self.rollout_fragment_length = 64
        self.lr = 1e-3
        self.gamma = 0.99
        self.hidden = 64
        self.buffer_capacity = 50_000
        self.prioritized = False
        self.batch_size = 64
        self.train_batches_per_iter = 32
        self.target_update_freq = 200  # in update steps
        self.epsilon_start = 1.0
        self.epsilon_end = 0.05
        self.epsilon_decay_iters = 15
        self.learning_starts = 500  # min buffer size before updates
        self.seed = 0

    def environment(self, env: str, env_config: dict | None = None):
        self.env_name = env
        self.env_config = dict(env_config or {})
        return self

    def env_runners(self, num_env_runners=None, num_envs_per_env_runner=None,
                    rollout_fragment_length=None):
        if num_env_runners is not None:
            self.num_env_runners = num_env_runners
        if num_envs_per_env_runner is not None:
            self.num_envs_per_runner = num_envs_per_env_runner
        if rollout_fragment_length is not None:
            self.rollout_fragment_length = rollout_fragment_length
        return self

    def training(self, *, lr=None, gamma=None, hidden=None,
                 buffer_capacity=None, prioritized=None, batch_size=None,
                 train_batches_per_iter=None, target_update_freq=None,
                 epsilon_decay_iters=None, learning_starts=None):
        for name, val in (
                ("lr", lr), ("gamma", gamma), ("hidden", hidden),
                ("buffer_capacity", buffer_capacity),
                ("prioritized", prioritized), ("batch_size", batch_size),
                ("train_batches_per_iter", train_batches_per_iter),
                ("target_update_freq", target_update_freq),
                ("epsilon_decay_iters", epsilon_decay_iters),
                ("learning_starts", learning_starts)):
            if val is not None:
                setattr(self, name, val)
        return self

    def build(self) -> "DQN":
        if self.env_name is None:
            raise ValueError("DQNConfig.environment(...) is required")
        return DQN(self)


class DQN:
    """Off-policy driver (ref: dqn.py DQN.training_step): parallel
    epsilon-greedy sampling -> replay buffer -> jitted double-DQN updates
    -> periodic target sync -> weight broadcast."""

    def __init__(self, config: DQNConfig):
        import jax

        if not ray_tpu.is_initialized():
            ray_tpu.init()
        self.config = config
        RunnerCls = ray_tpu.remote(DQNEnvRunner).options(num_cpus=0.5)
        self.runners = [
            RunnerCls.remote(
                config.env_name, config.num_envs_per_runner,
                seed=config.seed + 1000 * i, env_config=config.env_config,
            )
            for i in range(config.num_env_runners)
        ]
        obs_dim, n_actions = ray_tpu.get(
            self.runners[0].obs_and_action_space.remote(), timeout=120)
        self.params = q_init(jax.random.PRNGKey(config.seed), obs_dim,
                             n_actions, config.hidden)
        self.target_params = jax.tree.map(lambda x: x, self.params)
        self._update, optimizer = make_dqn_update(config.lr, config.gamma)
        self.opt_state = optimizer.init(self.params)
        buf_cls = PrioritizedReplayBuffer if config.prioritized else ReplayBuffer
        self.buffer = buf_cls(config.buffer_capacity, seed=config.seed)
        self._updates = 0
        self._iteration = 0
        self._sync_weights()

    def _sync_weights(self):
        ray_tpu.get([r.set_weights.remote(self.params) for r in self.runners],
                    timeout=120)

    def _epsilon(self) -> float:
        c = self.config
        frac = min(1.0, self._iteration / max(1, c.epsilon_decay_iters))
        return c.epsilon_start + frac * (c.epsilon_end - c.epsilon_start)

    def train(self) -> dict:
        import jax
        import jax.numpy as jnp

        t0 = time.monotonic()
        c = self.config
        eps = self._epsilon()
        ray_tpu.get([r.set_epsilon.remote(eps) for r in self.runners],
                    timeout=120)
        rollouts = ray_tpu.get(
            [r.sample.remote(c.rollout_fragment_length) for r in self.runners],
            timeout=600)
        for ro in rollouts:
            self.buffer.add_batch(ro)
        losses = []
        if len(self.buffer) >= c.learning_starts:
            for _ in range(c.train_batches_per_iter):
                batch = self.buffer.sample(c.batch_size)
                jb = {k: jnp.asarray(v) for k, v in batch.items()
                      if k != "indices"}
                self.params, self.opt_state, loss, td = self._update(
                    self.params, self.target_params, self.opt_state, jb)
                self.buffer.update_priorities(batch["indices"], np.asarray(td))
                losses.append(float(loss))
                self._updates += 1
                if self._updates % c.target_update_freq == 0:
                    self.target_params = jax.tree.map(lambda x: x, self.params)
        self._sync_weights()
        metrics_list = ray_tpu.get(
            [r.episode_metrics.remote() for r in self.runners], timeout=120)
        means = [m["episode_return_mean"] for m in metrics_list
                 if "episode_return_mean" in m]
        self._iteration += 1
        return {
            "training_iteration": self._iteration,
            "episode_return_mean": (sum(means) / len(means)
                                    if means else float("nan")),
            "episodes_this_iter": sum(m.get("episodes", 0)
                                      for m in metrics_list),
            "loss": sum(losses) / len(losses) if losses else float("nan"),
            "epsilon": eps,
            "buffer_size": len(self.buffer),
            "num_updates": self._updates,
            "time_this_iter_s": time.monotonic() - t0,
        }

    def get_weights(self):
        return self.params

    def stop(self):
        for a in self.runners:
            try:
                ray_tpu.kill(a)
            except Exception:  # raylint: disable=RT012 — teardown: actor may already be dead
                pass

    @classmethod
    def as_trainable(cls, config: "DQNConfig", stop_iters: int = 10):
        def trainable(tune_config: dict):
            from ray_tpu import tune

            cfg = config
            if "lr" in tune_config:
                cfg = cfg.training(lr=tune_config["lr"])
            algo = cfg.build()
            try:
                for _ in range(stop_iters):
                    tune.report(algo.train())
            finally:
                algo.stop()

        return trainable

"""SAC (discrete): twin soft Q critics + entropy-temperature autotuning.

TPU-native counterpart of the reference SAC (ref:
rllib/algorithms/sac/sac.py + sac_torch_learner.py twin-Q / alpha
losses), in the discrete-action form (Christodoulou 2019) matching this
module's gymnasium CartPole-class env surface: expectations over the
action simplex replace the reparameterized sample, so every update is
three fat batched matmuls — exactly what the MXU wants.

Losses per batch (s, a, r, s', d):
  y      = r + gamma (1-d) E_{a'~pi}[ min(Q1t,Q2t)(s',a') - alpha log pi ]
  L_Q    = MSE(Q1(s,a), y) + MSE(Q2(s,a), y)
  L_pi   = E_s E_{a~pi}[ alpha log pi(a|s) - min(Q1,Q2)(s,a) ]
  L_alpha= E_s E_{a~pi}[ -log_alpha (log pi(a|s) + target_entropy) ]
"""

from __future__ import annotations

import time

import numpy as np

import ray_tpu
from ray_tpu.rllib.env_runner import EnvRunner
from ray_tpu.rllib.replay_buffer import ReplayBuffer


def sac_init(key, obs_dim: int, n_actions: int, hidden: int = 64,
             initial_alpha: float = 1.0) -> dict:
    import jax
    import jax.numpy as jnp
    import numpy as _np

    from ray_tpu.rllib.core import mlp_init

    k1, k2, k3 = jax.random.split(key, 3)
    return {
        "pi": mlp_init(k1, [obs_dim, hidden, hidden, n_actions]),
        "q1": mlp_init(k2, [obs_dim, hidden, hidden, n_actions]),
        "q2": mlp_init(k3, [obs_dim, hidden, hidden, n_actions]),
        "log_alpha": jnp.asarray(float(_np.log(initial_alpha))),
    }


def make_sac_update(lr: float, gamma: float, tau: float,
                    target_entropy: float):
    import jax
    import jax.numpy as jnp
    import optax

    from ray_tpu.rllib.core import mlp_apply

    optimizer = optax.adam(lr)

    def heads(params, obs):
        logits = mlp_apply(params["pi"], obs)
        logp = jax.nn.log_softmax(logits)
        q1 = mlp_apply(params["q1"], obs)
        q2 = mlp_apply(params["q2"], obs)
        return logp, q1, q2

    def loss_fn(params, target_params, batch):
        logp, q1, q2 = heads(params, batch["obs"])
        alpha = jnp.exp(params["log_alpha"])
        a = batch["actions"][:, None]

        # --- critic target under the CURRENT policy at s'
        logp_n, _, _ = heads(params, batch["next_obs"])
        q1t = mlp_apply(target_params["q1"], batch["next_obs"])
        q2t = mlp_apply(target_params["q2"], batch["next_obs"])
        pi_n = jnp.exp(logp_n)
        soft_v = (pi_n * (jnp.minimum(q1t, q2t)
                          - jax.lax.stop_gradient(alpha) * logp_n)).sum(-1)
        y = batch["rewards"] + gamma * (1.0 - batch["dones"]) * \
            jax.lax.stop_gradient(soft_v)

        q1_a = jnp.take_along_axis(q1, a, axis=-1)[:, 0]
        q2_a = jnp.take_along_axis(q2, a, axis=-1)[:, 0]
        q_loss = ((q1_a - y) ** 2).mean() + ((q2_a - y) ** 2).mean()

        # --- actor: expectation over the simplex, critics frozen
        pi = jnp.exp(logp)
        q_min = jax.lax.stop_gradient(jnp.minimum(q1, q2))
        pi_loss = (pi * (jax.lax.stop_gradient(alpha) * logp - q_min)) \
            .sum(-1).mean()

        # --- temperature: push policy entropy toward target_entropy
        ent_err = jax.lax.stop_gradient((pi * logp).sum(-1)
                                        + target_entropy)
        alpha_loss = (-params["log_alpha"] * ent_err).mean()
        return q_loss + pi_loss + alpha_loss, (q_loss, pi_loss, alpha)

    @jax.jit
    def update(params, target_params, opt_state, batch):
        (loss, (q_loss, pi_loss, alpha)), grads = jax.value_and_grad(
            loss_fn, has_aux=True)(params, target_params, batch)
        updates, opt_state = optimizer.update(grads, opt_state, params)
        params = optax.apply_updates(params, updates)
        # polyak target update on the critics only
        target_params = {
            "q1": jax.tree.map(lambda t, s: (1 - tau) * t + tau * s,
                               target_params["q1"], params["q1"]),
            "q2": jax.tree.map(lambda t, s: (1 - tau) * t + tau * s,
                               target_params["q2"], params["q2"]),
        }
        return params, target_params, opt_state, loss, q_loss, alpha

    return update, optimizer


_PICK = None  # lazily jitted module-level sampler (one trace cache)


def _pick_action(params, obs, key):
    global _PICK
    if _PICK is None:
        import jax

        from ray_tpu.rllib.core import mlp_apply

        _PICK = jax.jit(lambda p, o, k: jax.random.categorical(
            k, mlp_apply(p["pi"], o)))
    return _PICK(params, obs, key)


class SACEnvRunner(EnvRunner):
    """On-policy stochastic sampling into flat replay transitions (same
    autoreset handling as the DQN runner)."""

    def __init__(self, *args, **kwargs):
        super().__init__(*args, **kwargs)
        self._prev_done = np.zeros(self.num_envs, dtype=bool)

    def sample(self, num_steps: int) -> dict:
        import jax
        import jax.numpy as jnp

        assert self.params is not None, "set_weights before sample"
        pick = _pick_action
        obs_l, act_l, rew_l, next_l, done_l = [], [], [], [], []
        for _ in range(num_steps):
            self._rng_counter += 1
            key = jax.random.PRNGKey(
                self.seed * 1_000_003 + self._rng_counter)
            action = np.asarray(pick(self.params, jnp.asarray(self.obs), key))
            next_obs, reward, term, trunc, _ = self.envs.step(action)
            keep = ~self._prev_done
            if keep.any():
                obs_l.append(self.obs[keep])
                act_l.append(action[keep])
                rew_l.append(np.asarray(reward, dtype=np.float32)[keep])
                next_l.append(next_obs[keep])
                done_l.append(np.asarray(term, dtype=np.float32)[keep])
            done = np.logical_or(term, trunc)
            self._ep_returns += np.where(keep, reward, 0.0)
            for i, d in enumerate(done):
                if d and keep[i]:
                    self.completed_returns.append(float(self._ep_returns[i]))
                    self._ep_returns[i] = 0.0
            self._prev_done = done & keep
            self.obs = next_obs
        return {
            "obs": np.concatenate(obs_l).astype(np.float32),
            "actions": np.concatenate(act_l).astype(np.int32),
            "rewards": np.concatenate(rew_l),
            "next_obs": np.concatenate(next_l).astype(np.float32),
            "dones": np.concatenate(done_l),
        }


class SACConfig:
    """Builder-style config (ref: sac.py SACConfig)."""

    def __init__(self):
        self.env_name: str | None = None
        self.env_config: dict = {}
        self.num_env_runners = 2
        self.num_envs_per_runner = 4
        self.rollout_fragment_length = 64
        self.lr = 3e-4
        self.gamma = 0.99
        self.tau = 0.01
        #: None -> 0.98 * log(n_actions) (the discrete-SAC convention)
        self.target_entropy: float | None = None
        #: starting temperature (the autotuner moves it from here)
        self.initial_alpha = 1.0
        self.buffer_capacity = 100_000
        self.batch_size = 256
        self.learning_starts = 500
        self.train_batches_per_iter = 16
        self.hidden = 64
        self.seed = 0

    def environment(self, env: str, env_config: dict | None = None):
        self.env_name = env
        self.env_config = dict(env_config or {})
        return self

    def env_runners(self, num_env_runners=None, num_envs_per_env_runner=None,
                    rollout_fragment_length=None):
        if num_env_runners is not None:
            self.num_env_runners = num_env_runners
        if num_envs_per_env_runner is not None:
            self.num_envs_per_runner = num_envs_per_env_runner
        if rollout_fragment_length is not None:
            self.rollout_fragment_length = rollout_fragment_length
        return self

    def training(self, *, lr=None, gamma=None, tau=None, target_entropy=None,
                 initial_alpha=None, buffer_capacity=None, batch_size=None,
                 learning_starts=None, train_batches_per_iter=None,
                 hidden=None):
        for name, val in (("lr", lr), ("gamma", gamma), ("tau", tau),
                          ("target_entropy", target_entropy),
                          ("initial_alpha", initial_alpha),
                          ("buffer_capacity", buffer_capacity),
                          ("batch_size", batch_size),
                          ("learning_starts", learning_starts),
                          ("train_batches_per_iter", train_batches_per_iter),
                          ("hidden", hidden)):
            if val is not None:
                setattr(self, name, val)
        return self

    def build(self) -> "SAC":
        if self.env_name is None:
            raise ValueError("SACConfig.environment(...) is required")
        return SAC(self)


class SAC:
    """Off-policy driver (ref: sac.py training_step): stochastic-policy
    sampling -> replay -> twin-critic soft updates with autotuned
    temperature -> weight broadcast."""

    def __init__(self, config: SACConfig):
        import jax
        import numpy as _np

        if not ray_tpu.is_initialized():
            ray_tpu.init()
        self.config = config
        RunnerCls = ray_tpu.remote(SACEnvRunner).options(num_cpus=0.5)
        self.runners = [
            RunnerCls.remote(
                config.env_name, config.num_envs_per_runner,
                seed=config.seed + 1000 * i, env_config=config.env_config,
            )
            for i in range(config.num_env_runners)
        ]
        obs_dim, n_actions = ray_tpu.get(
            self.runners[0].obs_and_action_space.remote(), timeout=120)
        self.params = sac_init(jax.random.PRNGKey(config.seed), obs_dim,
                               n_actions, config.hidden,
                               initial_alpha=config.initial_alpha)
        self.target_params = {
            "q1": jax.tree.map(lambda x: x, self.params["q1"]),
            "q2": jax.tree.map(lambda x: x, self.params["q2"]),
        }
        tgt_h = (config.target_entropy if config.target_entropy is not None
                 else 0.98 * float(_np.log(n_actions)))
        self._update, optimizer = make_sac_update(
            config.lr, config.gamma, config.tau, tgt_h)
        self.opt_state = optimizer.init(self.params)
        self.buffer = ReplayBuffer(config.buffer_capacity, seed=config.seed)
        self._iteration = 0
        self._updates = 0
        self._sync_weights()

    def _sync_weights(self):
        ray_tpu.get([r.set_weights.remote(self.params) for r in self.runners],
                    timeout=120)

    def train(self) -> dict:
        import jax.numpy as jnp

        t0 = time.monotonic()
        c = self.config
        rollouts = ray_tpu.get(
            [r.sample.remote(c.rollout_fragment_length)
             for r in self.runners], timeout=600)
        for ro in rollouts:
            self.buffer.add_batch(ro)
        losses, alphas = [], []
        if len(self.buffer) >= c.learning_starts:
            for _ in range(c.train_batches_per_iter):
                batch = self.buffer.sample(c.batch_size)
                jb = {k: jnp.asarray(v) for k, v in batch.items()
                      if k != "indices"}
                (self.params, self.target_params, self.opt_state,
                 loss, _q_loss, alpha) = self._update(
                    self.params, self.target_params, self.opt_state, jb)
                losses.append(float(loss))
                alphas.append(float(alpha))
                self._updates += 1
        self._sync_weights()
        metrics_list = ray_tpu.get(
            [r.episode_metrics.remote() for r in self.runners], timeout=120)
        means = [m["episode_return_mean"] for m in metrics_list
                 if "episode_return_mean" in m]
        self._iteration += 1
        return {
            "training_iteration": self._iteration,
            "episode_return_mean": (sum(means) / len(means)
                                    if means else float("nan")),
            "episodes_this_iter": sum(m.get("episodes", 0)
                                      for m in metrics_list),
            "loss": sum(losses) / len(losses) if losses else float("nan"),
            "alpha": sum(alphas) / len(alphas) if alphas else float("nan"),
            "buffer_size": len(self.buffer),
            "num_updates": self._updates,
            "time_this_iter_s": time.monotonic() - t0,
        }

    def get_weights(self):
        return self.params

    def stop(self):
        for a in self.runners:
            try:
                ray_tpu.kill(a)
            except Exception:  # raylint: disable=RT012 — teardown: actor may already be dead
                pass

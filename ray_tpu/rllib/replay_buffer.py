"""Replay buffers for off-policy RL.

TPU-native counterpart of the reference buffer layer (ref:
rllib/utils/replay_buffers/replay_buffer.py ReplayBuffer,
prioritized_episode_buffer.py): preallocated numpy rings holding flat
transition batches — sampling returns contiguous arrays ready for one
jitted update (the MXU wants one big batched Q step, not per-transition
work).
"""
from __future__ import annotations

import numpy as np


class ReplayBuffer:
    """Uniform ring buffer over flat transition arrays."""

    def __init__(self, capacity: int, seed: int = 0):
        self.capacity = int(capacity)
        self._rng = np.random.default_rng(seed)
        self._store: dict[str, np.ndarray] | None = None
        self._next = 0
        self._size = 0

    def __len__(self) -> int:
        return self._size

    def add_batch(self, batch: dict) -> None:
        """batch: {name: [N, ...]} transition arrays, all equal length."""
        n = len(next(iter(batch.values())))
        if self._store is None:
            self._store = {
                k: np.zeros((self.capacity,) + np.asarray(v).shape[1:],
                            dtype=np.asarray(v).dtype)
                for k, v in batch.items()
            }
        idx = (self._next + np.arange(n)) % self.capacity
        for k, v in batch.items():
            self._store[k][idx] = np.asarray(v)
        self._next = int((self._next + n) % self.capacity)
        self._size = int(min(self._size + n, self.capacity))
        self._added_indices = idx  # for subclasses (priority init)

    def sample(self, batch_size: int) -> dict:
        idx = self._rng.integers(0, self._size, size=batch_size)
        out = {k: v[idx] for k, v in self._store.items()}
        out["indices"] = idx
        out["weights"] = np.ones(batch_size, dtype=np.float32)
        return out

    def update_priorities(self, indices, priorities) -> None:
        pass  # uniform: no-op (shared API with the prioritized variant)


class PrioritizedReplayBuffer(ReplayBuffer):
    """Proportional prioritized sampling (ref:
    rllib/utils/replay_buffers/prioritized_replay_buffer.py): new
    transitions enter at max priority; sample probability ~ p^alpha with
    importance-sampling weights corrected by beta."""

    def __init__(self, capacity: int, alpha: float = 0.6, beta: float = 0.4,
                 seed: int = 0):
        super().__init__(capacity, seed)
        self.alpha = alpha
        self.beta = beta
        self._prios = np.zeros(capacity, dtype=np.float64)
        self._max_prio = 1.0

    def add_batch(self, batch: dict) -> None:
        super().add_batch(batch)
        self._prios[self._added_indices] = self._max_prio

    def sample(self, batch_size: int) -> dict:
        p = self._prios[: self._size] ** self.alpha
        p = p / p.sum()
        idx = self._rng.choice(self._size, size=batch_size, p=p)
        out = {k: v[idx] for k, v in self._store.items()}
        w = (self._size * p[idx]) ** (-self.beta)
        out["indices"] = idx
        out["weights"] = (w / w.max()).astype(np.float32)
        return out

    def update_priorities(self, indices, priorities) -> None:
        priorities = np.abs(np.asarray(priorities, dtype=np.float64)) + 1e-6
        self._prios[np.asarray(indices)] = priorities
        self._max_prio = max(self._max_prio, float(priorities.max()))

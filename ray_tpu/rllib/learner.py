"""PPO learner + learner group.

TPU-native counterpart of the reference learner stack (ref:
rllib/core/learner/learner.py:107 grads :170, learner_group.py:100
update :234 — remote learner actors with DDP). The update is one jitted
function (GAE + clipped-surrogate PPO over minibatch epochs via lax.scan);
multi-learner data parallelism allreduces gradients through
ray_tpu.collective (XLA collectives on TPU meshes, the cpu fake in tests).
"""
from __future__ import annotations

import numpy as np


def compute_gae(rollout: dict, gamma: float, lam: float) -> dict:
    """Flatten [T, N] rollouts into GAE advantages + returns (numpy; runs
    once per batch on host — the heavy math stays in the jitted update)."""
    rewards, values, dones = rollout["rewards"], rollout["values"], rollout["dones"]
    T, N = rewards.shape
    adv = np.zeros((T, N), dtype=np.float32)
    last_adv = np.zeros(N, dtype=np.float32)
    next_value = rollout["last_value"]
    for t in range(T - 1, -1, -1):
        nonterminal = 1.0 - dones[t].astype(np.float32)
        delta = rewards[t] + gamma * next_value * nonterminal - values[t]
        last_adv = delta + gamma * lam * nonterminal * last_adv
        adv[t] = last_adv
        next_value = values[t]
    returns = adv + values
    flat = lambda a: a.reshape(-1, *a.shape[2:])  # noqa: E731
    return {
        "obs": flat(rollout["obs"]).astype(np.float32),
        "actions": flat(rollout["actions"]).astype(np.int32),
        "logp_old": flat(rollout["logp"]).astype(np.float32),
        "advantages": flat(adv).astype(np.float32),
        "returns": flat(returns).astype(np.float32),
    }


def make_ppo_update(clip: float, vf_coeff: float, entropy_coeff: float,
                    lr: float, epochs: int, minibatches: int):
    """Build the jitted multi-epoch PPO update (ref: ppo.py training_step
    :388 + torch_learner grads, fused here into one compiled fn)."""
    import jax
    import jax.numpy as jnp
    import optax

    from ray_tpu.rllib.core import policy_logits, value_fn

    optimizer = optax.adam(lr)

    def loss_fn(params, mb):
        logits = policy_logits(params, mb["obs"])
        logp_all = jax.nn.log_softmax(logits)
        logp = logp_all[jnp.arange(mb["actions"].shape[0]), mb["actions"]]
        ratio = jnp.exp(logp - mb["logp_old"])
        adv = mb["advantages"]
        adv = (adv - adv.mean()) / (adv.std() + 1e-8)
        pg = -jnp.minimum(ratio * adv, jnp.clip(ratio, 1 - clip, 1 + clip) * adv).mean()
        v = value_fn(params, mb["obs"])
        vf = ((v - mb["returns"]) ** 2).mean()
        entropy = -(jnp.exp(logp_all) * logp_all).sum(-1).mean()
        return pg + vf_coeff * vf - entropy_coeff * entropy, (pg, vf, entropy)

    def update(params, opt_state, batch, perm_key):
        n = batch["obs"].shape[0]
        mb_size = n // minibatches

        def epoch_step(carry, key):
            params, opt_state = carry
            perm = jax.random.permutation(key, n)

            def mb_step(carry, i):
                params, opt_state = carry
                idx = jax.lax.dynamic_slice_in_dim(perm, i * mb_size, mb_size)
                mb = {k: v[idx] for k, v in batch.items()}
                (loss, aux), grads = jax.value_and_grad(loss_fn, has_aux=True)(params, mb)
                updates, opt_state = optimizer.update(grads, opt_state, params)
                params = optax.apply_updates(params, updates)
                return (params, opt_state), loss

            carry, losses = jax.lax.scan(
                mb_step, (params, opt_state), jnp.arange(minibatches)
            )
            return carry, losses.mean()

        keys = jax.random.split(perm_key, epochs)
        (params, opt_state), losses = jax.lax.scan(
            epoch_step, (params, opt_state), keys
        )
        return params, opt_state, losses.mean()

    return jax.jit(update), optimizer


class Learner:
    """Actor hosting one PPO learner replica (ref: learner.py:107).
    With world_size > 1, replicas sync after each local update by
    averaging BOTH params and float optimizer state (Adam moments) via
    the collective backend — integer state (step counts) stays local
    since schedules are identical across ranks."""

    def __init__(self, rank: int, world_size: int, config: dict,
                 group_name: str | None = None):
        import jax

        from ray_tpu.utils.device import configure_jax

        configure_jax()
        self.rank = rank
        self.world_size = world_size
        self.config = config
        self.group_name = group_name
        if world_size > 1:
            from ray_tpu import collective

            collective.init_collective_group(
                world_size, rank, backend=config.get("collective_backend", "cpu"),
                group_name=group_name or "rl_learners",
            )
        key = jax.random.PRNGKey(config.get("seed", 0))
        from ray_tpu.rllib.core import policy_init

        self.params = policy_init(
            key, config["obs_dim"], config["n_actions"], config.get("hidden", 64)
        )
        self._update, optimizer = make_ppo_update(
            clip=config.get("clip", 0.2),
            vf_coeff=config.get("vf_coeff", 0.5),
            entropy_coeff=config.get("entropy_coeff", 0.01),
            lr=config.get("lr", 3e-4),
            epochs=config.get("epochs", 4),
            minibatches=config.get("minibatches", 4),
        )
        self.opt_state = optimizer.init(self.params)
        self._step = 0
        # learner ConnectorV2 pipeline (ref: the learner connector stage):
        # applied to the host-side train batch after GAE, before device put
        lc = config.get("learner_connector")
        from ray_tpu.rllib.connectors import ConnectorCtx, ConnectorV2

        self.learner_pipe = (
            lc if isinstance(lc, ConnectorV2) or lc is None else lc())
        self._learner_ctx = ConnectorCtx(phase="learner")

    def get_weights(self):
        return self.params

    def update(self, rollouts: list[dict]) -> dict:
        """One training step over this learner's share of rollouts. A rank
        with an empty shard still participates in the sync (every rank must
        enter the collective or the group deadlocks)."""
        import jax
        import jax.numpy as jnp
        import numpy as np

        loss = 0.0
        samples = 0
        if rollouts:
            batches = [
                compute_gae(r, self.config.get("gamma", 0.99),
                            self.config.get("lam", 0.95))
                for r in rollouts
            ]
            batch = {k: np.concatenate([b[k] for b in batches]) for k in batches[0]}
            if self.learner_pipe is not None:
                batch = self.learner_pipe(batch, self._learner_ctx)
            batch = {k: jnp.asarray(v) for k, v in batch.items()}
            self._step += 1
            key = jax.random.PRNGKey(self.config.get("seed", 0) * 7919 + self._step)
            self.params, self.opt_state, loss = self._update(
                self.params, self.opt_state, batch, key
            )
            loss = float(loss)
            samples = int(batch["obs"].shape[0])
        if self.world_size > 1:
            from ray_tpu import collective

            group = self.group_name or "rl_learners"

            def sync(leaf):
                # float state (params + Adam moments) averages across
                # ranks; integer state (step counts) is rank-identical
                if hasattr(leaf, "dtype") and jnp.issubdtype(leaf.dtype, jnp.floating):
                    return collective.allreduce(leaf, group_name=group) / self.world_size
                return leaf

            self.params = jax.tree_util.tree_map(sync, self.params)
            self.opt_state = jax.tree_util.tree_map(sync, self.opt_state)
        return {"loss": loss, "samples": samples}

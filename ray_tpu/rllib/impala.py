"""IMPALA: asynchronous sampling with V-trace off-policy correction.

TPU-native counterpart of the reference IMPALA (ref:
rllib/algorithms/impala/impala.py + the V-trace math from
impala/vtrace_*.py, Espeholt et al. 2018): env-runners sample
continuously with whatever policy they last received — the driver never
blocks the learner on the slowest runner — and the learner corrects for
the resulting policy lag with truncated importance weights (rho/c bars).
Weights broadcast every ``broadcast_interval`` consumed batches, so
runner policies are deliberately stale in between: exactly the regime
V-trace exists for.
"""

from __future__ import annotations

import time
from functools import partial

import ray_tpu


def vtrace_returns(behavior_logp, target_logp, rewards, values, last_value,
                   dones, *, gamma: float, rho_bar: float = 1.0,
                   c_bar: float = 1.0):
    """V-trace targets + policy-gradient advantages over [T, N] arrays
    (jax; runs inside the learner's jitted update)."""
    import jax.numpy as jnp
    from jax import lax

    rho = jnp.minimum(jnp.exp(target_logp - behavior_logp), rho_bar)
    c = jnp.minimum(rho, c_bar)
    not_done = 1.0 - dones.astype(jnp.float32)
    next_values = jnp.concatenate([values[1:], last_value[None]], axis=0)
    deltas = rho * (rewards + gamma * not_done * next_values - values)

    def back(acc, xs):
        delta_t, c_t, nd_t = xs
        acc = delta_t + gamma * nd_t * c_t * acc
        return acc, acc

    _, vs_minus_v = lax.scan(
        back, jnp.zeros_like(last_value), (deltas, c, not_done),
        reverse=True)
    vs = values + vs_minus_v
    next_vs = jnp.concatenate([vs[1:], last_value[None]], axis=0)
    pg_adv = rho * (rewards + gamma * not_done * next_vs - values)
    return vs, pg_adv


def make_impala_update(lr: float, gamma: float, vf_coeff: float,
                       entropy_coeff: float, rho_bar: float, c_bar: float):
    import jax
    import jax.numpy as jnp
    import optax

    from ray_tpu.rllib.core import policy_logits, value_fn

    optimizer = optax.adam(lr)

    def loss_fn(params, batch):
        T, N = batch["actions"].shape
        obs = batch["obs"]  # [T, N, D]
        logits = policy_logits(params, obs)
        logp_all = jax.nn.log_softmax(logits)
        target_logp = jnp.take_along_axis(
            logp_all, batch["actions"][..., None], axis=-1)[..., 0]
        values = value_fn(params, obs)
        vs, pg_adv = vtrace_returns(
            batch["logp"], target_logp, batch["rewards"], values,
            value_fn(params, batch["last_obs"]), batch["dones"],
            gamma=gamma, rho_bar=rho_bar, c_bar=c_bar)
        vs = jax.lax.stop_gradient(vs)
        pg_adv = jax.lax.stop_gradient(pg_adv)
        pi_loss = -(target_logp * pg_adv).mean()
        vf_loss = 0.5 * ((values - vs) ** 2).mean()
        entropy = -(jnp.exp(logp_all) * logp_all).sum(-1).mean()
        return pi_loss + vf_coeff * vf_loss - entropy_coeff * entropy

    @jax.jit
    def update(params, opt_state, batch):
        loss, grads = jax.value_and_grad(loss_fn)(params, batch)
        updates, opt_state = optimizer.update(grads, opt_state, params)
        return optax.apply_updates(params, updates), opt_state, loss

    return update, optimizer


class IMPALAConfig:
    """Builder-style config (ref: impala.py IMPALAConfig)."""

    def __init__(self):
        self.env_name: str | None = None
        self.env_config: dict = {}
        self.num_env_runners = 2
        self.num_envs_per_runner = 4
        self.rollout_fragment_length = 64
        self.lr = 5e-4
        self.gamma = 0.99
        self.vf_coeff = 0.5
        self.entropy_coeff = 0.01
        self.rho_bar = 1.0
        self.c_bar = 1.0
        #: consumed batches between weight broadcasts (staleness window)
        self.broadcast_interval = 1
        #: batches consumed per train() call
        self.batches_per_iter = 4
        self.hidden = 64
        self.seed = 0

    def environment(self, env: str, env_config: dict | None = None):
        self.env_name = env
        self.env_config = dict(env_config or {})
        return self

    def env_runners(self, num_env_runners=None, num_envs_per_env_runner=None,
                    rollout_fragment_length=None):
        if num_env_runners is not None:
            self.num_env_runners = num_env_runners
        if num_envs_per_env_runner is not None:
            self.num_envs_per_runner = num_envs_per_env_runner
        if rollout_fragment_length is not None:
            self.rollout_fragment_length = rollout_fragment_length
        return self

    def training(self, *, lr=None, gamma=None, vf_coeff=None,
                 entropy_coeff=None, rho_bar=None, c_bar=None,
                 broadcast_interval=None, batches_per_iter=None, hidden=None):
        for name, val in (("lr", lr), ("gamma", gamma),
                          ("vf_coeff", vf_coeff),
                          ("entropy_coeff", entropy_coeff),
                          ("rho_bar", rho_bar), ("c_bar", c_bar),
                          ("broadcast_interval", broadcast_interval),
                          ("batches_per_iter", batches_per_iter),
                          ("hidden", hidden)):
            if val is not None:
                setattr(self, name, val)
        return self

    def _build_update(self):
        """(update_fn, optimizer) — subclass hook (APPO swaps the loss)."""
        return make_impala_update(
            self.lr, self.gamma, self.vf_coeff, self.entropy_coeff,
            self.rho_bar, self.c_bar)

    def build(self) -> "IMPALA":
        if self.env_name is None:
            raise ValueError("IMPALAConfig.environment(...) is required")
        return IMPALA(self)


class IMPALA:
    """Async driver (ref: impala.py training_step): a sample request is
    ALWAYS in flight on every runner; the learner consumes whichever
    finishes first and only rebroadcasts weights every
    broadcast_interval batches."""

    def __init__(self, config: IMPALAConfig):
        import jax

        from ray_tpu.rllib.core import policy_init
        from ray_tpu.rllib.env_runner import EnvRunner

        if not ray_tpu.is_initialized():
            ray_tpu.init()
        self.config = config
        RunnerCls = ray_tpu.remote(EnvRunner).options(num_cpus=0.5)
        self.runners = [
            RunnerCls.remote(
                config.env_name, config.num_envs_per_runner,
                seed=config.seed + 1000 * i, env_config=config.env_config,
            )
            for i in range(config.num_env_runners)
        ]
        obs_dim, n_actions = ray_tpu.get(
            self.runners[0].obs_and_action_space.remote(), timeout=120)
        self.params = policy_init(jax.random.PRNGKey(config.seed), obs_dim,
                                  n_actions, config.hidden)
        self._update, optimizer = config._build_update()
        self.opt_state = optimizer.init(self.params)
        self._iteration = 0
        self._consumed = 0
        ray_tpu.get([r.set_weights.remote(self.params) for r in self.runners],
                    timeout=120)
        # launch the standing sample requests (the async part)
        self._inflight = {
            runner.sample.remote(config.rollout_fragment_length): runner
            for runner in self.runners
        }

    def train(self) -> dict:
        import jax.numpy as jnp

        t0 = time.monotonic()
        c = self.config
        losses = []
        for _ in range(c.batches_per_iter):
            ready, _ = ray_tpu.wait(list(self._inflight), num_returns=1,
                                    timeout=600)
            ref = ready[0]
            runner = self._inflight.pop(ref)
            rollout = ray_tpu.get(ref, timeout=60)
            # relaunch IMMEDIATELY with the runner's current (stale-ok)
            # policy — sampling never waits for the learner
            self._inflight[runner.sample.remote(
                c.rollout_fragment_length)] = runner
            batch = {
                "obs": jnp.asarray(rollout["obs"]),
                "actions": jnp.asarray(rollout["actions"]),
                "logp": jnp.asarray(rollout["logp"]),
                "rewards": jnp.asarray(rollout["rewards"]),
                "dones": jnp.asarray(rollout["dones"]),
                "last_obs": jnp.asarray(rollout["last_obs"]),
            }
            self.params, self.opt_state, loss = self._update(
                self.params, self.opt_state, batch)
            losses.append(float(loss))
            self._consumed += 1
            if self._consumed % c.broadcast_interval == 0:
                # fire-and-forget broadcast: staleness is by design
                # (IMPALA corrects off-policy drift with V-trace), so a
                # lost update is repaired by the next broadcast
                runner.set_weights.remote(self.params)  # raylint: disable=RT003
                for other in self.runners:
                    if other is not runner:
                        other.set_weights.remote(self.params)  # raylint: disable=RT003
        metrics_list = ray_tpu.get(
            [r.episode_metrics.remote() for r in self.runners], timeout=120)
        means = [m["episode_return_mean"] for m in metrics_list
                 if "episode_return_mean" in m]
        self._iteration += 1
        return {
            "training_iteration": self._iteration,
            "episode_return_mean": (sum(means) / len(means)
                                    if means else float("nan")),
            "episodes_this_iter": sum(m.get("episodes", 0)
                                      for m in metrics_list),
            "loss": sum(losses) / len(losses) if losses else float("nan"),
            "batches_consumed": self._consumed,
            "time_this_iter_s": time.monotonic() - t0,
        }

    def get_weights(self):
        return self.params

    def stop(self):
        for a in self.runners:
            try:
                ray_tpu.kill(a)
            except Exception:  # raylint: disable=RT012 — teardown: actor may already be dead
                pass

"""Multi-agent environments and env runners.

TPU-native counterpart of the reference multi-agent layer (ref:
rllib/env/multi_agent_env.py MultiAgentEnv,
rllib/env/multi_agent_env_runner.py MultiAgentEnvRunner): an env steps a
DICT of per-agent actions and returns per-agent observations/rewards;
the runner maps agents onto policies (policy_mapping_fn) and returns one
PPO-format rollout per POLICY, so per-policy learners consume them with
the existing single-agent update path.
"""
from __future__ import annotations

import numpy as np


class MultiAgentEnv:
    """Dict-keyed env API (ref: multi_agent_env.py). Subclasses define:

    - ``agents``: list of agent ids
    - ``reset(seed) -> obs_dict``
    - ``step(action_dict) -> (obs, rewards, terminateds, truncateds, infos)``
      where each is a per-agent dict and terminateds may carry "__all__".
    - ``observation_space_shape(agent_id)``, ``n_actions(agent_id)``
    """

    agents: list = []

    def reset(self, seed=None):
        raise NotImplementedError

    def step(self, action_dict: dict):
        raise NotImplementedError

    def observation_space_shape(self, agent_id) -> tuple:
        raise NotImplementedError

    def n_actions(self, agent_id) -> int:
        raise NotImplementedError


class MultiAgentEnvRunner:
    """Actor sampling a MultiAgentEnv with per-policy networks (ref:
    multi_agent_env_runner.py:  sample() returns per-policy batches).

    env_maker: () -> MultiAgentEnv (cloudpickled into the actor)
    policy_mapping_fn: agent_id -> policy_id (default: shared policy)
    """

    def __init__(self, env_maker, policy_mapping_fn=None, seed: int = 0):
        from ray_tpu.utils.device import configure_jax

        configure_jax()
        self.env = env_maker()
        self.map_fn = policy_mapping_fn or (lambda aid: "default")
        self.seed = seed
        self._rng_counter = 0
        self._episode_counter = 0
        self.policies: dict = {}  # policy_id -> params
        self.obs = self.env.reset(seed=seed)
        self._dead: set = set()  # agents terminated before "__all__"
        self._ep_returns = {a: 0.0 for a in self.env.agents}
        self.completed_returns: dict = {a: [] for a in self.env.agents}

    def policy_ids(self) -> list:
        return sorted({self.map_fn(a) for a in self.env.agents})

    def spaces(self) -> dict:
        """policy_id -> (obs_dim, n_actions); shared policies must have
        homogeneous spaces (checked here, loudly)."""
        out: dict = {}
        for a in self.env.agents:
            pid = self.map_fn(a)
            dims = (int(np.prod(self.env.observation_space_shape(a))),
                    int(self.env.n_actions(a)))
            if pid in out and out[pid] != dims:
                raise ValueError(
                    f"policy {pid!r} maps agents with different spaces: "
                    f"{out[pid]} vs {dims} (agent {a!r})")
            out[pid] = dims
        return out

    def set_weights(self, weights: dict) -> bool:
        """weights: policy_id -> params."""
        self.policies.update(weights)
        return True

    def sample(self, num_steps: int) -> dict:
        """Collect num_steps env steps; returns policy_id -> rollout in the
        single-agent PPO format ([T, N=#agents-of-policy, ...]).

        Per step, agents are batched BY POLICY into one sample_action call
        (one jit dispatch per policy, not per agent). Agents that
        terminate before "__all__" stop acting; their remaining rows are
        masked (done=True, reward 0), so GAE never bootstraps across a
        dead agent's gap."""
        import jax
        import jax.numpy as jnp

        from ray_tpu.rllib.core import sample_action, value_fn

        agents = list(self.env.agents)
        agent_index = {a: i for i, a in enumerate(agents)}
        by_policy: dict = {}
        for a in agents:
            by_policy.setdefault(self.map_fn(a), []).append(a)
        per_agent: dict = {a: {"obs": [], "actions": [], "logp": [],
                               "values": [], "rewards": [], "dones": []}
                           for a in agents}
        dead = self._dead  # persists across sample() calls mid-episode
        zero_obs = {a: np.zeros(self.env.observation_space_shape(a),
                                np.float32) for a in agents}
        for _ in range(num_steps):
            self._rng_counter += 1
            actions, logps, values = {}, {}, {}
            for pid, members in by_policy.items():
                live = [a for a in members if a not in dead]
                if not live:
                    continue
                params = self.policies[pid]
                key = jax.random.PRNGKey(
                    self.seed * 1_000_003 + self._rng_counter * 131
                    + agent_index[live[0]])
                ob = jnp.asarray(np.stack(
                    [np.asarray(self.obs[a], np.float32) for a in live]))
                act, logp, val = sample_action(params, ob, key)
                for j, a in enumerate(live):
                    actions[a] = int(np.asarray(act)[j])
                    logps[a] = float(np.asarray(logp)[j])
                    values[a] = float(np.asarray(val)[j])
            next_obs, rewards, terms, truncs, _ = self.env.step(actions)
            done_all = terms.get("__all__", False) or truncs.get("__all__", False)
            for a in agents:
                st = per_agent[a]
                if a in dead:
                    # padding row: zero reward, done — inert under GAE
                    st["obs"].append(st["obs"][-1] if st["obs"]
                                     else zero_obs[a])
                    st["actions"].append(0)
                    st["logp"].append(0.0)
                    st["values"].append(0.0)
                    st["rewards"].append(0.0)
                    st["dones"].append(True)
                    continue
                d = bool(terms.get(a, False) or truncs.get(a, False) or done_all)
                st["obs"].append(np.asarray(self.obs[a], np.float32))
                st["actions"].append(actions[a])
                st["logp"].append(logps[a])
                st["values"].append(values[a])
                st["rewards"].append(float(rewards.get(a, 0.0)))
                st["dones"].append(d)
                self._ep_returns[a] += float(rewards.get(a, 0.0))
                if d:
                    self.completed_returns[a].append(self._ep_returns[a])
                    self._ep_returns[a] = 0.0
                if d and not done_all:
                    dead.add(a)
            if done_all:
                # Deterministically seeded mid-run resets: reset() with no
                # seed pulls OS entropy (np.random.default_rng(None)),
                # making every sample() run — and any learning test built
                # on it — nondeterministic run to run.
                self._episode_counter += 1
                try:
                    self.obs = self.env.reset(
                        seed=self.seed * 1_000_003 + self._episode_counter)
                except TypeError:  # env whose reset() takes no seed
                    self.obs = self.env.reset()
                dead.clear()
            else:
                # envs may omit finished agents from their obs dicts
                self.obs = {a: next_obs.get(a, zero_obs[a]) for a in agents}

        # bootstrap values for GAE from the CURRENT obs (zero for dead
        # agents — their last recorded row is done=True anyway)
        out: dict = {}
        for pid, members in by_policy.items():
            params = self.policies[pid]
            stacked = {
                k: np.stack(
                    [np.asarray(per_agent[a][k]) for a in members], axis=1)
                for k in ("obs", "actions", "logp", "values", "rewards",
                          "dones")
            }
            last_obs = jnp.asarray(
                np.stack([np.asarray(self.obs[a], np.float32)
                          for a in members]))
            last_val = np.asarray(value_fn(params, last_obs))
            alive_mask = np.array([a not in dead for a in members])
            stacked["last_value"] = np.where(alive_mask, last_val, 0.0).astype(
                np.float32)
            stacked["actions"] = stacked["actions"].astype(np.int32)
            stacked["rewards"] = stacked["rewards"].astype(np.float32)
            stacked["logp"] = stacked["logp"].astype(np.float32)
            stacked["values"] = stacked["values"].astype(np.float32)
            out[pid] = stacked
        return out

    def episode_metrics(self) -> dict:
        out = {}
        for a, rets in self.completed_returns.items():
            if rets:
                out[str(a)] = {"episodes": len(rets),
                               "episode_return_mean": float(np.mean(rets))}
            self.completed_returns[a] = []
        return out

"""EnvRunner: actor that samples episodes with the current policy.

TPU-native counterpart of the reference env-runner layer (ref:
rllib/env/single_agent_env_runner.py:68 sample :149, env_runner_group.py:71
sync_weights :570): gymnasium vector envs stepped with a jitted
sample_action; weights arrive by broadcast from the learner group.
"""
from __future__ import annotations

import numpy as np


class EnvRunner:
    def __init__(self, env_name: str, num_envs: int = 1, seed: int = 0,
                 env_config: dict | None = None, env_to_module=None,
                 module_to_env=None):
        import gymnasium as gym

        from ray_tpu.utils.device import configure_jax

        configure_jax()
        self.envs = gym.vector.SyncVectorEnv(
            [lambda i=i: gym.make(env_name, **(env_config or {}))
             for i in range(num_envs)]
        )
        self.num_envs = num_envs
        self.seed = seed
        self._rng_counter = 0
        self.params = None
        self.obs, _ = self.envs.reset(seed=seed)
        self._ep_returns = np.zeros(num_envs)
        self.completed_returns: list[float] = []
        # ConnectorV2 pipelines (ref: env_to_module_connector /
        # module_to_env_connector on the reference env runner); the module
        # AND the returned rollout see connector-processed observations,
        # so the learner trains on exactly what the policy acted on
        from ray_tpu.rllib.connectors import ConnectorCtx

        self.env_to_module = env_to_module
        self.module_to_env = module_to_env
        self._e2m_ctx = ConnectorCtx(phase="env_to_module", num_envs=num_envs)
        self._m2e_ctx = ConnectorCtx(phase="module_to_env", num_envs=num_envs)

    def _module_obs(self, obs):
        if self.env_to_module is None:
            return np.asarray(obs)
        return self.env_to_module(obs, self._e2m_ctx)

    def set_weights(self, params) -> bool:
        self.params = params
        return True

    # -- connector state sync (ref: EnvRunnerGroup merging env-to-module
    # connector states each iteration, then re-broadcasting) -------------
    def get_connector_state(self) -> dict:
        if self.env_to_module is None:
            return {}
        return self.env_to_module.get_state()

    def set_connector_state(self, state: dict) -> bool:
        if self.env_to_module is not None and state:
            self.env_to_module.set_state(state)
        return True

    def sample(self, num_steps: int) -> dict:
        """Collect num_steps per env; returns flat rollout arrays with
        bootstrap values for GAE (computed learner-side)."""
        import jax

        from ray_tpu.rllib.core import sample_action, value_fn

        assert self.params is not None, "set_weights before sample"
        obs_l, act_l, logp_l, val_l, rew_l, done_l = [], [], [], [], [], []
        for _ in range(num_steps):
            self._rng_counter += 1
            key = jax.random.PRNGKey(self.seed * 1_000_003 + self._rng_counter)
            mobs = self._module_obs(self.obs)
            action, logp, value = sample_action(self.params, mobs, key)
            action = np.asarray(action)
            # the env gets the connector-processed (e.g. clipped) action,
            # but the rollout stores the SAMPLED one — logp corresponds to
            # the sample, and a clipped action under the sampled logp
            # would bias PPO importance ratios (ref: RLlib trains on the
            # unclipped action, sends the clipped one to the env)
            env_action = action
            if self.module_to_env is not None:
                env_action = np.asarray(
                    self.module_to_env(action, self._m2e_ctx))
            next_obs, reward, term, trunc, _ = self.envs.step(env_action)
            done = np.logical_or(term, trunc)
            obs_l.append(mobs)
            act_l.append(action)
            logp_l.append(np.asarray(logp))
            val_l.append(np.asarray(value))
            rew_l.append(np.asarray(reward, dtype=np.float32))
            done_l.append(done)
            self._ep_returns += reward
            for i, d in enumerate(done):
                if d:
                    self.completed_returns.append(float(self._ep_returns[i]))
                    self._ep_returns[i] = 0.0
            self.obs = next_obs
        # bootstrap under the SAME observation transform the policy saw
        # (update=False would be ideal mid-connector, but one extra batch
        # of running-stat updates is harmless and keeps the code simple)
        last_mobs = self._module_obs(self.obs)
        last_value = np.asarray(value_fn(self.params, last_mobs))
        return {
            "obs": np.stack(obs_l),          # [T, N, obs_dim]
            "actions": np.stack(act_l),      # [T, N]
            "logp": np.stack(logp_l),
            "values": np.stack(val_l),
            "rewards": np.stack(rew_l),
            "dones": np.stack(done_l),
            "last_value": last_value,        # [N]
            # bootstrap OBS so off-policy learners (V-trace) can evaluate
            # it under the CURRENT policy rather than the behavior one
            "last_obs": np.asarray(last_mobs),
        }

    def episode_metrics(self) -> dict:
        rets = self.completed_returns
        self.completed_returns = []
        if not rets:
            return {"episodes": 0}
        return {
            "episodes": len(rets),
            "episode_return_mean": float(np.mean(rets)),
            "episode_return_max": float(np.max(rets)),
        }

    def obs_and_action_space(self) -> tuple[int, int]:
        return (
            int(np.prod(self.envs.single_observation_space.shape)),
            int(self.envs.single_action_space.n),
        )

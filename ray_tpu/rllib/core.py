"""RLModule-equivalent: pure-jax policy/value networks.

TPU-native counterpart of the reference RLModule layer (ref:
rllib/core/rl_module/rl_module.py, torch default impls
core/rl_module/torch/) — here a functional jax pytree + jitted forward
fns instead of torch nn.Modules: params are plain dicts that ship through
the object store and allreduce cleanly.
"""
from __future__ import annotations

import jax
import jax.numpy as jnp
import numpy as np


def mlp_init(key, sizes: list[int]) -> list[dict]:
    params = []
    for i, (d_in, d_out) in enumerate(zip(sizes[:-1], sizes[1:])):
        key, sub = jax.random.split(key)
        params.append({
            "w": jax.random.normal(sub, (d_in, d_out)) * np.sqrt(2.0 / d_in),
            "b": jnp.zeros(d_out),
        })
    return params


def mlp_apply(params: list[dict], x, activate_last: bool = False):
    for i, layer in enumerate(params):
        x = x @ layer["w"] + layer["b"]
        if i < len(params) - 1 or activate_last:
            x = jnp.tanh(x)
    return x


def policy_init(key, obs_dim: int, n_actions: int, hidden: int = 64) -> dict:
    """Separate policy and value heads (the reference's default PPO module
    shape)."""
    k1, k2 = jax.random.split(key)
    return {
        "pi": mlp_init(k1, [obs_dim, hidden, hidden, n_actions]),
        "vf": mlp_init(k2, [obs_dim, hidden, hidden, 1]),
    }


def policy_logits(params: dict, obs):
    return mlp_apply(params["pi"], obs)


def value_fn(params: dict, obs):
    return mlp_apply(params["vf"], obs)[..., 0]


@jax.jit
def sample_action(params: dict, obs, key):
    """Categorical sample + logp + value in one jitted call (the env-runner
    hot path)."""
    logits = policy_logits(params, obs)
    action = jax.random.categorical(key, logits)
    logp = jax.nn.log_softmax(logits)[jnp.arange(obs.shape[0]), action]
    value = value_fn(params, obs)
    return action, logp, value

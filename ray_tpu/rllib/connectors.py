"""ConnectorV2: composable transform pipelines between env, module, and
learner.

TPU-native counterpart of the reference connector layer (ref:
rllib/connectors/connector_v2.py:35 ConnectorV2,
connector_pipeline_v2.py:18 ConnectorPipelineV2, and the
env_to_module / module_to_env / learner default pipelines): small pure
callables ``(batch, ctx) -> batch`` that own optional state, composed
into mutable pipelines with insert/remove surgery. Where the reference
threads episode objects through, here batches are flat numpy dicts /
arrays — the shapes the jitted sample/update fns consume directly, so a
connector never forces a host round-trip of its own.

Stateful connectors (NormalizeObservations) expose get/set/merge state so
an algorithm can aggregate running statistics across env-runner actors
each iteration and re-broadcast (ref: env_runner_group sync of connector
states).
"""
from __future__ import annotations

from dataclasses import dataclass, field
from typing import Any, Callable

import numpy as np


@dataclass
class ConnectorCtx:
    """Call-site context (ref: ConnectorV2's rl_module/explore kwargs)."""

    phase: str = "env_to_module"  # or "module_to_env" / "learner"
    num_envs: int = 1
    extra: dict = field(default_factory=dict)


class ConnectorV2:
    """One transform stage. Subclasses override __call__; name defaults to
    the class name (pipeline surgery addresses stages by name)."""

    @property
    def name(self) -> str:
        return type(self).__name__

    def __call__(self, batch: Any, ctx: ConnectorCtx) -> Any:
        raise NotImplementedError

    # -- optional state (running statistics etc.) ------------------------
    def get_state(self) -> dict:
        return {}

    def set_state(self, state: dict) -> None:
        pass

    @staticmethod
    def merge_states(states: list[dict]) -> dict:
        return states[0] if states else {}


class ConnectorPipelineV2(ConnectorV2):
    """Ordered composition with list surgery (ref:
    connector_pipeline_v2.py insert_before/insert_after/prepend/append/
    remove)."""

    def __init__(self, *connectors: ConnectorV2):
        self.connectors: list[ConnectorV2] = list(connectors)

    def __call__(self, batch, ctx):
        for c in self.connectors:
            batch = c(batch, ctx)
        return batch

    def _index_of(self, name_or_cls) -> int:
        key = name_or_cls if isinstance(name_or_cls, str) \
            else name_or_cls.__name__
        for i, c in enumerate(self.connectors):
            if c.name == key:
                return i
        raise ValueError(f"no connector named {key!r} in pipeline")

    def prepend(self, connector: ConnectorV2) -> "ConnectorPipelineV2":
        self.connectors.insert(0, connector)
        return self

    def append(self, connector: ConnectorV2) -> "ConnectorPipelineV2":
        self.connectors.append(connector)
        return self

    def insert_before(self, name_or_cls, connector) -> "ConnectorPipelineV2":
        self.connectors.insert(self._index_of(name_or_cls), connector)
        return self

    def insert_after(self, name_or_cls, connector) -> "ConnectorPipelineV2":
        self.connectors.insert(self._index_of(name_or_cls) + 1, connector)
        return self

    def remove(self, name_or_cls) -> "ConnectorPipelineV2":
        del self.connectors[self._index_of(name_or_cls)]
        return self

    def __len__(self):
        return len(self.connectors)

    def __getitem__(self, i):
        return self.connectors[i]

    # state is keyed by stage name; duplicate names share state slots in
    # registration order
    def get_state(self) -> dict:
        return {f"{i}:{c.name}": c.get_state()
                for i, c in enumerate(self.connectors)}

    def set_state(self, state: dict) -> None:
        for i, c in enumerate(self.connectors):
            s = state.get(f"{i}:{c.name}")
            if s:
                c.set_state(s)

    def merge_states(self, states: list[dict]) -> dict:
        out = {}
        for i, c in enumerate(self.connectors):
            key = f"{i}:{c.name}"
            per = [s[key] for s in states if s.get(key)]
            if per:
                out[key] = type(c).merge_states(per)
        return out


# -------------------------------------------------------- env -> module
class FlattenObservations(ConnectorV2):
    """[N, *obs_shape] -> [N, prod(obs_shape)] float array."""

    def __call__(self, batch, ctx):
        obs = np.asarray(batch)
        return obs.reshape(obs.shape[0], -1)


class CastObservations(ConnectorV2):
    def __init__(self, dtype=np.float32):
        self.dtype = np.dtype(dtype)

    def __call__(self, batch, ctx):
        return np.asarray(batch, dtype=self.dtype)


def _welford_merge(a: tuple, b: tuple) -> tuple:
    """Combine two (count, mean, M2) accumulators exactly (Chan et al.)."""
    (ca, ma, m2a), (cb, mb, m2b) = a, b
    if ca == 0:
        return b
    if cb == 0:
        return a
    tot = ca + cb
    d = mb - ma
    return (tot, ma + d * (cb / tot), m2a + m2b + d * d * (ca * cb / tot))


class NormalizeObservations(ConnectorV2):
    """Running mean/std normalization (ref: the MeanStdFilter connector
    role). Keeps a BASE accumulator (last broadcast fleet-wide state) and
    a local DELTA since that broadcast; cross-runner merges combine the
    shared base once plus every runner's delta — exact parallel variance
    (Chan et al.), no double-counting of shared history across sync
    rounds."""

    def __init__(self, eps: float = 1e-8, clip: float = 10.0,
                 update: bool = True):
        self.eps = eps
        self.clip = clip
        self.update = update
        zero = (0.0, None, None)  # (count, mean, m2); arrays lazily sized
        self._base: tuple = zero
        self._delta: tuple = zero

    @staticmethod
    def _mat(state: tuple, dim: int) -> tuple:
        c, m, m2 = state
        if m is None:
            return (c, np.zeros(dim), np.zeros(dim))
        return state

    def _combined(self, dim: int) -> tuple:
        return _welford_merge(self._mat(self._base, dim),
                              self._mat(self._delta, dim))

    def __call__(self, batch, ctx):
        obs = np.asarray(batch, dtype=np.float64)
        flat = obs.reshape(obs.shape[0], -1)
        dim = flat.shape[1]
        if self.update:
            n = flat.shape[0]
            bmean = flat.mean(axis=0)
            bm2 = ((flat - bmean) ** 2).sum(axis=0)
            self._delta = _welford_merge(
                self._mat(self._delta, dim), (float(n), bmean, bm2))
        count, mean, m2 = self._combined(dim)
        if count < 2:
            return np.asarray(batch, dtype=np.float32)
        std = np.sqrt(m2 / count + self.eps)
        out = (flat - mean) / std
        return np.clip(out, -self.clip, self.clip).astype(
            np.float32).reshape(obs.shape)

    def get_state(self) -> dict:
        c, m, m2 = self._delta
        state: dict = {}
        if m is not None:
            state["delta"] = {"count": c, "mean": m, "m2": m2}
        bc, bm, bm2 = self._base
        if bm is not None:
            state["base"] = {"count": bc, "mean": bm, "m2": bm2}
        return state

    def set_state(self, state: dict) -> None:
        """Adopt a merged fleet-wide state as the new base; local delta
        restarts from zero (its samples are inside the merge)."""
        base = state.get("base") or state.get("delta")
        if base:
            self._base = (float(base["count"]), np.asarray(base["mean"]),
                          np.asarray(base["m2"]))
            self._delta = (0.0, None, None)

    @staticmethod
    def merge_states(states: list[dict]) -> dict:
        """base (shared; counted once) ⊕ every runner's delta."""
        states = [s for s in states if s]
        if not states:
            return {}
        acc = (0.0, None, None)

        def tup(d):
            return (float(d["count"]), np.asarray(d["mean"]),
                    np.asarray(d["m2"]))

        bases = [s["base"] for s in states if "base" in s]
        if bases:
            acc = tup(bases[0])  # identical across runners post-broadcast
        for s in states:
            if "delta" in s:
                d = tup(s["delta"])
                acc = _welford_merge(acc, d) if acc[1] is not None else d
        if acc[1] is None:
            return {}
        return {"base": {"count": acc[0], "mean": acc[1], "m2": acc[2]}}


# -------------------------------------------------------- module -> env
class ClipActions(ConnectorV2):
    """Clip continuous actions to the env's bounds; discrete passes
    through (ref: module_to_env clip-by-space)."""

    def __init__(self, low=None, high=None):
        self.low = low
        self.high = high

    def __call__(self, batch, ctx):
        if self.low is None and self.high is None:
            return batch
        return np.clip(np.asarray(batch), self.low, self.high)


# ------------------------------------------------------------- learner
class NormalizeAdvantages(ConnectorV2):
    """Standardize batch["advantages"] (ref: the learner pipeline's
    GeneralAdvantageEstimation postprocessing)."""

    def __call__(self, batch, ctx):
        adv = np.asarray(batch["advantages"], dtype=np.float32)
        batch = dict(batch)
        batch["advantages"] = (adv - adv.mean()) / (adv.std() + 1e-8)
        return batch


class LambdaConnector(ConnectorV2):
    """Inline connector from a plain function (handy in configs/tests)."""

    def __init__(self, fn: Callable, name: str = "LambdaConnector"):
        self._fn = fn
        self._name = name

    @property
    def name(self) -> str:
        return self._name

    def __call__(self, batch, ctx):
        return self._fn(batch, ctx)


# ------------------------------------------------------------- defaults
def default_env_to_module() -> ConnectorPipelineV2:
    """Flatten + cast; mirror of the reference's default env-to-module
    stack (add NormalizeObservations() for MeanStdFilter behavior)."""
    return ConnectorPipelineV2(FlattenObservations(), CastObservations())


def default_module_to_env() -> ConnectorPipelineV2:
    return ConnectorPipelineV2(ClipActions())


def default_learner_pipeline() -> ConnectorPipelineV2:
    return ConnectorPipelineV2(NormalizeAdvantages())

"""Offline RL: experience IO + Behavior Cloning + discrete CQL.

TPU-native counterpart of the reference offline stack (ref:
rllib/offline/offline_data.py + json_reader.py sample-batch JSON files;
rllib/algorithms/bc/bc.py; rllib/algorithms/cql/cql.py). Experiences are
JSONL fragments ({obs, actions, rewards, dones, next_obs} per line, the
SampleBatch shape); readers fan file shards out as ray_tpu tasks and
learners train jitted updates over the materialized transitions:

  - BC:  supervised cross-entropy of the policy on logged actions — the
    simplest offline baseline, and the imitation anchor.
  - CQL (discrete): SAC's twin soft critics + a conservative penalty
    ``logsumexp(Q) - Q(a_logged)`` that pushes down Q on actions the
    behavior policy never took, so the learned policy can't exploit
    out-of-distribution overestimates (Kumar et al. 2020).
"""

from __future__ import annotations

import json
import os
import time

import numpy as np

import ray_tpu


# ------------------------------------------------------------------------ IO
def write_rollouts(path: str, fragments: list[dict]) -> int:
    """Append sample fragments as JSONL (ref: offline json_writer.py).
    Each fragment: dict of array-likes keyed obs/actions/rewards/dones
    (+ optionally next_obs). Returns rows written."""
    os.makedirs(os.path.dirname(os.path.abspath(path)), exist_ok=True)
    n = 0
    with open(path, "a") as f:
        for frag in fragments:
            row = {k: np.asarray(v).tolist() for k, v in frag.items()}
            f.write(json.dumps(row) + "\n")
            n += len(row.get("actions", ()))
    return n


def collect_rollouts(env_name: str, path: str, *, num_steps: int = 1000,
                     num_envs: int = 2, seed: int = 0, policy_params=None,
                     hidden: int = 64, env_config: dict | None = None) -> int:
    """Roll a (random or given) policy in an env and log the experience —
    the `rllib train ... --output` role. Returns transitions written."""
    import jax

    from ray_tpu.rllib.core import policy_init
    from ray_tpu.rllib.env_runner import EnvRunner

    runner = EnvRunner(env_name, num_envs=num_envs, seed=seed,
                       env_config=env_config)
    obs_dim, n_actions = runner.obs_and_action_space()
    params = policy_params if policy_params is not None else policy_init(
        jax.random.PRNGKey(seed), obs_dim, n_actions, hidden)
    runner.set_weights(params)
    frags = []
    written = 0
    steps = 0
    while steps < num_steps:
        take = min(128, num_steps - steps)
        ro = runner.sample(take)
        T, N = ro["actions"].shape
        # flatten [T, N] to transitions; next_obs via the shifted obs rows
        next_obs = np.concatenate(
            [ro["obs"][1:], np.repeat(ro["last_obs"][None], 1, 0)], axis=0)
        frags.append({
            "obs": ro["obs"].reshape(T * N, -1),
            "actions": ro["actions"].reshape(-1),
            "rewards": ro["rewards"].reshape(-1),
            "dones": ro["dones"].reshape(-1).astype(np.float32),
            "next_obs": next_obs.reshape(T * N, -1),
        })
        steps += take
    written = write_rollouts(path, frags)
    return written


@ray_tpu.remote
def _read_shard(path: str) -> dict:
    cols: dict[str, list] = {}
    with open(path) as f:
        for line in f:
            if not line.strip():
                continue
            row = json.loads(line)
            for k, v in row.items():
                cols.setdefault(k, []).append(np.asarray(v))
    return {k: np.concatenate(v) for k, v in cols.items()} if cols else {}


class OfflineData:
    """Reader over one or more JSONL experience files (ref:
    offline_data.py OfflineData): file shards load as parallel tasks,
    transitions concatenate into one in-memory table served as seeded
    minibatches."""

    def __init__(self, paths: str | list[str], *, seed: int = 0):
        if isinstance(paths, str):
            paths = [paths]
        expanded: list[str] = []
        for p in paths:
            if os.path.isdir(p):
                expanded.extend(
                    os.path.join(p, f) for f in sorted(os.listdir(p))
                    if f.endswith((".json", ".jsonl")))
            else:
                expanded.append(p)
        if not expanded:
            raise ValueError(f"no offline data under {paths!r}")
        shards = ray_tpu.get([_read_shard.remote(p) for p in expanded],
                             timeout=600)
        shards = [s for s in shards if s]
        self.table = {
            k: np.concatenate([s[k] for s in shards]) for k in shards[0]
        }
        self.n = len(self.table["actions"])
        self._rng = np.random.default_rng(seed)

    def minibatch(self, size: int) -> dict:
        idx = self._rng.integers(0, self.n, size=min(size, self.n))
        return {k: v[idx] for k, v in self.table.items()}


# ------------------------------------------------------------------------ BC
def make_bc_update(lr: float):
    import jax
    import jax.numpy as jnp
    import optax

    from ray_tpu.rllib.core import policy_logits

    optimizer = optax.adam(lr)

    def loss_fn(params, batch):
        logp = jax.nn.log_softmax(policy_logits(params, batch["obs"]))
        picked = jnp.take_along_axis(
            logp, batch["actions"][:, None], axis=-1)[:, 0]
        return -picked.mean()

    @jax.jit
    def update(params, opt_state, batch):
        loss, grads = jax.value_and_grad(loss_fn)(params, batch)
        updates, opt_state = optimizer.update(grads, opt_state, params)
        return optax.apply_updates(params, updates), opt_state, loss

    return update, optimizer


class BCConfig:
    """Builder config (ref: bc.py BCConfig)."""

    def __init__(self):
        self.paths: list[str] | str | None = None
        self.lr = 1e-3
        self.batch_size = 256
        self.updates_per_iter = 64
        self.hidden = 64
        self.seed = 0
        self.obs_dim: int | None = None
        self.n_actions: int | None = None

    def offline_data(self, paths):
        self.paths = paths
        return self

    def training(self, *, lr=None, batch_size=None, updates_per_iter=None,
                 hidden=None):
        for name, val in (("lr", lr), ("batch_size", batch_size),
                          ("updates_per_iter", updates_per_iter),
                          ("hidden", hidden)):
            if val is not None:
                setattr(self, name, val)
        return self

    def build(self) -> "BC":
        if self.paths is None:
            raise ValueError("BCConfig.offline_data(...) is required")
        return BC(self)


class BC:
    """Behavior cloning learner (ref: bc.py — the marl_module reduces to
    a supervised policy head here)."""

    def __init__(self, config: BCConfig):
        import jax

        from ray_tpu.rllib.core import policy_init

        if not ray_tpu.is_initialized():
            ray_tpu.init()
        self.config = config
        self.data = OfflineData(config.paths, seed=config.seed)
        obs_dim = config.obs_dim or self.data.table["obs"].shape[-1]
        n_actions = config.n_actions or int(
            self.data.table["actions"].max()) + 1
        self.params = policy_init(
            jax.random.PRNGKey(config.seed), obs_dim, n_actions,
            config.hidden)
        self._update, optimizer = make_bc_update(config.lr)
        self.opt_state = optimizer.init(self.params)
        self._iteration = 0

    def train(self) -> dict:
        import jax.numpy as jnp

        t0 = time.monotonic()
        losses = []
        for _ in range(self.config.updates_per_iter):
            mb = self.data.minibatch(self.config.batch_size)
            batch = {"obs": jnp.asarray(mb["obs"], jnp.float32),
                     "actions": jnp.asarray(mb["actions"], jnp.int32)}
            self.params, self.opt_state, loss = self._update(
                self.params, self.opt_state, batch)
            losses.append(float(loss))
        self._iteration += 1
        return {
            "training_iteration": self._iteration,
            "loss": sum(losses) / len(losses),
            "num_transitions": self.data.n,
            "time_this_iter_s": time.monotonic() - t0,
        }

    def get_weights(self):
        return self.params

    def evaluate(self, num_episodes: int = 4, env_name: str | None = None,
                 env_config: dict | None = None) -> dict:
        """Greedy rollouts of the cloned policy (ref: bc evaluation)."""
        import gymnasium as gym
        import jax.numpy as jnp
        import numpy as np

        from ray_tpu.rllib.core import policy_logits

        env = gym.make(env_name, **(env_config or {}))
        returns = []
        for ep in range(num_episodes):
            obs, _ = env.reset(seed=1000 + ep)
            total, done = 0.0, False
            while not done:
                logits = policy_logits(self.params,
                                       jnp.asarray(obs[None], jnp.float32))
                a = int(np.asarray(logits).argmax())
                obs, r, term, trunc, _ = env.step(a)
                total += float(r)
                done = term or trunc
            returns.append(total)
        return {"episode_return_mean": float(np.mean(returns)),
                "episodes": num_episodes}

    def stop(self):
        pass


# ----------------------------------------------------------------------- CQL
def make_cql_update(lr: float, gamma: float, tau: float,
                    target_entropy: float, cql_alpha: float):
    """Discrete CQL = discrete SAC + conservative penalty
    ``E[logsumexp Q - Q(a_logged)]`` on both critics (ref: cql.py /
    cql_learner — there on top of continuous SAC)."""
    import jax
    import jax.numpy as jnp
    import optax

    from ray_tpu.rllib.core import mlp_apply

    optimizer = optax.adam(lr)

    def heads(params, obs):
        logits = mlp_apply(params["pi"], obs)
        logp = jax.nn.log_softmax(logits)
        return logp, mlp_apply(params["q1"], obs), mlp_apply(params["q2"], obs)

    def loss_fn(params, target_params, batch):
        logp, q1, q2 = heads(params, batch["obs"])
        alpha = jnp.exp(params["log_alpha"])
        a = batch["actions"][:, None]

        logp_n, _, _ = heads(params, batch["next_obs"])
        q1t = mlp_apply(target_params["q1"], batch["next_obs"])
        q2t = mlp_apply(target_params["q2"], batch["next_obs"])
        pi_n = jnp.exp(logp_n)
        soft_v = (pi_n * (jnp.minimum(q1t, q2t)
                          - jax.lax.stop_gradient(alpha) * logp_n)).sum(-1)
        y = batch["rewards"] + gamma * (1.0 - batch["dones"]) * \
            jax.lax.stop_gradient(soft_v)

        q1_a = jnp.take_along_axis(q1, a, axis=-1)[:, 0]
        q2_a = jnp.take_along_axis(q2, a, axis=-1)[:, 0]
        bellman = ((q1_a - y) ** 2).mean() + ((q2_a - y) ** 2).mean()
        # conservative term: penalize Q mass off the logged actions
        cql = ((jax.scipy.special.logsumexp(q1, axis=-1) - q1_a).mean()
               + (jax.scipy.special.logsumexp(q2, axis=-1) - q2_a).mean())
        q_loss = bellman + cql_alpha * cql

        pi = jnp.exp(logp)
        q_min = jax.lax.stop_gradient(jnp.minimum(q1, q2))
        pi_loss = (pi * (jax.lax.stop_gradient(alpha) * logp - q_min)) \
            .sum(-1).mean()
        ent_err = jax.lax.stop_gradient((pi * logp).sum(-1) + target_entropy)
        alpha_loss = (-params["log_alpha"] * ent_err).mean()
        return q_loss + pi_loss + alpha_loss, (bellman, cql)

    @jax.jit
    def update(params, target_params, opt_state, batch):
        (loss, (bellman, cql)), grads = jax.value_and_grad(
            loss_fn, has_aux=True)(params, target_params, batch)
        updates, opt_state = optimizer.update(grads, opt_state, params)
        params = optax.apply_updates(params, updates)
        target_params = {
            "q1": jax.tree.map(lambda t, s: (1 - tau) * t + tau * s,
                               target_params["q1"], params["q1"]),
            "q2": jax.tree.map(lambda t, s: (1 - tau) * t + tau * s,
                               target_params["q2"], params["q2"]),
        }
        return params, target_params, opt_state, loss, bellman, cql

    return update, optimizer


class CQLConfig:
    """Builder config (ref: cql.py CQLConfig)."""

    def __init__(self):
        self.paths = None
        self.lr = 3e-4
        self.gamma = 0.99
        self.tau = 0.005
        self.cql_alpha = 1.0
        self.n_actions: int | None = None
        self.batch_size = 256
        self.updates_per_iter = 64
        self.hidden = 64
        self.seed = 0
        self.target_entropy: float | None = None

    def offline_data(self, paths):
        self.paths = paths
        return self

    def training(self, *, lr=None, gamma=None, tau=None, cql_alpha=None,
                 batch_size=None, updates_per_iter=None, hidden=None,
                 target_entropy=None, n_actions=None):
        for name, val in (("lr", lr), ("gamma", gamma), ("tau", tau),
                          ("cql_alpha", cql_alpha), ("n_actions", n_actions),
                          ("batch_size", batch_size),
                          ("updates_per_iter", updates_per_iter),
                          ("hidden", hidden),
                          ("target_entropy", target_entropy)):
            if val is not None:
                setattr(self, name, val)
        return self

    def build(self) -> "CQL":
        if self.paths is None:
            raise ValueError("CQLConfig.offline_data(...) is required")
        return CQL(self)


class CQL:
    """Offline discrete-CQL learner over logged transitions."""

    def __init__(self, config: CQLConfig):
        import jax
        import numpy as _np

        from ray_tpu.rllib.sac import sac_init

        if not ray_tpu.is_initialized():
            ray_tpu.init()
        self.config = config
        self.data = OfflineData(config.paths, seed=config.seed)
        obs_dim = self.data.table["obs"].shape[-1]
        # a narrow behavior policy may never take the last action(s):
        # allow the action-space size to be given explicitly
        n_actions = config.n_actions or int(
            self.data.table["actions"].max()) + 1
        self.params = sac_init(jax.random.PRNGKey(config.seed), obs_dim,
                               n_actions, config.hidden)
        self.target_params = {
            "q1": jax.tree.map(lambda x: x, self.params["q1"]),
            "q2": jax.tree.map(lambda x: x, self.params["q2"]),
        }
        tgt_ent = config.target_entropy
        if tgt_ent is None:
            tgt_ent = 0.98 * float(_np.log(n_actions))
        self._update, optimizer = make_cql_update(
            config.lr, config.gamma, config.tau, tgt_ent, config.cql_alpha)
        self.opt_state = optimizer.init(self.params)
        self._iteration = 0

    def train(self) -> dict:
        import jax.numpy as jnp

        t0 = time.monotonic()
        losses, cqls = [], []
        for _ in range(self.config.updates_per_iter):
            mb = self.data.minibatch(self.config.batch_size)
            batch = {
                "obs": jnp.asarray(mb["obs"], jnp.float32),
                "actions": jnp.asarray(mb["actions"], jnp.int32),
                "rewards": jnp.asarray(mb["rewards"], jnp.float32),
                "dones": jnp.asarray(mb["dones"], jnp.float32),
                "next_obs": jnp.asarray(mb["next_obs"], jnp.float32),
            }
            out = self._update(self.params, self.target_params,
                               self.opt_state, batch)
            self.params, self.target_params, self.opt_state = out[:3]
            losses.append(float(out[3]))
            cqls.append(float(out[5]))
        self._iteration += 1
        return {
            "training_iteration": self._iteration,
            "loss": sum(losses) / len(losses),
            "cql_penalty": sum(cqls) / len(cqls),
            "num_transitions": self.data.n,
            "time_this_iter_s": time.monotonic() - t0,
        }

    def get_weights(self):
        return self.params

    def stop(self):
        pass

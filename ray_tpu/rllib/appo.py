"""APPO: asynchronous PPO — IMPALA's async sampling + V-trace correction
with PPO's clipped surrogate objective.

TPU-native counterpart of the reference APPO (ref:
rllib/algorithms/appo/appo.py + appo_learner.py: "APPO is an
IMPALA-variant that uses a PPO surrogate loss on V-trace-corrected
advantages"). The driver IS the IMPALA driver (standing sample requests,
stale-ok broadcasts); only the learner loss differs:

    ratio    = pi_target(a|s) / pi_behavior(a|s)
    L_pi     = -min(ratio * A_vtrace, clip(ratio, 1±eps) * A_vtrace)

so a runner's policy-lag shows up twice, both times bounded: in the
V-trace rho/c truncation of the TARGETS and in the clipped ratio of the
SURROGATE.
"""

from __future__ import annotations

from ray_tpu.rllib.impala import IMPALA, IMPALAConfig, vtrace_returns


def make_appo_update(lr: float, gamma: float, vf_coeff: float,
                     entropy_coeff: float, rho_bar: float, c_bar: float,
                     clip: float):
    import jax
    import jax.numpy as jnp
    import optax

    from ray_tpu.rllib.core import policy_logits, value_fn

    optimizer = optax.adam(lr)

    def loss_fn(params, batch):
        obs = batch["obs"]  # [T, N, D]
        logits = policy_logits(params, obs)
        logp_all = jax.nn.log_softmax(logits)
        target_logp = jnp.take_along_axis(
            logp_all, batch["actions"][..., None], axis=-1)[..., 0]
        values = value_fn(params, obs)
        vs, pg_adv = vtrace_returns(
            batch["logp"], target_logp, batch["rewards"], values,
            value_fn(params, batch["last_obs"]), batch["dones"],
            gamma=gamma, rho_bar=rho_bar, c_bar=c_bar)
        vs = jax.lax.stop_gradient(vs)
        adv = jax.lax.stop_gradient(pg_adv)
        # PPO clipped surrogate on the V-trace advantages (appo_learner)
        ratio = jnp.exp(target_logp - batch["logp"])
        surr = jnp.minimum(
            ratio * adv, jnp.clip(ratio, 1.0 - clip, 1.0 + clip) * adv)
        pi_loss = -surr.mean()
        vf_loss = 0.5 * ((values - vs) ** 2).mean()
        entropy = -(jnp.exp(logp_all) * logp_all).sum(-1).mean()
        return pi_loss + vf_coeff * vf_loss - entropy_coeff * entropy

    @jax.jit
    def update(params, opt_state, batch):
        loss, grads = jax.value_and_grad(loss_fn)(params, batch)
        updates, opt_state = optimizer.update(grads, opt_state, params)
        return optax.apply_updates(params, updates), opt_state, loss

    return update, optimizer


class APPOConfig(IMPALAConfig):
    """Builder config (ref: appo.py APPOConfig — an IMPALAConfig with the
    PPO clip parameter)."""

    def __init__(self):
        super().__init__()
        self.clip = 0.2

    def training(self, *, clip=None, **kw):
        if clip is not None:
            self.clip = clip
        super().training(**kw)
        return self

    def _build_update(self):
        return make_appo_update(
            self.lr, self.gamma, self.vf_coeff, self.entropy_coeff,
            self.rho_bar, self.c_bar, self.clip)

    def build(self) -> "APPO":
        if self.env_name is None:
            raise ValueError("APPOConfig.environment(...) is required")
        return APPO(self)


class APPO(IMPALA):
    """The IMPALA async driver with the APPO learner update."""

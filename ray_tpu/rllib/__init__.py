"""ray_tpu.rllib — reinforcement learning on the ray_tpu runtime.

TPU-native counterpart of RLlib's new API stack (ref: rllib/):
- core: functional jax policy modules (rl_module.py role)
- env_runner: gymnasium sampling actors (single_agent_env_runner.py:68)
- learner: jitted PPO updates + learner group (learner_group.py:100)
- ppo: PPOConfig builder + Algorithm driver (algorithms/ppo/ppo.py:362)

    from ray_tpu.rllib import PPOConfig

    algo = (PPOConfig()
            .environment("CartPole-v1")
            .env_runners(num_env_runners=2)
            .build())
    for _ in range(10):
        print(algo.train()["episode_return_mean"])
"""
from ray_tpu.rllib.core import policy_init, policy_logits, sample_action, value_fn
from ray_tpu.rllib.env_runner import EnvRunner
from ray_tpu.rllib.learner import Learner, compute_gae, make_ppo_update
from ray_tpu.rllib.ppo import PPO, PPOConfig

__all__ = [
    "EnvRunner",
    "Learner",
    "PPO",
    "PPOConfig",
    "compute_gae",
    "make_ppo_update",
    "policy_init",
    "policy_logits",
    "sample_action",
    "value_fn",
]

"""ray_tpu.rllib — reinforcement learning on the ray_tpu runtime.

TPU-native counterpart of RLlib's new API stack (ref: rllib/):
- core: functional jax policy modules (rl_module.py role)
- env_runner: gymnasium sampling actors (single_agent_env_runner.py:68)
- learner: jitted PPO updates + learner group (learner_group.py:100)
- ppo: PPOConfig builder + Algorithm driver (algorithms/ppo/ppo.py:362)
- dqn: off-policy double-DQN over replay buffers (algorithms/dqn/)
- impala: async sampling + V-trace correction (algorithms/impala/)
- sac: discrete twin-critic soft actor-critic with autotuned temperature
  (algorithms/sac/)
- replay_buffer: uniform + prioritized rings (utils/replay_buffers/)
- multi_agent: MultiAgentEnv + MultiAgentEnvRunner (env/multi_agent_*)
- appo: async PPO — IMPALA sampling + clipped surrogate (algorithms/appo/)
- offline: experience JSONL IO + BC + discrete CQL (rllib/offline/,
  algorithms/bc/, algorithms/cql/)
- connectors: ConnectorV2 pipelines between env, module, and learner
  (rllib/connectors/connector_v2.py, connector_pipeline_v2.py)

    from ray_tpu.rllib import PPOConfig

    algo = (PPOConfig()
            .environment("CartPole-v1")
            .env_runners(num_env_runners=2)
            .build())
    for _ in range(10):
        print(algo.train()["episode_return_mean"])
"""
from ray_tpu.rllib.appo import APPO, APPOConfig, make_appo_update
from ray_tpu.rllib.connectors import (CastObservations, ClipActions,
                                      ConnectorCtx, ConnectorPipelineV2,
                                      ConnectorV2, FlattenObservations,
                                      LambdaConnector, NormalizeAdvantages,
                                      NormalizeObservations,
                                      default_env_to_module,
                                      default_learner_pipeline,
                                      default_module_to_env)
from ray_tpu.rllib.core import policy_init, policy_logits, sample_action, value_fn
from ray_tpu.rllib.dqn import DQN, DQNConfig, DQNEnvRunner, make_dqn_update, q_init, q_values
from ray_tpu.rllib.env_runner import EnvRunner
from ray_tpu.rllib.impala import IMPALA, IMPALAConfig, make_impala_update, vtrace_returns
from ray_tpu.rllib.learner import Learner, compute_gae, make_ppo_update
from ray_tpu.rllib.offline import (BC, CQL, BCConfig, CQLConfig,
                                   OfflineData, collect_rollouts,
                                   write_rollouts)
from ray_tpu.rllib.multi_agent import MultiAgentEnv, MultiAgentEnvRunner
from ray_tpu.rllib.ppo import PPO, PPOConfig
from ray_tpu.rllib.replay_buffer import PrioritizedReplayBuffer, ReplayBuffer
from ray_tpu.rllib.sac import SAC, SACConfig, SACEnvRunner, make_sac_update, sac_init

__all__ = [
    "APPO",
    "APPOConfig",
    "CastObservations",
    "ClipActions",
    "ConnectorCtx",
    "ConnectorPipelineV2",
    "ConnectorV2",
    "FlattenObservations",
    "LambdaConnector",
    "NormalizeAdvantages",
    "NormalizeObservations",
    "default_env_to_module",
    "default_learner_pipeline",
    "default_module_to_env",
    "BC",
    "BCConfig",
    "CQL",
    "CQLConfig",
    "OfflineData",
    "collect_rollouts",
    "write_rollouts",
    "DQN",
    "DQNConfig",
    "DQNEnvRunner",
    "EnvRunner",
    "IMPALA",
    "IMPALAConfig",
    "Learner",
    "MultiAgentEnv",
    "MultiAgentEnvRunner",
    "PPO",
    "PPOConfig",
    "PrioritizedReplayBuffer",
    "SAC",
    "SACConfig",
    "SACEnvRunner",
    "ReplayBuffer",
    "compute_gae",
    "make_dqn_update",
    "make_impala_update",
    "make_ppo_update",
    "make_sac_update",
    "sac_init",
    "vtrace_returns",
    "policy_init",
    "policy_logits",
    "q_init",
    "q_values",
    "sample_action",
    "value_fn",
]

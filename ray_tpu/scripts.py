"""ray_tpu CLI: cluster lifecycle + state inspection.

TPU-native counterpart of the reference CLI (ref:
python/ray/scripts/scripts.py:2734 — `ray start/stop/status` plus the
`ray list/summary/timeline` state commands from util/state/state_cli.py).

    python -m ray_tpu start --head [--num-cpus N] [--autoscale MIN:MAX]
    python -m ray_tpu start --address HOST:PORT      # join as a new node
    python -m ray_tpu status  [--address HOST:PORT]
    python -m ray_tpu list tasks|actors|nodes|objects|pgs
    python -m ray_tpu summary
    python -m ray_tpu timeline --output trace.json
    python -m ray_tpu dashboard [--port 8265]
    python -m ray_tpu stop
"""
from __future__ import annotations

import argparse
import json
import os
import signal
import subprocess
import sys
import tempfile
import time

SESSION_FILE = os.path.join(tempfile.gettempdir(), "ray_tpu", "session.json")


def _save_session(data: dict):
    os.makedirs(os.path.dirname(SESSION_FILE), exist_ok=True)
    with open(SESSION_FILE, "w") as f:
        json.dump(data, f)


def _load_session() -> dict | None:
    try:
        with open(SESSION_FILE) as f:
            return json.load(f)
    except (FileNotFoundError, json.JSONDecodeError):
        return None


def _resolve_address(args) -> str:
    addr = getattr(args, "address", None)
    if addr:
        return addr
    sess = _load_session()
    if sess and sess.get("gcs_address"):
        return sess["gcs_address"]
    sys.exit("no running session found; pass --address HOST:PORT or `start --head`")


def _connect(address: str):
    import ray_tpu

    ray_tpu.init(address=address)
    return ray_tpu


# ------------------------------------------------------------------ commands
def cmd_start(args):
    env = dict(os.environ)
    pkg_parent = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
    env["PYTHONPATH"] = pkg_parent + os.pathsep + env.get("PYTHONPATH", "")
    # daemon children get log files, NOT the CLI's stdio: inherited pipes
    # would keep callers capturing our output blocked forever
    log_dir = os.path.join(tempfile.gettempdir(), "ray_tpu", "logs")
    os.makedirs(log_dir, exist_ok=True)

    def logf(name):
        return open(os.path.join(log_dir, f"{name}-{os.getpid()}.log"), "ab")

    pids = []
    if args.head:
        tmp = tempfile.mkdtemp(prefix="rt_cli_")
        addr_file = os.path.join(tmp, "gcs_addr")
        gcs = subprocess.Popen(
            [sys.executable, "-m", "ray_tpu.core.gcs", "--address-file", addr_file,
             *(("--port", str(args.port)) if args.port else ())],
            env=env, stdout=logf("gcs"), stderr=subprocess.STDOUT,
        )
        pids.append(gcs.pid)
        deadline = time.monotonic() + 30
        while not os.path.exists(addr_file):
            if time.monotonic() > deadline:
                sys.exit("GCS did not start")
            time.sleep(0.05)
        gcs_address = open(addr_file).read().strip()
    else:
        if not args.address:
            sys.exit("start needs --head or --address HOST:PORT")
        gcs_address = args.address

    raylet_cmd = [
        sys.executable, "-m", "ray_tpu.core.raylet", "--gcs", gcs_address,
        "--num-cpus", str(args.num_cpus if args.num_cpus is not None
                          else (os.cpu_count() or 1)),
    ]
    if args.num_tpus:
        raylet_cmd += ["--num-tpus", str(args.num_tpus)]
    if args.resources:
        raylet_cmd += ["--resources", args.resources]
    raylet = subprocess.Popen(raylet_cmd, env=env,
                              stdout=logf("raylet"), stderr=subprocess.STDOUT)
    pids.append(raylet.pid)

    autoscaler_note = ""
    if args.head and args.autoscale:
        lo, hi = (int(x) for x in args.autoscale.split(":"))
        mon = subprocess.Popen(
            [sys.executable, "-m", "ray_tpu.scripts", "_autoscaler_monitor",
             "--address", gcs_address, "--min-nodes", str(lo), "--max-nodes", str(hi)],
            env=env, stdout=logf("autoscaler"), stderr=subprocess.STDOUT,
        )
        pids.append(mon.pid)
        autoscaler_note = f", autoscaler {lo}:{hi}"

    if args.head:
        _save_session({"gcs_address": gcs_address, "pids": pids})
        print(f"ray_tpu head started at {gcs_address}{autoscaler_note}")
        print(f"  connect:  ray_tpu.init(address={gcs_address!r})")
        print(f"  stop:     python -m ray_tpu stop")
    else:
        sess = _load_session() or {"gcs_address": gcs_address, "pids": []}
        sess["pids"] = sess.get("pids", []) + pids
        _save_session(sess)
        print(f"ray_tpu node joined {gcs_address}")


def cmd_stop(args):
    sess = _load_session()
    if not sess:
        print("no session file; nothing to stop")
        return
    for pid in sess.get("pids", []):
        try:
            os.kill(pid, signal.SIGTERM)
        except ProcessLookupError:
            pass
    deadline = time.monotonic() + 5
    while time.monotonic() < deadline:
        if not any(_pid_alive(p) for p in sess.get("pids", [])):
            break
        time.sleep(0.1)
    for pid in sess.get("pids", []):
        if _pid_alive(pid):
            try:
                os.kill(pid, signal.SIGKILL)
            except ProcessLookupError:
                pass
    try:
        os.unlink(SESSION_FILE)
    except FileNotFoundError:
        pass
    print("stopped")


def _pid_alive(pid: int) -> bool:
    try:
        os.kill(pid, 0)
        return True
    except (ProcessLookupError, PermissionError):
        return False


def cmd_serve(args):
    """serve deploy/status/shutdown against a live cluster (ref: the
    reference's `serve` CLI group mounted on `ray`, scripts.py:2734)."""
    rt = _connect(_resolve_address(args))
    from ray_tpu import serve

    try:
        if args.serve_cmd == "deploy":
            handles = serve.deploy_config(args.config)
            for name in handles:
                print(f"deployed application {name!r}")
        elif args.serve_cmd == "status":
            print(json.dumps(serve.status(), indent=2, default=str))
        elif args.serve_cmd == "shutdown":
            serve.shutdown()
            print("serve shut down")
    finally:
        rt.shutdown()


def cmd_status(args):
    rt = _connect(_resolve_address(args))
    nodes = rt.nodes()
    total = rt.cluster_resources()
    avail = rt.available_resources()
    print(f"nodes: {len(nodes)}")
    for n in nodes:
        nid = n["node_id"].hex() if hasattr(n["node_id"], "hex") else n["node_id"]
        print(f"  {nid[:12]}  alive={n['alive']}  queued={n.get('queued_leases', 0)}")
    print("resources (available/total):")
    for k in sorted(total):
        print(f"  {k}: {avail.get(k, 0.0):g}/{total[k]:g}")
    rt.shutdown()


def cmd_list(args):
    from ray_tpu import state

    _connect(_resolve_address(args))
    fn = {
        "tasks": state.list_tasks,
        "actors": state.list_actors,
        "nodes": state.list_nodes,
        "objects": state.list_objects,
        "pgs": state.list_placement_groups,
    }[args.what]
    rows = fn()
    print(json.dumps(rows, indent=2, default=lambda o: o.hex()
                     if hasattr(o, "hex") else str(o)))


def cmd_summary(args):
    from ray_tpu import state

    _connect(_resolve_address(args))
    print(json.dumps(state.summary_tasks(), indent=2))


def cmd_timeline(args):
    from ray_tpu import state

    _connect(_resolve_address(args))
    events = state.timeline(args.output)
    print(f"wrote {len(events)} trace events to {args.output}")


def cmd_dashboard(args):
    from ray_tpu.dashboard import run_dashboard

    _connect(_resolve_address(args))
    print(f"dashboard on http://{args.host}:{args.port}")
    run_dashboard(args.host, args.port)


def cmd_job(args):
    """Job submission CLI (ref: `ray job submit/status/logs/list/stop`).
    With --dashboard-url, goes through the REST API + SDK; otherwise
    connects directly to the cluster (bare-shell mode)."""
    from ray_tpu import job as jobmod

    if getattr(args, "entrypoint", None) is not None:
        if args.entrypoint and args.entrypoint[0] == "--":
            args.entrypoint = args.entrypoint[1:]
        if not args.entrypoint:
            sys.exit("job submit needs an entrypoint, e.g. -- python script.py")
    if getattr(args, "dashboard_url", None):
        client = jobmod.JobSubmissionClient(args.dashboard_url)
        if args.job_cmd == "submit":
            env = {"working_dir": args.working_dir} if args.working_dir else None
            import shlex

            jid = client.submit_job(entrypoint=shlex.join(args.entrypoint),
                                    runtime_env=env)
            print(jid)
            if args.wait:
                while client.get_job_status(jid) not in (
                        "SUCCEEDED", "FAILED", "STOPPED"):
                    time.sleep(0.5)
                info = client.get_job_info(jid)
                print(f"{info['status']}: {info.get('message', '')}")
                sys.exit(0 if info["status"] == "SUCCEEDED" else 1)
        elif args.job_cmd == "status":
            print(json.dumps(client.get_job_info(args.job_id), indent=2))
        elif args.job_cmd == "logs":
            print(client.get_job_logs(args.job_id), end="")
        elif args.job_cmd == "list":
            print(json.dumps(client.list_jobs(), indent=2))
        elif args.job_cmd == "stop":
            print("stopped" if client.stop_job(args.job_id) else "not running")
        return

    _connect(_resolve_address(args))
    if args.job_cmd == "submit":
        env = {"working_dir": args.working_dir} if args.working_dir else None
        import shlex

        jid = jobmod.submit_job(shlex.join(args.entrypoint), runtime_env=env)
        print(jid)
        if args.wait:
            rec = jobmod.wait_job(jid, timeout=3600)
            print(f"{rec['status']}: {rec.get('message', '')}")
            sys.exit(0 if rec["status"] == "SUCCEEDED" else 1)
    elif args.job_cmd == "status":
        print(json.dumps(jobmod.job_status(args.job_id), indent=2))
    elif args.job_cmd == "logs":
        print(jobmod.job_logs(args.job_id), end="")
    elif args.job_cmd == "list":
        print(json.dumps(jobmod.list_jobs(), indent=2))
    elif args.job_cmd == "stop":
        print("stopped" if jobmod.stop_job(args.job_id) else "not running")


def cmd_autoscaler_monitor(args):
    """Internal: run the autoscaler reconciler (launched by start --head)."""
    from ray_tpu.autoscaler import Autoscaler, AutoscalerConfig, LocalSubprocessProvider

    host, port = args.address.rsplit(":", 1)
    provider = LocalSubprocessProvider(args.address)
    scaler = Autoscaler(
        (host, int(port)), provider,
        AutoscalerConfig(min_nodes=args.min_nodes, max_nodes=args.max_nodes),
    )
    stop_evt = {"stop": False}

    def _term(signum, frame):
        stop_evt["stop"] = True

    # `ray_tpu stop` sends SIGTERM: the provider's raylet children must
    # die with the monitor or they'd orphan against a dead GCS
    signal.signal(signal.SIGTERM, _term)
    signal.signal(signal.SIGINT, _term)
    scaler.start()
    try:
        while not stop_evt["stop"]:
            time.sleep(0.2)
    finally:
        scaler.stop()
        provider.shutdown()


def main(argv=None):
    parser = argparse.ArgumentParser(prog="ray_tpu")
    sub = parser.add_subparsers(dest="cmd", required=True)

    p = sub.add_parser("start", help="start a head node or join a cluster")
    p.add_argument("--head", action="store_true")
    p.add_argument("--address", default=None)
    p.add_argument("--port", type=int, default=0)
    p.add_argument("--num-cpus", type=float, default=None)
    p.add_argument("--num-tpus", type=float, default=0.0)
    p.add_argument("--resources", default="")
    p.add_argument("--autoscale", default=None, metavar="MIN:MAX")
    p.set_defaults(fn=cmd_start)

    p = sub.add_parser("stop", help="stop the local session")
    p.set_defaults(fn=cmd_stop)

    p = sub.add_parser("status", help="cluster nodes + resources")
    p.add_argument("--address", default=None)
    p.set_defaults(fn=cmd_status)

    p = sub.add_parser("list", help="list cluster state")
    p.add_argument("what", choices=["tasks", "actors", "nodes", "objects", "pgs"])
    p.add_argument("--address", default=None)
    p.set_defaults(fn=cmd_list)

    p = sub.add_parser("summary", help="task summary by name/state")
    p.add_argument("--address", default=None)
    p.set_defaults(fn=cmd_summary)

    p = sub.add_parser("timeline", help="export chrome trace")
    p.add_argument("--output", default="/tmp/ray_tpu_timeline.json")
    p.add_argument("--address", default=None)
    p.set_defaults(fn=cmd_timeline)

    p = sub.add_parser("dashboard", help="serve the web dashboard")
    p.add_argument("--host", default="127.0.0.1")
    p.add_argument("--port", type=int, default=8265)
    p.add_argument("--address", default=None)
    p.set_defaults(fn=cmd_dashboard)

    p = sub.add_parser("job", help="submit and manage cluster jobs")
    jsub = p.add_subparsers(dest="job_cmd", required=True)
    js = jsub.add_parser("submit")
    js.add_argument("--address", default=None)
    js.add_argument("--dashboard-url", default=None)
    js.add_argument("--working-dir", default=None)
    js.add_argument("--wait", action="store_true",
                    help="block until the job finishes; exit 0 on success")
    js.add_argument("entrypoint", nargs=argparse.REMAINDER,
                    help="command to run, e.g. -- python script.py")
    js.set_defaults(fn=cmd_job)
    for verb in ("status", "logs", "stop"):
        jp = jsub.add_parser(verb)
        jp.add_argument("job_id")
        jp.add_argument("--address", default=None)
        jp.add_argument("--dashboard-url", default=None)
        jp.set_defaults(fn=cmd_job)
    jp = jsub.add_parser("list")
    jp.add_argument("--address", default=None)
    jp.add_argument("--dashboard-url", default=None)
    jp.set_defaults(fn=cmd_job)

    p = sub.add_parser("serve", help="deploy and manage serve applications")
    ssub = p.add_subparsers(dest="serve_cmd", required=True)
    sp = ssub.add_parser("deploy", help="deploy apps from a YAML config")
    sp.add_argument("config", help="path to a ServeDeploySchema YAML")
    sp.add_argument("--address", default=None)
    sp.set_defaults(fn=cmd_serve)
    sp = ssub.add_parser("status")
    sp.add_argument("--address", default=None)
    sp.set_defaults(fn=cmd_serve)
    sp = ssub.add_parser("shutdown")
    sp.add_argument("--address", default=None)
    sp.set_defaults(fn=cmd_serve)

    from ray_tpu.devtools.lint.cli import add_lint_parser, cmd_lint

    lp = add_lint_parser(sub)
    # cmd_lint returns an exit code rather than printing-and-returning;
    # adapt it to the `args.fn(args)` convention the other commands use
    lp.set_defaults(fn=lambda args: sys.exit(cmd_lint(args)))

    from ray_tpu.devtools.chaos.cli import add_chaos_parser, cmd_chaos

    cp = add_chaos_parser(sub)
    cp.set_defaults(fn=lambda args: sys.exit(cmd_chaos(args)))

    p = sub.add_parser("_autoscaler_monitor")
    p.add_argument("--address", required=True)
    p.add_argument("--min-nodes", type=int, default=1)
    p.add_argument("--max-nodes", type=int, default=4)
    p.set_defaults(fn=cmd_autoscaler_monitor)

    args = parser.parse_args(argv)
    args.fn(args)


if __name__ == "__main__":
    main()

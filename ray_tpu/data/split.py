"""streaming_split: n coordinated consumers over one dataset execution.

(ref: python/ray/data/dataset.py:1731 streaming_split,
_internal/execution/streaming_executor apis + stream_split_iterator.py:37
SplitCoordinator actor). One coordinator actor drives the streaming executor
exactly once; train workers each own a DataIterator that pulls their
round-robin share of blocks. Blocks travel driver-free: coordinator task →
shm object store → consumer.
"""

from __future__ import annotations

import collections

import ray_tpu


class SplitCoordinator:
    """Actor: runs the stream, deals blocks round-robin to n consumers.

    equal=True deals whole blocks round-robin (±1 block skew — the
    reference's row-exact equalization is an upgrade, not a behavior
    change); consumers signal epoch restarts via reset()."""

    def __init__(self, dataset, n: int, equal: bool = True):
        self._dataset = dataset
        self._n = n
        self._equal = equal
        self._start()

    def _start(self):
        self._stream = iter(self._dataset.iter_block_refs())
        self._queues = [collections.deque() for _ in range(self._n)]
        self._next_assign = 0
        self._exhausted = False

    def next(self, i: int):
        """Next block for consumer i, or None at end of stream."""
        q = self._queues[i]
        while not q and not self._exhausted:
            try:
                ref = next(self._stream)
            except StopIteration:
                self._exhausted = True
                break
            self._queues[self._next_assign].append(ref)
            self._next_assign = (self._next_assign + 1) % self._n
        if not q:
            return None
        return ray_tpu.get(q.popleft())

    def reset(self):
        """Start a new epoch (re-executes the lazy plan)."""
        self._start()
        return True


def make_stream_splits(dataset, n: int, *, equal: bool = True) -> list:
    from ray_tpu.data.iterator import DataIterator

    Coord = ray_tpu.remote(SplitCoordinator).options(num_cpus=0)
    coord = Coord.remote(dataset, n, equal)

    def make_next(i):
        return lambda: ray_tpu.get(coord.next.remote(i))

    iterators = []
    for i in range(n):
        it = DataIterator(make_next(i), name=f"split-{i}/{n}")
        it._coordinator = coord  # keep the actor alive with the iterators
        iterators.append(it)
    return iterators

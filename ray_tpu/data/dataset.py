"""Dataset: lazy, streaming, task-parallel datasets.

Public face of ray_tpu.data (ref: python/ray/data/dataset.py:160 Dataset;
read API read_api.py; iteration iterator.py). Every transform is lazy —
consumption drives the streaming executor (executor.py) which keeps a
bounded number of block tasks in flight.

The TPU-relevant endpoints are ``iter_batches(batch_format="numpy")`` (host
columnar → jax.device_put) and ``streaming_split(n)`` (one coordinator
actor feeding n train workers; ref: dataset.py:1731 streaming_split,
stream_split_iterator.py:37).
"""

from __future__ import annotations

import builtins
import functools
import itertools
from typing import Any, Callable, Iterable

import numpy as np

import ray_tpu
from ray_tpu.data.block import BlockAccessor, normalize_block, rows_to_columns
from ray_tpu.data.executor import (
    LimitOp,
    MapBlocks,
    Plan,
    RepartitionOp,
    ShuffleOp,
    SortOp,
)

DEFAULT_BLOCK_ROWS = 1000


class ActorPoolStrategy:
    """compute= strategy for map_batches: a fixed pool of stateful map
    actors (ref: ActorPoolStrategy in python/ray/data/_internal/compute.py
    — the autoscaling min/max pool collapses to a fixed size here)."""

    def __init__(self, size: int = 2, *,
                 max_tasks_in_flight_per_actor: int = 2):
        if size < 1:
            raise ValueError("ActorPoolStrategy size must be >= 1")
        self.size = size
        self.max_tasks_in_flight_per_actor = max_tasks_in_flight_per_actor


class Dataset:
    def __init__(self, plan: Plan):
        self._plan = plan

    # ---------------------------------------------------------- transforms
    def map_batches(self, fn: Callable, *, batch_size: int | None = None,
                    batch_format: str | None = "numpy",
                    fn_kwargs: dict | None = None,
                    compute=None,
                    fn_constructor_args: tuple = (),
                    fn_constructor_kwargs: dict | None = None,
                    num_cpus: float = 1.0) -> "Dataset":
        """Apply fn to whole blocks rendered as ``batch_format``
        (ref: dataset.py map_batches). batch_size re-chunks first when
        given. ``compute=ActorPoolStrategy(size=N)`` (or a callable CLASS
        as fn) runs the map on a pool of stateful actors — construct the
        class once per actor and amortize model loads across blocks
        (ref: actor_pool_map_operator.py)."""
        kwargs = fn_kwargs or {}
        ds = self
        if batch_size is not None:
            ds = ds.repartition_by_rows(batch_size)
        if compute is not None or isinstance(fn, type):
            from ray_tpu.data.executor import ActorPoolMapBlocks

            strategy = compute or ActorPoolStrategy()
            if isinstance(fn, type):
                cls = fn

                class _Callable(cls):  # render batches + kwargs inside
                    def __call__(self, block, _k=kwargs, _bf=batch_format):
                        batch = BlockAccessor.for_block(block).to_batch(_bf)
                        return super().__call__(batch, **_k)

                target = _Callable
            else:
                def target(block, _fn=fn, _k=kwargs, _bf=batch_format):
                    batch = BlockAccessor.for_block(block).to_batch(_bf)
                    return _fn(batch, **_k) if _k else _fn(batch)
            return Dataset(ds._plan.with_op(ActorPoolMapBlocks(
                "map_batches(actors)", target,
                size=strategy.size,
                max_tasks_per_actor=strategy.max_tasks_in_flight_per_actor,
                fn_constructor_args=fn_constructor_args,
                fn_constructor_kwargs=fn_constructor_kwargs,
                num_cpus=num_cpus)))

        def apply(block):
            batch = BlockAccessor.for_block(block).to_batch(batch_format)
            return fn(batch, **kwargs) if kwargs else fn(batch)

        return Dataset(ds._plan.with_op(MapBlocks("map_batches", apply)))

    def map(self, fn: Callable) -> "Dataset":
        def apply(block):
            rows = [fn(r) for r in BlockAccessor.for_block(block).rows()]
            return rows_to_columns(rows) if rows and isinstance(rows[0], dict) else rows

        return Dataset(self._plan.with_op(
            MapBlocks("map", apply, preserves_rows=True)))

    def flat_map(self, fn: Callable) -> "Dataset":
        def apply(block):
            rows = [o for r in BlockAccessor.for_block(block).rows() for o in fn(r)]
            return rows_to_columns(rows) if rows and isinstance(rows[0], dict) else rows

        return Dataset(self._plan.with_op(MapBlocks("flat_map", apply)))

    def filter(self, fn: Callable) -> "Dataset":
        def apply(block):
            acc = BlockAccessor.for_block(block)
            if acc.is_tabular():
                mask = np.asarray([bool(fn(r)) for r in acc.rows()])
                return acc.mask(mask)
            return [r for r in acc.block if fn(r)]

        return Dataset(self._plan.with_op(MapBlocks("filter", apply)))

    def add_column(self, name: str, fn: Callable) -> "Dataset":
        def apply(batch):
            batch[name] = fn(batch)
            return batch

        return self.map_batches(apply, batch_format="numpy")

    def drop_columns(self, cols: list[str]) -> "Dataset":
        return self.map_batches(
            lambda b: {k: v for k, v in
                       BlockAccessor.for_block(b).columns().items()
                       if k not in set(cols)},
            batch_format="numpy",
        )

    def select_columns(self, cols: list[str]) -> "Dataset":
        cols = list(cols)

        def apply(block):
            batch = BlockAccessor.for_block(block).to_batch("numpy")
            return {k: batch[k] for k in cols}

        op = MapBlocks("select_columns", apply, preserves_rows=True)
        # optimizer hook: as the first op over parquet reads this becomes
        # a column projection on the read itself (optimizer.py
        # projection_pushdown)
        op.projected_columns = cols
        return Dataset(self._plan.with_op(op))

    def explain(self) -> str:
        """Logical vs optimized physical op chain (ref: Dataset.explain)."""
        from ray_tpu.data.optimizer import explain

        return explain(self._plan)

    def limit(self, n: int) -> "Dataset":
        return Dataset(self._plan.with_op(LimitOp(n)))

    def repartition(self, num_blocks: int) -> "Dataset":
        return Dataset(self._plan.with_op(RepartitionOp(num_blocks)))

    def repartition_by_rows(self, rows_per_block: int) -> "Dataset":
        """Helper used by map_batches(batch_size=...): barrier + resize."""
        total = self.count()
        blocks = max(1, -(-total // rows_per_block))
        return self.repartition(blocks)

    def random_shuffle(self, *, seed: int | None = None) -> "Dataset":
        return Dataset(self._plan.with_op(ShuffleOp(seed)))

    def sort(self, key=None, descending: bool = False) -> "Dataset":
        return Dataset(self._plan.with_op(SortOp(key, descending)))

    def groupby(self, key: str) -> "GroupedDataset":
        """Group rows by a key column (ref: dataset.py groupby ->
        grouped_data.py; hash-aggregated map-side partials + one merge)."""
        return GroupedDataset(self, key)

    def join(self, other: "Dataset", on: str, *, how: str = "inner",
             suffix: str = "_r", num_partitions: int | None = None,
             ) -> "Dataset":
        """Distributed hash join on a key column (ref:
        _internal/execution/operators/join.py:28 JoinOperator +
        hash_shuffle.py): both sides hash-partition their blocks by key
        (map side, one task per block), then each partition builds a hash
        table from its left rows and probes the right rows (one task per
        partition). Output columns: the key, left columns, right columns
        (name collisions on the right take ``suffix``).

        how: "inner" | "left" | "right" | "outer". Missing sides of
        outer rows are null-filled (Arrow take-with-null semantics).
        """
        if how not in ("inner", "left", "right", "outer"):
            raise ValueError(f"unknown join how={how!r}")
        left_refs = list(self.iter_block_refs())
        right_refs = list(other.iter_block_refs())
        P = num_partitions or builtins.min(
            16, builtins.max(len(left_refs), len(right_refs), 1))

        @ray_tpu.remote(num_returns=P)
        def shard(block):
            acc = BlockAccessor.for_block(block)
            n = acc.num_rows()
            if n == 0:
                empty = acc.slice(0, 0)
                return tuple(empty for _ in builtins.range(P)) \
                    if P > 1 else empty
            keys = acc.column(on) if acc.is_tabular() \
                else [r[on] for r in acc.rows()]
            part = np.array([_key_shard(k, P) for k in keys])
            outs = tuple(acc.take(np.nonzero(part == p)[0])
                         for p in builtins.range(P))
            return outs if P > 1 else outs[0]

        @ray_tpu.remote
        def join_partition(n_left, *parts):
            return _hash_join_blocks(
                list(parts[:n_left]), list(parts[n_left:]), on, how, suffix)

        lsh = [shard.remote(r) for r in left_refs]
        rsh = [shard.remote(r) for r in right_refs]

        def col(shards, p):
            if P == 1:
                return list(shards)
            return [s[p] for s in shards]

        out_refs = [
            join_partition.remote(
                len(lsh), *col(lsh, p), *col(rsh, p))
            for p in builtins.range(P)
        ]
        # hand the partition refs straight to the plan (NO remote re-fetch
        # hop: a read task get()ing a ref would hold the only lease on a
        # 1-CPU node while the join tasks it waits on need one)
        from ray_tpu.data.executor import InjectRefs

        return Dataset(Plan([], (InjectRefs("join", out_refs),)))

    def zip(self, other: "Dataset") -> "Dataset":
        """Column-wise zip of two datasets with equal row counts (ref:
        dataset.py zip; right-side name collisions take a ``_1`` suffix).
        A barrier over REFS only: right blocks are sliced to the left's
        block boundaries and each aligned pair zips in its own task —
        the driver never materializes a row."""
        from ray_tpu.data.executor import (InjectRefs, _count_rows,
                                           _slice_block)

        left_refs = list(self.iter_block_refs())
        right_refs = list(other.iter_block_refs())
        lcounts = ray_tpu.get([_count_rows.remote(r) for r in left_refs])
        rcounts = ray_tpu.get([_count_rows.remote(r) for r in right_refs])
        if sum(lcounts) != sum(rcounts):
            raise ValueError(
                f"zip requires equal row counts: "
                f"{sum(lcounts)} vs {sum(rcounts)}")

        @ray_tpu.remote
        def zip_blocks(lblock, *rparts):
            lacc = BlockAccessor.for_block(lblock)
            racc = BlockAccessor.for_block(BlockAccessor.concat(list(rparts)))
            out = []
            for lr, rr in builtins.zip(lacc.rows(), racc.rows()):
                row = dict(lr)
                for k, v in rr.items():
                    row[k + "_1" if k in row else k] = v
                out.append(row)
            return rows_to_columns(out) if out else []

        # walk right blocks, carving each left block's row range
        out_refs = []
        ri = 0       # current right block
        roff = 0     # rows of right block ri already consumed
        for lref, need in builtins.zip(left_refs, lcounts):
            parts = []
            remaining = need
            while remaining > 0:
                avail = rcounts[ri] - roff
                take = builtins.min(avail, remaining)
                if take == rcounts[ri] and roff == 0:
                    parts.append(right_refs[ri])
                else:
                    parts.append(_slice_block.remote(
                        right_refs[ri], roff, roff + take))
                roff += take
                remaining -= take
                if roff == rcounts[ri]:
                    ri += 1
                    roff = 0
            out_refs.append(zip_blocks.remote(lref, *parts))
        return Dataset(Plan([], (InjectRefs("zip", out_refs),)))

    def unique(self, column: str) -> list:
        """Distinct values of one column (ref: dataset.py unique) —
        per-block set on the workers, one merge here."""
        @ray_tpu.remote
        def block_unique(block):
            acc = BlockAccessor.for_block(block)
            if acc.is_tabular():
                return set(np.unique(acc.column(column)).tolist())
            return {r[column] for r in acc.rows()}

        sets = ray_tpu.get(
            [block_unique.remote(r) for r in self.iter_block_refs()])
        out: set = set()
        for s in sets:
            out |= s
        return sorted(out, key=str)

    def random_sample(self, fraction: float, *, seed: int | None = None
                      ) -> "Dataset":
        """Bernoulli row sample (ref: dataset.py random_sample)."""
        if not 0.0 <= fraction <= 1.0:
            raise ValueError("fraction must be in [0, 1]")

        def apply(block, index, _f=fraction, _s=seed):
            acc = BlockAccessor.for_block(block)
            n = acc.num_rows()
            # per-block seed from the STREAM INDEX: deterministic under a
            # fixed seed, and distinct across blocks even when their row
            # counts are identical (equal-sized blocks would otherwise
            # draw identical masks — a correlated, biased sample)
            rs = np.random.RandomState(
                None if _s is None else (_s * 7919 + index) % (2**31))
            return acc.take(np.nonzero(rs.random_sample(n) < _f)[0])

        return Dataset(self._plan.with_op(
            MapBlocks("random_sample", apply, indexed=True)))

    def columns(self) -> list[str] | None:
        """Column names of the first non-empty block (ref: Dataset.columns);
        None for non-record datasets (plain item lists)."""
        for block in self.iter_blocks():
            acc = BlockAccessor.for_block(block)
            if acc.num_rows():
                if acc.is_tabular():
                    return list(acc.column_names())
                first = next(iter(acc.rows()))
                return list(first) if isinstance(first, dict) else None
        return None

    def show(self, limit: int = 20) -> None:
        for row in self.take(limit):
            print(row)

    def union(self, other: "Dataset") -> "Dataset":
        if self._plan.ops or other._plan.ops:
            # materialize both sides into read tasks
            left = self.materialize()
            right = other.materialize()
            return Dataset(Plan(left._plan.read_tasks + right._plan.read_tasks))
        return Dataset(Plan(self._plan.read_tasks + other._plan.read_tasks))

    # ---------------------------------------------------------- execution
    def iter_block_refs(self) -> Iterable:
        stream, self._last_stats = self._plan.execute()
        return stream

    def iter_blocks(self) -> Iterable:
        # streaming by design: one materialised block in memory at a time;
        # batching the gets would buffer the whole dataset
        for ref in self.iter_block_refs():
            yield ray_tpu.get(ref)  # raylint: disable=RT002

    def materialize(self) -> "Dataset":
        """Execute now; the result holds its blocks (ref: MaterializedDataset)."""
        blocks = list(self.iter_blocks())
        return Dataset(Plan([_HoldBlock(b) for b in blocks]))

    def stats(self) -> str:
        st = getattr(self, "_last_stats", None)
        if not st:
            return "(not executed yet)"
        return "\n".join(s.row() for s in st)

    # --------------------------------------------------------- consumption
    def take(self, n: int = 20) -> list:
        out: list = []
        for block in self.limit(n).iter_blocks():
            out.extend(BlockAccessor.for_block(block).rows())
            if len(out) >= n:
                break
        return out[:n]

    def take_all(self) -> list:
        out: list = []
        for block in self.iter_blocks():
            out.extend(BlockAccessor.for_block(block).rows())
        return out

    def count(self) -> int:
        return sum(
            BlockAccessor.for_block(b).num_rows() for b in self.iter_blocks()
        )

    def schema(self):
        for block in self.iter_blocks():
            acc = BlockAccessor.for_block(block)
            if acc.num_rows():
                return acc.schema()
        return None

    def _column_agg(self, on: str | None, agg: Callable):
        vals: list = []
        for block in self.iter_blocks():
            acc = BlockAccessor.for_block(block)
            if acc.is_tabular():
                col = on or acc.column_names()[0]
                if acc.num_rows():
                    vals.append(acc.column(col))
            else:
                rows = [r[on] if on else r for r in acc.rows()]
                if rows:
                    vals.append(np.asarray(rows))
        if not vals:
            return None
        return agg(np.concatenate(vals))

    def sum(self, on: str | None = None):
        v = self._column_agg(on, np.sum)
        return None if v is None else v.item()

    def min(self, on: str | None = None):
        v = self._column_agg(on, np.min)
        return None if v is None else v.item()

    def max(self, on: str | None = None):
        v = self._column_agg(on, np.max)
        return None if v is None else v.item()

    def mean(self, on: str | None = None):
        v = self._column_agg(on, np.mean)
        return None if v is None else v.item()

    def iter_rows(self) -> Iterable:
        for block in self.iter_blocks():
            yield from BlockAccessor.for_block(block).rows()

    def iter_batches(self, *, batch_size: int = 256,
                     batch_format: str | None = "numpy",
                     drop_last: bool = False, prefetch_blocks: int = 2):
        from ray_tpu.data.iterator import iter_batches_over_refs

        return iter_batches_over_refs(
            self.iter_block_refs(), batch_size=batch_size,
            batch_format=batch_format, drop_last=drop_last,
            prefetch=prefetch_blocks,
        )

    def iter_torch_batches(self, *, batch_size: int = 256, drop_last=False):
        import torch

        for batch in self.iter_batches(batch_size=batch_size,
                                       batch_format="numpy",
                                       drop_last=drop_last):
            if isinstance(batch, dict):
                yield {k: torch.as_tensor(np.ascontiguousarray(v)) for k, v in batch.items()}
            else:
                yield torch.as_tensor(np.ascontiguousarray(batch))

    # ----------------------------------------------------------- splitting
    def split(self, n: int) -> list["Dataset"]:
        """Materialized equal split (ref: dataset.py split)."""
        parts = self.repartition(n).materialize()
        tasks = parts._plan.read_tasks
        per = max(1, len(tasks) // n)
        out = []
        for i in range(n):
            chunk = tasks[i * per: (i + 1) * per] if i < n - 1 else tasks[(n - 1) * per:]
            out.append(Dataset(Plan(list(chunk))))
        return out

    def streaming_split(self, n: int, *, equal: bool = True,
                        locality_hints=None) -> list:
        """n coordinated iterators over ONE execution of this dataset
        (ref: dataset.py:1731, stream_split_iterator.py:37): a
        SplitCoordinator actor runs the stream and hands blocks round-robin
        to consumers — the JaxTrainer input path."""
        from ray_tpu.data.split import make_stream_splits

        return make_stream_splits(self, n, equal=equal)

    def __repr__(self):
        ops = " -> ".join(op.name for op in self._plan.ops) or "source"
        return f"Dataset({len(self._plan.read_tasks)} read tasks, {ops})"

    # ------------------------------------------------------------- sinks
    def _write_files(self, path: str, ext: str, write_block: Callable) -> list[str]:
        """One file per block: path/part-<i>.<ext> (ref: write_parquet &
        friends — per-block write tasks, no driver materialization)."""
        import os

        os.makedirs(path, exist_ok=True)

        @ray_tpu.remote
        def write(block, out_path):
            write_block(block, out_path)
            return out_path

        refs = []
        for i, ref in enumerate(self.iter_block_refs()):
            refs.append(write.remote(ref, os.path.join(path, f"part-{i:05d}.{ext}")))
        return ray_tpu.get(refs)

    def write_parquet(self, path: str) -> list[str]:
        def wb(block, out_path):
            import pandas as pd
            import pyarrow as pa
            import pyarrow.parquet as pq

            cols = rows_to_columns(block) if isinstance(block, list) else block
            pq.write_table(pa.Table.from_pandas(pd.DataFrame(cols)), out_path)

        return self._write_files(path, "parquet", wb)

    def write_csv(self, path: str) -> list[str]:
        def wb(block, out_path):
            import pandas as pd

            cols = rows_to_columns(block) if isinstance(block, list) else block
            pd.DataFrame(cols).to_csv(out_path, index=False)

        return self._write_files(path, "csv", wb)

    def write_json(self, path: str) -> list[str]:
        def wb(block, out_path):
            import pandas as pd

            cols = rows_to_columns(block) if isinstance(block, list) else block
            pd.DataFrame(cols).to_json(out_path, orient="records", lines=True)

        return self._write_files(path, "json", wb)


class AggregateFn:
    """A named groupby aggregation (ref: python/ray/data/aggregate.py
    AggregateFn): ``init() -> state``, ``accumulate(state, row) -> state``,
    ``merge(a, b) -> state``, ``finalize(state) -> value``."""

    def __init__(self, init: Callable, accumulate: Callable, merge: Callable,
                 finalize: Callable | None = None, name: str = "agg"):
        self.init = init
        self.accumulate = accumulate
        self.merge = merge
        self.finalize = finalize or (lambda s: s)
        self.name = name


def _count_agg():
    return AggregateFn(lambda: 0, lambda s, r: s + 1, lambda a, b: a + b,
                       name="count()")


def _sum_agg(on):
    return AggregateFn(lambda: 0, lambda s, r: s + r[on], lambda a, b: a + b,
                       name=f"sum({on})")


def _min_agg(on):
    return AggregateFn(
        lambda: None, lambda s, r: r[on] if s is None else builtins.min(s, r[on]),
        lambda a, b: builtins.min(a, b), name=f"min({on})")


def _max_agg(on):
    return AggregateFn(
        lambda: None, lambda s, r: r[on] if s is None else builtins.max(s, r[on]),
        lambda a, b: builtins.max(a, b), name=f"max({on})")


def _mean_agg(on):
    return AggregateFn(
        lambda: (0.0, 0), lambda s, r: (s[0] + r[on], s[1] + 1),
        lambda a, b: (a[0] + b[0], a[1] + b[1]),
        lambda s: s[0] / s[1] if s[1] else float("nan"), name=f"mean({on})")


def _std_agg(on, ddof=1):
    # Welford-mergeable (count, mean, M2) — numerically stable across
    # shard merges, unlike sum/sum-of-squares
    def merge(a, b):
        (na, ma, m2a), (nb, mb, m2b) = a, b
        if na == 0:
            return b
        if nb == 0:
            return a
        n = na + nb
        d = mb - ma
        return (n, ma + d * nb / n, m2a + m2b + d * d * na * nb / n)

    def accum(s, r):
        n, m, m2 = s
        x = r[on]
        n += 1
        d = x - m
        m += d / n
        return (n, m, m2 + d * (x - m))

    return AggregateFn(
        lambda: (0, 0.0, 0.0), accum, merge,
        lambda s: (s[2] / (s[0] - ddof)) ** 0.5 if s[0] > ddof else float("nan"),
        name=f"std({on})")


class GroupedDataset:
    """Result of Dataset.groupby(key) (ref: grouped_data.py GroupedData:
    count/sum/min/max/mean/std/aggregate/map_groups). Aggregations run as
    a distributed hash aggregate (ref: execution/operators/
    hash_shuffle.py hash-aggregate shape): each block computes per-shard
    partial states (map side, num_returns=P), then P independent reduce
    tasks merge and finalize their shard — reduce parallelism P, no
    single task sees every group."""

    def __init__(self, ds: Dataset, key: str):
        self._ds = ds
        self._key = key

    def aggregate(self, *aggs: AggregateFn) -> Dataset:
        """Run several aggregations in one pass; output rows are
        {key, agg1.name: v1, ...} (ref: GroupedData.aggregate)."""
        key = self._key
        block_refs = list(self._ds.iter_block_refs())
        if not block_refs:
            return from_items([])
        P = builtins.min(len(block_refs), 16) or 1

        @ray_tpu.remote(num_returns=P)
        def partial(block):
            acc = BlockAccessor.for_block(block)
            shards: list[dict] = [{} for _ in builtins.range(P)]
            for row in acc.rows():
                k = row[key]
                states = shards[_key_shard(k, P)].get(k)
                if states is None:
                    states = [a.init() for a in aggs]
                    shards[_key_shard(k, P)][k] = states
                for i, a in enumerate(aggs):
                    states[i] = a.accumulate(states[i], row)
            return tuple(shards) if P > 1 else shards[0]

        @ray_tpu.remote
        def reduce_shard(*parts):
            merged: dict = {}
            for p in parts:
                for k, states in p.items():
                    cur = merged.get(k)
                    if cur is None:
                        merged[k] = list(states)
                    else:
                        for i, a in enumerate(aggs):
                            cur[i] = a.merge(cur[i], states[i])
            return [
                dict({key: k},
                     **{a.name: a.finalize(s)
                        for a, s in zip(aggs, merged[k])})
                for k in sorted(merged, key=str)
            ]

        @ray_tpu.remote
        def merge_sorted(*shard_rows):
            # shards are tiny (one row per group): a final key-sorted
            # merge keeps the pre-hash-aggregate contract of globally
            # key-ordered output without a driver materialization of
            # anything bigger than the aggregate itself
            out = [r for rows in shard_rows for r in rows]
            out.sort(key=lambda r: str(r[key]))
            return out

        sharded = [partial.remote(r) for r in block_refs]
        if P == 1:
            cols = [[s] for s in sharded]
        else:
            cols = [[sharded[b][p] for b in builtins.range(len(sharded))]
                    for p in builtins.range(P)]
        out_refs = [reduce_shard.remote(*col) for col in cols]
        from ray_tpu.data.executor import InjectRefs

        return Dataset(Plan(
            [], (InjectRefs("hash_aggregate",
                            [merge_sorted.remote(*out_refs)]),)))

    def count(self) -> Dataset:
        return self.aggregate(_count_agg())

    def sum(self, on: str) -> Dataset:
        return self.aggregate(_sum_agg(on))

    def min(self, on: str) -> Dataset:
        return self.aggregate(_min_agg(on))

    def max(self, on: str) -> Dataset:
        return self.aggregate(_max_agg(on))

    def mean(self, on: str) -> Dataset:
        return self.aggregate(_mean_agg(on))

    def std(self, on: str, ddof: int = 1) -> Dataset:
        return self.aggregate(_std_agg(on, ddof))

    def map_groups(self, fn: Callable) -> Dataset:
        """Apply fn(list_of_rows) -> list_of_rows per complete group.

        Hash-shuffle shape (ref: execution/operators/hash_shuffle.py): each
        block is hash-partitioned by key into P shards; one apply task per
        shard sees only its shard of every block — parallelism P, no task
        materializes the whole dataset."""
        key = self._key
        block_refs = list(self._ds.iter_block_refs())
        if not block_refs:
            return from_items([])
        P = builtins.min(len(block_refs), 16) or 1

        @ray_tpu.remote(num_returns=P)
        def partition(block):
            acc = BlockAccessor.for_block(block)
            shards: list[dict] = [{} for _ in builtins.range(P)]
            for row in acc.rows():
                k = row[key]
                shards[_key_shard(k, P)].setdefault(k, []).append(row)
            return tuple(shards) if P > 1 else shards[0]

        @ray_tpu.remote
        def apply_shard(*shard_parts):
            groups: dict = {}
            for p in shard_parts:
                for k, rows in p.items():
                    groups.setdefault(k, []).extend(rows)
            out = []
            for k in sorted(groups, key=str):
                out.extend(fn(groups[k]))
            return out

        sharded = [partition.remote(r) for r in block_refs]
        if P == 1:
            shard_cols = [[s] for s in sharded]
        else:
            shard_cols = [[sharded[b][p] for b in builtins.range(len(sharded))]
                          for p in builtins.range(P)]
        out_rows: list = []
        for rows in ray_tpu.get(
                [apply_shard.remote(*col) for col in shard_cols]):
            out_rows.extend(rows)
        return from_items(out_rows)


class _HoldBlock:
    """Picklable closure holding a materialized block as a read task."""

    def __init__(self, block):
        self.block = block

    def __call__(self):
        return self.block


# ------------------------------------------------------------------ sources
def _key_shard(k, P: int) -> int:
    """Stable partition of a join/group key (equal keys route identically
    across processes; 1 == 1.0 == True share an encoding)."""
    import zlib

    if isinstance(k, np.generic):
        k = k.item()
    if isinstance(k, str):
        b = b"s:" + k.encode()
    elif isinstance(k, bytes):
        b = b"b:" + k
    elif isinstance(k, (bool, int, float)):
        try:
            b = b"n:" + repr(float(k)).encode()
        except OverflowError:
            b = b"i:" + repr(int(k)).encode()
    else:
        b = b"o:" + repr(k).encode()
    return zlib.crc32(b) % P


def _hash_join_blocks(left_parts: list, right_parts: list, on: str,
                      how: str, suffix: str):
    """One partition's hash join: build key -> row-indices from the left,
    probe the right; row selection via Arrow take with null indices so
    outer rows null-fill naturally (ref: join.py:28 hash join build/probe)."""
    import pyarrow as pa
    import pyarrow.compute  # noqa: F401 — pa.compute is not auto-imported

    def side(parts):
        acc = BlockAccessor.for_block(BlockAccessor.concat(parts))
        if not acc.is_tabular() and acc.num_rows():
            # rows-list side (e.g. from_items): pivot to columnar once
            acc = BlockAccessor.for_block(rows_to_columns(list(acc.rows())))
        return acc

    lt = side(left_parts)
    rt = side(right_parts)
    n_l, n_r = lt.num_rows(), rt.num_rows()
    if (n_l == 0 and how in ("inner", "left")) or (
            n_r == 0 and how in ("inner", "right")):
        return []
    lkeys = lt.column(on).tolist() if n_l else []
    rkeys = rt.column(on).tolist() if n_r else []
    pos: dict = {}
    for i, k in enumerate(lkeys):
        pos.setdefault(k, []).append(i)
    li: list = []
    ri: list = []
    matched = np.zeros(n_l, dtype=bool)
    for j, k in enumerate(rkeys):
        hits = pos.get(k)
        if hits:
            matched[hits] = True
            for i in hits:
                li.append(i)
                ri.append(j)
        elif how in ("right", "outer"):
            li.append(None)
            ri.append(j)
    if how in ("left", "outer"):
        for i in np.nonzero(~matched)[0]:
            li.append(int(i))
            ri.append(None)
    if not li:
        return []

    def table_of(acc):
        b = acc.block
        if isinstance(b, pa.Table):
            return b
        t = acc.to_batch("pyarrow") if acc.num_rows() else pa.table({})
        return t

    ltab = table_of(lt) if n_l else None
    rtab = table_of(rt) if n_r else None
    lsel = ltab.take(pa.array(li, type=pa.int64())) if ltab is not None \
        else None
    rsel = rtab.take(pa.array(ri, type=pa.int64())) if rtab is not None \
        else None
    out: dict = {}
    # key column: from whichever side has it per row
    if lsel is not None and rsel is not None and on in rsel.column_names:
        lk, rk = lsel[on], rsel[on]
        out[on] = pa.chunked_array([
            pa.compute.if_else(pa.compute.is_valid(lk.combine_chunks()),
                               lk.combine_chunks(), rk.combine_chunks())])
    elif lsel is not None:
        out[on] = lsel[on]
    else:
        out[on] = rsel[on]
    if lsel is not None:
        for name in lsel.column_names:
            if name != on:
                out[name] = lsel[name]
    if rsel is not None:
        for name in rsel.column_names:
            if name == on:
                continue
            out[name + suffix if name in out else name] = rsel[name]
    return pa.table(out)


def range(n: int, *, parallelism: int = -1) -> Dataset:  # noqa: A001
    if parallelism <= 0:
        parallelism = max(1, min(8, n // DEFAULT_BLOCK_ROWS or 1))
    edges = np.linspace(0, n, parallelism + 1, dtype=np.int64)

    def make(lo: int, hi: int):
        return lambda: {"id": np.arange(lo, hi, dtype=np.int64)}

    return Dataset(Plan([make(int(lo), int(hi))
                         for lo, hi in zip(edges[:-1], edges[1:]) if hi > lo]))


def from_items(items: list, *, parallelism: int = -1) -> Dataset:
    items = list(items)
    if parallelism <= 0:
        parallelism = max(1, min(8, len(items) // DEFAULT_BLOCK_ROWS or 1))
    chunks = np.array_split(np.arange(len(items)), parallelism)

    def make(chunk_items):
        return lambda: list(chunk_items)

    return Dataset(Plan([make([items[i] for i in c]) for c in chunks if len(c)]))


def from_numpy(arr, *, parallelism: int = -1) -> Dataset:
    if isinstance(arr, dict):
        n = len(next(iter(arr.values())))
        cols = {k: np.asarray(v) for k, v in arr.items()}
    else:
        arr = np.asarray(arr)
        n = len(arr)
        cols = {"data": arr}
    if parallelism <= 0:
        parallelism = max(1, min(8, n // DEFAULT_BLOCK_ROWS or 1))
    edges = np.linspace(0, n, parallelism + 1, dtype=np.int64)

    def make(lo, hi):
        return lambda: {k: v[lo:hi] for k, v in cols.items()}

    return Dataset(Plan([make(int(lo), int(hi))
                         for lo, hi in zip(edges[:-1], edges[1:]) if hi > lo]))


def from_pandas(df) -> Dataset:
    return Dataset(Plan([functools.partial(normalize_block, df)]))


def from_arrow(table) -> Dataset:
    return Dataset(Plan([functools.partial(normalize_block, table)]))


def _expand_paths(paths) -> list[str]:
    import glob
    import os

    if isinstance(paths, str):
        paths = [paths]
    out: list[str] = []
    for p in paths:
        if os.path.isdir(p):
            out.extend(sorted(
                os.path.join(p, f) for f in os.listdir(p)
                if not f.startswith(".")
            ))
        elif any(ch in p for ch in "*?["):
            out.extend(sorted(glob.glob(p)))
        else:
            out.append(p)
    return out


def read_csv(paths, **pandas_kwargs) -> Dataset:
    files = _expand_paths(paths)

    def make(path):
        def read():
            import pandas as pd

            return normalize_block(pd.read_csv(path, **pandas_kwargs))

        return read

    return Dataset(Plan([make(p) for p in files]))


class _ParquetReadTask:
    """Projectable parquet read (the optimizer's projection_pushdown
    retargets ``columns`` when select_columns is the first op)."""

    def __init__(self, path: str, columns: list[str] | None):
        self.path = path
        self.columns = columns

    def __call__(self):
        import pyarrow.parquet as pq

        return normalize_block(pq.read_table(self.path, columns=self.columns))

    def with_columns(self, cols: list[str]) -> "_ParquetReadTask":
        if self.columns is not None and any(
                c not in self.columns for c in cols):
            # refuse rather than silently narrow: the optimizer then keeps
            # the select_columns op, which raises KeyError at execution —
            # the same observable behavior as the unoptimized plan
            raise AttributeError(
                f"projection {cols} not serveable from {self.columns}")
        return _ParquetReadTask(self.path, list(cols))


def read_parquet(paths, columns: list[str] | None = None) -> Dataset:
    files = _expand_paths(paths)
    return Dataset(Plan([_ParquetReadTask(p, columns) for p in files]))


def read_json(paths, *, lines: bool = True) -> Dataset:
    files = _expand_paths(paths)

    def make(path):
        def read():
            import json

            with open(path) as f:
                if lines:
                    return [json.loads(line) for line in f if line.strip()]
                data = json.load(f)
                return data if isinstance(data, list) else [data]

        return read

    return Dataset(Plan([make(p) for p in files]))


def read_text(paths) -> Dataset:
    files = _expand_paths(paths)

    def make(path):
        def read():
            with open(path) as f:
                return [{"text": line.rstrip("\n")} for line in f]

        return read

    return Dataset(Plan([make(p) for p in files]))


def read_numpy(paths) -> Dataset:
    files = _expand_paths(paths)

    def make(path):
        return lambda: {"data": np.load(path)}

    return Dataset(Plan([make(p) for p in files]))


def read_images(paths, *, size: tuple[int, int] | None = None,
                mode: str | None = None,
                include_paths: bool = False) -> Dataset:
    """One row per image: {"image": HxWxC uint8 array[, "path"]} (ref:
    read_api.py read_images). ``size=(h, w)`` resizes; ``mode`` converts
    (e.g. "RGB", "L")."""
    files = _expand_paths(paths)

    def make(path):
        def read():
            from PIL import Image

            img = Image.open(path)
            if mode:
                img = img.convert(mode)
            if size:
                img = img.resize((size[1], size[0]))
            row = {"image": np.asarray(img)}
            if include_paths:
                row["path"] = path
            return [row]

        return read

    return Dataset(Plan([make(p) for p in files]))


def read_sql(sql: str, connection_factory: Callable) -> Dataset:
    """Rows from any DB-API connection (ref: read_api.py read_sql —
    there over a connector zoo; here the caller supplies the
    ``connection_factory`` so sqlite3/psycopg/etc. all work the same).
    One read task executes the query on a worker (not the driver);
    ``.repartition(n)`` afterwards for downstream parallelism."""
    def read():
        conn = connection_factory()
        try:
            cur = conn.cursor()
            cur.execute(sql)
            if cur.description is None:
                raise ValueError(
                    "read_sql requires a statement that returns rows "
                    "(cursor.description is None — DDL/INSERT?)")
            cols = [d[0] for d in cur.description]
            return [dict(builtins.zip(cols, row)) for row in cur.fetchall()]
        finally:
            conn.close()

    return Dataset(Plan([read]))


def read_binary_files(paths, *, include_paths: bool = False) -> Dataset:
    """One row per file: {"bytes": ...[, "path": ...]} (ref:
    read_api.py read_binary_files)."""
    files = _expand_paths(paths)

    def make(path):
        def read():
            with open(path, "rb") as f:
                data = f.read()
            row = {"bytes": data}
            if include_paths:
                row["path"] = path
            return [row]

        return read

    return Dataset(Plan([make(p) for p in files]))

"""Rule-based logical plan optimizer.

The role of the reference's logical optimizer (ref: python/ray/data/
_internal/logical/optimizers.py LogicalOptimizer.rules +
rules/operator_fusion.py, rules/limit_pushdown.py) — rewrite the op chain
before execution so fewer tasks touch fewer rows:

  - EliminateRedundantOps  limit∘limit -> min; repartition/shuffle
                           immediately re-done -> last one wins (sorts
                           never collapse: stable-sort tie-breaks)
  - LimitPushdown          move limit below row-count-preserving maps, so
                           the map only sees surviving rows
  - ProjectionPushdown     select_columns as the FIRST op over parquet
                           reads -> read only those columns from disk
  - MapFusion              adjacent MapBlocks -> one task per block for
                           the whole chain (one serialization round-trip)
  - ReadMapFusion          leading MapBlocks folds into the read task
                           itself -> transform runs where the read ran

Every rule is a pure Plan -> Plan function; ``optimize`` runs them to a
bounded fixpoint. ``explain(plan)`` renders before/after for
Dataset.explain().
"""

from __future__ import annotations

from typing import Callable

from ray_tpu.data import executor as ex


def _is_map(op) -> bool:
    # indexed maps take (block, stream_index) — excluded from fusion,
    # whose composed fns assume the plain (block) signature
    return type(op) is ex.MapBlocks and not getattr(op, "indexed", False)


def eliminate_redundant(plan: "ex.Plan") -> "ex.Plan":
    ops = list(plan.ops)
    out: list = []
    for op in ops:
        if out:
            prev = out[-1]
            if isinstance(op, ex.LimitOp) and isinstance(prev, ex.LimitOp):
                out[-1] = ex.LimitOp(min(prev.n, op.n))
                continue
            # a barrier immediately followed by the same barrier kind:
            # only the last one determines the output
            # (repartition(4).repartition(8), shuffle().shuffle()). Sorts
            # do NOT collapse: sort is stable, so sort(a).sort(b) means
            # "by b, ties broken by a" — dropping sort(a) changes output.
            for kind in (ex.RepartitionOp, ex.ShuffleOp):
                if isinstance(op, kind) and isinstance(prev, kind):
                    out[-1] = op
                    break
            else:
                out.append(op)
            continue
        out.append(op)
    return ex.Plan(plan.read_tasks, tuple(out))


def limit_pushdown(plan: "ex.Plan") -> "ex.Plan":
    """limit after a rows-preserving map commutes with it: mapping rows
    that the limit then drops is wasted work (ref: rules/limit_pushdown)."""
    ops = list(plan.ops)
    changed = True
    while changed:
        changed = False
        for i in range(1, len(ops)):
            if (isinstance(ops[i], ex.LimitOp) and _is_map(ops[i - 1])
                    and getattr(ops[i - 1], "preserves_rows", False)):
                ops[i - 1], ops[i] = ops[i], ops[i - 1]
                changed = True
    return ex.Plan(plan.read_tasks, tuple(ops))


def projection_pushdown(plan: "ex.Plan") -> "ex.Plan":
    """select_columns as the first op over column-projectable reads
    (parquet) becomes a column list on the read itself (ref:
    planner/plan_read_op.py apply_output_blocks_handling... — here the
    read task carries the projection)."""
    if not plan.ops:
        return plan
    first = plan.ops[0]
    cols = getattr(first, "projected_columns", None)
    if not cols or not plan.read_tasks:
        return plan
    try:
        projected = [rt.with_columns(cols) for rt in plan.read_tasks]
    except (AttributeError, TypeError):
        return plan  # at least one read is not projectable
    return ex.Plan(projected, plan.ops[1:])


def _compose(f: Callable, g: Callable) -> Callable:
    def fused(block, _f=f, _g=g):
        return _g(ex.normalize_block(_f(block)))

    return fused


def map_fusion(plan: "ex.Plan") -> "ex.Plan":
    ops = list(plan.ops)
    out: list = []
    for op in ops:
        if out and _is_map(op) and _is_map(out[-1]):
            prev = out[-1]
            fused = ex.MapBlocks(
                f"{prev.name}->{op.name}", _compose(prev.fn, op.fn),
                max_in_flight=min(prev.max_in_flight, op.max_in_flight))
            fused.preserves_rows = (
                getattr(prev, "preserves_rows", False)
                and getattr(op, "preserves_rows", False))
            out[-1] = fused
        else:
            out.append(op)
    return ex.Plan(plan.read_tasks, tuple(out))


class _FusedRead:
    """Read task with a map folded in; keeps the original's projection
    hook so ProjectionPushdown and ReadMapFusion compose in either order."""

    def __init__(self, read_task, fn):
        self.read_task = read_task
        self.fn = fn

    def __call__(self):
        return self.fn(ex.normalize_block(self.read_task()))

    def with_columns(self, cols):
        if not hasattr(self.read_task, "with_columns"):
            raise AttributeError("inner read is not projectable")
        return _FusedRead(self.read_task.with_columns(cols), self.fn)

    @property
    def __name__(self):
        return "fused_read"


def read_map_fusion(plan: "ex.Plan") -> "ex.Plan":
    """Fold a leading MapBlocks into the read tasks: the transform runs in
    the same task (same worker, zero extra hop) as the read (ref:
    rules/operator_fusion.py fusing MapOperator into the upstream Read)."""
    if not plan.ops or not _is_map(plan.ops[0]) or not plan.read_tasks:
        return plan
    fn = plan.ops[0].fn
    return ex.Plan([_FusedRead(rt, fn) for rt in plan.read_tasks],
                   plan.ops[1:])


# projection BEFORE limit pushdown: limit_pushdown would otherwise swap a
# trailing limit in front of a leading select_columns (it preserves rows),
# after which select is no longer ops[0] and the parquet projection never
# fires — reading every column the select exists to drop
RULES: tuple = (
    eliminate_redundant,
    projection_pushdown,
    limit_pushdown,
    map_fusion,
    read_map_fusion,
)


def optimize(plan: "ex.Plan") -> "ex.Plan":
    for _ in range(4):  # bounded fixpoint: each rule is idempotent-ish
        before = _signature(plan)
        for rule in RULES:
            plan = rule(plan)
        if _signature(plan) == before:
            break
    return plan


def _signature(plan: "ex.Plan") -> tuple:
    return (len(plan.read_tasks),
            tuple((type(op).__name__, op.name) for op in plan.ops))


def describe(plan: "ex.Plan") -> str:
    src = f"read[{len(plan.read_tasks)} tasks]"
    chain = " -> ".join([src] + [op.name for op in plan.ops])
    return chain


def explain(plan: "ex.Plan") -> str:
    return (f"logical : {describe(plan)}\n"
            f"physical: {describe(optimize(plan))}")

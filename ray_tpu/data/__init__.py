"""ray_tpu.data — distributed datasets feeding device meshes.

The reference's Data library shape (ref: SURVEY §2.5 Data: lazy logical
plan -> streaming executor over blocks) at the scale this framework needs
for training input pipelines: lazy ops, task-parallel block transforms
with bounded in-flight streaming, arrow/numpy blocks, and
``streaming_split`` so each train worker pulls its own shard of one
stream (ref: data/dataset.py:1731 streaming_split).
"""

from ray_tpu.data.dataset import (  # noqa: F401
    Dataset,
    from_items,
    from_numpy,
    range as range_,  # noqa: A001
    read_csv,
    read_parquet,
)

range = range_  # noqa: A001  (mirror ray.data.range naming)

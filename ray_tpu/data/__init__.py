"""ray_tpu.data — distributed datasets feeding device meshes.

The reference's Data library shape (ref: SURVEY §2.5 Data: lazy logical
plan -> streaming executor over blocks) at the scale this framework needs
for training input pipelines: lazy ops, task-parallel block transforms
with bounded in-flight streaming, numpy/pandas/pyarrow blocks, and
``streaming_split`` so each train worker pulls its own shard of one
stream (ref: data/dataset.py:1731 streaming_split).
"""

from ray_tpu.data.block import BlockAccessor  # noqa: F401
from ray_tpu.data.dataset import (  # noqa: F401
    ActorPoolStrategy,
    AggregateFn,
    Dataset,
    GroupedDataset,
    from_arrow,
    from_items,
    from_numpy,
    from_pandas,
    read_binary_files,
    read_csv,
    read_json,
    read_numpy,
    read_images,
    read_parquet,
    read_sql,
    read_text,
)
from ray_tpu.data.dataset import range as _range
from ray_tpu.data.iterator import DataIterator  # noqa: F401

range = _range  # noqa: A001  (mirror ray.data.range naming)

__all__ = [
    "ActorPoolStrategy",
    "AggregateFn",
    "BlockAccessor",
    "DataIterator",
    "Dataset",
    "GroupedDataset",
    "from_arrow",
    "from_items",
    "from_numpy",
    "from_pandas",
    "range",
    "read_binary_files",
    "read_csv",
    "read_json",
    "read_numpy",
    "read_images",
    "read_parquet",
    "read_sql",
    "read_text",
]

"""Streaming block executor: bounded in-flight task-parallel execution.

The engine behind Dataset consumption — the reference's StreamingExecutor
shape (ref: python/ray/data/_internal/execution/streaming_executor.py:52,
OpState backpressure :167, task/actor pool map operators) reduced to its
load-bearing ideas:

  - the plan is a chain of block operators over a lazy source,
  - each operator keeps at most ``max_in_flight`` block tasks running
    (backpressure: upstream is only pulled when a slot frees),
  - blocks stream through the object store as ObjectRefs — the driver never
    holds more than a prefetch window of materialized data,
  - barrier ops (repartition / shuffle / sort) materialize their input ref
    list but still produce a streaming output.

Per-op wall-clock and task counts are recorded for Dataset.stats().
"""

from __future__ import annotations

import collections
import time
from typing import Any, Callable, Iterator

import ray_tpu
from ray_tpu.data.block import BlockAccessor, normalize_block

DEFAULT_MAX_IN_FLIGHT = 8


# Remote helpers live at module scope: workers import ray_tpu.data, so these
# ship by reference (cheap); user fns inside op specs cloudpickle by value.
@ray_tpu.remote
def _run_read_task(read_fn) -> Any:
    return normalize_block(read_fn())


@ray_tpu.remote
def _apply_op(fn, block) -> Any:
    return normalize_block(fn(block))


@ray_tpu.remote
def _count_rows(block) -> int:
    return BlockAccessor.for_block(block).num_rows()


@ray_tpu.remote
def _slice_block(block, start, end) -> Any:
    return BlockAccessor.for_block(block).slice(start, end)


@ray_tpu.remote
def _concat_blocks(*blocks) -> Any:
    return BlockAccessor.concat(list(blocks))


class OpStats:
    def __init__(self, name: str):
        self.name = name
        self.tasks = 0
        self.wall_s = 0.0

    def row(self) -> str:
        return f"{self.name}: {self.tasks} tasks, {self.wall_s:.2f}s wall"


class Operator:
    """Base logical op. ``transform`` rewrites a stream of block refs."""

    name = "op"

    def transform(self, refs: Iterator, stats: OpStats) -> Iterator:
        raise NotImplementedError


class MapBlocks(Operator):
    """map_batches / map / filter / flat_map all lower to this
    (ref: execution/operators/map_operator.py)."""

    def __init__(self, name: str, fn: Callable, max_in_flight: int | None = None):
        self.name = name
        self.fn = fn
        self.max_in_flight = max_in_flight or DEFAULT_MAX_IN_FLIGHT

    def transform(self, refs, stats):
        inflight: collections.deque = collections.deque()
        t0 = time.perf_counter()
        try:
            for ref in refs:
                while len(inflight) >= self.max_in_flight:
                    yield inflight.popleft()  # ordered: wait for the head
                inflight.append(_apply_op.remote(self.fn, ref))
                stats.tasks += 1
            while inflight:
                yield inflight.popleft()
        finally:
            stats.wall_s += time.perf_counter() - t0


class LimitOp(Operator):
    name = "limit"

    def __init__(self, n: int):
        self.n = n

    def transform(self, refs, stats):
        remaining = self.n
        t0 = time.perf_counter()
        try:
            for ref in refs:
                if remaining <= 0:
                    return
                count = ray_tpu.get(_count_rows.remote(ref))
                if count <= remaining:
                    remaining -= count
                    yield ref
                else:
                    yield _slice_block.remote(ref, 0, remaining)
                    remaining = 0
                    return
        finally:
            stats.wall_s += time.perf_counter() - t0


class RepartitionOp(Operator):
    """Barrier: rebalance the stream into ``num_blocks`` equal-ish blocks
    (ref: data repartition; the all-to-all exchange reduced to slice+concat
    tasks — no driver materialization of data, only of refs)."""

    name = "repartition"

    def __init__(self, num_blocks: int):
        self.num_blocks = num_blocks

    def transform(self, refs, stats):
        t0 = time.perf_counter()
        in_refs = list(refs)
        counts = ray_tpu.get([_count_rows.remote(r) for r in in_refs])
        total = sum(counts)
        stats.tasks += len(in_refs)
        if total == 0:
            stats.wall_s += time.perf_counter() - t0
            return
        # target row ranges per output block
        base, rem = divmod(total, self.num_blocks)
        sizes = [base + (1 if i < rem else 0) for i in range(self.num_blocks)]
        # map global row ranges onto (input block, local range) slices
        starts = []
        pos = 0
        for c in counts:
            starts.append(pos)
            pos += c
        out_pos = 0
        for size in sizes:
            if size == 0:
                continue
            pieces = []
            need_start, need_end = out_pos, out_pos + size
            for (bstart, c, ref) in zip(starts, counts, in_refs):
                bend = bstart + c
                lo, hi = max(need_start, bstart), min(need_end, bend)
                if lo < hi:
                    if lo == bstart and hi == bend:
                        pieces.append(ref)
                    else:
                        pieces.append(_slice_block.remote(ref, lo - bstart, hi - bstart))
                        stats.tasks += 1
            out_pos = need_end
            if len(pieces) == 1:
                yield pieces[0]
            else:
                stats.tasks += 1
                yield _concat_blocks.remote(*pieces)
        stats.wall_s += time.perf_counter() - t0


class ShuffleOp(Operator):
    """Barrier: random permutation of rows (ref: push-based shuffle reduced
    to a two-stage map: permute block order + per-block row shuffle + round-
    robin re-slice; exact global shuffle at this scale)."""

    name = "random_shuffle"

    def __init__(self, seed: int | None = None):
        self.seed = seed

    def transform(self, refs, stats):
        import numpy as np

        t0 = time.perf_counter()
        in_refs = list(refs)
        if not in_refs:
            return
        rng = np.random.RandomState(self.seed)
        seed_for = [int(rng.randint(0, 2**31 - 1)) for _ in in_refs]

        def shuffle_rows(block, s):
            acc = BlockAccessor.for_block(block)
            n = acc.num_rows()
            perm = np.random.RandomState(s).permutation(n)
            if isinstance(block, dict):
                return {k: np.asarray(v)[perm] for k, v in block.items()}
            return [block[i] for i in perm]

        shuffled = [
            _apply_op.remote(lambda b, s=s: shuffle_rows(b, s), r)
            for r, s in zip(in_refs, seed_for)
        ]
        stats.tasks += len(shuffled)
        order = rng.permutation(len(shuffled))
        for i in order:
            yield shuffled[i]
        stats.wall_s += time.perf_counter() - t0


class SortOp(Operator):
    """Barrier: global sort by key (ref: sort_task_spec.py two-phase
    sample/partition sort, collapsed to sort-merge at this scale)."""

    name = "sort"

    def __init__(self, key, descending: bool = False):
        self.key = key
        self.descending = descending

    def transform(self, refs, stats):
        import numpy as np

        t0 = time.perf_counter()
        in_refs = list(refs)
        if not in_refs:
            return
        key, desc = self.key, self.descending

        def sort_block(block):
            acc = BlockAccessor.for_block(block)
            if isinstance(block, dict):
                idx = np.argsort(np.asarray(block[key]), kind="stable")
                if desc:
                    idx = idx[::-1]
                return {k: np.asarray(v)[idx] for k, v in block.items()}
            rows = list(acc.rows())
            getter = (lambda r: r[key]) if key else (lambda r: r)
            return sorted(rows, key=getter, reverse=desc)

        # sort each block, then a single merge task (fine at library scale;
        # the reference's sampled range partitioning is a perf upgrade here)
        sorted_refs = [_apply_op.remote(sort_block, r) for r in in_refs]
        stats.tasks += len(sorted_refs) + 1

        def merge(*blocks):
            b = BlockAccessor.concat(list(blocks))
            return sort_block(b)

        yield _concat_and_apply.remote(merge, *sorted_refs)
        stats.wall_s += time.perf_counter() - t0


@ray_tpu.remote
def _concat_and_apply(fn, *blocks):
    return normalize_block(fn(*blocks))


class Plan:
    """Source + operator chain (ref: LogicalPlan/PhysicalPlan collapsed —
    op fusion is XLA's job on-device; host-side fusion here is just chained
    MapBlocks with no barrier between them)."""

    def __init__(self, read_tasks: list[Callable], ops: tuple = ()):
        self.read_tasks = list(read_tasks)
        self.ops = tuple(ops)

    def with_op(self, op: Operator) -> "Plan":
        return Plan(self.read_tasks, (*self.ops, op))

    def execute(self, max_source_in_flight: int = DEFAULT_MAX_IN_FLIGHT):
        """Returns (iterator of block refs, list[OpStats])."""
        all_stats = [OpStats("read")]

        def source():
            inflight: collections.deque = collections.deque()
            t0 = time.perf_counter()
            for rt in self.read_tasks:
                while len(inflight) >= max_source_in_flight:
                    yield inflight.popleft()
                inflight.append(_run_read_task.remote(rt))
                all_stats[0].tasks += 1
            while inflight:
                yield inflight.popleft()
            all_stats[0].wall_s += time.perf_counter() - t0

        stream = source()
        for op in self.ops:
            st = OpStats(op.name)
            all_stats.append(st)
            stream = op.transform(stream, st)
        return stream, all_stats

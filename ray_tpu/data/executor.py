"""Streaming block executor: bounded in-flight task-parallel execution.

The engine behind Dataset consumption — the reference's StreamingExecutor
shape (ref: python/ray/data/_internal/execution/streaming_executor.py:52,
OpState backpressure :167, task/actor pool map operators) reduced to its
load-bearing ideas:

  - the plan is a chain of block operators over a lazy source,
  - each operator keeps at most ``max_in_flight`` block tasks running
    (backpressure: upstream is only pulled when a slot frees),
  - blocks stream through the object store as ObjectRefs — the driver never
    holds more than a prefetch window of materialized data,
  - barrier ops (repartition / shuffle / sort) materialize their input ref
    list but still produce a streaming output.

Per-op wall-clock and task counts are recorded for Dataset.stats().
"""

from __future__ import annotations

import logging
import collections
import time
from typing import Any, Callable, Iterator

import ray_tpu
from ray_tpu.data.block import BlockAccessor, normalize_block

_log = logging.getLogger(__name__)

DEFAULT_MAX_IN_FLIGHT = 8


# Remote helpers live at module scope: workers import ray_tpu.data, so these
# ship by reference (cheap); user fns inside op specs cloudpickle by value.
@ray_tpu.remote
def _run_read_task(read_fn) -> Any:
    return normalize_block(read_fn())


@ray_tpu.remote
def _apply_op(fn, block) -> Any:
    return normalize_block(fn(block))


@ray_tpu.remote
def _apply_op_indexed(fn, index, block) -> Any:
    return normalize_block(fn(block, index))


@ray_tpu.remote
def _count_rows(block) -> int:
    return BlockAccessor.for_block(block).num_rows()


@ray_tpu.remote
def _slice_block(block, start, end) -> Any:
    return BlockAccessor.for_block(block).slice(start, end)


@ray_tpu.remote
def _concat_blocks(*blocks) -> Any:
    return BlockAccessor.concat(list(blocks))


class OpStats:
    def __init__(self, name: str):
        self.name = name
        self.tasks = 0
        self.wall_s = 0.0

    def row(self) -> str:
        return f"{self.name}: {self.tasks} tasks, {self.wall_s:.2f}s wall"


class Operator:
    """Base logical op. ``transform`` rewrites a stream of block refs."""

    name = "op"

    def transform(self, refs: Iterator, stats: OpStats) -> Iterator:
        raise NotImplementedError


class InjectRefs(Operator):
    """Source-style op: yields pre-computed block refs (join outputs and
    other already-launched distributed results) into the stream."""

    def __init__(self, name: str, refs: list):
        self.name = name
        self.refs = list(refs)

    def transform(self, refs: Iterator, stats: OpStats) -> Iterator:
        def gen():
            yield from refs  # upstream (usually empty for a ref source)
            yield from self.refs
            stats.tasks += len(self.refs)

        return gen()


class MapBlocks(Operator):
    """map_batches / map / filter / flat_map all lower to this
    (ref: execution/operators/map_operator.py)."""

    def __init__(self, name: str, fn: Callable, max_in_flight: int | None = None,
                 preserves_rows: bool = False, indexed: bool = False):
        self.name = name
        self.fn = fn
        self.max_in_flight = max_in_flight or DEFAULT_MAX_IN_FLIGHT
        # optimizer metadata (data/optimizer.py): True only when the op
        # emits exactly one output row per input row (map, add_column,
        # select_columns — NOT filter/flat_map/map_batches)
        self.preserves_rows = preserves_rows
        # indexed ops receive (block, stream_index) — per-block seeds etc.
        self.indexed = indexed

    def transform(self, refs, stats):
        inflight: collections.deque = collections.deque()
        t0 = time.perf_counter()
        try:
            for i, ref in enumerate(refs):
                while len(inflight) >= self.max_in_flight:
                    yield inflight.popleft()  # ordered: wait for the head
                if self.indexed:
                    inflight.append(_apply_op_indexed.remote(self.fn, i, ref))
                else:
                    inflight.append(_apply_op.remote(self.fn, ref))
                stats.tasks += 1
            while inflight:
                yield inflight.popleft()
        finally:
            stats.wall_s += time.perf_counter() - t0


class _MapWorker:
    """Stateful map actor (ref: _MapWorker in
    execution/operators/actor_pool_map_operator.py): constructs the
    user's callable class ONCE, then applies it per block — the whole
    point of actor compute is amortizing expensive setup (model loads,
    connections) across blocks."""

    def __init__(self, fn_or_cls, fn_constructor_args: tuple,
                 fn_constructor_kwargs: dict):
        if isinstance(fn_or_cls, type):
            self._fn = fn_or_cls(*fn_constructor_args,
                                 **fn_constructor_kwargs)
        else:
            self._fn = fn_or_cls

    def apply(self, block):
        from ray_tpu.data.block import normalize_block

        return normalize_block(self._fn(block))


class ActorPoolMapBlocks(Operator):
    """Map over a pool of stateful actors (ref:
    execution/operators/actor_pool_map_operator.py + ActorPoolStrategy):
    blocks dispatch to the least-loaded live actor, bounded in flight;
    output order is preserved. Actors are created lazily on first use and
    killed when the stream ends."""

    def __init__(self, name: str, fn_or_cls, *, size: int = 2,
                 max_tasks_per_actor: int = 2,
                 fn_constructor_args: tuple = (),
                 fn_constructor_kwargs: dict | None = None,
                 num_cpus: float = 1.0):
        self.name = name
        self.fn_or_cls = fn_or_cls
        self.size = max(1, int(size))
        self.max_tasks_per_actor = max(1, int(max_tasks_per_actor))
        self.fn_constructor_args = tuple(fn_constructor_args)
        self.fn_constructor_kwargs = dict(fn_constructor_kwargs or {})
        self.num_cpus = num_cpus

    def transform(self, refs, stats):
        t0 = time.perf_counter()
        WorkerCls = ray_tpu.remote(_MapWorker).options(num_cpus=self.num_cpus)
        actors = [
            WorkerCls.remote(
                self.fn_or_cls, self.fn_constructor_args,
                self.fn_constructor_kwargs)
            for _ in range(self.size)
        ]
        load = [0] * self.size
        inflight: collections.deque = collections.deque()  # (ref, actor_i)
        issued: list = []
        cap = self.size * self.max_tasks_per_actor
        try:
            for ref in refs:
                while len(inflight) >= cap:
                    done, ai = inflight.popleft()
                    load[ai] -= 1
                    yield done
                ai = min(range(self.size), key=load.__getitem__)
                load[ai] += 1
                out = actors[ai].apply.remote(ref)
                issued.append(out)
                inflight.append((out, ai))
                stats.tasks += 1
            while inflight:
                done, ai = inflight.popleft()
                load[ai] -= 1
                yield done
        finally:
            stats.wall_s += time.perf_counter() - t0
            # yielded refs may still be BACKED by pending actor tasks (a
            # downstream barrier op collects refs before resolving them):
            # the pool must outlive every issued task, not just the
            # generator — wait without fetching, then kill
            try:
                if issued:
                    ray_tpu.wait(issued, num_returns=len(issued),
                                 timeout=600, fetch_local=False)
            except Exception:
                _log.debug("drain-before-kill wait failed", exc_info=True)
            for a in actors:
                try:
                    ray_tpu.kill(a)
                except Exception:
                    _log.debug("actor kill failed", exc_info=True)


class LimitOp(Operator):
    name = "limit"

    def __init__(self, n: int):
        self.n = n

    def transform(self, refs, stats):
        remaining = self.n
        t0 = time.perf_counter()
        try:
            for ref in refs:
                if remaining <= 0:
                    return
                # limit stays lazy: count blocks one at a time and stop at
                # the cut instead of forcing the whole upstream stream
                count = ray_tpu.get(_count_rows.remote(ref))  # raylint: disable=RT002
                if count <= remaining:
                    remaining -= count
                    yield ref
                else:
                    yield _slice_block.remote(ref, 0, remaining)
                    remaining = 0
                    return
        finally:
            stats.wall_s += time.perf_counter() - t0


class RepartitionOp(Operator):
    """Barrier: rebalance the stream into ``num_blocks`` equal-ish blocks
    (ref: data repartition; the all-to-all exchange reduced to slice+concat
    tasks — no driver materialization of data, only of refs)."""

    name = "repartition"

    def __init__(self, num_blocks: int):
        self.num_blocks = num_blocks

    def transform(self, refs, stats):
        t0 = time.perf_counter()
        in_refs = list(refs)
        counts = ray_tpu.get([_count_rows.remote(r) for r in in_refs])
        total = sum(counts)
        stats.tasks += len(in_refs)
        if total == 0:
            stats.wall_s += time.perf_counter() - t0
            return
        # target row ranges per output block
        base, rem = divmod(total, self.num_blocks)
        sizes = [base + (1 if i < rem else 0) for i in range(self.num_blocks)]
        # map global row ranges onto (input block, local range) slices
        starts = []
        pos = 0
        for c in counts:
            starts.append(pos)
            pos += c
        out_pos = 0
        for size in sizes:
            if size == 0:
                continue
            pieces = []
            need_start, need_end = out_pos, out_pos + size
            for (bstart, c, ref) in zip(starts, counts, in_refs):
                bend = bstart + c
                lo, hi = max(need_start, bstart), min(need_end, bend)
                if lo < hi:
                    if lo == bstart and hi == bend:
                        pieces.append(ref)
                    else:
                        pieces.append(_slice_block.remote(ref, lo - bstart, hi - bstart))
                        stats.tasks += 1
            out_pos = need_end
            if len(pieces) == 1:
                yield pieces[0]
            else:
                stats.tasks += 1
                yield _concat_blocks.remote(*pieces)
        stats.wall_s += time.perf_counter() - t0


def _shuffle_rows(block, s):
    import numpy as np

    acc = BlockAccessor.for_block(block)
    n = acc.num_rows()
    perm = np.random.RandomState(s).permutation(n)
    return acc.take(perm)


@ray_tpu.remote
def _shuffle_split(block, seed: int, n_parts: int):
    """Map stage of the push-based shuffle: randomly permute this block's
    rows and cut them into n_parts slices (one per merger). Called with
    num_returns=n_parts so the slices stay in the object plane — the
    driver only ever handles refs."""
    import numpy as np

    block = _shuffle_rows(block, seed)
    acc = BlockAccessor.for_block(block)
    n = acc.num_rows()
    bounds = np.linspace(0, n, n_parts + 1).astype(int)
    parts = tuple(acc.slice(int(bounds[i]), int(bounds[i + 1]))
                  for i in range(n_parts))
    return parts if n_parts > 1 else parts[0]


@ray_tpu.remote
def _shuffle_merge(seed: int, *parts):
    """Merge stage: concatenate one partition's slices from every mapper
    (or every round-merge) and re-permute rows."""
    merged = BlockAccessor.concat([p for p in parts
                                   if BlockAccessor.for_block(p).num_rows()])
    return normalize_block(_shuffle_rows(merged, seed))


class ShuffleOp(Operator):
    """Barrier: exact global random permutation of rows.

    Small inputs use the simple per-block permute + reorder. Larger ones
    run a PUSH-BASED two-stage shuffle (ref: _internal/planner/exchange/
    push_based_shuffle_task_scheduler.py): mappers split each block into P
    random slices; merges run in ROUNDS as mapper outputs appear, so merge
    work overlaps the map stage and no single task ever touches more than
    ~round_size block slices — the property that lets the reference
    shuffle 100TB without head-of-line materialization."""

    name = "random_shuffle"
    PUSH_THRESHOLD = 8  # blocks; below this the simple path is cheaper
    ROUND = 4           # mappers per merge round

    def __init__(self, seed: int | None = None):
        self.seed = seed

    def transform(self, refs, stats):
        import numpy as np

        t0 = time.perf_counter()
        in_refs = list(refs)
        if not in_refs:
            return
        rng = np.random.RandomState(self.seed)
        try:
            if len(in_refs) <= self.PUSH_THRESHOLD:
                yield from self._simple(in_refs, rng, stats)
            else:
                yield from self._push_based(in_refs, rng, stats)
        finally:
            stats.wall_s += time.perf_counter() - t0

    def _simple(self, in_refs, rng, stats):
        seed_for = [int(rng.randint(0, 2**31 - 1)) for _ in in_refs]
        shuffled = [
            _apply_op.remote(lambda b, s=s: _shuffle_rows(b, s), r)
            for r, s in zip(in_refs, seed_for)
        ]
        stats.tasks += len(shuffled)
        for i in rng.permutation(len(shuffled)):
            yield shuffled[i]

    def _push_based(self, in_refs, rng, stats):
        n_parts = max(2, min(len(in_refs),
                             int(len(in_refs) ** 0.5) + 1))
        # per-partition accumulators of round-merge refs
        partials: list[list] = [[] for _ in range(n_parts)]
        round_splits: list = []

        def flush_round():
            # partial merges per partition over this round's mappers:
            # merge work starts while later mappers still run (the "push")
            for p in range(n_parts):
                parts = [splits[p] for splits in round_splits]
                if parts:
                    partials[p].append(_shuffle_merge.remote(
                        int(rng.randint(0, 2**31 - 1)), *parts))
                    stats.tasks += 1
            round_splits.clear()

        split_task = _shuffle_split.options(num_returns=n_parts)
        for r in in_refs:
            split = split_task.remote(
                r, int(rng.randint(0, 2**31 - 1)), n_parts)
            stats.tasks += 1
            round_splits.append(split if isinstance(split, list) else [split])
            if len(round_splits) >= self.ROUND:
                flush_round()
        flush_round()
        out = [
            _shuffle_merge.remote(int(rng.randint(0, 2**31 - 1)), *parts)
            for parts in partials if parts
        ]
        stats.tasks += len(out)
        for i in rng.permutation(len(out)):
            yield out[i]


class SortOp(Operator):
    """Barrier: global sort by key (ref: sort_task_spec.py two-phase
    sample/partition sort, collapsed to sort-merge at this scale)."""

    name = "sort"

    def __init__(self, key, descending: bool = False):
        self.key = key
        self.descending = descending

    def transform(self, refs, stats):
        import numpy as np

        t0 = time.perf_counter()
        in_refs = list(refs)
        if not in_refs:
            return
        key, desc = self.key, self.descending

        def sort_block(block):
            acc = BlockAccessor.for_block(block)
            if acc.is_tabular():
                idx = np.argsort(acc.column(key), kind="stable")
                if desc:
                    idx = idx[::-1]
                return acc.take(idx)
            rows = list(acc.rows())
            getter = (lambda r: r[key]) if key else (lambda r: r)
            return sorted(rows, key=getter, reverse=desc)

        # sort each block, then a single merge task (fine at library scale;
        # the reference's sampled range partitioning is a perf upgrade here)
        sorted_refs = [_apply_op.remote(sort_block, r) for r in in_refs]
        stats.tasks += len(sorted_refs) + 1

        def merge(*blocks):
            b = BlockAccessor.concat(list(blocks))
            return sort_block(b)

        yield _concat_and_apply.remote(merge, *sorted_refs)
        stats.wall_s += time.perf_counter() - t0


@ray_tpu.remote
def _concat_and_apply(fn, *blocks):
    return normalize_block(fn(*blocks))


class Plan:
    """Source + operator chain (ref: LogicalPlan over the streaming
    executor). ``execute`` first runs the rule optimizer
    (data/optimizer.py: redundant-op elimination, limit/projection
    pushdown, map and read-map fusion), then streams the physical chain."""

    def __init__(self, read_tasks: list[Callable], ops: tuple = ()):
        self.read_tasks = list(read_tasks)
        self.ops = tuple(ops)

    def with_op(self, op: Operator) -> "Plan":
        return Plan(self.read_tasks, (*self.ops, op))

    def execute(self, max_source_in_flight: int = DEFAULT_MAX_IN_FLIGHT,
                _optimize: bool = True):
        """Returns (iterator of block refs, list[OpStats])."""
        if _optimize:
            from ray_tpu.data.optimizer import optimize

            return optimize(self).execute(max_source_in_flight,
                                          _optimize=False)
        all_stats = [OpStats("read")]

        def source():
            inflight: collections.deque = collections.deque()
            t0 = time.perf_counter()
            for rt in self.read_tasks:
                while len(inflight) >= max_source_in_flight:
                    yield inflight.popleft()
                inflight.append(_run_read_task.remote(rt))
                all_stats[0].tasks += 1
            while inflight:
                yield inflight.popleft()
            all_stats[0].wall_s += time.perf_counter() - t0

        stream = source()
        for op in self.ops:
            st = OpStats(op.name)
            all_stats.append(st)
            stream = op.transform(stream, st)
        return stream, all_stats

"""Batch iteration over a stream of block refs with prefetch.

(ref: python/ray/data/iterator.py DataIterator.iter_batches + the batcher in
_internal/batcher.py). Keeps ``prefetch`` block fetches in flight while the
consumer works — on a TPU host this overlaps host IO with device steps.
"""

from __future__ import annotations

import collections
from typing import Iterable, Iterator

import numpy as np

import ray_tpu
from ray_tpu.data.block import BlockAccessor


def iter_batches_over_refs(refs: Iterable, *, batch_size: int,
                           batch_format: str | None, drop_last: bool,
                           prefetch: int = 2) -> Iterator:
    spare = None  # leftover rows as a block
    for block in _prefetched_blocks(refs, prefetch):
        if spare is not None:
            block = BlockAccessor.concat([spare, block])
            spare = None
        acc = BlockAccessor.for_block(block)
        n = acc.num_rows()
        pos = 0
        while n - pos >= batch_size:
            yield BlockAccessor.for_block(
                acc.slice(pos, pos + batch_size)
            ).to_batch(batch_format)
            pos += batch_size
        if pos < n:
            spare = acc.slice(pos, n)
    if spare is not None and not drop_last:
        acc = BlockAccessor.for_block(spare)
        if acc.num_rows():
            yield acc.to_batch(batch_format)


def _prefetched_blocks(refs: Iterable, prefetch: int):
    window: collections.deque = collections.deque()
    it = iter(refs)
    try:
        for _ in range(max(1, prefetch)):
            window.append(next(it))
    except StopIteration:
        pass
    while window:
        block = ray_tpu.get(window.popleft())
        try:
            window.append(next(it))
        except StopIteration:
            pass
        yield block


class DataIterator:
    """One consumer's view of a streaming_split (ref: DataIterator API)."""

    def __init__(self, next_block_fn, name: str = "split"):
        self._next_block = next_block_fn
        self._name = name

    def _blocks(self):
        while True:
            block = self._next_block()
            if block is None:
                return
            yield block

    def iter_batches(self, *, batch_size: int = 256,
                     batch_format: str | None = "numpy",
                     drop_last: bool = False):
        spare = None
        for block in self._blocks():
            if spare is not None:
                block = BlockAccessor.concat([spare, block])
                spare = None
            acc = BlockAccessor.for_block(block)
            n = acc.num_rows()
            pos = 0
            while n - pos >= batch_size:
                yield BlockAccessor.for_block(
                    acc.slice(pos, pos + batch_size)
                ).to_batch(batch_format)
                pos += batch_size
            if pos < n:
                spare = acc.slice(pos, n)
        if spare is not None and not drop_last:
            acc = BlockAccessor.for_block(spare)
            if acc.num_rows():
                yield acc.to_batch(batch_format)

    def iter_rows(self):
        for block in self._blocks():
            yield from BlockAccessor.for_block(block).rows()

    def __repr__(self):
        return f"DataIterator({self._name})"

"""Block model: the unit of data that flows through the streaming executor.

Mirrors the reference's Block/BlockAccessor split (ref: python/ray/data/
block.py, _internal/arrow_block.py, _internal/numpy_support.py) with
Arrow as the canonical tabular layout:

  - pyarrow.Table:          canonical tabular block — zero-copy numpy
                            column views, zero-copy IPC reads from shm
                            (serialization.py packs tables as one Arrow
                            IPC out-of-band buffer), O(1) slice
  - dict[str, np.ndarray]:  fallback columnar for columns Arrow cannot
                            hold (arbitrary-object columns); multi-dim
                            tensor columns ride Arrow's
                            FixedShapeTensorArray (the reference's
                            ArrowTensorArray role)
  - list:                   simple row path

Batches are rendered in the caller's requested batch_format; "numpy"
renders zero-copy views where Arrow's layout allows, which is the
TPU-relevant property — blocks feed jax.device_put without row pivots.
"""

from __future__ import annotations

from typing import Any, Iterable

import numpy as np


def _pa():
    import pyarrow as pa

    return pa


def _is_table(block) -> bool:
    try:
        import pyarrow as pa
    except ImportError:  # pragma: no cover
        return False
    return isinstance(block, pa.Table)


def _is_tabular(block) -> bool:
    return isinstance(block, dict) or _is_table(block)


def columns_to_table(cols: dict):
    """numpy-dict -> pa.Table, or None when Arrow can't hold a column
    (object arrays of arbitrary Python values). Multi-dim columns become
    FixedShapeTensorArrays (ref: _internal/arrow_block.py tensor
    extension)."""
    pa = _pa()
    arrays = {}
    for k, v in cols.items():
        v = np.asarray(v)
        try:
            if v.ndim > 1:
                flat = np.ascontiguousarray(v)
                arrays[k] = pa.FixedShapeTensorArray.from_numpy_ndarray(flat)
            else:
                arr = pa.array(v)
                if pa.types.is_null(arr.type) and len(arr):
                    return None  # all-None object column: keep numpy
                arrays[k] = arr
        except (pa.ArrowInvalid, pa.ArrowNotImplementedError, pa.ArrowTypeError,
                ValueError, TypeError):
            return None
    return pa.table(arrays)


def _col_to_numpy(chunked) -> np.ndarray:
    """One column -> numpy, zero-copy where the layout allows."""
    pa = _pa()
    arr = chunked.combine_chunks() if hasattr(chunked, "combine_chunks") \
        else chunked
    if isinstance(arr, pa.ChunkedArray):
        arr = arr.chunk(0) if arr.num_chunks == 1 else pa.concat_arrays(
            arr.chunks)
    if isinstance(arr.type, pa.FixedShapeTensorType):
        return arr.to_numpy_ndarray()
    try:
        return arr.to_numpy(zero_copy_only=True)
    except pa.ArrowInvalid:
        return arr.to_numpy(zero_copy_only=False)


class BlockAccessor:
    """Uniform view over a block (ref: block.py BlockAccessor.for_block)."""

    def __init__(self, block):
        self.block = block

    @staticmethod
    def for_block(block) -> "BlockAccessor":
        return BlockAccessor(normalize_block(block))

    # ------------------------------------------------------------- basics
    def is_tabular(self) -> bool:
        return _is_tabular(self.block)

    def num_rows(self) -> int:
        if _is_table(self.block):
            return self.block.num_rows
        if isinstance(self.block, dict):
            if not self.block:
                return 0
            return len(next(iter(self.block.values())))
        return len(self.block)

    def size_bytes(self) -> int:
        if _is_table(self.block):
            return int(self.block.nbytes)
        if isinstance(self.block, dict):
            return int(sum(np.asarray(v).nbytes for v in self.block.values()))
        total = 0
        for row in self.block[:10]:
            total += _rough_size(row)
        n = len(self.block)
        return (total // max(1, min(10, n))) * n if n else 0

    def schema(self):
        if _is_table(self.block):
            return {name: self.block.schema.field(name).type
                    for name in self.block.column_names}
        if isinstance(self.block, dict):
            return {k: np.asarray(v).dtype for k, v in self.block.items()}
        if self.block:
            first = self.block[0]
            if isinstance(first, dict):
                return {k: type(v).__name__ for k, v in first.items()}
            return type(first).__name__
        return None

    # ------------------------------------------------------------ columnar
    def column_names(self) -> list[str]:
        if _is_table(self.block):
            return list(self.block.column_names)
        if isinstance(self.block, dict):
            return list(self.block)
        raise TypeError("row blocks have no columns")

    def columns(self) -> dict[str, np.ndarray]:
        """Tabular block -> numpy column dict (zero-copy views where the
        Arrow layout allows)."""
        if _is_table(self.block):
            return {name: _col_to_numpy(self.block[name])
                    for name in self.block.column_names}
        if isinstance(self.block, dict):
            return {k: np.asarray(v) for k, v in self.block.items()}
        raise TypeError("row blocks have no columns")

    def column(self, name: str) -> np.ndarray:
        if _is_table(self.block):
            return _col_to_numpy(self.block[name])
        if isinstance(self.block, dict):
            return np.asarray(self.block[name])
        raise TypeError("row blocks have no columns")

    def take(self, indices) -> Any:
        """Row-select by integer indices, preserving block kind."""
        indices = np.asarray(indices)
        if _is_table(self.block):
            return self.block.take(_pa().array(indices))
        if isinstance(self.block, dict):
            return {k: np.asarray(v)[indices] for k, v in self.block.items()}
        return [self.block[int(i)] for i in indices]

    def mask(self, m) -> Any:
        m = np.asarray(m, dtype=bool)
        if _is_table(self.block):
            return self.block.filter(_pa().array(m))
        if isinstance(self.block, dict):
            return {k: np.asarray(v)[m] for k, v in self.block.items()}
        return [r for r, keep in zip(self.block, m) if keep]

    # -------------------------------------------------------------- slices
    def slice(self, start: int, end: int):
        if _is_table(self.block):
            return self.block.slice(start, end - start)  # zero-copy
        if isinstance(self.block, dict):
            return {k: v[start:end] for k, v in self.block.items()}
        return self.block[start:end]

    def rows(self) -> Iterable[Any]:
        if _is_table(self.block):
            cols = self.columns()
            keys = list(cols)
            for i in range(self.block.num_rows):
                yield {k: cols[k][i] for k in keys}
        elif isinstance(self.block, dict):
            keys = list(self.block)
            for i in range(self.num_rows()):
                yield {k: self.block[k][i] for k in keys}
        else:
            yield from self.block

    # ------------------------------------------------------------- formats
    def to_batch(self, batch_format: str | None):
        """Render this block in the requested format
        (ref: data iter_batches batch_format semantics)."""
        if batch_format in (None, "default", "numpy"):
            if _is_tabular(self.block):
                return self.columns()
            if self.block and isinstance(self.block[0], dict):
                return rows_to_columns(self.block)
            return np.asarray(self.block)
        if batch_format == "rows":
            return list(self.rows())
        if batch_format == "pandas":
            import pandas as pd

            if _is_table(self.block):
                try:
                    return self.block.to_pandas()
                except Exception:
                    return pd.DataFrame(self.columns())
            if isinstance(self.block, dict):
                return pd.DataFrame(
                    {k: np.asarray(v) for k, v in self.block.items()})
            return pd.DataFrame(list(self.rows()))
        if batch_format == "pyarrow":
            pa = _pa()
            if _is_table(self.block):
                return self.block
            if isinstance(self.block, dict):
                t = columns_to_table(self.block)
                if t is None:
                    raise ValueError(
                        "block columns cannot be represented in Arrow")
                return t
            return pa.Table.from_pylist(list(self.rows()))
        raise ValueError(f"unknown batch_format {batch_format!r}")

    # ---------------------------------------------------------------- ops
    @staticmethod
    def concat(blocks: list) -> Any:
        blocks = [normalize_block(b) for b in blocks]
        blocks = [b for b in blocks if BlockAccessor(b).num_rows() > 0]
        if not blocks:
            return []
        if all(_is_table(b) for b in blocks):
            import pyarrow as pa

            try:
                return pa.concat_tables(blocks, promote_options="default")
            except (pa.ArrowException, TypeError):
                pass  # schema drift: fall through to columnar concat
        if all(_is_tabular(b) for b in blocks):
            cols = [BlockAccessor(b).columns() for b in blocks]
            keys = list(cols[0])
            merged = {k: np.concatenate([c[k] for c in cols]) for k in keys}
            t = columns_to_table(merged)
            return t if t is not None else merged
        out: list = []
        for b in blocks:
            out.extend(BlockAccessor(b).rows())
        return out


def normalize_block(batch) -> Any:
    """Accept user/edge formats; canonicalize tabular data to pa.Table
    (numpy-dict when Arrow can't hold a column), rows stay a list."""
    if batch is None:
        return []
    if _is_table(batch):
        return batch
    cols = None
    try:
        import pandas as pd

        if isinstance(batch, pd.DataFrame):
            cols = {c: batch[c].to_numpy() for c in batch.columns}
    except ImportError:  # pragma: no cover
        pass
    if cols is None:
        if isinstance(batch, dict):
            cols = {k: np.asarray(v) for k, v in batch.items()}
        elif isinstance(batch, np.ndarray):
            cols = {"data": batch}
        elif isinstance(batch, (list, tuple)):
            return list(batch)
        else:
            raise TypeError(f"cannot treat {type(batch)} as a block")
    t = columns_to_table(cols)
    return t if t is not None else cols


def rows_to_columns(rows: list[dict]) -> dict[str, np.ndarray]:
    if not rows:
        return {}
    keys = list(rows[0])
    return {k: np.asarray([r[k] for r in rows]) for k in keys}


def _rough_size(obj) -> int:
    if isinstance(obj, np.ndarray):
        return obj.nbytes
    if isinstance(obj, dict):
        return sum(_rough_size(v) for v in obj.values()) + 64
    if isinstance(obj, (bytes, str)):
        return len(obj)
    return 32

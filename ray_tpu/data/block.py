"""Block model: the unit of data that flows through the streaming executor.

Mirrors the reference's Block/BlockAccessor split (ref: python/ray/data/
block.py, _internal/arrow_block.py, _internal/numpy_support.py) with two
canonical layouts instead of four:

  - "rows":   list of Python objects (possibly dicts)      — simple path
  - "numpy":  dict[str, np.ndarray] columnar               — tensor path

pyarrow Tables / pandas DataFrames are accepted at the edges and converted;
batches are rendered in the caller's requested batch_format. Columnar numpy
is the TPU-relevant layout: blocks deserialize zero-copy from shm and feed
jax.device_put without row pivots.
"""

from __future__ import annotations

from typing import Any, Iterable

import numpy as np


def _is_tabular(block) -> bool:
    return isinstance(block, dict)


class BlockAccessor:
    """Uniform view over a block (ref: block.py BlockAccessor.for_block)."""

    def __init__(self, block):
        self.block = block

    @staticmethod
    def for_block(block) -> "BlockAccessor":
        return BlockAccessor(normalize_block(block))

    # ------------------------------------------------------------- basics
    def num_rows(self) -> int:
        if _is_tabular(self.block):
            if not self.block:
                return 0
            return len(next(iter(self.block.values())))
        return len(self.block)

    def size_bytes(self) -> int:
        if _is_tabular(self.block):
            return int(sum(np.asarray(v).nbytes for v in self.block.values()))
        total = 0
        for row in self.block[:10]:
            total += _rough_size(row)
        n = len(self.block)
        return (total // max(1, min(10, n))) * n if n else 0

    def schema(self):
        if _is_tabular(self.block):
            return {k: np.asarray(v).dtype for k, v in self.block.items()}
        if self.block:
            first = self.block[0]
            if isinstance(first, dict):
                return {k: type(v).__name__ for k, v in first.items()}
            return type(first).__name__
        return None

    # -------------------------------------------------------------- slices
    def slice(self, start: int, end: int):
        if _is_tabular(self.block):
            return {k: v[start:end] for k, v in self.block.items()}
        return self.block[start:end]

    def rows(self) -> Iterable[Any]:
        if _is_tabular(self.block):
            keys = list(self.block)
            for i in range(self.num_rows()):
                yield {k: self.block[k][i] for k in keys}
        else:
            yield from self.block

    # ------------------------------------------------------------- formats
    def to_batch(self, batch_format: str | None):
        """Render this block in the requested format
        (ref: data iter_batches batch_format semantics)."""
        if batch_format in (None, "default", "numpy"):
            if _is_tabular(self.block):
                return {k: np.asarray(v) for k, v in self.block.items()}
            if self.block and isinstance(self.block[0], dict):
                return rows_to_columns(self.block)
            return np.asarray(self.block)
        if batch_format == "rows":
            return list(self.rows())
        if batch_format == "pandas":
            import pandas as pd

            if _is_tabular(self.block):
                return pd.DataFrame({k: np.asarray(v) for k, v in self.block.items()})
            return pd.DataFrame(list(self.rows()))
        if batch_format == "pyarrow":
            import pyarrow as pa

            if _is_tabular(self.block):
                return pa.table({k: np.asarray(v) for k, v in self.block.items()})
            return pa.Table.from_pylist(list(self.rows()))
        raise ValueError(f"unknown batch_format {batch_format!r}")

    # ---------------------------------------------------------------- ops
    @staticmethod
    def concat(blocks: list) -> Any:
        blocks = [normalize_block(b) for b in blocks if BlockAccessor(b).num_rows() or True]
        blocks = [b for b in blocks if BlockAccessor(b).num_rows() > 0]
        if not blocks:
            return []
        if all(_is_tabular(b) for b in blocks):
            keys = list(blocks[0])
            return {k: np.concatenate([np.asarray(b[k]) for b in blocks]) for k in keys}
        out: list = []
        for b in blocks:
            out.extend(BlockAccessor(b).rows())
        return out


def normalize_block(batch) -> Any:
    """Accept user/edge formats, store canonically (rows list or numpy dict)."""
    if batch is None:
        return []
    try:
        import pandas as pd

        if isinstance(batch, pd.DataFrame):
            return {c: batch[c].to_numpy() for c in batch.columns}
    except ImportError:  # pragma: no cover
        pass
    try:
        import pyarrow as pa

        if isinstance(batch, pa.Table):
            return {c: batch[c].to_numpy(zero_copy_only=False) for c in batch.column_names}
    except ImportError:  # pragma: no cover
        pass
    if isinstance(batch, dict):
        return {k: np.asarray(v) for k, v in batch.items()}
    if isinstance(batch, np.ndarray):
        return {"data": batch}
    if isinstance(batch, (list, tuple)):
        return list(batch)
    raise TypeError(f"cannot treat {type(batch)} as a block")


def rows_to_columns(rows: list[dict]) -> dict[str, np.ndarray]:
    if not rows:
        return {}
    keys = list(rows[0])
    return {k: np.asarray([r[k] for r in rows]) for k in keys}


def _rough_size(obj) -> int:
    if isinstance(obj, np.ndarray):
        return obj.nbytes
    if isinstance(obj, dict):
        return sum(_rough_size(v) for v in obj.values()) + 64
    if isinstance(obj, (bytes, str)):
        return len(obj)
    return 32

"""Workflow authoring + durable execution engine.

TPU-native counterpart of the reference workflow engine (ref:
python/ray/workflow/workflow_executor.py, step checkpointing
task_executor.py + workflow_storage.py). Design:

- @workflow.step wraps a function; .bind() builds a static DAG node
  (same authoring shape as compiled graphs / the reference's DAG API).
- run(dag, workflow_id) executes steps as ray_tpu tasks in dependency
  order; every completed step's result is pickled to the storage dir
  (filesystem — durable across driver and cluster restarts, the
  reference's default local storage role).
- resume(workflow_id) reloads the DAG definition itself from storage
  (cloudpickle) and replays: completed steps short-circuit to their
  checkpointed results; pending steps execute. Nothing about the
  original driver process is needed.
- Step failures retry per-step (max_retries); a failed workflow keeps
  its partial checkpoints and can resume after the bug/outage is fixed.
"""
from __future__ import annotations

import logging
import json
import os
import time
import uuid
from typing import Any, Callable

_log = logging.getLogger(__name__)

try:
    import cloudpickle
except ImportError:  # pragma: no cover
    import pickle as cloudpickle

DEFAULT_STORAGE = os.path.expanduser("~/.ray_tpu/workflows")

RUNNING = "RUNNING"
SUCCESSFUL = "SUCCESSFUL"
FAILED = "FAILED"


def _storage_root() -> str:
    return os.environ.get("RAY_TPU_WORKFLOW_STORAGE", DEFAULT_STORAGE)


def _wf_dir(workflow_id: str) -> str:
    return os.path.join(_storage_root(), workflow_id)


# ------------------------------------------------------------------ authoring
class WorkflowStep:
    """A step definition (ref: workflow step decorator)."""

    def __init__(self, fn: Callable, *, name: str | None = None,
                 max_retries: int = 0, num_cpus: float = 1.0,
                 resources: dict | None = None):
        self.fn = fn
        self.name = name or fn.__name__
        self.max_retries = max_retries
        self.num_cpus = num_cpus
        self.resources = resources or {}

    def options(self, **kw) -> "WorkflowStep":
        merged = dict(name=self.name, max_retries=self.max_retries,
                      num_cpus=self.num_cpus, resources=self.resources)
        merged.update(kw)
        return WorkflowStep(self.fn, **merged)

    def bind(self, *args, **kwargs) -> "StepNode":
        return StepNode(self, args, kwargs)

    def __call__(self, *a, **k):
        return self.fn(*a, **k)  # direct call runs locally (debugging)


class StepNode:
    """DAG node: a step bound to (possibly node-valued) arguments.
    step_id is assigned by _assign_ids at run time from the DAG's own
    structure (DFS order), so identical DAGs get identical ids no matter
    what else the process built before them."""

    def __init__(self, step: WorkflowStep, args: tuple, kwargs: dict):
        self.step = step
        self.args = args
        self.kwargs = kwargs
        self.step_id: str | None = None


def _assign_ids(dag: StepNode) -> None:
    """Deterministic ids: <name>_<k> by first-visit DFS order over args
    then kwargs (sorted). Persisted ids in a stored DAG are kept."""
    counters: dict[str, int] = {}
    seen: set[int] = set()

    def visit(node: StepNode):
        if id(node) in seen:
            return
        seen.add(id(node))
        for a in node.args:
            if isinstance(a, StepNode):
                visit(a)
        for k in sorted(node.kwargs):
            v = node.kwargs[k]
            if isinstance(v, StepNode):
                visit(v)
        if node.step_id is None:
            counters[node.step.name] = counters.get(node.step.name, 0) + 1
            node.step_id = f"{node.step.name}_{counters[node.step.name]}"

    visit(dag)


def step(fn=None, *, name: str | None = None, max_retries: int = 0,
         num_cpus: float = 1.0, resources: dict | None = None):
    """@workflow.step decorator."""

    def wrap(f):
        return WorkflowStep(f, name=name, max_retries=max_retries,
                            num_cpus=num_cpus, resources=resources)

    if fn is not None:
        return wrap(fn)
    return wrap


# ------------------------------------------------------------------ storage
class _Storage:
    """Filesystem checkpoint layout (ref: workflow_storage.py):
    <root>/<workflow_id>/{dag.pkl, status.json, steps/<step_id>.pkl}"""

    def __init__(self, workflow_id: str):
        self.workflow_id = workflow_id
        self.dir = _wf_dir(workflow_id)

    def _ensure_dirs(self):
        # write paths only: reads of unknown ids must not create phantom
        # workflow directories that pollute list_all()/resume_all()
        os.makedirs(os.path.join(self.dir, "steps"), exist_ok=True)

    def save_dag(self, dag: StepNode):
        self._ensure_dirs()
        with open(os.path.join(self.dir, "dag.pkl"), "wb") as f:
            cloudpickle.dump(dag, f)

    def load_dag(self) -> StepNode:
        with open(os.path.join(self.dir, "dag.pkl"), "rb") as f:
            return cloudpickle.load(f)

    def set_status(self, status: str, error: str | None = None):
        self._ensure_dirs()
        with open(os.path.join(self.dir, "status.json"), "w") as f:
            json.dump({"status": status, "error": error, "ts": time.time()}, f)

    def get_status(self) -> dict:
        try:
            with open(os.path.join(self.dir, "status.json")) as f:
                return json.load(f)
        except FileNotFoundError:
            return {"status": "NOT_FOUND"}

    def step_path(self, step_id: str) -> str:
        return os.path.join(self.dir, "steps", f"{step_id}.pkl")

    def has_step(self, step_id: str) -> bool:
        return os.path.exists(self.step_path(step_id))

    def save_step(self, step_id: str, value: Any):
        self._ensure_dirs()
        tmp = self.step_path(step_id) + ".tmp"
        with open(tmp, "wb") as f:
            cloudpickle.dump(value, f)
        os.replace(tmp, self.step_path(step_id))  # atomic: no torn results

    def load_step(self, step_id: str) -> Any:
        with open(self.step_path(step_id), "rb") as f:
            return cloudpickle.load(f)


# ------------------------------------------------------------------ executor
def _execute(dag: StepNode, storage: _Storage) -> Any:
    """DAG execution with checkpoint short-circuiting. Independent
    branches run in parallel: steps receive upstream ObjectRefs and the
    runtime's dependency resolution does the waiting; the driver then
    drains results in submission (topological) order to checkpoint them."""
    import ray_tpu
    from ray_tpu.core.ref import ObjectRef

    memo: dict[str, Any] = {}  # step_id -> ObjectRef | checkpointed value
    order: list[tuple[StepNode, Any]] = []  # submitted, pending checkpoint

    def submit(node: StepNode) -> Any:
        if node.step_id in memo:
            return memo[node.step_id]
        if storage.has_step(node.step_id):
            value = storage.load_step(node.step_id)  # replay from checkpoint
            memo[node.step_id] = value
            return value
        args = [submit(a) if isinstance(a, StepNode) else a for a in node.args]
        kwargs = {k: submit(v) if isinstance(v, StepNode) else v
                  for k, v in node.kwargs.items()}
        if getattr(node.step, "_rt_event_listener", None) is not None:
            # event waits poll keys scoped to THIS workflow first (see
            # KVEventListener.event_keys) so runs can't consume each
            # other's payloads
            kwargs["_wf_event_scope"] = storage.workflow_id
        remote_fn = ray_tpu.remote(node.step.fn)
        ref = remote_fn.options(
            num_cpus=node.step.num_cpus,
            resources=node.step.resources or None,
            max_retries=node.step.max_retries,
            name=f"wf:{node.step_id}",
        ).remote(*args, **kwargs)
        memo[node.step_id] = ref
        order.append((node, ref))
        return ref

    out = submit(dag)
    # per-step get is load-bearing for durability: each step checkpoints
    # the moment it completes, so a crash mid-workflow resumes from the
    # last saved step; one batched get would checkpoint all-or-nothing
    for node, ref in order:  # topological: deps checkpoint before dependents
        storage.save_step(node.step_id, ray_tpu.get(ref))  # raylint: disable=RT002
        listener = getattr(node.step, "_rt_event_listener", None)
        if listener is not None:
            # the payload is checkpointed now — delete the consumed KV
            # entry so a later run's wait can't short-circuit on it and
            # event blobs stop accumulating in the GCS WAL/snapshot
            _cleanup_event_keys(listener, storage.workflow_id, node)
    if isinstance(out, ObjectRef):
        return ray_tpu.get(out)
    return out


def _cleanup_event_keys(listener_cls, workflow_id: str, node: StepNode) -> None:
    """Best-effort delete of the CONSUMED event's KV entry, AFTER the
    waiting step checkpointed its result — a crash before the checkpoint
    must keep the payload for the re-wait.

    Only what the wait ACTUALLY consumed is deleted: the poll records the
    consumed key under a marker entry (see consumed_marker), so a sibling
    payload under the other candidate key — e.g. a shared-key event
    addressed to a different workflow, or a freshly posted scoped event
    for this workflow's NEXT wait — is never collaterally destroyed."""
    keys_fn = getattr(listener_cls, "event_keys", None)
    if keys_fn is None or not node.args:
        return
    try:
        from ray_tpu.core import api as _core_api

        core = _core_api.get_core()
        targets = []
        marker = None
        marker_fn = getattr(listener_cls, "consumed_marker", None)
        if marker_fn is not None:
            marker = marker_fn(workflow_id, node.args[0])
            consumed = core._run_sync(core.gcs.call(
                "kv_get", {"ns": listener_cls.NS, "key": marker}))
            if consumed is not None:
                targets = [consumed.decode()]
        if not targets:
            # no marker (replayed-from-checkpoint node, legacy DAG): the
            # conservative fallback deletes only the scoped key, which is
            # addressed to this workflow by construction
            candidates = keys_fn(workflow_id, node.args[0])
            targets = candidates[:1] if len(candidates) > 1 else candidates
        for k in targets:
            core._run_sync(core.gcs.call(
                "kv_del", {"ns": listener_cls.NS, "key": k}))
        if marker is not None:
            core._run_sync(core.gcs.call(
                "kv_del", {"ns": listener_cls.NS, "key": marker}))
    except Exception:
        # a failed delete only leaves a stale blob behind
        _log.debug("workflow event cleanup failed", exc_info=True)


def _run_to_completion(storage: _Storage, dag: StepNode) -> Any:
    storage.set_status(RUNNING)
    try:
        result = _execute(dag, storage)
    except Exception as e:
        storage.set_status(FAILED, error=repr(e))
        raise
    # the output checkpoint lands BEFORE the status flip: a crash between
    # the two leaves a resumable RUNNING workflow, never a SUCCESSFUL one
    # with no output
    storage.save_step("__output__", result)
    storage.set_status(SUCCESSFUL)
    return result


def run(dag: StepNode, *, workflow_id: str | None = None) -> Any:
    """Execute a workflow DAG durably (ref: api.py run:123)."""
    import ray_tpu

    if not isinstance(dag, StepNode):
        raise TypeError("workflow.run takes a bound step: my_step.bind(...)")
    if not ray_tpu.is_initialized():
        ray_tpu.init()
    workflow_id = workflow_id or f"wf-{uuid.uuid4().hex[:12]}"
    storage = _Storage(workflow_id)
    _assign_ids(dag)
    storage.save_dag(dag)
    return _run_to_completion(storage, dag)


def resume(workflow_id: str) -> Any:
    """Resume from checkpoints; the DAG definition comes from storage, so
    any process can resume any workflow (ref: api.py resume:243)."""
    import ray_tpu

    if not ray_tpu.is_initialized():
        ray_tpu.init()
    storage = _Storage(workflow_id)
    status = storage.get_status()
    if status.get("status") == "NOT_FOUND":
        raise ValueError(f"no workflow {workflow_id!r} in storage")
    if status.get("status") == SUCCESSFUL:
        return storage.load_step("__output__")
    dag = storage.load_dag()
    return _run_to_completion(storage, dag)


def resume_all(include_failed: bool = True) -> list[tuple[str, Any]]:
    """Resume every non-successful stored workflow (ref: api.py
    resume_all:502)."""
    out = []
    for wf_id in list_all():
        status = get_status(wf_id)
        if status == SUCCESSFUL:
            continue
        if status == FAILED and not include_failed:
            continue
        try:
            out.append((wf_id, resume(wf_id)))
        except Exception as e:  # keep going: one bad workflow isn't fatal
            out.append((wf_id, e))
    return out


def get_status(workflow_id: str) -> str:
    return _Storage(workflow_id).get_status().get("status", "NOT_FOUND")


def get_output(workflow_id: str) -> Any:
    storage = _Storage(workflow_id)
    if storage.get_status().get("status") != SUCCESSFUL:
        raise ValueError(f"workflow {workflow_id!r} has not succeeded")
    return storage.load_step("__output__")


def list_all() -> list[str]:
    root = _storage_root()
    try:
        return sorted(
            d for d in os.listdir(root)
            if os.path.isdir(os.path.join(root, d))
        )
    except FileNotFoundError:
        return []


# ------------------------------------------------------------------- events
class EventListener:
    """Pluggable event source for wait_for_event (ref:
    python/ray/workflow/event_listener.py EventListener.poll_for_event —
    async there; a plain blocking poll here, since the wait runs inside
    an ordinary worker task, not an event loop)."""

    def poll_for_event(self, *args, **kwargs):
        raise NotImplementedError


class TimerListener(EventListener):
    """Fires after a duration (ref: event_listener.py TimerListener)."""

    def poll_for_event(self, duration_s: float):
        time.sleep(duration_s)
        return duration_s


class KVEventListener(EventListener):
    """Fires when ``send_event(key, payload)`` posts to the cluster KV —
    the cross-process event channel (ref: the HTTP event provider role,
    workflow/http_event_provider.py, over this framework's GCS KV
    instead of an HTTP endpoint).

    Event lifecycle: a wait step polls the key scoped to its own workflow
    id first (``send_event(key, payload, workflow_id=...)``), then the
    shared plain key; once the waiting step's result is checkpointed the
    consumed entries are deleted (see _cleanup_event_keys), so a stale
    payload from a previous run can never short-circuit a later wait and
    blobs don't accumulate in the GCS WAL. Consequence: a shared-key
    event is consumed by ONE workflow — to address several concurrent
    workflows, send each a workflow_id-scoped event (or distinct keys);
    a shared key is not a broadcast channel."""

    NS = "wf_events"
    workflow_id: str | None = None  # injected by the wait_for_event step

    @classmethod
    def event_keys(cls, workflow_id: str | None, key: str) -> list[str]:
        """KV keys consulted for ``key``, most specific first."""
        keys = []
        if workflow_id:
            keys.append(f"wf:{workflow_id}:{key}")
        keys.append(key)
        return keys

    @classmethod
    def consumed_marker(cls, workflow_id: str, key: str) -> str:
        """KV key recording WHICH entry a workflow's wait consumed, so
        the post-checkpoint cleanup deletes exactly that entry — never a
        sibling payload addressed to someone else."""
        return f"wf-consumed::{workflow_id}::{key}"

    def poll_for_event(self, key: str, poll_interval_s: float = 0.2,
                       timeout_s: float | None = None):
        import ray_tpu
        from ray_tpu.core import api as _core_api

        core = _core_api.get_core()
        candidates = self.event_keys(self.workflow_id, key)
        deadline = None if timeout_s is None else time.monotonic() + timeout_s
        while True:
            for k in candidates:
                blob = core._run_sync(core.gcs.call(
                    "kv_get", {"ns": self.NS, "key": k}))
                if blob is not None:
                    if self.workflow_id:
                        # record the consumed key BEFORE returning: the
                        # driver-side cleanup reads it after the step
                        # checkpoints (a crash-retry simply overwrites it)
                        core._run_sync(core.gcs.call("kv_put", {
                            "ns": self.NS,
                            "key": self.consumed_marker(self.workflow_id,
                                                        key),
                            "value": k.encode()}))
                    return cloudpickle.loads(blob)
            if deadline is not None and time.monotonic() > deadline:
                raise TimeoutError(f"no event {key!r} within {timeout_s}s")
            time.sleep(poll_interval_s)


def send_event(key: str, payload: Any = None,
               workflow_id: str | None = None) -> None:
    """Deliver an event to any KVEventListener waiting on ``key``; with
    ``workflow_id`` the payload is addressed to that workflow's waits
    only (other workflows sharing the key name never observe it)."""
    from ray_tpu.core import api as _core_api

    if workflow_id:
        key = f"wf:{workflow_id}:{key}"
    core = _core_api.get_core()
    core._run_sync(core.gcs.call("kv_put", {
        "ns": KVEventListener.NS, "key": key,
        "value": cloudpickle.dumps(payload)}))


def wait_for_event(listener_cls: type, *args, name: str | None = None,
                   num_cpus: float = 0.1, **kwargs) -> StepNode:
    """A workflow step that completes when the listener's event arrives
    (ref: api.py wait_for_event:380). The delivered payload checkpoints
    like any step result, so a resumed workflow does NOT re-wait for an
    event it already consumed."""
    if not (isinstance(listener_cls, type)
            and issubclass(listener_cls, EventListener)):
        raise TypeError("wait_for_event takes an EventListener subclass")

    def poll(*a, _wf_event_scope=None, **k):
        listener = listener_cls()
        listener.workflow_id = _wf_event_scope
        return listener.poll_for_event(*a, **k)

    wrapped = WorkflowStep(
        poll, name=name or f"wait_{listener_cls.__name__}",
        num_cpus=num_cpus)
    # marks the step for scope injection + post-checkpoint KV cleanup
    wrapped._rt_event_listener = listener_cls
    return wrapped.bind(*args, **kwargs)

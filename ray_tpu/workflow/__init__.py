"""ray_tpu.workflow — durable workflows on the task runtime.

TPU-native counterpart of Ray Workflows (ref: python/ray/workflow/ —
api.py run:123/resume:243/resume_all:502, step checkpointing in
workflow_state.py + storage): a DAG of steps authored with .bind(),
executed as ordinary tasks, with every step's result checkpointed to
durable storage so a crashed/restarted driver resumes from the last
completed step instead of recomputing.

    from ray_tpu import workflow

    @workflow.step
    def fetch(x): ...
    @workflow.step
    def train(data): ...

    out = workflow.run(train.bind(fetch.bind(1)), workflow_id="exp1")
    # process dies mid-run? ->
    out = workflow.resume("exp1")   # completed steps replay from storage
"""
from ray_tpu.workflow.api import (
    EventListener,
    KVEventListener,
    TimerListener,
    WorkflowStep,
    get_output,
    get_status,
    list_all,
    resume,
    resume_all,
    run,
    send_event,
    step,
    wait_for_event,
)

__all__ = [
    "EventListener",
    "KVEventListener",
    "TimerListener",
    "WorkflowStep",
    "get_output",
    "get_status",
    "list_all",
    "resume",
    "resume_all",
    "run",
    "send_event",
    "step",
    "wait_for_event",
]

"""Job submission: run an entrypoint command on the cluster.

TPU-native counterpart of the reference job subsystem (ref:
python/ray/dashboard/modules/job/sdk.py:36 JobSubmissionClient.submit_job,
job_manager.py JobManager/JobSupervisor): a submitted job becomes a
supervisor actor that spawns the entrypoint as a driver subprocess with
the cluster address exported, captures its output, and records status in
the GCS KV. Three entry surfaces share one manager:

  * REST on the dashboard   POST/GET /api/jobs (ref: job REST head)
  * ``JobSubmissionClient`` SDK over that REST API
  * ``python -m ray_tpu job submit|status|logs|list|stop`` CLI
    (direct GCS mode — works from a bare shell with just the address)

Job records live in GCS KV ns="job_submissions"; logs stream to a file on
the supervisor's node and are served back through the actor.
"""
from __future__ import annotations

import base64
import json
import os
import time
import uuid

_NS = "job_submissions"

# terminal states (ref: job sdk JobStatus)
PENDING = "PENDING"
RUNNING = "RUNNING"
SUCCEEDED = "SUCCEEDED"
FAILED = "FAILED"
STOPPED = "STOPPED"


class JobSupervisor:
    """Actor wrapping one driver subprocess (ref: job_manager.py
    JobSupervisor). Runs the entrypoint with RT_ADDRESS exported so
    ``ray_tpu.init()`` inside the job joins this cluster."""

    def __init__(self, job_id: str, entrypoint: str, runtime_env: dict | None,
                 gcs_address: str):
        self.job_id = job_id
        self.entrypoint = entrypoint
        self.runtime_env = runtime_env or {}
        self.gcs_address = gcs_address
        self.proc = None
        self._stop_requested = False
        import tempfile

        self.log_path = os.path.join(
            tempfile.gettempdir(), "ray_tpu", "jobs", f"{job_id}.log")
        os.makedirs(os.path.dirname(self.log_path), exist_ok=True)

    def _kv_update(self, **fields):
        from ray_tpu.core import api

        core = api.get_core()
        rec = _get_record(core, self.job_id) or {}
        rec.update(fields)
        core._run_sync(core.gcs.call("kv_put", {
            "ns": _NS, "key": self.job_id,
            "value": json.dumps(rec).encode(), "overwrite": True}))

    def _prepare(self) -> tuple[dict, str | None]:
        """Build the driver env (and materialize the runtime_env).
        Sync — runs in an executor thread, where _run_sync is safe."""
        from ray_tpu.core import api
        from ray_tpu.runtime_env import apply_runtime_env

        env = dict(os.environ)
        env["RT_ADDRESS"] = self.gcs_address
        env["RT_JOB_ID"] = self.job_id
        pkg_parent = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
        env["PYTHONPATH"] = pkg_parent + os.pathsep + env.get("PYTHONPATH", "")
        cwd = None
        if self.runtime_env:
            core = api.get_core()

            def kv_get(key):
                return core._run_sync(core.gcs.call(
                    "kv_get", {"ns": "runtime_env_packages", "key": key}))

            # materialize in-process only to learn the extracted paths;
            # everything travels to the subprocess via env/cwd
            before = os.getcwd()
            apply_runtime_env(self.runtime_env, kv_get)
            cwd = os.getcwd()
            os.chdir(before)
            for k, v in (self.runtime_env.get("env_vars") or {}).items():
                env[k] = v
            from ray_tpu.runtime_env import _cache_dir

            extra = [os.path.join(_cache_dir(), d)
                     for d in self.runtime_env.get("py_modules", [])]
            if cwd != before:
                extra.insert(0, cwd)
            if extra:
                env["PYTHONPATH"] = (
                    os.pathsep.join(extra) + os.pathsep + env["PYTHONPATH"])
        return env, cwd

    async def run(self) -> str:
        """Spawn the driver and wait for it; returns the final status.

        Async so stop()/logs_tail() stay responsive on the actor's single
        executor thread; every _run_sync-using helper is pushed OFF the
        loop (calling _run_sync on the loop thread would deadlock)."""
        import asyncio
        import subprocess

        loop = asyncio.get_running_loop()
        try:
            env, cwd = await loop.run_in_executor(None, self._prepare)
        except Exception as e:
            await loop.run_in_executor(
                None, lambda: self._kv_update(
                    status=FAILED, message=f"runtime_env failed: {e}",
                    end_time=time.time()))
            return FAILED
        if self._stop_requested:  # stop() raced the startup: honor it
            await loop.run_in_executor(
                None, lambda: self._kv_update(
                    status=STOPPED, message="stopped before start",
                    end_time=time.time()))
            return STOPPED
        await loop.run_in_executor(
            None, lambda: self._kv_update(status=RUNNING,
                                          start_time=time.time()))
        logf = open(self.log_path, "ab")
        try:
            # own process group: stop() must reach the real driver behind
            # the shell wrapper (compound entrypoints would otherwise
            # orphan it)
            self.proc = subprocess.Popen(
                self.entrypoint, shell=True, env=env, cwd=cwd,
                stdout=logf, stderr=subprocess.STDOUT,
                start_new_session=True,
            )
            rc = await loop.run_in_executor(None, self.proc.wait)
        finally:
            logf.close()
        if self._stop_requested and rc != 0:
            status, msg = STOPPED, "stopped"
        elif rc == 0:
            status, msg = SUCCEEDED, ""
        else:
            # a signal exit we did NOT request (e.g. the kernel OOM killer
            # SIGKILLing the driver) is a failure, not a stop
            status, msg = FAILED, (
                f"terminated by signal {-rc}" if rc < 0
                else f"entrypoint exited with code {rc}")
        await loop.run_in_executor(
            None, lambda: self._kv_update(status=status, message=msg,
                                          end_time=time.time()))
        return status

    def stop(self) -> bool:
        """Request termination. True if the job will stop (even if the
        driver hasn't spawned yet — run() checks the flag)."""
        self._stop_requested = True
        if self.proc is None:
            return True  # pre-start: run() will honor the flag
        if self.proc.poll() is not None:
            return False  # already finished
        import signal

        try:
            os.killpg(os.getpgid(self.proc.pid), signal.SIGTERM)
        except (ProcessLookupError, PermissionError):
            self.proc.terminate()
        return True

    def logs_tail(self, nbytes: int = 1 << 20) -> str:
        try:
            with open(self.log_path, "rb") as f:
                f.seek(0, os.SEEK_END)
                size = f.tell()
                f.seek(max(0, size - nbytes))
                return f.read().decode(errors="replace")
        except OSError:
            return ""


# ------------------------------------------------------------- manager API
# (requires an initialized ray_tpu; used by the dashboard REST handlers,
# the CLI's direct mode, and tests)

def _get_record(core, job_id: str) -> dict | None:
    blob = core._run_sync(core.gcs.call("kv_get", {"ns": _NS, "key": job_id}))
    return json.loads(blob) if blob else None


def _gcs_address_str() -> str:
    from ray_tpu.core import api

    core = api.get_core()
    host, port = core.gcs_address
    return f"{host}:{port}"


def submit_job(entrypoint: str, runtime_env: dict | None = None,
               job_id: str | None = None, metadata: dict | None = None) -> str:
    """Start a job; returns its submission id (ref: sdk.py:126 submit_job)."""
    import ray_tpu
    from ray_tpu.core import api

    core = api.get_core()
    job_id = job_id or f"raysubmit_{uuid.uuid4().hex[:12]}"
    if _get_record(core, job_id) is not None:
        raise ValueError(f"job {job_id!r} already exists")
    desc = None
    if runtime_env:
        from ray_tpu.runtime_env import package_runtime_env

        def kv_put(key, blob):
            core._run_sync(core.gcs.call("kv_put", {
                "ns": "runtime_env_packages", "key": key, "value": blob}))

        # already-packaged descriptors (REST path) pass through untouched
        if runtime_env.get("_packaged"):
            desc = {k: v for k, v in runtime_env.items() if k != "_packaged"}
        else:
            desc = package_runtime_env(runtime_env, kv_put)
    rec = {
        "job_id": job_id,
        "entrypoint": entrypoint,
        "status": PENDING,
        "message": "",
        "submission_time": time.time(),
        "metadata": metadata or {},
    }
    core._run_sync(core.gcs.call("kv_put", {
        "ns": _NS, "key": job_id, "value": json.dumps(rec).encode()}))
    sup = ray_tpu.remote(JobSupervisor).options(
        name=f"_job_supervisor_{job_id}", num_cpus=0
    ).remote(job_id, entrypoint, desc, _gcs_address_str())
    # fire-and-forget by design: the supervisor reports terminal status
    # (and any error) into the GCS KV, which job_status() surfaces
    sup.run.remote()  # raylint: disable=RT003
    return job_id


def job_status(job_id: str) -> dict:
    from ray_tpu.core import api

    rec = _get_record(api.get_core(), job_id)
    if rec is None:
        raise KeyError(f"no such job {job_id!r}")
    return rec


def list_jobs() -> list[dict]:
    from ray_tpu.core import api

    core = api.get_core()
    keys = core._run_sync(core.gcs.call("kv_keys", {"ns": _NS, "prefix": ""}))
    out = []
    for k in keys or []:
        rec = _get_record(core, k if isinstance(k, str) else k.decode())
        if rec:
            out.append(rec)
    return sorted(out, key=lambda r: r.get("submission_time", 0))


def job_logs(job_id: str, nbytes: int = 1 << 20) -> str:
    import ray_tpu

    try:
        sup = ray_tpu.get_actor(f"_job_supervisor_{job_id}")
    except ValueError:
        rec = job_status(job_id)
        return rec.get("message", "")
    return ray_tpu.get(sup.logs_tail.remote(nbytes), timeout=30)


def stop_job(job_id: str) -> bool:
    import ray_tpu

    try:
        sup = ray_tpu.get_actor(f"_job_supervisor_{job_id}")
    except ValueError:
        return False
    return ray_tpu.get(sup.stop.remote(), timeout=30)


def wait_job(job_id: str, timeout: float = 300.0) -> dict:
    """Poll until the job reaches a terminal state (tests / CLI --wait)."""
    deadline = time.monotonic() + timeout
    while time.monotonic() < deadline:
        rec = job_status(job_id)
        if rec["status"] in (SUCCEEDED, FAILED, STOPPED):
            return rec
        time.sleep(0.3)
    raise TimeoutError(f"job {job_id} still {rec['status']} after {timeout}s")


# ----------------------------------------------------------------- REST SDK
class JobSubmissionClient:
    """HTTP client for the dashboard's /api/jobs endpoints (ref: sdk.py:36).
    Packages working_dir/py_modules locally and ships the blobs inline."""

    def __init__(self, address: str):
        self.base = address.rstrip("/")
        if not self.base.startswith("http"):
            self.base = "http://" + self.base

    def _request(self, method: str, path: str, body: dict | None = None):
        import urllib.request

        req = urllib.request.Request(
            self.base + path, method=method,
            data=json.dumps(body).encode() if body is not None else None,
            headers={"Content-Type": "application/json"},
        )
        with urllib.request.urlopen(req, timeout=60) as resp:
            return json.loads(resp.read().decode())

    def submit_job(self, *, entrypoint: str, runtime_env: dict | None = None,
                   submission_id: str | None = None,
                   metadata: dict | None = None) -> str:
        packages: dict[str, str] = {}
        desc = None
        if runtime_env:
            from ray_tpu.runtime_env import package_runtime_env

            def collect(key, blob):
                packages[key] = base64.b64encode(blob).decode()

            desc = package_runtime_env(runtime_env, collect)
        reply = self._request("POST", "/api/jobs", {
            "entrypoint": entrypoint,
            "runtime_env": desc,
            "packages": packages,
            "submission_id": submission_id,
            "metadata": metadata,
        })
        if "error" in reply:
            raise RuntimeError(reply["error"])
        return reply["job_id"]

    def get_job_status(self, job_id: str) -> str:
        return self._request("GET", f"/api/jobs/{job_id}")["status"]

    def get_job_info(self, job_id: str) -> dict:
        return self._request("GET", f"/api/jobs/{job_id}")

    def get_job_logs(self, job_id: str) -> str:
        return self._request("GET", f"/api/jobs/{job_id}/logs")["logs"]

    def list_jobs(self) -> list[dict]:
        return self._request("GET", "/api/jobs")

    def stop_job(self, job_id: str) -> bool:
        return self._request("POST", f"/api/jobs/{job_id}/stop")["stopped"]

/* ray_tpu dashboard SPA (ref role: python/ray/dashboard/client/src — the
 * React app's views, re-done as a dependency-free hash router + render
 * functions over the JSON state API). */
"use strict";

const $ = (sel) => document.querySelector(sel);
const main = $("#main");

function esc(v) {
  return String(v ?? "").replace(/[&<>"']/g,
    (c) => ({"&":"&amp;","<":"&lt;",">":"&gt;",'"':"&quot;","'":"&#39;"}[c]));
}
function short(id, n = 12) { return String(id || "").slice(0, n); }
function fmtBytes(n) {
  if (n == null) return "";
  const u = ["B", "KB", "MB", "GB", "TB"];
  let i = 0; n = Number(n);
  while (n >= 1024 && i < u.length - 1) { n /= 1024; i++; }
  return n.toFixed(n >= 100 || i === 0 ? 0 : 1) + " " + u[i];
}
function fmtDur(s) {
  if (s == null) return "";
  if (s < 1) return (s * 1000).toFixed(1) + "ms";
  if (s < 120) return s.toFixed(2) + "s";
  return (s / 60).toFixed(1) + "m";
}
function fmtTs(t) { return t ? new Date(t * 1000).toLocaleTimeString() : ""; }

async function fetchJSON(url, opts) {
  const r = await fetch(url, opts);
  if (!r.ok) throw new Error(url + " -> " + r.status);
  return r.json();
}

/* ---- sortable tables ------------------------------------------------- */
const sortState = {};  // view:col -> dir
function table(viewKey, cols, rows, onRow) {
  // cols: [{k, label, fmt?, cls?, raw?}]
  const key = sortState[viewKey];
  if (key) {
    const [col, dir] = key;
    const c = cols.find((c) => c.k === col);
    if (c) rows = [...rows].sort((a, b) => {
      const x = a[col], y = b[col];
      const r = x == null ? -1 : y == null ? 1 : x < y ? -1 : x > y ? 1 : 0;
      return dir === "asc" ? r : -r;
    });
  }
  let h = `<table data-view="${viewKey}"><tr>`;
  for (const c of cols) {
    const cls = key && key[0] === c.k ? `sorted-${key[1]}` : "";
    h += `<th data-col="${c.k}" class="${cls}">${esc(c.label)}</th>`;
  }
  h += "</tr>";
  rows.forEach((row, i) => {
    h += `<tr class="${onRow ? "clickable" : ""}" data-i="${i}">`;
    for (const c of cols) {
      const v = c.fmt ? c.fmt(row[c.k], row) : esc(row[c.k]);
      const cls = c.cls ? c.cls(row[c.k], row) : "";
      h += `<td class="${cls}">${v}</td>`;
    }
    h += "</tr>";
  });
  h += "</table>";
  return { html: h, rows, onRow };
}
function wireTable(container, t) {
  container.querySelectorAll("th[data-col]").forEach((th) => {
    th.onclick = () => {
      const view = th.closest("table").dataset.view;
      const col = th.dataset.col;
      const cur = sortState[view];
      sortState[view] = [col, cur && cur[0] === col && cur[1] === "desc" ? "asc" : "desc"];
      render();
    };
  });
  if (t && t.onRow) {
    container.querySelectorAll("tr.clickable").forEach((tr) => {
      tr.onclick = () => t.onRow(t.rows[Number(tr.dataset.i)]);
    });
  }
}

/* ---- metric history for sparklines ----------------------------------- */
const history = {};  // name|tag -> [values]
let latestMetrics = {};  // last /api/metrics payload (fetched once per render)
// structured samples ({tags:{...}, value|counts/sum}) -> [label, sample]
function metricSamples(m) {
  return (m.samples || []).map((s) => [
    Object.entries(s.tags || {}).map(([k, v]) => `${k}=${v}`).join(","), s]);
}
function pushHistory(name, tag, v) {
  const k = name + "|" + tag;
  (history[k] = history[k] || []).push(Number(v) || 0);
  if (history[k].length > 60) history[k].shift();
}
function spark(values, w = 120, h = 22) {
  if (!values || values.length < 2) return "";
  const min = Math.min(...values), max = Math.max(...values);
  const span = max - min || 1;
  const pts = values.map((v, i) =>
    `${(i / (values.length - 1) * w).toFixed(1)},${(h - 2 - (v - min) / span * (h - 4)).toFixed(1)}`);
  return `<svg class="spark" width="${w}" height="${h}">` +
    `<polyline fill="none" stroke="#6fd3c7" stroke-width="1.5" points="${pts.join(" ")}"/></svg>`;
}

/* ---- views ----------------------------------------------------------- */
// /api/summary/tasks returns {task_name: {state: count}}; flatten to
// {state: count} for the cards and the state filter.
function byState(summary) {
  const out = {};
  for (const states of Object.values(summary || {}))
    for (const [st, n] of Object.entries(states)) out[st] = (out[st] || 0) + n;
  return out;
}

const views = {};
let detail = null;  // {view, render: async () => html} overlay state

views.overview = async () => {
  const [nodes, summary, actors, objects] = await Promise.all([
    fetchJSON("/api/cluster"), fetchJSON("/api/summary/tasks"),
    fetchJSON("/api/actors"), fetchJSON("/api/objects"),
  ]);
  const metrics = latestMetrics;  // render() preamble already fetched it
  const alive = nodes.filter((n) => n.alive).length;
  const actorsAlive = actors.filter((a) => a.state === "ALIVE").length;
  const st = byState(summary);
  let h = `<h1>Cluster overview</h1><div class="cards">`;
  h += `<div class="card"><div class="v">${alive}/${nodes.length}</div><div class="k">nodes alive</div></div>`;
  h += `<div class="card"><div class="v">${actorsAlive}/${actors.length}</div><div class="k">actors alive</div></div>`;
  for (const k of ["RUNNING", "FINISHED", "FAILED", "PENDING"]) {
    if (st[k] != null)
      h += `<div class="card"><div class="v ${k === "FAILED" && st[k] ? "bad" : ""}">${st[k]}</div><div class="k">tasks ${k.toLowerCase()}</div></div>`;
  }
  h += `<div class="card"><div class="v">${objects.length}</div><div class="k">shm objects</div></div>`;
  h += `</div><h2>Resources</h2>`;
  for (const n of nodes) {
    for (const [k, total] of Object.entries(n.resources_total || {})) {
      const avail = (n.resources_available || {})[k] ?? 0;
      const used = total - avail, pct = total ? used / total * 100 : 0;
      h += `<div style="display:flex;gap:10px;align-items:center;margin:3px 0">
        <span style="width:230px" class="dim">${short(n.node_id, 8)} ${esc(k)}</span>
        <span class="bar ${pct > 85 ? "hot" : ""}" style="width:200px"><i style="width:${pct}%"></i></span>
        <span>${used}/${total}</span></div>`;
    }
  }
  const failed = (await fetchJSON("/api/tasks")).filter((t) => t.state === "FAILED").slice(0, 10);
  if (failed.length) {
    h += `<h2>Recent failures</h2>`;
    h += table("ovfail", [
      {k: "name", label: "task"}, {k: "state", label: "state", cls: () => "bad"},
      {k: "error", label: "error", fmt: (v) => `<span class="wrap">${esc(short(v, 120))}</span>`},
    ], failed).html;
  }
  // a couple of headline metrics if exported
  const rates = Object.entries(metrics).filter(([k, m]) => m.type !== "histogram").slice(0, 6);
  if (rates.length) {
    h += `<h2>Metrics</h2>`;
    for (const [k, m] of rates)
      for (const [tag, s] of metricSamples(m))
        h += `<div><span class="dim" style="display:inline-block;width:340px">${esc(k)}${tag ? " " + esc(tag) : ""}</span> ${esc(s.value)} ${spark(history[k + "|" + tag])}</div>`;
  }
  return h;
};

views.nodes = async () => {
  const nodes = await fetchJSON("/api/cluster");
  let h = `<h1>Nodes</h1>`;
  const t = table("nodes", [
    {k: "node_id", label: "node", fmt: (v) => short(v)},
    {k: "alive", label: "alive", cls: (v) => v ? "ok" : "bad"},
    {k: "address", label: "address", fmt: (v) => esc(Array.isArray(v) ? v.join(":") : v)},
    {k: "resources_total", label: "resources", fmt: (v, r) =>
      esc(Object.entries(v || {}).map(([k, t]) =>
        `${k}:${(r.resources_available || {})[k] ?? 0}/${t}`).join(" "))},
    {k: "queued_leases", label: "queued"},
  ], nodes, (row) => showDetail("nodes", `Node ${short(row.node_id)}`, row));
  return { html: h + t.html, after: (el) => wireTable(el, t) };
};

views.actors = async () => {
  const actors = await fetchJSON("/api/actors");
  let h = `<h1>Actors</h1>`;
  const t = table("actors", [
    {k: "actor_id", label: "actor", fmt: (v) => short(v)},
    {k: "name", label: "name"},
    {k: "state", label: "state", cls: (v) => v === "ALIVE" ? "ok" : v === "DEAD" ? "bad" : "warn"},
    {k: "node_id", label: "node", fmt: (v) => short(v, 8)},
    {k: "address", label: "address", fmt: (v) => esc(Array.isArray(v) ? v.join(":") : v || "")},
    {k: "num_restarts", label: "restarts"},
    {k: "death_cause", label: "death cause", fmt: (v) => `<span class="bad">${esc(short(v, 60))}</span>`},
  ], actors, (row) => showDetail("actors", `Actor ${short(row.actor_id)}`, row));
  return { html: h + t.html, after: (el) => wireTable(el, t) };
};

let taskFilter = {state: "", q: ""};
views.tasks = async () => {
  const [tasks, summary] = await Promise.all([
    fetchJSON("/api/tasks"), fetchJSON("/api/summary/tasks")]);
  const st = byState(summary);
  let rows = tasks;
  if (taskFilter.state) rows = rows.filter((t) => t.state === taskFilter.state);
  if (taskFilter.q) rows = rows.filter((t) => (t.name || "").includes(taskFilter.q));
  let h = `<h1>Tasks</h1><div class="controls">
    <select id="tf-state"><option value="">all states</option>
      ${Object.keys(st).map((s) => `<option ${taskFilter.state === s ? "selected" : ""}>${esc(s)}</option>`).join("")}
    </select>
    <input type="text" id="tf-q" placeholder="filter by name" value="${esc(taskFilter.q)}">
    <span class="dim">${rows.length}/${tasks.length} · ${Object.entries(st).map(([k, v]) => k + ":" + v).join("  ")}</span>
  </div>`;
  const t = table("tasks", [
    {k: "task_id", label: "id", fmt: (v) => short(v)},
    {k: "name", label: "name"},
    {k: "state", label: "state", cls: (v) => v === "FAILED" ? "bad" : v === "RUNNING" ? "warn" : "ok"},
    {k: "node_id", label: "node", fmt: (v) => short(v, 8)},
    {k: "duration_s", label: "duration", fmt: fmtDur},
    {k: "start_time", label: "started", fmt: fmtTs},
  ], rows.slice(0, 500), (row) => showDetail("tasks", `Task ${short(row.task_id)}`, row));
  return { html: h + t.html, after: (el) => {
    wireTable(el, t);
    el.querySelector("#tf-state").onchange = (e) => { taskFilter.state = e.target.value; render(); };
    el.querySelector("#tf-q").onchange = (e) => { taskFilter.q = e.target.value; render(); };
  }};
};

views.objects = async () => {
  const objects = await fetchJSON("/api/objects");
  let h = `<h1>Objects</h1><div class="muted-note">${objects.length} objects in the shm object directory (owner-inlined values are not listed)</div>`;
  return h + table("objects", [
    {k: "object_id", label: "object", fmt: (v) => short(v, 20)},
    {k: "locations", label: "holders", fmt: (v) =>
      esc((v || []).map((x) => short(x, 10)).join(", "))},
  ], objects.slice(0, 500)).html;
};

views.pgs = async () => {
  const pgs = await fetchJSON("/api/placement_groups");
  return `<h1>Placement groups</h1>` + table("pgs", [
    {k: "pg_id", label: "pg", fmt: (v) => short(v)},
    {k: "strategy", label: "strategy"},
    {k: "state", label: "state", cls: (v) => v === "CREATED" ? "ok" : "warn"},
    {k: "bundles", label: "bundles", fmt: (v) => esc(JSON.stringify(v))},
    {k: "bundle_nodes", label: "nodes", fmt: (v) =>
      esc((v || []).map((x) => short(x, 8)).join(", "))},
  ], pgs).html;
};

let jobLogId = null;
views.jobs = async () => {
  const jobs = await fetchJSON("/api/jobs");
  let h = `<h1>Jobs</h1>`;
  const t = table("jobs", [
    {k: "job_id", label: "job"},
    {k: "entrypoint", label: "entrypoint", fmt: (v) => `<span class="wrap">${esc(short(v, 80))}</span>`},
    {k: "status", label: "status", cls: (v) => v === "SUCCEEDED" ? "ok" : v === "FAILED" ? "bad" : "warn"},
    {k: "start_time", label: "started", fmt: fmtTs},
    {k: "job_id2", label: "", fmt: (_, r) =>
      `<button data-logs="${esc(r.job_id)}">logs</button> ` +
      (r.status === "RUNNING" ? `<button data-stop="${esc(r.job_id)}">stop</button>` : "")},
  ], jobs);
  h += t.html;
  if (jobLogId) {
    h += `<h2>Logs — ${esc(jobLogId)}</h2><pre class="log" id="job-log">loading…</pre>`;
  }
  return { html: h, after: async (el) => {
    wireTable(el, t);
    el.querySelectorAll("button[data-logs]").forEach((b) => {
      b.onclick = () => { jobLogId = b.dataset.logs; render(); };
    });
    el.querySelectorAll("button[data-stop]").forEach((b) => {
      b.onclick = async () => { await fetch("/api/jobs/" + b.dataset.stop + "/stop", {method: "POST"}); render(); };
    });
    if (jobLogId) {
      try {
        const res = await fetchJSON("/api/jobs/" + jobLogId + "/logs");
        const pre = el.querySelector("#job-log");
        if (pre) pre.textContent = res.logs || "(empty)";
      } catch (e) { /* job gone */ }
    }
  }};
};

views.serve = async () => {
  let status;
  try { status = await fetchJSON("/api/serve"); }
  catch (e) { return `<h1>Serve</h1><div class="muted-note">serve is not running</div>`; }
  let h = `<h1>Serve</h1>`;
  // serve.status() -> {app: {deployment: {target_replicas, replicas:
  // [{replica_id, healthy}], ongoing, deleting}}}
  if (status.error) return h + `<div class="muted-note">serve is not running</div>`;
  if (!Object.keys(status).length) h += `<div class="muted-note">no applications deployed</div>`;
  for (const [name, deps] of Object.entries(status)) {
    h += `<h2>${esc(name)}</h2>`;
    h += table("serve-" + name, [
      {k: "name", label: "deployment"},
      {k: "healthy", label: "healthy", fmt: (v, r) =>
        `<span class="${v >= r.target ? "ok" : "warn"}">${v}/${r.target}</span>`},
      {k: "ongoing", label: "in-flight"},
      {k: "deleting", label: "", fmt: (v) => v ? `<span class="warn">deleting</span>` : ""},
      {k: "replicas", label: "replicas", fmt: (v) =>
        esc((v || []).map((r) => short(r.replica_id, 10) + (r.healthy ? "" : "!")).join(", "))},
    ], Object.entries(deps).map(([dn, d]) => ({
      name: dn, target: d.target_replicas,
      healthy: (d.replicas || []).filter((r) => r.healthy).length,
      ongoing: d.ongoing, deleting: d.deleting, replicas: d.replicas,
    }))).html;
  }
  return h;
};

// server-side rollup history (GCS RollupStore): name -> [values].
// Counters plot their per-second rate, histograms their p99, gauges and
// derived ratios the value — 120s of real history, survives page loads.
async function rollupSeries() {
  let names;
  try { names = await fetchJSON("/api/metric_names"); } catch (e) { return {}; }
  const out = {};
  await Promise.all((names || []).map(async (name) => {
    try {
      const win = await fetchJSON(
        "/api/metric_window?name=" + encodeURIComponent(name) + "&secs=120");
      const pts = win.points || [];
      if (!pts.length) return;
      out[name] = pts.map((p) =>
        win.type === "counter" ? p.rate :
        win.type === "histogram" ? (p.p99 ?? p.rate) : p.value);
    } catch (e) { /* name raced retention */ }
  }));
  return out;
}

views.metrics = async () => {
  const metrics = latestMetrics;  // render() preamble already fetched it
  const series = await rollupSeries();
  let h = `<h1>Metrics</h1>
    <div class="muted-note">sparklines are server history from the GCS rollup store
    (counters as rate/s, histograms as p99) ·
    <a class="inline" href="/metrics" target="_blank">prometheus endpoint</a></div>`;
  // derived ratio series (accept rate, SLO breach fraction) have no
  // registry sample — surface them first, straight from the rollups
  const sampled = new Set(Object.keys(metrics));
  for (const [name, vals] of Object.entries(series)) {
    if (sampled.has(name)) continue;
    const last = vals[vals.length - 1];
    h += `<div><span class="dim" style="display:inline-block;width:360px">${esc(name)} <span class="dim">(derived)</span></span>
      <span style="display:inline-block;width:120px">${esc(typeof last === "number" ? +last.toFixed(3) : last)}</span>
      ${spark(vals)}</div>`;
  }
  for (const [name, m] of Object.entries(metrics)) {
    if (m.type === "histogram") {
      h += `<h2>${esc(name)} <span class="dim">(histogram)</span></h2>`;
      for (const [tag, hist] of metricSamples(m)) {
        const count = (hist.counts || []).reduce((a, b) => a + b, 0);
        h += `<div class="dim">${tag ? esc(tag) + " " : ""}count=${count} sum=${hist.sum ?? ""} ${spark(series[name])}</div>`;
      }
      continue;
    }
    for (const [tag, s] of metricSamples(m)) {
      const v = s.value;
      // rollup series are summed across tags; show it on the first
      // (untagged or sole) sample row, client history otherwise
      const sv = tag ? history[name + "|" + tag] : series[name] || history[name + "|" + tag];
      h += `<div><span class="dim" style="display:inline-block;width:360px">${esc(name)}${tag ? " " + esc(tag) : ""}</span>
        <span style="display:inline-block;width:120px">${esc(typeof v === "number" ? +v.toFixed(3) : v)}</span>
        ${spark(sv)}</div>`;
    }
  }
  return h;
};

views.timeline = async () => {
  const events = await fetchJSON("/api/timeline");
  let h = `<h1>Timeline</h1>
    <div class="muted-note">${events.length} events ·
    <a class="inline" href="/api/timeline" target="_blank" download="timeline.json">download chrome-trace JSON</a>
    (load into perfetto.dev / chrome://tracing for the full viewer)</div>`;
  const spans = events.filter((e) => e.ph === "X" && e.dur > 0);
  if (!spans.length) return h + `<div class="muted-note">no complete spans yet</div>`;
  const t0 = Math.min(...spans.map((s) => s.ts));
  const t1 = Math.max(...spans.map((s) => s.ts + s.dur));
  const span = t1 - t0 || 1;
  const lanes = {};
  for (const s of spans.slice(-800)) {
    const key = (s.pid || "?") + "/" + (s.tid || "?");
    (lanes[key] = lanes[key] || []).push(s);
  }
  const colors = ["#6fd3c7", "#9db8ff", "#e8c468", "#ef7b7b", "#b58aef", "#7fdc8a"];
  let ci = 0, colorOf = {};
  h += `<div class="tl-wrap">`;
  for (const [lane, ss] of Object.entries(lanes)) {
    h += `<div class="tl-row"><div class="tl-label">${esc(lane)}</div><div class="tl-track">`;
    for (const s of ss) {
      const left = (s.ts - t0) / span * 100, width = Math.max(s.dur / span * 100, 0.15);
      if (!(s.name in colorOf)) colorOf[s.name] = colors[ci++ % colors.length];
      h += `<span class="tl-span" style="left:${left.toFixed(3)}%;width:${width.toFixed(3)}%;background:${colorOf[s.name]}"
        title="${esc(s.name)} ${(s.dur / 1000).toFixed(2)}ms"></span>`;
    }
    h += `</div></div>`;
  }
  h += `</div><h2>Legend</h2>` + Object.entries(colorOf).map(([n, c]) =>
    `<span style="margin-right:14px"><span style="color:${c}">■</span> ${esc(n)}</span>`).join("");
  return h;
};

/* ---- detail overlay --------------------------------------------------- */
function showDetail(view, title, obj, extraHtml) {
  detail = { view, title, obj, extraHtml };
  render();
}
function detailHtml() {
  if (!detail) return "";
  let h = `<div class="detail"><div style="display:flex;justify-content:space-between">
    <h2 style="margin:0 0 8px">${esc(detail.title)}</h2>
    <button id="detail-close">close</button></div>`;
  if (detail.obj) {
    h += `<div class="kv">`;
    for (const [k, v] of Object.entries(detail.obj)) {
      h += `<span class="k">${esc(k)}</span><span class="wrap">${esc(
        typeof v === "object" ? JSON.stringify(v) : v)}</span>`;
    }
    h += `</div>`;
  }
  h += detail.extraHtml || "";
  return h + `</div>`;
}

/* ---- router / refresh loop ------------------------------------------- */
function currentView() {
  const m = location.hash.match(/^#\/(\w+)/);
  return m && views[m[1]] ? m[1] : "overview";
}

let rendering = false;
async function render() {
  if (rendering) return;
  rendering = true;
  const name = currentView();
  document.querySelectorAll("#nav a").forEach((a) =>
    a.classList.toggle("active", a.dataset.view === name));
  try {
    // ONE metrics fetch per cycle: feeds the sparkline history AND the
    // overview/metrics views (they read latestMetrics instead of
    // re-fetching)
    try {
      latestMetrics = await fetchJSON("/api/metrics");
      for (const [k, m] of Object.entries(latestMetrics))
        if (m.type !== "histogram")
          for (const [tag, s] of metricSamples(m)) pushHistory(k, tag, s.value);
    } catch (e) { /* metrics optional */ }
    const out = await views[name]();
    const html = typeof out === "string" ? out : out.html;
    main.innerHTML = (detail && detail.view === name ? detailHtml() : "") + html;
    const closeBtn = $("#detail-close");
    if (closeBtn) closeBtn.onclick = () => { detail = null; render(); };
    if (typeof out === "string") {
      wireTable(main, null);
      // re-wire plain tables' sort handlers + row clicks need table objects;
      // string views only get sort headers
      main.querySelectorAll("table").forEach(() => {});
    } else if (out.after) {
      await out.after(main);
    }
    $("#last-refresh").textContent = "updated " + new Date().toLocaleTimeString();
  } catch (e) {
    main.innerHTML = `<div class="err">dashboard error: ${esc(e.message || e)}</div>`;
  }
  rendering = false;
}

window.addEventListener("hashchange", () => { detail = null; jobLogId = null; render(); });
render();
setInterval(() => { if ($("#autorefresh").checked) render(); }, 2500);

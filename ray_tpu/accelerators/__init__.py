"""Accelerator managers (ref: python/ray/_private/accelerators/)."""
from ray_tpu.accelerators.tpu import (  # noqa: F401
    TPUAcceleratorManager,
    get_num_tpu_visible_chips_per_host,
    get_tpu_cores_per_chip,
    pod_head_resource,
    slice_placement_group,
)

"""TPU topology as a first-class scheduling resource.

TPU-native equivalent of the reference TPUAcceleratorManager (ref:
python/ray/_private/accelerators/tpu.py:24-61 chip detection + env
isolation, :232 set_current_process_visible_accelerator_ids, :236
_get_current_node_tpu_pod_type, :416 get_current_node_additional_resources
pod-head resources). Differences by design:

- Detection is env-first (GKE-style TPU_* env vars and this image's
  axon/pallas env) with /dev/accel* and /dev/vfio as fallbacks — no GCE
  metadata-server dependency (zero-egress environments).
- Topology is also exposed as node LABELS (tpu-pod-type / tpu-name /
  tpu-worker-id) so label-aware placement can gang-schedule a slice, not
  just count chips.

Node resources produced for a v4-16 worker 0 host:
    {"TPU": 4, "TPU-V4": 4, "my-tpu": 1, "TPU-v4-16-head": 1}
"""
from __future__ import annotations

import glob
import os
import re

TPU_VALID_CHIP_OPTIONS = (1, 2, 4, 8)
TPU_ACCELERATOR_TYPE_ENV = "TPU_ACCELERATOR_TYPE"  # e.g. "v4-16"
TPU_WORKER_ID_ENV = "TPU_WORKER_ID"
TPU_NAME_ENV = "TPU_NAME"
TPU_VISIBLE_CHIPS_ENV = "TPU_VISIBLE_CHIPS"
NOSET_TPU_VISIBLE_CHIPS_ENV = "RT_NOSET_TPU_VISIBLE_CHIPS"
TPU_CHIPS_PER_HOST_BOUNDS_ENV = "TPU_CHIPS_PER_HOST_BOUNDS"
TPU_HOST_BOUNDS_ENV = "TPU_HOST_BOUNDS"
_CHIPS_PER_HOST_BOUNDS_1 = "1,1,1"
_CHIPS_PER_HOST_BOUNDS_2 = "1,2,1"
_SINGLE_HOST_BOUNDS = "1,1,1"

# v2/v3/v4/v5p: 4 chips/host, 2 cores/chip; v5e(=v5litepod)/v6e: 8 chips, 1 core
_8_CHIP_TYPES = ("v5litepod", "v5e", "v6e")
_1_CORE_TYPES = ("v5litepod", "v5e", "v6e")
VALID_TPU_TYPES = ("v2", "v3", "v4", "v5p", "v5litepod", "v5e", "v6e")


def _accelerator_type_check(accelerator_type: str) -> None:
    # accept anything shaped v{generation}[variant]-{cores}: unknown future
    # generations fall back to the 4-chip/2-core default rather than
    # crashing node detection
    if not re.match(r"^v\d+[a-zA-Z]*(-\d+)?$", accelerator_type):
        raise ValueError(
            f"Invalid accelerator type: {accelerator_type!r}; expected "
            f"v<generation>-<cores>, e.g. one of {VALID_TPU_TYPES}"
        )


def get_num_tpu_visible_chips_per_host(accelerator_type: str) -> int:
    _accelerator_type_check(accelerator_type)
    return 8 if accelerator_type.startswith(_8_CHIP_TYPES) else 4


def get_tpu_cores_per_chip(accelerator_type: str) -> int:
    _accelerator_type_check(accelerator_type)
    return 1 if accelerator_type.startswith(_1_CORE_TYPES) else 2


class TPUAcceleratorManager:
    """Static env/topology introspection (one instance per process)."""

    @staticmethod
    def get_resource_name() -> str:
        return "TPU"

    # ---------------------------------------------------------- detection
    @staticmethod
    def get_current_process_visible_accelerator_ids() -> list[str] | None:
        visible = os.environ.get(TPU_VISIBLE_CHIPS_ENV)
        if visible is None:
            return None
        if visible == "":
            return []
        return visible.split(",")

    @staticmethod
    def get_current_node_num_accelerators() -> int:
        """Chips on this host: explicit env, axon/pallas tunnel, then
        device files (ref: get_current_node_num_accelerators :137)."""
        visible = TPUAcceleratorManager.get_current_process_visible_accelerator_ids()
        if visible is not None:
            return len(visible)
        pod_type = os.environ.get(TPU_ACCELERATOR_TYPE_ENV)
        if pod_type and TPUAcceleratorManager.is_valid_tpu_accelerator_type(pod_type):
            # explicit topology env wins over the axon tunnel fallback
            per_host = get_num_tpu_visible_chips_per_host(pod_type)
            cores = int(pod_type.split("-")[1])
            total_chips = cores // get_tpu_cores_per_chip(pod_type)
            return min(per_host, total_chips)
        if os.environ.get("PALLAS_AXON_TPU_GEN"):
            return 1  # axon tunnel exposes a single chip
        accel = glob.glob("/dev/accel*")
        if accel:
            return len(accel)
        try:
            return len([e for e in os.listdir("/dev/vfio") if e.isdigit()])
        except FileNotFoundError:
            return 0

    @staticmethod
    def is_valid_tpu_accelerator_type(tpu_accelerator_type: str) -> bool:
        """v{generation}{variant}-{cores} shape check (ref: :158)."""
        return re.match(r"^v\d+[a-zA-Z]*-\d+$", tpu_accelerator_type) is not None

    @staticmethod
    def get_current_node_tpu_pod_type() -> str | None:
        """The slice topology string, e.g. 'v4-16' (ref: :236)."""
        t = os.environ.get(TPU_ACCELERATOR_TYPE_ENV, "")
        if not t and os.environ.get("PALLAS_AXON_TPU_GEN"):
            # axon exposes generation only; a single tunneled chip is its own
            # single-host "slice"
            gen = os.environ["PALLAS_AXON_TPU_GEN"].lower().lstrip("v")
            t = f"v{gen}-1"
        if t and TPUAcceleratorManager.is_valid_tpu_accelerator_type(t):
            return t
        return None

    @staticmethod
    def get_current_node_tpu_name() -> str | None:
        return os.environ.get(TPU_NAME_ENV) or None

    @staticmethod
    def get_current_node_tpu_worker_id() -> int | None:
        w = os.environ.get(TPU_WORKER_ID_ENV)
        try:
            return int(w) if w is not None else None
        except ValueError:
            return None

    @staticmethod
    def get_num_workers_in_current_tpu_pod() -> int | None:
        """Hosts in this slice (ref: :316): ceil(total_cores / cores_per_host)."""
        pod_type = TPUAcceleratorManager.get_current_node_tpu_pod_type()
        if not pod_type:
            return None
        return slice_shape(pod_type)[0]

    @staticmethod
    def get_current_node_accelerator_type() -> str | None:
        """Generation marker resource, e.g. 'TPU-V4' (ref: :330)."""
        pod_type = TPUAcceleratorManager.get_current_node_tpu_pod_type()
        if pod_type is None:
            return None
        return "TPU-" + pod_type.split("-")[0].upper()

    # ---------------------------------------------------------- resources
    @staticmethod
    def get_current_node_tpu_resources() -> dict[str, float]:
        """Full TPU resource dict for node registration: chip count,
        generation marker, slice name, and the pod-head marker on worker 0
        (ref: get_current_node_additional_resources :416)."""
        n = TPUAcceleratorManager.get_current_node_num_accelerators()
        if n <= 0:
            return {}
        resources: dict[str, float] = {"TPU": float(n)}
        gen = TPUAcceleratorManager.get_current_node_accelerator_type()
        if gen:
            resources[gen] = float(n)
        name = TPUAcceleratorManager.get_current_node_tpu_name()
        worker_id = TPUAcceleratorManager.get_current_node_tpu_worker_id()
        pod_type = TPUAcceleratorManager.get_current_node_tpu_pod_type()
        if name and worker_id is not None and pod_type:
            resources[name] = 1.0
            if worker_id == 0:
                resources[f"TPU-{pod_type}-head"] = 1.0
        return resources

    @staticmethod
    def get_current_node_tpu_labels() -> dict[str, str]:
        """Topology labels for label-aware slice placement."""
        labels: dict[str, str] = {}
        pod_type = TPUAcceleratorManager.get_current_node_tpu_pod_type()
        if pod_type:
            labels["tpu-pod-type"] = pod_type
        name = TPUAcceleratorManager.get_current_node_tpu_name()
        if name:
            labels["tpu-name"] = name
        worker_id = TPUAcceleratorManager.get_current_node_tpu_worker_id()
        if worker_id is not None:
            labels["tpu-worker-id"] = str(worker_id)
        return labels

    # ---------------------------------------------------------- isolation
    @staticmethod
    def validate_resource_request_quantity(quantity: float) -> tuple[bool, str | None]:
        if quantity not in TPU_VALID_CHIP_OPTIONS:
            return (
                False,
                f"requested TPU={quantity}, but only chip configurations "
                f"{TPU_VALID_CHIP_OPTIONS} map onto TPU hosts",
            )
        return True, None

    @staticmethod
    def set_current_process_visible_accelerator_ids(visible_chips: list[str]) -> None:
        """Restrict this process to a chip subset via the env triplet the
        XLA runtime reads at first init (ref: :195 — the documented
        TPU_VISIBLE_CHIPS / *_BOUNDS combination; must run before jax
        touches the backend)."""
        if os.environ.get(NOSET_TPU_VISIBLE_CHIPS_ENV):
            return
        n = len(visible_chips)
        if n == TPUAcceleratorManager.get_current_node_num_accelerators():
            os.environ.pop(TPU_CHIPS_PER_HOST_BOUNDS_ENV, None)
            os.environ.pop(TPU_HOST_BOUNDS_ENV, None)
            return
        os.environ[TPU_VISIBLE_CHIPS_ENV] = ",".join(str(c) for c in visible_chips)
        if n == 1:
            os.environ[TPU_CHIPS_PER_HOST_BOUNDS_ENV] = _CHIPS_PER_HOST_BOUNDS_1
            os.environ[TPU_HOST_BOUNDS_ENV] = _SINGLE_HOST_BOUNDS
        elif n == 2:
            os.environ[TPU_CHIPS_PER_HOST_BOUNDS_ENV] = _CHIPS_PER_HOST_BOUNDS_2
            os.environ[TPU_HOST_BOUNDS_ENV] = _SINGLE_HOST_BOUNDS
        elif n == 4:
            # half of an 8-chip host (the documented jax chip-subset shape)
            os.environ[TPU_CHIPS_PER_HOST_BOUNDS_ENV] = "2,2,1"
            os.environ[TPU_HOST_BOUNDS_ENV] = _SINGLE_HOST_BOUNDS
        else:
            # no published bounds config for this subset: clear stale values
            # rather than leaving a previous lease's triplet behind
            os.environ.pop(TPU_CHIPS_PER_HOST_BOUNDS_ENV, None)
            os.environ.pop(TPU_HOST_BOUNDS_ENV, None)


# ------------------------------------------------------------------ helpers
def slice_shape(accelerator_type: str) -> tuple[int, int, str]:
    """(num_hosts, chips_per_bundle_host, generation_marker) for a slice —
    the one place the host math lives (ScalingConfig.topology,
    slice_placement_group, and pod-worker counting all call this)."""
    _accelerator_type_check(accelerator_type)
    chips_per_host = get_num_tpu_visible_chips_per_host(accelerator_type)
    cores_per_chip = get_tpu_cores_per_chip(accelerator_type)
    cores_per_host = chips_per_host * cores_per_chip
    num_cores = int(accelerator_type.split("-")[1])
    num_hosts = max(1, (num_cores + cores_per_host - 1) // cores_per_host)
    host_chips = max(1, min(chips_per_host, num_cores // cores_per_chip))
    gen = "TPU-" + accelerator_type.split("-")[0].upper()
    return num_hosts, host_chips, gen


def slice_placement_group(accelerator_type: str, *, strategy: str = "STRICT_SPREAD"):
    """Placement group spanning every host of one TPU slice: one bundle per
    host, each requesting that host's full chip count plus the generation
    marker (the TPU-first answer to 'STRICT_PACK = one contiguous slice').

    Usage:
        pg = slice_placement_group("v4-16")
        # bundle i -> host i of the slice
    """
    import ray_tpu

    num_hosts, host_chips, gen = slice_shape(accelerator_type)
    bundles = [
        {"TPU": float(host_chips), gen: float(host_chips)} for _ in range(num_hosts)
    ]
    return ray_tpu.placement_group(bundles, strategy=strategy)


def pod_head_resource(accelerator_type: str) -> dict[str, float]:
    """Resource dict targeting worker 0 of a slice, for launch-once pod
    coordination tasks (ref: the TPU-{pod}-head pattern, tpu.py:404)."""
    return {f"TPU-{accelerator_type}-head": 1.0}

"""Hot-path flight recorder: always-on, bounded-overhead stage telemetry.

The role of the reference's task-event instrumentation kept ALWAYS on
(ref: src/ray/core_worker/task_event_buffer.h per-task status/profile
events, src/ray/stats/metric_defs.cc stats families), built the way
Dapper-style production tracers are: every process keeps one fixed-size
ring of ns-stamped stage events in SHARED MEMORY, writes are a single
index bump + struct pack (no locks, no allocation, no syscalls), and the
expensive parts (percentile aggregation, GCS publishing, chrome-trace
expansion) happen off the hot path on the existing task-event flush
timer.

Clock model: stamps are ``time.perf_counter_ns()`` (CLOCK_MONOTONIC —
system-wide on Linux, so same-node processes' stamps are directly
comparable, which is exactly the fast lane's scope) plus ONE wall-clock
anchor captured at recorder creation; wall times are reconstructed as
``anchor_wall + (t - anchor_perf)`` so a clock step can never produce a
negative duration.

Because the ring lives in shm (a file under the session tree), the
raylet can map a SIGKILLed worker's recorder after death and dump the
victim's last-N events into its death report — the postmortem role of
the reference's worker crash logs, but with ns-resolution stage data.

Overhead budget: the recorder is ON by default and the task hot path
pays one ``record()`` per process per task (driver: one latency sample
at reply-apply; worker: one compact task record at exec end). Each
``record()`` is one ``struct.pack_into`` into the mapped ring plus an
index store — sub-microsecond; ``bench.py`` measures the end-to-end A/B
as ``recorder_overhead_us`` and the budget is < 1µs/task.
"""

from __future__ import annotations

import os
import struct
import time

from ray_tpu.config import get_config

# ------------------------------------------------------------------ stages
# Stage ids cover the fast-lane path submit-template pack -> ring push ->
# worker pop -> deserialize -> exec start/end -> completion push ->
# driver apply. Compact slots (W_TASK / SAMPLE) carry several stage
# durations in one write; events() expands them back into ordered
# per-stage events.
SUBMIT = 1            # driver: task record packed (t0, embedded in the wire record)
RING_PUSH = 2         # driver: one coalesced flush batch pushed (arg0=records)
WORKER_POP = 3        # worker: batch popped from the submit ring (arg0=records)
DESERIALIZE = 4       # worker: record unpacked + function resolved
EXEC_START = 5        # worker: user function entered
EXEC_END = 6          # worker: user function returned (arg: exec ns)
COMPLETION_PUSH = 7   # worker: reply batch pushed (arg0=records)
DRIVER_APPLY = 8      # driver: reply applied to the memory store
W_TASK = 9            # worker compact record: ring/deser/exec deltas, t=exec end
SAMPLE = 10           # driver compact record: full per-task stage breakdown
CHAOS = 11            # chaos fault fired (devtools/chaos): id slot carries
#                       the point name, args (rule, action code, fault seq)
# Sharded object plane (ray_tpu/sharded): per-shard seal/fetch and whole-
# array reshard events; args are (duration_ns clamped u32, nbytes lo,
# nbytes hi) so a postmortem shows which shard op a process died inside.
SHARD_SEAL = 12       # one shard sealed into the local shm arena
SHARD_FETCH = 13      # one shard read (zero-copy local or pulled)
RESHARD = 14          # collective-backed spec redistribute completed
# Disaggregated LLM serving (ray_tpu/llm/disagg): the request's journey
# through the prefill pool, the KV-page plane, and the decode pool; args
# are (duration_ns clamped u32, nbytes lo, nbytes hi) like the sharded
# stages, so a postmortem shows which leg a worker died inside.
PREFILL_QUEUE = 15    # request waited in a prefill worker's wave queue
KV_SHIP = 16          # KV pages sealed to shm (prefill) or adopted (decode)
DECODE_QUEUE = 17     # adopted request waited for a decode ring slot
# Cross-node node tunnel (core/tunnel.py): one event per coalesced frame
# in each direction — args are (records, bytes lo, bytes hi) so a trace
# shows how many ring-format records each tunnel frame carried (the
# coalescing evidence) and a postmortem shows the last frame a process
# shipped/received before dying.
TUNNEL_TX = 18        # driver: one coalesced record frame sent to a peer node
TUNNEL_RX = 19        # driver: one reply record frame received from a peer node
# Memory tiering (PR 18): the disk legs of the object plane; args are
# (duration_ns clamped u32, nbytes lo, nbytes hi) like the other byte-
# moving stages.
SPILL = 20            # arena pages written to a tier-1 spill file
RESTORE = 21          # tier-1 bytes restored into a fresh arena seal

STAGE_NAMES = {
    SUBMIT: "submit", RING_PUSH: "ring_push", WORKER_POP: "worker_pop",
    DESERIALIZE: "deserialize", EXEC_START: "exec_start",
    EXEC_END: "exec_end", COMPLETION_PUSH: "completion_push",
    DRIVER_APPLY: "driver_apply", W_TASK: "w_task", SAMPLE: "sample",
    CHAOS: "chaos", SHARD_SEAL: "shard_seal", SHARD_FETCH: "shard_fetch",
    RESHARD: "reshard", PREFILL_QUEUE: "prefill_queue", KV_SHIP: "kv_ship",
    DECODE_QUEUE: "decode_queue", TUNNEL_TX: "tunnel_tx",
    TUNNEL_RX: "tunnel_rx", SPILL: "spill", RESTORE: "restore",
}

# Reported latency stages (SAMPLE args, ns): both ring hops are covered —
# ring_sub is pack->worker-pop (hop 1, includes any coalescing defer),
# ring_reply is exec-end->driver-apply (hop 2, includes result pack +
# completion push + reply drain).
LATENCY_STAGES = ("ring_sub", "deserialize", "exec", "ring_reply", "total")

# ------------------------------------------------------------------- layout
_MAGIC = 0x52545245_43314100  # "RTREC1\0" + version byte
_HDR = struct.Struct("<QIIQQQ")  # magic, version, cap, write_seq, anchor_perf, anchor_wall
_HDR_SIZE = 64  # header padded to one cache line
_SLOT = struct.Struct("<QQ16sIIIIIII")  # seq, t_ns, tid, stage, a0..a5
_WTASK = struct.Struct("<QQ16sIIIII")   # prefix of _SLOT: a0..a3 only
_SLOT_SIZE = 64
_VERSION = 1
_SEQ_OFF = 16  # byte offset of write_seq within the header


class Recorder:
    """One process's stage-event ring.

    ``path=None`` keeps the ring in an anonymous buffer (driver default);
    a path maps a file so other processes (the raylet's postmortem read)
    can see it after this process dies.
    """

    def __init__(self, cap: int, path: str | None = None):
        cap = max(64, int(cap))
        self.cap = cap
        self.path = path
        size = _HDR_SIZE + cap * _SLOT_SIZE
        if path is None:
            self._mm = None
            self._buf = bytearray(size)
        else:
            import mmap

            os.makedirs(os.path.dirname(path), exist_ok=True)
            fd = os.open(path, os.O_CREAT | os.O_RDWR, 0o600)
            try:
                os.ftruncate(fd, size)
                self._mm = mmap.mmap(fd, size)
            finally:
                os.close(fd)
            self._buf = self._mm
        self.anchor_perf = time.perf_counter_ns()
        self.anchor_wall = time.time_ns()
        _HDR.pack_into(self._buf, 0, _MAGIC, _VERSION, cap, 0,
                       self.anchor_perf, self.anchor_wall)
        self._seq = 0
        # u64 view over the header's write_seq: publishing the cursor per
        # record is one int store, not a struct pack
        self._seqview = memoryview(self._buf)[_SEQ_OFF:_SEQ_OFF + 8].cast("Q")
        self._pack = _SLOT.pack_into  # bound-method lookup off the hot path

    # ------------------------------------------------------------- recording
    def record(self, tid: bytes, stage: int, t_ns: int = 0,
               a0: int = 0, a1: int = 0, a2: int = 0,
               a3: int = 0, a4: int = 0, a5: int = 0) -> None:
        """Append one stage event; lock-free, drop-oldest once the ring
        wraps. One pack_into + one cursor store — args must already fit
        u32 (callers clamp; masking here would tax every hot-path
        write). Writers are effectively serialized (driver: under the
        fast cv; worker: one pump per ring) and each pack_into is one
        GIL-atomic C call; a rare concurrent write can lose one event to
        last-writer-wins but never corrupt a slot."""
        seq = self._seq + 1
        self._seq = seq
        self._pack(self._buf,
                   _HDR_SIZE + (seq % self.cap) * _SLOT_SIZE,
                   seq, t_ns or time.perf_counter_ns(), tid, stage,
                   a0, a1, a2, a3, a4, a5)
        self._seqview[0] = seq

    def record_sample(self, tid: bytes, t_apply_ns: int, ring_ns: int,
                      deser_ns: int, exec_ns: int, reply_ns: int,
                      total_ns: int) -> None:
        """Driver-side compact per-task record (ONE slot for the whole
        stage breakdown; events() expands it)."""
        self.record(tid, SAMPLE, t_apply_ns, min(ring_ns, 0xFFFFFFFF),
                    min(deser_ns, 0xFFFFFFFF),
                    exec_ns & 0xFFFFFFFF, exec_ns >> 32,
                    min(reply_ns, 0xFFFFFFFF), min(total_ns, 0xFFFFFFFF))

    def record_wtask(self, tid: bytes, t_end_ns: int, ring_ns: int,
                     deser_ns: int, exec_ns: int) -> None:
        """Worker-side compact per-task record at exec end — the one
        recorder write on the worker's per-task hot path, so it packs
        directly (no generic record() indirection; ring/deser already
        clamped by the pump). Unwritten arg fields may hold stale bytes
        from a wrapped slot; W_TASK expansion never reads past a3."""
        seq = self._seq + 1
        self._seq = seq
        _WTASK.pack_into(self._buf,
                         _HDR_SIZE + (seq % self.cap) * _SLOT_SIZE,
                         seq, t_end_ns, tid, W_TASK, ring_ns, deser_ns,
                         exec_ns & 0xFFFFFFFF, exec_ns >> 32)
        self._seqview[0] = seq

    # --------------------------------------------------------------- reading
    def wall_ns(self, t_ns: int) -> int:
        return self.anchor_wall + (t_ns - self.anchor_perf)

    def raw_events(self, last: int | None = None) -> list[dict]:
        return _decode(self._buf, last)

    def events(self, last: int | None = None) -> list[dict]:
        """Decoded events oldest-first, with compact W_TASK/SAMPLE slots
        expanded into ordered per-stage events (synthesized timestamps
        walk backwards from the slot's anchor time)."""
        return _expand(self.raw_events(last))

    def close(self) -> None:
        if self._mm is not None:
            try:
                self._seqview.release()
                self._mm.close()
            except (BufferError, ValueError):
                pass
            self._mm = None

    def unlink(self) -> None:
        """Remove the backing file's NAME only — the mapping stays valid,
        so in-flight record() calls on other threads are safe; the pages
        go away when the process exits (same pattern as RingPair.unlink)."""
        if self.path:
            try:
                os.unlink(self.path)
            except OSError:
                pass


# -------------------------------------------------------- postmortem reading
def read_events(path: str, last: int | None = None) -> list[dict]:
    """Read a (possibly dead) process's recorder file: the raylet's
    postmortem path after a worker SIGKILL. Returns expanded events
    oldest-first, [] when the file is missing/garbage (a torn header must
    not sink the death report)."""
    try:
        with open(path, "rb") as f:
            buf = f.read()
    except OSError:
        return []
    try:
        return _expand(_decode(buf, last))
    except Exception:
        return []


def _decode(buf, last: int | None) -> list[dict]:
    if len(buf) < _HDR_SIZE:
        return []
    magic, version, cap, wseq, a_perf, a_wall = _HDR.unpack_from(buf, 0)
    if magic != _MAGIC or cap <= 0 or len(buf) < _HDR_SIZE + cap * _SLOT_SIZE:
        return []
    lo = max(1, wseq - cap + 1)
    if last is not None:
        lo = max(lo, wseq - last + 1)
    out = []
    for seq in range(lo, wseq + 1):
        off = _HDR_SIZE + (seq % cap) * _SLOT_SIZE
        s, t_ns, tid, stage, a0, a1, a2, a3, a4, a5 = _SLOT.unpack_from(buf, off)
        if s != seq:  # torn/unwritten slot (e.g. killed mid-write)
            continue
        out.append({
            "seq": s, "t_ns": t_ns, "wall_ns": a_wall + (t_ns - a_perf),
            "task_id": tid.hex(), "stage": STAGE_NAMES.get(stage, stage),
            "args": (a0, a1, a2, a3, a4, a5),
        })
    return out


def _expand(events: list[dict]) -> list[dict]:
    out: list[dict] = []
    for ev in events:
        a = ev["args"]
        if ev["stage"] == "w_task":
            ring, deser = a[0], a[1]
            exec_ns = a[2] | (a[3] << 32)
            t_end = ev["t_ns"]
            base = dict(task_id=ev["task_id"], seq=ev["seq"])
            anchor = ev["wall_ns"] - t_end
            for stage, t in (("worker_pop", t_end - exec_ns - deser),
                             ("deserialize", t_end - exec_ns),
                             ("exec_start", t_end - exec_ns),
                             ("exec_end", t_end)):
                out.append({**base, "stage": stage, "t_ns": t,
                            "wall_ns": anchor + t,
                            "args": (ring, deser, a[2], a[3], 0, 0)})
        elif ev["stage"] == "sample":
            ring, deser, reply = a[0], a[1], a[4]
            exec_ns = a[2] | (a[3] << 32)
            t_apply = ev["t_ns"]
            t0 = t_apply - reply - exec_ns - deser - ring
            base = dict(task_id=ev["task_id"], seq=ev["seq"])
            anchor = ev["wall_ns"] - t_apply
            for stage, t in (("submit", t0),
                             ("worker_pop", t0 + ring),
                             ("exec_start", t0 + ring + deser),
                             ("exec_end", t0 + ring + deser + exec_ns),
                             ("driver_apply", t_apply)):
                out.append({**base, "stage": stage, "t_ns": t,
                            "wall_ns": anchor + t,
                            "args": a})
        else:
            out.append(ev)
    return out


# ------------------------------------------------------------- latency stats
class StageStats:
    """Driver-side per-task stage accumulator. The hot path stores the
    RAW reply evidence — ``(t0, t_rx, tid, stamp_bytes)`` — as one tuple
    into a fixed ring (one list store, no parsing, no arithmetic);
    stamps are decoded into (ring_sub, deserialize, exec, ring_reply,
    total) durations lazily at flush/query time over bounded windows.
    This is the whole overhead trick: per task O(1) appends, per SECOND
    bounded decoding."""

    __slots__ = ("ring", "cap", "n", "flushed")

    def __init__(self, cap: int):
        self.cap = max(64, int(cap))
        self.ring: list = [None] * self.cap
        self.n = 0
        self.flushed = 0  # samples already fed to histograms

    def add(self, sample: tuple) -> None:
        self.ring[self.n % self.cap] = sample
        self.n += 1

    def _raw(self, lo: int, hi: int) -> list[tuple]:
        return [s for s in (self.ring[k % self.cap] for k in range(lo, hi))
                if s is not None]

    def window(self, limit: int | None = None) -> list[tuple]:
        """DECODED samples (ring_sub, deser, exec, reply, total) ns,
        oldest-first (``limit``: newest N only — flush-time aggregation
        bounds its work with this)."""
        n = self.n
        lo = max(0, n - self.cap)
        if limit is not None:
            lo = max(lo, n - limit)
        return [decode_sample(s) for s in self._raw(lo, n)]

    def new_since_flush(self, limit: int = 128) -> list[tuple]:
        """Decoded samples added since the last call (bounded: at most
        ``limit`` of the newest — histogram feeding is sampled under
        load, the Dapper trade)."""
        fresh = min(self.n - self.flushed, self.cap, limit)
        self.flushed = self.n
        if fresh <= 0:
            return []
        return [decode_sample(s) for s in self._raw(self.n - fresh, self.n)]

    def raw_window(self, limit: int) -> list[tuple]:
        """Newest raw (t0, t_rx, tid, stamp) tuples (timeline samples)."""
        n = self.n
        return self._raw(max(0, n - self.cap, n - limit), n)

    def snapshot(self, anchor_wall: int, anchor_perf: int) -> dict | None:
        """Publishable latency snapshot: per-stage duration lists from
        the retained window, capped at the newest 1024 — this runs on
        the 1Hz flush timer and its cost (decode + list build + pickle)
        must not scale with recorder_events_cap (the CoreClient flush
        attaches the newest raw wall-anchored samples for timeline
        enrichment)."""
        win = self.window(1024)
        if not win:
            return None
        stages = {name: [s[i] for s in win]
                  for i, name in enumerate(LATENCY_STAGES)}
        return {
            "count": self.n,
            "anchor_wall_ns": anchor_wall,
            "anchor_perf_ns": anchor_perf,
            "stages": stages,
        }


def decode_sample(raw: tuple) -> tuple:
    """(t0, t_rx, tid, stamp) -> (ring_sub, deser, exec, reply, total) ns."""
    t0, t_rx, _tid, stamp = raw
    ring_ns, deser_ns, exec_ns = _STAMPF.unpack(stamp)
    total = t_rx - t0 if t_rx > t0 else 0
    reply = total - ring_ns - deser_ns - exec_ns
    return (ring_ns, deser_ns, exec_ns, reply if reply > 0 else 0, total)


# mirror of core/fastpath.py's reply stamp layout (kept here so decode
# has no import cycle): <u32 ring_ns, u32 deser_ns, u64 exec_ns>
_STAMPF = struct.Struct("<IIQ")


def percentile(sorted_vals: list, q: float) -> float:
    """Nearest-rank percentile over a pre-sorted list."""
    if not sorted_vals:
        return 0.0
    k = min(len(sorted_vals) - 1, max(0, int(round(q * (len(sorted_vals) - 1)))))
    return float(sorted_vals[k])


# ------------------------------------------------------- process-level state
_recorder: Recorder | None = None
_stats: StageStats | None = None
_enabled: bool | None = None


def enabled() -> bool:
    global _enabled
    if _enabled is None:
        _enabled = get_config().recorder_enabled
    return _enabled


def set_enabled(on: bool) -> None:
    """Force the recorder on/off in-process (bench A/B)."""
    global _enabled
    _enabled = bool(on)


def init_process_recorder(path: str | None = None) -> Recorder | None:
    """Create (or re-anchor) this process's recorder. Workers pass a file
    path under the session tree so the raylet can read it postmortem;
    the driver keeps an anonymous ring."""
    global _recorder, _stats
    if not enabled():
        return None
    cap = get_config().recorder_events_cap
    try:
        _recorder = Recorder(cap, path)
    except OSError:
        _recorder = Recorder(cap, None)  # unwritable session dir: stay in-memory
    _stats = StageStats(cap)
    return _recorder


def get_recorder() -> Recorder | None:
    """The process recorder, lazily created anonymous when enabled;
    None while disabled (the single hot-path gate)."""
    if not enabled():
        return None
    if _recorder is None:
        init_process_recorder(None)
    return _recorder


def get_stats() -> StageStats | None:
    if not enabled():
        return None
    if _stats is None:
        init_process_recorder(None)
    return _stats


def worker_recorder_path(temp_dir: str, session: str, worker_hex: str) -> str:
    """Shared convention between worker (creates) and raylet (postmortem
    read): the recorder file of one worker process."""
    return os.path.join(temp_dir, f"session_{session}", "rec",
                        f"worker-{worker_hex[:12]}.rec")

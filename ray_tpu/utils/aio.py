"""Small asyncio helpers shared by the control-plane components.

The reference runtime tears down its event loops by joining C++ threads;
our asyncio equivalents instead track every background task they spawn so
close()/stop() can cancel them deterministically (no "Task was destroyed
but it is pending!" spray on interpreter exit).
"""
from __future__ import annotations

import asyncio
import logging

logger = logging.getLogger("ray_tpu")


class TaskGroup:
    """Tracks background tasks so they can be cancelled together on close."""

    def __init__(self) -> None:
        self._tasks: set[asyncio.Task] = set()
        self._closed = False

    def spawn(self, coro, loop: asyncio.AbstractEventLoop | None = None) -> asyncio.Task | None:
        if self._closed:
            coro.close()
            return None
        lp = loop or asyncio.get_running_loop()
        task = lp.create_task(coro)
        self._tasks.add(task)
        task.add_done_callback(self._on_done)
        return task

    def _on_done(self, task: asyncio.Task) -> None:
        self._tasks.discard(task)
        if not task.cancelled():
            # retrieve (and log) the exception now instead of asyncio's
            # nondeterministic "never retrieved" warning at GC time
            exc = task.exception()
            if exc is not None:
                logger.warning("background task %s failed", task.get_name(), exc_info=exc)

    async def cancel_all(self) -> None:
        self._closed = True
        tasks = list(self._tasks)
        for t in tasks:
            t.cancel()
        if tasks:
            await asyncio.gather(*tasks, return_exceptions=True)
        self._tasks.clear()

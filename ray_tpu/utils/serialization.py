"""Zero-copy object (de)serialization.

TPU-native equivalent of the reference's SerializationContext
(ref: python/ray/_private/serialization.py): pickle protocol-5 with
out-of-band buffers so large tensors are written straight into shared memory
with no intermediate copy, cloudpickle fallback for closures/lambdas, and a
wire layout of ``[u32 meta_len][meta pickle][buffer 0][buffer 1]...`` with
64-byte alignment per buffer so a deserialized numpy array can alias the shm
mapping directly (zero-copy ``get``).

jax.Array values are carried as host numpy and restored with
``jax.device_put`` on deserialization — host<->device transfer stays explicit,
which is the TPU-idiomatic stance (device buffers are not addressable shm).
"""

from __future__ import annotations

import io
import os
import pickle
import struct
import sys
import sysconfig
import types
from typing import Any

import numpy as np

try:
    import cloudpickle
except ImportError:  # pragma: no cover
    cloudpickle = None

_ALIGN = 64  # buffers aligned for vector loads / DMA


def _align(n: int) -> int:
    return (n + _ALIGN - 1) & ~(_ALIGN - 1)


# --------------------------------------------------------------- code shipping
#
# Functions/classes whose defining module is NOT importable inside a worker
# (driver scripts, pytest test modules, anything outside site-packages and the
# ray_tpu tree) must travel *by value*, matching the reference's
# always-cloudpickle behavior for function payloads
# (ref: python/ray/remote_function.py:41 pickled-function export,
# python/ray/_private/runtime_env/working_dir.py:1 motivates the importability
# test). cloudpickle pickles by reference whenever the module resolves in the
# *driver*, which is exactly wrong for test modules — so we register such
# modules with cloudpickle.register_pickle_by_value before dumping.

_INSTALLED_PREFIXES: tuple | None = None
_BY_VALUE_REGISTERED: set = set()


def _installed_prefixes() -> tuple:
    global _INSTALLED_PREFIXES
    if _INSTALLED_PREFIXES is None:
        paths = sysconfig.get_paths()
        prefs = {
            paths.get("purelib"),
            paths.get("platlib"),
            paths.get("stdlib"),
            paths.get("platstdlib"),
        }
        # pip --user / venv / distro site dirs live outside the sysconfig
        # scheme on some installs — anything importable from a site dir is
        # importable in workers too, so it must NOT ship by value
        try:
            import site

            prefs.update(site.getsitepackages())
            prefs.add(site.getusersitepackages())
        except (ImportError, AttributeError,  # pragma: no cover
                OSError):  # site can be absent (embedded interpreters)
            pass
        # trailing sep so /usr/lib/python3.12 doesn't match .../python3.12-foo
        _INSTALLED_PREFIXES = tuple(
            os.path.realpath(p) + os.sep for p in prefs if p
        )
    return _INSTALLED_PREFIXES


def module_ships_by_value(modname) -> bool:
    """True when a worker process cannot be assumed to import ``modname``."""
    if modname in ("__main__", "__mp_main__", None):
        return True
    root = modname.split(".")[0]
    if root == "ray_tpu":
        return False  # workers always have the package tree on sys.path
    m = sys.modules.get(root)
    if m is None:
        return True
    f = getattr(m, "__file__", None)
    if f is None:
        return False  # builtin / frozen — present everywhere
    f = os.path.realpath(f)
    return not any(f.startswith(p) for p in _installed_prefixes())


def _register_by_value(modname) -> None:
    if cloudpickle is None or not hasattr(cloudpickle, "register_pickle_by_value"):
        return
    root = (modname or "__main__").split(".")[0]
    if root in _BY_VALUE_REGISTERED or root in ("__main__", "__mp_main__"):
        return
    m = sys.modules.get(root)
    if m is not None and module_ships_by_value(modname):
        try:
            cloudpickle.register_pickle_by_value(m)
        except Exception:  # raylint: disable=RT012 — best-effort hint; pickling falls back by-reference
            pass
    _BY_VALUE_REGISTERED.add(root)


def _referenced_modules(obj, depth: int, seen: set):
    """Module names of ``obj`` and of functions/classes it references."""
    if id(obj) in seen or depth < 0:
        return
    seen.add(id(obj))
    if not isinstance(obj, (types.FunctionType, type)):
        return
    yield getattr(obj, "__module__", None)
    if isinstance(obj, types.FunctionType):
        refs = []
        for cell in obj.__closure__ or ():
            try:
                refs.append(cell.cell_contents)
            except ValueError:
                pass
        g = obj.__globals__
        refs.extend(g[n] for n in obj.__code__.co_names if n in g)
        for r in refs:
            yield from _referenced_modules(r, depth - 1, seen)


def ship_dumps(obj) -> bytes:
    """cloudpickle.dumps that forces by-value pickling of user modules.

    Used for the GCS function table and actor class blobs; also backs the
    per-object reducer in _Pickler so functions passed as task/actor-call
    *arguments* (the JaxTrainer train_loop path) survive the trip to a worker
    that cannot import the driver's module."""
    if cloudpickle is None:  # pragma: no cover
        return pickle.dumps(obj)
    for mod in _referenced_modules(obj, depth=3, seen=set()):
        _register_by_value(mod)
    return cloudpickle.dumps(obj)


def _restore_jax(np_val):
    import jax

    return jax.device_put(np_val)


def _restore_arrow_table(buf):
    import pyarrow as pa

    # pa.py_buffer wraps the (possibly shm-backed) view without copying;
    # IPC open_stream then maps the table's columns straight onto it —
    # the zero-copy read path of the reference's Arrow blocks
    # (ref: _internal/arrow_block.py + arrow serialization)
    return pa.ipc.open_stream(pa.py_buffer(buf)).read_all()


class _Pickler(pickle.Pickler):
    """Pickler with a jax.Array reducer (only when jax is already imported).

    Functions/classes from modules a worker cannot import (``__main__``,
    driver scripts, test modules) are rerouted through ship_dumps so they
    travel by value — the classic driver-script pitfall the reference avoids
    by always cloudpickling function payloads."""

    jax_array_type = None
    arrow_table_type = None

    def reducer_override(self, obj):
        if self.jax_array_type is not None and isinstance(obj, self.jax_array_type):
            return (_restore_jax, (np.asarray(obj),))
        if (self.arrow_table_type is not None
                and isinstance(obj, self.arrow_table_type)):
            import pyarrow as pa

            sink = pa.BufferOutputStream()
            with pa.ipc.new_stream(sink, obj.schema) as w:
                w.write_table(obj)
            # PickleBuffer rides the protocol-5 out-of-band path: the IPC
            # payload lands in shm unsplit, and readers re-open it
            # zero-copy (see _restore_arrow_table)
            return (_restore_arrow_table,
                    (pickle.PickleBuffer(sink.getvalue()),))
        if isinstance(obj, (types.FunctionType, type)) and module_ships_by_value(
            getattr(obj, "__module__", None)
        ):
            if cloudpickle is not None:
                return (cloudpickle.loads, (ship_dumps(obj),))
            raise pickle.PicklingError("user-module object needs cloudpickle")
        return NotImplemented


def _jax_array_type():
    import sys

    jax = sys.modules.get("jax")
    return jax.Array if jax is not None else None


def _arrow_table_type():
    import sys

    pa = sys.modules.get("pyarrow")
    return pa.Table if pa is not None else None


def serialize(obj: Any) -> tuple[bytes, list]:
    """Returns (pickle_header_bytes, out_of_band_buffers)."""
    buffers: list = []
    f = io.BytesIO()
    try:
        p = _Pickler(f, protocol=5, buffer_callback=buffers.append)
        p.jax_array_type = _jax_array_type()
        p.arrow_table_type = _arrow_table_type()
        p.dump(obj)
        header = f.getvalue()
    except Exception:
        if cloudpickle is None:
            raise
        buffers = []
        header = cloudpickle.dumps(obj, protocol=5, buffer_callback=buffers.append)
    return header, buffers


def dumps_with_buffers(obj: Any) -> tuple[bytes, list]:
    """Serialize; meta pickle embeds the out-of-band buffer sizes."""
    header, buffers = serialize(obj)
    sizes = [memoryview(b).nbytes for b in buffers]
    meta = pickle.dumps((sizes, header), protocol=4)
    return meta, buffers


def total_size(meta: bytes, buffers: list) -> int:
    total = 4 + len(meta)
    for b in buffers:
        total = _align(total) + memoryview(b).nbytes
    return total


_NT_MIN = 1 << 20  # below this, streaming stores don't pay for the sfence


def _copy_buffer(dest: memoryview, start: int, mv: memoryview) -> None:
    """One wire-buffer copy; large copies take the native non-temporal
    path (rt_copy_nt: streaming stores skip the destination
    read-for-ownership — the dest is shm another process will read, so
    there is no point pulling it through this core's cache)."""
    n = mv.nbytes
    if n >= _NT_MIN:
        try:
            from ray_tpu import _native

            lib = _native.get_lib()
            d = np.frombuffer(dest[start:start + n], dtype=np.uint8)
            s = np.frombuffer(mv, dtype=np.uint8)
            lib.rt_copy_nt(d.ctypes.data, s.ctypes.data, n)
            return
        except (ImportError, OSError, AttributeError):
            pass  # no native lib (client mode): plain slice copy
    dest[start:start + n] = mv


def pack_into(meta: bytes, buffers: list, dest: memoryview) -> int:
    """Write the wire layout into ``dest``; returns bytes written."""
    struct.pack_into("<I", dest, 0, len(meta))
    off = 4
    dest[off : off + len(meta)] = meta
    off += len(meta)
    for b in buffers:
        mv = memoryview(b).cast("B")
        start = _align(off)
        if mv.nbytes:
            _copy_buffer(dest, start, mv)
        off = start + mv.nbytes
    return off


def pack(obj: Any) -> bytes:
    """One-shot serialize to a contiguous blob (inline/small-object path)."""
    meta, buffers = dumps_with_buffers(obj)
    if not buffers:
        # submission hot path: small task args/results carry no
        # out-of-band buffers — skip the bytearray + pack_into round trip
        # (byte-identical wire layout: [u32 meta_len][meta])
        return struct.pack("<I", len(meta)) + meta
    out = bytearray(total_size(meta, buffers))
    pack_into(meta, buffers, memoryview(out))
    return bytes(out)


class _GuardedBuffer:
    """Buffer-protocol wrapper (PEP 688) tying a shm slice to a lifetime guard.

    Arrays deserialized from out-of-band buffers keep their source buffer
    object alive through the buffer protocol; wrapping each slice here means
    the ``guard`` (e.g. an object-store reference) lives exactly as long as
    any zero-copy view onto it — released when the last consumer is GC'd.
    """

    __slots__ = ("_mv", "_guard")

    def __init__(self, mv: memoryview, guard):
        self._mv = mv
        self._guard = guard

    def __buffer__(self, flags):
        return memoryview(self._mv)


# PEP 688 (__buffer__ on a plain class) only exists on 3.12+; earlier
# interpreters get a ctypes view, which exports the buffer protocol
# natively, pins the source buffer (from_buffer holds it), and carries the
# guard as an attribute — same zero-copy aliasing, same lifetime tie.
_HAVE_PEP688 = sys.version_info >= (3, 12)


def _guarded_slice(sl: memoryview, guard):
    if _HAVE_PEP688:
        return _GuardedBuffer(sl, guard)
    import ctypes

    try:
        view = (ctypes.c_char * sl.nbytes).from_buffer(sl)
    except (TypeError, ValueError):
        # read-only source: copy (no aliasing view to tie, but the guard
        # still rides along so the caller's release logic stays uniform)
        view = (ctypes.c_char * sl.nbytes).from_buffer_copy(sl)
    view._guard = guard
    return view


def unpack(src, guard=None) -> Any:
    """Deserialize a packed blob; array buffers alias ``src`` (zero-copy).

    If ``guard`` is given, every zero-copy view keeps it alive (see
    _GuardedBuffer); returns (value, had_out_of_band_buffers) semantics are
    folded into the guard: when there are no buffers the guard is unused.
    """
    src = memoryview(src).cast("B")
    (meta_len,) = struct.unpack_from("<I", src, 0)
    off = 4
    sizes, header = pickle.loads(bytes(src[off : off + meta_len]))
    off += meta_len
    slices = []
    for size in sizes:
        start = _align(off)
        sl = src[start : start + size]
        slices.append(sl if guard is None else _guarded_slice(sl, guard))
        off = start + size
    return pickle.loads(header, buffers=slices)


def unpack_has_buffers(src) -> bool:
    """True if the blob carries out-of-band (potentially aliasing) buffers."""
    src = memoryview(src).cast("B")
    (meta_len,) = struct.unpack_from("<I", src, 0)
    sizes, _ = pickle.loads(bytes(src[4 : 4 + meta_len]))
    return bool(sizes)

"""Asyncio RPC substrate for the control plane.

Fills the role of the reference's gRPC scaffolding (ref: src/ray/rpc/
grpc_server.h:88, client_call.h:203): request/response with correlation ids,
one-way notifications, and server-push messages over length-prefixed pickle
frames on TCP. Interfaces are deliberately service-shaped (method-name
dispatch) so a future C++/gRPC data plane can slot in behind the same call
sites.
"""

from __future__ import annotations

import asyncio
import itertools
import logging
import pickle
import random
import struct
import threading
from typing import Any, Awaitable, Callable

from ray_tpu.devtools import chaos

logger = logging.getLogger(__name__)

_LEN = struct.Struct("<Q")


def _chaos_frame(msg: Any, data: bytes):
    """"rpc.send" fault-point verdict for one outbound frame: the
    (possibly corrupted) bytes to write, None to drop the frame on the
    floor, or ``(data, data)`` when the frame must be written twice
    (duplicate). delay sleeps in place (the transport thread stalls —
    a slow/frozen peer link); an `error` action surfaces as
    ConnectionLost, the exact exception a dead transport raises, so the
    injected fault travels the same recovery paths the real one does."""
    try:
        # corruption targets the pickled body, not the length prefix: a
        # mangled prefix would desync the stream into a silent hang,
        # while a mangled body surfaces as a deserialization fault the
        # peer's read loop actually handles
        act = chaos.point(
            "rpc.send", data[_LEN.size:],
            method=msg.get("m") if isinstance(msg, dict) else None,
            kind=msg.get("k") if isinstance(msg, dict) else None)
    except chaos.ChaosError as e:
        raise ConnectionLost(f"chaos: {e}") from e
    if act is None:
        return data
    if act.kind == "drop":
        return None
    if act.kind == "corrupt" and act.payload is not None:
        return data[:_LEN.size] + act.payload
    if act.kind == "duplicate":
        return (data, data)
    return data


def _resolve_multi(pending: dict, items: list):
    """Resolve futures for a coalesced-response ("R") frame:
    items = [(corr_id, value, error)]."""
    for i, v, e in items:
        fut = pending.pop(i, None)
        if fut is not None and not fut.done():
            if e is not None:
                fut.set_exception(e)
            else:
                fut.set_result(v)


class RpcError(Exception):
    pass


class ConnectionLost(RpcError):
    pass


async def read_frame(reader: asyncio.StreamReader) -> Any:
    try:
        header = await reader.readexactly(_LEN.size)
        (n,) = _LEN.unpack(header)
        payload = await reader.readexactly(n)
    except (asyncio.IncompleteReadError, ConnectionResetError, BrokenPipeError) as e:
        raise ConnectionLost(str(e)) from None
    return pickle.loads(payload)


def frame_bytes(msg: Any) -> bytes:
    payload = pickle.dumps(msg, protocol=5)
    return _LEN.pack(len(payload)) + payload


class Connection:
    """One bidirectional peer link: concurrent calls, notifications, push."""

    def __init__(self, reader: asyncio.StreamReader, writer: asyncio.StreamWriter):
        self.reader = reader
        self.writer = writer
        self._ids = itertools.count(1)
        self._pending: dict[int, asyncio.Future] = {}
        self._closed = False
        self.on_message: Callable[[dict], Awaitable[Any] | None] | None = None
        self._reader_task: asyncio.Task | None = None

    @property
    def peername(self):
        try:
            return self.writer.get_extra_info("peername")
        except Exception:
            return None

    def start(self):
        self._reader_task = asyncio.get_running_loop().create_task(self._read_loop())

    async def _read_loop(self):
        try:
            while True:
                msg = await read_frame(self.reader)
                kind = msg.get("k")
                if kind == "r":  # response
                    fut = self._pending.pop(msg["i"], None)
                    if fut is not None and not fut.done():
                        if msg.get("e") is not None:
                            fut.set_exception(msg["e"])
                        else:
                            fut.set_result(msg.get("v"))
                elif kind == "R":  # coalesced responses (scatter replies)
                    _resolve_multi(self._pending, msg["f"])
                elif self.on_message is not None:
                    res = self.on_message(msg)
                    if asyncio.iscoroutine(res):
                        asyncio.get_running_loop().create_task(res)
        except (ConnectionLost, asyncio.CancelledError, Exception) as e:
            self._fail_pending(e if isinstance(e, Exception) else ConnectionLost("closed"))

    def _fail_pending(self, exc: Exception):
        self._closed = True
        # peer DIED (not a deliberate close): its replacement on this
        # address must re-handshake — a restart can change the wire
        # version, and ephemeral ports get reused
        if not getattr(self, "_closing", False):
            _VERIFIED_PEERS.discard(getattr(self, "_peer_key", None))
        exc = exc if isinstance(exc, ConnectionLost) else ConnectionLost(repr(exc))
        for fut in self._pending.values():
            if not fut.done():
                fut.set_exception(exc)
        self._pending.clear()

    def send_nowait(self, msg: dict):
        """Write a frame without awaiting backpressure (transport buffers)."""
        if self._closed:
            raise ConnectionLost("connection closed")
        data = frame_bytes(msg)
        if chaos.ENABLED:
            data = _chaos_frame(msg, data)
            if data is None:
                return  # dropped: the peer never sees this frame
            if isinstance(data, tuple):  # duplicated
                for d in data:
                    self.writer.write(d)
                return
        self.writer.write(data)

    async def send(self, msg: dict):
        self.send_nowait(msg)
        # Backpressure: only await when the transport is actually over its
        # high-water mark (drain() is a no-op await otherwise, and skipping
        # it saves a lock + await per frame on the hot path).
        try:
            if self.writer.transport.get_write_buffer_size() > (1 << 21):
                await self.writer.drain()
        except (ConnectionResetError, BrokenPipeError) as e:
            raise ConnectionLost(str(e)) from None

    async def call(self, method: str, payload: Any = None, timeout: float | None = None):
        i = next(self._ids)
        fut: asyncio.Future = asyncio.get_running_loop().create_future()
        self._pending[i] = fut
        await self.send({"k": "c", "i": i, "m": method, "p": payload})
        if timeout is None:
            return await fut
        return await asyncio.wait_for(fut, timeout)

    async def notify(self, method: str, payload: Any = None):
        await self.send({"k": "n", "m": method, "p": payload})

    async def respond(self, msg_id: int, value: Any = None, error: Exception | None = None):
        await self.send({"k": "r", "i": msg_id, "v": value, "e": error})

    async def respond_multi(self, items: list):
        """items: [(msg_id, value, error)] — one frame, many responses."""
        await self.send({"k": "R", "f": items})

    call_scatter = None  # bound below (shared with LoopbackConnection)

    async def close(self):
        self._closed = True
        self._closing = True  # deliberate: keep the peer's handshake cached
        if self._reader_task is not None:
            self._reader_task.cancel()
        try:
            self.writer.close()
            # wait_closed() can hang indefinitely when the reader task was
            # cancelled mid-frame; bound it — the fd is closed either way.
            await asyncio.wait_for(self.writer.wait_closed(), timeout=1.0)
        except (Exception, asyncio.TimeoutError):
            pass


class LoopbackConnection:
    """In-memory Connection pair end for same-process, same-loop peers.

    When the driver runs the GCS/raylet on its own event loop (head mode),
    TCP round-trips per control message are pure syscall overhead. A
    loopback pair delivers frames as loop callbacks instead; messages still
    take a pickle round-trip so payload isolation matches the wire path.
    Duck-types the subset of Connection the control plane uses.
    """

    def __init__(self):
        self.peer: "LoopbackConnection | None" = None
        self._ids = itertools.count(1)
        self._pending: dict[int, asyncio.Future] = {}
        self._closed = False
        self.on_message: Callable[[dict], Awaitable[Any] | None] | None = None
        # set on the server-side end only:
        self._server: "RpcServer | None" = None

    @property
    def peername(self):
        return ("loopback", 0)

    def _deliver(self, msg: dict):
        """Hand a frame to this end, as if read off the socket."""
        if self._closed:
            return
        msg = pickle.loads(pickle.dumps(msg, protocol=5))
        kind = msg.get("k")
        if self._server is not None:
            if kind in ("c", "n"):
                self._server._spawn_dispatch(self, msg)
            elif kind == "r":  # reply to a server-initiated call on this conn
                fut = self._pending.pop(msg["i"], None)
                if fut is not None and not fut.done():
                    if msg.get("e") is not None:
                        fut.set_exception(msg["e"])
                    else:
                        fut.set_result(msg.get("v"))
            elif kind == "R":
                self._apply_multi(msg["f"])
            return
        if kind == "r":
            fut = self._pending.pop(msg["i"], None)
            if fut is not None and not fut.done():
                if msg.get("e") is not None:
                    fut.set_exception(msg["e"])
                else:
                    fut.set_result(msg.get("v"))
        elif kind == "R":
            self._apply_multi(msg["f"])
        elif self.on_message is not None:
            res = self.on_message(msg)
            if asyncio.iscoroutine(res):
                asyncio.get_running_loop().create_task(res)

    def _fail_pending(self, exc: Exception):
        self._closed = True
        exc = exc if isinstance(exc, ConnectionLost) else ConnectionLost(repr(exc))
        for fut in self._pending.values():
            if not fut.done():
                fut.set_exception(exc)
        self._pending.clear()

    def send_nowait(self, msg: dict):
        if self._closed or self.peer is None:
            raise ConnectionLost("connection closed")
        if chaos.ENABLED:
            # loopback is still "rpc.send": head-mode in-process clusters
            # must see the same drop/duplicate/delay/error faults the
            # wire path does (corrupt has no byte frame here: log-only);
            # error surfaces as ConnectionLost exactly like the wire path
            try:
                act = chaos.point("rpc.send", method=msg.get("m"),
                                  kind=msg.get("k"))
            except chaos.ChaosError as e:
                raise ConnectionLost(f"chaos: {e}") from e
            if act is not None:
                if act.kind == "drop":
                    return
                if act.kind == "duplicate":
                    self.peer._deliver(msg)
        self.peer._deliver(msg)

    async def send(self, msg: dict):
        self.send_nowait(msg)

    async def call(self, method: str, payload: Any = None, timeout: float | None = None):
        i = next(self._ids)
        fut: asyncio.Future = asyncio.get_running_loop().create_future()
        self._pending[i] = fut
        await self.send({"k": "c", "i": i, "m": method, "p": payload})
        if timeout is None:
            return await fut
        return await asyncio.wait_for(fut, timeout)

    async def notify(self, method: str, payload: Any = None):
        await self.send({"k": "n", "m": method, "p": payload})

    async def respond(self, msg_id: int, value: Any = None, error: Exception | None = None):
        await self.send({"k": "r", "i": msg_id, "v": value, "e": error})

    async def respond_multi(self, items: list):
        await self.send({"k": "R", "f": items})

    def _apply_multi(self, items: list):
        _resolve_multi(self._pending, items)

    call_scatter = None  # bound below (shared with Connection)

    async def close(self):
        if self._closed:
            return
        self._fail_pending(ConnectionLost("connection closed"))
        peer = self.peer
        if peer is not None and not peer._closed:
            peer._fail_pending(ConnectionLost("peer disconnected"))
            srv = peer._server
            if srv is not None:
                srv._conns.discard(peer)
                if srv.on_disconnect is not None:
                    try:
                        srv.on_disconnect(peer)
                    except Exception:
                        logger.debug("on_disconnect hook failed",
                                     exc_info=True)


# (host, port) -> (RpcServer, loop) for servers in this process; lets
# rpc.connect() short-circuit same-loop connections through a loopback pair.
_LOCAL_SERVERS: dict[tuple, tuple] = {}

# peers whose version handshake already succeeded this process: a live
# peer's version cannot change, so repeat connects (e.g. per-call owner
# dials) skip the extra round-trip.
_VERIFIED_PEERS: set = set()


def _call_scatter(self, method: str, payloads: list) -> list:
    """Send MANY calls in ONE frame; the handler replies per item (each got
    its own correlation id), so batching the transport does not batch
    completion — a slow task can't hold back its batch-mates' replies.
    Returns one future per payload, resolved like call()'s."""
    loop = asyncio.get_running_loop()
    futs, items = [], []
    for p in payloads:
        i = next(self._ids)
        fut = loop.create_future()
        self._pending[i] = fut
        futs.append(fut)
        items.append((i, p))
    try:
        self.send_nowait({"k": "n", "m": method, "p": {"items": items}})
    except Exception as e:  # ConnectionLost, or an unpicklable payload
        if not isinstance(e, ConnectionLost):
            e = type(e)(str(e))  # detach from the traceback for the futures
        for i, _ in items:
            self._pending.pop(i, None)
        for f in futs:
            if not f.done():
                f.set_exception(e)
    return futs


Connection.call_scatter = _call_scatter
LoopbackConnection.call_scatter = _call_scatter


async def _hello_handler(conn, payload):
    """Version handshake (ref: protobuf schema versioning role — see
    utils/schema.py). Replies with our version; the CLIENT enforces
    compatibility so old peers get a clear error, not a hang."""
    from ray_tpu.utils import schema

    return {"proto": schema.PROTOCOL_VERSION}


class RpcServer:
    """Method-dispatch server. Handlers: async def h(conn, payload) -> value."""

    def __init__(self, host: str = "127.0.0.1", port: int = 0):
        self._host = host
        self._port = port
        self._server: asyncio.base_events.Server | None = None
        self._handlers: dict[str, Callable] = {"__hello__": _hello_handler}
        self._conns: set[Connection] = set()
        self._dispatch_tasks: set[asyncio.Task] = set()
        self.on_disconnect: Callable[[Connection], None] | None = None

    def route(self, name: str):
        def deco(fn):
            self._handlers[name] = fn
            return fn

        return deco

    def add_routes(self, obj: Any, prefix: str = ""):
        """Register every ``rpc_<name>`` coroutine method of ``obj``."""
        for attr in dir(obj):
            if attr.startswith("rpc_"):
                self._handlers[prefix + attr[4:]] = getattr(obj, attr)

    async def start(self) -> tuple[str, int]:
        self._server = await asyncio.start_server(self._on_client, self._host, self._port)
        sock = self._server.sockets[0]
        self._host, self._port = sock.getsockname()[:2]
        _LOCAL_SERVERS[(self._host, self._port)] = (self, asyncio.get_running_loop())
        return self._host, self._port

    def attach_loopback(self) -> LoopbackConnection:
        """Create an in-memory client connection to this server (same loop)."""
        client = LoopbackConnection()
        server_end = LoopbackConnection()
        server_end._server = self
        client.peer = server_end
        server_end.peer = client
        self._conns.add(server_end)
        return client

    def _spawn_dispatch(self, conn, msg: dict):
        t = asyncio.get_running_loop().create_task(self._dispatch(conn, msg))
        self._dispatch_tasks.add(t)
        t.add_done_callback(self._dispatch_tasks.discard)

    @property
    def address(self) -> tuple[str, int]:
        return self._host, self._port

    async def _on_client(self, reader, writer):
        conn = Connection(reader, writer)
        self._conns.add(conn)
        try:
            while True:
                msg = await read_frame(reader)
                kind = msg.get("k")
                if kind in ("c", "n"):
                    self._spawn_dispatch(conn, msg)
                elif kind == "r":
                    fut = conn._pending.pop(msg["i"], None)
                    if fut is not None and not fut.done():
                        if msg.get("e") is not None:
                            fut.set_exception(msg["e"])
                        else:
                            fut.set_result(msg.get("v"))
                elif kind == "R":
                    _resolve_multi(conn._pending, msg["f"])
        except (ConnectionLost, ConnectionResetError):
            pass
        finally:
            self._conns.discard(conn)
            conn._fail_pending(ConnectionLost("peer disconnected"))
            if self.on_disconnect is not None:
                try:
                    self.on_disconnect(conn)
                except Exception:
                    logger.debug("on_disconnect hook failed", exc_info=True)
            try:
                writer.close()
            except OSError:
                pass

    async def _dispatch(self, conn: Connection, msg: dict):
        handler = self._handlers.get(msg["m"])
        if msg["k"] == "n":
            if handler is not None:
                try:
                    await handler(conn, msg.get("p"))
                except Exception:
                    import traceback

                    traceback.print_exc()
            return
        try:
            if handler is None:
                raise RpcError(f"no handler for {msg['m']!r}")
            value = await handler(conn, msg.get("p"))
            await conn.respond(msg["i"], value=value)
        except ConnectionLost:
            pass
        except Exception as e:
            try:
                await conn.respond(msg["i"], error=e)
            except (ConnectionLost, OSError):
                pass  # caller hung up: nobody is owed this error

    async def stop(self):
        _LOCAL_SERVERS.pop((self._host, self._port), None)
        # close live connections first: their handler coroutines sit in
        # read_frame(), and 3.12's wait_closed() waits for handlers to finish
        for conn in list(self._conns):
            if isinstance(conn, LoopbackConnection):
                conn._closed = True
                if conn.peer is not None:
                    conn.peer._fail_pending(ConnectionLost("server stopped"))
                self._conns.discard(conn)
            else:
                await conn.close()
        for t in list(self._dispatch_tasks):
            t.cancel()
        if self._dispatch_tasks:
            await asyncio.gather(*self._dispatch_tasks, return_exceptions=True)
        if self._server is not None:
            self._server.close()
            try:
                await asyncio.wait_for(self._server.wait_closed(), timeout=2)
            except asyncio.TimeoutError:
                pass


async def connect(host: str, port: int, timeout: float = 30.0,
                  handshake: bool = True) -> Connection:
    local = _LOCAL_SERVERS.get((host, port))
    if local is not None and local[1] is asyncio.get_running_loop():
        return local[0].attach_loopback()  # same process: same version
    deadline = asyncio.get_running_loop().time() + timeout
    last_err: Exception | None = None
    refused = 0
    while asyncio.get_running_loop().time() < deadline:
        try:
            reader, writer = await asyncio.open_connection(host, port)
            conn = Connection(reader, writer)
            conn._peer_key = (host, port)
            conn.start()
            if handshake and (host, port) not in _VERIFIED_PEERS:
                remaining = deadline - asyncio.get_running_loop().time()
                await _check_version(conn, max(1.0, remaining))
                _VERIFIED_PEERS.add((host, port))
                if len(_VERIFIED_PEERS) > 4096:  # port-reuse churn bound
                    _VERIFIED_PEERS.clear()
            return conn
        except (ConnectionRefusedError, OSError) as e:
            last_err = e
            # backoff (capped low: callers are usually waiting on a
            # process that binds within tens of ms) so mass reconnects
            # after a peer restart don't arrive in lockstep
            refused += 1
            await asyncio.sleep(min(0.4, 0.05 * (2 ** (refused - 1)))
                                * (0.5 + random.random()))
    raise ConnectionLost(f"could not connect to {host}:{port}: {last_err}")


async def _check_version(conn: Connection, timeout: float):
    """Enforce wire-schema compatibility (utils/schema.py) at connect time."""
    from ray_tpu.utils import schema

    try:
        reply = await conn.call("__hello__", {"proto": schema.PROTOCOL_VERSION},
                                timeout=timeout)
    except asyncio.TimeoutError:
        await conn.close()
        raise RpcError("peer did not answer the version handshake") from None
    except RpcError as e:
        if "no handler" in str(e):
            # pre-handshake peer: the handshake itself is a 1.x minor
            # addition, so an unknown-method reply means "same major,
            # older minor" — compatible by policy
            return
        await conn.close()
        raise
    peer = tuple(reply.get("proto", (0, 0))) if isinstance(reply, dict) else (0, 0)
    if not schema.compatible(peer):
        await conn.close()
        raise RpcError(
            f"incompatible wire protocol: peer speaks {peer}, "
            f"we speak {schema.PROTOCOL_VERSION}"
        )


class EventLoopThread:
    """A dedicated asyncio loop on a daemon thread; sync<->async bridge.

    The driver-side equivalent of the reference CoreWorker's io_service
    thread — all control-plane sockets live here while the user thread
    blocks in the sync API (ref: core_worker.h:166 io_service_).
    """

    def __init__(self, name: str = "rt-io"):
        self.loop = asyncio.new_event_loop()
        self._thread = threading.Thread(target=self._run, name=name, daemon=True)
        self._thread.start()

    def _run(self):
        asyncio.set_event_loop(self.loop)
        self.loop.run_forever()

    def run(self, coro, timeout: float | None = None):
        fut = asyncio.run_coroutine_threadsafe(coro, self.loop)
        return fut.result(timeout)

    def spawn(self, coro):
        return asyncio.run_coroutine_threadsafe(coro, self.loop)

    def stop(self):
        self.loop.call_soon_threadsafe(self.loop.stop)
        self._thread.join(timeout=5)


class MuxConnection:
    """Server-side connection face over the native epoll mux
    (_native/src/mux.cc). Duck-types the subset of Connection the control
    plane uses on INCOMING connections: respond/respond_multi/notify/
    send_nowait/close (server-initiated call() always dials a fresh
    client connection, never rides an accepted one)."""

    __slots__ = ("_server", "conn_id", "_pending", "_closed", "_ids",
                 "on_message")

    def __init__(self, server: "NativeRpcServer", conn_id: int):
        self._server = server
        self.conn_id = conn_id
        self._pending: dict[int, asyncio.Future] = {}
        self._ids = itertools.count(1)
        self._closed = False
        self.on_message = None

    @property
    def peername(self):
        return ("mux", self.conn_id)

    def send_nowait(self, msg: dict):
        if self._closed:
            raise ConnectionLost("connection closed")
        data = frame_bytes(msg)
        if chaos.ENABLED:
            data = _chaos_frame(msg, data)
            if data is None:
                return
            if isinstance(data, tuple):
                data = b"".join(data)  # one mux write, both frames
        st = self._server._mux_send(self.conn_id, data)
        if st != 0:
            # a conn we can no longer reply on is DEAD, not just muted:
            # close the socket so the peer observes the disconnect instead
            # of blocking forever on replies that silently stopped (-2 is
            # a >256MB write backlog — a peer that far behind is gone)
            self._closed = True
            self._server._mux_close(self.conn_id)
            raise ConnectionLost(f"mux send failed ({st})")

    async def send(self, msg: dict):
        self.send_nowait(msg)

    async def call(self, method: str, payload: Any = None,
                   timeout: float | None = None):
        i = next(self._ids)
        fut: asyncio.Future = asyncio.get_running_loop().create_future()
        self._pending[i] = fut
        await self.send({"k": "c", "i": i, "m": method, "p": payload})
        if timeout is None:
            return await fut
        return await asyncio.wait_for(fut, timeout)

    async def notify(self, method: str, payload: Any = None):
        await self.send({"k": "n", "m": method, "p": payload})

    async def respond(self, msg_id: int, value: Any = None,
                      error: Exception | None = None):
        await self.send({"k": "r", "i": msg_id, "v": value, "e": error})

    async def respond_multi(self, items: list):
        await self.send({"k": "R", "f": items})

    call_scatter = _call_scatter

    def _fail_pending(self, exc: Exception):
        self._closed = True
        exc = exc if isinstance(exc, ConnectionLost) else ConnectionLost(repr(exc))
        for fut in self._pending.values():
            if not fut.done():
                fut.set_exception(exc)
        self._pending.clear()

    async def close(self):
        if not self._closed:
            self._closed = True
            self._server._mux_close(self.conn_id)


class NativeRpcServer(RpcServer):
    """RpcServer over the native epoll mux (ref: grpc_server.h:88 — the
    completion-queue-threads role). The C++ thread owns every socket and
    frames every message; this loop wakes ONCE per burst via eventfd and
    drains the whole batch in one callback — no per-connection reader
    coroutine, no per-frame Task for the transport."""

    _RECV_BUF0 = 1 << 20

    def __init__(self, host: str = "127.0.0.1", port: int = 0):
        super().__init__(host, port)
        self._mux = None
        self._efd = -1
        self._muxconns: dict[int, MuxConnection] = {}
        self._recvbuf = None
        self._loop: asyncio.AbstractEventLoop | None = None

    async def start(self) -> tuple[str, int]:
        import ctypes
        import socket

        try:
            from ray_tpu import _native

            lib = _native.get_lib()
            # mux.cc binds with inet_addr (numeric only): resolve names
            # here — 'localhost' would otherwise parse as INADDR_NONE and
            # bind to 255.255.255.255
            host = self._host
            try:
                host = socket.gethostbyname(host)
            except OSError:
                pass  # let the native bind reject it -> asyncio fallback
            out_port = ctypes.c_uint16(0)
            out_efd = ctypes.c_int(-1)
            h = lib.rt_mux_create(host.encode(), self._port,
                                  ctypes.byref(out_port),
                                  ctypes.byref(out_efd))
            if not h:
                raise OSError(
                    f"rt_mux_create failed on {host}:{self._port}")
        except Exception:
            # degrade to the asyncio transport (identical dispatch
            # surface) instead of aborting GCS/raylet startup — a host
            # string or environment that worked under start_server must
            # keep working when the native mux can't come up
            return await super().start()
        self._lib = lib
        self._host = host
        self._mux = h
        self._efd = out_efd.value
        self._port = out_port.value
        self._recvbuf = ctypes.create_string_buffer(self._RECV_BUF0)
        self._loop = asyncio.get_running_loop()
        self._loop.add_reader(self._efd, self._drain)
        _LOCAL_SERVERS[(self._host, self._port)] = (self, self._loop)
        return self._host, self._port

    def _mux_send(self, conn_id: int, framed: bytes) -> int:
        return self._lib.rt_mux_send(self._mux, conn_id, framed, len(framed))

    def _mux_close(self, conn_id: int):
        self._lib.rt_mux_close_conn(self._mux, conn_id)

    def _drain(self):
        import ctypes
        import struct as _s

        if self._mux is None:
            return
        n = self._lib.rt_mux_recv_batch(
            self._mux,
            ctypes.cast(self._recvbuf, ctypes.POINTER(ctypes.c_uint8)),
            len(self._recvbuf))
        if n < 0:  # one record larger than the buffer: grow and retry
            self._recvbuf = ctypes.create_string_buffer(
                max(-n, len(self._recvbuf) * 2))
            return  # eventfd re-signaled; the loop calls us again
        if n == 0:
            return
        buf = self._recvbuf.raw[:n]
        off = 0
        while off + 16 <= n:
            conn_id, rtype, ln = _s.unpack_from("<QII", buf, off)
            payload = buf[off + 16: off + 16 + ln]
            off += 16 + ln
            if rtype == 1:  # connected
                conn = MuxConnection(self, conn_id)
                self._muxconns[conn_id] = conn
                self._conns.add(conn)
                continue
            conn = self._muxconns.get(conn_id)
            if conn is None:
                continue
            if rtype == 2:  # disconnected
                self._muxconns.pop(conn_id, None)
                self._conns.discard(conn)
                conn._fail_pending(ConnectionLost("peer disconnected"))
                if self.on_disconnect is not None:
                    try:
                        self.on_disconnect(conn)
                    except Exception:
                        logger.debug("on_disconnect hook failed",
                                     exc_info=True)
                self._lib.rt_mux_release(self._mux, conn_id)
                continue
            try:
                msg = pickle.loads(payload)
            except Exception:
                continue  # garbage frame: drop it, keep the connection
            kind = msg.get("k")
            if kind in ("c", "n"):
                self._spawn_dispatch(conn, msg)
            elif kind == "r":
                fut = conn._pending.pop(msg["i"], None)
                if fut is not None and not fut.done():
                    if msg.get("e") is not None:
                        fut.set_exception(msg["e"])
                    else:
                        fut.set_result(msg.get("v"))
            elif kind == "R":
                _resolve_multi(conn._pending, msg["f"])

    async def stop(self):
        if self._mux is None and self._server is not None:
            # start() degraded to the asyncio transport: its stop path
            # owns the listener socket and stream connections
            await super().stop()
            return
        _LOCAL_SERVERS.pop((self._host, self._port), None)
        if self._loop is not None and self._efd >= 0:
            try:
                self._loop.remove_reader(self._efd)
            except (OSError, ValueError, RuntimeError):
                pass  # loop already closed / fd already unregistered
        for conn in list(self._conns):
            if isinstance(conn, LoopbackConnection):
                conn._closed = True
                if conn.peer is not None:
                    conn.peer._fail_pending(ConnectionLost("server stopped"))
            elif isinstance(conn, MuxConnection):
                conn._fail_pending(ConnectionLost("server stopped"))
        self._conns.clear()
        self._muxconns.clear()
        for t in list(self._dispatch_tasks):
            t.cancel()
        if self._dispatch_tasks:
            await asyncio.gather(*self._dispatch_tasks, return_exceptions=True)
        if self._mux is not None:
            # rt_mux_stop joins the epoll thread; cheap enough to inline
            self._lib.rt_mux_stop(self._mux)
            self._mux = None


def make_server(host: str = "127.0.0.1", port: int = 0) -> RpcServer:
    """Control-plane server factory: the native mux when enabled, the
    host has cores to run its IO thread CONCURRENTLY with Python
    (native_mux_min_cpus — on a 1-core host the thread only preempts the
    interpreter), and the build succeeds; else the asyncio server
    (identical dispatch surface). RT_NATIVE_MUX_MIN_CPUS=1 forces it on."""
    import os as _os

    from ray_tpu.config import get_config

    cfg = get_config()
    if (cfg.native_mux_enabled
            and (_os.cpu_count() or 1) >= cfg.native_mux_min_cpus):
        try:
            from ray_tpu import _native

            _native.get_lib()  # force the build before committing to it
            return NativeRpcServer(host, port)
        except Exception:
            logger.debug("native mux unavailable; asyncio transport",
                         exc_info=True)
    return RpcServer(host, port)

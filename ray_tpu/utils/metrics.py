"""In-process metrics registry.

TPU-native equivalent of the reference stats layer (ref:
src/ray/stats/metric_defs.cc metric definitions, python/ray/util/metrics.py
user-facing Counter/Gauge/Histogram). Each process keeps one registry;
component code records locally (lock-free dict bumps on the hot path) and
the core client piggybacks periodic snapshots to the GCS KV
(ns="metrics", key=worker hex) on the task-event flush timer, where the
state API aggregates them cluster-wide.

Snapshot format: each metric exports structured ``samples`` —
``{"tags": {...}, "value": v}`` (counters/gauges) or
``{"tags": {...}, "counts": [...], "sum": s}`` (histograms) — so
``state.prometheus_metrics()`` can emit real labels without reparsing
stringified tag tuples, and the GCS rollup plane
(``core/metrics_store.py``) can window counter deltas and merge
histogram buckets across sources. Counters are monotonic cumulatives on
the wire; rates live GCS-side (``state.metric_window``), never here.
"""
from __future__ import annotations

import threading
import time
from typing import Sequence


class Metric:
    def __init__(self, name: str, description: str = "", tag_keys: Sequence[str] = ()):
        self.name = name
        self.description = description
        self.tag_keys = tuple(tag_keys)
        _registry.register(self)

    def _key(self, tags: dict | None) -> tuple:
        if not tags:
            return ()
        return tuple(sorted(tags.items()))


class Counter(Metric):
    def __init__(self, name, description="", tag_keys=()):
        self._values: dict[tuple, float] = {}
        super().__init__(name, description, tag_keys)

    def inc(self, value: float = 1.0, tags: dict | None = None):
        k = self._key(tags)
        self._values[k] = self._values.get(k, 0.0) + value

    def snapshot(self):
        return {"type": "counter",
                "samples": [{"tags": dict(k), "value": v}
                            for k, v in self._values.items()]}


class Gauge(Metric):
    def __init__(self, name, description="", tag_keys=()):
        self._values: dict[tuple, float] = {}
        super().__init__(name, description, tag_keys)

    def set(self, value: float, tags: dict | None = None):
        self._values[self._key(tags)] = value

    def snapshot(self):
        return {"type": "gauge",
                "samples": [{"tags": dict(k), "value": v}
                            for k, v in self._values.items()]}


class Histogram(Metric):
    """Fixed-boundary histogram (ref: metrics.py Histogram)."""

    def __init__(self, name, description="", boundaries: Sequence[float] = (), tag_keys=()):
        self.boundaries = tuple(boundaries) or (
            0.001, 0.01, 0.1, 1.0, 10.0, 100.0, 1000.0
        )
        self._counts: dict[tuple, list[int]] = {}
        self._sums: dict[tuple, float] = {}
        super().__init__(name, description, tag_keys)

    def observe(self, value: float, tags: dict | None = None):
        k = self._key(tags)
        counts = self._counts.setdefault(k, [0] * (len(self.boundaries) + 1))
        i = 0
        while i < len(self.boundaries) and value > self.boundaries[i]:
            i += 1
        counts[i] += 1
        self._sums[k] = self._sums.get(k, 0.0) + value

    def observe_many(self, values, tags: dict | None = None):
        """Bulk feed (flush-time batches, e.g. the flight recorder's
        sampled stage latencies): one key lookup + bisect per value
        instead of a linear boundary scan per observe."""
        from bisect import bisect_left

        k = self._key(tags)
        counts = self._counts.setdefault(k, [0] * (len(self.boundaries) + 1))
        b = self.boundaries
        total = 0.0
        for v in values:
            counts[bisect_left(b, v)] += 1
            total += v
        self._sums[k] = self._sums.get(k, 0.0) + total

    def snapshot(self):
        return {
            "type": "histogram",
            "boundaries": list(self.boundaries),
            "samples": [{"tags": dict(k), "counts": list(c),
                         "sum": self._sums.get(k, 0.0)}
                        for k, c in self._counts.items()],
        }


class _Registry:
    def __init__(self):
        self._metrics: dict[str, Metric] = {}
        self._lock = threading.Lock()

    def register(self, metric: Metric):
        with self._lock:
            self._metrics[metric.name] = metric

    def get(self, name: str) -> Metric | None:
        return self._metrics.get(name)

    def snapshot(self) -> dict:
        with self._lock:
            return {
                "ts": time.time(),
                "metrics": {name: m.snapshot() for name, m in self._metrics.items()},
            }


_registry = _Registry()


def registry() -> _Registry:
    return _registry


# --- core runtime metrics (ref: metric_defs.cc tasks/objects families) ------
tasks_submitted = Counter("rt_tasks_submitted", "tasks submitted by this process")
tasks_finished = Counter("rt_tasks_finished", "task replies applied, by outcome",
                         tag_keys=("outcome",))
actor_calls = Counter("rt_actor_calls", "actor method calls submitted")
objects_put = Counter("rt_objects_put", "objects created via put")
object_bytes_put = Counter("rt_object_bytes_put", "bytes written via put")
objects_spilled = Counter("rt_objects_spilled", "objects spilled to disk")
objects_restored = Counter("rt_objects_restored", "spilled objects restored")
# memory tiering (PR 18): byte-granular spill/restore traffic plus the
# prefix cache's tier-1 effectiveness (set from cache stats)
spill_bytes_total = Counter("rt_spill_bytes_total",
                            "bytes written to tier-1 spill files")
restore_bytes_total = Counter("rt_restore_bytes_total",
                              "bytes restored from tier-1 into shm arenas")
tier1_hit_rate = Gauge("rt_tier1_hit_rate",
                       "fraction of prefix-cache hits served from tier-1")
# arena watermarks (rollup plane): live/peak/capacity bytes per arena the
# tiering registry knows (core/tiering.py stats providers — prefix cache,
# shard plane, KV staging; the raylet hand-rolls the object_store cells
# into its own snapshot). Set at flush time from sample_arenas().
arena_bytes = Gauge("rt_arena_bytes", "live bytes in a tiering arena",
                    tag_keys=("arena",))
arena_peak_bytes = Gauge("rt_arena_peak_bytes",
                         "high-water bytes a tiering arena has held",
                         tag_keys=("arena",))
arena_capacity_bytes = Gauge("rt_arena_capacity_bytes",
                             "configured capacity of a tiering arena",
                             tag_keys=("arena",))
task_exec_seconds = Histogram("rt_task_exec_seconds", "worker-side task execution time")

# --- flight-recorder families (PR 4; see utils/recorder.py) -----------------
# Per-stage fast-lane latency. Fed at flush time from the recorder's
# retained sample window (bounded batch per flush — Dapper-style
# sampling under load), NOT per task: the hot path pays one ring store.
task_stage_seconds = Histogram(
    "rt_task_stage_seconds",
    "fast-lane per-stage task latency (sampled by the flight recorder)",
    boundaries=(1e-6, 1e-5, 1e-4, 1e-3, 1e-2, 0.1, 1.0, 10.0),
    tag_keys=("stage",))
task_stage_us = Gauge(
    "rt_task_stage_us",
    "fast-lane per-stage latency percentiles over the recorder window (µs)",
    tag_keys=("stage", "q"))
recorder_samples = Gauge(
    "rt_recorder_samples", "per-task latency samples recorded (lifetime)")
# --- LLM decode-plane signals (llm/disagg/telemetry.py) ---------------------
# Published per decode-worker process; the disagg scheduler and serve
# router admit on tokens-in-flight + page headroom instead of request
# counts (cross-replica decode batching), and the spec-decode gauges are
# the same numbers the bench's A/B arm reports.
llm_decode_tokens_in_flight = Gauge(
    "rt_llm_decode_tokens_in_flight",
    "decode tokens still owed by this process's LLM engine")
llm_spec_accept_rate = Gauge(
    "rt_llm_spec_accept_rate",
    "speculative-decode draft acceptance rate (lifetime ratio)")
llm_tokens_per_step = Gauge(
    "rt_llm_tokens_per_step",
    "tokens emitted per fused decode step (recent-block mean)")
# monotonic spec-decode cumulatives: the rollup plane's derived
# llm_spec_accept_rate series is accepted/proposed per window slot —
# restart-safe and windowable, unlike the lifetime-ratio gauge above
llm_spec_proposed_total = Counter(
    "rt_llm_spec_proposed_total",
    "draft tokens proposed to the fused spec-decode verify")
llm_spec_accepted_total = Counter(
    "rt_llm_spec_accepted_total",
    "draft tokens the fused spec-decode verify accepted")
# serve SLO cumulatives: serve_slo_breach_fraction = breaches/requests
# per window slot (boundary-free, unlike bucketing latencies at the SLO)
serve_requests_total = Counter(
    "rt_serve_requests_total", "serve requests completed by a replica",
    tag_keys=("key",))
serve_slo_breaches_total = Counter(
    "rt_serve_slo_breaches_total",
    "serve requests that finished over their deployment's latency SLO",
    tag_keys=("key",))
# NOTE: rt_request_critical_path_us (the GCS trace assembler's per-stage
# request-latency histogram) is deliberately NOT declared here: the GCS
# hand-rolls its cells (core/gcs.py _trace_metrics_tick) because an
# in-process GCS shares this process-global registry with the driver,
# and publishing the shared snapshot under a second kv key would
# double-count every driver metric.
# Native shm transport counters (ring.cc RingStats / store.cc StoreStats),
# summed over live lanes and set at flush time.
fastpath_ring = Gauge(
    "rt_fastpath_ring",
    "shm task-ring counters summed over live lanes (ring.cc RingStats)",
    tag_keys=("which", "stat"))
object_store_stat = Gauge(
    "rt_object_store",
    "shm arena counters (store.cc StoreStats)",
    tag_keys=("stat",))

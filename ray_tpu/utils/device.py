"""JAX platform/device configuration helpers.

Centralizes backend selection so tests and workers can force the virtual
CPU mesh (``RT_FORCE_CPU_DEVICES=N``) before any jax backend initialization.
The axon TPU plugin pins ``jax_platforms`` regardless of the JAX_PLATFORMS
env var, so forcing must go through jax.config before first device use.
"""

from __future__ import annotations

import os

_configured = False


def configure_jax() -> None:
    """Apply RT_FORCE_CPU_DEVICES if set. Call before any jax backend use."""
    global _configured
    if _configured:
        return
    _configured = True
    n = int(os.environ.get("RT_FORCE_CPU_DEVICES", "0") or 0)
    if n > 0:
        flags = os.environ.get("XLA_FLAGS", "")
        if "xla_force_host_platform_device_count" not in flags:
            os.environ["XLA_FLAGS"] = (
                flags + f" --xla_force_host_platform_device_count={n}"
            ).strip()
        import jax

        try:
            jax.config.update("jax_platforms", "cpu")
        except (AttributeError, ValueError):
            pass  # older jax without the knob: XLA_FLAGS above suffices


def devices():
    configure_jax()
    import jax

    return jax.devices()


def local_device_count() -> int:
    return len(devices())


def is_tpu() -> bool:
    configure_jax()
    import jax

    try:
        return jax.devices()[0].platform in ("tpu", "axon")
    except Exception:
        return False

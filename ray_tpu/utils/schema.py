"""Versioned wire schema for the control plane.

The role of the reference's protobuf schema tree (ref: src/ray/protobuf/
gcs_service.proto, node_manager.proto, core_worker.proto): one place that
names every RPC service, method, and payload field, with the version each
was introduced in. Peers exchange PROTOCOL_VERSION at connect time
(rpc.connect ``__hello__`` handshake) and refuse major mismatches; minor
additions are backwards-compatible (unknown payload keys are ignored by
every handler, the dict-payload equivalent of proto field skipping).

tests/test_wire_schema.py machine-checks this catalog against the live
``rpc_*`` handlers, so adding a method without cataloging it fails CI —
the same forcing function a .proto file provides.
"""

from __future__ import annotations

# (major, minor): bump MAJOR for incompatible changes (renamed/removed
# methods, changed field meaning), MINOR for additions.
#
# 1.7: flight-recorder telemetry on the fastpath shm records (not RPC
# methods, but versioned here because both sides must agree): task
# records may carry an 8-byte submit stamp (prefixes "Q"/"R" beside the
# unstamped "P"/"S"), and reply records may carry a 16-byte stage stamp
# (status flag 0x100) — see core/fastpath.py pack_task/pack_reply.
#
# 1.8: actor fast lane v2. Actor-lane task records use the "A"/"C"
# prefixes with a <u32 seq, u64 t_submit_ns> header (per-lane call
# sequence number); reply records may carry the echoed seq (status flag
# 0x200, 4 bytes after the optional stamp) so completions can stream
# back OUT of submission order (async actors) while ring order stays the
# per-caller FIFO dispatch invariant. attach_fast_ring's actor reply is
# now a dict carrying the actor's init-time method eligibility table —
# see core/fastpath.py pack_actor_task/pack_reply.
#
# 2.0: cross-node fast lane (MAJOR: OK_SHM payloads and record argument
# slots changed meaning). Node tunnels (core/tunnel.py) carry the shm
# rings' packed records between node pairs: raylet tunnel_bind /
# tunnel_frame / tunnel_detach + worker tunnel_attach / tunnel_records /
# tunnel_detach route coalesced record frames driver <-> raylet <->
# worker. OK_SHM reply payloads may carry <Q size><16s node> (the
# sealing node's id — the record IS the location registration,
# pack_shm_desc); record arguments may be TunnelArgRef descriptors
# ((oid, owner, node, nbytes) — oversized values adopt via the new
# batched pull_objects). Also batched control: raylet lease_workers,
# prepare_bundles, commit_bundles. The record prefix/flag byte catalog
# below (RECORD_PREFIXES / RECORD_FLAGS) is machine-checked against
# _native/src/rt_wire.h so a shipped-but-uncataloged wire entry fails
# tier-1 (PRs 10/11 both shipped one).
# 2.2: metric rollup queries. The GCS folds every ns="metrics" snapshot
# put into ring-buffered 1s/10s/60s windows (core/metrics_store.py) and
# serves them back: metric_window (rate/quantile series over trailing
# secs), metric_names (everything the rollup plane has seen + derived
# ratio series), metric_export (trailing counter rates, the prometheus
# :rate family feed). No record-plane changes.
# 2.1: wire-level trace context (Dapper-style — utils/tracing.py).
# "Q"/"R"/"A"/"C" records may carry a 25-byte trace leg
# (<16s trace_id><8s span_id><u8 sampled>) behind their header, flagged
# by TRACE_CTX_BIT (bit 63 of the u64 t_submit field — free for ~292
# years of CLOCK_MONOTONIC); seq-echoed replies may echo the leg
# (status flag 0x400, after the stamp/seq legs), so the driver's
# reply-apply stamps the wire-level call span for untracked serve
# fast-lane calls without a lookup. Unsampled records are byte-identical
# to 2.0 ones. Also: GCS get_trace / list_traces (the trace assembler),
# get_task_events limit/offset/span_only pagination.
# 2.3: streaming plane. Stream-called generator methods ride the actor
# lanes as ordinary "A"/"C" records whose method key uses the "gm:"
# marker (vs "am:"); the worker pumps flush one "G" chunk record per
# yielded item (core/fastpath.py pack_chunk — the "A" header shape with
# the seq slot carrying the per-stream chunk index, same TRACE_BIT trace
# leg) with body status CHUNK (inline packed item) or CHUNK_SHM
# (oversized item sealed under return index chunk_seq + 1, payload =
# shm size/desc like OK_SHM), then ONE ordinary terminal reply (OK +
# <u32 nchunks> / ERR) on the lane's seq machinery. Reply STATUS CODES
# are now cataloged (RECORD_STATUS below, mirrored by rt_wire.h
# kReplyStatus*) beside the prefix/flag bytes. Also: worker
# stream_abandon (driver stops an open stream's pump mid-flight —
# client disconnect), serve-level mid-stream cancellation rides the
# existing cancel_request actor method.
PROTOCOL_VERSION = (2, 3)

# ------------------------------------------------------ fastpath records
# Every record prefix byte and reply-status flag the shm rings / node
# tunnels ship (core/fastpath.py). rt_wire.h mirrors this catalog for
# native peers; tests/test_wire_schema.py asserts byte-for-byte parity
# in BOTH directions, so adding a prefix or flag on either side without
# cataloging it here is a tier-1 failure.
RECORD_PREFIXES: dict[str, dict] = {
    "P": {"since": (1, 3), "doc": "task record, C-pickled body, no stamp"},
    "S": {"since": (1, 3), "doc": "task record, serialization.pack body"},
    "Q": {"since": (1, 7), "doc": "task record, C-pickled, u64 submit stamp"},
    "R": {"since": (1, 7), "doc": "task record, packed, u64 submit stamp"},
    "A": {"since": (1, 8), "doc": "actor record, C-pickled, <u32 seq, u64 t>"},
    "C": {"since": (1, 8), "doc": "actor record, packed, <u32 seq, u64 t>"},
    "G": {"since": (2, 3), "doc": "stream chunk, 'A' header shape with the "
                                  "seq slot = per-stream chunk index, body "
                                  "<16s task_id><u32 status> + payload"},
}
# Reply status CODES (low bits of the reply/chunk status word, below the
# flag bits): cataloged since 2.3 alongside the flags — rt_wire.h mirrors
# them as kReplyStatus* and tests/test_wire_schema.py asserts parity in
# both directions like the prefixes/flags.
RECORD_STATUS: dict[str, dict] = {
    "OK": {"value": 0, "since": (1, 3), "doc": "payload = packed value"},
    "OK_SHM": {"value": 1, "since": (1, 3),
               "doc": "result sealed in the node arena; payload = "
                      "shm size (1.7) / <Q size><16s node> desc (2.0)"},
    "ERR": {"value": 2, "since": (1, 3), "doc": "payload = pickled error"},
    "NEED_SLOW": {"value": 3, "since": (1, 3),
                  "doc": "declined without executing: RPC path owns it"},
    "CHUNK": {"value": 4, "since": (2, 3),
              "doc": "'G' records only: one inline packed stream item"},
    "CHUNK_SHM": {"value": 5, "since": (2, 3),
                  "doc": "'G' records only: oversized item sealed under "
                         "return index chunk_seq + 1; payload = shm "
                         "size/desc"},
}
RECORD_FLAGS: dict[str, dict] = {
    "STAMPED": {"value": 0x100, "since": (1, 7),
                "doc": "reply carries a 16-byte worker stage stamp"},
    "SEQED": {"value": 0x200, "since": (1, 8),
              "doc": "reply echoes the submit record's u32 seq"},
    "TRACED": {"value": 0x400, "since": (2, 1),
               "doc": "reply echoes the submit record's 25-byte trace "
                      "leg (after the stamp/seq legs)"},
}
# Record-side trace flag (2.1): bit 63 of the u64 t_submit field of
# "Q"/"R"/"A"/"C" records — set = a 25-byte trace leg follows the
# record header. Mirrored by rt_wire.h kRecordTraceCtxBit/kTraceCtxLen
# and asserted against core/fastpath.py by tests/test_wire_schema.py.
TRACE_CTX_BIT = 1 << 63
TRACE_CTX_LEN = 25

# service -> method -> {"since": (major, minor), "fields": {...}}
# field values document type + meaning; "->" entries are the reply shape.
CATALOG: dict[str, dict[str, dict]] = {
    # ---------------------------------------------------------------- GCS
    # (ref: gcs_service.proto services)
    "gcs": {
        "register_node": {"since": (1, 0), "fields": {
            "node_id": "hex", "address": "(host, port)", "resources": "dict",
            "labels": "dict", "store_name": "str", "->": "cluster view"}},
        "register_job": {"since": (1, 0), "fields": {"job_id": "hex"}},
        "register_actor": {"since": (1, 0), "fields": {
            "actor_id": "ActorID", "cls_blob": "bytes", "opts": "dict"}},
        "get_actor": {"since": (1, 0), "fields": {
            "actor_id": "ActorID | None", "name": "str | None",
            "->": "actor info dict"}},
        "kill_actor": {"since": (1, 0), "fields": {
            "actor_id": "ActorID", "no_restart": "bool"}},
        "report_actor_death": {"since": (1, 0), "fields": {
            "actor_id": "ActorID", "reason": "str"}},
        "list_actors": {"since": (1, 0), "fields": {"->": "[actor info]"}},
        "heartbeat": {"since": (1, 0), "fields": {
            "node_id": "hex", "resources_available": "dict", "load": "dict",
            "version": "int — monotone view version (since 1.1)",
            "queued_leases": "int demand signal"}},
        "get_cluster": {"since": (1, 0), "fields": {"->": "[node info]"}},
        "drain_node": {"since": (1, 0), "fields": {"node_id": "hex"}},
        "subscribe": {"since": (1, 0), "fields": {"channels": "[str]"}},
        "publish": {"since": (1, 9), "fields": {
            "channel": "str — client-originated pubsub fan-out (the serve "
                       "controller's serve_autoscale decisions)",
            "message": "any"}},
        "kv_put": {"since": (1, 0), "fields": {
            "ns": "str", "key": "str", "value": "bytes", "overwrite": "bool"}},
        "kv_get": {"since": (1, 0), "fields": {"ns": "str", "key": "str"}},
        "kv_multi_get": {"since": (1, 0), "fields": {"ns": "str", "keys": "[str]"}},
        "kv_del": {"since": (1, 0), "fields": {"ns": "str", "key": "str"}},
        "kv_keys": {"since": (1, 0), "fields": {"ns": "str", "prefix": "str"}},
        "kv_exists": {"since": (1, 0), "fields": {"ns": "str", "key": "str"}},
        "create_placement_group": {"since": (1, 0), "fields": {
            "bundles": "[dict]", "strategy": "PACK|SPREAD|STRICT_*"}},
        "remove_placement_group": {"since": (1, 0), "fields": {"pg_id": "PGID"}},
        "get_placement_group": {"since": (1, 0), "fields": {"pg_id": "PGID"}},
        "list_placement_groups": {"since": (1, 0), "fields": {}},
        "report_task_events": {"since": (1, 0), "fields": {"events": "[dict]"}},
        "get_task_events": {"since": (1, 0), "fields": {
            "job_id": "hex | None", "limit": "int",
            "offset": "int (since (2, 1)) — newest-last pagination "
                      "window over the bounded event ring",
            "span_only": "bool (since (2, 1)) — only state='SPAN' rows "
                         "(state.list_spans pagination)"}},
        "get_trace": {"since": (2, 1), "fields": {
            "trace_id": "hex — one assembled trace from the bounded "
                        "trace table (span rows folded per trace_id on "
                        "report_task_events ingest)",
            "->": "{trace_id, spans: [span dict], start_ts, end_ts, "
                  "critical_path: TraceCriticalPath.compute()} | None"}},
        "list_traces": {"since": (2, 1), "fields": {
            "limit": "int", "offset": "int — newest first",
            "->": "[{trace_id, root_name, start_ts, dur_ms, n_spans, "
                  "procs, sealed}] — slow-trace retention keeps the p99 "
                  "outliers past the table cap"}},
        "metric_window": {"since": (2, 2), "fields": {
            "name": "metric or derived-ratio name (rt_* / "
                    "llm_spec_accept_rate / serve_slo_breach_fraction)",
            "secs": "trailing window length; picks the finest rollup "
                    "resolution (1s/10s/60s) whose retention covers it",
            "tags": "dict | None — exact tag-cell filter (default: "
                    "aggregate across cells)",
            "->": "{name, type, res, points: [{ts, ...}]} — counter "
                  "points carry value/rate, histograms count/sum/rate/"
                  "p50/p90/p99, ratios value/num/den (RollupStore.window)"}},
        "metric_names": {"since": (2, 2), "fields": {
            "->": "[{name, type}] — every metric the rollup plane has "
                  "seen plus its derived ratio series"}},
        "metric_export": {"since": (2, 2), "fields": {
            "secs": "trailing rate window (default 10)",
            "->": "{name: {type, samples: [{tags, rate}]}} — the "
                  "prometheus :rate<secs>s family feed"}},
    },
    # -------------------------------------------------------------- raylet
    # (ref: node_manager.proto NodeManagerService)
    "raylet": {
        "register_client": {"since": (1, 0), "fields": {
            "worker_id": "hex", "address": "(host, port)"}},
        "lease_worker": {"since": (1, 0), "fields": {
            "resources": "dict", "pg_id": "PGID | None", "bundle_index": "int",
            "owner_bound": "bool", "no_spill": "bool", "for_actor": "ActorID",
            "language": "python|cpp (since 1.1)",
            "strategy": "scheduling-strategy wire dict: {type: spread | "
                        "node_affinity | node_label, ...} (since 1.3)"}},
        "return_lease": {"since": (1, 0), "fields": {
            "lease_id": "int", "kill": "bool"}},
        "report_demand": {"since": (1, 3), "fields": {
            "count": "int — driver-side queued tasks no live lease will "
                     "absorb (autoscaler demand signal)"}},
        "heap_profile_worker": {"since": (1, 4), "fields": {
            "worker_id": "hex prefix — proxies a heap_profile RPC",
            "action": "start | snapshot | stop",
            "top": "snapshot: top-N allocation sites"}},
        "cpu_profile_worker": {"since": (1, 5), "fields": {
            "worker_id": "hex prefix — proxies a cpu_profile RPC",
            "duration_s": "sampling window (capped 30s)",
            "interval_s": "sample period"}},
        "dump_worker_stack": {"since": (1, 3), "fields": {
            "worker_id": "hex prefix — proxies a dump_stack RPC to the "
                         "matching worker (live stack profiling)"}},
        "worker_ready": {"since": (1, 0), "fields": {
            "worker_id": "hex", "address": "(host, port)", "pid": "int",
            "language": "str (since 1.1)"}},
        "get_lease_env": {"since": (1, 0), "fields": {"worker_id": "hex"}},
        "kill_worker": {"since": (1, 0), "fields": {"worker_id": "hex"}},
        "prepare_bundle": {"since": (1, 0), "fields": {
            "pg_id": "PGID", "bundle_index": "int", "resources": "dict"}},
        "commit_bundle": {"since": (1, 0), "fields": {
            "pg_id": "PGID", "bundle_index": "int"}},
        "return_bundle": {"since": (1, 0), "fields": {
            "pg_id": "PGID", "bundle_index": "int"}},
        "list_bundles": {"since": (1, 9), "fields": {
            "->": "[{pg_id, bundle_index, resources, committed, "
                  "prepared_at}] — the PG-reservation audit surface "
                  "(shipped in 1.8's PG-FT work, cataloged late)"}},
        "lease_workers": {"since": (2, 0), "fields": {
            "requests": "[lease_worker payloads] — batched grants in ONE "
                        "ledger pass; never parks (busy replies retry "
                        "caller-side)",
            "->": "[lease_worker replies], positional"}},
        "prepare_bundles": {"since": (2, 0), "fields": {
            "pg_id": "PGID", "bundles": "[(index, resources)] — one "
                                        "batched 2PC phase-1 ledger pass",
            "->": "[{ok}] positional"}},
        "commit_bundles": {"since": (2, 0), "fields": {
            "pg_id": "PGID", "indices": "[int] — batched 2PC phase 2",
            "->": "[{ok}] positional"}},
        "tunnel_bind": {"since": (2, 0), "fields": {
            "kind": "actor | task",
            "worker_id": "hex (task lanes)",
            "actor_id": "hex (actor lanes; the raylet resolves the "
                        "hosting worker)",
            "->": "{ok, lane, methods?} — lane id multiplexing this "
                  "binding over the node tunnel (core/tunnel.py)"}},
        "tunnel_frame": {"since": (2, 0), "fields": {
            "frames": "[(lane, framed record bytes)] — coalesced "
                      "ring-format records (notify, both directions: "
                      "driver->raylet submits, raylet->driver replies)"}},
        "tunnel_detach": {"since": (2, 0), "fields": {
            "lanes": "[lane ids] closed by the driver (notify)"}},
        "pull_objects": {"since": (2, 0), "fields": {
            "objects": "[{object_id, holders_hint}] — batched pull: one "
                       "round trip per arg/KV-manifest set, ONE GCS "
                       "kv_multi_get for the unhinted miss-set",
            "->": "{oid hex: bool}"}},
        "pull_object": {"since": (1, 0), "fields": {
            "object_id": "bytes", "owner_address": "(host, port)",
            "holders_hint": "[node_id bytes] optional (since (1, 6)): "
                            "location-cache hint tried before the GCS "
                            "directory; stale hints fall back in-call"}},
        "fetch_object": {"since": (1, 0), "fields": {"object_id": "bytes"}},
        "fetch_object_meta": {"since": (1, 0), "fields": {"object_id": "bytes"}},
        "fetch_object_chunk": {"since": (1, 0), "fields": {
            "object_id": "bytes", "offset": "int", "length": "int"}},
        "fetch_object_done": {"since": (1, 0), "fields": {"object_id": "bytes"}},
        "delete_object": {"since": (1, 0), "fields": {"object_id": "bytes"}},
        "get_log": {"since": (1, 1), "fields": {
            "worker_id": "hex (prefix ok)", "stream": "out|err",
            "tail": "int bytes", "->": "str | None"}},
        "register_spill_provider": {"since": (2, 2), "fields": {
            "address": "(host, port) — a local client process that can "
                       "serve cold arena-owner spill candidates "
                       "(core/tiering.py registry; shipped in the 2.2-era "
                       "memory-tiering work, cataloged late)"}},
        "spill_objects": {"since": (2, 2), "fields": {
            "object_ids": "[bytes] — owner-initiated spill of specific "
                          "sealed objects (prefix-cache spill-not-drop "
                          "eviction)",
            "->": "{oid hex: {ok, path}}"}},
        "spill_now": {"since": (1, 2), "fields": {
            "need": "int bytes of headroom wanted — spill pass runs to "
                    "low-water (ref: local_object_manager.h:42)"}},
        # cross-node DAG channels (the RegisterMutableObjectReader role,
        # ref: core_worker.proto:577)
        "channel_create": {"since": (1, 2), "fields": {
            "chan_id": "bytes", "size": "int", "num_readers": "int"}},
        "channel_push": {"since": (1, 2), "fields": {
            "chan_id": "bytes", "payload": "packed bytes (one version)"}},
        "channel_register_remote": {"since": (1, 2), "fields": {
            "chan_id": "bytes", "readers": "[(host, port)] mirror raylets"}},
        "channel_close": {"since": (1, 2), "fields": {"chan_id": "bytes"}},
    },
    # ------------------------------------------------- owner (CoreClient)
    # (ref: core_worker.proto owner-side RPCs)
    "owner": {
        "get_object": {"since": (1, 0), "fields": {"object_id": "bytes"}},
        "probe_object": {"since": (1, 0), "fields": {"object_id": "bytes"}},
        "wait_object": {"since": (1, 0), "fields": {"object_id": "bytes"}},
        "borrow_object": {"since": (1, 0), "fields": {
            "object_id": "bytes", "borrower": "hex"}},
        "unborrow_object": {"since": (1, 0), "fields": {
            "object_id": "bytes", "borrower": "hex"}},
        "recover_object": {"since": (1, 0), "fields": {"object_id": "bytes"}},
        "fast_result": {"since": (1, 6), "fields": {
            "records": "[reply record bytes] — completion records the "
                       "worker spilled over RPC when the result ring "
                       "stayed full (see core/fastpath.py)"}},
        "generator_item": {"since": (1, 0), "fields": {
            "task_id": "TaskID", "index": "int", "item": "packed | None",
            "done": "bool"}},
        "arena_spill_candidates": {"since": (2, 2), "fields": {
            "need": "int bytes of headroom wanted",
            "cold_after_s": "float — age gate for cold candidates",
            "->": "[(oid bytes, nbytes)] cold REFERENCED objects the "
                  "registered arena owners (core/tiering.py) may trade "
                  "to tier-1 (cataloged late, 2.2-era tiering)"}},
        "arena_spilled": {"since": (2, 2), "fields": {
            "spilled": "[(oid bytes, path, offset)] — owners stamp their "
                       "manifest entries' (tier, path) legs"}},
    },
    # ------------------------------------------------------------- worker
    # (ref: core_worker.proto PushTask + worker-side control)
    "worker": {
        "push_task": {"since": (1, 0), "fields": {"spec": "task spec dict"}},
        "push_actor_task": {"since": (1, 0), "fields": {
            "spec": "actor task spec", "seq": "int"}},
        "create_actor": {"since": (1, 0), "fields": {
            "actor_id": "ActorID", "cls_blob": "bytes", "args": "[arg]",
            "opts": "dict"}},
        "cancel_if_current": {"since": (1, 1), "fields": {"task_id": "TaskID"}},
        "push_task_multi": {"since": (1, 2), "fields": {
            "items": "[(corr_id, {spec})] — scatter push; one reply frame "
                     "per item as each task finishes"}},
        "push_actor_task_multi": {"since": (1, 2), "fields": {
            "items": "[(corr_id, {spec})] — scatter push of actor calls"}},
        "exit_worker": {"since": (1, 0), "fields": {}},
        "ping": {"since": (1, 0), "fields": {}},
        "start_dag_loop": {"since": (1, 0), "fields": {"schedule": "dict"}},
        "attach_fast_ring": {"since": (1, 3), "fields": {
            "name": "str — shm name of the task RingPair this worker "
                    "should pump (see core/fastpath.py)",
            "kind": "'actor' for actor-call rings (since 1.3)",
            "owner": "(host, port) optional (since (1, 6)): driver server "
                     "address — the result-ring spill target",
            "->": "bool, or for actor rings since (1, 8) "
                  "{ok: bool, methods: {name: (sync|async|gen, group)}} — "
                  "the actor's init-time method eligibility table; the "
                  "driver routes gen/unknown methods to the RPC path per "
                  "call without a ring round trip"}},
        "tunnel_attach": {"since": (2, 0), "fields": {
            "lane": "int — raylet-assigned tunnel lane id",
            "kind": "actor | task",
            "->": "{ok, methods?} — actor lanes ship the method "
                  "eligibility table like attach_fast_ring"}},
        "tunnel_records": {"since": (2, 0), "fields": {
            "frames": "[(lane, framed record bytes)] — submit records "
                      "off the node tunnel (notify); replies return as "
                      "tunnel_replies pushes on the same connection"}},
        "tunnel_detach": {"since": (2, 0), "fields": {
            "lanes": "[lane ids] to drop (notify)"}},
        "stream_abandon": {"since": (2, 3), "fields": {
            "task_ids": "[TaskID bytes] — open stream calls whose driver-"
                        "side consumer went away (client disconnect / "
                        "stream aclose): the pump stops flushing chunks "
                        "and closes the user generator (GeneratorExit "
                        "surfaces in its finally) instead of streaming "
                        "to nobody (notify, best-effort)"}},
        "dump_stack": {"since": (1, 3), "fields": {}},
        "heap_profile": {"since": (1, 4), "fields": {
            "action": "start | snapshot | stop (tracemalloc control)",
            "top": "snapshot: top-N allocation sites",
            "nframes": "start: traceback depth"}},
        "cpu_profile": {"since": (1, 5), "fields": {
            "duration_s": "sampling window (capped 30s)",
            "interval_s": "sample period — folded stacks returned"}},
    },
}


def compatible(peer: tuple[int, int]) -> bool:
    """Same major = compatible; minor additions are tolerated both ways."""
    return peer[0] == PROTOCOL_VERSION[0]


def methods(service: str) -> set[str]:
    return set(CATALOG[service])

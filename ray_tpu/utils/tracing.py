"""Distributed request tracing: sampled span context as a wire citizen.

TPU-native counterpart of the reference tracing layer (ref:
python/ray/util/tracing/tracing_helper.py:36-60 — there OTel span context
is injected into task specs by decorator wrappers and child spans open
around execution), grown the Dapper way (Sigelman et al., 2010): the
context ``(trace_id_128, span_id_64, sampled)`` rides the wire ITSELF —
packed fast-lane records and node-tunnel frames carry an optional
25-byte trace leg (core/fastpath.py, flag ``TRACED``) — so causality is
cheap enough to leave on in production. Spans use OTel-shaped ids
(128-bit trace, 64-bit span), ride the task-event pipeline into the GCS
trace assembler (``state.get_trace`` / ``state.list_traces``) and the
chrome timeline. If the ``opentelemetry`` API is installed and
configured, spans are mirrored onto it as well.

Enable with ``Config.tracing_enabled`` (env ``RT_TRACING_ENABLED=1``).
Sampling is HEAD-BASED (``Config.trace_sample_rate``): the decision is
made once where a trace starts (the serve router's root, a driver
``.remote()`` with no active context) and carried in the wire leg;
an unsampled request pays one contextvar read and one branch — the
chaos-gate cost model — and ships NO trace bytes.

Propagation model: a contextvar holds the active (trace_id, span_id).
Submitting a task captures it into the spec (``trace_ctx``) or the
packed record's trace leg; executing a task opens a child span and
activates it for the duration of the user function, so nested
``.remote()`` calls chain parent -> child across any number of
processes and transports (shm ring, node tunnel, RPC).

Span ids come from a per-process random prefix + counter — one urandom
syscall per process, not per span (the per-call ``os.urandom`` measured
~288µs under the syscall-intercepting sandbox, the same hot-path cost
PR 8 and PR 11 evicted from task and promise ids).
"""

from __future__ import annotations

import contextvars
import itertools
import os
import struct
import threading as _threading
import time

from ray_tpu.config import get_config

_ctx: contextvars.ContextVar[tuple[str, str] | None] = contextvars.ContextVar(
    "rt_trace_ctx", default=None)

# Span clock: durations come from perf_counter_ns (monotonic, ns
# resolution — time.time() collapses sub-ms spans to zero on coarse
# clocks and a wall-clock step mid-span would yield a NEGATIVE
# duration); one wall anchor captured at import reconstructs absolute
# start/end times for the timeline.
_ANCHOR_PERF_NS = time.perf_counter_ns()
_ANCHOR_WALL_NS = time.time_ns()


def _wall_s(t_perf_ns: int) -> float:
    return (_ANCHOR_WALL_NS + (t_perf_ns - _ANCHOR_PERF_NS)) / 1e9

try:  # probe ONCE: a failed import per span would be a hot-path tax
    from opentelemetry import trace as _otel_trace
except Exception:  # pragma: no cover - otel genuinely optional
    _otel_trace = None


def enabled() -> bool:
    return get_config().tracing_enabled


# ------------------------------------------------------------------ id gen
# Prefix + counter, the TaskID.generate scheme (utils/ids.py): ONE
# urandom per process; the counter's next() is a single GIL-atomic C
# step so user threads and the loop thread can mint ids concurrently.
# 128/64-bit OTel shapes are kept: trace ids are 9 random bytes + a
# 7-byte counter, span ids 4 random bytes + 4-byte counter.
_gen_lock = _threading.Lock()
_trace_prefix: bytes = b""
_trace_counter = None
_span_prefix: bytes = b""
_span_counter = None


def _gen_trace_id() -> str:
    global _trace_prefix, _trace_counter
    if _trace_counter is None:
        with _gen_lock:
            if _trace_counter is None:
                _trace_prefix = os.urandom(9)  # raylint: disable=RT021 -- one-time prefix init, counter per call
                _trace_counter = itertools.count()
    n = next(_trace_counter) % (1 << 56)
    return (_trace_prefix + n.to_bytes(7, "little")).hex()


def _gen_span_id() -> str:
    global _span_prefix, _span_counter
    if _span_counter is None:
        with _gen_lock:
            if _span_counter is None:
                _span_prefix = os.urandom(4)  # raylint: disable=RT021 -- one-time prefix init, counter per call
                _span_counter = itertools.count()
    n = next(_span_counter) % (1 << 32)
    return (_span_prefix + n.to_bytes(4, "little")).hex()


def _reset_prefixes() -> None:
    global _trace_prefix, _trace_counter, _span_prefix, _span_counter
    with _gen_lock:
        _trace_prefix = b""
        _trace_counter = None
        _span_prefix = b""
        _span_counter = None


if hasattr(os, "register_at_fork"):  # a fork child must mint fresh ids
    os.register_at_fork(after_in_child=_reset_prefixes)


# ---------------------------------------------------------------- sampling
# Head-based, deterministic: every Nth root is sampled (N derived from
# trace_sample_rate), so the unsampled path is one counter bump + one
# compare — no RNG, no syscall. The decision is carried in the wire
# leg's sampled bit; children never re-decide.
_sample_counter = itertools.count()
_stride_cache: tuple[float, int] | None = None


def sample() -> bool:
    """One head-sampling decision (call only where a trace would START)."""
    global _stride_cache
    rate = get_config().trace_sample_rate
    if rate >= 1.0:
        return True
    if rate <= 0.0:
        return False
    cached = _stride_cache
    if cached is None or cached[0] != rate:
        cached = _stride_cache = (rate, max(1, round(1.0 / rate)))
    return next(_sample_counter) % cached[1] == 0


# ------------------------------------------------------------- wire format
# The 25-byte trace leg packed records carry (core/fastpath.py, wire
# 2.1): <16s trace_id><8s span_id><B flags> — flags bit0 = sampled.
# Unsampled requests ship NO leg at all; the leg's presence is flagged
# by the record's TRACE_CTX bit / the reply's TRACED status flag.
_WIRE = struct.Struct("<16s8sB")
WIRE_LEN = _WIRE.size  # 25


def pack_ctx(trace_id: str, span_id: str, sampled: bool = True) -> bytes:
    return _WIRE.pack(bytes.fromhex(trace_id), bytes.fromhex(span_id),
                      1 if sampled else 0)


def unpack_ctx(leg: bytes) -> dict:
    tid, sid, flags = _WIRE.unpack_from(leg)
    return {"trace_id": tid.hex(), "parent_span_id": sid.hex(),
            "sampled": bool(flags & 1)}


# Sentinel an UNSAMPLED root installs in the contextvar: the head
# decision is per REQUEST, so downstream submits inside an unsampled
# request must not re-draw (each stray draw would mint an orphan
# partial trace AND consume a stride tick, skewing the configured rate).
UNSAMPLED = ("", "")


def current() -> tuple[str, str] | None:
    """(trace_id, span_id) of the active span, if any."""
    ctx = _ctx.get()
    return None if ctx is UNSAMPLED else ctx


def suppress():
    """Mark the current context UNSAMPLED (a root that lost the head
    draw): downstream :func:`submit_context` calls inherit the decision
    instead of re-drawing. Returns a token for :func:`deactivate`."""
    return _ctx.set(UNSAMPLED)


def is_suppressed() -> bool:
    return _ctx.get() is UNSAMPLED


def inject() -> dict:
    """Capture the caller's span context for a task spec; starts a fresh
    trace when the caller has none (every traced task belongs to some
    trace — the reference behaves the same for root calls). Does NOT
    apply sampling: use :func:`submit_context` on request paths."""
    ctx = _ctx.get()
    if ctx is None or ctx is UNSAMPLED:
        return {"trace_id": _gen_trace_id(), "parent_span_id": None}
    return {"trace_id": ctx[0], "parent_span_id": ctx[1]}


def submit_context() -> dict | None:
    """Sampling-aware :func:`inject`: inherit the active (already
    decided) context, or head-sample a fresh root. None = this request
    is unsampled — ship nothing, record nothing."""
    ctx = _ctx.get()
    if ctx is not None:
        if ctx is UNSAMPLED:
            return None  # decided at the request's root: no re-draw
        return {"trace_id": ctx[0], "parent_span_id": ctx[1]}
    if not sample():
        return None
    return {"trace_id": _gen_trace_id(), "parent_span_id": None}


class span:
    """Context manager recording one span into ``sink`` (a callable
    taking the span dict — typically the task-event buffer's emit).
    Extra ``attributes`` land in the span dict verbatim; ``stage``
    (queue | exec | wire | pull) and ``transport`` (ring | tunnel |
    rpc) are the ones TraceCriticalPath understands."""

    def __init__(self, name: str, trace_ctx: dict | None, sink,
                 **attributes):
        self.name = name
        self.sink = sink
        self.attributes = attributes
        ctx = trace_ctx or inject()
        self.trace_id = ctx["trace_id"]
        self.parent_span_id = ctx.get("parent_span_id")
        self.span_id = _gen_span_id()
        self._token = None
        self._otel = None

    def __enter__(self):
        self._t0_ns = time.perf_counter_ns()
        self.start = _wall_s(self._t0_ns)
        self._token = _ctx.set((self.trace_id, self.span_id))
        if _otel_trace is not None:
            try:  # optional mirror onto a configured OTel SDK
                self._otel = _otel_trace.get_tracer("ray_tpu").start_span(
                    self.name)
            except Exception:
                self._otel = None
        return self

    def __exit__(self, exc_type, exc, tb):
        _ctx.reset(self._token)
        # same monotonic clock as __enter__: end >= start ALWAYS, and a
        # 2µs span reports 2µs instead of 0.0
        end = self.start + (time.perf_counter_ns() - self._t0_ns) / 1e9
        if self._otel is not None:
            try:
                self._otel.end()
            except Exception:  # raylint: disable=RT012 — optional exporter must never break user code
                pass
        self.sink({
            "trace_id": self.trace_id,
            "span_id": self.span_id,
            "parent_span_id": self.parent_span_id,
            "name": self.name,
            "start_ts": self.start,
            "end_ts": end,
            "error": repr(exc) if exc is not None else None,
            **self.attributes,
        })
        return False


def emit_point(name: str, trace_ctx: dict, sink, **attributes) -> str:
    """Record a zero-duration span (the submit-side marker) and return
    its span id — the parent the executing side's child span links to."""
    span_id = _gen_span_id()
    now = _wall_s(time.perf_counter_ns())
    sink({
        "trace_id": trace_ctx["trace_id"], "span_id": span_id,
        "parent_span_id": trace_ctx.get("parent_span_id"),
        "name": name, "start_ts": now, "end_ts": now,
        **attributes,
    })
    return span_id


def emit_retro(name: str, trace_ctx: dict, sink, dur_s: float,
               **attributes) -> str:
    """Record a span for an operation that already FINISHED (duration
    known after the fact — the disagg telemetry shape, where stage
    durations are measured first and reported once)."""
    span_id = _gen_span_id()
    end = _wall_s(time.perf_counter_ns())
    sink({
        "trace_id": trace_ctx["trace_id"], "span_id": span_id,
        "parent_span_id": trace_ctx.get("parent_span_id"),
        "name": name, "start_ts": end - max(0.0, dur_s), "end_ts": end,
        **attributes,
    })
    return span_id


def activate(trace_ctx: dict | None):
    """Set the ambient context from a spec's trace_ctx WITHOUT opening a
    span (thread-side helper); returns a reset token or None."""
    if not trace_ctx:
        return None
    return _ctx.set((trace_ctx["trace_id"],
                     trace_ctx.get("parent_span_id") or _gen_span_id()))


def deactivate(token) -> None:
    if token is not None:
        _ctx.reset(token)


# -------------------------------------------------------- critical path
class TraceCriticalPath:
    """Attribute one assembled trace's latency to stages.

    Walks the span tree of one request and splits the root span's wall
    time into ``queue`` (admission/batch queues), ``exec`` (user code),
    ``wire`` (submit/reply hops, routing), ``pull`` (object/KV-page
    movement) and ``other`` — each span's SELF time (its duration minus
    the union of its children's overlap) is charged to its stage, so
    concurrent children never double-bill the parent. The result feeds
    the ``request_critical_path_us`` metrics and the ``/api/trace/<id>``
    waterfall's stage strip.
    """

    STAGES = ("queue", "exec", "wire", "pull", "other")

    @staticmethod
    def classify(s: dict) -> str:
        stage = s.get("stage")
        if stage in TraceCriticalPath.STAGES:
            return stage
        name = s.get("name", "")
        if name.endswith("::run") or name.endswith("::exec"):
            return "exec"
        if name.endswith(".remote") or name.endswith("::call"):
            return "wire"
        if "queue" in name or "admission" in name:
            return "queue"
        if ("adopt" in name or "ship" in name or "pull" in name
                or "kv_" in name):
            return "pull"
        return "other"

    @staticmethod
    def compute(spans: list[dict]) -> dict | None:
        """-> {total_us, stages: {stage: us}, root_span_id, path: [span
        ids root->leaf along the latest-finishing chain]} or None for an
        empty/parentless span set."""
        if not spans:
            return None
        by_id = {s["span_id"]: s for s in spans if s.get("span_id")}
        children: dict[str | None, list[dict]] = {}
        for s in spans:
            children.setdefault(s.get("parent_span_id"), []).append(s)
        roots = [s for s in spans
                 if s.get("parent_span_id") not in by_id]
        if not roots:
            return None
        root = min(roots, key=lambda s: s.get("start_ts", 0.0))
        stages = {st: 0.0 for st in TraceCriticalPath.STAGES}

        def self_time(s: dict) -> float:
            dur = max(0.0, s.get("end_ts", 0.0) - s.get("start_ts", 0.0))
            kids = children.get(s.get("span_id"), ())
            if not kids:
                return dur
            # union of child intervals clipped to this span
            ivs = sorted(
                (max(k["start_ts"], s["start_ts"]),
                 min(k["end_ts"], s["end_ts"])) for k in kids)
            covered = 0.0
            cur_a = cur_b = None
            for a, b in ivs:
                if b <= a:
                    continue
                if cur_b is None or a > cur_b:
                    if cur_b is not None:
                        covered += cur_b - cur_a
                    cur_a, cur_b = a, b
                else:
                    cur_b = max(cur_b, b)
            if cur_b is not None:
                covered += cur_b - cur_a
            return max(0.0, dur - covered)

        # attribute self time over the whole tree under the chosen root
        seen = set()
        stack = [root]
        tree_end = root.get("end_ts", 0.0)
        while stack:
            s = stack.pop()
            sid = s.get("span_id")
            if sid in seen:
                continue
            seen.add(sid)
            tree_end = max(tree_end, s.get("end_ts", 0.0))
            stages[TraceCriticalPath.classify(s)] += self_time(s)
            stack.extend(children.get(sid, ()))
        # critical chain: from the root, follow the latest-finishing child
        path = [root["span_id"]]
        cur = root
        while True:
            kids = [k for k in children.get(cur.get("span_id"), ())
                    if k.get("span_id") not in path]
            if not kids:
                break
            cur = max(kids, key=lambda k: k.get("end_ts", 0.0))
            path.append(cur["span_id"])
        # total spans the whole tree, not just the root's own interval —
        # a driver-rooted trace's root is a zero-duration submit POINT
        # whose children carry all the time
        total = max(0.0, tree_end - root.get("start_ts", 0.0))
        return {
            "total_us": total * 1e6,
            "stages": {st: v * 1e6 for st, v in stages.items()},
            "root_span_id": root["span_id"],
            "root_name": root.get("name"),
            "path": path,
        }

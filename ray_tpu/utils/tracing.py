"""Distributed task tracing: span propagation across remote calls.

TPU-native counterpart of the reference tracing layer (ref:
python/ray/util/tracing/tracing_helper.py:36-60 — there OTel span context
is injected into task specs by decorator wrappers and child spans open
around execution). Here the span layer is native and always importable
(no SDK required): spans use OTel-shaped ids (128-bit trace, 64-bit
span), ride the task-event pipeline into the GCS, and surface through
``ray_tpu.state.list_spans()`` / the chrome timeline. If the
``opentelemetry`` API is installed and configured, spans are mirrored
onto it as well.

Enable with ``Config.tracing_enabled`` (env ``RT_TRACING_ENABLED=1``):
off by default, the hot path pays one boolean check.

Propagation model: a contextvar holds the active (trace_id, span_id).
Submitting a task captures it into the spec (``trace_ctx``); executing a
task opens a child span and activates it for the duration of the user
function, so nested ``.remote()`` calls chain parent -> child across any
number of processes.
"""

from __future__ import annotations

import contextvars
import os
import time

from ray_tpu.config import get_config

_ctx: contextvars.ContextVar[tuple[str, str] | None] = contextvars.ContextVar(
    "rt_trace_ctx", default=None)

# Span clock: durations come from perf_counter_ns (monotonic, ns
# resolution — time.time() collapses sub-ms spans to zero on coarse
# clocks and a wall-clock step mid-span would yield a NEGATIVE
# duration); one wall anchor captured at import reconstructs absolute
# start/end times for the timeline.
_ANCHOR_PERF_NS = time.perf_counter_ns()
_ANCHOR_WALL_NS = time.time_ns()


def _wall_s(t_perf_ns: int) -> float:
    return (_ANCHOR_WALL_NS + (t_perf_ns - _ANCHOR_PERF_NS)) / 1e9

try:  # probe ONCE: a failed import per span would be a hot-path tax
    from opentelemetry import trace as _otel_trace
except Exception:  # pragma: no cover - otel genuinely optional
    _otel_trace = None


def enabled() -> bool:
    return get_config().tracing_enabled


def _gen_trace_id() -> str:
    return os.urandom(16).hex()


def _gen_span_id() -> str:
    return os.urandom(8).hex()


def current() -> tuple[str, str] | None:
    """(trace_id, span_id) of the active span, if any."""
    return _ctx.get()


def inject() -> dict:
    """Capture the caller's span context for a task spec; starts a fresh
    trace when the caller has none (every traced task belongs to some
    trace — the reference behaves the same for root calls)."""
    ctx = _ctx.get()
    if ctx is None:
        return {"trace_id": _gen_trace_id(), "parent_span_id": None}
    return {"trace_id": ctx[0], "parent_span_id": ctx[1]}


class span:
    """Context manager recording one span into ``sink`` (a callable
    taking the span dict — typically the task-event buffer's emit)."""

    def __init__(self, name: str, trace_ctx: dict | None, sink,
                 **attributes):
        self.name = name
        self.sink = sink
        self.attributes = attributes
        ctx = trace_ctx or inject()
        self.trace_id = ctx["trace_id"]
        self.parent_span_id = ctx.get("parent_span_id")
        self.span_id = _gen_span_id()
        self._token = None
        self._otel = None

    def __enter__(self):
        self._t0_ns = time.perf_counter_ns()
        self.start = _wall_s(self._t0_ns)
        self._token = _ctx.set((self.trace_id, self.span_id))
        if _otel_trace is not None:
            try:  # optional mirror onto a configured OTel SDK
                self._otel = _otel_trace.get_tracer("ray_tpu").start_span(
                    self.name)
            except Exception:
                self._otel = None
        return self

    def __exit__(self, exc_type, exc, tb):
        _ctx.reset(self._token)
        # same monotonic clock as __enter__: end >= start ALWAYS, and a
        # 2µs span reports 2µs instead of 0.0
        end = self.start + (time.perf_counter_ns() - self._t0_ns) / 1e9
        if self._otel is not None:
            try:
                self._otel.end()
            except Exception:  # raylint: disable=RT012 — optional exporter must never break user code
                pass
        self.sink({
            "trace_id": self.trace_id,
            "span_id": self.span_id,
            "parent_span_id": self.parent_span_id,
            "name": self.name,
            "start_ts": self.start,
            "end_ts": end,
            "error": repr(exc) if exc is not None else None,
            **self.attributes,
        })
        return False


def activate(trace_ctx: dict | None):
    """Set the ambient context from a spec's trace_ctx WITHOUT opening a
    span (thread-side helper); returns a reset token or None."""
    if not trace_ctx:
        return None
    return _ctx.set((trace_ctx["trace_id"],
                     trace_ctx.get("parent_span_id") or _gen_span_id()))


def deactivate(token) -> None:
    if token is not None:
        _ctx.reset(token)

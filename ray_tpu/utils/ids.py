"""Binary IDs for jobs, tasks, actors, objects and nodes.

Mirrors the reference's ID scheme (ref: src/ray/common/id.h): fixed-size binary
ids; an ObjectID embeds the id of the task that created it plus a return-index,
so ownership (which worker's memory store owns the value) is derivable from the
id itself — the property the reference's ownership-based object directory
relies on (ref: src/ray/object_manager/ownership_object_directory.cc).
"""

from __future__ import annotations

import itertools
import os
import struct
import threading as _threading

_TASK_ID_SIZE = 16
_UNIQUE_ID_SIZE = 16
_OBJECT_INDEX_SIZE = 4
_OBJECT_ID_SIZE = _TASK_ID_SIZE + _OBJECT_INDEX_SIZE  # 20 bytes


class BaseID:
    SIZE = _UNIQUE_ID_SIZE
    __slots__ = ("_bytes", "_hash")

    def __init__(self, b: bytes):
        if len(b) != self.SIZE:
            raise ValueError(f"{type(self).__name__} needs {self.SIZE} bytes, got {len(b)}")
        self._bytes = bytes(b)
        # ids key nearly every hot-path dict (memory store, refcounts,
        # lineage): cache the hash instead of re-hashing 20 bytes per lookup
        self._hash = hash(self._bytes)

    @classmethod
    def generate(cls):
        return cls(os.urandom(cls.SIZE))

    @classmethod
    def nil(cls):
        return cls(b"\x00" * cls.SIZE)

    def is_nil(self) -> bool:
        return self._bytes == b"\x00" * self.SIZE

    def binary(self) -> bytes:
        return self._bytes

    def hex(self) -> str:
        return self._bytes.hex()

    @classmethod
    def from_hex(cls, h: str):
        return cls(bytes.fromhex(h))

    def __hash__(self):
        return self._hash

    def __eq__(self, other):
        return type(other) is type(self) and other._bytes == self._bytes

    def __lt__(self, other):
        return self._bytes < other._bytes

    def __repr__(self):
        return f"{type(self).__name__}({self._bytes.hex()[:12]})"

    def __reduce__(self):
        return (type(self), (self._bytes,))


class JobID(BaseID):
    SIZE = 4


class NodeID(BaseID):
    pass


class WorkerID(BaseID):
    pass


class ActorID(BaseID):
    pass


class PlacementGroupID(BaseID):
    pass


class TaskID(BaseID):
    SIZE = _TASK_ID_SIZE
    # Last id byte tags the task kind so owners can tell actor tasks apart
    # from normal tasks without per-task state (cancel semantics differ).
    _ACTOR_MARK = 0xA5

    # Normal task ids are a random per-process prefix + a counter: one
    # urandom syscall per process instead of one per task (ids are a
    # measurable slice of the submission hot path). itertools.count is the
    # counter because its __next__ is a single C step — generate() is
    # called concurrently from user and loop threads and a Python-level
    # read-modify-write would mint duplicate ids. The final byte is the
    # kind tag (never _ACTOR_MARK for normal tasks).
    _gen_prefix: bytes = b""
    _gen_counter = None
    _gen_lock = _threading.Lock()

    @classmethod
    def for_driver(cls, job_id: JobID) -> "TaskID":
        return cls(job_id.binary() + b"\x00" * (cls.SIZE - JobID.SIZE))

    @classmethod
    def _generate_marked(cls, mark: bytes) -> "TaskID":
        # fork safety WITHOUT a per-call os.getpid(): that's a real
        # syscall (~30us under syscall-intercepting sandboxes) on the
        # submission hot path. _reset_task_prefix below invalidates the
        # prefix in fork children; fresh processes start invalidated.
        if cls._gen_counter is None:
            with cls._gen_lock:
                if cls._gen_counter is None:
                    # ONE urandom per process (double-checked init);
                    # per-call ids come from the counter below
                    cls._gen_prefix = os.urandom(cls.SIZE - 8)  # raylint: disable=RT021 -- init-once
                    cls._gen_counter = itertools.count()
        n = next(cls._gen_counter) % (1 << 56)
        return cls(cls._gen_prefix + n.to_bytes(7, "little") + mark)

    @classmethod
    def generate(cls):
        return cls._generate_marked(b"\x00")

    @classmethod
    def generate_actor(cls) -> "TaskID":
        # same prefix+counter scheme as generate() — a per-call
        # os.urandom(16) measured ~288us under the syscall-intercepting
        # sandbox, 60%+ of the whole actor submission hot path. The kind
        # tag in the final byte keeps actor ids disjoint from normal
        # task ids minted from the same prefix and counter.
        return cls._generate_marked(bytes((cls._ACTOR_MARK,)))

    def is_actor_task(self) -> bool:
        return self._bytes[-1] == self._ACTOR_MARK

    @classmethod
    def _reset_prefix(cls) -> None:
        with cls._gen_lock:
            cls._gen_prefix = b""
            cls._gen_counter = None


if hasattr(os, "register_at_fork"):  # a fork child must mint fresh ids
    os.register_at_fork(after_in_child=TaskID._reset_prefix)


class ObjectID(BaseID):
    SIZE = _OBJECT_ID_SIZE

    @classmethod
    def for_task_return(cls, task_id: TaskID, index: int) -> "ObjectID":
        return cls(task_id.binary() + struct.pack("<I", index))

    @classmethod
    def from_random(cls) -> "ObjectID":
        # ``put()`` objects: owner task id + random index space (high bit set
        # to never collide with task returns). The task-id half rides the
        # prefix+counter scheme, not a per-call os.urandom(16) — the serve
        # router mints one of these per request (promise refs), and the
        # urandom syscall measured ~288us under the intercepting sandbox
        # (the same cost generate_actor shed in PR 8).
        return cls(TaskID.generate().binary() + struct.pack("<I", 1 << 31))

    def task_id(self) -> TaskID:
        return TaskID(self._bytes[:_TASK_ID_SIZE])

    def return_index(self) -> int:
        return struct.unpack("<I", self._bytes[_TASK_ID_SIZE:])[0]

"""Dashboard: JSON API + SPA frontend.

TPU-native counterpart of the reference dashboard role (ref:
python/ray/dashboard/ head + python/ray/dashboard/client/src React SPA —
here an aiohttp app over the state API serving a dependency-free
hash-routed JS app from ``dashboard_client/``, no build step):

    GET /                      SPA shell (views: overview/nodes/actors/
                               tasks/objects/pgs/jobs/serve/metrics/timeline)
    GET /static/*              SPA assets
    GET /api/cluster           nodes + resources
    GET /api/tasks             latest task states
    GET /api/actors            actor table
    GET /api/objects           object table (size/location/spill/refs)
    GET /api/placement_groups  placement group table
    GET /api/summary/tasks     task counts by state
    GET /api/serve             serve applications/deployments status
    GET /api/serve_autoscale   fired autoscale decisions (?key=app/dep)
    GET /api/slo_burn          SLO burn-rate alerts (?key=app/dep)
    GET /api/traces            assembled request traces (newest first)
    GET /api/trace/{id}        one trace as a waterfall + critical path
    GET /api/metrics           aggregated cluster metrics
    GET /api/metric_window     rollup timeseries (?name=&secs=&tag.k=v)
    GET /api/metric_names      metric names known to the rollup store
    GET /api/timeline          chrome-trace events (load into perfetto)
    GET /api/latency           flight-recorder per-stage task latency
    GET /api/llm               LLM decode-plane panel (disagg stages + spec gauges)
    GET /api/tiering           memory-tiering panel (spill/restore stages + tier-1 counters)
    GET /api/worker_deaths     worker postmortems (recorder event dumps)
    GET /api/workers/{id}/stack  live stack dump (py-spy role)
    GET /api/workers/{id}/heap   tracemalloc heap profile
"""
from __future__ import annotations

import os

_CLIENT_DIR = os.path.join(os.path.dirname(__file__), "dashboard_client")

_PAGE = """<!doctype html><html><head><title>ray_tpu dashboard</title>
<style>
body{font-family:monospace;margin:2em;background:#111;color:#ddd}
h1{color:#7fd} h2{color:#adf;margin-top:1.2em} table{border-collapse:collapse}
td,th{border:1px solid #444;padding:4px 10px;text-align:left}
.ok{color:#7f7}.bad{color:#f77}
</style></head><body>
<h1>ray_tpu dashboard</h1>
<div id="out">loading…</div>
<script>
function esc(v){return String(v ?? '').replace(/[&<>"']/g,
  c=>({'&':'&amp;','<':'&lt;','>':'&gt;','"':'&quot;',"'":'&#39;'}[c]));}
async function refresh(){
  const [cluster, tasks, actors, metrics] = await Promise.all([
    fetch('/api/cluster').then(r=>r.json()),
    fetch('/api/tasks').then(r=>r.json()),
    fetch('/api/actors').then(r=>r.json()),
    fetch('/api/metrics').then(r=>r.json()),
  ]);
  let h = '<h2>nodes</h2><table><tr><th>node</th><th>alive</th><th>resources (avail/total)</th><th>queued</th></tr>';
  for (const n of cluster){
    const res = Object.keys(n.resources_total).map(k=>
      `${k}: ${n.resources_available[k] ?? 0}/${n.resources_total[k]}`).join('  ');
    h += `<tr><td>${esc(n.node_id).slice(0,12)}</td><td class="${n.alive?'ok':'bad'}">${n.alive}</td><td>${esc(res)}</td><td>${n.queued_leases||0}</td></tr>`;
  }
  h += '</table><h2>tasks (latest)</h2><table><tr><th>name</th><th>state</th><th>duration</th></tr>';
  for (const t of tasks.slice(0,30)){
    h += `<tr><td>${esc(t.name)}</td><td class="${t.state==='FAILED'?'bad':'ok'}">${t.state}</td><td>${t.duration_s?t.duration_s.toFixed(3)+'s':''}</td></tr>`;
  }
  h += '</table><h2>actors</h2><table><tr><th>actor</th><th>name</th><th>state</th><th>restarts</th></tr>';
  for (const a of actors){
    h += `<tr><td>${esc(a.actor_id).slice(0,12)}</td><td>${esc(a.name||'')}</td><td class="${a.state==='ALIVE'?'ok':'bad'}">${a.state}</td><td>${a.num_restarts}</td></tr>`;
  }
  h += '</table><h2>metrics</h2><table><tr><th>metric</th><th>value</th></tr>';
  for (const [k,m] of Object.entries(metrics)){
    if (m.type !== 'histogram')
      for (const s of (m.samples || [])){
        const tag = Object.entries(s.tags || {}).map(([tk,tv])=>`${tk}=${tv}`).join(',');
        h += `<tr><td>${esc(k)}${tag?' {'+esc(tag)+'}':''}</td><td>${esc(s.value)}</td></tr>`;
      }
  }
  h += '</table>';
  document.getElementById('out').innerHTML = h;
}
refresh(); setInterval(refresh, 2000);
</script></body></html>"""


def build_app():
    from aiohttp import web

    from ray_tpu import state

    async def index(request):
        path = os.path.join(_CLIENT_DIR, "index.html")
        if os.path.exists(path):
            with open(path) as f:
                return web.Response(text=f.read(), content_type="text/html")
        return web.Response(text=_PAGE, content_type="text/html")

    def _json(fn):
        async def handler(request):
            import asyncio

            return web.json_response(await asyncio.to_thread(fn))

        return handler

    # job submissions ship runtime_env packages inline (base64) — the
    # default 1MB body cap would reject any real working_dir
    app = web.Application(client_max_size=256 * 1024 * 1024)
    app.router.add_get("/", index)
    app.router.add_get("/api/cluster", _json(lambda: _plain(state.list_nodes())))
    app.router.add_get("/api/tasks", _json(lambda: _plain(state.list_tasks())))
    app.router.add_get("/api/actors", _json(lambda: _plain(state.list_actors())))
    app.router.add_get("/api/metrics", _json(lambda: _plain(state.cluster_metrics())))

    async def prometheus(request):
        # Prometheus scrape endpoint (text exposition format); the
        # conventional path so a scrape_config needs only the address.
        # to_thread: the render calls the GCS synchronously and must not
        # run on the core loop
        import asyncio

        text = await asyncio.to_thread(state.prometheus_metrics)
        return web.Response(text=text, content_type="text/plain")

    app.router.add_get("/metrics", prometheus)

    async def metric_window(request):
        # rollup-plane timeseries: windowed points for one metric from
        # the GCS RollupStore (counters as rates, histograms as
        # mergeable quantiles, ratios as num/den) — the same series the
        # control loops (SLO monitor, spill trigger) read
        import asyncio

        name = request.query.get("name")
        if not name:
            return web.json_response({"error": "name required"}, status=400)
        tags = None
        for k, v in request.query.items():
            if k.startswith("tag."):
                tags = dict(tags or {})
                tags[k[4:]] = v
        try:
            win = await asyncio.to_thread(
                state.metric_window, name,
                float(request.query.get("secs", 60.0)), tags)
            return web.json_response(_plain(win))
        except Exception as e:
            return web.json_response({"error": str(e)}, status=503)

    app.router.add_get("/api/metric_window", metric_window)
    app.router.add_get(
        "/api/metric_names", _json(lambda: _plain(state.metric_names())))
    app.router.add_get("/api/timeline", _json(lambda: state.timeline()))
    # flight-recorder surfaces: per-stage latency percentiles and worker
    # postmortems (see utils/recorder.py, state.list_task_latency)
    app.router.add_get(
        "/api/latency", _json(lambda: _plain(state.list_task_latency())))
    # LLM decode-plane panel: disagg stage windows (incl. speculative
    # tokens_per_step / spec_accept_rate) + rt_llm_* gauges
    app.router.add_get(
        "/api/llm", _json(lambda: _plain(state.list_llm_metrics())))
    # memory-tiering panel: spill/restore stage windows + tier-1 byte
    # counters and the prefix cache hit-rate gauge (state.list_tiering)
    app.router.add_get(
        "/api/tiering", _json(lambda: _plain(state.list_tiering())))
    app.router.add_get(
        "/api/worker_deaths",
        _json(lambda: _plain(state.list_worker_deaths())))
    app.router.add_get(
        "/api/objects", _json(lambda: _plain(state.list_objects())))
    app.router.add_get(
        "/api/placement_groups",
        _json(lambda: _plain(state.list_placement_groups())))
    app.router.add_get(
        "/api/summary/tasks", _json(lambda: _plain(state.summary_tasks())))

    async def serve_status(request):
        import asyncio

        def do():
            from ray_tpu import serve

            return _plain(serve.status())

        try:
            return web.json_response(await asyncio.to_thread(do))
        except Exception as e:
            return web.json_response({"error": str(e)}, status=503)

    app.router.add_get("/api/serve", serve_status)

    async def serve_autoscale(request):
        import asyncio

        key = request.query.get("key")
        try:
            events = await asyncio.to_thread(
                state.list_serve_autoscale_events, key)
            return web.json_response(_plain(events))
        except Exception as e:
            return web.json_response({"error": str(e)}, status=503)

    # fired autoscale decisions with causes (serve/dataplane/autoscaler)
    app.router.add_get("/api/serve_autoscale", serve_autoscale)

    async def slo_burn(request):
        import asyncio

        key = request.query.get("key")
        try:
            events = await asyncio.to_thread(state.list_slo_burn_events, key)
            return web.json_response(_plain(events))
        except Exception as e:
            return web.json_response({"error": str(e)}, status=503)

    # SLO error-budget burn-rate alerts (serve/dataplane/slo.py)
    app.router.add_get("/api/slo_burn", slo_burn)

    async def traces(request):
        import asyncio

        try:
            rows = await asyncio.to_thread(
                state.list_traces,
                int(request.query.get("limit", 100)),
                int(request.query.get("offset", 0)))
            return web.json_response(_plain(rows))
        except Exception as e:
            return web.json_response({"error": str(e)}, status=503)

    async def trace_waterfall(request):
        """One assembled trace as a waterfall: spans sorted by start,
        each with its offset/duration relative to the trace start plus
        the critical-path stage attribution — render directly, or feed
        the spans to any OTel-style viewer."""
        import asyncio

        trace_id = request.match_info["trace_id"]
        try:
            tr = await asyncio.to_thread(state.get_trace, trace_id)
        except Exception as e:
            return web.json_response({"error": str(e)}, status=503)
        if tr is None:
            return web.json_response({"error": "unknown trace"}, status=404)
        t0 = tr.get("start_ts", 0.0)
        for s in tr.get("spans", []):
            s["offset_ms"] = max(0.0, (s.get("start_ts", t0) - t0) * 1e3)
            s["dur_ms"] = max(
                0.0, (s.get("end_ts", 0.0) - s.get("start_ts", 0.0)) * 1e3)
        return web.json_response(_plain(tr))

    # trace assembler surfaces (state.get_trace / list_traces)
    app.router.add_get("/api/traces", traces)
    app.router.add_get("/api/trace/{trace_id}", trace_waterfall)

    async def worker_stack(request):
        import asyncio

        wid = request.match_info["worker_id"]
        try:
            res = await asyncio.to_thread(state.get_stack, wid)
            if res is None:
                return web.json_response({"error": "worker not found"}, status=404)
            return web.json_response(_plain(res))
        except Exception as e:
            return web.json_response({"error": str(e)}, status=500)

    async def worker_heap(request):
        import asyncio

        wid = request.match_info["worker_id"]
        action = request.query.get("action", "snapshot")
        try:
            res = await asyncio.to_thread(
                state.get_heap_profile, wid, action=action)
            if res is None:
                return web.json_response({"error": "worker not found"}, status=404)
            return web.json_response(_plain(res))
        except Exception as e:
            return web.json_response({"error": str(e)}, status=500)

    async def worker_profile(request):
        import asyncio

        wid = request.match_info["worker_id"]
        try:
            res = await asyncio.to_thread(
                state.get_cpu_profile, wid,
                duration_s=float(request.query.get("duration_s", 2.0)),
                format=request.query.get("format", "speedscope"))
            if res is None:
                return web.json_response({"error": "worker not found"}, status=404)
            return web.json_response(_plain(res))
        except Exception as e:
            return web.json_response({"error": str(e)}, status=500)

    app.router.add_get("/api/workers/{worker_id}/stack", worker_stack)
    app.router.add_get("/api/workers/{worker_id}/heap", worker_heap)
    app.router.add_get("/api/workers/{worker_id}/profile", worker_profile)
    if os.path.isdir(_CLIENT_DIR):
        app.router.add_static("/static", _CLIENT_DIR)
    _add_job_routes(app)
    return app


def _add_job_routes(app):
    """Job REST API (ref: dashboard/modules/job REST head + sdk.py):

        POST /api/jobs                  {entrypoint, runtime_env, packages}
        GET  /api/jobs                  list
        GET  /api/jobs/{id}             status record
        GET  /api/jobs/{id}/logs        captured driver output
        POST /api/jobs/{id}/stop
    """
    import asyncio
    import base64

    from aiohttp import web

    from ray_tpu import job as jobmod

    async def submit(request):
        body = await request.json()
        try:
            def do():
                from ray_tpu.core import api

                core = api.get_core()
                for digest, blob_b64 in (body.get("packages") or {}).items():
                    core._run_sync(core.gcs.call("kv_put", {
                        "ns": "runtime_env_packages", "key": digest,
                        "value": base64.b64decode(blob_b64)}))
                env = body.get("runtime_env")
                if env:
                    env = {**env, "_packaged": True}
                return jobmod.submit_job(
                    body["entrypoint"], runtime_env=env,
                    job_id=body.get("submission_id"),
                    metadata=body.get("metadata"),
                )

            job_id = await asyncio.to_thread(do)
            return web.json_response({"job_id": job_id})
        except Exception as e:
            return web.json_response({"error": str(e)}, status=400)

    async def listing(request):
        return web.json_response(await asyncio.to_thread(jobmod.list_jobs))

    async def status(request):
        try:
            rec = await asyncio.to_thread(
                jobmod.job_status, request.match_info["job_id"])
            return web.json_response(rec)
        except KeyError as e:
            return web.json_response({"error": str(e)}, status=404)

    async def logs(request):
        try:
            text = await asyncio.to_thread(
                jobmod.job_logs, request.match_info["job_id"])
            return web.json_response({"logs": text})
        except KeyError as e:
            return web.json_response({"error": str(e)}, status=404)

    async def stop(request):
        ok = await asyncio.to_thread(
            jobmod.stop_job, request.match_info["job_id"])
        return web.json_response({"stopped": bool(ok)})

    app.router.add_post("/api/jobs", submit)
    app.router.add_get("/api/jobs", listing)
    app.router.add_get("/api/jobs/{job_id}", status)
    app.router.add_get("/api/jobs/{job_id}/logs", logs)
    app.router.add_post("/api/jobs/{job_id}/stop", stop)


def _plain(obj):
    """IDs and tuples -> JSON-safe."""
    if isinstance(obj, dict):
        return {str(k): _plain(v) for k, v in obj.items()}
    if isinstance(obj, (list, tuple)):
        return [_plain(v) for v in obj]
    if hasattr(obj, "hex") and not isinstance(obj, (str, bytes)):
        return obj.hex()
    if isinstance(obj, bytes):
        return obj.hex()
    return obj


def run_dashboard(host: str = "127.0.0.1", port: int = 8265):
    """Blocking server (the CLI entry; ref: dashboard default port 8265)."""
    from aiohttp import web

    web.run_app(build_app(), host=host, port=port, print=None)


def start_dashboard_async(host: str = "127.0.0.1", port: int = 0):
    """Start on the caller-provided loop; returns (runner, (host, port))."""
    import asyncio

    from aiohttp import web

    async def go():
        runner = web.AppRunner(build_app())
        await runner.setup()
        site = web.TCPSite(runner, host, port)
        await site.start()
        actual = runner.addresses[0][1] if port == 0 else port
        return runner, (host, actual)

    return go()

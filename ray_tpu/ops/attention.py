"""Attention dispatch: one entry point, backend picked by mesh/hardware.

- plain exact attention (XLA fuses well at short T)
- pallas flash attention on TPU (ops/flash_attention.py) for long T
- ring attention over the sp mesh axis when sequence is sharded
- ulysses all-to-all variant for head-divisible meshes
"""

from __future__ import annotations

import jax.numpy as jnp

from ray_tpu.parallel.ring_attention import reference_attention


def attention(q, k, v, *, causal: bool = True, sm_scale=None, mesh=None,
              seq_axis: str | None = None, impl: str = "auto"):
    """q/k/v: [B, T, H, D] (kv may have fewer heads — GQA broadcast here).

    impl: auto | plain | flash | ring | ulysses
    """
    if k.shape[2] != q.shape[2]:  # grouped-query: repeat kv heads
        rep = q.shape[2] // k.shape[2]
        k = jnp.repeat(k, rep, axis=2)
        v = jnp.repeat(v, rep, axis=2)

    if impl == "auto":
        if mesh is not None and seq_axis and mesh.shape.get(seq_axis, 1) > 1:
            impl = "ring"
        else:
            impl = _default_local_impl(q)

    if impl == "ring":
        from ray_tpu.parallel.ring_attention import ring_attention

        return ring_attention(q, k, v, mesh, axis_name=seq_axis or "sp",
                              causal=causal, sm_scale=sm_scale)
    if impl == "ulysses":
        from ray_tpu.parallel.ulysses import ulysses_attention

        return ulysses_attention(q, k, v, mesh, axis_name=seq_axis or "sp",
                                 causal=causal, sm_scale=sm_scale)
    if impl == "flash":
        from ray_tpu.ops.flash_attention import flash_attention

        return flash_attention(q, k, v, causal=causal, sm_scale=sm_scale)
    return reference_attention(q, k, v, causal=causal, sm_scale=sm_scale)


def _default_local_impl(q) -> str:
    from ray_tpu.utils.device import is_tpu

    B, T, H, D = q.shape
    if is_tpu() and T >= 1024 and T % 512 == 0 and D in (64, 128, 256):
        return "flash"
    return "plain"

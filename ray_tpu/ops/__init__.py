"""Core tensor ops: norms, rotary embeddings, attention dispatch, pallas kernels."""

from ray_tpu.ops.basic import rms_norm, rope, swiglu  # noqa: F401
from ray_tpu.ops.attention import attention  # noqa: F401

"""Elementwise / normalization building blocks.

Kept as plain jnp compositions on purpose: XLA fuses these into the
surrounding matmuls (SURVEY's HBM-bandwidth guidance); pallas is reserved
for ops XLA can't fuse well (attention softmax streaming — see
ops/flash_attention.py).
"""

from __future__ import annotations

import jax
import jax.numpy as jnp


def rms_norm(x, scale, eps: float = 1e-6):
    """RMSNorm with fp32 accumulation, output in input dtype."""
    x32 = x.astype(jnp.float32)
    var = jnp.mean(x32 * x32, axis=-1, keepdims=True)
    out = x32 * jax.lax.rsqrt(var + eps)
    return (out * scale).astype(x.dtype)


def rope_freqs(head_dim: int, max_len: int, theta: float = 10000.0):
    inv = 1.0 / (theta ** (jnp.arange(0, head_dim, 2, dtype=jnp.float32) / head_dim))
    t = jnp.arange(max_len, dtype=jnp.float32)
    freqs = jnp.outer(t, inv)  # [T, D/2]
    return jnp.cos(freqs), jnp.sin(freqs)


def rope(x, cos, sin, positions=None):
    """Rotary position embedding. x: [B, T, H, D]; cos/sin: [T_max, D/2]."""
    B, T, H, D = x.shape
    if positions is None:
        c = cos[:T][None, :, None, :]  # [1, T, 1, D/2]
        s = sin[:T][None, :, None, :]
    else:
        c = cos[positions][:, :, None, :]
        s = sin[positions][:, :, None, :]
    x1, x2 = jnp.split(x, 2, axis=-1)
    out = jnp.concatenate([x1 * c - x2 * s, x1 * s + x2 * c], axis=-1)
    return out.astype(x.dtype)


def swiglu(x, w_gate, w_up, w_down):
    """SwiGLU FFN: (silu(x@Wg) * (x@Wu)) @ Wd.

    The gate/up products carry a checkpoint name so remat policies can
    opt into saving them (they are the bulk of a block's recompute);
    inert unless a policy matches the name."""
    from jax.ad_checkpoint import checkpoint_name

    gate = checkpoint_name(x @ w_gate, "ffn_hidden")
    up = checkpoint_name(x @ w_up, "ffn_hidden")
    return (jax.nn.silu(gate) * up) @ w_down

"""Pallas TPU flash attention — forward AND backward kernels.

The hot op the MXU guidance calls for: blockwise streaming softmax so the
[T, T] score matrix never materializes in HBM (no in-tree reference
counterpart — SURVEY §5.7 confirms the reference outsources attention to
torch/vLLM; this is first-class TPU work).

Forward: grid (batch*heads, q_blocks, k_blocks) with the k axis innermost;
online-softmax accumulators (m, l, acc) live in VMEM scratch and survive
across k steps; the output block and the per-row logsumexp (residual for the
backward) are written once on the last k step. Causal masking skips whole
blocks above the diagonal via @pl.when.

Backward (FlashAttention-2 style, two kernels so each output is written by
exactly one grid cell):
  - dq kernel: grid (B*H, q_blocks, k_blocks), k innermost; recomputes
    p = exp(s - lse), ds = p * (dp - delta), accumulates dq in VMEM.
  - dkv kernel: grid (B*H, k_blocks, q_blocks), q innermost; accumulates
    dk and dv.
delta = rowsum(dO * O) is precomputed in plain XLA (cheap elementwise).

Exposed via jax.custom_vjp so jax.grad / value_and_grad see a real kernel on
both sides — no autodiff-through-pallas (which the TPU lowering rejects).
"""

from __future__ import annotations

import functools
import math
import os

import jax
import jax.numpy as jnp
from jax.experimental import pallas as pl

try:  # TPU backend bits are absent on CPU-only builds
    from jax.experimental.pallas import tpu as pltpu

    _VMEM = pltpu.VMEM
except Exception:  # pragma: no cover
    pltpu = None
    _VMEM = None

_NEG_BIG = -1e30
_LANES = 128


# ------------------------------------------------------------------ forward
def _fwd_kernel(q_ref, k_ref, v_ref, o_ref, lse_ref, m_scr, l_scr, acc_scr, *,
                sm_scale: float, causal: bool, block_q: int, block_k: int):
    kj = pl.program_id(2)
    qi = pl.program_id(1)
    nk = pl.num_programs(2)

    @pl.when(kj == 0)
    def _init():
        m_scr[...] = jnp.full_like(m_scr, _NEG_BIG)
        l_scr[...] = jnp.zeros_like(l_scr)
        acc_scr[...] = jnp.zeros_like(acc_scr)

    def _compute():
        q = q_ref[0]  # [block_q, D]
        k = k_ref[0]  # [block_k, D]
        v = v_ref[0]
        scores = jax.lax.dot_general(
            q, k, (((1,), (1,)), ((), ())), preferred_element_type=jnp.float32
        ) * sm_scale  # [block_q, block_k]
        if causal:
            rows = qi * block_q + jax.lax.broadcasted_iota(jnp.int32, scores.shape, 0)
            cols = kj * block_k + jax.lax.broadcasted_iota(jnp.int32, scores.shape, 1)
            mask = rows >= cols
            scores = jnp.where(mask, scores, _NEG_BIG)

        m_prev = m_scr[:, 0]  # [block_q]
        m_new = jnp.maximum(m_prev, scores.max(axis=1))
        p = jnp.exp(scores - m_new[:, None])
        if causal:
            p = jnp.where(mask, p, 0.0)
        correction = jnp.exp(m_prev - m_new)
        l_new = l_scr[:, 0] * correction + p.sum(axis=1)
        acc_scr[...] = acc_scr[...] * correction[:, None] + jax.lax.dot_general(
            p, v.astype(jnp.float32), (((1,), (0,)), ((), ())),
            preferred_element_type=jnp.float32,
        )
        m_scr[...] = jnp.broadcast_to(m_new[:, None], m_scr.shape)
        l_scr[...] = jnp.broadcast_to(l_new[:, None], l_scr.shape)

    if causal:
        # skip blocks strictly above the diagonal
        @pl.when(kj * block_k <= qi * block_q + (block_q - 1))
        def _():
            _compute()
    else:
        _compute()

    @pl.when(kj == nk - 1)
    def _finalize():
        denom = jnp.maximum(l_scr[:, 0], 1e-30)
        o_ref[0] = (acc_scr[...] / denom[:, None]).astype(o_ref.dtype)
        # lse broadcast across the 128 lanes: TPU blocks need a (8k, 128)-
        # divisible tail, so per-row scalars ride a full lane dim (same
        # layout jax's own tpu flash kernel uses for its l/m residuals)
        lse = m_scr[:, 0] + jnp.log(denom)
        lse_ref[0] = jnp.broadcast_to(lse[:, None], lse_ref[0].shape)


def _flash_forward(qb, kb, vb, *, causal, sm_scale, block_q, block_k, interpret):
    """qb/kb/vb: [BH, T, D] → (out [BH, T, D], lse [BH, T])."""
    BH, T, D = qb.shape
    Tk = kb.shape[1]
    grid = (BH, T // block_q, Tk // block_k)
    kernel = functools.partial(
        _fwd_kernel, sm_scale=sm_scale, causal=causal,
        block_q=block_q, block_k=block_k,
    )
    scratch = [
        _VMEM((block_q, _LANES), jnp.float32),
        _VMEM((block_q, _LANES), jnp.float32),
        _VMEM((block_q, D), jnp.float32),
    ]
    out, lse = pl.pallas_call(
        kernel,
        out_shape=(
            jax.ShapeDtypeStruct(qb.shape, qb.dtype),
            jax.ShapeDtypeStruct((BH, T, _LANES), jnp.float32),
        ),
        grid=grid,
        in_specs=[
            pl.BlockSpec((1, block_q, D), lambda b, i, j: (b, i, 0)),
            pl.BlockSpec((1, block_k, D), lambda b, i, j: (b, j, 0)),
            pl.BlockSpec((1, block_k, D), lambda b, i, j: (b, j, 0)),
        ],
        out_specs=(
            pl.BlockSpec((1, block_q, D), lambda b, i, j: (b, i, 0)),
            pl.BlockSpec((1, block_q, _LANES), lambda b, i, j: (b, i, 0)),
        ),
        scratch_shapes=scratch,
        interpret=interpret,
    )(qb, kb, vb)
    return out, lse


# ----------------------------------------------------------------- backward
def _bwd_dq_kernel(q_ref, k_ref, v_ref, do_ref, lse_ref, delta_ref, dq_ref,
                   dq_scr, *, sm_scale, causal, block_q, block_k):
    kj = pl.program_id(2)
    qi = pl.program_id(1)
    nk = pl.num_programs(2)

    @pl.when(kj == 0)
    def _init():
        dq_scr[...] = jnp.zeros_like(dq_scr)

    def _compute():
        q = q_ref[0]
        k = k_ref[0]
        v = v_ref[0]
        do = do_ref[0].astype(jnp.float32)
        lse = lse_ref[0][:, 0]  # [block_q] (lane-broadcast residual)
        delta = delta_ref[0][:, 0]  # [block_q]
        scores = jax.lax.dot_general(
            q, k, (((1,), (1,)), ((), ())), preferred_element_type=jnp.float32
        ) * sm_scale
        p = jnp.exp(scores - lse[:, None])  # [block_q, block_k]
        if causal:
            rows = qi * block_q + jax.lax.broadcasted_iota(jnp.int32, p.shape, 0)
            cols = kj * block_k + jax.lax.broadcasted_iota(jnp.int32, p.shape, 1)
            p = jnp.where(rows >= cols, p, 0.0)
        dp = jax.lax.dot_general(
            do, v.astype(jnp.float32), (((1,), (1,)), ((), ())),
            preferred_element_type=jnp.float32,
        )  # [block_q, block_k]
        ds = p * (dp - delta[:, None]) * sm_scale
        dq_scr[...] += jax.lax.dot_general(
            ds, k.astype(jnp.float32), (((1,), (0,)), ((), ())),
            preferred_element_type=jnp.float32,
        )

    if causal:
        @pl.when(kj * block_k <= qi * block_q + (block_q - 1))
        def _():
            _compute()
    else:
        _compute()

    @pl.when(kj == nk - 1)
    def _finalize():
        dq_ref[0] = dq_scr[...].astype(dq_ref.dtype)


def _bwd_dkv_kernel(q_ref, k_ref, v_ref, do_ref, lse_ref, delta_ref,
                    dk_ref, dv_ref, dk_scr, dv_scr, *,
                    sm_scale, causal, block_q, block_k):
    qi = pl.program_id(2)  # q innermost here
    kj = pl.program_id(1)
    nq = pl.num_programs(2)

    @pl.when(qi == 0)
    def _init():
        dk_scr[...] = jnp.zeros_like(dk_scr)
        dv_scr[...] = jnp.zeros_like(dv_scr)

    def _compute():
        q = q_ref[0]
        k = k_ref[0]
        v = v_ref[0]
        do = do_ref[0].astype(jnp.float32)
        lse = lse_ref[0][:, 0]
        delta = delta_ref[0][:, 0]
        # scores^T: [block_k, block_q]
        st = jax.lax.dot_general(
            k, q, (((1,), (1,)), ((), ())), preferred_element_type=jnp.float32
        ) * sm_scale
        pt = jnp.exp(st - lse[None, :])
        if causal:
            krows = kj * block_k + jax.lax.broadcasted_iota(jnp.int32, pt.shape, 0)
            qcols = qi * block_q + jax.lax.broadcasted_iota(jnp.int32, pt.shape, 1)
            pt = jnp.where(qcols >= krows, pt, 0.0)
        dv_scr[...] += jax.lax.dot_general(
            pt, do, (((1,), (0,)), ((), ())), preferred_element_type=jnp.float32
        )
        # dp^T = v @ do^T: [block_k, block_q]
        dpt = jax.lax.dot_general(
            v.astype(jnp.float32), do, (((1,), (1,)), ((), ())),
            preferred_element_type=jnp.float32,
        )
        dst = pt * (dpt - delta[None, :]) * sm_scale
        dk_scr[...] += jax.lax.dot_general(
            dst, q.astype(jnp.float32), (((1,), (0,)), ((), ())),
            preferred_element_type=jnp.float32,
        )

    if causal:
        # skip q blocks that end before this k block starts
        @pl.when(qi * block_q + (block_q - 1) >= kj * block_k)
        def _():
            _compute()
    else:
        _compute()

    @pl.when(qi == nq - 1)
    def _finalize():
        dk_ref[0] = dk_scr[...].astype(dk_ref.dtype)
        dv_ref[0] = dv_scr[...].astype(dv_ref.dtype)


def _flash_backward(qb, kb, vb, ob, lse, dob, *, causal, sm_scale, block_q,
                    block_k, interpret):
    BH, T, D = qb.shape
    Tk = kb.shape[1]
    delta = jnp.sum(dob.astype(jnp.float32) * ob.astype(jnp.float32), axis=-1)
    delta = jnp.broadcast_to(delta[..., None], (*delta.shape, _LANES))

    q_spec = pl.BlockSpec((1, block_q, D), lambda b, i, j: (b, i, 0))
    k_spec_for_dq = pl.BlockSpec((1, block_k, D), lambda b, i, j: (b, j, 0))
    row_spec = pl.BlockSpec((1, block_q, _LANES), lambda b, i, j: (b, i, 0))

    dq = pl.pallas_call(
        functools.partial(_bwd_dq_kernel, sm_scale=sm_scale, causal=causal,
                          block_q=block_q, block_k=block_k),
        out_shape=jax.ShapeDtypeStruct(qb.shape, qb.dtype),
        grid=(BH, T // block_q, Tk // block_k),
        in_specs=[q_spec, k_spec_for_dq, k_spec_for_dq, q_spec, row_spec, row_spec],
        out_specs=q_spec,
        scratch_shapes=[_VMEM((block_q, D), jnp.float32)],
        interpret=interpret,
    )(qb, kb, vb, dob, lse, delta)

    # dkv: grid is (BH, k_blocks, q_blocks) — q axis innermost
    q_spec2 = pl.BlockSpec((1, block_q, D), lambda b, j, i: (b, i, 0))
    k_spec2 = pl.BlockSpec((1, block_k, D), lambda b, j, i: (b, j, 0))
    row_spec2 = pl.BlockSpec((1, block_q, _LANES), lambda b, j, i: (b, i, 0))
    dk, dv = pl.pallas_call(
        functools.partial(_bwd_dkv_kernel, sm_scale=sm_scale, causal=causal,
                          block_q=block_q, block_k=block_k),
        out_shape=(
            jax.ShapeDtypeStruct(kb.shape, kb.dtype),
            jax.ShapeDtypeStruct(vb.shape, vb.dtype),
        ),
        grid=(BH, Tk // block_k, T // block_q),
        in_specs=[q_spec2, k_spec2, k_spec2, q_spec2, row_spec2, row_spec2],
        out_specs=(k_spec2, k_spec2),
        scratch_shapes=[
            _VMEM((block_k, D), jnp.float32),
            _VMEM((block_k, D), jnp.float32),
        ],
        interpret=interpret,
    )(qb, kb, vb, dob, lse, delta)
    return dq, dk, dv


# ------------------------------------------------------------ custom_vjp API
@functools.partial(jax.custom_vjp, nondiff_argnums=(3, 4, 5, 6, 7))
def _flash(qb, kb, vb, causal, sm_scale, block_q, block_k, interpret):
    out, _ = _flash_forward(
        qb, kb, vb, causal=causal, sm_scale=sm_scale,
        block_q=block_q, block_k=block_k, interpret=interpret,
    )
    return out


def _flash_fwd_rule(qb, kb, vb, causal, sm_scale, block_q, block_k, interpret):
    out, lse = _flash_forward(
        qb, kb, vb, causal=causal, sm_scale=sm_scale,
        block_q=block_q, block_k=block_k, interpret=interpret,
    )
    # Residuals carry the "attn_out" checkpoint name so the model's remat
    # policy can SAVE them: without this, rematerialized blocks re-run the
    # whole O(T^2) forward kernel just to regenerate lse — measured ~10
    # MFU points at 8k context. lse is saved in slim [BH, T] form (its
    # kernel layout is lane-broadcast x128) and re-broadcast in the bwd.
    from jax.ad_checkpoint import checkpoint_name

    out_r = checkpoint_name(out, "attn_out")
    lse_r = checkpoint_name(lse[:, :, 0], "attn_out")
    return out, (qb, kb, vb, out_r, lse_r)


def _flash_bwd_rule(causal, sm_scale, block_q, block_k, interpret, res, dout):
    qb, kb, vb, out, lse_slim = res
    lse = jnp.broadcast_to(lse_slim[..., None], (*lse_slim.shape, _LANES))
    dq, dk, dv = _flash_backward(
        qb, kb, vb, out, lse, dout, causal=causal, sm_scale=sm_scale,
        block_q=block_q, block_k=block_k, interpret=interpret,
    )
    return dq, dk, dv


_flash.defvjp(_flash_fwd_rule, _flash_bwd_rule)


# default tile sizes, env-overridable for block sweeps (RT_FLASH_BLOCK_Q/K,
# read at import time).
# r5 sweep on v5e, 551M model, T=8192 train step (MFU): 512/512 54.2,
# 512/1024 59.4, 1024/512 55.9, **1024/1024 61.7**; bk=2048 overflows
# VMEM. Bigger tiles amortize the online-softmax rescale + mask overhead
# over 4x the MXU work per grid cell. Full table in BENCHVS.md.
_BLOCK_Q = int(os.environ.get("RT_FLASH_BLOCK_Q", "1024"))
_BLOCK_K = int(os.environ.get("RT_FLASH_BLOCK_K", "1024"))


def flash_attention(q, k, v, *, causal: bool = True, sm_scale: float | None = None,
                    block_q: int | None = None, block_k: int | None = None,
                    interpret: bool | None = None):
    """q/k/v: [B, T, H, D] with equal head counts (GQA expanded upstream).

    Differentiable: backward runs the dedicated Pallas kernels above through
    jax.custom_vjp (autodiff through pallas_call is rejected by the TPU
    lowering, and a recompute-free bwd kernel is faster anyway)."""
    if _VMEM is None:
        raise RuntimeError("pallas TPU backend unavailable; use attn impl 'plain'")
    if sm_scale is None:
        sm_scale = 1.0 / math.sqrt(q.shape[-1])
    if interpret is None:
        from ray_tpu.utils.device import is_tpu

        interpret = not is_tpu()
    B, T, H, D = q.shape
    Tk = k.shape[1]
    # DEFAULTED blocks clamp then halve until they divide the sequence
    # (the auto dispatch admits any T % 512 == 0, so the 1024 default
    # degrades to 512 for T = 1536, 2560, ... instead of raising);
    # EXPLICIT blocks stay strict — a tile sweep must fail loudly on a
    # mismatched T, never silently record results under the wrong label
    def resolve(requested, default, n):
        if requested is not None:
            return requested  # strict: validated below
        b = min(default, n)
        while b > 128 and n % b:
            b //= 2
        return b

    block_q = resolve(block_q, _BLOCK_Q, T)
    block_k = resolve(block_k, _BLOCK_K, Tk)
    if T % block_q or Tk % block_k:
        raise ValueError(f"seq lens ({T},{Tk}) must divide blocks ({block_q},{block_k})")

    def to_bhtd(x):
        return x.transpose(0, 2, 1, 3).reshape(B * H, x.shape[1], D)

    out = _flash(to_bhtd(q), to_bhtd(k), to_bhtd(v), causal, float(sm_scale),
                 block_q, block_k, bool(interpret))
    return out.reshape(B, H, T, D).transpose(0, 2, 1, 3)

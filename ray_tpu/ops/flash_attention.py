"""Pallas TPU flash attention (forward).

The hot op the MXU guidance calls for: blockwise streaming softmax so the
[T, T] score matrix never materializes in HBM. Grid = (batch*heads,
q_blocks, k_blocks) with the k axis innermost; online-softmax accumulators
(m, l, acc) live in VMEM scratch and survive across k steps, the output
block is written once on the last k step. Causal masking skips the upper
triangle at block granularity via @pl.when.

Backward uses XLA autodiff over the reference implementation via
jax.custom_vjp residuals (a dedicated backward kernel is a later-round
optimization); training paths that shard the sequence use
parallel/ring_attention.py instead, which is already O(T/n) per chip.
"""

from __future__ import annotations

import functools
import math

import jax
import jax.numpy as jnp
from jax.experimental import pallas as pl

try:  # TPU backend bits are absent on CPU-only builds
    from jax.experimental.pallas import tpu as pltpu

    _VMEM = pltpu.VMEM
except Exception:  # pragma: no cover
    pltpu = None
    _VMEM = None

_NEG_BIG = -1e30
_LANES = 128


def _flash_kernel(q_ref, k_ref, v_ref, o_ref, m_scr, l_scr, acc_scr, *,
                  sm_scale: float, causal: bool, block_q: int, block_k: int):
    kj = pl.program_id(2)
    qi = pl.program_id(1)
    nk = pl.num_programs(2)

    @pl.when(kj == 0)
    def _init():
        m_scr[...] = jnp.full_like(m_scr, _NEG_BIG)
        l_scr[...] = jnp.zeros_like(l_scr)
        acc_scr[...] = jnp.zeros_like(acc_scr)

    def _compute():
        q = q_ref[0]  # [block_q, D]
        k = k_ref[0]  # [block_k, D]
        v = v_ref[0]
        scores = jax.lax.dot_general(
            q, k, (((1,), (1,)), ((), ())), preferred_element_type=jnp.float32
        ) * sm_scale  # [block_q, block_k]
        if causal:
            rows = qi * block_q + jax.lax.broadcasted_iota(jnp.int32, scores.shape, 0)
            cols = kj * block_k + jax.lax.broadcasted_iota(jnp.int32, scores.shape, 1)
            mask = rows >= cols
            scores = jnp.where(mask, scores, _NEG_BIG)

        m_prev = m_scr[:, 0]  # [block_q]
        m_new = jnp.maximum(m_prev, scores.max(axis=1))
        p = jnp.exp(scores - m_new[:, None])
        if causal:
            p = jnp.where(mask, p, 0.0)
        correction = jnp.exp(m_prev - m_new)
        l_new = l_scr[:, 0] * correction + p.sum(axis=1)
        acc_scr[...] = acc_scr[...] * correction[:, None] + jax.lax.dot_general(
            p, v.astype(jnp.float32), (((1,), (0,)), ((), ())),
            preferred_element_type=jnp.float32,
        )
        m_scr[...] = jnp.broadcast_to(m_new[:, None], m_scr.shape)
        l_scr[...] = jnp.broadcast_to(l_new[:, None], l_scr.shape)

    if causal:
        # skip blocks strictly above the diagonal
        @pl.when(kj * block_k <= qi * block_q + (block_q - 1))
        def _():
            _compute()
    else:
        _compute()

    @pl.when(kj == nk - 1)
    def _finalize():
        denom = jnp.maximum(l_scr[:, 0], 1e-30)
        o_ref[0] = (acc_scr[...] / denom[:, None]).astype(o_ref.dtype)


def _flash_forward(q, k, v, *, causal: bool, sm_scale: float, block_q: int,
                   block_k: int, interpret: bool):
    B, T, H, D = q.shape
    # layout: [B*H, T, D] so the head axis rides the grid
    def to_bhtd(x):
        return x.transpose(0, 2, 1, 3).reshape(B * H, x.shape[1], D)

    qb, kb, vb = to_bhtd(q), to_bhtd(k), to_bhtd(v)
    Tk = kb.shape[1]
    block_q = min(block_q, T)
    block_k = min(block_k, Tk)
    grid = (B * H, T // block_q, Tk // block_k)

    kernel = functools.partial(
        _flash_kernel, sm_scale=sm_scale, causal=causal,
        block_q=block_q, block_k=block_k,
    )
    if _VMEM is None:
        raise RuntimeError("pallas TPU backend unavailable")
    scratch = [
        _VMEM((block_q, _LANES), jnp.float32),
        _VMEM((block_q, _LANES), jnp.float32),
        _VMEM((block_q, D), jnp.float32),
    ]
    out = pl.pallas_call(
        kernel,
        out_shape=jax.ShapeDtypeStruct(qb.shape, q.dtype),
        grid=grid,
        in_specs=[
            pl.BlockSpec((1, block_q, D), lambda b, i, j: (b, i, 0)),
            pl.BlockSpec((1, block_k, D), lambda b, i, j: (b, j, 0)),
            pl.BlockSpec((1, block_k, D), lambda b, i, j: (b, j, 0)),
        ],
        out_specs=pl.BlockSpec((1, block_q, D), lambda b, i, j: (b, i, 0)),
        scratch_shapes=scratch,
        interpret=interpret,
    )(qb, kb, vb)
    return out.reshape(B, H, T, D).transpose(0, 2, 1, 3)


def flash_attention(q, k, v, *, causal: bool = True, sm_scale: float | None = None,
                    block_q: int = 512, block_k: int = 512,
                    interpret: bool | None = None):
    """q/k/v: [B, T, H, D] with equal head counts (GQA expanded upstream)."""
    if sm_scale is None:
        sm_scale = 1.0 / math.sqrt(q.shape[-1])
    if interpret is None:
        from ray_tpu.utils.device import is_tpu

        interpret = not is_tpu()
    return _flash_forward(
        q, k, v, causal=causal, sm_scale=sm_scale,
        block_q=block_q, block_k=block_k, interpret=interpret,
    )

"""Remote-driver client (the Ray Client role, ref: python/ray/util/client/
gRPC proxy + ARCHITECTURE.md).

A driver on a machine OUTSIDE the cluster connects with::

    import ray_tpu.client
    ctx = ray_tpu.client.connect("head-host:6379")
    ... ray_tpu.remote / get / put / actors as usual ...
    ctx.disconnect()

Architecture difference from the reference: no proxy process. The wire
protocol is already network-transparent (length-prefixed pickle RPC with a
version handshake), so the remote driver speaks directly to the GCS, the
head raylet, and its leased workers. What changes in client mode:

- no shm attach: objects the driver owns live in its in-process memory
  store and are owner-served to borrowers over RPC;
- shm-resident results (large task returns, borrowed large objects) are
  materialized through the raylet's chunked transfer RPCs (pull to the
  raylet arena, then stream);
- everything else (leases, actors, placement groups, collectives metadata)
  already rides RPC.

The driver must be network-reachable from cluster nodes (workers dial the
owner back for argument fetches), as with any multi-node deployment.
"""

from __future__ import annotations

from ray_tpu.core import api as _api


class ClientContext:
    """Handle for an active remote-driver connection."""

    def __init__(self, address: str):
        self.address = address
        self._connected = True

    def disconnect(self) -> None:
        if self._connected:
            _api.shutdown()
            self._connected = False

    def __enter__(self) -> "ClientContext":
        return self

    def __exit__(self, *exc) -> None:
        self.disconnect()


def connect(address: str, *, runtime_env: dict | None = None) -> ClientContext:
    """Attach this process to a remote cluster as a client-mode driver.

    ``address`` is the GCS address ("host:port"). Returns a ClientContext;
    use it as a context manager or call .disconnect().
    """
    _api.init(address, runtime_env=runtime_env, _client_mode=True)
    return ClientContext(address)

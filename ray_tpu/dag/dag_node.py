"""DAG authoring: bind actor methods into a static dataflow graph.

TPU-native equivalent of the reference's Compiled Graphs authoring surface
(ref: python/ray/dag/dag_node.py:265 experimental_compile entry,
dag/class_node.py ClassMethodNode, dag/input_node.py, dag/output_node.py).
The node graph is pure description — no execution happens until
``experimental_compile()`` turns it into per-actor static schedules over
shared-memory channels (see compiled_dag.py).

Design difference from the reference: no FunctionNode / per-call task DAGs —
the compiled path is the only path (the reference's dynamic DAG execute is
its classic task API, which we already have as plain tasks). Tensor
transport over ICI is expressed at the JAX level (the compiled loop runs
jitted SPMD steps), not as a channel type.
"""

from __future__ import annotations

from typing import Any


class DAGNode:
    """Base: a node in the authored graph."""

    def __init__(self, upstream: list["DAGNode"]):
        self.upstream = upstream

    def experimental_compile(self, *, buffer_size_bytes: int = 8 << 20,
                             timeout_s: float = 30.0, overlap: bool = True):
        from ray_tpu.dag.compiled_dag import CompiledDAG

        return CompiledDAG(self, buffer_size_bytes=buffer_size_bytes,
                           timeout_s=timeout_s, overlap=overlap)

    # -- traversal helpers ---------------------------------------------------
    def walk(self, seen: set | None = None):
        if seen is None:
            seen = set()
        if id(self) in seen:
            return
        seen.add(id(self))
        for up in self.upstream:
            yield from up.walk(seen)
        yield self


class InputNode(DAGNode):
    """The driver-fed input (ref: dag/input_node.py). Context manager so the
    authoring block reads naturally:

        with InputNode() as inp:
            x = actor_a.step.bind(inp)
            dag = actor_b.step.bind(x)
    """

    def __init__(self):
        super().__init__([])

    def __enter__(self):
        return self

    def __exit__(self, *exc):
        return False


class ClassMethodNode(DAGNode):
    """One actor-method invocation per iteration (ref: dag/class_node.py)."""

    def __init__(self, actor_handle, method_name: str, args: tuple):
        upstream = [a for a in args if isinstance(a, DAGNode)]
        super().__init__(upstream)
        self.actor_handle = actor_handle
        self.method_name = method_name
        self.args = args  # mix of DAGNode and static values


class MultiOutputNode(DAGNode):
    """Wraps N leaves so execute() returns a list (ref: dag/output_node.py)."""

    def __init__(self, outputs: list[DAGNode]):
        super().__init__(list(outputs))
        self.outputs = list(outputs)


class CollectiveNode(ClassMethodNode):
    """A collective op over one actor's iteration value (ref:
    dag/collective_node.py CollectiveOutputNode +
    experimental/collective/operations.py): every actor in ``group_name``
    binds its own CollectiveNode; at runtime each DAG loop calls the
    collective backend with its local value, and the backend's rendezvous
    synchronizes the group (XLA/ICI on TPU, the CPU fake in tests)."""

    def __init__(self, actor_handle, op: str, arg, group_name: str):
        super().__init__(actor_handle, f"__collective_{op}__", (arg,))
        self.op = op
        self.group_name = group_name


def allreduce_bind(inputs: list, group_name: str = "default") -> list:
    """Bind an allreduce over a set of per-actor DAG nodes (one per group
    member). Returns one CollectiveNode per input, each bound to that
    input's actor (ref: ray.experimental.collective.allreduce.bind)."""
    out = []
    for node in inputs:
        if not isinstance(node, ClassMethodNode):
            raise ValueError("allreduce_bind takes actor method nodes")
        out.append(CollectiveNode(node.actor_handle, "allreduce", node,
                                  group_name))
    return out


def bind(actor_handle, method_name: str, *args: Any) -> ClassMethodNode:
    return ClassMethodNode(actor_handle, method_name, args)

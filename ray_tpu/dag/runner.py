"""Worker-side compiled-DAG execution loop.

The per-actor static schedule executor (ref: compiled_dag_node.py
ExecutableTask :481 + the actor's _execute_until loop): runs on a dedicated
thread inside the actor's worker process, blocking on native channel
conditions (ctypes calls release the GIL), so the actor's normal RPC surface
stays live. Zero per-iteration task submissions — each iteration is
READ(chans) → COMPUTE(method) → WRITE(chan) straight against shared memory.

Overlap mode (the reference's READ/COMPUTE/WRITE op interleaving, ref:
dag/dag_node_operation.py:14 + dag_operation_future.py): channel READs run
one iteration AHEAD on a prefetch thread and WRITEs drain on a writer
thread, so a stage's blocking input wait + deserialize and its output's
backpressure wait ride UNDER the current compute instead of serializing
with it — the substrate pipeline-parallel serving needs.
"""

from __future__ import annotations

import queue
import threading

from ray_tpu.dag.channel import ChannelClosed, ShmChannel
from ray_tpu.utils.ids import ObjectID

_CLOSED = object()  # prefetch sentinel: upstream channel closed


def run_dag_loop(worker, schedule: dict) -> dict:
    store = worker.core.store
    chans: dict[bytes, ShmChannel] = {}

    def chan(cid: bytes) -> ShmChannel:
        c = chans.get(cid)
        if c is None:
            c = chans[cid] = ShmChannel(store, ObjectID(cid),
                                        size=schedule.get("chan_size", 8 << 20))
        return c

    tasks = schedule["tasks"]
    if schedule.get("overlap", True):
        return _run_overlapped(worker, tasks, chan, chans)
    return _run_sequential(worker, tasks, chan)


def _exec_task(worker, t, args):
    if t.get("collective"):
        # collective op node: the group's rendezvous synchronizes the
        # members (ref: dag/collective_node.py + aDAG allreduce);
        # XLA/ICI group on TPU, CPU fake in tests
        from ray_tpu.collective import collective as col

        fn = getattr(col, t["collective"])
        return fn(args[0], group_name=t["group"])
    method = getattr(worker.actor_instance, t["method"])
    return method(*args)


def _run_sequential(worker, tasks, chan) -> dict:
    iterations = 0
    try:
        while True:
            read_cache: dict[bytes, object] = {}  # one read per chan per iter
            local_vals: dict[int, object] = {}
            for t in tasks:
                args = []
                for kind, v in t["args"]:
                    if kind == "chan":
                        if v not in read_cache:
                            read_cache[v] = chan(v).read()
                        args.append(read_cache[v])
                    elif kind == "local":
                        args.append(local_vals[v])
                    else:  # static
                        args.append(v)
                out = _exec_task(worker, t, args)
                local_vals[t["node_index"]] = out
                if t["out_chan"] is not None:
                    chan(t["out_chan"]).write(out)
            iterations += 1
    except ChannelClosed:
        return {"iterations": iterations}


def _run_overlapped(worker, tasks, chan, chans) -> dict:
    """READ one iteration ahead + WRITE behind, COMPUTE in the middle.

    One prefetch thread walks the schedule's channel reads in order
    (preserving per-channel version order) and stages each iteration's
    read-set in a depth-1 queue; one writer thread drains a depth-1 queue
    of outputs. Depth 1 keeps the end-to-end backpressure contract: at
    most one iteration's values are buffered per stage beyond what the
    depth-1 channels themselves hold."""
    # channel ids each iteration reads, in schedule order (deduped)
    read_ids: list[bytes] = []
    for t in tasks:
        for kind, v in t["args"]:
            if kind == "chan" and v not in read_ids:
                read_ids.append(v)

    reads_q: queue.Queue = queue.Queue(maxsize=1)
    writes_q: queue.Queue = queue.Queue(maxsize=1)
    stop = threading.Event()

    def prefetch():
        try:
            while not stop.is_set():
                batch = {}
                for cid in read_ids:
                    batch[cid] = chan(cid).read()
                reads_q.put(batch)
        except ChannelClosed:
            reads_q.put(_CLOSED)
        except BaseException as e:  # noqa: BLE001 — surface on compute side
            reads_q.put(e)

    def drain_writes():
        try:
            while True:
                item = writes_q.get()
                if item is None:
                    return
                cid, value = item
                chan(cid).write(value)
        except BaseException as e:  # noqa: BLE001
            write_err.append(e)
            # keep draining so the compute side never deadlocks on put()
            while writes_q.get() is not None:
                pass

    write_err: list = []
    threads = []
    if read_ids:
        tr = threading.Thread(target=prefetch, name="rt-dag-read", daemon=True)
        tr.start()
        threads.append(tr)
    tw = threading.Thread(target=drain_writes, name="rt-dag-write", daemon=True)
    tw.start()

    iterations = 0
    try:
        while True:
            if read_ids:
                batch = reads_q.get()
                if batch is _CLOSED:
                    raise ChannelClosed("upstream")
                if isinstance(batch, BaseException):
                    raise batch
            else:
                batch = {}
            if write_err:
                raise write_err[0]
            local_vals: dict[int, object] = {}
            for t in tasks:
                args = []
                for kind, v in t["args"]:
                    if kind == "chan":
                        args.append(batch[v])
                    elif kind == "local":
                        args.append(local_vals[v])
                    else:  # static
                        args.append(v)
                out = _exec_task(worker, t, args)
                local_vals[t["node_index"]] = out
                if t["out_chan"] is not None:
                    writes_q.put((t["out_chan"], out))
            iterations += 1
    except ChannelClosed:
        return {"iterations": iterations}
    finally:
        stop.set()
        # close channels FIRST: unblocks a prefetch thread mid-read and a
        # writer thread stuck on backpressure (the driver's teardown close
        # already does this for the normal path; this covers error exits).
        # Only then enqueue the writer's stop sentinel — the queue may be
        # full until the unblocked writer drains it.
        for c in chans.values():
            try:
                c.close()
            except Exception:  # raylint: disable=RT012 — teardown best-effort: loops observe the closes that DID land
                pass
        # drain the read queue so a prefetch thread blocked in put()
        # (error exits leave staged batches behind) can run, observe the
        # closed channels and exit instead of leaking with its payloads
        for _ in range(3):
            try:
                reads_q.get(timeout=0.2)
            except queue.Empty:
                break
        writes_q.put(None)

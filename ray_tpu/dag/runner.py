"""Worker-side compiled-DAG execution loop.

The per-actor static schedule executor (ref: compiled_dag_node.py
ExecutableTask :481 + the actor's _execute_until loop): runs on a dedicated
thread inside the actor's worker process, blocking on native channel
conditions (ctypes calls release the GIL), so the actor's normal RPC surface
stays live. Zero per-iteration task submissions — each iteration is
READ(chans) → COMPUTE(method) → WRITE(chan) straight against shared memory.
"""

from __future__ import annotations

from ray_tpu.dag.channel import ChannelClosed, ShmChannel
from ray_tpu.utils.ids import ObjectID


def run_dag_loop(worker, schedule: dict) -> dict:
    store = worker.core.store
    chans: dict[bytes, ShmChannel] = {}

    def chan(cid: bytes) -> ShmChannel:
        c = chans.get(cid)
        if c is None:
            c = chans[cid] = ShmChannel(store, ObjectID(cid),
                                        size=schedule.get("chan_size", 8 << 20))
        return c

    tasks = schedule["tasks"]
    iterations = 0
    try:
        while True:
            read_cache: dict[bytes, object] = {}  # one read per chan per iter
            local_vals: dict[int, object] = {}
            for t in tasks:
                args = []
                for kind, v in t["args"]:
                    if kind == "chan":
                        if v not in read_cache:
                            read_cache[v] = chan(v).read()
                        args.append(read_cache[v])
                    elif kind == "local":
                        args.append(local_vals[v])
                    else:  # static
                        args.append(v)
                if t.get("collective"):
                    # collective op node: the group's rendezvous synchronizes
                    # the members (ref: dag/collective_node.py + aDAG
                    # allreduce); XLA/ICI group on TPU, CPU fake in tests
                    from ray_tpu.collective import collective as col

                    fn = getattr(col, t["collective"])
                    out = fn(args[0], group_name=t["group"])
                else:
                    method = getattr(worker.actor_instance, t["method"])
                    out = method(*args)
                local_vals[t["node_index"]] = out
                if t["out_chan"] is not None:
                    chan(t["out_chan"]).write(out)
            iterations += 1
    except ChannelClosed:
        return {"iterations": iterations}

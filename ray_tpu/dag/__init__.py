"""Compiled graphs (aDAG equivalent): static dataflow over actors on shm
channels (ref: python/ray/dag/ + python/ray/experimental/channel/)."""

from ray_tpu.dag.channel import ChannelClosed, ShmChannel
from ray_tpu.dag.compiled_dag import CompiledDAG, CompiledDAGRef
from ray_tpu.dag.dag_node import (
    ClassMethodNode,
    DAGNode,
    InputNode,
    MultiOutputNode,
    bind,
)

__all__ = [
    "ChannelClosed",
    "ShmChannel",
    "CompiledDAG",
    "CompiledDAGRef",
    "ClassMethodNode",
    "DAGNode",
    "InputNode",
    "MultiOutputNode",
    "bind",
]

"""Compiled graphs (aDAG equivalent): static dataflow over actors on shm
channels (ref: python/ray/dag/ + python/ray/experimental/channel/), with
cross-node channels mirrored over the raylet transfer plane and collective
nodes riding the collective backend."""

from ray_tpu.dag.channel import ChannelClosed, ShmChannel
from ray_tpu.dag.compiled_dag import (CompiledDAG, CompiledDAGFuture,
                                      CompiledDAGRef)
from ray_tpu.dag.dag_node import (
    ClassMethodNode,
    CollectiveNode,
    DAGNode,
    InputNode,
    MultiOutputNode,
    allreduce_bind,
    bind,
)

__all__ = [
    "ChannelClosed",
    "ShmChannel",
    "CompiledDAG",
    "CompiledDAGRef",
    "CompiledDAGFuture",
    "ClassMethodNode",
    "CollectiveNode",
    "DAGNode",
    "InputNode",
    "MultiOutputNode",
    "allreduce_bind",
    "bind",
]

"""Serialized-value wrapper over the native mutable shm channels.

The Python face of the C++ versioned 1-writer-N-reader channel cells in
store.cc (the reference's mutable-object protocol,
ref: src/ray/core_worker/experimental_mutable_object_manager.h:44,
python/ray/experimental/channel/shared_memory_channel.py:151).

One ShmChannel = one fixed-size cell; write() blocks until every reader of
the previous version released (depth-1 backpressure — exactly the reference's
default), read() blocks for the next version. Values are serialized with the
zero-copy pickle5 layout; on read the payload is copied out of the cell
before release so returned arrays never alias a buffer the writer is about
to overwrite.
"""

from __future__ import annotations

from ray_tpu.utils import serialization
from ray_tpu.utils.ids import ObjectID


class ChannelClosed(Exception):
    pass


class ShmChannel:
    def __init__(self, store, chan_id: ObjectID, *, size: int = 8 << 20,
                 num_readers: int = 1, create: bool = False):
        self.store = store
        self.chan_id = chan_id
        self.size = size
        if create:
            store.channel_create(chan_id, size, num_readers)
        self._last_read_version = 0

    def write(self, value, timeout_ms: int = -1) -> None:
        from ray_tpu.core.object_store import ChannelClosedError

        meta, buffers = serialization.dumps_with_buffers(value)
        need = serialization.total_size(meta, buffers)
        if need > self.size:
            raise ValueError(
                f"value of {need} bytes exceeds channel capacity {self.size}; "
                f"recompile with a larger buffer_size_bytes"
            )
        try:
            buf = self.store.channel_write_acquire(self.chan_id, timeout_ms)
            serialization.pack_into(meta, buffers, buf)
            self.store.channel_write_release(self.chan_id, need)
        except ChannelClosedError:
            raise ChannelClosed(str(self.chan_id)) from None

    def read(self, timeout_ms: int = -1):
        """Returns the next version's value (copies out of the cell)."""
        from ray_tpu.core.object_store import ChannelClosedError

        try:
            payload, version = self.store.channel_read_acquire(
                self.chan_id, self._last_read_version, timeout_ms
            )
            value = serialization.unpack(bytes(payload))
            self.store.channel_read_release(self.chan_id)
        except ChannelClosedError:
            raise ChannelClosed(str(self.chan_id)) from None
        self._last_read_version = version
        return value

    def close(self) -> None:
        self.store.channel_close(self.chan_id)

"""CompiledDAG: static per-actor schedules over shm channels.

The TPU-native equivalent of the reference's compiled graphs
(ref: python/ray/dag/compiled_dag_node.py:805 CompiledDAG._get_or_compile
:1542, execute :2536; dag/dag_node_operation.py:14 READ/COMPUTE/WRITE op
schedules). Compilation:

  1. walk the authored graph (topological — DFS postorder),
  2. allocate one native shm channel per cross-process edge
     (num_readers = #consumer processes; same-actor edges pass values
     in-process with no channel),
  3. ship each actor a static schedule [{read chans -> method -> write chan}]
     executed by a long-running loop (worker.rpc_start_dag_loop) — ZERO
     per-iteration task submissions, the reference's whole point,
  4. driver I/O: execute() writes the input channel, result refs read the
     leaf channels.

Single-node by design for now: channels live in the node's shm arena (the
reference's cross-node channel registration, core_worker.proto:577, is the
round-3+ extension; multi-host TPU pipelines run *inside* one jitted SPMD
program over the mesh instead — see parallel/pipeline.py).
"""

from __future__ import annotations

import threading
import time
from typing import Any

from ray_tpu.core import api
from ray_tpu.dag.channel import ChannelClosed, ShmChannel
from ray_tpu.dag.dag_node import (
    ClassMethodNode,
    DAGNode,
    InputNode,
    MultiOutputNode,
)
from ray_tpu.utils.ids import ObjectID


def _as_bytes(node_id) -> bytes:
    return node_id.binary() if hasattr(node_id, "binary") else bytes(node_id)


class CompiledDAGRef:
    """Future for one execute() iteration (ref: compiled_dag_ref.py:37)."""

    def __init__(self, dag: "CompiledDAG", version: int):
        self._dag = dag
        self._version = version
        self._value = None
        self._done = False

    def get(self, timeout: float | None = None):
        if not self._done:
            self._value = self._dag._read_output(self._version, timeout)
            self._done = True
        return self._value


class CompiledDAG:
    def __init__(self, root: DAGNode, *, buffer_size_bytes: int = 8 << 20,
                 timeout_s: float = 30.0):
        self.root = root
        self.buffer_size = buffer_size_bytes
        self.timeout_s = timeout_s
        self._compiled = False
        self._torn_down = False
        self._exec_version = 0
        self._read_version = 0
        self._read_lock = threading.Lock()
        self._loop_futures: list = []
        self._compile()

    # ------------------------------------------------------------- compile
    def _compile(self) -> None:
        core = api.get_core()
        nodes = list(self.root.walk())
        self.input_node = None
        for n in nodes:
            if isinstance(n, InputNode):
                if self.input_node is not None:
                    raise ValueError("compiled DAG supports exactly one InputNode")
                self.input_node = n
        if self.input_node is None:
            raise ValueError("DAG has no InputNode")
        if isinstance(self.root, MultiOutputNode):
            self.leaves = self.root.outputs
            body = [n for n in nodes if isinstance(n, ClassMethodNode)]
        else:
            if not isinstance(self.root, ClassMethodNode):
                raise ValueError("DAG root must be a ClassMethodNode or MultiOutputNode")
            self.leaves = [self.root]
            body = [n for n in nodes if isinstance(n, ClassMethodNode)]
        if not body:
            raise ValueError("DAG has no actor method nodes")

        # consumer processes per producer node ("driver" or actor_id bytes)
        consumers: dict[int, set] = {id(n): set() for n in nodes}
        for n in body:
            akey = n.actor_handle.actor_id.binary()
            for a in n.args:
                if isinstance(a, DAGNode):
                    consumers[id(a)].add(akey)
        for leaf in self.leaves:
            if not isinstance(leaf, ClassMethodNode):
                raise ValueError("DAG outputs must be actor method nodes")
            consumers[id(leaf)].add("driver")

        # verify all actors are on this node (shm channels are node-local)
        my_node = core.node_id.binary()
        for n in body:
            info = core._run_sync(
                core.gcs.call("get_actor", {"actor_id": n.actor_handle.actor_id})
            )
            if info is None:
                raise ValueError(f"actor {n.actor_handle.actor_id!r} not found")
            node_id = info.get("node_id")
            if node_id is not None and _as_bytes(node_id) != my_node:
                raise NotImplementedError(
                    "compiled DAGs currently require all actors on the "
                    "driver's node (shm channels; cross-node channels are the "
                    "DCN extension)"
                )

        store = core.store
        # one channel per node that has at least one *cross-process* consumer
        self.channels: dict[int, ShmChannel] = {}
        node_actor = {id(n): n.actor_handle.actor_id.binary() for n in body}

        def needs_channel(n) -> set:
            """Remote consumer set for node n (producers never read their own
            channel: same-actor edges are passed in-process)."""
            owner = node_actor.get(id(n), "driver")
            return {c for c in consumers[id(n)] if c != owner}

        for n in [self.input_node] + body:
            remote = needs_channel(n)
            if remote:
                cid = ObjectID.from_random()
                self.channels[id(n)] = ShmChannel(
                    store, cid, size=self.buffer_size,
                    num_readers=len(remote), create=True,
                )

        # build per-actor schedules in topo order
        node_index = {id(n): i for i, n in enumerate(nodes)}
        schedules: dict[bytes, list] = {}
        for n in body:
            akey = node_actor[id(n)]
            args_spec = []
            for a in n.args:
                if isinstance(a, DAGNode):
                    if node_actor.get(id(a)) == akey:
                        args_spec.append(("local", node_index[id(a)]))
                    else:
                        ch = self.channels[id(a)]
                        args_spec.append(("chan", ch.chan_id.binary()))
                else:
                    args_spec.append(("static", a))
            out = self.channels.get(id(n))
            schedules.setdefault(akey, []).append({
                "node_index": node_index[id(n)],
                "method": n.method_name,
                "args": args_spec,
                "out_chan": out.chan_id.binary() if out else None,
            })
        for sched in schedules.values():
            sched.sort(key=lambda t: t["node_index"])

        # start the per-actor loops (long-running RPC; replies on teardown)
        self.input_channel = self.channels[id(self.input_node)]
        self.leaf_channels = [self.channels[id(leaf)] for leaf in self.leaves]
        self._actor_handles = {node_actor[id(n)]: n.actor_handle for n in body}
        for akey, sched in schedules.items():
            handle = self._actor_handles[akey]
            fut = core.start_dag_loop(handle, {"tasks": sched,
                                               "chan_size": self.buffer_size})
            self._loop_futures.append(fut)
        # give loops a beat to attach to channels before first execute
        time.sleep(0.05)
        self._compiled = True

    # ------------------------------------------------------------- execute
    def execute(self, value: Any) -> CompiledDAGRef:
        if self._torn_down:
            raise RuntimeError("DAG was torn down")
        self.input_channel.write(value, timeout_ms=int(self.timeout_s * 1000))
        self._exec_version += 1
        return CompiledDAGRef(self, self._exec_version)

    def _read_output(self, version: int, timeout: float | None):
        deadline_ms = int((timeout or self.timeout_s) * 1000)
        with self._read_lock:
            if version != self._read_version + 1:
                raise RuntimeError(
                    "compiled DAG results must be read in execute order "
                    f"(asked v{version}, next is v{self._read_version + 1})"
                )
            vals = [ch.read(timeout_ms=deadline_ms) for ch in self.leaf_channels]
            self._read_version = version
        if isinstance(self.root, MultiOutputNode):
            return vals
        return vals[0]

    # ------------------------------------------------------------ teardown
    def teardown(self) -> None:
        if self._torn_down:
            return
        self._torn_down = True
        for ch in self.channels.values():
            try:
                ch.close()
            except Exception:
                pass
        # loops observe the close and reply; drain their results
        core = api.get_core()
        for fut in self._loop_futures:
            try:
                core.wait_dag_loop(fut, timeout=5.0)
            except Exception:
                pass

    def __del__(self):
        try:
            self.teardown()
        except Exception:
            pass

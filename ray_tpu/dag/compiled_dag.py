"""CompiledDAG: static per-actor schedules over shm channels.

The TPU-native equivalent of the reference's compiled graphs
(ref: python/ray/dag/compiled_dag_node.py:805 CompiledDAG._get_or_compile
:1542, execute :2536; dag/dag_node_operation.py:14 READ/COMPUTE/WRITE op
schedules). Compilation:

  1. walk the authored graph (topological — DFS postorder),
  2. allocate one native shm channel per cross-process edge
     (num_readers = #consumer processes; same-actor edges pass values
     in-process with no channel),
  3. ship each actor a static schedule [{read chans -> method -> write chan}]
     executed by a long-running loop (worker.rpc_start_dag_loop) — ZERO
     per-iteration task submissions, the reference's whole point,
  4. driver I/O: execute() writes the input channel, result refs read the
     leaf channels.

Cross-node DAGs: a channel's origin cell lives in the producer's node
arena; every remote reader node gets a local mirror cell, fed one push per
version by a raylet forwarder that releases the origin only after all
mirrors accepted — the reference's remote-reader registration
(ref: core_worker.proto:577 RegisterMutableObjectReader,
experimental_mutable_object_provider.cc), with end-to-end depth-1
backpressure preserved across the network. Multi-host TPU pipelines can
still run *inside* one jitted SPMD program over the mesh instead — see
parallel/pipeline.py.
"""

from __future__ import annotations

import threading
import time
from typing import Any

from ray_tpu.core import api
from ray_tpu.dag.channel import ChannelClosed, ShmChannel
from ray_tpu.dag.dag_node import (
    ClassMethodNode,
    CollectiveNode,
    DAGNode,
    InputNode,
    MultiOutputNode,
)
from ray_tpu.utils.ids import ObjectID


def _as_bytes(node_id) -> bytes:
    return node_id.binary() if hasattr(node_id, "binary") else bytes(node_id)


class CompiledDAGRef:
    """Future for one execute() iteration (ref: compiled_dag_ref.py:37)."""

    def __init__(self, dag: "CompiledDAG", version: int):
        self._dag = dag
        self._version = version
        self._value = None
        self._done = False

    def get(self, timeout: float | None = None):
        if not self._done:
            self._value = self._dag._read_output(self._version, timeout)
            self._done = True
        return self._value


class CompiledDAGFuture:
    """Awaitable result of one execute_async() iteration (ref:
    compiled_dag_ref.py:154 CompiledDAGFuture). The blocking channel read
    runs in a thread executor, so awaiting never stalls the event loop;
    like the reference, a future may only be awaited once (results must
    drain in execute order)."""

    def __init__(self, dag: "CompiledDAG", version: int):
        self._dag = dag
        self._version = version
        self._awaited = False

    def __await__(self):
        if self._awaited:
            raise RuntimeError(
                "CompiledDAGFuture can only be awaited once")
        self._awaited = True
        import asyncio

        loop = asyncio.get_event_loop()
        return loop.run_in_executor(
            None, self._dag._read_output, self._version, None).__await__()


class CompiledDAG:
    def __init__(self, root: DAGNode, *, buffer_size_bytes: int = 8 << 20,
                 timeout_s: float = 30.0, overlap: bool = True):
        self.root = root
        self.buffer_size = buffer_size_bytes
        self.timeout_s = timeout_s
        self.overlap = overlap  # READ/COMPUTE/WRITE interleave (runner.py)
        self._compiled = False
        self._torn_down = False
        self._exec_version = 0
        self._read_version = 0
        self._read_lock = threading.Lock()
        self._loop_futures: list = []
        self._compile()

    # ------------------------------------------------------------- compile
    def _compile(self) -> None:
        core = api.get_core()
        nodes = list(self.root.walk())
        self.input_node = None
        for n in nodes:
            if isinstance(n, InputNode):
                if self.input_node is not None:
                    raise ValueError("compiled DAG supports exactly one InputNode")
                self.input_node = n
        if self.input_node is None:
            raise ValueError("DAG has no InputNode")
        if isinstance(self.root, MultiOutputNode):
            self.leaves = self.root.outputs
            body = [n for n in nodes if isinstance(n, ClassMethodNode)]
        else:
            if not isinstance(self.root, ClassMethodNode):
                raise ValueError("DAG root must be a ClassMethodNode or MultiOutputNode")
            self.leaves = [self.root]
            body = [n for n in nodes if isinstance(n, ClassMethodNode)]
        if not body:
            raise ValueError("DAG has no actor method nodes")

        # consumer processes per producer node ("driver" or actor_id bytes)
        consumers: dict[int, set] = {id(n): set() for n in nodes}
        for n in body:
            akey = n.actor_handle.actor_id.binary()
            for a in n.args:
                if isinstance(a, DAGNode):
                    consumers[id(a)].add(akey)
        for leaf in self.leaves:
            if not isinstance(leaf, ClassMethodNode):
                raise ValueError("DAG outputs must be actor method nodes")
            consumers[id(leaf)].add("driver")

        # locate every participant: actors may live on ANY node — channels
        # get their origin cell on the producer's node and a mirror cell on
        # every remote reader node, fed by a raylet forwarder per version
        # (ref: core_worker.proto:577 RegisterMutableObjectReader,
        # experimental_mutable_object_provider.cc)
        my_node = core.node_id.binary()
        actor_node: dict[bytes, bytes] = {}  # actor_id -> node_id bytes
        for n in body:
            akey = n.actor_handle.actor_id.binary()
            if akey in actor_node:
                continue
            # channels are wired to the actor's NODE, so compile must know
            # real placements — wait briefly for pending creations instead
            # of silently guessing (a wrong guess wires cells to the wrong
            # arena and the loop's first read hangs)
            deadline = time.monotonic() + 30.0
            node_id = None
            while time.monotonic() < deadline:
                info = core._run_sync(
                    core.gcs.call("get_actor",
                                  {"actor_id": n.actor_handle.actor_id})
                )
                if info is None:
                    raise ValueError(
                        f"actor {n.actor_handle.actor_id!r} not found")
                node_id = info.get("node_id")
                if node_id is not None:
                    break
                time.sleep(0.1)
            if node_id is None:
                raise RuntimeError(
                    f"actor {n.actor_handle.actor_id!r} is not placed yet "
                    "(still PENDING_CREATION after 30s); compile after the "
                    "actor is running")
            actor_node[akey] = _as_bytes(node_id)
        # raylet address per node (for mirror creation + forwarder setup)
        cluster = core._run_sync(core.gcs.call("get_cluster", {}))
        node_addr = {_as_bytes(v["node_id"]): tuple(v["address"])
                     for v in cluster}

        def loc(consumer_key) -> bytes:
            return my_node if consumer_key == "driver" else actor_node[consumer_key]

        store = core.store
        if store is None:
            raise RuntimeError(
                "compiled DAGs need a local shm arena (not available in "
                "remote-client mode)")
        self.channels: dict[int, ShmChannel] = {}
        self._remote_cells: list[tuple[tuple, bytes]] = []  # (addr, chan_id)
        node_actor = {id(n): n.actor_handle.actor_id.binary() for n in body}

        def needs_channel(n) -> set:
            """Cross-process consumer set for node n (producers never read
            their own channel: same-actor edges are passed in-process)."""
            owner = node_actor.get(id(n), "driver")
            return {c for c in consumers[id(n)] if c != owner}

        _raylet_call = self._raylet_call

        for n in [self.input_node] + body:
            readers = needs_channel(n)
            if not readers:
                continue
            prod_node = loc(node_actor.get(id(n), "driver"))
            by_node: dict[bytes, int] = {}
            for c in readers:
                by_node[loc(c)] = by_node.get(loc(c), 0) + 1
            remote_nodes = [nid for nid in by_node if nid != prod_node]
            local_readers = by_node.get(prod_node, 0)
            cid = ObjectID.from_random()
            origin_readers = local_readers + (1 if remote_nodes else 0)
            # origin cell on the producer's node
            if prod_node == my_node:
                ch = ShmChannel(store, cid, size=self.buffer_size,
                                num_readers=origin_readers, create=True)
            else:
                core._run_sync(_raylet_call(
                    node_addr[prod_node], "channel_create",
                    {"chan_id": cid.binary(), "size": self.buffer_size,
                     "num_readers": origin_readers}))
                self._remote_cells.append((node_addr[prod_node], cid.binary()))
                ch = ShmChannel(store, cid, size=self.buffer_size,
                                num_readers=by_node.get(my_node, 0) or 1,
                                create=False)
            # mirror cells on every remote reader node + the forwarder
            if remote_nodes:
                for nid in remote_nodes:
                    if nid == my_node:
                        ShmChannel(store, cid, size=self.buffer_size,
                                   num_readers=by_node[nid], create=True)
                    else:
                        core._run_sync(_raylet_call(
                            node_addr[nid], "channel_create",
                            {"chan_id": cid.binary(), "size": self.buffer_size,
                             "num_readers": by_node[nid]}))
                        self._remote_cells.append((node_addr[nid], cid.binary()))
                core._run_sync(_raylet_call(
                    node_addr[prod_node], "channel_register_remote",
                    {"chan_id": cid.binary(),
                     "readers": [list(node_addr[nid]) for nid in remote_nodes]}))
            self.channels[id(n)] = ch

        # build per-actor schedules in topo order
        node_index = {id(n): i for i, n in enumerate(nodes)}
        schedules: dict[bytes, list] = {}
        for n in body:
            akey = node_actor[id(n)]
            args_spec = []
            for a in n.args:
                if isinstance(a, DAGNode):
                    if node_actor.get(id(a)) == akey:
                        args_spec.append(("local", node_index[id(a)]))
                    else:
                        ch = self.channels[id(a)]
                        args_spec.append(("chan", ch.chan_id.binary()))
                else:
                    args_spec.append(("static", a))
            out = self.channels.get(id(n))
            task = {
                "node_index": node_index[id(n)],
                "method": n.method_name,
                "args": args_spec,
                "out_chan": out.chan_id.binary() if out else None,
            }
            if isinstance(n, CollectiveNode):
                task["collective"] = n.op
                task["group"] = n.group_name
            schedules.setdefault(akey, []).append(task)
        for sched in schedules.values():
            sched.sort(key=lambda t: t["node_index"])

        # Overlap safety per actor: the prefetch thread reads ALL of an
        # iteration's channels before any compute, so it deadlocks if one
        # of an actor's channel reads transitively depends on a node the
        # SAME actor executes this iteration (a -> b -> a shapes). Those
        # actors fall back to the lazy sequential schedule.
        deps: dict[int, set] = {}

        def transitive_actors(n) -> set:
            got = deps.get(id(n))
            if got is not None:
                return got
            acc: set = set()
            if id(n) in node_actor:
                acc.add(node_actor[id(n)])
            for a in getattr(n, "args", ()):  # InputNode has no args
                if isinstance(a, DAGNode):
                    acc |= transitive_actors(a)
            deps[id(n)] = acc
            return acc

        overlap_ok: dict[bytes, bool] = {}
        for n in body:
            akey = node_actor[id(n)]
            for a in n.args:
                if (isinstance(a, DAGNode)
                        and node_actor.get(id(a)) != akey
                        and akey in transitive_actors(a)):
                    overlap_ok[akey] = False
            overlap_ok.setdefault(akey, True)

        # start the per-actor loops (long-running RPC; replies on teardown)
        self.input_channel = self.channels[id(self.input_node)]
        self.leaf_channels = [self.channels[id(leaf)] for leaf in self.leaves]
        self._actor_handles = {node_actor[id(n)]: n.actor_handle for n in body}
        for akey, sched in schedules.items():
            handle = self._actor_handles[akey]
            fut = core.start_dag_loop(handle, {
                "tasks": sched,
                "chan_size": self.buffer_size,
                "overlap": self.overlap and overlap_ok.get(akey, True),
            })
            self._loop_futures.append(fut)
        # give loops a beat to attach to channels before first execute
        time.sleep(0.05)
        self._compiled = True

    @staticmethod
    async def _raylet_call(addr, method, payload):
        """One RPC to a raylet, reusing the persistent connection when it's
        the driver's own."""
        core = api.get_core()
        if tuple(addr) == tuple(core.raylet_address):
            return await core.raylet.call(method, payload)
        from ray_tpu.utils import rpc as _rpc

        c = await _rpc.connect(*addr, timeout=10)
        try:
            return await c.call(method, payload, timeout=30)
        finally:
            await c.close()

    # ------------------------------------------------------------- execute
    def execute(self, value: Any) -> CompiledDAGRef:
        if self._torn_down:
            raise RuntimeError("DAG was torn down")
        self.input_channel.write(value, timeout_ms=int(self.timeout_s * 1000))
        self._exec_version += 1
        return CompiledDAGRef(self, self._exec_version)

    async def execute_async(self, value: Any) -> CompiledDAGFuture:
        """Async twin of execute() (ref: compiled_dag_node.py:2617
        execute_async): the input write (which can block on channel
        backpressure) runs in a thread executor, and the returned future
        is awaited — not .get()ed — for the result."""
        if self._torn_down:
            raise RuntimeError("DAG was torn down")
        import asyncio

        loop = asyncio.get_event_loop()
        await loop.run_in_executor(
            None, self.input_channel.write, value,
            int(self.timeout_s * 1000))
        self._exec_version += 1
        return CompiledDAGFuture(self, self._exec_version)

    def _read_output(self, version: int, timeout: float | None):
        deadline_ms = int((timeout or self.timeout_s) * 1000)
        with self._read_lock:
            if version != self._read_version + 1:
                raise RuntimeError(
                    "compiled DAG results must be read in execute order "
                    f"(asked v{version}, next is v{self._read_version + 1})"
                )
            vals = [ch.read(timeout_ms=deadline_ms) for ch in self.leaf_channels]
            self._read_version = version
        if isinstance(self.root, MultiOutputNode):
            return vals
        return vals[0]

    # ------------------------------------------------------------ teardown
    def teardown(self) -> None:
        if self._torn_down:
            return
        self._torn_down = True
        for ch in self.channels.values():
            try:
                ch.close()
            except Exception:  # raylint: disable=RT012 — teardown best-effort: remaining cells close below
                pass
        core = api.get_core()
        # close origin/mirror cells living on other nodes, concurrently
        # (forwarders see the close and propagate it)
        cells = getattr(self, "_remote_cells", [])
        if cells:
            async def _close_all():
                import asyncio as _a

                await _a.gather(*[
                    self._raylet_call(addr, "channel_close", {"chan_id": cid})
                    for addr, cid in cells
                ], return_exceptions=True)

            try:
                core._run_sync(_close_all())
            except Exception:  # raylint: disable=RT012 — mirror nodes may already be dead
                pass
        # loops observe the close and reply; drain their results
        for fut in self._loop_futures:
            try:
                core.wait_dag_loop(fut, timeout=5.0)
            except Exception:  # raylint: disable=RT012 — loop workers may have died with their channels
                pass

    def __del__(self):
        try:
            self.teardown()
        except Exception:  # raylint: disable=RT012 — __del__ may run at interpreter exit
            pass

"""Batch LLM inference over ray_tpu.data datasets.

TPU-native counterpart of the reference's Data-LLM processors (ref:
python/ray/llm/_internal/batch/processor/ — vllm/sglang engine
processors built on Ray Data map_batches). Here the engine is the
jit-compiled KV-cache generate; the dataset pipeline streams batches
through it with bounded in-flight work.
"""
from __future__ import annotations

from typing import Any, Callable


def build_llm_processor(model_config, *, params=None, batch_size: int = 8,
                        max_new_tokens: int = 32, temperature: float = 0.0,
                        input_column: str = "prompt_tokens",
                        output_column: str = "completion_tokens") -> Callable:
    """Returns dataset -> dataset applying batched generation
    (ref: batch/processor/ Processor.__call__)."""

    def apply(dataset):
        # Engine state (params + compiled fns) lives per worker process;
        # closure-captured params ship once via the object store.
        def infer_batch(batch: dict[str, Any]) -> dict[str, Any]:
            import jax

            from ray_tpu.llm.generation import generate
            from ray_tpu.models.llama import llama_init

            p = params
            if p is None:
                p = _cached_params(model_config)
            prompts = [list(map(int, row)) for row in batch[input_column]]
            outs = generate(p, model_config, prompts,
                            max_new_tokens=max_new_tokens,
                            temperature=temperature)
            out = dict(batch)
            out[output_column] = outs
            return out

        return dataset.map_batches(infer_batch, batch_size=batch_size)

    return apply


_param_cache: dict = {}


def _cached_params(cfg):
    """Random-init weights once per worker (testing / benchmarking path;
    real checkpoints arrive via the params argument)."""
    key = cfg  # LlamaConfig is a frozen (hashable) dataclass
    if key not in _param_cache:
        import jax

        from ray_tpu.models.llama import llama_init

        _param_cache[key] = llama_init(jax.random.PRNGKey(0), cfg)
    return _param_cache[key]

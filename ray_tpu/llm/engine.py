"""Continuous-batching LLM engine with a paged KV cache and LoRA multiplex.

TPU-native counterpart of the reference's delegated vLLM engine (ref:
python/ray/llm/_internal/serve/deployments/llm/vllm/vllm_engine.py:95 —
there Ray wires vLLM; here the engine is owned). Design maps the vLLM
ideas onto XLA's static-shape world:

* **Fixed decode slots.** One jitted decode step advances ALL ``max_batch``
  slots every iteration; inactive slots are masked. Admission = writing a
  new request's prompt KV into a free slot's pages *between* decode steps
  — a request never waits for the running batch to drain (continuous
  batching at decode-step granularity).
* **Paged KV.** One global pool ``[layers, n_pages, page_size, kv, hd]``;
  each slot owns a page table. Decode gathers the slot's pages for
  attention; prefill scatters prompt KV into freshly allocated pages.
  Shapes never depend on sequence length, so XLA compiles exactly one
  decode program (plus one prefill program per prompt-length bucket).
* **Streaming.** Every request gets an asyncio queue; tokens land there
  the step they are sampled.
* **LoRA multiplex** (ref: serve/multiplex.py): stacked low-rank adapters
  on the q/v projections, selected per slot — different requests in one
  decode batch can use different adapters (adapter 0 = base model).
"""
from __future__ import annotations

import asyncio
import collections
import itertools
import time
from dataclasses import dataclass, field
from functools import partial

import jax
import jax.numpy as jnp
import numpy as np

from ray_tpu.models.llama import LlamaConfig
from ray_tpu.ops.basic import rms_norm, rope, rope_freqs


def _lora_delta(h, loras, name, aid):
    """Per-slot low-rank delta: h[B,T,D] x A[aid][D,r] x Bm[aid][r,O]."""
    if loras is None:
        return 0.0
    a = loras[name + "_a"][aid]  # [B, D, r]
    b = loras[name + "_b"][aid]  # [B, r, O]
    return jnp.einsum("btd,bdr->btr", h, a) @ b if a.ndim == 3 else (h @ a) @ b


# shared with the static-batch path — one implementation of the numerics
from ray_tpu.llm.generation import _ffn, _gqa_attn  # noqa: E402


@partial(jax.jit, static_argnames=("cfg",), donate_argnums=(5, 6))
def paged_prefill(params, loras, aid, tokens, pages, kpool, vpool,
                  true_len, cfg: LlamaConfig):
    """Process one request's prompt; scatter its KV into ``pages``.

    tokens: [1, Tp] RIGHT-padded prompt; true_len: scalar real length;
    pages: [n] pool page indices covering Tp (Tp = n * page_size).
    Returns (last-real-position logits [V], kpool, vpool)."""
    B, Tp = tokens.shape
    L, P, PS, KV, hd = kpool.shape
    cos, sin = rope_freqs(cfg.head_dim, cfg.max_seq_len, cfg.rope_theta)
    positions = jnp.arange(Tp)[None, :]
    idx = jnp.arange(Tp)
    mask = idx[None, :, None] >= idx[None, None, :]  # causal

    row = pages[idx // PS]  # pool row per prompt position
    off = idx % PS
    x = params["tok"]["embedding"][tokens]
    for i in range(cfg.n_layers):
        layer = params[f"layers_{i}"]
        h = rms_norm(x, layer["attn_norm"]["scale"])
        q = (h @ layer["wq"]["kernel"] + _lora_delta(h, loras, "wq", aid)
             ).reshape(B, Tp, cfg.n_heads, hd)
        k = (h @ layer["wk"]["kernel"]).reshape(B, Tp, KV, hd)
        v = (h @ layer["wv"]["kernel"] + _lora_delta(h, loras, "wv", aid)
             ).reshape(B, Tp, KV, hd)
        q = rope(q, cos, sin, positions)
        k = rope(k, cos, sin, positions)
        kpool = kpool.at[i, row, off].set(k[0])
        vpool = vpool.at[i, row, off].set(v[0])
        att = _gqa_attn(q, k, v, mask)
        x = x + att.reshape(B, Tp, -1) @ layer["wo"]["kernel"]
        x = _ffn(layer, x)
    x = rms_norm(x, params["norm"]["scale"])
    logits = x[0, true_len - 1] @ params["lm_head"]["kernel"]
    return logits, kpool, vpool


@partial(jax.jit, static_argnames=("cfg",), donate_argnums=(6, 7))
def paged_decode_step(params, loras, aids, tokens, seq_lens, page_tables,
                      kpool, vpool, active, temps, key, cfg: LlamaConfig):
    """One decode step for every slot (masked where inactive).

    tokens: [B] current input token; seq_lens: [B] tokens already cached
    (the new token lands at that position); page_tables: [B, MAXP];
    aids: [B] adapter ids; temps: [B]. Returns (next_tok [B], kpool, vpool).
    """
    B = tokens.shape[0]
    L, P, PS, KV, hd = kpool.shape
    MAXP = page_tables.shape[1]
    cos, sin = rope_freqs(cfg.head_dim, cfg.max_seq_len, cfg.rope_theta)
    pos = seq_lens
    positions = pos[:, None]
    row = jnp.take_along_axis(page_tables, (pos // PS)[:, None], axis=1)[:, 0]
    off = pos % PS
    key_idx = jnp.arange(MAXP * PS)
    mask = key_idx[None, None, :] <= pos[:, None, None]

    x = params["tok"]["embedding"][tokens][:, None, :]
    for i in range(cfg.n_layers):
        layer = params[f"layers_{i}"]
        h = rms_norm(x, layer["attn_norm"]["scale"])
        q = (h @ layer["wq"]["kernel"] + _lora_delta(h, loras, "wq", aids)
             ).reshape(B, 1, cfg.n_heads, hd)
        k = (h @ layer["wk"]["kernel"]).reshape(B, 1, KV, hd)
        v = (h @ layer["wv"]["kernel"] + _lora_delta(h, loras, "wv", aids)
             ).reshape(B, 1, KV, hd)
        q = rope(q, cos, sin, positions)
        k = rope(k, cos, sin, positions)
        kpool = kpool.at[i, row, off].set(k[:, 0])
        vpool = vpool.at[i, row, off].set(v[:, 0])
        kb = kpool[i][page_tables].reshape(B, MAXP * PS, KV, hd)
        vb = vpool[i][page_tables].reshape(B, MAXP * PS, KV, hd)
        att = _gqa_attn(q, kb, vb, mask)
        x = x + att.reshape(B, 1, -1) @ layer["wo"]["kernel"]
        x = _ffn(layer, x)
    x = rms_norm(x, params["norm"]["scale"])
    logits = x[:, 0] @ params["lm_head"]["kernel"]

    greedy = jnp.argmax(logits, axis=-1).astype(jnp.int32)
    sampled = jax.random.categorical(
        key, logits / jnp.maximum(temps, 1e-6)[:, None]).astype(jnp.int32)
    next_tok = jnp.where(temps > 0, sampled, greedy)
    return jnp.where(active, next_tok, 0), kpool, vpool


def make_lora_stack(cfg: LlamaConfig, adapters: dict[str, dict], rank: int):
    """Stack named adapters into gatherable arrays. Index 0 is the base
    model (zero delta). adapters: name -> {"wq_a": [D,r], "wq_b": [r,O],
    "wv_a": ..., "wv_b": ...}. Returns (stack dict, name->index map)."""
    D = cfg.d_model
    O_q = cfg.n_heads * cfg.head_dim
    O_v = cfg.n_kv_heads * cfg.head_dim
    names = ["__base__"] + sorted(adapters)
    idx = {n: i for i, n in enumerate(names)}
    stack = {
        "wq_a": np.zeros((len(names), D, rank), np.float32),
        "wq_b": np.zeros((len(names), rank, O_q), np.float32),
        "wv_a": np.zeros((len(names), D, rank), np.float32),
        "wv_b": np.zeros((len(names), rank, O_v), np.float32),
    }
    for name, ad in adapters.items():
        i = idx[name]
        for k in stack:
            if k in ad:
                stack[k][i] = np.asarray(ad[k], np.float32)
    return {k: jnp.asarray(v) for k, v in stack.items()}, idx


@dataclass
class _Request:
    req_id: int
    prompt: list[int]
    max_tokens: int
    temperature: float
    adapter: int
    out: asyncio.Queue = field(default_factory=asyncio.Queue)
    slot: int = -1
    emitted: int = 0
    cancelled: bool = False
    finished: bool = False  # completed normally (max_tokens or eos)


class EngineFull(Exception):
    """No free slot/pages and the waiting queue is at capacity."""


class ContinuousBatchingEngine:
    """Single-process engine; drive with ``await engine.start()`` then
    ``submit`` / ``stream`` from the same event loop."""

    def __init__(self, params, cfg: LlamaConfig, *, max_batch: int = 8,
                 page_size: int = 16, n_pages: int = 256,
                 max_seq_len: int = 512, eos_id: int | None = None,
                 lora_adapters: dict[str, dict] | None = None,
                 lora_rank: int = 8, max_waiting: int = 256):
        self.params = params
        self.cfg = cfg
        self.B = max_batch
        self.PS = page_size
        self.MAXP = -(-max_seq_len // page_size)
        self.eos_id = eos_id
        self.max_waiting = max_waiting
        dtype = jnp.dtype(cfg.dtype)
        self.kpool = jnp.zeros(
            (cfg.n_layers, n_pages, page_size, cfg.n_kv_heads, cfg.head_dim),
            dtype)
        self.vpool = jnp.zeros_like(self.kpool)
        self.n_pages = n_pages
        self.free_pages = list(range(1, n_pages))  # page 0 = junk page
        self.loras = None
        self.lora_index = {"__base__": 0}
        if lora_adapters:
            self.loras, self.lora_index = make_lora_stack(
                cfg, lora_adapters, lora_rank)
        # slot state (host side)
        self.slot_req: list[_Request | None] = [None] * self.B
        self.page_tables = np.zeros((self.B, self.MAXP), np.int32)
        self.seq_lens = np.zeros(self.B, np.int32)
        self.next_tok = np.zeros(self.B, np.int32)
        self.temps = np.zeros(self.B, np.float32)
        self.aids = np.zeros(self.B, np.int32)
        self.waiting: list[_Request] = []
        self._req_ids = itertools.count(1)
        self._reqs: dict[int, _Request] = {}
        # finished requests not yet drained by a stream() consumer; bounded
        # LRU so fire-and-forget submitters can't leak token queues forever
        self._done: collections.OrderedDict[int, _Request] = (
            collections.OrderedDict())
        self._done_cap = 4 * self.B + max_waiting
        self._wake = asyncio.Event()
        self._running = False
        self._task = None
        self._rng = jax.random.PRNGKey(0)
        self.error: BaseException | None = None  # fatal loop failure
        # counters for benchmarks / tests
        self.steps = 0
        self.tokens_out = 0

    # ----------------------------------------------------------- public API
    async def start(self):
        if self._task is None:
            self._running = True
            self._task = asyncio.get_running_loop().create_task(self._loop())

    async def stop(self):
        self._running = False
        self._wake.set()
        if self._task is not None:
            await self._task
            self._task = None
        # nothing will produce more tokens: unblock every live consumer
        self._terminate_all_streams()

    def _terminate_all_streams(self):
        for req in list(self._reqs.values()):
            req.out.put_nowait(None)
        self._reqs.clear()
        self._done.clear()
        self.waiting.clear()
        self.slot_req = [None] * self.B

    def submit(self, prompt_tokens: list[int], *, max_tokens: int = 32,
               temperature: float = 0.0, adapter: str | None = None) -> int:
        """Queue a request; returns its id. Tokens arrive on stream()."""
        if self.error is not None:
            raise RuntimeError("engine loop died") from self.error
        if len(self.waiting) >= self.max_waiting:
            raise EngineFull(f"{len(self.waiting)} requests already waiting")
        if len(prompt_tokens) + max_tokens > self.MAXP * self.PS:
            raise ValueError(
                f"prompt ({len(prompt_tokens)}) + max_tokens ({max_tokens}) "
                f"exceeds the engine's max_seq_len ({self.MAXP * self.PS})")
        n_need = -(-(len(prompt_tokens) + max_tokens) // self.PS)
        if n_need > self.n_pages - 1:
            raise ValueError(
                f"request needs {n_need} KV pages but the pool only has "
                f"{self.n_pages - 1}")
        aid = self.lora_index.get(adapter or "__base__")
        if aid is None:
            raise ValueError(f"unknown LoRA adapter {adapter!r} "
                             f"(loaded: {sorted(self.lora_index)})")
        req = _Request(next(self._req_ids), list(prompt_tokens),
                       int(max_tokens), float(temperature), aid)
        self._reqs[req.req_id] = req
        self.waiting.append(req)
        self._wake.set()
        return req.req_id

    async def stream(self, req_id: int):
        """Async iterator of generated token ids for one request. Raises
        if the engine died before the request finished. The request stays
        registered until its consumer drains the terminal None here — a
        caller may finish awaiting something else before streaming and the
        already-queued tokens must still be reachable."""
        req = self._reqs.get(req_id)
        if req is None:
            req = self._done[req_id]
        try:
            while True:
                item = await req.out.get()
                if item is None:
                    if self.error is not None and not req.finished:
                        raise RuntimeError("engine loop died") from self.error
                    break
                yield item
        finally:
            # only unregister finished requests: a consumer erroring out
            # mid-stream must not make cancel() a no-op on a live request
            self._done.pop(req_id, None)

    async def generate(self, prompt_tokens: list[int], **kw) -> list[int]:
        rid = self.submit(prompt_tokens, **kw)
        return [t async for t in self.stream(rid)]

    def cancel(self, req_id: int):
        req = self._reqs.get(req_id)
        if req is not None:
            req.cancelled = True
            self._wake.set()

    # ------------------------------------------------------------ internals
    def _alloc_pages(self, n: int) -> list[int] | None:
        if len(self.free_pages) < n:
            return None
        out = self.free_pages[:n]
        del self.free_pages[:n]
        return out

    def _free_slot(self, slot: int):
        req = self.slot_req[slot]
        self.slot_req[slot] = None
        # the table holds ALL pages allocated at admission (prompt +
        # max_tokens worth), not just the ones reached — free every entry
        self.free_pages.extend(
            int(p) for p in self.page_tables[slot] if p != 0)
        self.page_tables[slot, :] = 0
        self.seq_lens[slot] = 0
        if req is not None:
            # move live → finished-awaiting-drain: stream() can still reach
            # the queued tokens, cancel() only sees live requests, and the
            # bounded _done map caps leakage from never-streamed submits
            self._reqs.pop(req.req_id, None)
            self._done[req.req_id] = req
            while len(self._done) > self._done_cap:
                self._done.popitem(last=False)
            req.out.put_nowait(None)

    def _admit(self, req: _Request) -> bool:
        """Prefill one waiting request into a free slot (between decode
        steps — the running batch never drains first)."""
        slot = next((i for i, r in enumerate(self.slot_req) if r is None), -1)
        if slot < 0:
            return False
        Tp = len(req.prompt)
        n_need = -(-(Tp + req.max_tokens) // self.PS)
        pages = self._alloc_pages(n_need)
        if pages is None:
            return False
        # pad the prompt to a page multiple (one prefill compile per bucket)
        Tp_pad = -(-Tp // self.PS) * self.PS
        toks = np.zeros((1, Tp_pad), np.int32)
        toks[0, :Tp] = req.prompt
        n_prompt_pages = Tp_pad // self.PS
        logits, self.kpool, self.vpool = paged_prefill(
            self.params, self.loras, jnp.int32(req.adapter),
            jnp.asarray(toks), jnp.asarray(pages[:n_prompt_pages], jnp.int32),
            self.kpool, self.vpool, jnp.int32(Tp), self.cfg)
        if req.temperature > 0:
            self._rng, sub = jax.random.split(self._rng)
            tok = int(jax.random.categorical(
                sub, logits / max(req.temperature, 1e-6)))
        else:
            tok = int(jnp.argmax(logits))
        req.slot = slot
        self.slot_req[slot] = req
        self.page_tables[slot, :] = 0
        self.page_tables[slot, :n_need] = pages
        self.seq_lens[slot] = Tp
        self.next_tok[slot] = tok
        self.temps[slot] = req.temperature
        self.aids[slot] = req.adapter
        self._emit(req, tok)
        return True

    def _emit(self, req: _Request, tok: int):
        req.emitted += 1
        self.tokens_out += 1
        req.out.put_nowait(tok)
        if req.emitted >= req.max_tokens or (
                self.eos_id is not None and tok == self.eos_id):
            req.finished = True
            req.cancelled = True  # finished: reclaim on the next sweep

    async def _loop(self):
        """Engine driver. Any exception here is fatal for the engine:
        record it, fail every live stream, and exit — hung consumers on a
        silently dead loop are the worst failure mode."""
        try:
            await self._loop_inner()
        except BaseException as e:  # noqa: BLE001
            self.error = e
            self._running = False
            self._terminate_all_streams()
            import traceback

            traceback.print_exc()

    async def _loop_inner(self):
        while self._running:
            # reclaim finished/cancelled slots, then admit as many waiting
            # requests as capacity allows
            for i, req in enumerate(self.slot_req):
                if req is not None and req.cancelled:
                    self._free_slot(i)
            while self.waiting:
                nxt = self.waiting[0]
                if nxt.cancelled:
                    self.waiting.pop(0)
                    nxt.out.put_nowait(None)
                    self._reqs.pop(nxt.req_id, None)
                    continue
                if not self._admit(nxt):
                    break
                self.waiting.pop(0)
            active = np.array([r is not None for r in self.slot_req])
            if not active.any():
                # idle, OR the head-of-queue request can't be admitted yet
                # (pages still held elsewhere): either way we must yield —
                # a bare continue would spin the loop without ever
                # letting consumers/stop() run
                self._wake.clear()
                try:
                    await asyncio.wait_for(self._wake.wait(), timeout=1.0)
                except asyncio.TimeoutError:
                    pass
                continue
            self._rng, sub = jax.random.split(self._rng)
            toks, self.kpool, self.vpool = paged_decode_step(
                self.params, self.loras, jnp.asarray(self.aids),
                jnp.asarray(self.next_tok), jnp.asarray(self.seq_lens),
                jnp.asarray(self.page_tables), self.kpool, self.vpool,
                jnp.asarray(active), jnp.asarray(self.temps), sub, self.cfg)
            toks = np.asarray(toks)
            self.steps += 1
            for i, req in enumerate(self.slot_req):
                if req is None:
                    continue
                self.seq_lens[i] += 1
                if req.cancelled:
                    continue
                tok = int(toks[i])
                self.next_tok[i] = tok
                self._emit(req, tok)
            # hand the loop to consumers/admitters every step
            await asyncio.sleep(0)

"""Continuous-batching LLM engine with a paged KV cache and LoRA multiplex.

TPU-native counterpart of the reference's delegated vLLM engine (ref:
python/ray/llm/_internal/serve/deployments/llm/vllm/vllm_engine.py:95 —
there Ray wires vLLM; here the engine is owned). Design maps the vLLM
ideas onto XLA's static-shape world:

* **Fixed decode slots.** One jitted decode step advances ALL ``max_batch``
  slots every iteration; inactive slots are masked. Admission = writing a
  new request's prompt KV into a free slot's pages *between* decode steps
  — a request never waits for the running batch to drain (continuous
  batching at decode-step granularity).
* **Paged KV.** One global pool ``[layers, n_pages, page_size, kv, hd]``;
  each slot owns a page table. Decode gathers the slot's pages for
  attention; prefill scatters prompt KV into freshly allocated pages.
  Shapes never depend on sequence length, so XLA compiles exactly one
  decode program (plus one prefill program per prompt-length bucket).
* **Streaming.** Every request gets an asyncio queue; tokens land there
  the step they are sampled.
* **LoRA multiplex** (ref: serve/multiplex.py): stacked low-rank adapters
  on the q/v projections, selected per slot — different requests in one
  decode batch can use different adapters (adapter 0 = base model).
"""
from __future__ import annotations

import asyncio
import collections
import itertools
import time
from dataclasses import dataclass, field
from functools import partial

import jax
import jax.numpy as jnp
import numpy as np

from ray_tpu.devtools import chaos
from ray_tpu.models.llama import LlamaConfig
from ray_tpu.ops.basic import rms_norm, rope, rope_freqs


def _lora_delta(h, loras, name, aid):
    """Per-slot low-rank delta: h[B,T,D] x A[aid][D,r] x Bm[aid][r,O]."""
    if loras is None:
        return 0.0
    a = loras[name + "_a"][aid]  # [B, D, r]
    b = loras[name + "_b"][aid]  # [B, r, O]
    return jnp.einsum("btd,bdr->btr", h, a) @ b if a.ndim == 3 else (h @ a) @ b


# shared with the static-batch path — one implementation of the numerics
from ray_tpu.llm.generation import _ffn, _gqa_attn  # noqa: E402


def _kv_shape(pool):
    return (pool["q"] if isinstance(pool, dict) else pool).shape


def _kv_write(pool, i, row, off, val):
    """Store new K/V rows; int8 pools ({"q": int8, "s": f32 scales})
    quantize symmetrically per (token, kv-head) — one scale per hd
    vector, the granularity that keeps dequant a fused broadcast-mul.

    val: [..., KV, hd] float; row/off index [L, P, PS] positions."""
    if not isinstance(pool, dict):
        return pool.at[i, row, off].set(val)
    s = jnp.max(jnp.abs(val), axis=-1) / 127.0           # [..., KV]
    # clip BEFORE the int8 cast: low-precision (bf16) scale rounding can
    # put the max element's quotient at 128, and float->int overflow is
    # implementation-defined in XLA (saturates here, wraps elsewhere)
    q = jnp.clip(jnp.round(val / jnp.maximum(s, 1e-8)[..., None]),
                 -127, 127).astype(jnp.int8)
    return {"q": pool["q"].at[i, row, off].set(q),
            "s": pool["s"].at[i, row, off].set(s.astype(jnp.float32))}


def _kv_read(pool, i, page_tables, B, MAXP, PS, KV, hd, dtype):
    """Gather the decode attention window. int8 pools move HALF the HBM
    bytes of bf16 through the page-table gather (the decode bottleneck
    past ~64 slots); the scale gather is hd-times smaller — noise."""
    if not isinstance(pool, dict):
        return pool[i][page_tables].reshape(B, MAXP * PS, KV, hd)
    q = pool["q"][i][page_tables].reshape(B, MAXP * PS, KV, hd)
    s = pool["s"][i][page_tables].reshape(B, MAXP * PS, KV, 1)
    return q.astype(dtype) * s.astype(dtype)


@partial(jax.jit, donate_argnums=(0,))
def _scatter_pages_jit(pool, idx, stack):
    if isinstance(pool, dict):
        return {"q": pool["q"].at[:, idx].set(stack["q"]),
                "s": pool["s"].at[:, idx].set(stack["s"])}
    return pool.at[:, idx].set(stack.astype(pool.dtype))


def scatter_pages(pool, page_ids, stack):
    """Write an adopted page stack into pool rows ``page_ids`` (device
    op; the engine runs this at admission points, ordered like a prefill
    dispatch). ``stack`` is a bare ``[L, n, PS, KV, hd]`` array for plain
    pools or a ``{"q", "s"}`` dict for int8 pools — the shape
    ``disagg.adopt_pages`` returns. The pool is DONATED: an unjitted
    ``.at[].set`` copies the entire pool per adoption (tens of MB for a
    few adopted KB), which priced cache hits above the prefills they
    save; callers must rebind their pool to the return value."""
    idx = jnp.asarray(np.asarray(page_ids, np.int32))
    if isinstance(pool, dict):
        stack = {"q": jnp.asarray(stack["q"]), "s": jnp.asarray(stack["s"])}
    else:
        stack = jnp.asarray(stack)
    return _scatter_pages_jit(pool, idx, stack)


def _decode_body(params, loras, aids, tokens, pos, page_tables,
                 kpool, vpool, active, temps, key, cfg: LlamaConfig):
    """One decode step for every slot (masked where inactive).

    tokens: [B] current input token; pos: [B] tokens already cached (the
    new token lands at that position); page_tables: [B, MAXP]; aids: [B]
    adapter ids; temps: [B]. Returns (next_tok [B], kpool, vpool).
    Pools are either plain [L, P, PS, KV, hd] arrays (cfg dtype) or int8
    quantized dicts (see _kv_write) — the engine's kv_dtype option."""
    B = tokens.shape[0]
    L, P, PS, KV, hd = _kv_shape(kpool)
    MAXP = page_tables.shape[1]
    cos, sin = rope_freqs(cfg.head_dim, cfg.max_seq_len, cfg.rope_theta)
    positions = pos[:, None]
    row = jnp.take_along_axis(page_tables, (pos // PS)[:, None], axis=1)[:, 0]
    off = pos % PS
    key_idx = jnp.arange(MAXP * PS)
    mask = key_idx[None, None, :] <= pos[:, None, None]

    Dq = cfg.n_heads * hd
    Dkv = KV * hd
    x = params["tok"]["embedding"][tokens][:, None, :]
    for i in range(cfg.n_layers):
        layer = params[f"layers_{i}"]
        h = rms_norm(x, layer["attn_norm"]["scale"])
        # fused qkv / gate-up matmuls: at decode batch sizes each step is
        # dominated by per-op dispatch, not FLOPs — the concatenated
        # weights are loop-invariant, so XLA hoists them out of the scan
        # and every layer runs 2 fat matmuls instead of 5 thin ones
        wqkv = jnp.concatenate(
            [layer["wq"]["kernel"], layer["wk"]["kernel"],
             layer["wv"]["kernel"]], axis=1)
        qkv = h @ wqkv
        q = (qkv[..., :Dq] + _lora_delta(h, loras, "wq", aids)
             ).reshape(B, 1, cfg.n_heads, hd)
        k = qkv[..., Dq:Dq + Dkv].reshape(B, 1, KV, hd)
        v = (qkv[..., Dq + Dkv:] + _lora_delta(h, loras, "wv", aids)
             ).reshape(B, 1, KV, hd)
        q = rope(q, cos, sin, positions)
        k = rope(k, cos, sin, positions)
        kpool = _kv_write(kpool, i, row, off, k[:, 0])
        vpool = _kv_write(vpool, i, row, off, v[:, 0])
        kb = _kv_read(kpool, i, page_tables, B, MAXP, PS, KV, hd, k.dtype)
        vb = _kv_read(vpool, i, page_tables, B, MAXP, PS, KV, hd, v.dtype)
        att = _gqa_attn(q, kb, vb, mask)
        x = x + att.reshape(B, 1, -1) @ layer["wo"]["kernel"]
        hf = rms_norm(x, layer["ffn_norm"]["scale"])
        w_gu = jnp.concatenate(
            [layer["w_gate"]["kernel"], layer["w_up"]["kernel"]], axis=1)
        gu = hf @ w_gu
        ff = gu.shape[-1] // 2
        x = x + (jax.nn.silu(gu[..., :ff]) * gu[..., ff:]
                 ) @ layer["w_down"]["kernel"]
    x = rms_norm(x, params["norm"]["scale"])
    logits = x[:, 0] @ params["lm_head"]["kernel"]

    greedy = jnp.argmax(logits, axis=-1).astype(jnp.int32)

    def sampled():
        # Threefry bits for [B, V] gumbels are NOT free at decode batch
        # sizes — only pay when some slot actually samples
        s = jax.random.categorical(
            key, logits / jnp.maximum(temps, 1e-6)[:, None]).astype(jnp.int32)
        return jnp.where(temps > 0, s, greedy)

    next_tok = jax.lax.cond(jnp.any(temps > 0), sampled, lambda: greedy)
    return jnp.where(active, next_tok, 0), kpool, vpool


@partial(jax.jit, static_argnames=("cfg", "n_steps"), donate_argnums=(6, 7))
def paged_decode_multi(params, loras, aids, tokens, seq_lens, page_tables,
                       kpool, vpool, active, temps, key, cfg: LlamaConfig,
                       n_steps: int):
    """``n_steps`` fused decode steps as ONE device program (lax.scan).

    Decode is memory-bound; what killed throughput was the per-step host
    round trip (dispatch latency + arg upload + token download + asyncio),
    ~100x the step itself. Fusing K steps amortizes all of it K-fold; the
    host sees tokens in [K, B] blocks. The final (tokens, positions) carry
    is returned ON DEVICE so consecutive blocks chain without any host
    round trip — the engine pipelines the next block's dispatch before
    syncing this block's tokens. Slots that finish mid-block keep decoding
    junk — their page-table gathers clip to allocated (or junk) pages,
    future-position writes are masked until legitimately overwritten, and
    the host discards the extra tokens, so over-decode is pure (bounded)
    waste, never corruption."""
    def step(carry, k):
        tok, pos, kpool, vpool = carry
        nxt, kpool, vpool = _decode_body(
            params, loras, aids, tok, pos, page_tables, kpool, vpool,
            active, temps, jax.random.fold_in(key, k), cfg)
        return (nxt, pos + 1, kpool, vpool), nxt

    (tok, pos, kpool, vpool), toks = jax.lax.scan(
        step, (tokens, seq_lens, kpool, vpool), jnp.arange(n_steps))
    return toks, tok, pos, kpool, vpool


@partial(jax.jit, static_argnames=("cfg",), donate_argnums=(5, 6))
def paged_prefill_batch(params, loras, aids, tokens, pages, kpool, vpool,
                        true_lens, temps, key, cfg: LlamaConfig):
    """Prefill a whole admission wave as ONE batched forward.

    tokens: [N, Tp_pad] right-padded prompts (same pad bucket); pages:
    [N, n_pages] pool pages per request (dummy rows use the junk page 0);
    true_lens/temps: [N]. Returns (first tokens [N], kpool, vpool).
    Batching the wave (instead of scanning rows at batch 1) matters
    because small-batch steps are per-op-overhead bound; one fat forward
    amortizes it across the whole wave."""
    N, Tp = tokens.shape
    L, P, PS, KV, hd = _kv_shape(kpool)
    cos, sin = rope_freqs(cfg.head_dim, cfg.max_seq_len, cfg.rope_theta)
    positions = jnp.arange(Tp)[None, :]
    idx = jnp.arange(Tp)
    mask = idx[None, :, None] >= idx[None, None, :]  # causal
    rows = pages[:, idx // PS]  # [N, Tp] pool row per prompt position
    offs = jnp.broadcast_to(idx % PS, (N, Tp))
    x = params["tok"]["embedding"][tokens]  # [N, Tp, D]
    for i in range(cfg.n_layers):
        layer = params[f"layers_{i}"]
        h = rms_norm(x, layer["attn_norm"]["scale"])
        q = (h @ layer["wq"]["kernel"] + _lora_delta(h, loras, "wq", aids)
             ).reshape(N, Tp, cfg.n_heads, hd)
        k = (h @ layer["wk"]["kernel"]).reshape(N, Tp, KV, hd)
        v = (h @ layer["wv"]["kernel"] + _lora_delta(h, loras, "wv", aids)
             ).reshape(N, Tp, KV, hd)
        q = rope(q, cos, sin, positions)
        k = rope(k, cos, sin, positions)
        kpool = _kv_write(kpool, i, rows, offs, k)
        vpool = _kv_write(vpool, i, rows, offs, v)
        att = _gqa_attn(q, k, v, mask)  # prefill attends the FRESH k/v:
        # quantization only affects what later decode steps read back
        x = x + att.reshape(N, Tp, -1) @ layer["wo"]["kernel"]
        x = _ffn(layer, x)
    x = rms_norm(x, params["norm"]["scale"])
    last = jnp.take_along_axis(
        x, (true_lens - 1)[:, None, None].astype(jnp.int32), axis=1)[:, 0]
    logits = last @ params["lm_head"]["kernel"]  # [N, V]
    greedy = jnp.argmax(logits, axis=-1).astype(jnp.int32)

    def sampled():
        s = jax.random.categorical(
            key, logits / jnp.maximum(temps, 1e-6)[:, None]).astype(jnp.int32)
        return jnp.where(temps > 0, s, greedy)

    toks = jax.lax.cond(jnp.any(temps > 0), sampled, lambda: greedy)
    return toks, kpool, vpool


@partial(jax.jit, static_argnames=("cfg",), donate_argnums=(5, 6))
def paged_prefill_suffix(params, loras, aids, tokens, pages, kpool, vpool,
                         prefix_lens, true_lens, temps, key, cfg: LlamaConfig):
    """Prefill only a prompt's SUFFIX over already-resident prefix KV —
    the cross-request prefix-cache fast path (vLLM's PagedAttention
    sharing argument run cross-request: a cached prefix of k full pages
    is adopted into this pool verbatim and never recomputed).

    tokens: [N, Ts_pad] right-padded suffix tokens; pages: [N, W] page
    table covering prefix AND suffix positions in prompt order (junk
    page 0 beyond); prefix_lens: [N] PAGE-ALIGNED token counts already
    in the pool; true_lens: [N] real suffix lengths. Suffix position j
    sits at absolute position prefix_len + j, so its KV lands in the
    suffix pages and its attention window — gathered through the page
    table exactly like decode — covers the prefix for free. Returns
    (first tokens [N], kpool, vpool).

    int8 pools: the suffix queries read the prefix (and their own fresh
    K/V) back through dequantization, where full prefill attends the
    fresh float K/V directly — parity with the aggregated path is exact
    for float pools and within quantization noise for int8."""
    N, Ts = tokens.shape
    L, P, PS, KV, hd = _kv_shape(kpool)
    W = pages.shape[1]
    cos, sin = rope_freqs(cfg.head_dim, cfg.max_seq_len, cfg.rope_theta)
    positions = prefix_lens[:, None] + jnp.arange(Ts)[None, :]  # [N, Ts]
    rows = jnp.take_along_axis(pages, positions // PS, axis=1)
    offs = positions % PS
    key_idx = jnp.arange(W * PS)
    # window index == absolute position (the table is prompt-ordered),
    # so causal masking is one compare; tail junk-page keys sit past
    # every real position and mask out
    mask = key_idx[None, None, :] <= positions[:, :, None]  # [N, Ts, W*PS]
    x = params["tok"]["embedding"][tokens]
    for i in range(cfg.n_layers):
        layer = params[f"layers_{i}"]
        h = rms_norm(x, layer["attn_norm"]["scale"])
        q = (h @ layer["wq"]["kernel"] + _lora_delta(h, loras, "wq", aids)
             ).reshape(N, Ts, cfg.n_heads, hd)
        k = (h @ layer["wk"]["kernel"]).reshape(N, Ts, KV, hd)
        v = (h @ layer["wv"]["kernel"] + _lora_delta(h, loras, "wv", aids)
             ).reshape(N, Ts, KV, hd)
        q = rope(q, cos, sin, positions)
        k = rope(k, cos, sin, positions)
        kpool = _kv_write(kpool, i, rows, offs, k)
        vpool = _kv_write(vpool, i, rows, offs, v)
        kb = _kv_read(kpool, i, pages, N, W, PS, KV, hd, k.dtype)
        vb = _kv_read(vpool, i, pages, N, W, PS, KV, hd, v.dtype)
        att = _gqa_attn(q, kb, vb, mask)
        x = x + att.reshape(N, Ts, -1) @ layer["wo"]["kernel"]
        x = _ffn(layer, x)
    x = rms_norm(x, params["norm"]["scale"])
    last = jnp.take_along_axis(
        x, (true_lens - 1)[:, None, None].astype(jnp.int32), axis=1)[:, 0]
    logits = last @ params["lm_head"]["kernel"]
    greedy = jnp.argmax(logits, axis=-1).astype(jnp.int32)

    def sampled():
        s = jax.random.categorical(
            key, logits / jnp.maximum(temps, 1e-6)[:, None]).astype(jnp.int32)
        return jnp.where(temps > 0, s, greedy)

    toks = jax.lax.cond(jnp.any(temps > 0), sampled, lambda: greedy)
    return toks, kpool, vpool


# --------------------------------------------------------------- speculative
def _ngram_propose(hist, pos, k: int, m: int):
    """Self-drafting prompt-lookup (Leviathan-style speculative decoding
    with the request's OWN history as the drafter): find the most recent
    earlier occurrence of the trailing ``m``-gram in ``hist`` and
    propose the ``k`` tokens that followed it. Pure device math — the
    drafter lives INSIDE the fused scan, so a spec block never pays a
    host round trip to draft.

    hist: [B, H] token history; positions ``0..pos`` are valid and
    ``hist[b, pos[b]]`` is the pending input token. Returns
    (drafts [B, k], draft_len [B]) with draft_len 0 where no match."""
    B, H = hist.shape
    n_win = H - m + 1
    gidx = pos[:, None] - (m - 1) + jnp.arange(m)[None, :]
    pattern = jnp.take_along_axis(hist, jnp.clip(gidx, 0, H - 1), axis=1)
    # all H-m+1 windows of width m as m shifted views: wins[b, i, t] =
    # hist[b, i + t] — one [B, n_win, m] compare finds every candidate
    wins = jnp.stack([hist[:, t:t + n_win] for t in range(m)], axis=-1)
    match = jnp.all(wins == pattern[:, None, :], axis=-1)     # [B, n_win]
    ends = jnp.arange(n_win) + (m - 1)                        # window end j
    valid = (ends[None, :] < pos[:, None]) & (pos[:, None] >= m)
    # a match at j proposes the pos-j tokens that FOLLOWED it, capped at
    # k — so prefer the most recent match with a full k followers (on
    # periodic text the nearest match sits at pos-1 and would draft just
    # ONE token), falling back to the nearest match otherwise
    hit = match & valid
    j_full = jnp.max(jnp.where(hit & (ends[None, :] <= pos[:, None] - k),
                               ends[None, :], -1), axis=1)
    j_any = jnp.max(jnp.where(hit, ends[None, :], -1), axis=1)
    j = jnp.where(j_full >= 0, j_full, j_any)
    found = j >= 0
    dl = jnp.where(found, jnp.minimum(k, pos - j), 0).astype(jnp.int32)
    didx = j[:, None] + 1 + jnp.arange(k)[None, :]
    drafts = jnp.take_along_axis(hist, jnp.clip(didx, 0, H - 1), axis=1)
    return drafts, dl


def _spec_verify_body(params, loras, aids, inputs, positions, page_tables,
                      kpool, vpool, temps, key, cfg: LlamaConfig):
    """One fused multi-position forward over ``T = k+1`` decode
    positions per slot — the ``paged_prefill_suffix`` shape run at the
    decode batch: token j of a slot sits at absolute position
    ``positions[b, j]``, its KV lands in the slot's pages through the
    page table, and its attention window (gathered exactly like decode)
    covers everything at or before it — including the sibling draft
    positions written THIS step, which is precisely the speculative
    verification semantics (draft j attends drafts 1..j-1).

    Returns (greedy [B, T] target tokens per position, next0 [B] the
    position-0 token with sampling applied for temps > 0 rows, kpool,
    vpool)."""
    B, T = inputs.shape
    L, P, PS, KV, hd = _kv_shape(kpool)
    MAXP = page_tables.shape[1]
    cos, sin = rope_freqs(cfg.head_dim, cfg.max_seq_len, cfg.rope_theta)
    rows = jnp.take_along_axis(page_tables, positions // PS, axis=1)
    offs = positions % PS
    key_idx = jnp.arange(MAXP * PS)
    mask = key_idx[None, None, :] <= positions[:, :, None]  # [B,T,MAXP*PS]
    Dq = cfg.n_heads * hd
    Dkv = KV * hd
    x = params["tok"]["embedding"][inputs]  # [B, T, D]
    for i in range(cfg.n_layers):
        layer = params[f"layers_{i}"]
        h = rms_norm(x, layer["attn_norm"]["scale"])
        wqkv = jnp.concatenate(
            [layer["wq"]["kernel"], layer["wk"]["kernel"],
             layer["wv"]["kernel"]], axis=1)
        qkv = h @ wqkv
        q = (qkv[..., :Dq] + _lora_delta(h, loras, "wq", aids)
             ).reshape(B, T, cfg.n_heads, hd)
        kk = qkv[..., Dq:Dq + Dkv].reshape(B, T, KV, hd)
        v = (qkv[..., Dq + Dkv:] + _lora_delta(h, loras, "wv", aids)
             ).reshape(B, T, KV, hd)
        q = rope(q, cos, sin, positions)
        kk = rope(kk, cos, sin, positions)
        kpool = _kv_write(kpool, i, rows, offs, kk)
        vpool = _kv_write(vpool, i, rows, offs, v)
        kb = _kv_read(kpool, i, page_tables, B, MAXP, PS, KV, hd, kk.dtype)
        vb = _kv_read(vpool, i, page_tables, B, MAXP, PS, KV, hd, v.dtype)
        att = _gqa_attn(q, kb, vb, mask)
        x = x + att.reshape(B, T, -1) @ layer["wo"]["kernel"]
        hf = rms_norm(x, layer["ffn_norm"]["scale"])
        w_gu = jnp.concatenate(
            [layer["w_gate"]["kernel"], layer["w_up"]["kernel"]], axis=1)
        gu = hf @ w_gu
        ff = gu.shape[-1] // 2
        x = x + (jax.nn.silu(gu[..., :ff]) * gu[..., ff:]
                 ) @ layer["w_down"]["kernel"]
    x = rms_norm(x, params["norm"]["scale"])
    logits = x @ params["lm_head"]["kernel"]  # [B, T, V]
    greedy = jnp.argmax(logits, axis=-1).astype(jnp.int32)

    def sampled():
        s = jax.random.categorical(
            key, logits[:, 0] / jnp.maximum(temps, 1e-6)[:, None]
        ).astype(jnp.int32)
        return jnp.where(temps > 0, s, greedy[:, 0])

    next0 = jax.lax.cond(jnp.any(temps > 0), sampled, lambda: greedy[:, 0])
    return greedy, next0, kpool, vpool


def _spec_verify_accept(params, loras, aids, tok, pos, drafts, dl,
                        page_tables, kpool, vpool, active, temps, key,
                        cfg: LlamaConfig):
    """Verify ``drafts`` against the target in ONE fused forward and
    apply the greedy accept rule: accept the longest draft prefix the
    target agrees with, then take the target's own token at the first
    disagreement (or the bonus token after a full accept). Emission is
    token-identical to the non-speculative greedy engine by
    construction — every emitted token IS the target's argmax given the
    same prefix. Rejected tail positions hold junk KV that the next
    step's inputs legitimately overwrite (write-before-read per layer),
    so rollback is pure position arithmetic: no pool copy.

    Returns (out [B, k+1] emission candidates, n_emit [B], n_acc [B],
    new_tok [B], new_pos [B], kpool, vpool)."""
    B, k = drafts.shape
    inputs = jnp.concatenate([tok[:, None], drafts], axis=1)
    positions = pos[:, None] + jnp.arange(k + 1)[None, :]
    greedy, next0, kpool, vpool = _spec_verify_body(
        params, loras, aids, inputs, positions, page_tables, kpool, vpool,
        temps, key, cfg)
    okm = (drafts == greedy[:, :-1]) & (jnp.arange(k)[None, :] < dl[:, None])
    n_acc = jnp.sum(jnp.cumprod(okm.astype(jnp.int32), axis=1), axis=1)
    out = jnp.concatenate([next0[:, None], greedy[:, 1:]], axis=1)
    n_emit = jnp.where(active, n_acc + 1, 0).astype(jnp.int32)
    new_tok = jnp.where(
        active, jnp.take_along_axis(out, n_acc[:, None], axis=1)[:, 0], 0)
    return out, n_emit, n_acc, new_tok, pos + n_acc + 1, kpool, vpool


@partial(jax.jit, static_argnames=("cfg", "n_steps", "k", "ngram"),
         donate_argnums=(5, 7, 8))
def paged_decode_spec(params, loras, aids, tokens, seq_lens, hist,
                      page_tables, kpool, vpool, active, spec_ok, temps,
                      key, cfg: LlamaConfig, n_steps: int, k: int,
                      ngram: int):
    """``n_steps`` SPECULATIVE decode steps as one device program: each
    scan step drafts ``k`` tokens per slot with the on-device n-gram
    matcher, verifies all of them in one fused multi-position forward,
    and advances each slot by ``n_acc + 1`` positions — so one host
    round trip can emit up to ``n_steps * (k + 1)`` tokens instead of
    ``n_steps``. The (token, position, history) carry chains on device
    between blocks exactly like ``paged_decode_multi``'s; slots where
    ``spec_ok`` is False (sampled rows, per-request opt-out) run with
    draft_len 0, i.e. plain one-token decode — a mixed spec/plain wave
    is one program, one compiled bucket per (n_steps, k).

    Returns (toks [S, B, k+1], n_emit [S, B], n_prop [S, B], tok, pos,
    hist, kpool, vpool); the host emits the first ``n_emit[s, b]``
    tokens of each row and discards the rest (the rollback)."""
    def step(carry, s):
        tok, pos, hist, kpool, vpool = carry
        drafts, dl = _ngram_propose(hist, pos, k, ngram)
        dl = jnp.where(spec_ok, dl, 0)
        out, n_emit, n_acc, tok, pos, kpool, vpool = _spec_verify_accept(
            params, loras, aids, tok, pos, drafts, dl, page_tables,
            kpool, vpool, active, temps, jax.random.fold_in(key, s), cfg)
        # record the emitted tokens into the history so the NEXT step's
        # n-gram drafter sees them (indices past n_acc drop out-of-bounds)
        B, H = hist.shape
        widx = pos[:, None] - n_acc[:, None] + jnp.arange(k + 1)[None, :]
        widx = jnp.where(jnp.arange(k + 1)[None, :] <= n_acc[:, None],
                         widx, H)
        hist = hist.at[jnp.arange(B)[:, None], widx].set(out, mode="drop")
        return (tok, pos, hist, kpool, vpool), (out, n_emit, dl)

    (tok, pos, hist, kpool, vpool), (toks, n_emit, n_prop) = jax.lax.scan(
        step, (tokens, seq_lens, hist, kpool, vpool), jnp.arange(n_steps))
    return toks, n_emit, n_prop, tok, pos, hist, kpool, vpool


@partial(jax.jit, static_argnames=("cfg", "k"), donate_argnums=(7, 8))
def paged_decode_verify(params, loras, aids, tokens, seq_lens, drafts,
                        page_tables, kpool, vpool, draft_lens, active,
                        temps, key, cfg: LlamaConfig, k: int):
    """One speculative step with HOST-provided drafts — the drafter-hook
    path (``spec_drafter=``: a real small model, a custom matcher). Same
    verify/accept as the fused scan, but one step per dispatch since the
    host drafter needs the accepted tokens back before proposing the
    next window. Returns (toks [B, k+1], n_emit [B], n_prop [B], tok,
    pos, kpool, vpool)."""
    out, n_emit, n_acc, tok, pos, kpool, vpool = _spec_verify_accept(
        params, loras, aids, tokens, seq_lens, drafts, draft_lens,
        page_tables, kpool, vpool, active, temps, key, cfg)
    return out, n_emit, draft_lens, tok, pos, kpool, vpool


def make_lora_stack(cfg: LlamaConfig, adapters: dict[str, dict], rank: int):
    """Stack named adapters into gatherable arrays. Index 0 is the base
    model (zero delta). adapters: name -> {"wq_a": [D,r], "wq_b": [r,O],
    "wv_a": ..., "wv_b": ...}. Returns (stack dict, name->index map)."""
    D = cfg.d_model
    O_q = cfg.n_heads * cfg.head_dim
    O_v = cfg.n_kv_heads * cfg.head_dim
    names = ["__base__"] + sorted(adapters)
    idx = {n: i for i, n in enumerate(names)}
    stack = {
        "wq_a": np.zeros((len(names), D, rank), np.float32),
        "wq_b": np.zeros((len(names), rank, O_q), np.float32),
        "wv_a": np.zeros((len(names), D, rank), np.float32),
        "wv_b": np.zeros((len(names), rank, O_v), np.float32),
    }
    for name, ad in adapters.items():
        i = idx[name]
        for k in stack:
            if k in ad:
                stack[k][i] = np.asarray(ad[k], np.float32)
    return {k: jnp.asarray(v) for k, v in stack.items()}, idx


def make_kv_pools(cfg: LlamaConfig, page_size: int, n_pages: int,
                  kv_dtype: str | None):
    """One (kpool, vpool) pair for a paged cache: plain
    ``[L, P, PS, KV, hd]`` arrays for native/bf16, ``{"q", "s"}``
    quantized dicts for int8. Shared by the engine and the disagg
    prefill workers so the two pools are structurally identical and a
    page sliced from one scatters into the other."""
    dtype = jnp.dtype(cfg.dtype)
    pool_shape = (cfg.n_layers, n_pages, page_size, cfg.n_kv_heads,
                  cfg.head_dim)
    if kv_dtype == "int8":
        # quantized cache: half the HBM bytes through the decode
        # page-table gather (the bottleneck past ~64 slots) at the
        # cost of per-(token, kv-head) symmetric int8 rounding
        def make_pool():
            return {"q": jnp.zeros(pool_shape, jnp.int8),
                    "s": jnp.zeros(pool_shape[:-1], jnp.float32)}

        return make_pool(), make_pool()
    if kv_dtype in (None, "native"):
        kpool = jnp.zeros(pool_shape, dtype)
        return kpool, jnp.zeros_like(kpool)
    if kv_dtype == "bf16":
        # explicit half-precision cache, regardless of cfg.dtype
        kpool = jnp.zeros(pool_shape, jnp.bfloat16)
        return kpool, jnp.zeros_like(kpool)
    raise ValueError(f"unknown kv_dtype {kv_dtype!r}")


@dataclass
class _Request:
    req_id: int
    prompt: list[int]
    max_tokens: int
    temperature: float
    adapter: int
    out: asyncio.Queue = field(default_factory=asyncio.Queue)
    slot: int = -1
    emitted: int = 0
    planned: int = 0  # tokens scheduled on-device (planned mode)
    cancelled: bool = False
    finished: bool = False  # completed normally (max_tokens or eos)
    # disaggregated admission (llm/disagg): (k_stack, v_stack, first_tok)
    # adopted from a prefill worker's KVPageManifest — admission scatters
    # the stacks into this engine's pool instead of running a prefill
    prefilled: tuple | None = None
    # speculative decoding: this request's draft state rides here — the
    # opt-in flag (greedy-only; sampled rows always decode plain) plus
    # its slice of the engine's token-history mirror (the drafter's
    # context), which _reserve_slot/_emit_spec_block maintain
    spec: bool = False


class EngineFull(Exception):
    """No free slot/pages and the waiting queue is at capacity."""


class ContinuousBatchingEngine:
    """Single-process engine; drive with ``await engine.start()`` then
    ``submit`` / ``stream`` from the same event loop."""

    def __init__(self, params, cfg: LlamaConfig, *, max_batch: int = 8,
                 page_size: int = 16, n_pages: int = 256,
                 max_seq_len: int = 512, eos_id: int | None = None,
                 lora_adapters: dict[str, dict] | None = None,
                 lora_rank: int = 8, max_waiting: int = 256,
                 block_buckets: tuple[int, ...] = (4, 8, 16, 32, 64),
                 kv_dtype: str | None = None, spec_enable: bool = False,
                 spec_k: int = 4, spec_ngram: int = 2,
                 spec_drafter=None):
        self.params = params
        self.cfg = cfg
        self.B = max_batch
        self.PS = page_size
        self.MAXP = -(-max_seq_len // page_size)
        self.eos_id = eos_id
        self.max_waiting = max_waiting
        # fused-decode block sizes (one compiled program per bucket); the
        # loop picks the smallest bucket covering the longest remaining
        # request, so short interactive requests stay low-latency while
        # long generations amortize dispatch 64x
        self.block_buckets = tuple(sorted(block_buckets))
        self.kpool, self.vpool = make_kv_pools(cfg, page_size, n_pages,
                                               kv_dtype)
        self.kv_dtype = kv_dtype or "native"
        self.n_pages = n_pages
        self.free_pages = list(range(1, n_pages))  # page 0 = junk page
        self.loras = None
        self.lora_index = {"__base__": 0}
        if lora_adapters:
            self.loras, self.lora_index = make_lora_stack(
                cfg, lora_adapters, lora_rank)
        # slot state (host side)
        self.slot_req: list[_Request | None] = [None] * self.B
        self.page_tables = np.zeros((self.B, self.MAXP), np.int32)
        self.seq_lens = np.zeros(self.B, np.int32)
        self.next_tok = np.zeros(self.B, np.int32)
        self.temps = np.zeros(self.B, np.float32)
        self.aids = np.zeros(self.B, np.int32)
        self.waiting: list[_Request] = []
        self._req_ids = itertools.count(1)
        self._reqs: dict[int, _Request] = {}
        # finished requests not yet drained by a stream() consumer; bounded
        # LRU so fire-and-forget submitters can't leak token queues forever
        self._done: collections.OrderedDict[int, _Request] = (
            collections.OrderedDict())
        self._done_cap = 4 * self.B + max_waiting
        self._wake = asyncio.Event()
        self._running = False
        self._task = None
        self._rng = jax.random.PRNGKey(0)
        self.error: BaseException | None = None  # fatal loop failure
        # speculative decoding (README § Speculative decoding): greedy
        # requests draft spec_k tokens per step (on-device n-gram
        # matcher over spec_ngram-grams, or the spec_drafter hook) and
        # the target verifies them in one fused multi-position forward
        self.spec_enable = bool(spec_enable)
        self.spec_k = int(spec_k)
        self.spec_ngram = int(spec_ngram)
        self.spec_drafter = spec_drafter
        # token-history mirror [B, max_seq_len]: hist[i, :seq_lens[i]+1]
        # holds slot i's known tokens (prompt + emitted + pending input)
        # — the drafter's context, and the rebuild source for the
        # device-resident hist carry at admission points
        self.hist = np.zeros((self.B, self.MAXP * page_size), np.int32)
        # counters for benchmarks / tests
        self.steps = 0
        self.tokens_out = 0
        self.spec_steps = 0      # speculative verify steps run
        self.spec_proposed = 0   # draft tokens proposed (live spec rows)
        self.spec_accepted = 0   # draft tokens the target accepted
        # bounded per-block log the disagg telemetry drains:
        # (n_steps, emitted, proposed, accepted) per synced spec block
        self._block_log: collections.deque = collections.deque(maxlen=256)

    # ----------------------------------------------------------- public API
    async def start(self):
        if self._task is None:
            self._running = True
            self._task = asyncio.get_running_loop().create_task(self._loop())

    async def stop(self):
        self._running = False
        self._wake.set()
        if self._task is not None:
            await self._task
            self._task = None
        # nothing will produce more tokens: unblock every live consumer
        self._terminate_all_streams()

    def _terminate_all_streams(self):
        for req in list(self._reqs.values()):
            req.out.put_nowait(None)
        self._reqs.clear()
        self._done.clear()
        self.waiting.clear()
        self.slot_req = [None] * self.B

    def submit(self, prompt_tokens: list[int], *, max_tokens: int = 32,
               temperature: float = 0.0, adapter: str | None = None,
               spec: bool | None = None) -> int:
        """Queue a request; returns its id. Tokens arrive on stream().
        ``spec`` overrides the engine's ``spec_enable`` default for this
        request (greedy requests only; sampled rows decode plain either
        way)."""
        if self.error is not None:
            raise RuntimeError("engine loop died") from self.error
        if len(self.waiting) >= self.max_waiting:
            raise EngineFull(f"{len(self.waiting)} requests already waiting")
        if len(prompt_tokens) + max_tokens > self.MAXP * self.PS:
            raise ValueError(
                f"prompt ({len(prompt_tokens)}) + max_tokens ({max_tokens}) "
                f"exceeds the engine's max_seq_len ({self.MAXP * self.PS})")
        n_need = -(-(len(prompt_tokens) + max_tokens) // self.PS)
        if n_need > self.n_pages - 1:
            raise ValueError(
                f"request needs {n_need} KV pages but the pool only has "
                f"{self.n_pages - 1}")
        aid = self.lora_index.get(adapter or "__base__")
        if aid is None:
            raise ValueError(f"unknown LoRA adapter {adapter!r} "
                             f"(loaded: {sorted(self.lora_index)})")
        req = _Request(next(self._req_ids), list(prompt_tokens),
                       int(max_tokens), float(temperature), aid,
                       spec=self.spec_enable if spec is None else bool(spec))
        self._reqs[req.req_id] = req
        self.waiting.append(req)
        self._wake.set()
        return req.req_id

    def submit_prefilled(self, prompt_tokens: list[int], k_stack, v_stack,
                         first_token: int, *, max_tokens: int = 32,
                         temperature: float = 0.0,
                         adapter: str | None = None,
                         spec: bool | None = None) -> int:
        """Queue a request whose prompt KV was ALREADY produced elsewhere
        (a disaggregated prefill worker): admission scatters the adopted
        page stacks (``[L, n_pages, PS, KV, hd]`` arrays, or ``{"q","s"}``
        dicts for int8 pools — the shape ``disagg.adopt_pages`` returns)
        into this engine's pool and starts decoding at position
        ``len(prompt_tokens)`` with ``first_token`` — no prefill dispatch,
        no recompute. The stacks must cover ``ceil(len(prompt)/PS)`` pages
        of a pool with this engine's page_size and kv_dtype."""
        if self.error is not None:
            raise RuntimeError("engine loop died") from self.error
        if len(self.waiting) >= self.max_waiting:
            raise EngineFull(f"{len(self.waiting)} requests already waiting")
        if len(prompt_tokens) + max_tokens > self.MAXP * self.PS:
            raise ValueError(
                f"prompt ({len(prompt_tokens)}) + max_tokens ({max_tokens}) "
                f"exceeds the engine's max_seq_len ({self.MAXP * self.PS})")
        n_cover = -(-len(prompt_tokens) // self.PS)
        n_got = (k_stack["q"] if isinstance(k_stack, dict)
                 else k_stack).shape[1]
        if n_got < n_cover:
            raise ValueError(
                f"adopted stacks cover {n_got} pages but the prompt "
                f"needs {n_cover}")
        aid = self.lora_index.get(adapter or "__base__")
        if aid is None:
            raise ValueError(f"unknown LoRA adapter {adapter!r} "
                             f"(loaded: {sorted(self.lora_index)})")
        req = _Request(next(self._req_ids), list(prompt_tokens),
                       int(max_tokens), float(temperature), aid,
                       spec=self.spec_enable if spec is None else bool(spec))
        req.prefilled = (k_stack, v_stack, int(first_token))
        self._reqs[req.req_id] = req
        self.waiting.append(req)
        self._wake.set()
        return req.req_id

    def export_pages(self, req_id: int):
        """Page-export hook: seal a LIVE request's prompt KV pages into
        the local shm arena and return their ``KVPageManifest`` — how an
        aggregated engine donates a prefix to the cross-request cache.
        Must be called while the request still holds its slot (prompt
        positions are stable once prefilled; decode writes land past
        them)."""
        from ray_tpu.llm.disagg.kv_plane import ship_pages

        req = self._reqs.get(req_id)
        if req is None or req.slot < 0:
            raise KeyError(f"request {req_id} is not holding a slot")
        n_cover = -(-len(req.prompt) // self.PS)
        page_ids = [int(p) for p in self.page_tables[req.slot, :n_cover]]
        return ship_pages(self.kpool, self.vpool, page_ids, req.prompt,
                          page_size=self.PS, kv_dtype=self.kv_dtype)

    def tokens_in_flight(self) -> int:
        """Decode tokens this engine still owes: remaining scheduled
        tokens of resident requests plus everything waiting — the
        cross-replica batching admission signal (a ring full of
        nearly-done requests drains fast; a shallow queue of long
        generations does not; request COUNTS can't tell them apart)."""
        live = sum(max(0, r.max_tokens - r.emitted)
                   for r in self.slot_req if r is not None and not r.cancelled)
        return live + sum(max(0, r.max_tokens - r.emitted)
                          for r in self.waiting if not r.cancelled)

    def spec_stats(self, drain: bool = False) -> dict:
        """Speculative-decoding counters + the per-block log. With
        ``drain`` the log is consumed (the disagg telemetry's exactly-
        once feed into the tokens_per_step / spec_accept_rate windows);
        without it this is a pure read."""
        blocks = list(self._block_log)
        if drain:
            self._block_log.clear()
        return {"spec_steps": self.spec_steps,
                "spec_proposed": self.spec_proposed,
                "spec_accepted": self.spec_accepted,
                "spec_accept_rate": (self.spec_accepted
                                     / max(1, self.spec_proposed)),
                "blocks": blocks}

    def headroom(self) -> dict:
        """Admission-control snapshot for the disagg scheduler: free KV
        pages and decode slots, queue depth, and the decode
        tokens-in-flight signal."""
        return {"free_pages": len(self.free_pages),
                "free_slots": sum(r is None for r in self.slot_req),
                "waiting": len(self.waiting),
                "tokens_in_flight": self.tokens_in_flight(),
                "n_pages": self.n_pages, "page_size": self.PS,
                "max_batch": self.B, "kv_dtype": self.kv_dtype}

    async def stream(self, req_id: int):
        """Async iterator of generated token ids for one request. Raises
        if the engine died before the request finished. The request stays
        registered until its consumer drains the terminal None here — a
        caller may finish awaiting something else before streaming and the
        already-queued tokens must still be reachable."""
        req = self._reqs.get(req_id)
        if req is None:
            req = self._done[req_id]
        try:
            while True:
                item = await req.out.get()
                if item is None:
                    if self.error is not None and not req.finished:
                        raise RuntimeError("engine loop died") from self.error
                    break
                yield item
        finally:
            # only unregister finished requests: a consumer erroring out
            # mid-stream must not make cancel() a no-op on a live request
            self._done.pop(req_id, None)

    async def stream_blocks(self, req_id: int):
        """Block-coalesced stream: lists of token ids, one per wake.

        ``_emit_block`` pushes a whole fused ``lax.scan`` block's tokens
        into the request queue in one synchronous burst, so draining the
        queue greedily after the first await yields exactly one delta per
        fused decode block (per accepted run in spec mode). This is the
        streaming-serve producer shape: one "G" chunk record per BLOCK on
        the wire instead of one per token — token-identical to
        ``stream()``, at block-granularity overhead."""
        req = self._reqs.get(req_id)
        if req is None:
            req = self._done[req_id]
        try:
            while True:
                item = await req.out.get()
                if item is None:
                    if self.error is not None and not req.finished:
                        raise RuntimeError("engine loop died") from self.error
                    return
                blk = [item]
                while True:
                    try:
                        nxt = req.out.get_nowait()
                    except asyncio.QueueEmpty:
                        break
                    if nxt is None:
                        # terminal already queued behind the block
                        yield blk
                        if self.error is not None and not req.finished:
                            raise RuntimeError(
                                "engine loop died") from self.error
                        return
                    blk.append(nxt)
                yield blk
        finally:
            self._done.pop(req_id, None)

    async def generate(self, prompt_tokens: list[int], **kw) -> list[int]:
        rid = self.submit(prompt_tokens, **kw)
        out: list[int] = []
        # block-granular drain: one loop wake per fused decode block
        # instead of one per token
        async for blk in self.stream_blocks(rid):
            out.extend(blk)
        return out

    def cancel(self, req_id: int):
        req = self._reqs.get(req_id)
        if req is not None:
            req.cancelled = True
            self._wake.set()

    # ------------------------------------------------------------ internals
    def _alloc_pages(self, n: int) -> list[int] | None:
        if len(self.free_pages) < n:
            return None
        out = self.free_pages[:n]
        del self.free_pages[:n]
        return out

    def _free_slot(self, slot: int):
        req = self.slot_req[slot]
        self.slot_req[slot] = None
        # the table holds ALL pages allocated at admission (prompt +
        # max_tokens worth), not just the ones reached — free every entry
        self.free_pages.extend(
            int(p) for p in self.page_tables[slot] if p != 0)
        self.page_tables[slot, :] = 0
        self.seq_lens[slot] = 0
        if req is not None:
            self._finish_stream(req)

    def _finish_stream(self, req: _Request) -> None:
        """Unregister a request and close its token stream: live -> the
        bounded finished-awaiting-drain map (stream() can still reach the
        queued tokens; cancel() only sees live requests; the cap bounds
        leakage from never-streamed submits)."""
        self._reqs.pop(req.req_id, None)
        self._done[req.req_id] = req
        while len(self._done) > self._done_cap:
            self._done.popitem(last=False)
        req.out.put_nowait(None)

    def _reserve_slot(self, req: _Request) -> int | None:
        """Claim a slot + pages for one waiting request (host bookkeeping
        only; the prefill itself is dispatched per wave)."""
        slot = next((i for i, r in enumerate(self.slot_req) if r is None), -1)
        if slot < 0:
            return None
        Tp = len(req.prompt)
        n_need = -(-(Tp + req.max_tokens) // self.PS)
        pages = self._alloc_pages(n_need)
        if pages is None:
            return None
        req.slot = slot
        self.slot_req[slot] = req
        self.page_tables[slot, :] = 0
        self.page_tables[slot, :n_need] = pages
        self.seq_lens[slot] = Tp
        self.temps[slot] = req.temperature
        self.aids[slot] = req.adapter
        if self.spec_enable:
            # drafter context: the prompt (first token lands at
            # _admit_wave emission, generated tokens at spec emission)
            self.hist[slot, :] = 0
            self.hist[slot, :Tp] = req.prompt
        return slot

    _WAVE_BUCKETS = (1, 2, 4, 8, 16)

    def _admit_wave(self) -> bool:
        """Admit every waiting request that fits, prefilling each pad
        bucket's group in ONE device dispatch (one host sync per group,
        not per request). Returns True if anything was admitted."""
        groups = self._admit_dispatch()
        for reqs, first in groups:
            first = np.asarray(first)  # ONE sync per group
            for j, req in enumerate(reqs):
                self.next_tok[req.slot] = int(first[j])
                if self.spec_enable:
                    self.hist[req.slot, len(req.prompt)] = int(first[j])
                self._emit(req, int(first[j]))
        return bool(groups)

    def _admit_dispatch(self) -> list[tuple[list[_Request], object]]:
        """Reserve slots and DISPATCH batched prefills for every waiting
        request that fits; no host sync — returns [(requests,
        first-token device array)] per pad-bucket group."""
        groups: dict[int, list[_Request]] = {}
        adopted: list[_Request] = []
        while self.waiting:
            nxt = self.waiting[0]
            if nxt.cancelled:
                self.waiting.pop(0)
                self._finish_stream(nxt)
                continue
            if self._reserve_slot(nxt) is None:
                break
            self.waiting.pop(0)
            if nxt.prefilled is not None:
                adopted.append(nxt)
                continue
            Tp_pad = -(-len(nxt.prompt) // self.PS) * self.PS
            groups.setdefault(Tp_pad, []).append(nxt)
        out = []
        for req in adopted:
            # slot adoption (llm/disagg): the prompt KV was produced by a
            # prefill worker and fetched via the KV-page plane — scatter
            # it into this pool's freshly allocated pages. Runs at the
            # same admission points as prefill dispatches, so the
            # functional pool update is ordered exactly like one.
            k_stack, v_stack, first = req.prefilled
            req.prefilled = None  # release the host copies after scatter
            n_cover = -(-len(req.prompt) // self.PS)
            rows = self.page_tables[req.slot, :n_cover].copy()
            self.kpool = scatter_pages(self.kpool, rows, k_stack)
            self.vpool = scatter_pages(self.vpool, rows, v_stack)
            out.append(([req], np.asarray([first], np.int32)))
        for Tp_pad, reqs in groups.items():
            npages = Tp_pad // self.PS
            nb = next(b for b in self._WAVE_BUCKETS if b >= len(reqs)) \
                if len(reqs) <= self._WAVE_BUCKETS[-1] else len(reqs)
            toks = np.zeros((nb, Tp_pad), np.int32)
            pages = np.zeros((nb, npages), np.int32)  # dummy rows: junk page
            aids = np.zeros(nb, np.int32)
            true_lens = np.ones(nb, np.int32)
            temps = np.zeros(nb, np.float32)
            for j, req in enumerate(reqs):
                toks[j, :len(req.prompt)] = req.prompt
                pages[j] = self.page_tables[req.slot, :npages]
                aids[j] = req.adapter
                true_lens[j] = len(req.prompt)
                temps[j] = req.temperature
            self._rng, sub = jax.random.split(self._rng)
            first, self.kpool, self.vpool = paged_prefill_batch(
                self.params, self.loras, jnp.asarray(aids),
                jnp.asarray(toks), jnp.asarray(pages), self.kpool,
                self.vpool, jnp.asarray(true_lens), jnp.asarray(temps),
                sub, self.cfg)
            out.append((reqs, first))
        return out

    def _emit(self, req: _Request, tok: int):
        req.emitted += 1
        self.tokens_out += 1
        req.out.put_nowait(tok)
        if req.emitted >= req.max_tokens or (
                self.eos_id is not None and tok == self.eos_id):
            req.finished = True
            req.cancelled = True  # finished: reclaim on the next sweep
            if req.slot < 0:
                # planned mode already retired the slot; close the stream
                self._finish_stream(req)

    async def _loop(self):
        """Engine driver. Any exception here is fatal for the engine:
        record it, fail every live stream, and exit — hung consumers on a
        silently dead loop are the worst failure mode."""
        try:
            await self._loop_inner()
        except BaseException as e:  # noqa: BLE001
            self.error = e
            self._running = False
            self._terminate_all_streams()
            import traceback

            traceback.print_exc()

    @staticmethod
    def _ramp(emitted: int) -> int:
        # per-request fusion ramp: fresh requests decode in small blocks
        # (streaming first-token latency, fast completion of short
        # requests, bounded admission latency for newcomers), deep ones
        # amortize dispatch with bigger ones. Capped at 32 — the ramp only
        # applies at low occupancy, where a 64-block would let a lone
        # generation schedule so far ahead that a newcomer queues behind
        # all of it; the 64 bucket is reserved for full batches.
        if emitted < 8:
            return 8
        if emitted < 24:
            return 16
        return 32

    def _pick_block(self, planned: bool = False) -> int:
        """Fused-steps bucket for this dispatch: the smallest bucket
        covering every active request's ramp, each capped by its exact
        remaining count (no over-decode on final blocks). A request about
        to finish therefore caps the block so it completes — and frees
        its slot for waiting admissions — without riding out a long
        batch's block (continuous-batching latency semantics).

        At high occupancy the ramp is skipped: a full batch is the
        throughput regime, where small early blocks would multiply
        dispatch round trips for no latency benefit (newcomers can't be
        admitted into a full batch anyway).

        ``planned`` counts dispatch-scheduled tokens instead of emitted
        ones (the planned loop runs ahead of emission)."""
        live = [r for r in self.slot_req
                if r is not None and not r.cancelled]
        if not live:
            return 1

        def done_count(r):
            return r.planned if planned else r.emitted

        if 2 * len(live) >= self.B:
            want = min(r.max_tokens - done_count(r) for r in live)
        else:
            want = min(min(self._ramp(done_count(r)),
                           r.max_tokens - done_count(r)) for r in live)
        want = max(1, want)
        for b in self.block_buckets:
            if want <= b:
                return b
        return self.block_buckets[-1]

    def _emit_block(self, entry) -> None:
        """Host-side emission of one synced decode block."""
        K, toks, slot_snapshot = entry
        toks = np.asarray(toks)  # [K, B]; blocks until the device is done
        self.steps += K
        for i, req in enumerate(slot_snapshot):
            if req is None:
                continue
            if self.slot_req[i] is req:
                # planned mode may have retired + re-admitted this slot
                # while the block was in flight; host per-slot state then
                # belongs to the newcomer
                self.seq_lens[i] += K
            for k in range(K):
                if req.cancelled:
                    break  # finished/cancelled mid-block: discard rest
                tok = int(toks[k, i])
                if self.slot_req[i] is req:
                    self.next_tok[i] = tok
                self._emit(req, tok)

    async def _loop_inner(self):
        if self.spec_enable:
            # accepted counts are data-dependent: completion steps are
            # unknowable at dispatch, so spec mode always drives the
            # reactive-shaped loop (planned mode needs a schedule)
            await self._loop_spec()
        elif self.eos_id is None:
            await self._loop_planned()
        else:
            await self._loop_reactive()

    async def _loop_planned(self):
        """Fully pipelined driver for length-deterministic generation
        (no EOS): every request's completion step is known at dispatch
        time, so slots are retired and re-admitted ON SCHEDULE without
        ever draining the pipeline — prefills, carry merges and decode
        blocks stream to the device back to back, and the only host syncs
        are the trailing token emissions riding two blocks behind."""
        pending: list = []  # dispatch-ordered: ("prefill",...)|("block",...)
        carry = None

        def sync_oldest():
            kind, *rest = pending.pop(0)
            if kind == "prefill":
                reqs, first = rest
                first = np.asarray(first)
                for j, req in enumerate(reqs):
                    if not req.cancelled:  # user-cancelled: stream closed
                        self._emit(req, int(first[j]))
            else:
                self._emit_block(rest)

        while self._running:
            # retire slots whose scheduled tokens are all dispatched; their
            # in-flight junk writes land on pages ordered BEFORE any new
            # prefill, so immediate reuse is safe (see paged_decode_multi)
            for i, req in enumerate(self.slot_req):
                if req is not None and (req.planned >= req.max_tokens
                                        or req.cancelled):
                    req.slot = -1  # emission closes the stream at finish
                    self.slot_req[i] = None
                    self.free_pages.extend(
                        int(p) for p in self.page_tables[i] if p != 0)
                    self.page_tables[i, :] = 0
                    self.seq_lens[i] = 0
                    if req.cancelled and not req.finished:
                        # user-cancelled: no finish emission will ever
                        # close this stream — close it here
                        self._finish_stream(req)
            if self.waiting and any(r is None for r in self.slot_req):
                groups = self._admit_dispatch()
                if groups:
                    if carry is None:
                        carry = (jnp.asarray(self.next_tok.copy()),
                                 jnp.asarray(self.seq_lens.copy()))
                    tok_d, lens_d = carry
                    for reqs, first in groups:
                        slots = jnp.asarray([r.slot for r in reqs],
                                            jnp.int32)
                        lens = jnp.asarray(
                            [len(r.prompt) for r in reqs], jnp.int32)
                        # device-side carry merge: no host sync
                        tok_d = tok_d.at[slots].set(first[:len(reqs)])
                        lens_d = lens_d.at[slots].set(lens)
                        for r in reqs:
                            r.planned = 1
                        pending.append(("prefill", reqs, first))
                    carry = (tok_d, lens_d)
            live = [r for r in self.slot_req if r is not None]
            if not live:
                while pending:
                    sync_oldest()
                    # yield between blocks: consumers must observe tokens
                    # in emission order, not one burst after the drain
                    await asyncio.sleep(0)
                carry = None
                self._wake.clear()
                try:
                    await asyncio.wait_for(self._wake.wait(), timeout=1.0)
                except asyncio.TimeoutError:
                    pass
                continue
            # pace dispatch to emission + 2 entries: enough run-ahead to
            # hide the dispatch round trip under device compute, little
            # enough that a newly arriving request interleaves within a
            # couple of blocks instead of queueing behind a whole
            # pre-scheduled generation. Yield right after each sync so
            # consumers see tokens before the next dispatch (whose first
            # use may compile) occupies the loop thread.
            while len(pending) >= 2:
                sync_oldest()
                await asyncio.sleep(0)
            K = self._pick_block(planned=True)
            self._rng, sub = jax.random.split(self._rng)
            # .copy() on every host array that this loop later mutates
            # (page_tables/seq_lens/next_tok/aids/temps): PJRT CPU
            # zero-copies aligned numpy buffers into device arrays, so a
            # retire/emission mutation while the async dispatch is still
            # in flight would corrupt the program's view of them (race
            # observed as garbage decode tokens under load).
            if carry is None:
                carry = (jnp.asarray(self.next_tok.copy()),
                         jnp.asarray(self.seq_lens.copy()))
            tok_d, lens_d = carry
            active = np.array([r is not None for r in self.slot_req])
            toks, tok_d, lens_d, self.kpool, self.vpool = paged_decode_multi(
                self.params, self.loras, jnp.asarray(self.aids.copy()),
                tok_d, lens_d, jnp.asarray(self.page_tables.copy()),
                self.kpool, self.vpool, jnp.asarray(active),
                jnp.asarray(self.temps.copy()), sub, self.cfg, K)
            carry = (tok_d, lens_d)
            for r in live:
                r.planned = min(r.max_tokens, r.planned + K)
            pending.append(("block", K, toks, list(self.slot_req)))
            await asyncio.sleep(0)

    async def _loop_reactive(self):
        # pipeline of dispatched-but-unsynced decode blocks. Depth 2:
        # block N+1 is enqueued before block N's tokens come back, so the
        # tunnel round trip rides under device compute. The (tok, pos)
        # carry chains ON DEVICE between pipelined blocks; it is rebuilt
        # from host state only after the pipeline drains at admission
        # points (a new slot changes page_tables/active for the next
        # dispatch).
        pending: list = []
        carry = None  # (tok_dev, lens_dev) device-resident between blocks

        def drain():
            while pending:
                self._emit_block(pending.pop(0))

        while self._running:
            for i, req in enumerate(self.slot_req):
                if req is not None and req.cancelled and req.slot >= 0:
                    if pending:
                        break  # free only with no block in flight
                    self._free_slot(i)
            if self.waiting and any(r is None for r in self.slot_req):
                drain()  # admission changes device-visible state
                for i, req in enumerate(self.slot_req):
                    if req is not None and req.cancelled:
                        self._free_slot(i)
                if self._admit_wave():
                    carry = None
                    # the wave just emitted each admitted request's
                    # prefill token: let consumers flush it (TTFC) before
                    # the next decode dispatch occupies the loop thread
                    await asyncio.sleep(0)
            active = np.array([r is not None for r in self.slot_req])
            if not active.any():
                drain()
                # idle, OR the head-of-queue request can't be admitted yet
                # (pages still held elsewhere): either way we must yield —
                # a bare continue would spin the loop without ever
                # letting consumers/stop() run
                self._wake.clear()
                try:
                    await asyncio.wait_for(self._wake.wait(), timeout=1.0)
                except asyncio.TimeoutError:
                    pass
                continue
            K = self._pick_block()
            self._rng, sub = jax.random.split(self._rng)
            if carry is None:
                tok_d = jnp.asarray(self.next_tok.copy())
                lens_d = jnp.asarray(self.seq_lens.copy())
            else:
                tok_d, lens_d = carry
            toks, tok_d, lens_d, self.kpool, self.vpool = paged_decode_multi(
                self.params, self.loras, jnp.asarray(self.aids.copy()),
                tok_d, lens_d, jnp.asarray(self.page_tables.copy()),
                self.kpool, self.vpool, jnp.asarray(active),
                jnp.asarray(self.temps.copy()), sub, self.cfg, K)
            carry = (tok_d, lens_d)
            pending.append((K, toks, list(self.slot_req)))
            if len(pending) >= 2:
                self._emit_block(pending.pop(0))
            # a finished request must stop the pipeline at the next
            # admission point rather than over-decoding forever
            if any(r is not None and r.cancelled for r in self.slot_req):
                drain()
                carry = None
            # hand the loop to consumers/admitters every block
            await asyncio.sleep(0)

    # ------------------------------------------------------- speculative loop
    _SPEC_BUCKETS = (1, 2, 4)

    def _spec_inflight_steps(self, pending) -> list[int]:
        """Per-slot spec steps already dispatched but not yet synced."""
        steps = [0] * self.B
        for entry in pending:
            S, snap = entry[0], entry[4]
            for i, rq in enumerate(snap):
                if rq is not None and self.slot_req[i] is rq:
                    steps[i] += S
        return steps

    def _pick_spec_block(self, deficits: list[int]) -> int:
        """Fused spec-steps bucket: sized to the smallest GUARANTEED
        remaining need (each step advances >= 1 token), so a finishing
        request frees its slot without riding out a long block. Buckets
        stop at 4: a spec step can emit up to k+1 tokens, and the
        optimistic dispatch gate stops issuing blocks once in-flight
        steps COULD satisfy every request — a coarser bucket would turn
        that possibility into up to a whole wasted block of verifies."""
        want = max(1, min(deficits))
        for b in self._SPEC_BUCKETS:
            if want <= b:
                return b
        return self._SPEC_BUCKETS[-1]

    def _host_drafts(self, spec_ok):
        """Drafter-hook path: ask ``spec_drafter(context, pos, k)`` for
        up to k draft tokens per live greedy slot. ``context`` is the
        slot's token history through the pending input (a numpy view),
        ``pos`` its length minus one — the small-model-on-TPU hook rides
        here."""
        k = self.spec_k
        drafts = np.zeros((self.B, k), np.int32)
        dlens = np.zeros(self.B, np.int32)
        for i, req in enumerate(self.slot_req):
            if req is None or not spec_ok[i]:
                continue
            n = int(self.seq_lens[i])
            got = list(self.spec_drafter(self.hist[i, :n + 1], n, k))[:k]
            drafts[i, :len(got)] = got
            dlens[i] = len(got)
        return drafts, dlens

    def _emit_spec_block(self, entry) -> None:
        """Host-side emission of one synced speculative block: per step
        and slot, emit the first ``n_emit`` candidate tokens (the
        accepted drafts plus the target's correction/bonus token) and
        discard the rest — the rejected tail's rollback is exactly this
        truncation plus the seq_lens arithmetic (the junk KV those
        positions hold is overwritten when they are legitimately
        decoded)."""
        S, toks, n_emit, n_prop, snapshot, spec_snap = entry
        toks = np.asarray(toks)      # [S, B, k+1]; ONE sync per block
        n_emit = np.asarray(n_emit)  # [S, B]
        n_prop = np.asarray(n_prop)
        self.steps += S
        self.spec_steps += S
        emitted = proposed = accepted = 0
        H = self.hist.shape[1]
        for s in range(S):
            for i, req in enumerate(snapshot):
                if req is None:
                    continue
                ne = int(n_emit[s, i])
                if ne <= 0:
                    continue
                live = self.slot_req[i] is req
                if live:
                    base = int(self.seq_lens[i])
                    self.seq_lens[i] += ne
                if spec_snap[i] and not req.cancelled:
                    proposed += int(n_prop[s, i])
                    accepted += ne - 1
                for j in range(ne):
                    if req.cancelled:
                        break  # finished/cancelled mid-block: discard
                    tok = int(toks[s, i, j])
                    if live:
                        self.next_tok[i] = tok
                        if base + j + 1 < H:
                            self.hist[i, base + j + 1] = tok
                    emitted += 1
                    self._emit(req, tok)
        self.spec_proposed += proposed
        self.spec_accepted += accepted
        self._block_log.append((S, emitted, proposed, accepted))

    async def _loop_spec(self):
        """Speculative driver (reactive shape, README § Speculative
        decoding): with the on-device n-gram drafter the whole
        draft→verify→accept cycle lives inside ``paged_decode_spec``'s
        scan, the (token, position, history) carry chains on device, and
        blocks pipeline 2-deep exactly like ``_loop_reactive``. With a
        host ``spec_drafter`` hook each dispatch is one verify step and
        syncs immediately — the drafter needs the accepted tokens before
        it can propose the next window."""
        pending: list = []
        carry = None  # (tok_dev, lens_dev, hist_dev) between blocks
        # device uploads of the per-slot tables (page_tables/aids/temps/
        # active/spec_ok): these only change at admission/free points,
        # exactly where carry resets — hoisting them out of the dispatch
        # keeps the per-block host cost at one RNG split + one append
        # (spec blocks are smaller than plain blocks, so per-dispatch
        # overhead multiplies faster here)
        statics = None
        k = self.spec_k
        host_draft = callable(self.spec_drafter)

        def drain():
            while pending:
                self._emit_spec_block(pending.pop(0))

        while self._running:
            for i, req in enumerate(self.slot_req):
                if req is not None and req.cancelled and req.slot >= 0:
                    if pending:
                        break  # free only with no block in flight
                    self._free_slot(i)
            if self.waiting and any(r is None for r in self.slot_req):
                drain()  # admission changes device-visible state
                for i, req in enumerate(self.slot_req):
                    if req is not None and req.cancelled:
                        self._free_slot(i)
                if self._admit_wave():
                    carry = None
                    # flush the just-emitted prefill tokens (TTFC) before
                    # the next spec dispatch occupies the loop thread
                    await asyncio.sleep(0)
            active = np.array([r is not None for r in self.slot_req])
            if not active.any():
                drain()
                carry = None
                self._wake.clear()
                try:
                    await asyncio.wait_for(self._wake.wait(), timeout=1.0)
                except asyncio.TimeoutError:
                    pass
                continue
            # optimistic dispatch gate: a spec step emits 1..k+1 tokens,
            # so in-flight blocks COULD have satisfied a request long
            # before the 1-token lower bound says so. Once every live
            # request's optimistic bound (emitted + (k+1) x in-flight
            # steps) covers its budget, SYNC the oldest block instead of
            # dispatching — at high accept rates this is what keeps the
            # loop from verifying junk a finished request will discard;
            # when acceptance was actually low the sync corrects the
            # bound from real emissions and dispatch resumes.
            inflight = self._spec_inflight_steps(pending)
            deficits = [r.max_tokens - r.emitted - (k + 1) * inflight[i]
                        for i, r in enumerate(self.slot_req)
                        if r is not None and not r.cancelled]
            if not deficits or max(deficits) <= 0:
                if pending:
                    self._emit_spec_block(pending.pop(0))
                else:
                    self._wake.clear()
                    try:
                        await asyncio.wait_for(self._wake.wait(),
                                               timeout=0.05)
                    except asyncio.TimeoutError:
                        pass
                if any(r is not None and r.cancelled
                       for r in self.slot_req):
                    drain()
                    carry = None
                await asyncio.sleep(0)
                continue
            self._rng, sub = jax.random.split(self._rng)
            if carry is None:
                carry = (jnp.asarray(self.next_tok.copy()),
                         jnp.asarray(self.seq_lens.copy()),
                         jnp.asarray(self.hist.copy()))
                statics = None
            if statics is None:
                spec_ok = np.array([
                    r is not None and not r.cancelled and r.spec
                    and r.temperature <= 0 for r in self.slot_req])
                statics = (jnp.asarray(self.aids.copy()),
                           jnp.asarray(self.page_tables.copy()),
                           jnp.asarray(active),
                           jnp.asarray(spec_ok),
                           jnp.asarray(self.temps.copy()),
                           spec_ok)
            aids_d, pt_d, act_d, sok_d, tmp_d, spec_ok = statics
            tok_d, lens_d, hist_d = carry
            if host_draft:
                drafts, dlens = self._host_drafts(spec_ok)
                (toks, n_emit, n_prop, tok_d, lens_d, self.kpool,
                 self.vpool) = paged_decode_verify(
                    self.params, self.loras, aids_d, tok_d, lens_d,
                    jnp.asarray(drafts), pt_d, self.kpool, self.vpool,
                    jnp.asarray(dlens), act_d, tmp_d, sub, self.cfg, k)
                self._emit_spec_block((1, toks[None], n_emit[None],
                                       n_prop[None], list(self.slot_req),
                                       spec_ok))
                carry = None  # host state is authoritative per step
            else:
                S = self._pick_spec_block([d for d in deficits if d > 0])
                if chaos.ENABLED:
                    # "llm.spec_block": fires once per fused speculative
                    # block — a seeded kill here dies MID-speculative-
                    # window (accepted-but-unsynced tokens in flight),
                    # the recovery window tests/plans/spec_decode_kill
                    # exercises
                    chaos.point("llm.spec_block", steps=S, k=k)
                (toks, n_emit, n_prop, tok_d, lens_d, hist_d, self.kpool,
                 self.vpool) = paged_decode_spec(
                    self.params, self.loras, aids_d, tok_d, lens_d,
                    hist_d, pt_d, self.kpool, self.vpool, act_d, sok_d,
                    tmp_d, sub, self.cfg, S, k, self.spec_ngram)
                carry = (tok_d, lens_d, hist_d)
                pending.append((S, toks, n_emit, n_prop,
                                list(self.slot_req), spec_ok))
                if len(pending) >= 2:
                    self._emit_spec_block(pending.pop(0))
            if any(r is not None and r.cancelled for r in self.slot_req):
                drain()
                carry = None
            await asyncio.sleep(0)

"""ray_tpu.llm — LLM batch inference + serving on the ray_tpu runtime.

TPU-native counterpart of ray.llm (ref: python/ray/llm/): the engine is
not vLLM but owned — a jit-compiled prefill + decode over the native
Llama implementation (static shapes, batched MXU matmuls), with a
continuous-batching paged-KV engine for serving.

- generation: prefill/decode_step/generate with left-padded ragged batches
- engine: ContinuousBatchingEngine — paged KV, decode-step admission,
  token streaming, LoRA multiplexing
- serving: LLMServer (@serve.batch coalescing) and LLMEngineServer
  (continuous batching + streaming) deployments
- batch: build_llm_processor over ray_tpu.data datasets
- disagg: disaggregated serving — prefill/decode pools over the KV-page
  plane with cross-request prefix caching (DisaggLLMServer)
"""
from ray_tpu.llm.batch import build_llm_processor
from ray_tpu.llm.disagg import (
    DecodeWorker,
    DisaggLLMServer,
    KVPageManifest,
    PrefillWorker,
    PrefixCache,
    build_disagg_deployment,
    prefix_hint,
)
from ray_tpu.llm.engine import ContinuousBatchingEngine, EngineFull
from ray_tpu.llm.generation import generate, generate_tokens, pad_prompts
from ray_tpu.llm.serving import (
    LLMEngineServer,
    LLMServer,
    build_llm_deployment,
    build_llm_engine_deployment,
)

__all__ = [
    "ContinuousBatchingEngine",
    "DecodeWorker",
    "DisaggLLMServer",
    "EngineFull",
    "KVPageManifest",
    "LLMEngineServer",
    "LLMServer",
    "PrefillWorker",
    "PrefixCache",
    "build_disagg_deployment",
    "build_llm_deployment",
    "build_llm_engine_deployment",
    "build_llm_processor",
    "generate",
    "generate_tokens",
    "pad_prompts",
]

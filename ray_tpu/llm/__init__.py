"""ray_tpu.llm — LLM batch inference + serving on the ray_tpu runtime.

TPU-native counterpart of ray.llm (ref: python/ray/llm/): the engine is
not vLLM but a jit-compiled prefill + lax.scan KV-cache decode over the
native Llama implementation (static shapes, batched MXU matmuls).

- generation: prefill/decode_step/generate with left-padded ragged batches
- serving: LLMServer deployment (@serve.batch coalescing) +
  build_llm_deployment
- batch: build_llm_processor over ray_tpu.data datasets
"""
from ray_tpu.llm.batch import build_llm_processor
from ray_tpu.llm.generation import generate, generate_tokens, pad_prompts
from ray_tpu.llm.serving import LLMServer, build_llm_deployment

__all__ = [
    "LLMServer",
    "build_llm_deployment",
    "build_llm_processor",
    "generate",
    "generate_tokens",
    "pad_prompts",
]

"""LLM serving deployment: batched decode behind ray_tpu.serve.

TPU-native counterpart of the reference serve-LLM stack (ref:
python/ray/llm/_internal/serve/ — LLMServer + vLLM engine + OpenAI
router). The deployment batches concurrent requests into ONE generate
call via @serve.batch (the MXU wants batch-N decode, not N batch-1
loops) and exposes an OpenAI-completions-shaped dict protocol that the
HTTP proxy serves at /{app}/LLMServer.
"""
from __future__ import annotations

import time


class LLMServer:
    """Deployment class; bind with a model config + params source."""

    def __init__(self, model_config, params=None, params_fn=None,
                 max_batch_size: int = 8, batch_wait_timeout_s: float = 0.02,
                 default_max_tokens: int = 32):
        from ray_tpu import serve
        from ray_tpu.utils.device import configure_jax

        configure_jax()
        self.cfg = model_config
        if params is None:
            params = params_fn() if params_fn is not None else None
        if params is None:
            import jax

            from ray_tpu.models.llama import llama_init

            params = llama_init(jax.random.PRNGKey(0), model_config)
        self.params = params
        self.default_max_tokens = default_max_tokens
        self._batched = serve.batch(
            max_batch_size=max_batch_size,
            batch_wait_timeout_s=batch_wait_timeout_s,
        )(self._generate_batch)

    async def _generate_batch(self, requests: list[dict]) -> list[dict]:
        from ray_tpu.llm.generation import generate

        t0 = time.monotonic()
        max_new = max(
            int(r.get("max_tokens", self.default_max_tokens)) for r in requests
        )
        # sampling settings are per-request: decode one sub-batch per
        # distinct temperature so no request's settings are overridden
        by_temp: dict[float, list[int]] = {}
        for i, r in enumerate(requests):
            by_temp.setdefault(float(r.get("temperature", 0.0)), []).append(i)
        outs: list = [None] * len(requests)
        for temp, idxs in by_temp.items():
            sub = generate(
                self.params, self.cfg,
                [list(requests[i]["prompt_tokens"]) for i in idxs],
                max_new_tokens=max_new, temperature=temp,
            )
            for i, o in zip(idxs, sub):
                outs[i] = o
        dt = time.monotonic() - t0
        results = []
        for r, out in zip(requests, outs):
            want = int(r.get("max_tokens", self.default_max_tokens))
            results.append({
                "completion_tokens": out[:want],
                "usage": {
                    "prompt_tokens": len(r["prompt_tokens"]),
                    "completion_tokens": want,
                    "batch_size": len(requests),
                    "latency_s": dt,
                },
            })
        return results

    async def __call__(self, request: dict) -> dict:
        """request: {prompt_tokens: [...], max_tokens?, temperature?}"""
        return await self._batched(request)


class LLMEngineServer:
    """Deployment around the continuous-batching engine (ref: the vLLM
    engine the reference delegates to, vllm_engine.py:95 — owned here).
    Requests join the running decode batch at step granularity; responses
    can stream token-by-token; "model" selects a LoRA adapter
    (ref: serve/multiplex.py model multiplexing)."""

    def __init__(self, model_config, params=None, params_fn=None, *,
                 max_batch: int = 8, page_size: int = 16, n_pages: int = 512,
                 max_seq_len: int = 512, eos_id: int | None = None,
                 lora_adapters: dict | None = None, lora_rank: int = 8,
                 default_max_tokens: int = 32, kv_dtype: str | None = None):
        from ray_tpu.utils.device import configure_jax

        configure_jax()
        if params is None:
            params = params_fn() if params_fn is not None else None
        if params is None:
            import jax

            from ray_tpu.models.llama import llama_init

            params = llama_init(jax.random.PRNGKey(0), model_config)
        from ray_tpu.llm.engine import ContinuousBatchingEngine

        self.engine = ContinuousBatchingEngine(
            params, model_config, max_batch=max_batch, page_size=page_size,
            n_pages=n_pages, max_seq_len=max_seq_len, eos_id=eos_id,
            lora_adapters=lora_adapters, lora_rank=lora_rank,
            kv_dtype=kv_dtype)
        self.default_max_tokens = default_max_tokens

    async def _ensure_started(self):
        await self.engine.start()

    def _submit(self, request: dict) -> int:
        from ray_tpu.llm.engine import EngineFull
        from ray_tpu.serve.exceptions import BackPressureError

        try:
            return self.engine.submit(
                list(request["prompt_tokens"]),
                max_tokens=int(request.get("max_tokens",
                                           self.default_max_tokens)),
                temperature=float(request.get("temperature", 0.0)),
                adapter=request.get("model"),
            )
        except EngineFull as e:
            # typed, never-dispatched refusal: the PR 6 router retries /
            # hedges this request on another replica instead of surfacing
            # an untyped ActorError from an overloaded engine
            raise BackPressureError(
                f"LLM engine full: {e}",
                # a waiting slot frees at decode-block granularity; queue
                # depth is the best local estimate of the drain time
                retry_after_s=min(2.0,
                                  0.02 * (1 + len(self.engine.waiting))),
            ) from None

    async def __call__(self, request: dict) -> dict:
        """Full completion: {prompt_tokens, max_tokens?, temperature?,
        model?} -> {completion_tokens, usage}."""
        await self._ensure_started()
        t0 = time.monotonic()
        rid = self._submit(request)
        # block-granular drain: the engine emits whole fused decode
        # blocks host-side, so draining per block costs one loop wake per
        # block instead of one per token
        out: list[int] = []
        async for blk in self.engine.stream_blocks(rid):
            out.extend(blk)
        return {
            "completion_tokens": out,
            "usage": {
                "prompt_tokens": len(request["prompt_tokens"]),
                "completion_tokens": len(out),
                "latency_s": time.monotonic() - t0,
            },
        }

    async def stream(self, request: dict):
        """Async generator of token ids — served to callers through the
        handle's .stream() (one ObjectRef per token). An abandoned
        consumer cancels the request: the decode slot and its KV pages
        free at the next block boundary, not when the generation would
        have finished."""
        await self._ensure_started()
        rid = self._submit(request)
        try:
            async for tok in self.engine.stream(rid):
                yield tok
        finally:
            self.engine.cancel(rid)  # no-op once finished

    async def stream_deltas(self, request: dict):
        """Streaming-serve producer: one ``{"tokens": [...]}`` delta per
        fused decode block (served as one "G" chunk record each through
        the handle's ``.stream_chunks()``), then a terminal delta with
        ``usage``. Token-identical to ``__call__``'s completion_tokens.
        Closing the stream mid-generation cancels the engine request —
        the replica wrapper's GeneratorExit reaches the ``finally`` here
        and the decode slot frees at the next block boundary."""
        await self._ensure_started()
        t0 = time.monotonic()
        rid = self._submit(request)
        n = 0
        try:
            async for blk in self.engine.stream_blocks(rid):
                n += len(blk)
                yield {"tokens": blk}
            yield {
                "tokens": [],
                "done": True,
                "usage": {
                    "prompt_tokens": len(request["prompt_tokens"]),
                    "completion_tokens": n,
                    "latency_s": time.monotonic() - t0,
                },
            }
        finally:
            self.engine.cancel(rid)  # no-op once finished

    def engine_stats(self) -> dict:
        return {"steps": self.engine.steps, "tokens_out": self.engine.tokens_out,
                "waiting": len(self.engine.waiting),
                "free_pages": len(self.engine.free_pages)}


def build_llm_engine_deployment(model_config, *, params=None, params_fn=None,
                                num_replicas: int = 1, num_tpus: float = 0.0,
                                name: str = "LLMEngineServer", **engine_kw):
    """Bound serve application around the owned engine."""
    from ray_tpu import serve

    opts: dict = {}
    if num_tpus:
        opts["num_tpus"] = num_tpus
    dep = serve.deployment(
        LLMEngineServer,
        name=name,
        num_replicas=num_replicas,
        max_ongoing_requests=64,
        ray_actor_options=opts,
    )
    return dep.bind(model_config, params, params_fn, **engine_kw)


def build_llm_deployment(model_config, *, params=None, params_fn=None,
                         num_replicas: int = 1, max_batch_size: int = 8,
                         num_tpus: float = 0.0, name: str = "LLMServer"):
    """Bound serve application for a Llama config (ref: serve/llm
    build_openai_app shape)."""
    from ray_tpu import serve

    opts: dict = {}
    if num_tpus:
        opts["num_tpus"] = num_tpus
    dep = serve.deployment(
        LLMServer,
        name=name,
        num_replicas=num_replicas,
        max_ongoing_requests=max_batch_size * 2,
        ray_actor_options=opts,
    )
    return dep.bind(model_config, params, params_fn, max_batch_size)

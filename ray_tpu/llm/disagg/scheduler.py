"""DisaggLLMServer: the deployment that composes the planes.

One serve replica of this class fronts TWO actor pools (its private
prefill and decode workers) plus a replica-local :class:`PrefixCache`:

    request -> [admission: decode page headroom]
            -> [prefix cache lookup (pinned)]
            -> prefill pool   (full prompt, or suffix over cached pages)
            -> KV-page plane  (manifest: metadata through RPC, pages via shm)
            -> decode pool    (adopt + continuous-batching ring)
            -> [cache insert of the new full pages] -> response

Admission control is page-headroom based: the scheduler tracks an
optimistic in-flight page estimate per decode worker and refuses — with
the serve layer's typed :class:`BackPressureError`, carrying
``retry_after_s`` — before any prefill work is spent on a request the
decode pool cannot seat. ``EngineFull`` therefore never reaches a
caller: the PR 6 router treats the refusal as never-dispatched and
retries/hedges to another replica.

Fault story (the decode-death window ``tests/plans/llm_decode_kill.json``
exercises): a decode worker dying mid-request surfaces as an
ActorError-class failure. The prompt's KV pages live in the PREFILL
workers' shm arenas — they survive the death — so recovery is manifest
RE-ADOPTION on another decode worker, zero duplicate prefill FLOPs.
Only when the pages themselves are gone (KVShipError / ObjectLostError:
injected loss, arena eviction on a dead node) does the scheduler
re-prefill, counting it in ``duplicate_prefills`` so tests can bound the
wasted work.
"""

from __future__ import annotations

import asyncio
import itertools
import logging
import pickle
import time
import uuid

from ray_tpu.core.ref import (
    ActorError,
    ObjectLostError,
    WorkerCrashedError,
)
from ray_tpu.llm.disagg import telemetry
from ray_tpu.llm.disagg.kv_plane import KVPageManifest, KVShipError
from ray_tpu.llm.disagg.pools import DecodeWorker, PrefillWorker
from ray_tpu.llm.disagg.prefix_cache import PrefixCache

log = logging.getLogger(__name__)

#: GCS kv namespace of the cross-replica decode registry
#: (``decode_share_group``): each DisaggLLMServer replica publishes its
#: decode workers' handles + live signals under
#: ``<group>/<replica-uuid>`` so siblings can steal onto idle rings
_SHARE_NS = "llm_decode"
#: a sibling record older than this is a dead replica, not a target
_SHARE_TTL_S = 5.0


def _is_worker_death(e: BaseException) -> bool:
    from ray_tpu.utils import rpc

    return isinstance(e, (ActorError, WorkerCrashedError,
                          rpc.ConnectionLost))


class DisaggLLMServer:
    """Deployment class; bind with a model config + params source (the
    LLMEngineServer surface, served disaggregated)."""

    def __init__(self, model_config, params=None, params_fn=None, *,
                 n_prefill: int = 2, n_decode: int = 2,
                 max_batch: int = 8, page_size: int = 16,
                 n_pages: int = 256, max_seq_len: int = 512,
                 eos_id: int | None = None, kv_dtype: str | None = None,
                 lora_adapters: dict | None = None, lora_rank: int = 8,
                 default_max_tokens: int = 32,
                 prefix_cache_bytes: int = 64 << 20,
                 prefill_n_pages: int | None = None,
                 max_wave: int = 8, wave_wait_s: float = 0.004,
                 max_attempts: int = 3, decode_max_restarts: int = 2,
                 pool_resources: dict | None = None,
                 spec_enable: bool = False, spec_k: int = 4,
                 spec_ngram: int = 2, spec_drafter=None,
                 decode_share_group: str | None = None,
                 signal_refresh_s: float = 0.2):
        import ray_tpu

        self.PS = page_size
        self.n_pages = n_pages
        self.default_max_tokens = default_max_tokens
        self.max_attempts = max_attempts
        from ray_tpu.config import get_config
        _cfg = get_config()
        # tiering opt-in rides config (RT_PREFIX_CACHE_SPILL et al): the
        # replica's cache spills cold pages to the raylet's tier-1
        # instead of dropping them, so a refill-after-evict costs one
        # disk read instead of a duplicate prefill
        self.cache = PrefixCache(page_size,
                                 capacity_bytes=prefix_cache_bytes,
                                 kv_dtype=kv_dtype or "native",
                                 spill=bool(_cfg.prefix_cache_spill),
                                 tier1_capacity_bytes=int(
                                     _cfg.prefix_cache_tier1_bytes),
                                 spill_cold_after_s=float(
                                     _cfg.spill_cold_after_s))
        model_kw = dict(kv_dtype=kv_dtype, lora_adapters=lora_adapters,
                        lora_rank=lora_rank)
        # prefill pool: async actors with enough concurrency for calls to
        # coalesce into padded waves; staging pools freed per wave
        # pool placement (e.g. {"bee": 0.25} / TPU-host resources): pin
        # the pool workers beside their replica so pool hops ride the
        # same-node shm rings and KV adoption stays zero-copy
        pool_opts = ({"resources": dict(pool_resources)}
                     if pool_resources else {})
        pf_cls = ray_tpu.remote(PrefillWorker).options(
            max_concurrency=max(16, 4 * max_wave), **pool_opts)
        self.prefill_pool = [
            pf_cls.remote(model_config, params, params_fn,
                          page_size=page_size,
                          n_pages=prefill_n_pages or n_pages,
                          max_wave=max_wave, wave_wait_s=wave_wait_s,
                          seed=i, **model_kw)
            for i in range(n_prefill)]
        # decode pool: restartable (a killed worker rejoins the rotation;
        # in-flight requests re-adopt elsewhere meanwhile)
        dw_cls = ray_tpu.remote(DecodeWorker).options(
            max_concurrency=max(16, 2 * max_batch),
            max_restarts=decode_max_restarts, **pool_opts)
        self.decode_pool = [
            dw_cls.remote(model_config, params, params_fn,
                          max_batch=max_batch, page_size=page_size,
                          n_pages=n_pages, max_seq_len=max_seq_len,
                          eos_id=eos_id, spec_enable=spec_enable,
                          spec_k=spec_k, spec_ngram=spec_ngram,
                          spec_drafter=spec_drafter, **model_kw)
            for i in range(n_decode)]
        # optimistic in-flight page estimate per decode worker — the
        # admission-control floor (refreshed implicitly: reservations
        # are returned in the same finally that awaited the decode) —
        # plus a tokens-in-flight ledger and the decode workers' LIVE
        # signals (tokens_in_flight/free_pages probed by _signal_loop):
        # admission ranks workers by decode tokens still owed, not by
        # request counts
        self._est_pages = [0] * n_decode
        self._est_tokens = [0] * n_decode
        self._signals: list[dict | None] = [None] * n_decode
        self._capacity = n_pages - 1  # page 0 is the junk page
        self._pf_rr = itertools.count()
        self._dw_rr = itertools.count()
        # frozen per-(pool actor, method) fast-lane templates (_pool_call)
        self._pool_tmpls: dict = {}
        # cross-replica decode batching (decode_share_group): sibling
        # replicas' decode workers, flattened as key -> {handle, signal}
        self._share_group = decode_share_group
        self.signal_refresh_s = float(signal_refresh_s)
        self._uuid = uuid.uuid4().hex[:12]
        self._foreign: dict[str, dict] = {}
        self._sig_task = None
        self._last_req_ts = 0.0
        self.duplicate_prefills = 0
        self.decode_retries = 0
        self.backpressured = 0
        self.requests = 0
        self.decode_tokens = [0] * n_decode  # per-ring traffic proof
        self.stolen = 0          # requests decoded on a sibling replica
        self.stolen_tokens = 0

    # ------------------------------------------------------------ routing
    def _worker_load(self, i: int) -> int:
        """Decode tokens still owed by worker ``i``: the live probed
        tokens_in_flight plus our own picks the probe hasn't seen yet
        (the router's inflight-at-probe subtraction, run against the
        token ledger instead of request counts)."""
        sig = self._signals[i]
        if sig is not None and time.monotonic() - sig["ts"] < 2.0:
            unseen = max(0, self._est_tokens[i] - sig["est_at_tokens"])
            return sig["tokens_in_flight"] + unseen
        return self._est_tokens[i]

    def _worker_free_pages(self, i: int) -> int:
        """Free-page headroom for worker ``i``: the optimistic ledger,
        tightened by the live signal when fresh (a shared ring — steal
        traffic from sibling replicas — burns pages our ledger never
        saw)."""
        free = self._capacity - self._est_pages[i]
        sig = self._signals[i]
        if sig is not None and time.monotonic() - sig["ts"] < 2.0:
            unseen = max(0, self._est_pages[i] - sig["est_at_pages"])
            free = min(free, sig["free_pages"] - unseen)
        return free

    def _pick_decode(self, n_need: int, exclude: set[int]) -> int | None:
        """Signal-first pick: among workers with page headroom, take the
        one owing the FEWEST decode tokens (tokens-in-flight + page
        headroom are the admission signals — a ring full of nearly-done
        requests outranks a shallow queue of long generations, which
        request counts get backwards); round-robin start for tie
        spread. None = no pool-wide headroom (backpressure)."""
        start = next(self._dw_rr) % len(self.decode_pool)
        best, best_load = None, None
        for off in range(len(self.decode_pool)):
            i = (start + off) % len(self.decode_pool)
            if i in exclude:
                continue
            if self._worker_free_pages(i) < n_need:
                continue
            load = self._worker_load(i)
            if best_load is None or load < best_load:
                best, best_load = i, load
        return best

    def _pick_foreign(self, n_need: int,
                      exclude: set[str]) -> tuple[str, object] | None:
        """Idlest sibling-replica decode worker with page headroom (the
        work-stealing leg): returns (key, actor handle) or None. The
        signals come from the sibling's own probe loop via the GCS
        registry — stale entries age out at discovery."""
        best = best_load = None
        for key, ent in self._foreign.items():
            if key in exclude:
                continue
            sig = ent.get("signal") or {}
            if sig.get("free_pages", 0) < n_need:
                continue
            load = sig.get("tokens_in_flight", 0)
            if best_load is None or load < best_load:
                best, best_load = (key, ent["handle"]), load
        return best

    # ---------------------------------------------------- decode signals
    def _ensure_signal_loop(self):
        """Lazy-start the probe loop (and retire it after 3s idle — the
        router's probe-pause idiom); restarted by the next request."""
        self._last_req_ts = time.monotonic()
        if self._sig_task is None or self._sig_task.done():
            self._sig_task = asyncio.get_running_loop().create_task(
                self._signal_loop())

    async def _signal_loop(self):
        try:
            while time.monotonic() - self._last_req_ts < 3.0:
                for i, w in enumerate(self.decode_pool):
                    # snapshot the ledgers BEFORE the probe: anything we
                    # admit while the probe is in flight is "unseen"
                    est_t, est_p = self._est_tokens[i], self._est_pages[i]
                    try:
                        # bounded: a probe hung on a killed worker's
                        # half-broken lane must not wedge the loop (the
                        # respawned worker needs the NEXT probe)
                        hr = await asyncio.wait_for(
                            self._pool_call(w, "headroom", (), {}), 2.0)
                    except Exception:
                        continue  # dead/restarting worker: keep stale
                    self._signals[i] = {
                        "tokens_in_flight": int(
                            hr.get("tokens_in_flight", 0)),
                        "free_pages": int(hr.get("free_pages", 0)),
                        "est_at_tokens": est_t, "est_at_pages": est_p,
                        "ts": time.monotonic()}
                await self._share_sync()
                await asyncio.sleep(self.signal_refresh_s)
        except asyncio.CancelledError:
            raise
        except Exception:
            log.debug("decode signal loop died", exc_info=True)

    async def _gcs(self, method: str, payload: dict):
        from ray_tpu.core import api as _api

        core = _api.get_core()
        try:
            on_core = asyncio.get_running_loop() is core.loop
        except RuntimeError:
            on_core = False
        if on_core:
            return await core.gcs.call(method, payload)
        return await asyncio.wrap_future(asyncio.run_coroutine_threadsafe(
            core.gcs.call(method, payload), core.loop))

    async def _share_sync(self):
        """Publish our decode workers' handles + live signals to the
        share-group registry and refresh the sibling view. Handles
        pickle through the GCS kv like any actor arg; a steal then rides
        ``_pool_call`` (shm ring same-node, node tunnel cross-node)
        unchanged."""
        if not self._share_group:
            return
        try:
            rec = {"handles": list(self.decode_pool),
                   "signals": [self._signals[i] or {}
                               for i in range(len(self.decode_pool))],
                   "ts": time.time()}
            me = f"{self._share_group}/{self._uuid}"
            await self._gcs("kv_put", {"ns": _SHARE_NS, "key": me,
                                       "value": pickle.dumps(rec)})
            keys = await self._gcs("kv_keys", {
                "ns": _SHARE_NS, "prefix": f"{self._share_group}/"})
            keys = [k for k in (keys or []) if k != me]
            foreign: dict[str, dict] = {}
            if keys:
                blobs = await self._gcs("kv_multi_get",
                                        {"ns": _SHARE_NS, "keys": keys})
                for k, blob in (blobs or {}).items():
                    try:
                        sib = pickle.loads(blob)
                    except Exception:
                        continue
                    if time.time() - sib.get("ts", 0) > _SHARE_TTL_S:
                        continue
                    for j, h in enumerate(sib.get("handles", ())):
                        foreign[f"{k}#{j}"] = {
                            "handle": h,
                            "signal": (sib.get("signals") or [{}] * (j + 1)
                                       )[j] or {}}
            self._foreign = foreign
        except Exception:
            log.debug("decode share-group sync failed", exc_info=True)

    def __serve_load__(self) -> float:
        """The serve router's user-load probe hook: this replica's
        decode tokens-in-flight in request-equivalents, so the router's
        pow-2 choice (and handle-side admission) sees decode-plane
        pressure instead of raw request counts."""
        total = sum(self._worker_load(i)
                    for i in range(len(self.decode_pool)))
        return total / max(1, self.default_max_tokens)

    async def _pool_call(self, handle, method: str, args: tuple,
                         kwargs: dict):
        """Pool hop on the LOOP-side actor fast lane — the serve
        router's mechanism (``fast_actor_submit_loop``) composed inward
        (ROADMAP items 2/4): same-node pool workers ride the shm rings,
        cross-node ones the node tunnel, with per-call RPC fallback for
        anything the lane cannot carry. A broken lane surfaces as
        ``ConnectionLost``, which :func:`_is_worker_death` already
        classifies — the scheduler's own re-adopt/re-prefill retry owns
        replay, and both legs are idempotent by construction (re-prefill
        recomputes, re-adopt re-reads sealed pages). Sampled trace
        context rides the record's wire leg either way."""
        from ray_tpu.core import api as _api
        from ray_tpu.core.core_client import FastLaneDeclined

        core = _api.get_core()
        try:
            on_core = asyncio.get_running_loop() is core.loop
        except RuntimeError:
            on_core = False
        if on_core and getattr(core.cfg, "fastpath_enabled", False):
            key = (handle.actor_id, method)
            tmpl = self._pool_tmpls.get(key)
            if tmpl is None:
                tmpl = self._pool_tmpls[key] = core.actor_call_template(
                    handle.actor_id, method, 1, None)
            out = core.fast_actor_submit_loop(handle.actor_id, method,
                                              args, kwargs, tmpl)
            if out is not None:
                try:
                    return await core.fast_actor_await(out[0], out[1])
                except FastLaneDeclined:
                    pass  # stale method table: RPC below, lane survives
        return await getattr(handle, method).remote(*args, **kwargs)

    async def _pool_stream(self, handle, method, args, kwargs):
        """Streaming twin of :meth:`_pool_call`: yields the pool worker
        generator's items. Fast path = ONE "G"-chunked stream on the
        worker's ring/tunnel lane (``fast_actor_submit_stream``) — token
        deltas hop scheduler<-decode with no per-item ObjectRef; fallback
        = the per-item ObjectRef plane. A NEED_SLOW decline provably
        precedes execution, so the fallback re-dispatch never duplicates
        decode work."""
        from ray_tpu.core import api as _api
        from ray_tpu.core.core_client import FastLaneDeclined

        core = _api.get_core()
        try:
            on_core = asyncio.get_running_loop() is core.loop
        except RuntimeError:
            on_core = False
        if on_core and getattr(core.cfg, "fastpath_enabled", False):
            out = core.fast_actor_submit_stream(handle.actor_id, method,
                                                args, kwargs)
            if out is not None:
                agen = core.fast_actor_stream(out[0], out[1])
                try:
                    try:
                        async for item in agen:
                            yield item
                        return
                    except FastLaneDeclined:
                        pass  # stale method table: RPC below, nothing ran
                finally:
                    await agen.aclose()
        gen = getattr(handle, method).options(
            num_returns="streaming").remote(*args, **kwargs)
        try:
            async for ref in gen:
                (item,) = await core.get_async([ref])
                yield item
        finally:
            aclose = getattr(gen, "aclose", None)
            if aclose is not None:
                await aclose()

    def _backpressure(self, n_need: int):
        from ray_tpu.serve.exceptions import BackPressureError

        self.backpressured += 1
        total_free = sum(self._capacity - e for e in self._est_pages)
        # drain estimate: decode frees pages as resident requests finish;
        # scale the hint by how oversubscribed the pools are
        raise BackPressureError(
            f"decode pools out of KV page headroom ({n_need} pages "
            f"needed, {total_free} free across {len(self.decode_pool)} "
            f"workers)",
            retry_after_s=min(2.0, 0.05 * max(1, n_need)),
        )

    # ------------------------------------------------------------ serving
    async def __call__(self, request: dict) -> dict:
        """{prompt_tokens, max_tokens?, temperature?, model?} ->
        {completion_tokens, usage} — the LLMEngineServer protocol."""
        toks = [int(t) for t in request["prompt_tokens"]]
        if not toks:
            raise ValueError("empty prompt")
        mt = int(request.get("max_tokens", self.default_max_tokens))
        temp = float(request.get("temperature", 0.0))
        adapter = request.get("model")
        t_arr = time.perf_counter_ns()
        self.requests += 1
        self._ensure_signal_loop()
        n_need = -(-(len(toks) + mt) // self.PS)
        if n_need > self._capacity:
            raise ValueError(
                f"request needs {n_need} KV pages but decode pools hold "
                f"{self._capacity}")
        excluded: set[int] = set()
        f_excluded: set[str] = set()
        prefix_m = None   # pinned cache manifest (release on every exit)
        manifest = extra = first = None
        t_first = None
        last_err = None
        try:
            for attempt in range(self.max_attempts + 1):
                widx = self._pick_decode(n_need, excluded)
                fkey = fhandle = None
                if widx is None:
                    # no local headroom: a queued-but-unadmitted request
                    # migrates to an idle SIBLING replica's decode ring
                    # (decode_share_group) — the same manifest re-adopts
                    # there, so the steal costs zero duplicate prefill
                    # FLOPs and rides _pool_call's fast lanes unchanged
                    picked = self._pick_foreign(n_need, f_excluded)
                    if picked is not None:
                        fkey, fhandle = picked
                if widx is None and fhandle is None and excluded:
                    # every worker burned by THIS request: let it retry
                    # anywhere (a restarted worker may be back) rather
                    # than dead-ending with headroom elsewhere
                    excluded.clear()
                    widx = self._pick_decode(n_need, excluded)
                if widx is None and fhandle is None:
                    self._backpressure(n_need)
                # reserve at PICK time, not after the prefill: concurrent
                # requests admitting against a zero estimate would all
                # pass and spend prefill work the decode pools cannot
                # seat — the exact waste admission control exists to stop
                if widx is not None:
                    self._est_pages[widx] += n_need
                    self._est_tokens[widx] += mt
                try:
                    if manifest is None:
                        try:
                            (manifest, extra, first,
                             prefix_m) = await self._prefill(
                                toks, temp, adapter)
                        except Exception as e:  # noqa: BLE001 — prefill leg
                            last_err = e
                            if isinstance(e, (KVShipError,
                                              ObjectLostError)):
                                # cached prefix pages vanished mid-adopt:
                                # drop the cached path, full re-prefill
                                self.cache.invalidate(toks)
                                prefix_m = None
                                continue
                            if _is_worker_death(e):
                                # a PREFILL actor died — retry the
                                # prefill; the decode pick stays valid
                                continue
                            raise
                        if attempt:
                            self.duplicate_prefills += 1
                            telemetry.count(duplicate_prefills=1)
                        if t_first is None:
                            t_first = time.perf_counter_ns()
                            telemetry.record(telemetry.TTFT,
                                             t_first - t_arr)
                    with telemetry.traced("disagg::decode"):
                        target = (self.decode_pool[widx]
                                  if widx is not None else fhandle)
                        out = await self._pool_call(
                            target, "decode_adopted",
                            (toks, manifest, extra, first),
                            dict(max_tokens=mt, temperature=temp,
                                 adapter=adapter))
                    if widx is not None:
                        self.decode_tokens[widx] += len(out)
                    else:
                        self.stolen += 1
                        self.stolen_tokens += len(out)
                    return self._finish(toks, out, manifest, extra,
                                        prefix_m, t_arr, t_first,
                                        widx if widx is not None
                                        else f"steal:{fkey}", attempt)
                except Exception as e:  # noqa: BLE001 — decode leg
                    last_err = e
                    if isinstance(e, (KVShipError, ObjectLostError)):
                        # the pages themselves are gone: drop the cached
                        # path and re-prefill (the bounded-duplicate leg)
                        self.cache.release(prefix_m)
                        self.cache.invalidate(toks)
                        prefix_m = manifest = extra = first = None
                        continue
                    if _is_worker_death(e):
                        # decode worker died holding the request; the
                        # pages survive in the prefill arenas — re-adopt
                        # the SAME manifest elsewhere
                        if widx is not None:
                            excluded.add(widx)
                        else:
                            f_excluded.add(fkey)
                            self._foreign.pop(fkey, None)
                        self.decode_retries += 1
                        continue
                    from ray_tpu.serve.exceptions import BackPressureError

                    if isinstance(e, BackPressureError):
                        # headroom estimate was stale for this worker
                        if widx is not None:
                            excluded.add(widx)
                        else:
                            f_excluded.add(fkey)
                        continue
                    raise
                finally:
                    if widx is not None:
                        self._est_pages[widx] -= n_need
                        self._est_tokens[widx] -= mt
            raise last_err
        finally:
            self.cache.release(prefix_m)

    async def stream(self, request: dict):
        """Streaming disagg completion: ``{"tokens": [...]}`` deltas —
        one per fused decode block, hopping decode ring -> scheduler ->
        replica as "G" chunk records — then a terminal delta carrying
        ``usage``. Concatenated tokens are identical to ``__call__``'s
        ``completion_tokens``.

        Fault contract: initial routing (prefill + decode admission)
        reuses the bounded retry/steal machinery unchanged; once a delta
        has been consumed the stream is NEVER replayed — a decode-worker
        death mid-stream surfaces as a typed
        :class:`~ray_tpu.serve.streaming.StreamBrokenError`. Abandoning
        the stream (client disconnect) cancels the decode — the slot and
        KV pages free at the next block boundary — with zero duplicate
        prefills spent."""
        from ray_tpu.serve.exceptions import BackPressureError
        from ray_tpu.serve.streaming import StreamBrokenError

        toks = [int(t) for t in request["prompt_tokens"]]
        if not toks:
            raise ValueError("empty prompt")
        mt = int(request.get("max_tokens", self.default_max_tokens))
        temp = float(request.get("temperature", 0.0))
        adapter = request.get("model")
        t_arr = time.perf_counter_ns()
        self.requests += 1
        self._ensure_signal_loop()
        n_need = -(-(len(toks) + mt) // self.PS)
        if n_need > self._capacity:
            raise ValueError(
                f"request needs {n_need} KV pages but decode pools hold "
                f"{self._capacity}")
        cancel_key = f"{self._uuid}:{self.requests}"
        excluded: set[int] = set()
        f_excluded: set[str] = set()
        prefix_m = None
        manifest = extra = first = None
        t_first = None
        last_err = None
        target = None
        completed = False
        n_out = 0
        try:
            for attempt in range(self.max_attempts + 1):
                widx = self._pick_decode(n_need, excluded)
                fkey = fhandle = None
                if widx is None:
                    picked = self._pick_foreign(n_need, f_excluded)
                    if picked is not None:
                        fkey, fhandle = picked
                if widx is None and fhandle is None and excluded:
                    excluded.clear()
                    widx = self._pick_decode(n_need, excluded)
                if widx is None and fhandle is None:
                    self._backpressure(n_need)
                if widx is not None:
                    self._est_pages[widx] += n_need
                    self._est_tokens[widx] += mt
                try:
                    if manifest is None:
                        try:
                            (manifest, extra, first,
                             prefix_m) = await self._prefill(
                                toks, temp, adapter)
                        except Exception as e:  # noqa: BLE001 — prefill leg
                            last_err = e
                            if isinstance(e, (KVShipError,
                                              ObjectLostError)):
                                self.cache.invalidate(toks)
                                prefix_m = None
                                continue
                            if _is_worker_death(e):
                                continue
                            raise
                        if attempt:
                            self.duplicate_prefills += 1
                            telemetry.count(duplicate_prefills=1)
                        if t_first is None:
                            t_first = time.perf_counter_ns()
                            telemetry.record(telemetry.TTFT,
                                             t_first - t_arr)
                    target = (self.decode_pool[widx]
                              if widx is not None else fhandle)
                    with telemetry.traced("disagg::decode"):
                        async for blk in self._pool_stream(
                                target, "decode_adopted_stream",
                                (toks, manifest, extra, first),
                                dict(max_tokens=mt, temperature=temp,
                                     adapter=adapter,
                                     cancel_key=cancel_key)):
                            n_out += len(blk)
                            yield {"tokens": blk}
                    t_done = time.perf_counter_ns()
                    if n_out > 1:
                        telemetry.record(telemetry.TPOT,
                                         (t_done - t_first) // (n_out - 1))
                    if widx is not None:
                        self.decode_tokens[widx] += n_out
                    else:
                        self.stolen += 1
                        self.stolen_tokens += n_out
                    pages = list(manifest.pages) + (
                        list(extra.pages) if extra else [])
                    if pages and len(toks) >= self.PS:
                        self.cache.insert(KVPageManifest(
                            token_ids=tuple(toks), page_size=self.PS,
                            kv_dtype=self.cache.kv_dtype, pages=pages))
                    completed = True
                    yield {
                        "tokens": [],
                        "done": True,
                        "usage": {
                            "prompt_tokens": len(toks),
                            "completion_tokens": n_out,
                            "cached_prefix_tokens": (prefix_m.n_tokens
                                                     if prefix_m else 0),
                            "latency_s": (t_done - t_arr) / 1e9,
                            "ttft_s": (t_first - t_arr) / 1e9,
                            "decode_worker": (widx if widx is not None
                                              else f"steal:{fkey}"),
                            "attempts": attempt + 1,
                        },
                    }
                    return
                except Exception as e:  # noqa: BLE001 — decode leg
                    if n_out:
                        # consumed deltas are never replayed: surface the
                        # break typed, with how far the stream got
                        if _is_worker_death(e):
                            raise StreamBrokenError(
                                f"decode stream broke after {n_out} "
                                f"token(s): {e}",
                                chunks_consumed=n_out) from e
                        raise
                    last_err = e
                    if isinstance(e, (KVShipError, ObjectLostError)):
                        self.cache.release(prefix_m)
                        self.cache.invalidate(toks)
                        prefix_m = manifest = extra = first = None
                        continue
                    if _is_worker_death(e):
                        if widx is not None:
                            excluded.add(widx)
                        else:
                            f_excluded.add(fkey)
                            self._foreign.pop(fkey, None)
                        self.decode_retries += 1
                        continue
                    if isinstance(e, BackPressureError):
                        if widx is not None:
                            excluded.add(widx)
                        else:
                            f_excluded.add(fkey)
                        continue
                    raise
                finally:
                    if widx is not None:
                        self._est_pages[widx] -= n_need
                        self._est_tokens[widx] -= mt
            raise last_err
        finally:
            self.cache.release(prefix_m)
            if not completed and target is not None:
                # abandoned/broken mid-flight: free the decode slot NOW.
                # The ring plane's abandon already closed the worker's
                # generator; this reaches streams on the RPC fallback.
                try:
                    target.cancel_decode.remote(cancel_key)  # raylint: disable=RT003 — best-effort cancel; the stream's remainder is discarded either way
                except Exception:  # raylint: disable=RT012 — worker may be gone; its stream died with it
                    pass

    async def _prefill(self, toks, temp, adapter):
        """Cache-aware prefill: longest cached page prefix rides the
        suffix path; a miss runs the full prompt. Returns
        (manifest, extra, first_token, pinned_prefix)."""
        # cap the prefix below the prompt length: the prefill must see
        # >= 1 suffix token to produce the first-token logits
        prefix_m = self.cache.lookup(toks, max_tokens=len(toks) - 1)
        pf = self.prefill_pool[next(self._pf_rr) % len(self.prefill_pool)]
        try:
            with telemetry.traced("disagg::prefill"):
                if prefix_m is not None:
                    sm, first = await self._pool_call(
                        pf, "prefill", (toks[prefix_m.n_tokens:],),
                        dict(temperature=temp, adapter=adapter,
                             prefix=prefix_m))
                    return prefix_m, sm, first, prefix_m
                m, first = await self._pool_call(
                    pf, "prefill", (toks,),
                    dict(temperature=temp, adapter=adapter))
                return m, None, first, None
        except BaseException:
            self.cache.release(prefix_m)
            raise

    def _finish(self, toks, out, manifest, extra, prefix_m, t_arr,
                t_first, widx, attempt) -> dict:
        t_done = time.perf_counter_ns()
        if len(out) > 1:
            telemetry.record(telemetry.TPOT,
                             (t_done - t_first) // (len(out) - 1))
        # cache the request's full pages for the NEXT request sharing the
        # prefix (existing nodes are shared, new suffix pages extend them)
        pages = list(manifest.pages) + (list(extra.pages) if extra else [])
        if pages and len(toks) >= self.PS:
            self.cache.insert(KVPageManifest(
                token_ids=tuple(toks), page_size=self.PS,
                kv_dtype=self.cache.kv_dtype, pages=pages))
        return {
            "completion_tokens": out,
            "usage": {
                "prompt_tokens": len(toks),
                "completion_tokens": len(out),
                "cached_prefix_tokens": (prefix_m.n_tokens
                                         if prefix_m else 0),
                "latency_s": (t_done - t_arr) / 1e9,
                "ttft_s": (t_first - t_arr) / 1e9,
                "decode_worker": widx,
                "attempts": attempt + 1,
            },
        }

    # ---------------------------------------------------------- telemetry
    async def stats(self) -> dict:
        """Scheduler + cache + pool-wide KV-plane counters (the byte
        ledger summed across every worker process)."""
        # monitoring counts as interest in fresh decode signals: keep the
        # probe loop alive so ``decode_signals`` tracks live workers (a
        # respawned worker replaces its dead predecessor's stale entry)
        self._ensure_signal_loop()
        refs = [w.disagg_counters.remote()
                for w in (*self.prefill_pool, *self.decode_pool)]
        vals = await asyncio.gather(*refs, return_exceptions=True)
        ledger: dict[str, int] = {}
        for v in vals:
            if isinstance(v, dict):
                for k, n in v.items():
                    ledger[k] = ledger.get(k, 0) + int(n)
        for k, n in telemetry.counters().items():  # scheduler-local leg
            ledger[k] = ledger.get(k, 0) + int(n)
        return {
            "requests": self.requests,
            "duplicate_prefills": self.duplicate_prefills,
            "decode_retries": self.decode_retries,
            "backpressured": self.backpressured,
            "est_pages": list(self._est_pages),
            "est_tokens": list(self._est_tokens),
            "decode_tokens": list(self.decode_tokens),
            "decode_signals": [dict(s) if s else None
                               for s in self._signals],
            "stolen": self.stolen,
            "stolen_tokens": self.stolen_tokens,
            "foreign_workers": sorted(self._foreign),
            "prefix_cache": self.cache.stats(),
            "kv_plane": ledger,
        }

    def stage_windows(self) -> dict:
        """This replica's bounded TTFT/TPOT stage windows (ns values) —
        the serve-driven bench reads its percentiles through the
        deployment because the windows live in the replica process."""
        return {"ttft": telemetry.stage_window(telemetry.TTFT),
                "tpot": telemetry.stage_window(telemetry.TPOT)}

    async def shutdown(self):
        if self._sig_task is not None:
            self._sig_task.cancel()
            self._sig_task = None
        if self._share_group:
            try:
                await self._gcs("kv_del", {
                    "ns": _SHARE_NS,
                    "key": f"{self._share_group}/{self._uuid}"})
            except Exception:
                log.debug("share-group deregister failed", exc_info=True)
        refs = [w.stop.remote() for w in self.decode_pool]
        await asyncio.gather(*refs, return_exceptions=True)
        # release the pool leases NOW: explicit kills instead of waiting
        # for handle GC (shutdown is the one place we know no more calls
        # are coming), so a replaced/redeployed replica's fresh pools
        # never contend with the old pools' still-leased CPUs
        for w in (*self.prefill_pool, *self.decode_pool):
            try:
                await self._gcs("kill_actor", {
                    "actor_id": w.actor_id, "no_restart": True})
            except Exception:
                log.debug("pool actor kill failed", exc_info=True)


def build_disagg_deployment(model_config, *, params=None, params_fn=None,
                            num_replicas: int = 1, num_tpus: float = 0.0,
                            name: str = "DisaggLLMServer",
                            max_ongoing_requests: int = 64,
                            ray_actor_options: dict | None = None, **kw):
    """Bound serve application around the disaggregated stack. Route
    with ``handle.options(routing_hint=prefix_hint(tokens)).remote(...)``
    so requests sharing a cacheable prefix land on the replica already
    holding its pages. ``ray_actor_options`` (e.g. ``{"resources":
    {"tpu-host": 1}}``) pins the REPLICA; pair it with
    ``pool_resources`` so its prefill/decode pools land beside it."""
    from ray_tpu import serve

    opts: dict = dict(ray_actor_options or {})
    if num_tpus:
        opts["num_tpus"] = num_tpus
    dep = serve.deployment(
        DisaggLLMServer,
        name=name,
        num_replicas=num_replicas,
        max_ongoing_requests=max_ongoing_requests,
        ray_actor_options=opts,
    )
    return dep.bind(model_config, params, params_fn, **kw)

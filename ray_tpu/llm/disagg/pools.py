"""Prefill/decode worker pools — the two halves of disaggregated serving.

DistServe's observation (Zhong et al., OSDI'24): prefill is a
throughput-bound batch matmul, decode is a latency-bound memory-bound
loop, and colocating them makes each the other's tail. Here the two
phases run in SEPARATE actor pools connected only by the KV-page plane:

- :class:`PrefillWorker` owns a transient paged pool. Concurrent
  ``prefill`` calls accumulate into padded waves (one
  ``paged_prefill_batch`` dispatch per pad bucket — the engine's own
  admission-wave shape, run standalone); each prompt's pages are then
  sealed into the local shm arena (:func:`ship_pages`) and the pool rows
  are freed immediately — the pool is a staging buffer, the shm arena is
  the KV's home. A ``prefix`` manifest switches the call onto
  ``paged_prefill_suffix``: cached prefix pages are adopted into the
  staging pool verbatim and only the suffix runs through the model.
- :class:`DecodeWorker` wraps the continuous-batching engine. It admits
  requests ONLY with adopted KV (``submit_prefilled``): the engine's
  decode ring never runs a prefill, so admission cost is one page
  scatter and long prompts can no longer stall resident decodes.

Queue-time telemetry: every prefill job records ``prefill_queue`` (enqueue
-> wave dispatch) and every adopted request records ``decode_queue``
(submit -> first slot grant), the two legs a disaggregated request can
starve in; ``kv_ship`` is recorded by the plane itself.
"""

from __future__ import annotations

import asyncio
import functools
import time
from dataclasses import dataclass, field

import numpy as np

from ray_tpu.core.ref import ObjectLostError
from ray_tpu.devtools import chaos
from ray_tpu.llm import engine as _engine
from ray_tpu.llm.disagg import telemetry
from ray_tpu.llm.disagg.kv_plane import (
    KVPageManifest,
    KVShipError,
    adopt_pages,
    ship_pages,
)


def _resolve_params(model_config, params, params_fn):
    if params is None:
        params = params_fn() if params_fn is not None else None
    if params is None:
        import jax

        from ray_tpu.models.llama import llama_init

        params = llama_init(jax.random.PRNGKey(0), model_config)
    return params


@dataclass
class _Job:
    tokens: list[int]
    temperature: float
    aid: int
    prefix: KVPageManifest | None
    fut: asyncio.Future
    t_enq: int = field(default_factory=time.perf_counter_ns)
    # owning request's (trace_id, span_id), captured ONCE at enqueue —
    # the wave loop runs outside the request's context, so batch-stamped
    # telemetry (queue span, page-seal span) carries this instead
    tctx: tuple | None = None


class PrefillWorker:
    """Stateless-per-request prefill actor: prompts in, manifests out.

    Run with ``max_concurrency > 1`` so concurrent calls can coalesce
    into one padded wave (the scheduler's pool factory does this)."""

    #: wave padding buckets, shared shape discipline with the engine
    _WAVE_BUCKETS = _engine.ContinuousBatchingEngine._WAVE_BUCKETS

    def __init__(self, model_config, params=None, params_fn=None, *,
                 page_size: int = 16, n_pages: int = 256,
                 max_wave: int = 8, wave_wait_s: float = 0.004,
                 kv_dtype: str | None = None,
                 lora_adapters: dict | None = None, lora_rank: int = 8,
                 seed: int = 0):
        from ray_tpu.utils.device import configure_jax

        configure_jax()
        import jax

        self.cfg = model_config
        self.params = _resolve_params(model_config, params, params_fn)
        self.PS = page_size
        self.n_pages = n_pages
        self.kv_dtype = kv_dtype or "native"
        self.kpool, self.vpool = _engine.make_kv_pools(
            model_config, page_size, n_pages, kv_dtype)
        self.free_pages = list(range(1, n_pages))  # page 0 = junk page
        self.loras = None
        self.lora_index = {"__base__": 0}
        if lora_adapters:
            self.loras, self.lora_index = _engine.make_lora_stack(
                model_config, lora_adapters, lora_rank)
        self.max_wave = max_wave
        self.wave_wait_s = wave_wait_s
        self._rng = jax.random.PRNGKey(seed)
        self._pending: list[_Job] = []
        self._arrived: asyncio.Event | None = None
        self._task = None
        self.waves = 0

    # ------------------------------------------------------------- public
    async def prefill(self, token_ids, *, temperature: float = 0.0,
                      adapter: str | None = None,
                      prefix: KVPageManifest | None = None):
        """Prefill one prompt — or, with ``prefix``, only its suffix over
        the cached prefix pages — and return ``(manifest, first_token)``.
        The manifest covers exactly the pages THIS call produced (the
        suffix pages when ``prefix`` is given); adoption appends them to
        the prefix's. Concurrent calls batch into one padded wave."""
        aid = self.lora_index.get(adapter or "__base__")
        if aid is None:
            raise ValueError(f"unknown LoRA adapter {adapter!r} "
                             f"(loaded: {sorted(self.lora_index)})")
        tokens = [int(t) for t in token_ids]
        if prefix is not None:
            if prefix.n_tokens % self.PS:
                raise ValueError(
                    f"prefix must be page-aligned, got {prefix.n_tokens} "
                    f"tokens at page_size {self.PS}")
            if prefix.kv_dtype != self.kv_dtype:
                raise ValueError(
                    f"prefix kv_dtype {prefix.kv_dtype!r} != pool "
                    f"{self.kv_dtype!r}")
            if not tokens:
                raise ValueError("suffix prefill needs >= 1 suffix token")
        need = self._pages_needed(tokens, prefix)
        if need > self.n_pages - 1:
            raise ValueError(
                f"prompt needs {need} staging pages but the prefill pool "
                f"only has {self.n_pages - 1}")
        loop = asyncio.get_running_loop()
        if self._arrived is None:
            self._arrived = asyncio.Event()
        if self._task is None or self._task.done():
            self._task = loop.create_task(self._wave_loop())
        job = _Job(tokens, float(temperature), aid, prefix,
                   loop.create_future(),
                   tctx=telemetry.capture_trace_ctx())
        self._pending.append(job)
        self._arrived.set()
        return await job.fut

    def headroom(self) -> dict:
        return {"free_pages": len(self.free_pages),
                "pending": len(self._pending),
                "page_size": self.PS, "kv_dtype": self.kv_dtype}

    def disagg_counters(self) -> dict:
        """This process's KV-plane byte/op ledger (the scheduler sums
        these across the pool for the zero-copy proof)."""
        return telemetry.counters()

    # ---------------------------------------------------------- internals
    def _pages_needed(self, tokens: list[int], prefix) -> int:
        if prefix is None:
            return -(-len(tokens) // self.PS)
        return prefix.n_pages + -(-len(tokens) // self.PS)

    async def _wave_loop(self):
        while True:
            while not self._pending:
                self._arrived.clear()
                await self._arrived.wait()
            # let a wave accumulate: concurrent callers land within this
            # window and share one dispatch
            await asyncio.sleep(self.wave_wait_s)
            wave: list[_Job] = []
            free = len(self.free_pages)
            while self._pending and len(wave) < self.max_wave:
                need = self._pages_needed(self._pending[0].tokens,
                                          self._pending[0].prefix)
                if need > free and wave:
                    break  # next wave, once these pages are freed
                job = self._pending.pop(0)
                free -= need
                wave.append(job)
            try:
                await self._dispatch_wave(wave)
            except Exception as e:  # noqa: BLE001 — fail the wave's callers
                for job in wave:
                    if not job.fut.done():
                        job.fut.set_exception(e)

    def _alloc(self, n: int) -> list[int]:
        if n > len(self.free_pages):
            # can only happen if pages leaked — a short allocation would
            # leave page-table slots at 0 and silently write KV into the
            # shared junk page
            raise RuntimeError(
                f"staging pool exhausted: need {n} pages, "
                f"{len(self.free_pages)} free")
        out = self.free_pages[:n]
        del self.free_pages[:n]
        return out

    async def _dispatch_wave(self, wave: list[_Job]):
        t_dispatch = time.perf_counter_ns()
        full: dict[int, list[_Job]] = {}
        sfx: dict[tuple[int, int], list[_Job]] = {}
        for job in wave:
            telemetry.record(telemetry.PREFILL_QUEUE,
                             t_dispatch - job.t_enq, trace_ctx=job.tctx)
            if job.prefix is None:
                Tp_pad = -(-len(job.tokens) // self.PS) * self.PS
                full.setdefault(Tp_pad, []).append(job)
            else:
                Ts_pad = -(-len(job.tokens) // self.PS) * self.PS
                W = job.prefix.n_pages + Ts_pad // self.PS
                sfx.setdefault((Ts_pad, W), []).append(job)
        self.waves += bool(wave)
        for Tp_pad, jobs in full.items():
            self._dispatch_full(Tp_pad, jobs)
        for (Ts_pad, W), jobs in sfx.items():
            await self._dispatch_suffix(Ts_pad, W, jobs)

    def _bucket(self, n: int) -> int:
        return (next(b for b in self._WAVE_BUCKETS if b >= n)
                if n <= self._WAVE_BUCKETS[-1] else n)

    def _finish(self, jobs, first, pages_of):
        """Ship each job's freshly written pages, free the staging rows,
        resolve the futures."""
        first = np.asarray(first)  # ONE sync for the whole group
        for j, job in enumerate(jobs):
            try:
                m = ship_pages(self.kpool, self.vpool, pages_of[j],
                               job.tokens, page_size=self.PS,
                               kv_dtype=self.kv_dtype, trace_ctx=job.tctx)
            except Exception as e:  # noqa: BLE001 — per-job failure
                job.fut.set_exception(e)
                continue
            finally:
                self.free_pages.extend(pages_of[j])
            telemetry.count(
                **{"prefills" if job.prefix is None else "suffix_prefills":
                   1})
            job.fut.set_result((m, int(first[j])))

    def _dispatch_full(self, Tp_pad: int, jobs: list[_Job]):
        import jax
        import jax.numpy as jnp

        npages = Tp_pad // self.PS
        nb = self._bucket(len(jobs))
        toks = np.zeros((nb, Tp_pad), np.int32)
        pages = np.zeros((nb, npages), np.int32)  # dummy rows: junk page
        aids = np.zeros(nb, np.int32)
        true_lens = np.ones(nb, np.int32)
        temps = np.zeros(nb, np.float32)
        pages_of = []
        try:
            for j, job in enumerate(jobs):
                mine = self._alloc(-(-len(job.tokens) // self.PS))
                pages_of.append(mine)
                toks[j, :len(job.tokens)] = job.tokens
                pages[j, :len(mine)] = mine
                aids[j] = job.aid
                true_lens[j] = len(job.tokens)
                temps[j] = job.temperature
            self._rng, sub = jax.random.split(self._rng)
            first, self.kpool, self.vpool = _engine.paged_prefill_batch(
                self.params, self.loras, jnp.asarray(aids),
                jnp.asarray(toks), jnp.asarray(pages), self.kpool,
                self.vpool, jnp.asarray(true_lens), jnp.asarray(temps),
                sub, self.cfg)
        except BaseException:
            # a failed dispatch must not leak staging rows — _finish
            # (which normally frees them per job) never ran
            for rows in pages_of:
                self.free_pages.extend(rows)
            raise
        self._finish(jobs, first, pages_of)

    async def _dispatch_suffix(self, Ts_pad: int, W: int, jobs: list[_Job]):
        """Suffix wave: adopt each job's cached prefix pages into the
        staging pool (zero-copy when the cache lives on this node), then
        run ONLY the suffix through the model.

        Adoption runs off the event loop: with >1 prefill worker a
        suffix prefix may be sealed by a sibling whose loop is likewise
        inside a suffix wave — a blocking fetch here deadlocks both."""
        import jax
        import jax.numpy as jnp

        loop = asyncio.get_running_loop()
        nb = self._bucket(len(jobs))
        toks = np.zeros((nb, Ts_pad), np.int32)
        pages = np.zeros((nb, W), np.int32)
        aids = np.zeros(nb, np.int32)
        prefix_lens = np.zeros(nb, np.int32)
        true_lens = np.ones(nb, np.int32)
        temps = np.zeros(nb, np.float32)
        pages_of = []   # suffix pages: shipped then freed
        adopted_of = []  # prefix staging pages: freed, never shipped
        try:
            # overlap the jobs' independent prefix fetches (each may pull
            # a sibling worker's pages through the object plane) instead
            # of paying one serial round trip per cache hit
            stacks = await asyncio.gather(*(
                loop.run_in_executor(
                    None, functools.partial(adopt_pages, job.prefix,
                                            role="prefill"))
                for job in jobs))
            for j, job in enumerate(jobs):
                k = job.prefix.n_pages
                prows = self._alloc(k)
                adopted_of.append(prows)
                k_stack, v_stack = stacks[j]
                self.kpool = _engine.scatter_pages(self.kpool, prows,
                                                   k_stack)
                self.vpool = _engine.scatter_pages(self.vpool, prows,
                                                   v_stack)
                mine = self._alloc(-(-len(job.tokens) // self.PS))
                pages_of.append(mine)
                toks[j, :len(job.tokens)] = job.tokens
                pages[j, :k] = prows
                pages[j, k:k + len(mine)] = mine
                aids[j] = job.aid
                prefix_lens[j] = job.prefix.n_tokens
                true_lens[j] = len(job.tokens)
                temps[j] = job.temperature
            self._rng, sub = jax.random.split(self._rng)
            first, self.kpool, self.vpool = _engine.paged_prefill_suffix(
                self.params, self.loras, jnp.asarray(aids),
                jnp.asarray(toks), jnp.asarray(pages), self.kpool,
                self.vpool, jnp.asarray(prefix_lens),
                jnp.asarray(true_lens), jnp.asarray(temps), sub, self.cfg)
        except BaseException:
            for rows in (*adopted_of, *pages_of):
                self.free_pages.extend(rows)
            raise
        try:
            self._finish(jobs, first, pages_of)
        finally:
            for prows in adopted_of:
                self.free_pages.extend(prows)


class DecodeWorker:
    """Decode actor: the continuous-batching engine, admitting requests
    only with adopted KV. ``EngineFull`` is translated to the serve
    layer's typed :class:`BackPressureError` here, so an overloaded
    decode pool reads as router/scheduler backpressure, never as an
    untyped actor failure."""

    def __init__(self, model_config, params=None, params_fn=None, *,
                 max_batch: int = 8, page_size: int = 16,
                 n_pages: int = 256, max_seq_len: int = 512,
                 eos_id: int | None = None, kv_dtype: str | None = None,
                 lora_adapters: dict | None = None, lora_rank: int = 8,
                 max_waiting: int = 256, spec_enable: bool = False,
                 spec_k: int = 4, spec_ngram: int = 2, spec_drafter=None):
        from ray_tpu.utils.device import configure_jax

        configure_jax()
        params = _resolve_params(model_config, params, params_fn)
        self.engine = _engine.ContinuousBatchingEngine(
            params, model_config, max_batch=max_batch, page_size=page_size,
            n_pages=n_pages, max_seq_len=max_seq_len, eos_id=eos_id,
            lora_adapters=lora_adapters, lora_rank=lora_rank,
            max_waiting=max_waiting, kv_dtype=kv_dtype,
            spec_enable=spec_enable, spec_k=spec_k, spec_ngram=spec_ngram,
            spec_drafter=spec_drafter)
        # live streaming decodes by scheduler-chosen key: the explicit
        # cancel path for streams riding the per-item RPC fallback (the
        # fast lane's abandon reaches the generator's finally directly)
        self._stream_rids: dict[str, int] = {}

    async def decode_adopted(self, token_ids, manifest: KVPageManifest,
                             extra: KVPageManifest | None = None,
                             first_token: int = 0, *, max_tokens: int = 32,
                             temperature: float = 0.0,
                             adapter: str | None = None) -> list[int]:
        """Adopt a prompt's KV pages and decode: returns the full token
        list (``first_token`` first — emission parity with the aggregated
        engine, which emits the prefill token itself). The adoption fetch
        runs on a pool thread so resident decodes never stall behind a
        cross-node page pull."""
        from ray_tpu.serve.exceptions import BackPressureError

        await self.engine.start()
        loop = asyncio.get_running_loop()
        try:
            k_stack, v_stack = await loop.run_in_executor(
                None, adopt_pages, manifest, extra)
        except ObjectLostError as e:
            # normalize onto the plane's typed failure (passthrough-
            # marked): the scheduler re-prefills on it either way
            raise KVShipError(f"adopt: sealed pages lost: {e}") from None
        try:
            rid = self.engine.submit_prefilled(
                [int(t) for t in token_ids], k_stack, v_stack,
                int(first_token), max_tokens=max_tokens,
                temperature=temperature, adapter=adapter)
        except _engine.EngineFull as e:
            raise BackPressureError(
                f"decode engine full: {e}",
                retry_after_s=0.05 * (1 + len(self.engine.waiting)),
            ) from None
        t_submit = time.perf_counter_ns()
        out: list[int] = []
        async for tok in self.engine.stream(rid):
            if not out:
                # first emission == slot grant: the decode-queue leg
                telemetry.record(telemetry.DECODE_QUEUE,
                                 time.perf_counter_ns() - t_submit)
            out.append(tok)
        # refresh the decode-plane signals (tokens-in-flight gauge +
        # spec windows) on the way out — every completed request keeps
        # the scheduler's and the dashboard's numbers fresh
        telemetry.publish_decode_signals(self.engine)
        return out

    async def decode_adopted_stream(self, token_ids,
                                    manifest: KVPageManifest,
                                    extra: KVPageManifest | None = None,
                                    first_token: int = 0, *,
                                    max_tokens: int = 32,
                                    temperature: float = 0.0,
                                    adapter: str | None = None,
                                    cancel_key: str = ""):
        """Streaming twin of :meth:`decode_adopted`: yields token-id
        DELTAS, one list per fused decode block (the engine's
        ``stream_blocks`` coalescing), concatenating to exactly what
        ``decode_adopted`` would have returned. Closing the stream — the
        worker pump's GeneratorExit when the consumer abandons the "G"
        chunk stream, or :meth:`cancel_decode` with ``cancel_key`` on the
        RPC fallback plane — cancels the engine request: the decode slot
        and its KV pages free at the next block boundary, with zero
        duplicate prefill spent."""
        from ray_tpu.serve.exceptions import BackPressureError

        await self.engine.start()
        loop = asyncio.get_running_loop()
        try:
            k_stack, v_stack = await loop.run_in_executor(
                None, adopt_pages, manifest, extra)
        except ObjectLostError as e:
            raise KVShipError(f"adopt: sealed pages lost: {e}") from None
        try:
            rid = self.engine.submit_prefilled(
                [int(t) for t in token_ids], k_stack, v_stack,
                int(first_token), max_tokens=max_tokens,
                temperature=temperature, adapter=adapter)
        except _engine.EngineFull as e:
            raise BackPressureError(
                f"decode engine full: {e}",
                retry_after_s=0.05 * (1 + len(self.engine.waiting)),
            ) from None
        if cancel_key:
            self._stream_rids[cancel_key] = rid
        t_submit = time.perf_counter_ns()
        first = True
        try:
            async for blk in self.engine.stream_blocks(rid):
                if chaos.ENABLED:
                    chaos.point("llm.decode_block", n_tokens=len(blk))
                if first:
                    first = False
                    telemetry.record(telemetry.DECODE_QUEUE,
                                     time.perf_counter_ns() - t_submit)
                yield blk
        finally:
            self.engine.cancel(rid)  # no-op once finished
            if cancel_key:
                self._stream_rids.pop(cancel_key, None)
            telemetry.publish_decode_signals(self.engine)

    def cancel_decode(self, cancel_key: str) -> bool:
        """Cancel a live streaming decode by the scheduler's key —
        the mid-stream disconnect path for streams on the per-item RPC
        fallback, where no ring abandon reaches the generator."""
        rid = self._stream_rids.get(cancel_key)
        if rid is None:
            return False
        self.engine.cancel(rid)
        return True

    def headroom(self) -> dict:
        telemetry.publish_decode_signals(self.engine)
        return self.engine.headroom()

    def engine_stats(self) -> dict:
        return {"steps": self.engine.steps,
                "tokens_out": self.engine.tokens_out,
                "waiting": len(self.engine.waiting),
                "free_pages": len(self.engine.free_pages),
                "tokens_in_flight": self.engine.tokens_in_flight(),
                **{k: v for k, v in self.engine.spec_stats().items()
                   if k != "blocks"}}

    def disagg_counters(self) -> dict:
        return telemetry.counters()

    async def stop(self):
        await self.engine.stop()

"""ray_tpu.llm.disagg — disaggregated LLM serving on the ray_tpu runtime.

The TPU-native composition of DistServe's prefill/decode disaggregation
(Zhong et al., OSDI'24) and vLLM's paged-KV-as-shareable-cache insight
(Kwon et al., SOSP'23) over this repo's own planes:

- **KV-page plane** (:mod:`.kv_plane`): prefill workers seal the KV
  pages they produce directly into the local shm arena (the sharded
  plane's ``put_value(prefer_shm=True)`` path) and hand decode workers a
  :class:`KVPageManifest` — token ids + per-page object refs + node +
  nbytes, the ShardManifest shape at page granularity. Adoption scatters
  the pages into free slots of the decode pool: zero-copy when
  same-node, via the object plane across nodes; array bytes never cross
  the driver.
- **Prefill/decode pools** (:mod:`.pools`): ``PrefillWorker`` batches
  prompts into padded waves on ``paged_prefill_batch`` (suffix-only
  prefill over cached prefix pages via ``paged_prefill_suffix``);
  ``DecodeWorker`` runs the existing continuous-batching ring, admitting
  requests only with adopted KV.
- **Scheduler** (:mod:`.scheduler`): ``DisaggLLMServer`` — a serve
  deployment fronting both pools with admission control driven by
  decode-pool page headroom (``EngineFull`` never reaches the caller; it
  becomes router backpressure) and decode-death recovery by manifest
  re-adoption or re-prefill.
- **Cross-request prefix cache** (:mod:`.prefix_cache`): a radix tree
  over token-id pages mapping to pinned manifests, with hit/miss
  accounting, arena-pressure LRU eviction, and prefix-affinity routing
  hints surfaced through the serve layer.
"""

from ray_tpu.llm.disagg.kv_plane import (
    KVPageManifest,
    KVShipError,
    adopt_pages,
    ship_pages,
)
from ray_tpu.llm.disagg.pools import DecodeWorker, PrefillWorker
from ray_tpu.llm.disagg.prefix_cache import PrefixCache, prefix_hint
from ray_tpu.llm.disagg.scheduler import (
    DisaggLLMServer,
    build_disagg_deployment,
)

__all__ = [
    "DecodeWorker",
    "DisaggLLMServer",
    "KVPageManifest",
    "KVShipError",
    "PrefillWorker",
    "PrefixCache",
    "adopt_pages",
    "build_disagg_deployment",
    "prefix_hint",
    "ship_pages",
]

"""Disagg-serving telemetry: stage windows, Prometheus feeds, byte ledger.

Mirrors the sharded plane's instrumentation (sharded/telemetry.py):
every disagg operation records (stage, duration_ns, nbytes) — stages
``prefill_queue`` / ``kv_ship`` / ``decode_queue`` plus the derived
request metrics ``ttft`` / ``tpot`` — into

- the process flight-recorder ring (utils/recorder.py stage ids 15-17),
  so postmortems show which serving leg a worker died inside;
- ``metrics.task_stage_seconds`` histograms + ``task_stage_us``
  percentile gauges (Prometheus/dashboard, the same families the task
  and sharded stages feed);
- a bounded per-process latency window published on the task-event
  flush under GCS ns="latency" (key ``<worker>.llm``) so
  ``state.list_task_latency()`` merges the serving stages beside
  ring_sub/exec/... with no extra surface.

The byte ledger backs the zero-copy claim: ``kv_driver_bytes`` counts
only manifest metadata that crossed the driver/actor RPC plane;
``kv_array_bytes`` counts KV page payload bytes that moved via shm or
the object plane instead.
"""

from __future__ import annotations

import contextlib
import threading

from ray_tpu.utils import metrics, recorder

PREFILL_QUEUE = "prefill_queue"
KV_SHIP = "kv_ship"
DECODE_QUEUE = "decode_queue"
TTFT = "ttft"
TPOT = "tpot"
# speculative-decoding block metrics (scaled integers riding the same
# ns-valued windows: tokens_per_step is stored in MILLI-tokens/step and
# spec_accept_rate in rate×1e6, so the generic µs percentile columns of
# state.list_task_latency() read as tokens/step and rate×1e3)
TOKENS_PER_STEP = "tokens_per_step"
SPEC_ACCEPT = "spec_accept_rate"
# memory tiering (PR 18): time a spill request / a tier-1 restore took,
# nbytes = the disk-leg payload moved
SPILL = "spill"
RESTORE = "restore"
STAGES = (PREFILL_QUEUE, KV_SHIP, DECODE_QUEUE, TTFT, TPOT,
          TOKENS_PER_STEP, SPEC_ACCEPT, SPILL, RESTORE)

# ttft/tpot are request-level derived metrics: they live in the latency
# window + Prometheus but not in the per-op recorder ring
_REC_STAGE = {PREFILL_QUEUE: recorder.PREFILL_QUEUE,
              KV_SHIP: recorder.KV_SHIP,
              DECODE_QUEUE: recorder.DECODE_QUEUE,
              SPILL: recorder.SPILL,
              RESTORE: recorder.RESTORE}

_WINDOW_CAP = 2048

_lock = threading.Lock()
_windows: dict[str, list[int]] = {s: [] for s in STAGES}
_count = 0
_published = -1
_snapped = -1
_counters = {"kv_driver_bytes": 0, "kv_array_bytes": 0,
             "pages_shipped": 0, "pages_adopted": 0,
             "prefills": 0, "suffix_prefills": 0, "adoptions": 0,
             # disk-leg split of the byte ledger: payload bytes that
             # came back from tier-1 instead of staying shm-resident
             "kv_disk_bytes": 0, "pages_restored": 0}
_registered_core = None


# request-trace stage class per disagg stage (TraceCriticalPath's
# vocabulary): queue waits vs page movement; ttft/tpot are derived
# request metrics, not operations — they get no span
_SPAN_STAGE = {PREFILL_QUEUE: "queue", DECODE_QUEUE: "queue",
               KV_SHIP: "pull", SPILL: "pull", RESTORE: "pull"}


def record(stage: str, dur_ns: int, nbytes: int = 0,
           trace_ctx=None) -> None:
    """One disagg stage event (ms-scale ops: inline histogram observe).

    When the owning request is SAMPLED (ambient trace context, or an
    explicitly captured ``trace_ctx`` (trace_id, span_id) tuple for
    wave-coalesced work running outside the request's context), the
    event additionally lands as a retro span in the request's trace —
    so a disagg request's waterfall shows its queue waits and KV-page
    movement beside the prefill/decode exec spans."""
    global _count
    dur_ns = max(0, int(dur_ns))
    with _lock:
        win = _windows[stage]
        win.append(dur_ns)
        if len(win) > _WINDOW_CAP:
            del win[: len(win) - _WINDOW_CAP]
        _count += 1
    metrics.task_stage_seconds.observe(dur_ns / 1e9, tags={"stage": stage})
    rec_stage = _REC_STAGE.get(stage)
    if rec_stage is not None:
        rec = recorder.get_recorder()
        if rec is not None:
            rec.record(b"", rec_stage,
                       a0=min(dur_ns, 0xFFFFFFFF),
                       a1=nbytes & 0xFFFFFFFF,
                       a2=(nbytes >> 32) & 0xFFFFFFFF)
    span_stage = _SPAN_STAGE.get(stage)
    if span_stage is not None:
        from ray_tpu.utils import tracing

        if tracing.enabled():
            ctx = trace_ctx or tracing.current()
            sink = _span_sink()
            if ctx is not None and sink is not None:
                tracing.emit_retro(
                    f"disagg::{stage}",
                    {"trace_id": ctx[0], "parent_span_id": ctx[1]},
                    sink, dur_ns / 1e9, stage=span_stage, nbytes=nbytes)
    _maybe_register()


def capture_trace_ctx():
    """The ambient (trace_id, span_id) when this request is sampled, or
    None — captured ONCE where a request enters a coalescing queue (the
    prefill wave, the decode ring) so batch-stamped telemetry can keep
    attributing work to the right trace outside the request's context
    (the raylint RT016 shape: never re-derive per loop iteration)."""
    from ray_tpu.utils import tracing

    if not tracing.enabled():
        return None
    return tracing.current()


def traced(name: str, stage: str = "exec"):
    """Child span around one disagg leg when the ambient request is
    sampled; a no-op context manager otherwise. Used by the scheduler
    for the prefill/adopt/decode legs of a request."""
    from ray_tpu.utils import tracing

    if not tracing.enabled():
        return contextlib.nullcontext()
    ctx = tracing.current()
    sink = _span_sink()
    if ctx is None or sink is None:
        return contextlib.nullcontext()
    return tracing.span(name, {"trace_id": ctx[0], "parent_span_id": ctx[1]},
                        sink, stage=stage)


def _span_sink():
    """Span rows ride the same task-event flush everything else uses."""
    from ray_tpu.core import api

    core = api._core
    if core is None:
        return None

    def sink(s):
        core.task_events.emit(name=s["name"], state="SPAN", span=s,
                              worker_id=core.worker_id.hex())
    return sink


def publish_decode_signals(engine) -> None:
    """Drain one engine's per-block speculative log into the stage
    windows and refresh the decode-plane gauges — called by the decode
    worker after each request and from ``headroom()`` probes, so the
    scheduler's admission signal, Prometheus, the dashboard LLM panel
    and the bench all read the SAME numbers."""
    st = engine.spec_stats(drain=True)
    for n_steps, emitted, proposed, accepted in st["blocks"]:
        record(TOKENS_PER_STEP, emitted * 1000 // max(1, n_steps))
        if proposed:
            record(SPEC_ACCEPT, accepted * 1_000_000 // proposed)
            # monotonic cumulatives for the rollup plane: the GCS
            # derives the windowed llm_spec_accept_rate series
            # (state.metric_window) from these two counters' deltas
            metrics.llm_spec_proposed_total.inc(proposed)
            metrics.llm_spec_accepted_total.inc(accepted)
        count(spec_proposed=proposed, spec_accepted=accepted,
              spec_steps=n_steps, spec_tokens=emitted)
    metrics.llm_decode_tokens_in_flight.set(engine.tokens_in_flight())
    if st["spec_proposed"]:
        metrics.llm_spec_accept_rate.set(st["spec_accept_rate"])
    win = stage_window(TOKENS_PER_STEP)
    if win:
        metrics.llm_tokens_per_step.set(
            sum(win[-64:]) / len(win[-64:]) / 1000.0)


def count(**deltas: int) -> None:
    """Bump ledger counters (kv_driver_bytes, kv_array_bytes, ...).
    Unseen keys start at zero — recovery-path counters
    (duplicate_prefills, ...) only exist on runs that took that path."""
    with _lock:
        for k, v in deltas.items():
            _counters[k] = _counters.get(k, 0) + int(v)


def counters() -> dict:
    with _lock:
        return dict(_counters)


def reset_counters() -> None:
    """Bench A/B support: zero the byte/op counters (windows kept)."""
    with _lock:
        for k in _counters:
            _counters[k] = 0


def stage_window(stage: str) -> list[int]:
    """Copy of one stage's bounded duration window (ns) — the bench arm
    reads ttft/tpot percentiles from here without a GCS round trip."""
    with _lock:
        return list(_windows[stage])


def snapshot_if_fresh() -> dict | None:
    """Latency-source hook (CoreClient.add_latency_source): the bounded
    stage windows in the ns="latency" publish format, or None when
    nothing new happened since the last CONFIRMED publish."""
    global _snapped
    with _lock:
        if _count == _published:
            return None
        _snapped = _count
        stages = {s: list(w) for s, w in _windows.items() if w}
    if not stages:
        return None
    for name, vals in stages.items():
        svals = sorted(vals)
        for q, qn in ((0.5, "p50"), (0.99, "p99")):
            metrics.task_stage_us.set(
                recorder.percentile(svals, q) / 1e3,
                tags={"stage": name, "q": qn})
    return {"stages": stages}


def mark_published() -> None:
    """Publish confirmation from the flush (kv_put landed)."""
    global _published
    with _lock:
        _published = _snapped


def _maybe_register() -> None:
    """Attach this window to the CURRENT CoreClient's latency publish
    loop (idempotent per core identity — an init/shutdown/init cycle
    re-registers on the fresh core, same invariant as the sharded
    source)."""
    global _registered_core
    from ray_tpu.core import api

    core = api._core
    if core is None or core is _registered_core:
        return
    try:
        core.add_latency_source("llm", snapshot_if_fresh,
                                confirm=mark_published)
        _registered_core = core
    except AttributeError:
        pass


def _reset_for_tests() -> None:
    global _count, _published, _snapped, _registered_core
    with _lock:
        for w in _windows.values():
            w.clear()
        _count = 0
        _published = -1
        _snapped = -1
        _registered_core = None
        for k in _counters:
            _counters[k] = 0
